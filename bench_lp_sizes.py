"""Throughput vs network size for the batched rFBA LP (VERDICT r4 item 5).

Solves one batched flux_balance step for each packaged network at several
colony sizes and records agent-solves/s, per-agent FLOP estimates, and
the implied utilization. The LP is O(M^3 + M^2 R) per iteration per
agent, so the MXU payoff concentrates at reference scale — this records
where.

Writes BENCH_LP_SIZES.json {backend, rows: [{network, m, r, batch,
solves_per_s, iters, flops_per_solve, flops_per_s}...]} and prints one
JSON line per row. CPU-safe; runs on TPU when the relay is up
(bench-script preamble: utils.platform.guard_accelerator_or_exit).
"""

import json
import time

import numpy as np

from lens_tpu.utils.platform import guard_accelerator_or_exit


def lp_flops(m: int, r: int, iters: float) -> float:
    """Per-solve FLOP model: each IPM iteration forms A·D·Aᵀ (2·m²·r),
    factors (m³/3), and runs 4 triangular solve pairs with refinement
    (~8·m²), plus the matvec soup (~10·m·r). Two polish solves at exit."""
    per_iter = 2.0 * m * m * r + m**3 / 3.0 + 8.0 * m * m + 10.0 * m * r
    return per_iter * (iters + 2.0)


def main():
    guard_accelerator_or_exit()
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()

    from lens_tpu.ops.linprog import flux_balance
    from lens_tpu.processes.fba_metabolism import FBAMetabolism

    rows = []
    configs = [
        ("core_skeleton", {"lp_tol": 1e-5, "lp_leak": 0.0, "lp_iterations": 35}),
        ("ecoli_core", {"lp_tol": 1e-4, "lp_leak": 1.5e-3, "lp_iterations": 45}),
        ("ecoli_core_full", {"lp_tol": 1e-5, "lp_leak": 1.5e-3, "lp_iterations": 45}),
    ]
    rng = np.random.default_rng(0)
    for name, lp_cfg in configs:
        p = FBAMetabolism({"network": name, **lp_cfg})
        m_rows = len(p.internal)
        n_cols = len(p.reactions) + (m_rows if lp_cfg["lp_leak"] > 0 else 0)
        base = {"glc": 10.0, "o2": 50.0, "nh4": 50.0, "ace": 2.0}
        for batch in (256, 1024, 4096):
            ext = np.zeros((batch, len(p.external)), np.float32)
            for e, mol in enumerate(p.external):
                ext[:, e] = base.get(mol, 0.0) * rng.uniform(0.7, 1.3, batch)

            def solve(e):
                lb, ub = p.regulated_bounds(e, 1.0)
                return flux_balance(
                    p.stoichiometry, p.objective, lb, ub,
                    n_iter=lp_cfg["lp_iterations"], tol=lp_cfg["lp_tol"],
                    leak=lp_cfg["lp_leak"],
                )

            step = jax.jit(jax.vmap(solve))
            ext_j = jnp.asarray(ext)
            sol = step(ext_j)
            jax.block_until_ready(sol.x)
            n_rep = 3 if batch >= 4096 else 6
            t0 = time.perf_counter()
            for _ in range(n_rep):
                sol = step(ext_j)
            jax.block_until_ready(sol.x)
            dt = (time.perf_counter() - t0) / n_rep
            iters = float(np.asarray(sol.iterations).mean())
            conv = float(np.asarray(sol.converged).mean())
            fl = lp_flops(m_rows, n_cols, iters)
            row = {
                "network": name,
                "m": m_rows,
                "r": n_cols,
                "batch": batch,
                "solves_per_s": batch / dt,
                "iters_mean": iters,
                "converged_frac": conv,
                "flops_per_solve": fl,
                "flops_per_s": fl * batch / dt,
            }
            rows.append(row)
            print(json.dumps({k: (round(v, 4) if isinstance(v, float) else v)
                              for k, v in row.items()}))

    out = {"backend": backend, "rows": rows}
    with open("BENCH_LP_SIZES.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
