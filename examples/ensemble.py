"""Replicate ensemble: the distribution of stochastic expression runs.

Runs N independent replicates of the hybrid Gillespie+ODE colony
(config 4's cell) as ONE device program (colony.Ensemble) and draws the
fan chart of mean protein copy number — median, quantile band, and every
replicate's trace. The reference would need N cluster runs for this;
here it is one compile and one scan.

    python examples/ensemble.py            # chip-sized (64 x 1k cells)
    python examples/ensemble.py --small    # CPU-sized check (8 x 32)

Writes ENSEMBLE.json (ENSEMBLE_SMALL.json for --small) +
out/ensemble_fan.png.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import numpy as np

    from lens_tpu.colony import Colony, Ensemble
    from lens_tpu.models.composites import hybrid_cell

    if args.small:
        reps, n, total, emit_every = 8, 32, 120.0, 5
    else:
        reps, n, total, emit_every = 64, 1024, 600.0, 10

    colony = Colony(
        hybrid_cell({}), capacity=n, division_trigger=("global", "divide")
    )
    ens = Ensemble(colony, reps)
    states = ens.initial_state(n // 2, key=jax.random.PRNGKey(0))

    run = jax.jit(lambda s: ens.run(s, total, 1.0, emit_every=emit_every))
    t0 = time.perf_counter()
    final, traj = jax.block_until_ready(run(states))
    wall = time.perf_counter() - t0

    from lens_tpu.analysis import ensemble_series, plot_ensemble_fan

    protein = ensemble_series(traj, ("counts", "protein"))  # [T, R]
    finals = protein[-1]
    # executed agent-steps follow the GROWING live population: sum the
    # emitted live counts over time/replicates, scaled by the emit stride
    # (same convention as north_star.py's mean_agent_steps_per_sec)
    live_counts = np.asarray(traj["alive"]).sum(axis=(1, 2))  # [T]
    agent_steps = float(live_counts.sum()) * emit_every
    summary = {
        "scenario": "replicate ensemble, hybrid Gillespie+ODE colony",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "replicates": reps,
        "cells_per_replicate": n // 2,
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "final_mean_protein_median": round(float(np.median(finals)), 2),
        "final_mean_protein_min": round(float(finals.min()), 2),
        "final_mean_protein_max": round(float(finals.max()), 2),
        "replicates_diverged": bool(finals.min() < finals.max()),
        "agent_steps_per_sec": round(agent_steps / wall, 1),
    }
    record = "ENSEMBLE_SMALL.json" if args.small else "ENSEMBLE.json"
    with open(record, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))

    os.makedirs(args.out_dir, exist_ok=True)
    p = plot_ensemble_fan(
        traj,
        path=("counts", "protein"),
        out_path=os.path.join(args.out_dir, "ensemble_fan.png"),
    )
    print(f"plot: {p}")


if __name__ == "__main__":
    main()
