"""The reference's signature demo: run/tumble cells climbing a gradient.

A colony of MWC-chemoreceptor + flagellar-motor cells is dropped on the
left side of an attractant ramp; temporal gradient sensing (methylation
adaptation) lengthens up-gradient runs, so the population drifts right —
while eating the very attractant it is climbing. Writes the trajectory
overlaid on the evolving field, the population's center-of-mass track,
and a summary JSON.

    python examples/chemotaxis.py            # chip-sized (2k cells)
    python examples/chemotaxis.py --small    # 1-minute CPU-sized check

Writes CHEMOTAXIS.json (CHEMOTAXIS_SMALL.json for --small) +
out/chemotaxis_*.png.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lens_tpu.models.composites import chemotaxis_lattice

    if args.small:
        cap, n0, shape, total, emit_every = 128, 64, (32, 64), 120.0, 4
    else:
        cap, n0, shape, total, emit_every = 2048, 2048, (64, 128), 600.0, 10

    h_um, w_um = 10.0 * shape[0], 10.0 * shape[1]
    spatial, _ = chemotaxis_lattice(
        {
            "capacity": cap,
            "shape": shape,
            "size": (h_um, w_um),
            "division": False,  # keep the population fixed: this demo
            # measures taxis, not growth
        }
    )
    # cells start in the left quarter of the domain
    rng = np.random.default_rng(1)
    locs = np.stack(
        [
            rng.uniform(10.0, h_um - 10.0, size=cap),
            rng.uniform(5.0, 0.2 * w_um, size=cap),
        ],
        axis=1,
    ).astype(np.float32)
    ss = spatial.initial_state(
        n0, jax.random.PRNGKey(0), locations=jnp.asarray(locs)
    )
    # attractant ramp rising to the right, spanning the receptor's
    # sensitive range
    ramp = jnp.linspace(0.02, 1.0, shape[1])[None, None, :]
    ss = ss._replace(fields=jnp.broadcast_to(ramp, ss.fields.shape) * 1.0)

    run = jax.jit(lambda s: spatial.run(s, total, 1.0, emit_every=emit_every))
    t0 = time.perf_counter()
    final, traj = jax.block_until_ready(run(ss))
    wall = time.perf_counter() - t0

    alive = np.asarray(traj["alive"]).astype(bool)          # [T, N]
    locations = np.asarray(traj["boundary"]["location"])    # [T, N, 2]
    t = np.arange(1, alive.shape[0] + 1) * emit_every
    com_col = np.ma.masked_array(
        locations[:, :, 1], mask=~alive
    ).mean(axis=1).filled(np.nan)
    start = float(com_col[0])
    end = float(com_col[-1])

    summary = {
        "scenario": "chemotaxis: run/tumble colony climbing an attractant ramp",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "cells": int(n0),
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "com_along_gradient_um": [round(float(x), 1) for x in com_col[:: max(1, len(t) // 10)]],
        "net_displacement_um": round(end - start, 1),
        "climbed": bool(end > start + 10.0),
    }
    record = "CHEMOTAXIS_SMALL.json" if args.small else "CHEMOTAXIS.json"
    with open(record, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))

    os.makedirs(args.out_dir, exist_ok=True)
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    from lens_tpu.analysis import plot_field_snapshots

    p1 = plot_field_snapshots(
        traj,
        locations=locations,
        dx=10.0,
        n_snapshots=4,
        out_path=os.path.join(args.out_dir, "chemotaxis_snapshots.png"),
    )

    fig, ax = plt.subplots(figsize=(7, 4))
    ax.plot(t, com_col)
    ax.set_xlabel("time (s)")
    ax.set_ylabel("population center of mass, gradient axis (um)")
    ax.set_title("chemotactic drift up the attractant ramp")
    p2 = os.path.join(args.out_dir, "chemotaxis_drift.png")
    fig.tight_layout()
    fig.savefig(p2, dpi=110)
    print(f"plots: {p1} {p2}")


if __name__ == "__main__":
    main()
