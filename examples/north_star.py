"""THE north-star scenario, actually executed end to end.

BASELINE.json: "100,000-cell E. coli colony, 1 simulated hour, dt=1s" at
>= 10,000 agent-steps/sec/chip. The benchmarks measure windows of it;
this script RUNS it — 3600 simulated seconds of the 100k-cell
mixed-species colony (config 4: two distinct process sets, one 256x256
two-molecule lattice), with segmented emission, then writes a summary
JSON and the standard plots.

    python examples/north_star.py            # full hour on the TPU
    python examples/north_star.py --small    # 2-minute CPU-sized check

Writes NORTH_STAR.json + out/north_star_*.png.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny CPU-sized variant (shape/cells/time scaled down)")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lens_tpu.models.composites import mixed_species_lattice

    if args.small:
        cap_each, n_each, shape, total, seg = 1024, 200, (32, 32), 120.0, 30.0
    else:
        # Real division headroom (VERDICT r4): 50k founders in 256k rows
        # per species = two full doublings plus margin at the default
        # ~23-minute doubling, so the hour runs with division_backlog 0
        # throughout (the summary records the max backlog to prove it).
        # 256k (not 512k) also keeps the lineage-id stride inside int32
        # for the 3600-step run: 3600 * 2 * 262144 = 1.9e9 < 2^31.
        cap_each, n_each, shape, total, seg = 262144, 50000, (256, 256), 3600.0, 300.0

    multi, _ = mixed_species_lattice(
        {"capacity": {"ecoli": cap_each, "scavenger": cap_each},
         "shape": shape}
    )
    state = multi.initial_state(
        {"ecoli": n_each, "scavenger": n_each}, jax.random.PRNGKey(0)
    )

    n_segments = int(round(total / seg))
    emit_every = max(int(seg) // 10, 1)   # ~10 emits per segment
    # ONE jitted segment program reused across the loop. Calling the raw
    # multi.run per segment retraces (scan_schedule's closures are fresh
    # per call) — measured on the full scenario: every 300 sim-s segment
    # paid the full ~43 min XLA-CPU compile again. The Experiment layer
    # caches its programs the same way (parallel.base.cached_jit).
    window = jax.jit(lambda s: multi.run(s, seg, 1.0, emit_every=emit_every))
    t_wall0 = time.perf_counter()
    alive_series = []
    glc_series = []
    backlog_series = []
    trajs = []
    for k in range(n_segments):
        t0 = time.perf_counter()
        state, traj = window(state)
        jax.block_until_ready(state)
        wall = time.perf_counter() - t0
        alive = {
            name: int(jnp.sum(state.species[name].alive))
            for name in multi.species
        }
        glc = float(jnp.sum(state.fields[multi.lattice.index("glucose")]))
        alive_series.append(alive)
        glc_series.append(glc)
        backlog_max = max(
            int(np.asarray(traj[name]["division_backlog"]).max())
            for name in multi.species
            if "division_backlog" in traj[name]
        )
        backlog_series.append(backlog_max)
        trajs.append(
            {  # keep only small per-segment series for plotting
                name: {"alive": np.asarray(traj[name]["alive"])}
                for name in multi.species
            }
        )
        rate = (sum(alive.values()) * seg) / wall
        print(
            f"segment {k + 1}/{n_segments}: sim t={int((k + 1) * seg)}s "
            f"wall={wall:.1f}s alive={alive} ~{rate:,.0f} agent-steps/s",
            flush=True,
        )

    wall_total = time.perf_counter() - t_wall0
    total_agents = sum(alive_series[-1].values())
    summary = {
        "scenario": "north star: 100k-cell mixed colony, 1 sim hour, dt=1s"
        if not args.small else "north star (small CPU variant)",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "sim_seconds": total,
        "wall_seconds": round(wall_total, 1),
        "sim_faster_than_real_time_x": round(total / wall_total, 2),
        "final_alive": alive_series[-1],
        # proof the run had real division headroom: 0 means no division
        # was ever suppressed for lack of free rows
        "max_division_backlog": max(backlog_series) if backlog_series else None,
        "mean_agent_steps_per_sec": round(
            sum(sum(a.values()) for a in alive_series) * seg / wall_total, 1
        ),
        "glucose_field_total": glc_series,
    }
    out_name = "NORTH_STAR.json" if not args.small else "NORTH_STAR_SMALL.json"
    with open(out_name, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps({k: v for k, v in summary.items()
                      if k != "glucose_field_total"}))

    # population curves per species across the whole run
    os.makedirs(args.out_dir, exist_ok=True)
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(7, 4))
    for name in multi.species:
        counts = np.concatenate(
            [t[name]["alive"].sum(axis=1) for t in trajs]
        )
        ax.plot(
            np.arange(1, len(counts) + 1) * emit_every, counts, label=name
        )
    ax.set_xlabel("simulated time (s)")
    ax.set_ylabel("live cells")
    ax.set_title(summary["scenario"])
    ax.legend()
    fig.tight_layout()
    plot = os.path.join(args.out_dir, "north_star_population.png")
    fig.savefig(plot, dpi=110)
    print(f"plot: {plot}")


if __name__ == "__main__":
    main()
