"""Colony-scale diauxie on the data-layer core-carbon network.

The classic Covert–Palsson regulated-FBA experiment (the reference's
metabolism lineage, SURVEY.md §2 "Metabolism"): cells on a glucose +
lactose lattice eat glucose first (catabolite repression gates
``lcts_uptake`` and the lac genes), overflow acetate while doing it,
then derepress lactose uptake when glucose runs out and finally clean up
the secreted acetate — three growth phases from one boolean-regulated
LP, solved per cell per second on the device.

Everything here is data-layer content: the network and its regulation
rules come from ``lens_tpu/data/ecoli_core_{species,reactions}.tsv``.

    python examples/diauxie.py            # chip-sized (4k cells)
    python examples/diauxie.py --small    # 2-minute CPU-sized check

Writes DIAUXIE.json (DIAUXIE_SMALL.json for --small) + out/diauxie_*.png.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="tiny CPU-sized variant (cells/lattice/time scaled)")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import numpy as np

    from lens_tpu.models.composites import rfba_lattice

    if args.small:
        cap, n0, shape, total, emit_every = 128, 48, (16, 16), 240.0, 8
    else:
        cap, n0, shape, total, emit_every = 4096, 2048, (128, 128), 900.0, 10

    spatial, comp = rfba_lattice(
        {
            "capacity": cap,
            "shape": shape,
            "size": (float(shape[0]), float(shape[1])),  # 1 um bins
            "metabolism": {"network": "ecoli_core"},
            "expression": {"genes": "ecoli_core"},
            # glucose AND lactose from t=0; the phases come from the
            # regulation rules, not from a media timeline
            "initial": {"glc": 6.0, "lcts": 6.0, "o2": 8.0, "nh4": 8.0},
        }
    )
    metab = comp.processes["metabolism"]
    mol_index = {m: i for i, m in enumerate(metab.external)}
    rxn_index = {r: j for j, r in enumerate(metab.reactions)}

    ss = spatial.initial_state(n0, jax.random.PRNGKey(0))
    run = jax.jit(lambda s: spatial.run(s, total, 1.0, emit_every=emit_every))

    t0 = time.perf_counter()
    final, traj = jax.block_until_ready(run(ss))
    wall = time.perf_counter() - t0

    # -- phase bookkeeping ---------------------------------------------------
    fields = np.asarray(traj["fields"])                  # [T, M, H, W]
    alive = np.asarray(traj["alive"]).astype(bool)       # [T, N]
    fluxes = np.asarray(traj["fluxes"]["reaction_fluxes"])  # [T, N, R]
    # scan_schedule emits AFTER each emit_every block (no t=0 frame), so
    # frame k is sim time (k+1)*emit_every
    t = np.arange(1, fields.shape[0] + 1) * emit_every

    totals = {m: fields[:, mol_index[m]].sum(axis=(1, 2)) for m in ("glc", "lcts", "ace")}
    f0 = np.asarray(jax.device_get(ss.fields))           # true t=0 fields
    initial_glc = f0[mol_index["glc"]].sum()
    mean_flux = {}
    for r in ("glc_pts", "lcts_uptake", "pta_ack", "ace_uptake"):
        if r in rxn_index:
            v = fluxes[:, :, rxn_index[r]]
            mean_flux[r] = np.ma.masked_array(v, mask=~alive).mean(axis=1).filled(0.0)

    glc_gone = next(
        (float(t[k]) for k in range(len(t)) if totals["glc"][k] < 0.05 * initial_glc),
        None,
    )
    lcts_flux = mean_flux.get("lcts_uptake")
    lcts_started = None
    if lcts_flux is not None:
        lcts_started = next(
            (float(t[k]) for k in range(len(t)) if lcts_flux[k] > 1e-3), None
        )
    summary = {
        "scenario": "colony diauxie (ecoli_core rFBA + 32-gene expression)",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "cells_initial": int(n0),
        "cells_final": int(np.asarray(jax.device_get(final.colony.alive)).sum()),
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "glc_total": [round(float(x), 2) for x in totals["glc"][:: max(1, len(t) // 8)]],
        "lcts_total": [round(float(x), 2) for x in totals["lcts"][:: max(1, len(t) // 8)]],
        "ace_total": [round(float(x), 2) for x in totals["ace"][:: max(1, len(t) // 8)]],
        "t_glucose_exhausted": glc_gone,
        "t_lactose_uptake_on": lcts_started,
        "diauxie_order_ok": (
            glc_gone is not None
            and lcts_started is not None
            and lcts_started >= glc_gone - emit_every
        ),
    }
    record = "DIAUXIE_SMALL.json" if args.small else "DIAUXIE.json"
    with open(record, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))

    # -- plots ---------------------------------------------------------------
    os.makedirs(args.out_dir, exist_ok=True)
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(8, 7), sharex=True)
    for m, color in (("glc", "tab:blue"), ("lcts", "tab:orange"), ("ace", "tab:green")):
        ax1.plot(t, totals[m], label=m, color=color)
    ax1b = ax1.twinx()
    ax1b.plot(t, alive.sum(axis=1), color="gray", linestyle="--", label="live cells")
    ax1.set_ylabel("field total")
    ax1b.set_ylabel("live cells")
    h1, l1 = ax1.get_legend_handles_labels()
    h2, l2 = ax1b.get_legend_handles_labels()
    ax1.legend(h1 + h2, l1 + l2, loc="center right", fontsize=8)
    ax1.set_title("diauxie: glucose, then lactose, then the acetate it spilled")

    for r, series in mean_flux.items():
        ax2.plot(t, series, label=r)
    if glc_gone is not None:
        ax2.axvline(glc_gone, color="gray", linewidth=0.8, linestyle=":")
    ax2.set_xlabel("time (s)")
    ax2.set_ylabel("mean flux (live cells)")
    ax2.legend(fontsize=8)
    fig.tight_layout()
    path = os.path.join(args.out_dir, "diauxie_phases.png")
    fig.savefig(path, dpi=110)
    print(f"plot: {path}")


if __name__ == "__main__":
    main()
