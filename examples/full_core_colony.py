"""Spatially-emergent overflow metabolism on the TRUE e_coli_core.

A dense colony on the canonical 72x95 network (data-layer
``ecoli_core_full``): cells in the crowded center deplete local oxygen
faster than diffusion replaces it, flip to fermentation (PFL/ADH — the
"not o2" regulation plus the stoichiometry itself), and secrete
ethanol + formate + acetate into the field; cells at the aerated edge
keep respiring. No switch is scripted — the aerobic/anaerobic phenotype
split is decided per cell per step by each agent's regulated LP reading
its own bin of the lattice.

    python examples/full_core_colony.py          # chip-sized
    python examples/full_core_colony.py --small  # CPU-sized check

Writes FULL_CORE_COLONY[_SMALL].json + out/full_core_*.png.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from lens_tpu.models.composites import rfba_lattice

    if args.small:
        # short window: the closed box holds ~30 s of nutrients for 64
        # clustered cells; past that everything starves uniformly and
        # the gradient story disappears
        n, shape, total = 64, (16, 16), 30.0
    else:
        n, shape, total = 4096, (64, 64), 600.0

    spatial, _ = rfba_lattice(
        {
            "capacity": n,
            "shape": shape,
            "division": False,            # phenotype map, not growth story
            "motility": {"sigma": 0.0},
            "metabolism": {"network": "ecoli_core_full"},
            # thin the oxygen supply so the crowded center goes anoxic
            # while the aerated rim still respires
            "initial": {"o2": 1.5, "glc": 20.0},
        }
    )

    # Clustered placement: a Gaussian blob of cells in the center makes
    # the crowding gradient (uniform random placement would aerate all).
    key = jax.random.PRNGKey(0)
    h, w = shape
    center = jnp.asarray([h / 2.0, w / 2.0]) * spatial.lattice.dx
    spread = 0.12 * h * spatial.lattice.dx
    locs = center + spread * jax.random.normal(key, (n, 2))
    size = jnp.asarray([h * spatial.lattice.dx, w * spatial.lattice.dx])
    locs = jnp.clip(locs, 0.05 * size, 0.95 * size)
    state = spatial.initial_state(n, key, locations=locs)

    t0 = time.perf_counter()
    state, traj = spatial.run(state, total, 1.0, emit_every=max(int(total) // 10, 1))
    jax.block_until_ready(state)
    wall = time.perf_counter() - t0

    lat = spatial.lattice
    o2 = np.asarray(state.fields[lat.index("o2")])
    etoh = np.asarray(state.fields[lat.index("etoh")])
    formate = np.asarray(state.fields[lat.index("for")])
    # center vs edge: quarter-box around the middle vs the frame
    ci = slice(h // 2 - h // 4, h // 2 + h // 4)
    center_o2 = float(o2[ci, ci].mean())
    edge_o2 = float(np.concatenate([o2[0], o2[-1], o2[:, 0], o2[:, -1]]).mean())
    center_etoh = float(etoh[ci, ci].mean())
    edge_etoh = float(
        np.concatenate([etoh[0], etoh[-1], etoh[:, 0], etoh[:, -1]]).mean()
    )

    # per-cell phenotype at the end: fermenting = PFL carries flux
    proc = spatial.colony.compartment.processes["metabolism"]
    v = np.asarray(state.colony.agents["fluxes"]["reaction_fluxes"])
    alive = np.asarray(state.colony.alive)
    pfl = v[:, proc.reactions.index("PFL")]
    cytbd = v[:, proc.reactions.index("CYTBD")]
    fermenting = int(((pfl > 0.01) & alive).sum())
    respiring = int(((cytbd > 0.01) & alive).sum())

    summary = {
        "scenario": "spatially-emergent overflow on ecoli_core_full (72x95)"
        + (" [small]" if args.small else ""),
        "backend": jax.default_backend(),
        "agents": n,
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "center_o2": center_o2,
        "edge_o2": edge_o2,
        "center_etoh": center_etoh,
        "edge_etoh": edge_etoh,
        "fermenting_cells": fermenting,
        "respiring_cells": respiring,
        "formate_total": float(formate.sum()),
        "lp_converged_frac": float(
            np.asarray(state.colony.agents["fluxes"]["lp_converged"])[alive].mean()
        ),
    }
    name = "FULL_CORE_COLONY_SMALL.json" if args.small else "FULL_CORE_COLONY.json"
    with open(name, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary))

    os.makedirs(args.out_dir, exist_ok=True)
    import matplotlib

    matplotlib.use("Agg", force=False)
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 3, figsize=(13, 4))
    for ax, (field, title) in zip(
        axes,
        [(o2, "O2 (anoxic pocket)"), (etoh, "ethanol (fermentation)"),
         (formate, "formate (PFL route)")],
    ):
        im = ax.imshow(field, origin="lower", cmap="viridis")
        ax.set_title(title)
        fig.colorbar(im, ax=ax, shrink=0.8)
    fig.suptitle(summary["scenario"])
    fig.tight_layout()
    plot = os.path.join(args.out_dir, "full_core_fields.png")
    fig.savefig(plot, dpi=110)
    print(f"plot: {plot}")


if __name__ == "__main__":
    main()
