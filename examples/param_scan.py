"""Parameter scan via lens_tpu.sweep: a batch-culture yield curve.

Scans initial glucose with a declarative GRID sweep over the
wcEcoli-minimal cell (config 3's metabolism + expression + division
composite) on the sweep subsystem's direct-ensemble backend: the whole
dose grid packs onto the replicate axis of one compiled
``colony.Ensemble`` program, each trial keyed by its own
``(sweep_seed, trial_index)``-derived PRNG seed. Each replicate is a
batch culture — cells burn their finite substrate and growth stops —
so the objective (final live biomass, ``final_live_sum`` over
``global/mass``) tracks the dose: the classic substrate-limited yield
curve, with population counts responding only once a dose buys a full
volume doubling. The reference would submit one experiment cluster per
dose (SURVEY.md §3.3); here it is ~15 lines of spec, and the same spec
fed to ``python -m lens_tpu sweep`` runs it from the CLI with ledger
resume (docs/sweeps.md).

    python examples/param_scan.py            # chip-sized (16 doses x 1k cells)
    python examples/param_scan.py --small    # CPU-sized check (6 doses x 32)

Writes PARAM_SCAN.json (PARAM_SCAN_SMALL.json for --small) +
out/param_scan.png (dose-response curve).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import numpy as np

    from lens_tpu.sweep import run_sweep

    if args.small:
        doses_n, n, total, emit_every = 6, 32, 450.0, 10
    else:
        doses_n, n, total, emit_every = 16, 1024, 600.0, 10

    # log-spaced doses spanning sub-Km starvation to saturation
    # (network Km for glucose is 0.5 mM — processes/metabolism.py)
    doses = np.logspace(-1.5, 1.0, doses_n)

    spec = {
        "composite": "minimal_wcecoli",
        "space": {
            "kind": "grid",
            "params": {"metabolites/glc": {"grid": [float(d) for d in doses]}},
        },
        "seed": 0,
        "horizon": total,
        "emit_every": emit_every,
        "n_agents": n // 4,
        "capacity": n,
        "objective": {
            "path": "global/mass",
            "reduction": "final_live_sum",
            "mode": "max",
        },
        # dense finite grid -> the one-compile vmapped-Ensemble backend
        "backend": {"kind": "ensemble", "batch": doses_n},
    }

    t0 = time.perf_counter()
    result = run_sweep(spec)
    wall = time.perf_counter() - t0

    # per-dose curves off the per-trial emitted trajectories
    # (trial order == grid order == dose order)
    ts = [result.timeseries[i] for i in range(doses_n)]
    pops = np.asarray(
        [t["alive"][-1].sum() for t in ts]
    )  # [R] final populations
    total_mass = np.asarray(
        [row["objective"] for row in result.table]
    )  # [R] final live biomass (the sweep objective)
    live_counts = np.asarray([t["alive"].sum() for t in ts])
    agent_steps = float(live_counts.sum()) * emit_every

    d = np.asarray(doses)
    summary = {
        "scenario": "glucose dose-response scan, wcEcoli-minimal colony "
        "(lens_tpu.sweep grid space, direct-ensemble backend: one "
        "compiled program, trials on the replicate axis)",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "doses_mM": [round(float(x), 4) for x in d],
        "cells_per_dose": n // 4,
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "final_population_per_dose": [int(p) for p in pops],
        "final_live_mass_per_dose": [round(float(m), 1) for m in total_mass],
        "monotone_dose_response": bool(
            (np.diff(pops) >= 0).all()
            and (np.diff(total_mass) >= 0).all()
            and total_mass[-1] > total_mass[0]
        ),
        "agent_steps_per_sec": round(agent_steps / wall, 1),
        "best_dose_mM": round(
            float(result.best["params"]["metabolites/glc"]), 4
        ),
    }
    record = "PARAM_SCAN_SMALL.json" if args.small else "PARAM_SCAN.json"
    with open(record, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(10, 4))
    ax1.semilogx(d, total_mass, "o-", color="tab:green")
    ax1.set_xlabel("initial glucose (mM)")
    ax1.set_ylabel("final live biomass (fg)")
    ax1.set_title("batch-culture yield vs dose")
    ax2.semilogx(d, pops, "o-")
    ax2.set_xlabel("initial glucose (mM)")
    ax2.set_ylabel(f"population after {total:g} s")
    ax2.set_title("divisions vs dose")
    fig.tight_layout()
    os.makedirs(args.out_dir, exist_ok=True)
    p = os.path.join(args.out_dir, "param_scan.png")
    fig.savefig(p, dpi=120)
    print(f"plot: {p}")


if __name__ == "__main__":
    main()
