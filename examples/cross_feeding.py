"""Network-scale syntrophy: overflow acetate feeds a second species.

Runs the ``rfba_cross_feeding`` composite: an exact-rFBA E. coli colony
(regulated core-carbon LP per cell, lens_tpu.processes.fba_metabolism)
overflow-secretes acetate while growing on glucose; a kinetic scavenger
species lives ENTIRELY off that secretion — its acetate field starts
empty, so every molecule it eats passed through an E. coli cell first.
The two populations couple only through the shared lattice.

    python examples/cross_feeding.py           # chip-sized (2 x 1k cells)
    python examples/cross_feeding.py --small   # CPU-sized check (2 x 16)

Writes CROSS_FEEDING.json (CROSS_FEEDING_SMALL.json for --small) +
out/cross_feeding.png (population + field trajectories).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true")
    ap.add_argument("--out-dir", default="out")
    args = ap.parse_args()

    if args.small:
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax
    import numpy as np

    from lens_tpu.models.composites import rfba_cross_feeding

    if args.small:
        cap, n0, shape, total, emit_every = 16, 8, (8, 8), 120.0, 10
    else:
        cap, n0, shape, total, emit_every = 1024, 512, (64, 64), 600.0, 20

    multi, _ = rfba_cross_feeding(
        {
            "capacity": {"ecoli": cap, "scavenger": cap},
            "shape": shape,
            "size": (float(shape[0]), float(shape[1])),
        }
    )
    ms = multi.initial_state(
        {"ecoli": n0, "scavenger": n0}, jax.random.PRNGKey(0)
    )
    ace_idx = multi.lattice.molecules.index("ace")
    glc_idx = multi.lattice.molecules.index("glc")
    assert float(ms.fields[ace_idx].sum()) == 0.0  # scavenger starts starved

    run = jax.jit(lambda s: multi.run(s, total, 1.0, emit_every=emit_every))
    t0 = time.perf_counter()
    ms, traj = jax.block_until_ready(run(ms))
    wall = time.perf_counter() - t0

    fields = np.asarray(traj["fields"])  # [T, M, H, W]
    ace_total = fields[:, ace_idx].sum(axis=(1, 2))
    glc_total = fields[:, glc_idx].sum(axis=(1, 2))
    pool = np.asarray(ms.species["scavenger"].agents["cell"]["ace_internal"])
    alive_scav = np.asarray(ms.species["scavenger"].alive)
    pops = {
        name: np.asarray(traj[name]["alive"]).sum(axis=1)
        for name in ("ecoli", "scavenger")
    }
    agent_steps = float(sum(p.sum() for p in pops.values())) * emit_every

    summary = {
        "scenario": "rFBA cross-feeding: overflow acetate feeds a "
        "scavenger species (shared-field syntrophy)",
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "capacity_per_species": cap,
        "initial_cells_per_species": n0,
        "sim_seconds": total,
        "wall_seconds": round(wall, 1),
        "acetate_appeared": bool(ace_total[-1] > 0.0),
        "glucose_consumed": bool(glc_total[-1] < glc_total[0]),
        "scavenger_fed": bool(pool[alive_scav].max() > 0.0),
        "final_populations": {k: int(v[-1]) for k, v in pops.items()},
        "agent_steps_per_sec": round(agent_steps / wall, 1),
    }
    record = (
        "CROSS_FEEDING_SMALL.json" if args.small else "CROSS_FEEDING.json"
    )
    with open(record, "w") as f:
        json.dump(summary, f, indent=2)
    print(json.dumps(summary, indent=2))

    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    # run() trajectories carry no __time__ (emitters inject it); one emit
    # per emit_every steps of dt=1 s
    t = np.arange(1, len(ace_total) + 1) * emit_every
    fig, (ax1, ax2) = plt.subplots(2, 1, figsize=(8, 7), sharex=True)
    for name, p in pops.items():
        ax1.plot(t, p, label=name)
    ax1.set_ylabel("live cells")
    ax1.legend()
    ax1.set_title("populations")
    ax2.plot(t, glc_total, label="glucose (total)")
    ax2.plot(t, ace_total, label="acetate (total, overflow-fed)")
    ax2.set_xlabel("time (s)")
    ax2.set_ylabel("field total (mM·bins)")
    ax2.legend()
    ax2.set_title("shared fields")
    fig.tight_layout()
    os.makedirs(args.out_dir, exist_ok=True)
    p = os.path.join(args.out_dir, "cross_feeding.png")
    fig.savefig(p, dpi=120)
    print(f"plot: {p}")


if __name__ == "__main__":
    main()
