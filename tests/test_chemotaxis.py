"""Chemotaxis: receptor adaptation, motor statistics, gradient climbing.

SURVEY.md §2 "Chemotaxis processes": MWC chemoreceptor cluster + flagellar
motor run/tumble. The end-to-end test places a colony in a glucose
gradient and requires net drift up-gradient — the defining behavior.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.colony.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.spatial import SpatialColony
from lens_tpu.processes.chemotaxis import (
    FlagellarMotor,
    MWCChemoreceptor,
    RunTumbleMotility,
)

CHEMO_TOPOLOGY = {
    "receptor": {
        "external": ("boundary", "external"),
        "internal": ("cell",),
    },
    "motor": {"internal": ("cell",)},
    "motility": {"boundary": ("boundary",), "internal": ("cell",)},
}


def chemotaxis_compartment():
    return Compartment(
        processes={
            "receptor": MWCChemoreceptor(),
            "motor": FlagellarMotor(),
            "motility": RunTumbleMotility({"speed": 10.0}),
        },
        topology=CHEMO_TOPOLOGY,
    )


class TestReceptor:
    def comp(self):
        return Compartment(
            processes={"receptor": MWCChemoreceptor()},
            topology={
                "receptor": {
                    "external": ("boundary",),
                    "internal": ("cell",),
                }
            },
        )

    def test_activity_drops_on_attractant_step(self):
        """Attractant step -> activity falls below setpoint (tumble less)."""
        comp = self.comp()
        # adapt at low ligand
        state = comp.initial_state({"boundary": {"glucose": 0.01}})
        adapted, _ = comp.run(state, 500.0, 1.0)
        a0 = float(adapted["cell"]["chemoreceptor_activity"])
        # step the ligand up
        step = jax.tree.map(lambda x: x, adapted)
        step["boundary"]["glucose"] = jnp.asarray(1.0)
        after = comp.step(step, 1.0)
        assert float(after["cell"]["chemoreceptor_activity"]) < a0 * 0.8

    def test_perfect_adaptation(self):
        """After a step, activity relaxes back toward the setpoint."""
        comp = self.comp()
        state = comp.initial_state({"boundary": {"glucose": 0.01}})
        adapted, _ = comp.run(state, 500.0, 1.0)
        step = jax.tree.map(lambda x: x, adapted)
        step["boundary"]["glucose"] = jnp.asarray(1.0)
        readapted, _ = comp.run(step, 500.0, 1.0)
        np.testing.assert_allclose(
            float(readapted["cell"]["chemoreceptor_activity"]),
            1.0 / 3.0,
            rtol=0.1,
        )


class TestMotor:
    def test_tumble_fraction_rises_with_activity(self):
        comp = Compartment(
            processes={"motor": FlagellarMotor()},
            topology={"motor": {"internal": ("cell",)}},
        )
        key = jax.random.PRNGKey(0)

        def tumble_fraction(activity):
            single = comp.initial_state(
                {"cell": {"chemoreceptor_activity": activity}}
            )
            stacked = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (512,) + x.shape), single
            )
            keys = jax.random.split(key, 512)
            state = stacked
            for t in range(50):
                step_keys = jax.vmap(
                    lambda k, t=t: jax.random.fold_in(k, t)
                )(keys)
                state = jax.vmap(
                    lambda s, k: comp.step(s, 0.1, k)
                )(state, step_keys)
            return float(jnp.mean(state["cell"]["motor_state"]))

        low = tumble_fraction(0.1)
        high = tumble_fraction(0.9)
        assert high > low + 0.2

    def test_motor_state_is_binary(self):
        comp = Compartment(
            processes={"motor": FlagellarMotor()},
            topology={"motor": {"internal": ("cell",)}},
        )
        state = comp.initial_state()
        for t in range(20):
            state = comp.step(state, 0.5, jax.random.PRNGKey(t))
            m = float(state["cell"]["motor_state"])
            assert m in (0.0, 1.0)


class TestGradientClimbing:
    def test_colony_drifts_up_gradient(self):
        """A chemotactic colony in a linear attractant gradient must show
        net displacement toward high concentration vs. its start."""
        comp = chemotaxis_compartment()
        colony = Colony(comp, capacity=256)
        h, w = 32, 32
        lattice = Lattice(
            molecules=["glucose"],
            shape=(h, w),
            size=(320.0, 320.0),
            diffusion=0.0,  # frozen gradient
            initial=0.0,
            timestep=0.1,
        )
        spatial = SpatialColony(
            colony,
            lattice,
            field_ports={
                # sense-only coupling: the chemotaxis cell reads the
                # attractant but does not consume it (exchange=None)
                "glucose": (("boundary", "external", "glucose"), None),
            },
            location_path=("boundary", "location"),
        )
        key = jax.random.PRNGKey(42)
        # start everyone in the middle of the y axis
        locations = jnp.stack(
            [
                jax.random.uniform(key, (256,), minval=0.0, maxval=320.0),
                jnp.full((256,), 160.0),
            ],
            axis=1,
        )
        ss = spatial.initial_state(256, key, locations=locations)
        # linear gradient along y (axis 1 of the field grid = second
        # location coordinate / lattice width axis)
        grad = jnp.linspace(0.0, 1.0, w)
        fields = jnp.broadcast_to(grad[None, None, :], (1, h, w)).copy()
        ss = ss._replace(fields=fields)
        final, _ = spatial.run(ss, 60.0, 0.1)
        y_final = np.asarray(
            final.colony.agents["boundary"]["location"][:, 1]
        )
        drift = float(np.mean(y_final) - 160.0)
        # up-gradient drift, beyond what pure noise would give
        assert drift > 5.0


def test_chemotaxis_schema_has_sense_port():
    """The sense-only wiring above requires the local-env path in the
    schema (the receptor's external port resolved through the topology)."""
    comp = chemotaxis_compartment()
    assert ("boundary", "external", "glucose") in comp.updaters
