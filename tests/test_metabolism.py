"""Regulated kinetic metabolism + transport lookup + derivers."""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.core.engine import Compartment
from lens_tpu.processes.derivers import (
    DeriveConcentrations,
    DeriveVolume,
    DivideCondition,
    MassGrowth,
)
from lens_tpu.processes.metabolism import Metabolism
from lens_tpu.processes.transport_lookup import TransportLookup, bilinear_lookup
from lens_tpu.utils.units import millimolar_to_counts


def metabolism_compartment(config=None):
    return Compartment(
        processes={"metabolism": Metabolism(config)},
        topology={
            "metabolism": {
                "metabolites": ("metabolites",),
                "global": ("global",),
                "fluxes": ("fluxes",),
            }
        },
    )


class TestMetabolism:
    def test_glucose_consumed_mass_produced(self):
        comp = metabolism_compartment()
        state = comp.initial_state({"metabolites": {"glc": 10.0}})
        final, _ = comp.run(state, 100.0, 1.0)
        assert float(final["metabolites"]["glc"]) < 10.0
        assert float(final["global"]["mass"]) > 330.0

    def test_catabolite_repression_diauxie(self):
        """Acetate uptake must stay off while glucose is present, then
        turn on once glucose is exhausted (Covert-Palsson regulation)."""
        comp = metabolism_compartment()
        state = comp.initial_state(
            {"metabolites": {"glc": 2.0, "ace": 5.0}}
        )
        # phase 1: short run, glucose still present -> acetate only grows
        # (overflow) or stays; uptake gate is closed
        mid, _ = comp.run(state, 20.0, 1.0)
        assert float(mid["metabolites"]["ace"]) >= 5.0
        # phase 2: long run, glucose exhausted -> acetate is consumed
        final, _ = comp.run(mid, 2000.0, 1.0)
        assert float(final["metabolites"]["glc"]) < 0.06
        assert float(final["metabolites"]["ace"]) < float(
            mid["metabolites"]["ace"]
        )

    def test_fluxes_emitted(self):
        comp = metabolism_compartment()
        final, _ = comp.run(comp.initial_state(), 5.0, 1.0)
        fluxes = final["fluxes"]["reaction_fluxes"]
        assert fluxes.shape == (3,)
        assert float(fluxes[0]) > 0.0  # glycolysis running

    def test_vmaps(self):
        comp = metabolism_compartment()
        single = comp.initial_state()
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (8,) + x.shape), single
        )
        stepped = jax.vmap(lambda s: comp.step(s, 1.0))(stacked)
        assert stepped["global"]["mass"].shape == (8,)


class TestTransportLookup:
    def test_bilinear_matches_grid_points(self):
        table = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        xg = jnp.asarray([0.0, 1.0])
        yg = jnp.asarray([0.0, 1.0])
        np.testing.assert_allclose(
            float(bilinear_lookup(table, xg, yg, 0.0, 1.0)), 1.0, atol=1e-6
        )
        np.testing.assert_allclose(
            float(bilinear_lookup(table, xg, yg, 0.5, 0.5)), 1.5, atol=1e-6
        )

    def test_edge_clamping(self):
        table = jnp.asarray([[0.0, 1.0], [2.0, 3.0]])
        xg = jnp.asarray([0.0, 1.0])
        yg = jnp.asarray([0.0, 1.0])
        np.testing.assert_allclose(
            float(bilinear_lookup(table, xg, yg, 99.0, 99.0)), 3.0, atol=1e-6
        )

    def test_lookup_matches_mm_source(self):
        """The default table tabulates MM-with-inhibition; lookup at a grid
        point must reproduce the closed form."""
        proc = TransportLookup()
        comp = Compartment(
            processes={"transport": proc},
            topology={
                "transport": {
                    "external": ("boundary",),
                    "internal": ("cell",),
                    "exchange": ("exchange",),
                }
            },
        )
        state = comp.initial_state({"boundary": {"glucose": 10.0}})
        stepped = comp.step(state, 1.0)
        internal = float(stepped["cell"]["glucose_internal"])
        # closed form at internal=0: 0.1 * 10/(0.5+10)
        expected = 0.1 * 10.0 / 10.5
        np.testing.assert_allclose(internal, expected, rtol=1e-3)
        np.testing.assert_allclose(
            float(stepped["exchange"]["glucose_exchange"]),
            -expected,
            rtol=1e-3,
        )


class TestDerivers:
    def grow_derive_compartment(self):
        return Compartment(
            processes={
                "growth": MassGrowth({"rate": 0.001}),
                "derive_volume": DeriveVolume(),
                "divide": DivideCondition(
                    {"variable": "mass", "threshold": 660.0}
                ),
            },
            topology={
                "growth": {"global": ("global",)},
                "derive_volume": {"global": ("global",)},
                "divide": {"global": ("global",)},
            },
        )

    def test_volume_tracks_mass(self):
        comp = self.grow_derive_compartment()
        final, _ = comp.run(comp.initial_state(), 200.0, 1.0)
        mass = float(final["global"]["mass"])
        vol = float(final["global"]["volume"])
        np.testing.assert_allclose(vol, mass / 330.0, rtol=1e-5)
        assert mass > 330.0

    def test_divide_condition_trips_at_double_mass(self):
        comp = self.grow_derive_compartment()
        # ln(2)/0.001 ~ 693s to double
        state = comp.initial_state()
        mid, _ = comp.run(state, 600.0, 1.0)
        assert float(mid["global"]["divide"]) == 0.0
        final, _ = comp.run(mid, 200.0, 1.0)
        assert float(final["global"]["divide"]) == 1.0

    def test_derive_concentrations(self):
        comp = Compartment(
            processes={
                "concs": DeriveConcentrations({"molecules": ("protein",)}),
            },
            topology={
                "concs": {
                    "counts": ("counts",),
                    "global": ("global",),
                    "concentrations": ("concentrations",),
                }
            },
        )
        counts = float(millimolar_to_counts(2.0, 1.5))
        state = comp.initial_state(
            {"counts": {"protein": counts}, "global": {"volume": 1.5}}
        )
        stepped = comp.step(state, 1.0)
        np.testing.assert_allclose(
            float(stepped["concentrations"]["protein"]), 2.0, rtol=1e-5
        )


def test_divide_condition_on_derived_volume():
    """DivideCondition watching DeriveVolume's volume must mirror its
    'set' declaration (regression: hard-coded accumulate broke the
    grow-mass/derive-volume/divide-on-volume composite)."""
    comp = Compartment(
        processes={
            "growth": MassGrowth({"rate": 0.001}),
            "derive_volume": DeriveVolume(),
            "divide": DivideCondition(
                {
                    "variable": "volume",
                    "threshold": 2.0,
                    "default": 1.0,
                    "updater": "set",
                }
            ),
        },
        topology={
            "growth": {"global": ("global",)},
            "derive_volume": {"global": ("global",)},
            "divide": {"global": ("global",)},
        },
    )
    final, _ = comp.run(comp.initial_state(), 800.0, 1.0)
    assert float(final["global"]["volume"]) >= 2.0
    assert float(final["global"]["divide"]) == 1.0


def test_transport_lookup_partial_table_config_rejected():
    import pytest

    with pytest.raises(ValueError, match="needs all of"):
        TransportLookup({"ext_grid": [0.0, 1.0]})
