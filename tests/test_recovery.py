"""Crash-recoverable serving: the WAL, snapshot spills, and the
SIGKILL-at-every-kill-point bitwise pin (round 12).

The contract (docs/serving.md, "Fault tolerance & recovery"): a server
built with ``recover_dir`` WALs every client submit/resubmit/terminal,
spills held snapshots via the checkpoint rename protocol, and — killed
at ANY point and rebuilt over the same directory — produces per-request
result logs bitwise equal to an uninterrupted run's. Finished requests
keep their logs; unfinished ones re-run from their exact inputs, which
the serving determinism contract turns into a bitwise resume.

The quick tests exercise recovery in-process (abandon without close —
the streamer/writer threads are daemons, so this under-approximates a
real kill only in that OS buffers survive; the slow tier SIGKILLs real
subprocesses at every named kill-point, which approximates nothing).
"""

import glob
import json
import os
import signal
import subprocess
import sys

import pytest

from lens_tpu.emit.log import JsonFrameLog
from lens_tpu.serve import (
    DONE,
    ScenarioRequest,
    ServeWal,
    SimServer,
)
from lens_tpu.serve.faults import KILL_SEAMS
from lens_tpu.serve.wal import key_from_json, key_to_json


def _mk(out_dir, recover_dir, composite="toggle_colony", **kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket(
        composite, out_dir=str(out_dir), sink="log",
        recover_dir=str(recover_dir), **kw,
    )


def _lens_bytes(out_dir):
    return {
        os.path.basename(p): open(p, "rb").read()
        for p in glob.glob(os.path.join(str(out_dir), "*.lens"))
    }


class TestServeWal:
    def test_key_json_roundtrip(self):
        key = ("bucket", 3, (("ecoli", 2), ("scav", 1)), "abcd", 64)
        assert key_from_json(
            json.loads(json.dumps(key_to_json(key)))
        ) == key
        assert key_from_json(key_to_json(("held", "req-000001"))) \
            == ("held", "req-000001")

    def test_torn_tail_frame_is_truncated_on_replay(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        wal = ServeWal(path)
        wal.append({"event": "submit", "rid": "req-000000"})
        wal.close()
        size = os.path.getsize(path)
        with open(path, "ab") as f:
            f.write(b"LENS-torn")  # kill mid-append
        wal2 = ServeWal(path)
        assert [e["event"] for e in wal2.events] == ["submit"]
        assert os.path.getsize(path) == size  # torn bytes dropped
        wal2.append({"event": "retire", "rid": "req-000000"})
        wal2.close()
        assert len(ServeWal(path).events) == 2  # clean append after

    def test_begin_refuses_changed_bucket_fingerprint(self, tmp_path):
        path = str(tmp_path / "serve.wal")
        wal = ServeWal(path)
        wal.begin("fp-aaaa", {"toggle_colony": {}})
        wal.close()
        wal2 = ServeWal(path)
        wal2.begin("fp-aaaa", {"toggle_colony": {}})  # same: fine
        with pytest.raises(ValueError, match="fingerprint"):
            wal2.begin("fp-bbbb", {"toggle_colony": {}})
        wal2.close()

    def test_recover_dir_requires_log_sink(self, tmp_path):
        with pytest.raises(ValueError, match="sink='log'"):
            SimServer.single_bucket(
                "toggle_colony", capacity=16,
                recover_dir=str(tmp_path / "wal"),
            )

    def test_changed_bucket_config_refused_at_construction(
        self, tmp_path
    ):
        srv = _mk(tmp_path / "out", tmp_path / "wal")
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.close()
        with pytest.raises(ValueError, match="fingerprint"):
            _mk(tmp_path / "out", tmp_path / "wal", capacity=32)


class TestRecoveryInProcess:
    """Abandon-without-close crashes: replay, re-queue, rehydrate."""

    REQS = [
        dict(composite="toggle_colony", seed=1, horizon=24.0),
        dict(composite="toggle_colony", seed=2, horizon=24.0,
             prefix={"horizon": 8.0},
             overrides={"global": {"volume": 1.1}}),
        dict(composite="toggle_colony", seed=3, horizon=16.0,
             emit={"every": 2}),
    ]

    def _reference(self, tmp_path):
        out = tmp_path / "ref"
        srv = _mk(out, tmp_path / "ref_wal")
        for r in self.REQS:
            srv.submit(dict(r))
        srv.run_until_idle(max_ticks=300)
        srv.close()
        return _lens_bytes(out)

    def test_mid_flight_crash_recovers_bitwise(self, tmp_path):
        ref = self._reference(tmp_path)
        out, wal = tmp_path / "cr", tmp_path / "cr_wal"
        srv = _mk(out, wal)
        for r in self.REQS:
            srv.submit(dict(r))
        srv.tick()
        srv.tick()  # some windows ran, nothing finished
        srv._streamer.drain()  # settle in-flight appends, then vanish
        del srv

        srv2 = _mk(out, wal)
        c = srv2.metrics()["counters"]
        assert c["recovered"] == 3  # every client request re-queued
        srv2.run_until_idle(max_ticks=300)
        assert _lens_bytes(out) == ref  # bitwise, per-request
        # the recovered server keeps serving normally
        extra = srv2.submit(ScenarioRequest(
            composite="toggle_colony", seed=9, horizon=8.0,
        ))
        srv2.run_until_idle(max_ticks=100)
        assert srv2.status(extra)["status"] == DONE
        srv2.close()

    def test_finished_requests_are_not_re_run(self, tmp_path):
        """Requests with a durable streamed event materialize as
        terminal tickets over their existing logs — recovery re-runs
        only what lacks one."""
        ref = self._reference(tmp_path)
        out, wal = tmp_path / "cr", tmp_path / "cr_wal"
        srv = _mk(out, wal)
        first = srv.submit(dict(self.REQS[0]))
        srv.run_until_idle(max_ticks=300)  # finish request 0 alone
        assert srv.status(first)["status"] == DONE
        for r in self.REQS[1:]:
            srv.submit(dict(r))
        srv.tick()
        srv._streamer.drain()
        finished_log = open(
            os.path.join(str(out), f"{first}.lens"), "rb"
        ).read()
        del srv

        srv2 = _mk(out, wal)
        c = srv2.metrics()["counters"]
        assert c["recovered"] == 2  # only the unfinished pair
        assert srv2.status(first)["status"] == DONE  # replayed terminal
        assert srv2.result(first).endswith(f"{first}.lens")
        srv2.run_until_idle(max_ticks=300)
        srv2.close()
        got = _lens_bytes(out)
        assert got == ref
        # the finished request's log was never touched, not re-written
        assert got[f"{first}.lens"] == finished_log

    def test_resubmit_chain_recovers_from_spilled_hold(self, tmp_path):
        """A continuation killed mid-run re-queues from the parent's
        SPILLED snapshot (rehydrated, not recomputed), and the
        recovered parent stays resubmittable — the stochastic
        hybrid_cell composite, so bitwise equality is meaningful."""
        def chain(out, wal, crash):
            srv = _mk(out, wal, composite="hybrid_cell",
                      window=4, capacity=8)
            parent = srv.submit(ScenarioRequest(
                composite="hybrid_cell", seed=3, horizon=8.0,
                hold_state=True,
            ))
            srv.run_until_idle(max_ticks=200)
            cont = srv.resubmit(parent, 8.0)
            if crash:
                srv.tick()
                srv._streamer.drain()
                del srv
                return parent, cont
            srv.run_until_idle(max_ticks=200)
            srv.close()
            return parent, cont

        ref_out = tmp_path / "ref"
        chain(ref_out, tmp_path / "ref_wal", crash=False)
        ref = _lens_bytes(ref_out)

        out, wal = tmp_path / "cr", tmp_path / "cr_wal"
        parent, cont = chain(out, wal, crash=True)
        srv2 = _mk(out, wal, composite="hybrid_cell",
                   window=4, capacity=8)
        assert srv2.status(parent)["status"] == DONE
        assert srv2.metrics()["counters"]["recovered"] == 1
        srv2.run_until_idle(max_ticks=300)
        assert srv2.status(cont)["status"] == DONE
        assert _lens_bytes(out) == ref
        # held snapshot was re-pinned from its spill: still extendable
        again = srv2.resubmit(parent, 4.0)
        srv2.run_until_idle(max_ticks=200)
        assert srv2.status(again)["status"] == DONE
        srv2.close()

    def test_released_hold_is_replayed_as_released(self, tmp_path):
        srv = _mk(tmp_path / "out", tmp_path / "wal")
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.release_state(rid)
        srv.close()
        srv2 = _mk(tmp_path / "out", tmp_path / "wal")
        with pytest.raises(ValueError, match="no final state"):
            srv2.resubmit(rid, 8.0)  # the release survived the restart
        assert srv2.snapshots.refs_total() == 0
        srv2.close()


def _run_cli(args, cwd, expect_kill=False, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "lens_tpu", "serve", *args],
        cwd=cwd, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}"
        )
    return proc


_CLI_REQS = [
    {"seed": 1, "horizon": 24.0, "hold_state": True},
    {"seed": 2, "horizon": 24.0, "prefix": {"horizon": 8.0},
     "overrides": {"global": {"volume": 1.1}}},
    {"seed": 3, "horizon": 16.0},
]


def _kill_point_roundtrip(tmp_path, repo_root, seam, composite,
                          extra_flags=()):
    """SIGKILL a real serve process at ``seam``, recover over the same
    dir, and return (reference bytes, recovered bytes)."""
    reqs = tmp_path / "reqs.json"
    reqs.write_text(json.dumps(_CLI_REQS))
    base = [
        "--composite", composite, "--capacity", "8", "--lanes", "2",
        "--window", "4", "--requests", str(reqs), *extra_flags,
    ]
    tag = seam.replace(".", "_")
    ref_out = tmp_path / f"ref_{tag}"
    _run_cli(
        base + ["--out-dir", str(ref_out),
                "--recover-dir", str(tmp_path / f"ref_wal_{tag}")],
        repo_root,
    )
    out = tmp_path / f"out_{tag}"
    wal = tmp_path / f"wal_{tag}"
    faults = tmp_path / f"faults_{tag}.json"
    faults.write_text(json.dumps([{"kind": "kill", "at": seam}]))
    _run_cli(
        base + ["--out-dir", str(out), "--recover-dir", str(wal),
                "--faults", str(faults)],
        repo_root, expect_kill=True,
    )
    _run_cli(
        base + ["--out-dir", str(out), "--recover-dir", str(wal)],
        repo_root,
    )
    return _lens_bytes(ref_out), _lens_bytes(out)


@pytest.fixture(scope="module")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestKillPoints:
    """A real SIGKILL through the CLI, recovered over the same dir —
    the quick-tier representative; the slow tier sweeps EVERY seam."""

    def test_kill_at_window_dispatch_recovers_bitwise(
        self, tmp_path, repo_root
    ):
        ref, got = _kill_point_roundtrip(
            tmp_path, repo_root, "window.dispatched", "toggle_colony"
        )
        assert set(ref) <= set(got)  # recovery may add later requests
        for name, data in ref.items():
            assert got[name] == data, f"{name} differs after recovery"


@pytest.mark.slow
class TestKillPointsExhaustive:
    """SIGKILL at EVERY named kill-point, stochastic composite,
    pipeline on, check_finite armed — the full ISSUE-10 chaos pin."""

    @pytest.mark.parametrize(
        "seam",
        # resubmit.walled needs a resubmit-driving client (covered
        # in-process above); result.* seams fire only with the result
        # cache armed (tests/test_results.py runs that drill); the CLI
        # list exercises the rest
        [s for s in KILL_SEAMS
         if s != "resubmit.walled" and not s.startswith("result.")],
    )
    def test_kill_everywhere_recovers_bitwise(
        self, tmp_path, repo_root, seam
    ):
        ref, got = _kill_point_roundtrip(
            tmp_path, repo_root, seam, "hybrid_cell",
            extra_flags=("--check-finite", "window"),
        )
        assert ref, "reference run produced no logs?"
        for name, data in ref.items():
            assert got[name] == data, f"{name} differs after {seam}"


class TestJsonFrameLogShared:
    """The framing layer the ledger AND the WAL ride (emit/log.py)."""

    def test_group_commit_policy_defers_fsync_not_write(self, tmp_path):
        path = str(tmp_path / "ev.log")
        log = JsonFrameLog(path, fsync_every=False)
        log.append({"a": 1})
        # flushed to the OS even before sync(): a reader sees it now
        assert len(JsonFrameLog(str(tmp_path / "ev.log")).events) == 1
        log.sync()
        log.close()

    def test_undecodable_complete_frame_raises(self, tmp_path):
        from lens_tpu.emit.log import frame

        path = str(tmp_path / "bad.log")
        with open(path, "wb") as f:
            f.write(frame(b"\xff\xfenot json"))
        with pytest.raises(ValueError, match="not an event log"):
            JsonFrameLog(path)
