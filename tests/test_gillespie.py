"""Stochastic kinetics: tau-leap vs exact SSA vs analytic moments (config 4).

Correctness model (SURVEY.md §7 "Gillespie on TPU"): the device path is
tau-leaping, validated against (a) closed-form stationary moments of the
expression network and (b) the exact Gillespie direct-method oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.colony import Colony
from lens_tpu.models import hybrid_cell
from lens_tpu.ops.gillespie import ssa_exact, tau_leap_window


# birth-death: 0 --k--> X --gamma--> 0; stationary X ~ Poisson(k/gamma)
_BD_STOICH = jnp.asarray([[1.0], [-1.0]])


def _bd_propensity(k, gamma):
    return lambda x: jnp.stack([jnp.asarray(k), gamma * x[0]])


def test_tau_leap_birth_death_stationary_moments():
    """Ensemble mean AND variance match Poisson(k/gamma) stationary law."""
    k, gamma = 8.0, 0.4  # stationary mean = var = 20
    n_agents = 2048
    keys = jax.random.split(jax.random.PRNGKey(0), n_agents)

    @jax.jit
    @jax.vmap
    def run(key):
        # 60 s, tau = 0.25 s: well past the 1/gamma = 2.5 s relaxation time
        return tau_leap_window(
            key, jnp.asarray([0.0]), _BD_STOICH,
            _bd_propensity(k, gamma), 60.0, 240,
        )[0]

    x = np.asarray(run(keys))
    mean, var = x.mean(), x.var()
    assert abs(mean - 20.0) < 0.5, mean
    assert abs(var - 20.0) < 2.5, var


def test_tau_leap_matches_exact_ssa():
    """Tau-leap ensemble mean vs the exact direct-method oracle."""
    k, gamma = 3.0, 0.3
    t_end = 12.0
    rng = np.random.default_rng(7)
    stoich_np = np.asarray([[1.0], [-1.0]])

    def prop_np(x):
        return np.asarray([k, gamma * x[0]])

    exact = np.asarray(
        [ssa_exact(rng, np.zeros(1), stoich_np, prop_np, t_end)[0]
         for _ in range(400)]
    )

    keys = jax.random.split(jax.random.PRNGKey(1), 2048)

    @jax.jit
    @jax.vmap
    def run(key):
        return tau_leap_window(
            key, jnp.asarray([0.0]), _BD_STOICH,
            _bd_propensity(k, gamma), t_end, 120,
        )[0]

    leap = np.asarray(run(keys))
    # transient at t=12: mean = (k/g)(1 - exp(-g t)) = 9.73
    expected = (k / gamma) * (1 - np.exp(-gamma * t_end))
    assert abs(exact.mean() - expected) < 0.6, exact.mean()
    assert abs(leap.mean() - exact.mean()) < 0.6, (leap.mean(), exact.mean())


def test_tau_leap_never_negative():
    """Aggressive decay + big tau: the cap/clamp keeps counts >= 0."""
    stoich = jnp.asarray([[-3.0]])
    prop = lambda x: jnp.stack([10.0 * x[0]])
    keys = jax.random.split(jax.random.PRNGKey(2), 512)
    out = jax.vmap(
        lambda k: tau_leap_window(k, jnp.asarray([5.0]), stoich, prop, 4.0, 4)
    )(keys)
    assert float(jnp.min(out)) >= 0.0


def test_hybrid_colony_mixed_species():
    """Config 4 shape: one SPMD colony, two species with different k_tx
    (parameters-as-state), hybrid ODE+tau-leap, protein means separate."""
    # growth fast enough that cells actually divide within the run
    comp = hybrid_cell({"expression": {"d_p": 0.1}, "growth": {"rate": 0.01}})
    capacity = 256
    colony = Colony(comp, capacity, division_trigger=("global", "divide"))
    # species A (rows < 128): k_tx = 0.2; species B: k_tx = 2.0
    k_tx = jnp.where(jnp.arange(capacity) < 128, 0.2, 2.0)
    n_alive = 200
    cs = colony.initial_state(
        n_alive,
        overrides={"rates": {"k_tx": k_tx}},
        key=jax.random.PRNGKey(3),
    )
    out, traj = jax.jit(
        lambda s: colony.run(s, 120.0, 1.0, emit_every=120)
    )(cs)

    alive = np.asarray(out.alive)
    assert alive.sum() > n_alive, "expected divisions (exercises dividers)"
    protein = np.asarray(out.agents["counts"]["protein"])
    glucose = np.asarray(out.agents["cell"]["glucose_internal"])
    assert np.isfinite(protein).all() and np.isfinite(glucose).all()
    mean_a = protein[:128][alive[:128]].mean()
    mean_b = protein[128:][alive[128:]].mean()
    # E[p] = k_tx*k_tl/(d_m*d_p): A -> 40, B -> 400
    assert mean_a < 100 < mean_b, (mean_a, mean_b)
    assert glucose[alive].min() > 0.0  # the ODE half ran too
    # binomial divider kept counts integral through the divisions
    assert np.allclose(protein, np.round(protein))
