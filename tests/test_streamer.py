"""The round-10 serve pipeline: background streaming, batched flush,
deferred hold_state, and the pins that keep it honest.

The load-bearing claims, in this repo's bitwise culture:

- pipelined == synchronous == solo, BITWISE, including the stochastic
  tau-leap composite — the pipeline reorders WHEN host work happens,
  never what bits it projects;
- a tailing reader under the batched-flush writer still sees only
  whole frames and resumes across a torn trailing frame;
- backpressure really stalls the scheduler (bounded staleness), and a
  stream-thread failure really surfaces in ``tick()``;
- ``close()`` drains/joins the streamer and writes ``server_meta.json``
  even when the driver is unwinding an exception.
"""

import json
import os
import threading
import time

import jax
import numpy as np
import pytest

from lens_tpu.emit import LogEmitter
from lens_tpu.emit.log import (
    FramedWriter,
    encode_record,
    frame,
    read_experiment,
    tail_records,
)
from lens_tpu.serve import ScenarioRequest, SimServer
from lens_tpu.serve.streamer import (
    LaneSlice,
    Streamer,
    WindowItem,
    subsample_rows,
)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _serve_one(submissions, target_seed, composite="toggle_colony",
               **kw):
    kw.setdefault("lanes", 4)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    srv = SimServer.single_bucket(composite, **kw)
    target = None
    for sub in submissions:
        rid = srv.submit(ScenarioRequest(composite=composite, **sub))
        if sub.get("seed") == target_seed:
            target = rid
    srv.run_until_idle(max_ticks=300)
    out = srv.result(target)
    srv.close()
    return out


class TestSubsampleRows:
    def test_matches_the_replaced_python_loop(self):
        for first in (0, 1, 3, 7, 40):
            for n_valid in (0, 1, 5, 8, 33):
                for every in (1, 2, 3, 4, 7):
                    ref = [
                        r for r in range(n_valid)
                        if (first + r + 1) % every == 0
                    ]
                    got = subsample_rows(first, n_valid, every)
                    np.testing.assert_array_equal(got, ref)


class TestPipelinedParity:
    """solo == co-batched == pipelined, bitwise — the r10 contract."""

    def test_pipelined_equals_sync_stochastic_cobatch(self):
        """hybrid_cell (tau-leap Gillespie): the composite where any
        pipeline-induced reordering of device work would show."""
        subs = [
            {"seed": 7, "horizon": 8.0},
            {"seed": 3, "horizon": 24.0},
            {"seed": 11, "horizon": 40.0},
            {"seed": 5, "horizon": 16.0},
        ]
        piped = _serve_one(
            subs, 3, composite="hybrid_cell", pipeline="on"
        )
        sync = _serve_one(
            subs, 3, composite="hybrid_cell", pipeline="off"
        )
        solo = _serve_one(
            [{"seed": 3, "horizon": 24.0}], 3,
            composite="hybrid_cell", pipeline="on",
        )
        assert _leaves_equal(piped, sync)
        assert _leaves_equal(piped, solo)

    def test_pipelined_emit_spec_parity(self):
        """Path filter + every-k subsample run on the stream thread;
        bits and row selection must match the synchronous path."""
        sub = {
            "seed": 2, "horizon": 24.0,
            "emit": {"paths": ["global"], "every": 4},
        }
        piped = _serve_one([sub], 2, pipeline="on")
        sync = _serve_one([sub], 2, pipeline="off")
        np.testing.assert_array_equal(
            piped["__times__"], [4.0, 8.0, 12.0, 16.0, 20.0, 24.0]
        )
        assert _leaves_equal(piped, sync)

    def test_pipelined_resubmit_chain_stays_bitwise(self):
        """Deferred (device-side) hold_state capture: a pipelined
        resubmit chain must equal one long request, and must also
        equal the synchronous chain's bits."""

        def chain(pipeline):
            srv = SimServer.single_bucket(
                "hybrid_cell", lanes=4, window=8, capacity=16,
                pipeline=pipeline,
            )
            one_shot = srv.submit(ScenarioRequest(
                composite="hybrid_cell", seed=3, horizon=24.0
            ))
            rid = srv.submit(ScenarioRequest(
                composite="hybrid_cell", seed=3, horizon=8.0,
                hold_state=True,
            ))
            srv.run_until_idle(max_ticks=300)
            parts = [srv.result(rid)]
            for _ in range(2):
                rid = srv.resubmit(rid, extra_horizon=8.0)
                srv.run_until_idle(max_ticks=300)
                parts.append(srv.result(rid))
            stitched = jax.tree.map(
                lambda *xs: np.concatenate(
                    [np.asarray(x) for x in xs]
                ),
                *parts,
            )
            ref = srv.result(one_shot)
            srv.close()
            return stitched, ref

        piped, piped_ref = chain("on")
        sync, _ = chain("off")
        assert _leaves_equal(piped, piped_ref)
        assert _leaves_equal(piped, sync)

    def test_pipelined_log_sink_equals_sync_log_sink(self, tmp_path):
        """The full disk path: segments written by the stream thread
        through the batched-flush emitter decode to the same records."""

        def run(pipeline, sub):
            out = str(tmp_path / pipeline)
            srv = SimServer.single_bucket(
                "toggle_colony", lanes=2, window=4, capacity=16,
                out_dir=out, sink="log", pipeline=pipeline,
            )
            rid = srv.submit(ScenarioRequest(
                composite="toggle_colony", **sub
            ))
            srv.run_until_idle(max_ticks=100)
            path = srv.status(rid)["result_path"]
            srv.close()
            return read_experiment(path)

        sub = {"seed": 5, "horizon": 16.0}
        header_p, recs_p = run("on", sub)
        header_s, recs_s = run("off", sub)
        assert header_p["config"]["seed"] == header_s["config"]["seed"]
        assert len(recs_p) == len(recs_s) == 16
        for rp, rs in zip(recs_p, recs_s):
            assert _leaves_equal(rp, rs)


class TestBatchedFlushWriter:
    def _record(self, i):
        return {"x": np.arange(4) + i, "i": np.asarray(i)}

    def test_reader_while_writer_sees_only_whole_frames(self, tmp_path):
        """A tailing reader racing the background batched-flush writer
        must only ever observe complete frames, in order, and end with
        all of them."""
        p = str(tmp_path / "log.lens")
        w = FramedWriter(p, flush_every=3)
        n = 50
        seen = []
        offset = 0
        stop = threading.Event()

        def tail_loop():
            nonlocal offset
            while not stop.is_set():
                recs, offset = tail_records(p, offset)
                seen.extend(recs)

        reader = threading.Thread(target=tail_loop)
        reader.start()
        for i in range(n):
            w.write(encode_record(self._record(i)))
        w.close()
        stop.set()
        reader.join()
        recs, offset = tail_records(p, offset)
        seen.extend(recs)
        assert [int(r["i"]) for r in seen] == list(range(n))

    def test_tail_resumes_across_torn_trailing_frame(self, tmp_path):
        """Batched flush can leave a torn tail on crash; the reader
        stops at the last whole frame and resumes once the tail
        completes — never a duplicate, never a skip."""
        p = str(tmp_path / "log.lens")
        w = FramedWriter(p, flush_every=2)
        for i in range(3):
            w.write(encode_record(self._record(i)))
        w.close()
        torn = frame(encode_record(self._record(3)))
        with open(p, "ab") as f:
            f.write(torn[: len(torn) // 2])
        recs, off = tail_records(p, 0)
        assert [int(r["i"]) for r in recs] == [0, 1, 2]
        with open(p, "ab") as f:
            f.write(torn[len(torn) // 2:])
        recs, off = tail_records(p, off)
        assert [int(r["i"]) for r in recs] == [3]

    def test_log_emitter_flush_every_visibility(self, tmp_path):
        """LogEmitter(flush_every=k): after k records land, a reader
        sees them without any explicit flush call."""
        p = str(tmp_path / "e.lens")
        em = LogEmitter(
            experiment_id="x", path=p, native=False, flush_every=2
        )
        em.emit({"v": np.asarray(1)})  # header + 1 record = 2 frames
        deadline = time.time() + 5.0
        recs = []
        while time.time() < deadline and len(recs) < 2:
            recs, _ = tail_records(p, 0)
        assert len(recs) == 2  # header + the record, whole frames only
        em.close()

    def test_byte_capped_queue_backpressure_still_writes_all(
        self, tmp_path
    ):
        """A cap smaller than one frame forces the producer through
        the backpressure wait on every write (serialized to the
        writer thread); every frame must still land, in order — and a
        frame larger than the cap must not deadlock."""
        p = str(tmp_path / "cap.lens")
        w = FramedWriter(p, flush_every=1, max_queue_bytes=64)
        n = 20
        for i in range(n):
            w.write(encode_record(self._record(i)))
        w.close()
        recs, _ = tail_records(p, 0)
        assert [int(r["i"]) for r in recs] == list(range(n))

    def test_framed_writer_validates(self, tmp_path):
        with pytest.raises(ValueError, match="flush_every"):
            FramedWriter(str(tmp_path / "x.lens"), flush_every=0)
        with pytest.raises(ValueError, match="max_queue_bytes"):
            FramedWriter(str(tmp_path / "z.lens"), max_queue_bytes=0)
        with pytest.raises(ValueError, match="flush_every"):
            LogEmitter(path=str(tmp_path / "y.lens"), native=False,
                       flush_every=0)


class _SlowSink:
    def __init__(self, delay=0.05):
        self.delay = delay
        self.appended = 0
        self.closed = False

    def append(self, tree, times):
        time.sleep(self.delay)
        self.appended += 1

    def close(self):
        self.closed = True


class _BoomSink(_SlowSink):
    def append(self, tree, times):
        raise IOError("disk on fire")


class TestStreamerMechanics:
    def _item(self, sink, close_after=False):
        return WindowItem(
            traj={"x": np.zeros((2, 1, 1))},
            slices=[LaneSlice(
                "r", sink, lane=0, idx=np.arange(2),
                times=np.arange(2.0), close_after=close_after,
            )],
            dispatched_at=time.perf_counter(),
        )

    def test_backpressure_stalls_submit(self):
        s = Streamer(max_inflight=1)
        sink = _SlowSink(delay=0.15)
        assert s.submit(self._item(sink)) == 0.0
        stalled = s.submit(self._item(sink))  # queue full: must wait
        assert stalled > 0.0
        s.drain()
        assert sink.appended == 2
        s.close()

    def test_error_propagates_and_streamer_stops(self):
        s = Streamer(max_inflight=2)
        s.submit(self._item(_BoomSink()))
        with pytest.raises(IOError, match="disk on fire"):
            s.drain()
        with pytest.raises(IOError):
            s.submit(self._item(_SlowSink()))
        with pytest.raises(IOError):
            s.close()

    def test_close_order_appends_before_close(self):
        s = Streamer(max_inflight=2)
        sink = _SlowSink(delay=0.02)
        s.submit(self._item(sink))
        s.submit_close(sink)
        s.drain()
        assert sink.appended == 1 and sink.closed
        s.close()

    def test_streamer_validates(self):
        with pytest.raises(ValueError, match="max_inflight"):
            Streamer(max_inflight=0)


class TestServerPipelineLifecycle:
    def test_sink_error_surfaces_in_tick(self):
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=1, window=4, capacity=16
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0
        ))
        srv.tick()  # admit + window 1 handed to the streamer
        if srv._streamer is not None:
            srv._streamer.drain()

        def boom(tree, times):
            raise IOError("sink exploded")

        srv._results[rid].append = boom
        with pytest.raises(IOError, match="sink exploded"):
            # window 2 streams on the background thread; the failure
            # must surface in the scheduler loop, not vanish
            for _ in range(50):
                srv.tick()
                time.sleep(0.01)
        with pytest.raises(IOError, match="sink exploded"):
            srv.close()

    def test_close_writes_meta_on_exception_path(self, tmp_path):
        """A driver unwinding an exception mid-serve must still get
        drained sinks + server_meta.json from the context manager."""
        out = str(tmp_path / "serve")
        with pytest.raises(RuntimeError, match="driver crashed"):
            with SimServer.single_bucket(
                "toggle_colony", lanes=2, window=4, capacity=16,
                out_dir=out, sink="log",
            ) as srv:
                rid = srv.submit(ScenarioRequest(
                    composite="toggle_colony", seed=1, horizon=8.0
                ))
                srv.tick()
                raise RuntimeError("driver crashed")
        meta_path = os.path.join(out, "server_meta.json")
        assert os.path.exists(meta_path)
        with open(meta_path) as f:
            meta = json.load(f)
        assert meta["counters"]["submitted"] == 1
        # the request's log is complete and closed: whole frames only
        path = os.path.join(out, f"{rid}.lens")
        header, _ = read_experiment(path)
        assert header["config"]["seed"] == 1

    def test_close_is_idempotent_and_joins(self):
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=1, window=4, capacity=16
        )
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        srv.run_until_idle(max_ticks=50)
        thread = srv._streamer._thread
        srv.close()
        srv.close()
        assert not thread.is_alive()

    def test_result_midflight_is_complete_per_request(self):
        """result() of a DONE request must return ALL its records even
        while another request is still running/streaming — the
        per-request completion wait, not a whole-pipe drain."""
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=2, window=4, capacity=16
        )
        short = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        long = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=64.0
        ))
        for _ in range(200):
            srv.tick()
            if srv.status(short)["status"] == "done":
                break
        ts = srv.result(short)  # long may still be mid-flight
        assert len(ts["__times__"]) == 8
        srv.run_until_idle(max_ticks=200)
        assert len(srv.result(long)["__times__"]) == 64
        srv.close()

    def test_tick_after_close_fails_fast(self):
        """Driving a closed server must raise, not deadlock on the
        joined streamer thread's full queue."""
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=1, window=4, capacity=16
        )
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        srv.run_until_idle(max_ticks=50)
        srv.close()
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=8.0
        ))
        with pytest.raises(RuntimeError, match="closed"):
            for _ in range(10):
                srv.tick()

    def test_pipeline_metrics_gauges_populate(self):
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=2, window=4, capacity=16
        )
        for s in range(4):
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=16.0
            ))
        srv.run_until_idle(max_ticks=100)
        snap = srv.metrics()
        assert 0.0 < snap["device_busy_fraction"] <= 1.0
        assert snap["stream_lag_seconds"]["p50"] is not None
        assert snap["host_gap_seconds"]["p50"] is not None
        assert snap["stream_stall_seconds"] >= 0.0
        assert snap["retraces"] == 0
        srv.close()

    def test_pipeline_off_has_no_streamer_thread(self):
        srv = SimServer.single_bucket(
            "toggle_colony", lanes=1, window=4, capacity=16,
            pipeline="off",
        )
        assert srv._streamer is None
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        srv.run_until_idle(max_ticks=50)
        assert srv.status(rid)["status"] == "done"
        # sync mode still feeds the stream gauges (same accounting)
        assert srv.metrics()["device_busy_fraction"] is not None
        srv.close()

    def test_server_validates_pipeline_knobs(self):
        with pytest.raises(ValueError, match="pipeline"):
            SimServer.single_bucket(
                "toggle_colony", capacity=16, pipeline="maybe"
            )
        with pytest.raises(ValueError, match="flush_every"):
            SimServer.single_bucket(
                "toggle_colony", capacity=16, flush_every=0
            )
