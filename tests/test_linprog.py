"""Batched interior-point LP vs a scipy.optimize.linprog oracle.

The solver is the exact-FBA engine (SURVEY.md §7 "hard parts": batched LP
on TPU), so correctness is checked the way §4 prescribes for every
numerical kernel: against an independent CPU oracle on randomized
problems, plus structural tests (vmap batching, jit purity, infeasible
handling).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from lens_tpu.ops.linprog import flux_balance, linprog_box


def random_feasible_lp(rng, m=4, r=9):
    """A random bounded LP guaranteed feasible (b = A @ interior point)."""
    A = rng.normal(size=(m, r))
    lb = -rng.uniform(0.5, 3.0, size=r)
    ub = rng.uniform(0.5, 3.0, size=r)
    x0 = rng.uniform(0.25, 0.75, size=r) * (ub - lb) + lb
    b = A @ x0
    c = rng.normal(size=r)
    return c, A, b, lb, ub


def oracle(c, A, b, lb, ub):
    res = scipy.optimize.linprog(
        c, A_eq=A, b_eq=b, bounds=list(zip(lb, ub)), method="highs"
    )
    assert res.success, res.message
    return res


class TestAgainstScipy:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_problems_match_highs(self, seed):
        rng = np.random.default_rng(seed)
        c, A, b, lb, ub = random_feasible_lp(rng)
        ref = oracle(c, A, b, lb, ub)
        res = linprog_box(
            jnp.asarray(c), jnp.asarray(A), jnp.asarray(b),
            jnp.asarray(lb), jnp.asarray(ub),
        )
        assert bool(res.converged), (res.primal_residual, res.dual_gap)
        scale = 1.0 + abs(ref.fun)
        assert abs(float(res.objective) - ref.fun) / scale < 5e-4
        np.testing.assert_allclose(A @ np.asarray(res.x), b, atol=5e-4)
        assert np.all(np.asarray(res.x) >= lb - 1e-4)
        assert np.all(np.asarray(res.x) <= ub + 1e-4)

    def test_no_equality_constraints(self):
        # Pure box LP: optimum sits at the bound selected by the sign of c.
        c = jnp.asarray([1.0, -2.0, 0.5])
        A = jnp.zeros((0, 3))
        b = jnp.zeros((0,))
        lb = jnp.asarray([-1.0, -1.0, -1.0])
        ub = jnp.asarray([2.0, 2.0, 2.0])
        res = linprog_box(c, A, b, lb, ub)
        np.testing.assert_allclose(
            np.asarray(res.x), [-1.0, 2.0, -1.0], atol=1e-4
        )

    def test_pinned_variable(self):
        # lb == ub pins a variable without breaking the interior method.
        rng = np.random.default_rng(3)
        c, A, b, lb, ub = random_feasible_lp(rng, m=2, r=5)
        lb[0] = ub[0] = 0.7
        x0 = (lb + ub) / 2
        b = A @ x0
        ref = oracle(c, A, b, lb, ub)
        res = linprog_box(
            jnp.asarray(c), jnp.asarray(A), jnp.asarray(b),
            jnp.asarray(lb), jnp.asarray(ub),
        )
        assert abs(float(res.objective) - ref.fun) / (1 + abs(ref.fun)) < 1e-3
        assert abs(float(res.x[0]) - 0.7) < 1e-3


class TestStructure:
    def test_vmap_batches_over_bounds(self):
        """The FBA batching pattern: one network, per-cell bounds."""
        rng = np.random.default_rng(11)
        c, A, b, lb, ub = random_feasible_lp(rng, m=3, r=7)
        scales = np.asarray([0.5, 1.0, 2.0])
        lbs = jnp.asarray(lb[None, :] * scales[:, None])
        ubs = jnp.asarray(ub[None, :] * scales[:, None])
        bs = jnp.asarray(np.stack([b * s for s in scales]))

        batched = jax.jit(
            jax.vmap(
                lambda bb, l, u: linprog_box(
                    jnp.asarray(c), jnp.asarray(A), bb, l, u
                )
            )
        )
        res = batched(bs, lbs, ubs)
        assert res.x.shape == (3, 7)
        for k, s in enumerate(scales):
            ref = oracle(c, A, b * s, lb * s, ub * s)
            assert (
                abs(float(res.objective[k]) - ref.fun) / (1 + abs(ref.fun))
                < 1e-3
            )

    def test_jit_and_grad_free_purity(self):
        rng = np.random.default_rng(5)
        c, A, b, lb, ub = random_feasible_lp(rng)
        args = tuple(jnp.asarray(v) for v in (c, A, b, lb, ub))
        eager = linprog_box(*args)
        jitted = jax.jit(linprog_box)(*args)
        np.testing.assert_allclose(
            np.asarray(eager.x), np.asarray(jitted.x), atol=1e-5
        )

    def test_early_exit_is_a_fixed_point(self):
        """Raising the iteration CAP cannot change the answer.

        The while-loop solve exits when every lane freezes; a frozen
        iterate is a fixed point of the iteration, so n_iter=45 and
        n_iter=200 must give bitwise-identical solutions (this is the
        property that makes the adaptive exit semantically free).
        """
        rng = np.random.default_rng(17)
        c, A, b, lb, ub = random_feasible_lp(rng, m=3, r=7)
        args = tuple(jnp.asarray(v) for v in (c, A, b, lb, ub))
        lo = linprog_box(*args, n_iter=45)
        hi = linprog_box(*args, n_iter=200)
        assert bool(lo.converged)
        np.testing.assert_array_equal(np.asarray(lo.x), np.asarray(hi.x))
        assert int(lo.iterations) == int(hi.iterations)
        assert int(lo.iterations) < 45  # actually exited early

    def test_batched_iteration_counts_are_per_lane(self):
        """Under vmap each lane's `iterations` stops at its own freeze."""
        rng = np.random.default_rng(23)
        c, A, b, lb, ub = random_feasible_lp(rng, m=3, r=7)
        # lane 0: the feasible problem; lane 1: an infeasible variant that
        # must burn the whole cap (freeze never triggers)
        bs = jnp.stack([jnp.asarray(b), jnp.asarray(b) + 100.0])
        res = jax.vmap(
            lambda bb: linprog_box(
                jnp.asarray(c), jnp.asarray(A), bb,
                jnp.asarray(lb), jnp.asarray(ub), n_iter=40,
            )
        )(bs)
        assert bool(res.converged[0]) and not bool(res.converged[1])
        assert int(res.iterations[0]) < 40
        assert int(res.iterations[1]) == 40
        # and the easy lane's answer matches its solo (un-batched) solve
        # to solver tolerance (vmap changes fusion/reduction order, so the
        # freeze can land an iteration apart; near-degenerate optima then
        # move x more than the objective, which is what tol bounds)
        solo = linprog_box(
            jnp.asarray(c), jnp.asarray(A), jnp.asarray(b),
            jnp.asarray(lb), jnp.asarray(ub), n_iter=40,
        )
        scale = 1.0 + abs(float(solo.objective))
        assert (
            abs(float(res.objective[0]) - float(solo.objective)) / scale
            < 1e-3
        )

    def test_infeasible_reports_not_converged(self):
        # x1 + x2 = 10 is unreachable inside [0, 1]^2.
        c = jnp.asarray([1.0, 1.0])
        A = jnp.asarray([[1.0, 1.0]])
        b = jnp.asarray([10.0])
        res = linprog_box(c, A, b, jnp.zeros(2), jnp.ones(2))
        assert not bool(res.converged)
        assert float(res.primal_residual) > 1.0


class TestFluxBalance:
    def test_hand_solvable_network(self):
        """uptake -> A -> biomass chain: growth = uptake bound."""
        # reactions: v0 (-> A), v1 (A -> B), v2 (B ->, biomass)
        S = jnp.asarray(
            [
                [1.0, -1.0, 0.0],   # A
                [0.0, 1.0, -1.0],   # B
            ]
        )
        objective = jnp.asarray([0.0, 0.0, 1.0])
        lb = jnp.zeros(3)
        ub = jnp.asarray([2.0, 10.0, 10.0])
        res = flux_balance(S, objective, lb, ub)
        assert bool(res.converged)
        np.testing.assert_allclose(np.asarray(res.x), [2.0, 2.0, 2.0], atol=1e-4)
        assert abs(float(res.objective) - 2.0) < 1e-4

    def test_branch_picks_higher_yield(self):
        """Two routes A->biomass with different yields: LP takes the better."""
        # v0: -> A (bound 1); v1: A -> 1 bio ; v2: A -> 2 bio (better)
        S = jnp.asarray([[1.0, -1.0, -1.0]])  # A balance
        objective = jnp.asarray([0.0, 1.0, 2.0])
        lb = jnp.zeros(3)
        ub = jnp.asarray([1.0, 5.0, 5.0])
        res = flux_balance(S, objective, lb, ub)
        assert abs(float(res.objective) - 2.0) < 1e-4
        assert float(res.x[1]) < 1e-3  # low-yield route unused


class TestWarmStart:
    """Warm-starting is a HINT: identical acceptance tests, fewer
    iterations on a sequence of related problems (the FBA usage —
    SURVEY.md §2 "Metabolism": one LP per agent per step, environments
    drifting slowly between steps)."""

    def _drifting_bounds(self, t, r, rng_phase):
        lb = jnp.zeros(r)
        ub = jnp.asarray(
            1.0 + 0.5 * np.abs(np.sin(0.05 * t + rng_phase)), jnp.float32
        )
        return lb, ub

    def test_warm_matches_cold_and_cuts_iterations(self):
        # A -> B -> biomass chain with drifting uptake bounds.
        S = jnp.asarray([[1.0, -1.0, 0.0], [0.0, 1.0, -1.0]])
        objective = jnp.asarray([0.0, 0.0, 1.0])
        rng = np.random.default_rng(7)
        phase = rng.uniform(0, 3, size=3)
        warm = None
        iters_cold, iters_warm = [], []
        for t in range(8):
            lb, ub = self._drifting_bounds(t, 3, phase)
            cold = flux_balance(S, objective, lb, ub)
            res = (
                cold
                if warm is None
                else flux_balance(S, objective, lb, ub, warm=warm)
            )
            warm = res.warm
            assert bool(res.converged)
            # same optimum to solver tolerance
            scale = 1.0 + abs(float(cold.objective))
            assert (
                abs(float(res.objective) - float(cold.objective)) / scale
                < 5e-4
            )
            iters_cold.append(int(cold.iterations))
            iters_warm.append(int(res.iterations))
        # After the first step, the warm chain must be strictly cheaper in
        # total (each subsequent problem differs only by a small drift).
        assert sum(iters_warm[1:]) < sum(iters_cold[1:]), (
            iters_warm,
            iters_cold,
        )

    def test_flag_zero_reproduces_cold_bitwise(self):
        from lens_tpu.ops.linprog import WarmStart

        rng = np.random.default_rng(3)
        c, A, b, lb, ub = random_feasible_lp(rng)
        args = (
            jnp.asarray(c), jnp.asarray(A), jnp.asarray(b),
            jnp.asarray(lb), jnp.asarray(ub),
        )
        cold = linprog_box(*args)
        # garbage warm data with flag = 0 must be ignored per-lane
        bogus = WarmStart(
            x=jnp.full_like(cold.x, 123.0),
            y=cold.warm.y * 0 + 9.0,
            z=jnp.full_like(cold.x, 5.0),
            w=jnp.full_like(cold.x, 5.0),
            flag=jnp.asarray(0.0),
        )
        res = linprog_box(*args, warm=bogus)
        np.testing.assert_array_equal(np.asarray(res.x), np.asarray(cold.x))
        assert int(res.iterations) == int(cold.iterations)

    def test_failed_solve_flag_is_zero(self):
        c = jnp.asarray([1.0, 1.0])
        A = jnp.asarray([[1.0, 1.0]])
        b = jnp.asarray([10.0])
        res = linprog_box(c, A, b, jnp.zeros(2), jnp.ones(2))
        assert not bool(res.converged)
        assert float(res.warm.flag) == 0.0

    def test_pack_unpack_roundtrip(self):
        from lens_tpu.ops.linprog import pack_warm, unpack_warm, warm_size

        S = jnp.asarray([[1.0, -1.0, 0.0], [0.0, 1.0, -1.0]])
        objective = jnp.asarray([0.0, 0.0, 1.0])
        res = flux_balance(S, objective, jnp.zeros(3), jnp.ones(3))
        vec = pack_warm(res.warm)
        assert vec.shape == (warm_size(2, 3),)
        ws = unpack_warm(vec, 2, 3)
        for a, b_ in zip(ws, res.warm):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b_))
