"""In-flight suffix dedup (round 18): identical concurrent requests
cost one lane.

A follower never queues and never owns a lane — it rides its leader's
per-lane stream with its OWN sink, so its ``.lens`` log is byte-equal
to the log its solo run would write (the determinism contract makes
the shared window bytes its window bytes). Pinned here:

- **Bytes**: follower log == its own solo run's log, bitwise — deterministic
  AND stochastic composites, pipeline on, through SSE.
- **Lifecycle**: follower cancel detaches without touching the leader;
  leader FAILED poisons followers with the cause; leader
  CANCELLED/TIMEOUT detaches followers back to independent requests.
- **Migration**: coalesced tickets refuse withdrawal (both ends).
- **Recovery**: replayed SUBMITs re-coalesce deterministically.
- **Off switch**: both knobs off leaves the round-17 submit path
  untouched (no fingerprint hashing, no results state).
"""

import json
import os

import pytest

from lens_tpu.serve import (
    CANCELLED,
    DONE,
    FAILED,
    ScenarioRequest,
    SimServer,
)
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.metrics import request_timing_row

BASE = {"composite": "toggle_colony", "seed": 7, "horizon": 32.0}


def _server(tmp_path, tag, **kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    kw.setdefault("sink", "log")
    kw.setdefault("out_dir", str(tmp_path / f"{tag}_out"))
    return SimServer.single_bucket("toggle_colony", **kw)


def _lens(path):
    with open(path, "rb") as f:
        return f.read()


def _solo_reference(tmp_path, reqs, tag="ref", composite=None, **kw):
    """Each request served with dedup OFF: what every rid's own solo
    run writes (solo == co-batched is already pinned upstream)."""
    kw.setdefault("out_dir", str(tmp_path / f"{tag}_out"))
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    kw.setdefault("sink", "log")
    srv = SimServer.single_bucket(
        composite or "toggle_colony", **kw
    )
    rids = [srv.submit(dict(r)) for r in reqs]
    srv.run_until_idle(max_ticks=500)
    out = {r: _lens(srv.status(r)["result_path"]) for r in rids}
    srv.close()
    return out


class TestCoalesce:
    def test_followers_ride_one_lane_bitwise(self, tmp_path):
        ref = _solo_reference(tmp_path, [BASE] * 3)
        srv = _server(tmp_path, "dd", dedup="on")
        rids = [srv.submit(dict(BASE)) for _ in range(3)]
        srv.run_until_idle(max_ticks=300)
        m = srv.metrics()["counters"]
        assert m["suffix_coalesced"] == 2
        assert m["admitted"] == 1  # one lane for the whole group
        assert m["device_seconds_saved"] > 0
        for rid in rids:
            st = srv.status(rid)
            assert st["status"] == DONE
            assert st["steps_done"] == st["horizon_steps"]
            assert _lens(st["result_path"]) == ref[rid], rid
        # satellite: a follower's timing row is complete — it came
        # alive at its leader's admission and streamed to the end
        row = request_timing_row(srv.tickets[rids[1]], 0.0)
        assert row["admitted"] is not None
        assert row["first_window"] is not None
        assert row["last_streamed"] is not None
        srv.close()

    def test_stochastic_composite_pipelined(self, tmp_path):
        """hybrid_cell is stochastic: byte equality is meaningful, not
        an ODE's inevitability."""
        req = {"composite": "hybrid_cell", "seed": 3, "horizon": 8.0}
        ref = _solo_reference(
            tmp_path, [req] * 2, composite="hybrid_cell", window=4,
        )
        srv = SimServer.single_bucket(
            "hybrid_cell", lanes=2, window=4, capacity=16,
            sink="log", out_dir=str(tmp_path / "sto_out"),
            dedup="on", pipeline="on",
        )
        a = srv.submit(dict(req))
        b = srv.submit(dict(req))
        srv.run_until_idle(max_ticks=300)
        assert srv.metrics()["counters"]["suffix_coalesced"] == 1
        assert _lens(srv.status(a)["result_path"]) == ref[a]
        assert _lens(srv.status(b)["result_path"]) == ref[b]
        srv.close()

    def test_distinct_requests_never_coalesce(self, tmp_path):
        srv = _server(tmp_path, "dis", dedup="on")
        srv.submit(dict(BASE))
        srv.submit({**BASE, "seed": 8})
        srv.submit({**BASE, "hold_state": True})  # holds run alone
        srv.run_until_idle(max_ticks=300)
        m = srv.metrics()["counters"]
        assert m["suffix_coalesced"] == 0 and m["admitted"] == 3
        srv.close()


class TestLifecycle:
    def test_follower_cancel_leaves_leader_green(self, tmp_path):
        ref = _solo_reference(tmp_path, [BASE])
        srv = _server(tmp_path, "fc", dedup="on")
        leader = srv.submit(dict(BASE))
        follower = srv.submit(dict(BASE))
        assert srv.cancel(follower) in (CANCELLED, "queued")
        srv.run_until_idle(max_ticks=300)
        assert srv.status(follower)["status"] == CANCELLED
        st = srv.status(leader)
        assert st["status"] == DONE
        assert _lens(st["result_path"]) == ref[leader]
        srv.close()

    def test_leader_cancel_detaches_follower_to_solo(self, tmp_path):
        ref = _solo_reference(tmp_path, [BASE] * 2)
        srv = _server(tmp_path, "lc", dedup="on")
        leader = srv.submit(dict(BASE))
        follower = srv.submit(dict(BASE))
        srv.cancel(leader)
        srv.run_until_idle(max_ticks=300)
        assert srv.status(leader)["status"] == CANCELLED
        st = srv.status(follower)
        assert st["status"] == DONE
        # the detached follower re-ran independently; its log is still
        # its solo run's, bitwise
        assert _lens(st["result_path"]) == ref["req-000001"]
        srv.close()

    def test_leader_failure_poisons_followers_with_cause(self, tmp_path):
        plan = FaultPlan([{"kind": "io_error", "request": "req-000000"}])
        srv = _server(
            tmp_path, "lf", dedup="on", sink_errors="request",
            faults=plan, lanes=1, window=4,
        )
        leader = srv.submit(dict(BASE))
        follower = srv.submit(dict(BASE))
        srv.run_until_idle(max_ticks=300)
        assert srv.status(leader)["status"] == FAILED
        st = srv.status(follower)
        assert st["status"] == FAILED
        assert leader in st["error"]  # the cause names the leader
        srv.close()

    def test_coalesced_tickets_refuse_withdrawal(self, tmp_path):
        srv = _server(tmp_path, "wd", dedup="on")
        leader = srv.submit(dict(BASE))
        srv.submit(dict(BASE))
        with pytest.raises(ValueError, match="followers do not migrate"):
            srv.withdraw("req-000001")
        with pytest.raises(ValueError, match="coalesced group"):
            srv.withdraw(leader)  # nor leaders with followers
        srv.close()


class TestRecovery:
    def test_replayed_submits_recoalesce(self, tmp_path):
        ref = _solo_reference(tmp_path, [BASE] * 2)
        out, wal = tmp_path / "rc_out", tmp_path / "rc_wal"
        srv = _server(
            tmp_path, "rc", dedup="on", out_dir=str(out),
            recover_dir=str(wal),
        )
        srv.submit(dict(BASE))
        srv.submit(dict(BASE))
        del srv  # vanish with both still queued (coalesced)
        srv2 = _server(
            tmp_path, "rc", dedup="on", out_dir=str(out),
            recover_dir=str(wal),
        )
        m = srv2.metrics()["counters"]
        assert m["recovered"] == 2
        assert m["suffix_coalesced"] == 1  # re-coalesced on replay
        srv2.run_until_idle(max_ticks=300)
        for rid, data in ref.items():
            st = srv2.status(rid)
            assert st["status"] == DONE
            assert _lens(st["result_path"]) == data, rid
        srv2.close()


class TestKnobsOff:
    def test_default_server_skips_all_cdn_state(self, tmp_path):
        ref = _solo_reference(tmp_path, [BASE])
        srv = _server(tmp_path, "off")
        rid = srv.submit(dict(BASE))
        # the round-17 submit path exactly: no content address hashed,
        # no dedup bookkeeping, no results dir, no results gauges
        assert srv.tickets[rid].fingerprint is None
        srv.run_until_idle(max_ticks=300)
        assert _lens(srv.status(rid)["result_path"]) == ref[rid]
        m = srv.metrics()
        assert m["counters"]["suffix_coalesced"] == 0
        assert m["counters"]["result_hits"] == 0
        assert m["result_entries"] == 0
        assert "results" not in srv.status(rid)["server"]
        srv.close()


class TestClusterCdn:
    def test_router_answers_repeats_and_workers_coalesce(self, tmp_path):
        from lens_tpu.cluster import ClusterServer
        from lens_tpu.emit.log import iter_frames

        ref = _solo_reference(tmp_path, [BASE])
        body_ref = list(iter_frames(
            str(tmp_path / "ref_out" / "req-000000.lens")
        ))[1:]
        cs = ClusterServer(
            {"toggle_colony": {"lanes": 2, "window": 8,
                               "capacity": 16}},
            hosts=2, cluster_dir=str(tmp_path / "cluster"),
            local=True, result_cache_mb=64, dedup="on",
        )
        try:
            r1 = cs.submit(dict(BASE))
            cs.run_until_idle()
            assert list(iter_frames(cs.result(r1)))[1:] == body_ref
            # the repeat is answered AT THE ROUTER: terminal with no
            # host placement, served from the shared results dir the
            # worker published into at completion
            r2 = cs.submit(dict(BASE))
            t2 = cs.tickets[r2]
            assert t2.status == DONE and t2.host is None
            assert list(iter_frames(cs.result(r2)))[1:] == body_ref
            m = cs.metrics()
            assert m["counters"]["router_result_hits"] == 1
            assert m["results"]["entries"] >= 1
        finally:
            cs.close()


class TestSseBytes:
    """The front door streams a cache hit / a follower byte-identically
    to the underlying log (SSE payload == file, the round-15 pin,
    extended to tickets that never touched a lane)."""

    def test_sse_stream_of_cached_hit_matches_log(self, tmp_path):
        import http.client

        from lens_tpu.frontdoor import FrontDoor, decode_record_events

        out = str(tmp_path / "door_out")
        server = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4,
            sink="log", out_dir=out, dedup="on",
            result_cache_mb=32,
            recover_dir=str(tmp_path / "door_wal"),
        )
        fd = FrontDoor(server, own_server=True)
        fd.start()
        try:
            conn = http.client.HTTPConnection(
                "127.0.0.1", fd.port, timeout=60
            )

            def call(method, path, body=None):
                conn.request(
                    method, path,
                    body=json.dumps(body) if body is not None
                    else None,
                )
                r = conn.getresponse()
                raw = r.read()
                return r.status, raw

            body = {"seed": 11, "horizon": 8.0}
            code, sub = call("POST", "/v1/requests", body)
            assert code == 202
            rid1 = json.loads(sub)["rid"]
            import time as _time

            def wait_done(rid):
                for _ in range(600):
                    code, raw = call("GET", f"/v1/requests/{rid}")
                    st = json.loads(raw)
                    if st["status"] == "done" and \
                            st["timing"]["last_streamed"] is not None:
                        return st
                    _time.sleep(0.02)
                raise AssertionError(f"{rid} never finished: {st}")

            wait_done(rid1)  # fully streamed: the result is filable
            # the repeat is a durable cache hit: served whole at the
            # admission thread's submit, and its SSE stream is the
            # spliced log, bitwise
            code, sub = call("POST", "/v1/requests", body)
            assert code == 202
            rid2 = json.loads(sub)["rid"]
            st = wait_done(rid2)
            assert st["timing"]["admitted"] is None  # lane-less ticket
            code, raw = call("GET", f"/v1/requests/{rid2}/stream")
            assert code == 200
            sse_bytes, end = decode_record_events(raw)
            assert end["status"] == "done" and end["error"] is None
            with open(os.path.join(out, f"{rid2}.lens"), "rb") as f:
                assert sse_bytes == f.read()
            conn.close()
        finally:
            fd.close()
