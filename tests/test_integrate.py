"""Integrator correctness vs closed forms and scipy.odeint oracle."""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.integrate import odeint as scipy_odeint

from lens_tpu.ops.integrate import odeint_trajectory, odeint_window


def test_exponential_decay_rk4():
    rhs = lambda t, y, args: -y
    y = odeint_window(rhs, jnp.float32(1.0), 0.0, 0.01, 100)
    np.testing.assert_allclose(float(y), np.exp(-1.0), rtol=1e-5)


def test_pytree_state():
    rhs = lambda t, y, args: {"a": -y["a"], "b": 2.0 * jnp.ones_like(y["b"])}
    y0 = {"a": jnp.float32(1.0), "b": jnp.zeros(3, jnp.float32)}
    y = odeint_window(rhs, y0, 0.0, 0.01, 100)
    np.testing.assert_allclose(float(y["a"]), np.exp(-1.0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y["b"]), 2.0, rtol=1e-5)


def test_methods_converge():
    rhs = lambda t, y, args: jnp.cos(t) * y  # y(t) = exp(sin t)
    exact = np.exp(np.sin(1.0))
    for method, tol in [("euler", 2e-2), ("heun", 1e-3), ("rk4", 1e-6)]:
        y = odeint_window(rhs, jnp.float32(1.0), 0.0, 0.01, 100, method=method)
        assert abs(float(y) - exact) < tol, method


def test_vs_scipy_oracle_nonlinear():
    """Michaelis-Menten style nonlinearity vs scipy.odeint."""
    vmax, km = 1.5, 0.3

    def rhs_jax(t, y, args):
        s, p = y
        v = vmax * s / (km + s)
        return (-v, v)

    def rhs_scipy(y, t):
        s, p = y
        v = vmax * s / (km + s)
        return [-v, v]

    y = odeint_window(rhs_jax, (jnp.float32(2.0), jnp.float32(0.0)), 0.0, 0.05, 200)
    ref = scipy_odeint(rhs_scipy, [2.0, 0.0], [0.0, 10.0])[-1]
    np.testing.assert_allclose(
        [float(y[0]), float(y[1])], ref, rtol=1e-4, atol=1e-5
    )


def test_trajectory_shape_and_vmap():
    rhs = lambda t, y, args: -args * y
    y0 = jnp.ones(8, jnp.float32)
    rates = jnp.linspace(0.1, 1.0, 8)
    final, traj = jax.vmap(
        lambda y, r: odeint_trajectory(rhs, y, 0.0, 0.1, 10, args=r)
    )(y0, rates)
    assert traj.shape == (8, 10)
    np.testing.assert_allclose(
        np.asarray(final), np.exp(-np.asarray(rates)), rtol=1e-4
    )


# -- stiff / implicit (VERDICT r2 item 6) -------------------------------------


def test_implicit_stable_where_rk4_diverges():
    """Stiff linear relaxation y' = -k (y - cos t) with k dt = 1000:
    rk4's stability region ends near |k dt| ~ 2.8, so it explodes at
    dt=1; implicit Euler (L-stable) tracks the slow manifold."""
    k = 1000.0

    def rhs(t, y, args):
        return -k * (y - jnp.cos(t))

    y0 = jnp.asarray(0.0)
    bad = odeint_window(rhs, y0, 0.0, 1.0, 10, method="rk4")
    assert (not np.isfinite(float(bad))) or abs(float(bad)) > 1e6

    good = odeint_window(rhs, y0, 0.0, 1.0, 10, method="implicit")
    # solution hugs cos(t) to O(1/k) + O(dt) manifold error
    assert abs(float(good) - np.cos(10.0)) < 0.1


def test_implicit_vs_lsoda_robertson():
    """Robertson's problem — THE classic stiff benchmark (rate constants
    spanning 9 decades) — against scipy LSODA (the reference's
    scipy.odeint stiff path). dt = 0.05 over t in [0, 10]."""
    k1, k2, k3 = 0.04, 3e7, 1e4

    def rhs(t, y, args):
        a, b, c = y[0], y[1], y[2]
        r1 = k1 * a
        r2 = k2 * b * b
        r3 = k3 * b * c
        return jnp.stack([-r1 + r3, r1 - r2 - r3, r2])

    y0 = jnp.asarray([1.0, 0.0, 0.0])
    got = odeint_window(
        rhs, y0, 0.0, 0.05, 200, method="implicit"
    )

    # oracle: scipy's stiff BDF at tight tolerance (plain odeint bails
    # with "excess work" on Robertson at any reasonable mxstep). Pure
    # numpy rhs: BDF makes ~1e4 evaluations, so routing them through
    # eager jax would take minutes of dispatch overhead.
    from scipy.integrate import solve_ivp

    def rhs_scipy(t, y):
        a, b, c = y
        r1, r2, r3 = k1 * a, k2 * b * b, k3 * b * c
        return [-r1 + r3, r1 - r2 - r3, r2]

    ref = solve_ivp(
        rhs_scipy, [0.0, 10.0], [1.0, 0.0, 0.0],
        method="BDF", rtol=1e-10, atol=1e-14,
    ).y[:, -1]
    got = np.asarray(got, np.float64)
    # a and c are O(1); b is O(1e-5) — compare with per-component scales
    assert np.isfinite(got).all()
    np.testing.assert_allclose(got[0], ref[0], rtol=2e-3)
    np.testing.assert_allclose(got[2], ref[2], atol=2e-3)
    np.testing.assert_allclose(got[1], ref[1], rtol=0.25)
    # mass conserved exactly by the scheme (sum of rows of S is 0)
    np.testing.assert_allclose(float(got.sum()), 1.0, rtol=1e-5)


def test_implicit_matches_rk4_nonstiff():
    """On a non-stiff problem the implicit stepper agrees with rk4 to
    its first-order accuracy."""

    def rhs(t, y, args):
        return -0.5 * y

    y0 = jnp.asarray(1.0)
    a = odeint_window(rhs, y0, 0.0, 0.01, 100, method="implicit")
    b = odeint_window(rhs, y0, 0.0, 0.01, 100, method="rk4")
    np.testing.assert_allclose(float(a), float(b), rtol=5e-3)


def test_implicit_pytree_and_vmap():
    def rhs(t, y, args):
        return {"x": -100.0 * y["x"], "v": y["x"] - y["v"]}

    y0 = {"x": jnp.ones(4), "v": jnp.zeros(4)}
    out = jax.vmap(
        lambda x, v: odeint_window(
            rhs, {"x": x, "v": v}, 0.0, 0.5, 8, method="implicit"
        )
    )(y0["x"], y0["v"])
    assert out["x"].shape == (4,)
    assert np.isfinite(np.asarray(out["x"])).all()
    assert (np.asarray(out["x"]) >= 0).all()  # stiff decay stays stable


class TestTRBDF2:
    """TR-BDF2 (VERDICT r3 item 8): the second-order L-stable stepper —
    LSODA's ACCURACY half, not just its stability half, at fixed shapes."""

    k1, k2, k3 = 0.04, 3e7, 1e4

    def rhs(self, t, y, args):
        a, b, c = y[0], y[1], y[2]
        r1, r2, r3 = self.k1 * a, self.k2 * b * b, self.k3 * b * c
        return jnp.stack([-r1 + r3, r1 - r2 - r3, r2])

    def robertson_oracle(self, t_end):
        from scipy.integrate import solve_ivp

        def rhs_scipy(t, y):
            a, b, c = y
            r1, r2, r3 = self.k1 * a, self.k2 * b * b, self.k3 * b * c
            return [-r1 + r3, r1 - r2 - r3, r2]

        return solve_ivp(
            rhs_scipy, [0.0, t_end], [1.0, 0.0, 0.0],
            method="BDF", rtol=1e-10, atol=1e-14,
        ).y[:, -1]

    def test_accuracy_beats_implicit_euler_at_dt_1(self):
        """The VERDICT's bar: accuracy at dt = 1 s on Robertson. The
        first-order stepper's error there is accuracy-limited; TR-BDF2
        must land an order of magnitude closer to the BDF oracle."""
        y0 = jnp.asarray([1.0, 0.0, 0.0])
        ref = self.robertson_oracle(100.0)
        got2 = np.asarray(
            odeint_window(self.rhs, y0, 0.0, 1.0, 100, method="tr_bdf2"),
            np.float64,
        )
        got1 = np.asarray(
            odeint_window(self.rhs, y0, 0.0, 1.0, 100, method="implicit"),
            np.float64,
        )
        assert np.isfinite(got2).all()
        err2 = abs(got2[0] - ref[0]) + abs(got2[2] - ref[2])
        err1 = abs(got1[0] - ref[0]) + abs(got1[2] - ref[2])
        assert err2 < err1 / 10.0, (err1, err2)  # adaptive Newton reaches the f32 floor
        # and absolutely accurate on the O(1) components
        np.testing.assert_allclose(got2[0], ref[0], rtol=2e-4)
        np.testing.assert_allclose(got2[2], ref[2], atol=2e-4)
        np.testing.assert_allclose(float(got2.sum()), 1.0, rtol=1e-5)

    def test_second_order_convergence(self):
        """Halving dt must cut the error ~4x (order 2) on a nonlinear
        non-stiff problem with a tight oracle."""

        def rhs(t, y, args):
            return -y * y  # y(t) = 1 / (1 + t)

        errs = []
        for n, dt in ((16, 0.25), (32, 0.125), (64, 0.0625)):
            got = float(
                odeint_window(rhs, jnp.asarray(1.0), 0.0, dt, n,
                              method="tr_bdf2")
            )
            errs.append(abs(got - 1.0 / 5.0))
        assert errs[0] / errs[1] > 3.0, errs
        assert errs[1] / errs[2] > 3.0, errs

    def test_l_stable_where_rk4_diverges(self):
        """Stiff decay at |lambda| dt = 500: explicit steppers explode,
        TR-BDF2 damps to the slow manifold."""

        def rhs(t, y, args):
            return jnp.stack([-500.0 * (y[0] - jnp.cos(t)), -0.1 * y[1]])

        y0 = jnp.asarray([0.0, 1.0])
        got = np.asarray(
            odeint_window(rhs, y0, 0.0, 1.0, 10, method="tr_bdf2")
        )
        assert np.isfinite(got).all()
        assert abs(got[0] - np.cos(10.0)) < 0.05
        bad = np.asarray(odeint_window(rhs, y0, 0.0, 1.0, 10, method="rk4"))
        assert not np.isfinite(bad).all() or abs(bad[0]) > 1e3

    def test_pytree_and_vmap(self):
        def rhs(t, y, args):
            return {"x": -y["x"], "v": -50.0 * y["v"]}

        y0 = {"x": jnp.ones(8) * jnp.arange(1, 9), "v": jnp.ones(8)}
        out = jax.vmap(
            lambda x, v: odeint_window(
                rhs, {"x": x, "v": v}, 0.0, 0.5, 8, method="tr_bdf2"
            )
        )(y0["x"], y0["v"])
        # dt = 0.5 on y' = -y: TR-BDF2's per-step error is ~5e-3 of y
        # (second order with a visible constant); this test pins the
        # pytree/vmap mechanics, accuracy is pinned above
        np.testing.assert_allclose(
            np.asarray(out["x"]),
            np.arange(1, 9) * np.exp(-4.0),
            rtol=5e-2,
        )
        assert np.all(np.abs(np.asarray(out["v"])) < 1e-6)
