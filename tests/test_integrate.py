"""Integrator correctness vs closed forms and scipy.odeint oracle."""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.integrate import odeint as scipy_odeint

from lens_tpu.ops.integrate import odeint_trajectory, odeint_window


def test_exponential_decay_rk4():
    rhs = lambda t, y, args: -y
    y = odeint_window(rhs, jnp.float32(1.0), 0.0, 0.01, 100)
    np.testing.assert_allclose(float(y), np.exp(-1.0), rtol=1e-5)


def test_pytree_state():
    rhs = lambda t, y, args: {"a": -y["a"], "b": 2.0 * jnp.ones_like(y["b"])}
    y0 = {"a": jnp.float32(1.0), "b": jnp.zeros(3, jnp.float32)}
    y = odeint_window(rhs, y0, 0.0, 0.01, 100)
    np.testing.assert_allclose(float(y["a"]), np.exp(-1.0), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(y["b"]), 2.0, rtol=1e-5)


def test_methods_converge():
    rhs = lambda t, y, args: jnp.cos(t) * y  # y(t) = exp(sin t)
    exact = np.exp(np.sin(1.0))
    for method, tol in [("euler", 2e-2), ("heun", 1e-3), ("rk4", 1e-6)]:
        y = odeint_window(rhs, jnp.float32(1.0), 0.0, 0.01, 100, method=method)
        assert abs(float(y) - exact) < tol, method


def test_vs_scipy_oracle_nonlinear():
    """Michaelis-Menten style nonlinearity vs scipy.odeint."""
    vmax, km = 1.5, 0.3

    def rhs_jax(t, y, args):
        s, p = y
        v = vmax * s / (km + s)
        return (-v, v)

    def rhs_scipy(y, t):
        s, p = y
        v = vmax * s / (km + s)
        return [-v, v]

    y = odeint_window(rhs_jax, (jnp.float32(2.0), jnp.float32(0.0)), 0.0, 0.05, 200)
    ref = scipy_odeint(rhs_scipy, [2.0, 0.0], [0.0, 10.0])[-1]
    np.testing.assert_allclose(
        [float(y[0]), float(y[1])], ref, rtol=1e-4, atol=1e-5
    )


def test_trajectory_shape_and_vmap():
    rhs = lambda t, y, args: -args * y
    y0 = jnp.ones(8, jnp.float32)
    rates = jnp.linspace(0.1, 1.0, 8)
    final, traj = jax.vmap(
        lambda y, r: odeint_trajectory(rhs, y, 0.0, 0.1, 10, args=r)
    )(y0, rates)
    assert traj.shape == (8, 10)
    np.testing.assert_allclose(
        np.asarray(final), np.exp(-np.asarray(rates)), rtol=1e-4
    )
