"""Emitter subsystem (record log, native writer) + offline analysis."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.analysis import (
    alive_counts,
    load,
    masked_agent_series,
    plot_colony_growth,
    plot_field_snapshots,
    plot_timeseries,
)
from lens_tpu.emit import (
    LogEmitter,
    NullEmitter,
    RamEmitter,
    get_emitter,
    read_experiment,
)
from lens_tpu.emit.log import (
    decode_record,
    encode_record,
    frame,
    read_records,
    stack_records,
)


class TestRecordLog:
    def test_encode_decode_roundtrip(self):
        record = {
            "cell": {"glucose": np.asarray([1.0, 2.0]), "n": np.asarray(3)},
            "alive": np.asarray([True, False]),
        }
        out = decode_record(encode_record(record))
        np.testing.assert_array_equal(out["cell"]["glucose"], [1.0, 2.0])
        np.testing.assert_array_equal(out["alive"], [True, False])
        assert int(out["cell"]["n"]) == 3

    def test_corrupt_magic_raises(self, tmp_path):
        path = str(tmp_path / "bad.lens")
        with open(path, "wb") as f:
            f.write(b"\x00" * 32)
        with pytest.raises(ValueError, match="bad record magic"):
            list(read_records(path))

    def test_truncated_tail_is_dropped(self, tmp_path):
        path = str(tmp_path / "trunc.lens")
        payload = encode_record({"x": np.asarray(1.0)})
        framed = frame(payload)
        with open(path, "wb") as f:
            f.write(framed)
            f.write(framed[: len(framed) // 2])  # killed mid-record
        records = list(read_records(path))
        assert len(records) == 1  # complete record kept, tail dropped

    def test_crc_mismatch_raises(self, tmp_path):
        path = str(tmp_path / "crc.lens")
        framed = bytearray(frame(encode_record({"x": np.asarray(1.0)})))
        framed[-1] ^= 0xFF
        with open(path, "wb") as f:
            f.write(bytes(framed))
        with pytest.raises(ValueError, match="CRC mismatch"):
            list(read_records(path))


class TestEmitters:
    def make_trajectory(self, steps=5, agents=4):
        return {
            "cell": {"v": jnp.arange(steps * agents, dtype=jnp.float32).reshape(steps, agents)},
            "alive": jnp.ones((steps, agents), bool),
        }

    def test_ram_emitter_stacks(self):
        em = RamEmitter()
        em.emit_trajectory(self.make_trajectory(), times=np.arange(5) * 2.0)
        ts = em.timeseries()
        assert ts["cell"]["v"].shape == (5, 4)
        np.testing.assert_array_equal(ts["__time__"], [0, 2, 4, 6, 8])

    def test_null_emitter_noop(self):
        em = NullEmitter()
        em.emit({"x": 1})
        em.close()

    def test_get_emitter_registry(self):
        assert isinstance(get_emitter({"type": "null"}), NullEmitter)
        assert isinstance(get_emitter(None), RamEmitter)
        with pytest.raises(ValueError, match="unknown emitter"):
            get_emitter({"type": "kafka"})

    @pytest.mark.parametrize("native", [True, False])
    def test_log_emitter_roundtrip(self, tmp_path, native):
        path = str(tmp_path / f"exp_{native}.lens")
        with LogEmitter(
            experiment_id="exp1",
            config={"note": "test"},
            path=path,
            native=native,
        ) as em:
            if native:
                # the toolchain is baked into this image; the native build
                # must actually succeed here, not silently fall back
                assert em.native, "native emit writer failed to build/load"
            em.emit_trajectory(self.make_trajectory())
        header, records = read_experiment(path)
        assert header["experiment_id"] == "exp1"
        assert header["config"] == {"note": "test"}
        assert len(records) == 5
        ts = stack_records(records)
        assert ts["cell"]["v"].shape == (5, 4)

    def test_native_and_python_writers_byte_identical(self, tmp_path):
        pa = str(tmp_path / "a.lens")
        pb = str(tmp_path / "b.lens")
        traj = self.make_trajectory()
        with LogEmitter("same", path=pa, native=True) as ea:
            ea.emit_trajectory(traj)
        with LogEmitter("same", path=pb, native=False) as eb:
            eb.emit_trajectory(traj)
        with open(pa, "rb") as fa, open(pb, "rb") as fb:
            assert fa.read() == fb.read()

    def test_flush_makes_records_visible(self, tmp_path):
        path = str(tmp_path / "fl.lens")
        em = LogEmitter("fl", path=path)
        em.emit({"x": np.asarray(1.0)})
        em.flush()
        header, records = read_experiment(path)
        assert len(records) == 1
        em.close()


class TestAnalysis:
    def emitted_colony_log(self, tmp_path):
        """Run a real colony and emit it to a log (end-to-end path)."""
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        comp = grow_divide({"growth": {"rate": 0.01}})
        colony = Colony(comp, capacity=32, division_trigger=("global", "divide"))
        cs = colony.initial_state(2)
        final, traj = colony.run(cs, 120.0, 1.0, emit_every=10)
        path = str(tmp_path / "colony.lens")
        with LogEmitter("colony-exp", path=path) as em:
            em.emit_trajectory(traj, times=np.arange(12) * 10.0)
        return path

    def test_load_and_growth_curve(self, tmp_path):
        path = self.emitted_colony_log(tmp_path)
        header, ts = load(path)
        assert header["experiment_id"] == "colony-exp"
        counts = alive_counts(ts)
        assert counts[0] == 2
        assert counts[-1] > 2  # division happened

    def test_masked_series(self, tmp_path):
        _, ts = load(self.emitted_colony_log(tmp_path))
        vol = masked_agent_series(ts, ("global", "volume"))
        assert vol.shape == (12, 32)
        # dead rows masked
        assert vol.mask[0].sum() == 30

    def test_plots_render(self, tmp_path):
        _, ts = load(self.emitted_colony_log(tmp_path))
        p1 = plot_timeseries(
            ts, paths=[("global", "volume")], out_path=str(tmp_path / "t.png")
        )
        p2 = plot_colony_growth(ts, out_path=str(tmp_path / "g.png"))
        assert os.path.getsize(p1) > 1000
        assert os.path.getsize(p2) > 1000

    def test_field_snapshot_plot(self, tmp_path):
        ts = {
            "fields": np.random.rand(6, 1, 8, 8).astype(np.float32),
            "alive": np.ones((6, 4), bool),
        }
        locs = np.random.rand(6, 4, 2) * 8.0
        p = plot_field_snapshots(
            ts, out_path=str(tmp_path / "f.png"), locations=locs
        )
        assert os.path.getsize(p) > 1000


class TestLineage:
    """Framework-level lineage: colony._divide mints fresh ids for both
    daughters and records the parent id; analysis reconstructs the tree
    (VERDICT r2 item 5)."""

    def deep_colony(self, total=260.0, emit_every=5):
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        # fast growth + low division threshold -> several generations
        comp = grow_divide({"growth": {"rate": 0.01}})
        colony = Colony(
            comp, capacity=64, division_trigger=("global", "divide")
        )
        cs = colony.initial_state(2, key=jax.random.PRNGKey(4))
        final, traj = colony.run(cs, total, 1.0, emit_every=emit_every)
        return colony, final, traj

    def test_ids_unique_and_parents_recorded(self):
        colony, final, traj = self.deep_colony()
        assert int(jnp.sum(final.alive)) > 8  # several rounds of division
        lin = final.agents["lineage"]
        ids = np.asarray(lin["cell_id"])[np.asarray(final.alive)]
        assert len(set(ids.tolist())) == len(ids)  # unique among live
        parents = np.asarray(lin["parent_id"])[np.asarray(final.alive)]
        # every live cell today was born by division (founders divided
        # away over 260 s at rate 0.01 -> threshold 2.0 by ~t=70)
        assert (parents >= 0).all()
        # both-daughters-new convention: no live cell keeps a founder id
        # after its row divided; birth steps are populated
        assert (np.asarray(lin["birth_step"])[np.asarray(final.alive)] > 0).any()

    def test_lineage_table_generations(self):
        from lens_tpu.analysis import ancestry, lineage_table

        _, _, traj = self.deep_colony()
        table = lineage_table(traj)
        gens = max(n["generation"] for n in table.values())
        assert gens >= 3, f"expected >=3 generations, got {gens}"
        # every observed non-founder's parent resolves into the table
        for cid, node in table.items():
            if node["parent"] != -1:
                assert node["parent"] in table
        # ancestry chains are root-first and consistent
        deepest = max(table, key=lambda c: table[c]["generation"])
        chain = ancestry(table, deepest)
        assert chain[-1] == deepest
        assert len(chain) == table[deepest]["generation"] + 1

    def test_lineage_plots_render(self, tmp_path):
        from lens_tpu.analysis import plot_generation_trace, plot_lineage

        _, _, traj = self.deep_colony()
        p1 = plot_lineage(traj, out_path=str(tmp_path / "lineage.png"))
        p2 = plot_generation_trace(
            traj, ("global", "volume"),
            out_path=str(tmp_path / "trace.png"),
        )
        assert os.path.getsize(p1) > 1000
        assert os.path.getsize(p2) > 1000

    def test_field_animation_renders(self, tmp_path):
        from lens_tpu.analysis import animate_fields

        ts = {
            "fields": np.random.rand(5, 1, 8, 8).astype(np.float32),
            "alive": np.ones((5, 4), bool),
        }
        locs = np.random.rand(5, 4, 2) * 8.0
        p = animate_fields(
            ts, out_path=str(tmp_path / "f.gif"), locations=locs, fps=4
        )
        assert os.path.getsize(p) > 1000

    def test_sharded_lineage_ids_unique(self):
        """Per-shard division mints ids from the GLOBAL row_id leaf, so
        ids stay unique across shards."""
        from lens_tpu.models import ecoli_lattice
        from lens_tpu.parallel import ShardedSpatialColony, make_mesh

        spatial = ecoli_lattice(
            {
                "capacity": 128,
                "shape": (32, 32),
                "size": (32.0, 32.0),
                "growth": {"rate": 0.05},
                "transport": {"yield_": 1.0, "k_consume": 0.0},
            }
        )[0]
        mesh = make_mesh(n_agents=4, n_space=2)
        sharded = ShardedSpatialColony(spatial, mesh)
        ss = sharded.initial_state(60, jax.random.PRNGKey(2))
        out, _ = sharded.run(ss, 20.0, 1.0, emit_every=20)
        alive = np.asarray(out.colony.alive)
        assert alive.sum() > 60  # divisions happened on the mesh
        ids = np.asarray(out.colony.agents["lineage"]["cell_id"])[alive]
        assert len(set(ids.tolist())) == len(ids)
        parents = np.asarray(out.colony.agents["lineage"]["parent_id"])[alive]
        assert (parents >= -1).all()


class TestDomainPlots:
    """Round-3 analysis breadth: mixed-species snapshots, expression
    heatmaps, FBA flux traces (SURVEY §2 Analysis ~1000 LoC scope)."""

    def test_species_snapshots(self, tmp_path):
        from lens_tpu.analysis import plot_species_snapshots
        from lens_tpu.models import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {"capacity": {"ecoli": 16, "scavenger": 16},
             "shape": (16, 16), "size": (16.0, 16.0)}
        )
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0)
        )
        _, traj = multi.run(ms, 6.0, 1.0, emit_every=2)
        p = plot_species_snapshots(
            traj, n_snapshots=3, out_path=str(tmp_path / "sp.png")
        )
        assert os.path.getsize(p) > 1000

    def test_expression_heatmap_and_fluxes(self, tmp_path):
        from lens_tpu.analysis import (
            plot_expression_heatmap,
            plot_reaction_fluxes,
        )
        from lens_tpu.models.composites import rfba_lattice
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        spatial, comp = rfba_lattice(
            {"capacity": 8, "shape": (8, 8), "division": False,
             "metabolism": {"network": "ecoli_core"},
             "expression": {"genes": "ecoli_core"}}
        )
        ss = spatial.initial_state(4, jax.random.PRNGKey(0))
        _, traj = spatial.run(ss, 8.0, 1.0, emit_every=1)

        genes = comp.processes["expression"].genes
        p1 = plot_expression_heatmap(
            traj, genes, out_path=str(tmp_path / "genes.png")
        )
        p = FBAMetabolism({"network": "ecoli_core"})
        p2 = plot_reaction_fluxes(
            traj, p.reactions,
            reactions=["glc_pts", "oxphos_nadh", "pta_ack", "biomass"],
            out_path=str(tmp_path / "flux.png"),
        )
        assert os.path.getsize(p1) > 1000
        assert os.path.getsize(p2) > 1000


class TestReport:
    """`analysis.report` + the `analyze` CLI: the one-stop offline
    analysis pass (the reference's per-script analysis layer, SURVEY
    §3.5), auto-detecting what the emitted tree supports."""

    def spatial_log(self, tmp_path):
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {
                "capacity": 64,
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "growth": {"rate": 0.05},
            }
        )
        ss = spatial.initial_state(4, jax.random.PRNGKey(1))
        _, traj = spatial.run(ss, 40.0, 1.0, emit_every=4)
        path = str(tmp_path / "emit.lens")
        with LogEmitter("report-exp", path=path) as em:
            em.emit_trajectory(traj, times=np.arange(10) * 4.0)
        return path

    def test_report_writes_applicable_plots(self, tmp_path):
        from lens_tpu.analysis import report

        written = report(self.spatial_log(tmp_path))
        # a divided spatial colony supports the full single-species set
        for name in (
            "colony_growth",
            "timeseries",
            "field_snapshots",
            "lineage",
            "generation_trace",
        ):
            assert name in written, (name, sorted(written))
            assert os.path.getsize(written[name]) > 1000
        assert os.path.dirname(written["colony_growth"]).endswith("analysis")

    def test_report_multispecies(self, tmp_path):
        from lens_tpu.analysis import report
        from lens_tpu.models import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {"capacity": {"ecoli": 16, "scavenger": 16},
             "shape": (16, 16), "size": (16.0, 16.0)}
        )
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0)
        )
        _, traj = multi.run(ms, 6.0, 1.0, emit_every=2)
        path = str(tmp_path / "emit.lens")
        with LogEmitter("ms-exp", path=path) as em:
            em.emit_trajectory(traj, times=np.arange(3) * 2.0)
        written = report(path, out_dir=str(tmp_path / "plots"))
        for name in (
            "ecoli.colony_growth",
            "scavenger.timeseries",
            "species_snapshots",
        ):
            assert name in written
            assert os.path.getsize(written[name]) > 1000

    def test_analyze_cli(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        path = self.spatial_log(tmp_path)
        rc = main(["analyze", str(tmp_path)])  # dir form -> dir/emit.lens
        assert rc == 0
        out = capsys.readouterr().out
        assert "colony_growth" in out and "analysis" in out
