"""Regulated FBA metabolism: the Covert–Palsson phenomena, exactly.

Checks the biology the regulated-FBA lineage exists to reproduce —
aerobic growth, overflow acetate secretion, catabolite-repressed diauxie,
anaerobic fermentation — plus framework integration: vmap across a
colony, the rfba_lattice composite end-to-end, and exchange mass balance
against the lattice fields.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.processes.fba_metabolism import FBAMetabolism


def states_for(env, mass=330.0):
    p = FBAMetabolism()
    s = p.initial_state()
    for mol, conc in env.items():
        s["external"][mol] = jnp.asarray(conc)
    s["global"]["mass"] = jnp.asarray(mass)
    return p, s


class TestPhenomena:
    def test_aerobic_glucose_growth(self):
        p, s = states_for({"glc": 10.0, "ace": 0.0, "o2": 5.0})
        upd = p.next_update(1.0, s)
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        assert float(upd["fluxes"]["growth_rate"]) > 0.05
        assert float(upd["global"]["mass"]) > 0
        # glucose taken up (negative exchange = uptake)
        assert float(upd["exchange"]["glc_exchange"]) < 0

    def test_overflow_secretes_acetate(self):
        """With oxygen limiting, excess carbon ferments out as acetate."""
        p, s = states_for({"glc": 10.0, "ace": 0.0, "o2": 0.05})
        upd = p.next_update(1.0, s)
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        ferm = v[p.reactions.index("fermentation")]
        assert ferm > 1e-3
        assert float(upd["exchange"]["ace_exchange"]) > 0  # net secretion

    def test_catabolite_repression_diauxie(self):
        """Acetate route is off while glucose is present, on once it's gone."""
        p, s_glc = states_for({"glc": 10.0, "ace": 5.0, "o2": 5.0})
        upd = p.next_update(1.0, s_glc)
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("ace_uptake")] < 1e-4  # repressed

        _, s_noglc = states_for({"glc": 0.0, "ace": 5.0, "o2": 5.0})
        upd2 = p.next_update(1.0, s_noglc)
        v2 = np.asarray(upd2["fluxes"]["reaction_fluxes"])
        assert v2[p.reactions.index("ace_uptake")] > 1e-3  # derepressed
        assert float(upd2["fluxes"]["growth_rate"]) > 0  # grows on acetate
        # and growth on acetate is slower than on glucose
        assert float(upd2["fluxes"]["growth_rate"]) < float(
            upd["fluxes"]["growth_rate"]
        )

    def test_anaerobic_fermentation_only(self):
        """No oxygen: respiration off (NADH cannot be re-oxidized), growth
        rides fermentation ATP and is slower than aerobic."""
        p, s_aer = states_for({"glc": 10.0, "ace": 0.0, "o2": 5.0})
        aer = float(p.next_update(1.0, s_aer)["fluxes"]["growth_rate"])
        _, s_ana = states_for({"glc": 10.0, "ace": 0.0, "o2": 0.0})
        upd = p.next_update(1.0, s_ana)
        ana = float(upd["fluxes"]["growth_rate"])
        assert 0 < ana < aer
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("oxidation")] < 5e-3  # NADH-blocked

    def test_starvation_is_infeasible_not_garbage(self):
        """No carbon at all: maintenance cannot be met -> LP infeasible ->
        zero fluxes, zero growth (the documented failure mode)."""
        p, s = states_for({"glc": 0.0, "ace": 0.0, "o2": 5.0})
        upd = p.next_update(1.0, s)
        assert float(upd["fluxes"]["lp_converged"]) == 0.0
        assert float(upd["fluxes"]["growth_rate"]) == 0.0
        np.testing.assert_allclose(
            np.asarray(upd["fluxes"]["reaction_fluxes"]), 0.0
        )

    def test_uptake_limited_by_availability(self):
        """dt * uptake never exceeds the local environment amount."""
        p, s = states_for({"glc": 0.01, "ace": 0.0, "o2": 5.0})
        dt = 10.0
        upd = p.next_update(dt, s)
        taken = -float(upd["exchange"]["glc_exchange"])
        assert taken <= 0.01 + 1e-5

    def test_two_importers_share_availability_cap(self):
        """Two import reactions for one species may not jointly overdraw
        the bin: the cap bounds their SUMMED uptake."""
        import copy

        net = copy.deepcopy(
            __import__(
                "lens_tpu.processes.fba_metabolism", fromlist=["x"]
            ).CORE_RFBA_NETWORK
        )
        # second glucose importer, as permissive as the first
        net["reactions"]["glc_uptake2"] = {
            "stoich": {"C": 2.0},
            "bounds": (0.0, 1.0),
            "exchange": "glc",
            "km": 0.5,
            "rule": "",
        }
        p = FBAMetabolism({"network": net})
        s = p.initial_state()
        s["external"]["glc"] = jnp.asarray(0.01)  # scarce
        s["external"]["ace"] = jnp.asarray(0.0)
        s["external"]["o2"] = jnp.asarray(5.0)
        dt = 10.0
        upd = p.next_update(dt, s)
        taken = -float(upd["exchange"]["glc_exchange"])
        assert taken <= 0.01 + 1e-5, taken

    def test_gated_importer_does_not_dilute_share(self):
        """The availability split counts ACTIVE importers only: a
        regulation-silenced importer must not halve the live one's cap."""
        import copy

        from lens_tpu.processes.fba_metabolism import CORE_RFBA_NETWORK

        net = copy.deepcopy(CORE_RFBA_NETWORK)
        net["reactions"]["glc_uptake2"] = {
            "stoich": {"C": 2.0},
            "bounds": (0.0, 1.0),
            "exchange": "glc",
            "km": 0.5,
            "rule": "not glc",  # off whenever glucose is present
        }
        p = FBAMetabolism({"network": net})
        s = p.initial_state()
        # scarce enough that the availability cap binds (not the MM bound),
        # rich enough that maintenance stays feasible
        s["external"]["glc"] = jnp.asarray(0.5)
        s["external"]["ace"] = jnp.asarray(0.0)
        s["external"]["o2"] = jnp.asarray(5.0)
        dt = 10.0
        upd = p.next_update(dt, s)
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        taken = -float(upd["exchange"]["glc_exchange"])
        cap = p.config["uptake_cap_fraction"] * 0.5
        # the single ACTIVE importer gets the whole capped share; a static
        # two-importer split would stop at cap/2
        assert taken > 0.8 * cap, (taken, cap)
        assert taken <= 0.5 + 1e-4


class TestIntegration:
    def test_vmap_over_colony(self):
        """The engine's batching pattern: one network, N environments."""
        p = FBAMetabolism()
        base = p.initial_state()

        def step_one(glc, o2):
            s = {
                "external": {
                    "glc": glc, "ace": jnp.asarray(0.0), "o2": o2
                },
                "exchange": base["exchange"],
                "global": base["global"],
                "fluxes": base["fluxes"],
            }
            return p.next_update(1.0, s)

        glcs = jnp.asarray([10.0, 10.0, 0.0])
        o2s = jnp.asarray([5.0, 0.0, 5.0])
        out = jax.jit(jax.vmap(step_one))(glcs, o2s)
        growth = np.asarray(out["fluxes"]["growth_rate"])
        assert growth[0] > growth[1] > 0      # aerobic beats anaerobic
        assert growth[2] == 0                 # starved

    def test_rfba_lattice_end_to_end(self):
        """The composite grows, drains glucose, and conserves exchange mass."""
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {"capacity": 64, "shape": (16, 16), "division": True}
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        glc0 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass0 = float(
            jnp.sum(
                jnp.where(
                    ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
                )
            )
        )
        ss, _ = spatial.run(ss, 30.0, 1.0, emit_every=30)
        glc1 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass1 = float(
            jnp.sum(
                jnp.where(
                    ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
                )
            )
        )
        assert glc1 < glc0          # colony drained the field
        assert mass1 > mass0        # and turned it into biomass
        assert bool(jnp.all(jnp.isfinite(ss.fields)))

    def test_colony_diauxie_timecourse(self):
        """Well-mixed closed batch: glucose falls, acetate rises (overflow:
        carbon influx exceeds respiratory capacity) then falls (diauxie)."""
        p = FBAMetabolism()
        base = p.initial_state()

        @jax.jit
        def step(glc, ace, o2):
            s = {
                "external": {"glc": glc, "ace": ace, "o2": o2},
                "exchange": base["exchange"],
                "global": base["global"],
                "fluxes": base["fluxes"],
            }
            upd = p.next_update(1.0, s)
            return (
                jnp.maximum(glc + upd["exchange"]["glc_exchange"], 0.0),
                jnp.maximum(ace + upd["exchange"]["ace_exchange"], 0.0),
                jnp.maximum(o2 + upd["exchange"]["o2_exchange"], 0.0),
            )

        glc, ace, o2 = jnp.asarray(10.0), jnp.asarray(0.0), jnp.asarray(1e4)
        ace_peak = 0.0
        saw_ace_consumption = False
        for _ in range(120):
            glc, new_ace, o2 = step(glc, ace, o2)
            if float(new_ace) < float(ace) - 1e-6:
                saw_ace_consumption = True
            ace = new_ace
            ace_peak = max(ace_peak, float(ace))
        assert float(glc) < 1e-3     # glucose exhausted
        assert ace_peak > 1e-3       # acetate transiently accumulated
        assert saw_ace_consumption   # then was re-consumed (diauxie)
