"""Regulated FBA metabolism: the Covert–Palsson phenomena, exactly.

Checks the biology the regulated-FBA lineage exists to reproduce —
aerobic growth, overflow acetate secretion, catabolite-repressed diauxie,
anaerobic fermentation — plus framework integration: vmap across a
colony, the rfba_lattice composite end-to-end, and exchange mass balance
against the lattice fields.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.processes.fba_metabolism import FBAMetabolism


def states_for(env, mass=330.0):
    p = FBAMetabolism()
    s = p.initial_state()
    for mol, conc in env.items():
        s["external"][mol] = jnp.asarray(conc)
    s["global"]["mass"] = jnp.asarray(mass)
    return p, s


class TestPhenomena:
    def test_aerobic_glucose_growth(self):
        p, s = states_for({"glc": 10.0, "ace": 0.0, "o2": 5.0})
        upd = p.next_update(1.0, s)
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        assert float(upd["fluxes"]["growth_rate"]) > 0.05
        assert float(upd["global"]["mass"]) > 0
        # glucose taken up (negative exchange = uptake)
        assert float(upd["exchange"]["glc_exchange"]) < 0

    def test_overflow_secretes_acetate(self):
        """With oxygen limiting, excess carbon ferments out as acetate."""
        p, s = states_for({"glc": 10.0, "ace": 0.0, "o2": 0.05})
        upd = p.next_update(1.0, s)
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        ferm = v[p.reactions.index("fermentation")]
        assert ferm > 1e-3
        assert float(upd["exchange"]["ace_exchange"]) > 0  # net secretion

    def test_catabolite_repression_diauxie(self):
        """Acetate route is off while glucose is present, on once it's gone."""
        p, s_glc = states_for({"glc": 10.0, "ace": 5.0, "o2": 5.0})
        upd = p.next_update(1.0, s_glc)
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("ace_uptake")] < 1e-4  # repressed

        _, s_noglc = states_for({"glc": 0.0, "ace": 5.0, "o2": 5.0})
        upd2 = p.next_update(1.0, s_noglc)
        v2 = np.asarray(upd2["fluxes"]["reaction_fluxes"])
        assert v2[p.reactions.index("ace_uptake")] > 1e-3  # derepressed
        assert float(upd2["fluxes"]["growth_rate"]) > 0  # grows on acetate
        # and growth on acetate is slower than on glucose
        assert float(upd2["fluxes"]["growth_rate"]) < float(
            upd["fluxes"]["growth_rate"]
        )

    def test_anaerobic_fermentation_only(self):
        """No oxygen: respiration off (NADH cannot be re-oxidized), growth
        rides fermentation ATP and is slower than aerobic."""
        p, s_aer = states_for({"glc": 10.0, "ace": 0.0, "o2": 5.0})
        aer = float(p.next_update(1.0, s_aer)["fluxes"]["growth_rate"])
        _, s_ana = states_for({"glc": 10.0, "ace": 0.0, "o2": 0.0})
        upd = p.next_update(1.0, s_ana)
        ana = float(upd["fluxes"]["growth_rate"])
        assert 0 < ana < aer
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("oxidation")] < 5e-3  # NADH-blocked

    def test_starvation_is_infeasible_not_garbage(self):
        """No carbon at all: maintenance cannot be met -> LP infeasible ->
        zero fluxes, zero growth (the documented failure mode)."""
        p, s = states_for({"glc": 0.0, "ace": 0.0, "o2": 5.0})
        upd = p.next_update(1.0, s)
        assert float(upd["fluxes"]["lp_converged"]) == 0.0
        assert float(upd["fluxes"]["growth_rate"]) == 0.0
        np.testing.assert_allclose(
            np.asarray(upd["fluxes"]["reaction_fluxes"]), 0.0
        )

    def test_uptake_limited_by_availability(self):
        """dt * uptake never exceeds the local environment amount."""
        p, s = states_for({"glc": 0.01, "ace": 0.0, "o2": 5.0})
        dt = 10.0
        upd = p.next_update(dt, s)
        taken = -float(upd["exchange"]["glc_exchange"])
        assert taken <= 0.01 + 1e-5

    def test_two_importers_share_availability_cap(self):
        """Two import reactions for one species may not jointly overdraw
        the bin: the cap bounds their SUMMED uptake."""
        import copy

        net = copy.deepcopy(
            __import__(
                "lens_tpu.processes.fba_metabolism", fromlist=["x"]
            ).CORE_RFBA_NETWORK
        )
        # second glucose importer, as permissive as the first
        net["reactions"]["glc_uptake2"] = {
            "stoich": {"C": 2.0},
            "bounds": (0.0, 1.0),
            "exchange": "glc",
            "km": 0.5,
            "rule": "",
        }
        p = FBAMetabolism({"network": net})
        s = p.initial_state()
        s["external"]["glc"] = jnp.asarray(0.01)  # scarce
        s["external"]["ace"] = jnp.asarray(0.0)
        s["external"]["o2"] = jnp.asarray(5.0)
        dt = 10.0
        upd = p.next_update(dt, s)
        taken = -float(upd["exchange"]["glc_exchange"])
        assert taken <= 0.01 + 1e-5, taken

    def test_gated_importer_does_not_dilute_share(self):
        """The availability split counts ACTIVE importers only: a
        regulation-silenced importer must not halve the live one's cap."""
        import copy

        from lens_tpu.processes.fba_metabolism import CORE_RFBA_NETWORK

        net = copy.deepcopy(CORE_RFBA_NETWORK)
        net["reactions"]["glc_uptake2"] = {
            "stoich": {"C": 2.0},
            "bounds": (0.0, 1.0),
            "exchange": "glc",
            "km": 0.5,
            "rule": "not glc",  # off whenever glucose is present
        }
        p = FBAMetabolism({"network": net})
        s = p.initial_state()
        # scarce enough that the availability cap binds (not the MM bound),
        # rich enough that maintenance stays feasible
        s["external"]["glc"] = jnp.asarray(0.5)
        s["external"]["ace"] = jnp.asarray(0.0)
        s["external"]["o2"] = jnp.asarray(5.0)
        dt = 10.0
        upd = p.next_update(dt, s)
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        taken = -float(upd["exchange"]["glc_exchange"])
        cap = p.config["uptake_cap_fraction"] * 0.5
        # the single ACTIVE importer gets the whole capped share; a static
        # two-importer split would stop at cap/2
        assert taken > 0.8 * cap, (taken, cap)
        assert taken <= 0.5 + 1e-4


class TestIntegration:
    def test_vmap_over_colony(self):
        """The engine's batching pattern: one network, N environments."""
        p = FBAMetabolism()
        base = p.initial_state()

        def step_one(glc, o2):
            s = {
                "external": {
                    "glc": glc, "ace": jnp.asarray(0.0), "o2": o2
                },
                "exchange": base["exchange"],
                "global": base["global"],
                "fluxes": base["fluxes"],
            }
            return p.next_update(1.0, s)

        glcs = jnp.asarray([10.0, 10.0, 0.0])
        o2s = jnp.asarray([5.0, 0.0, 5.0])
        out = jax.jit(jax.vmap(step_one))(glcs, o2s)
        growth = np.asarray(out["fluxes"]["growth_rate"])
        assert growth[0] > growth[1] > 0      # aerobic beats anaerobic
        assert growth[2] == 0                 # starved

    def test_rfba_lattice_end_to_end(self):
        """The composite grows, drains glucose, and conserves exchange mass."""
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {"capacity": 64, "shape": (16, 16), "division": True}
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        glc0 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass0 = float(
            jnp.sum(
                jnp.where(
                    ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
                )
            )
        )
        ss, _ = spatial.run(ss, 30.0, 1.0, emit_every=30)
        glc1 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass1 = float(
            jnp.sum(
                jnp.where(
                    ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
                )
            )
        )
        assert glc1 < glc0          # colony drained the field
        assert mass1 > mass0        # and turned it into biomass
        assert bool(jnp.all(jnp.isfinite(ss.fields)))

    def test_colony_diauxie_timecourse(self):
        """Well-mixed closed batch: glucose falls, acetate rises (overflow:
        carbon influx exceeds respiratory capacity) then falls (diauxie)."""
        p = FBAMetabolism()
        base = p.initial_state()

        @jax.jit
        def step(glc, ace, o2):
            s = {
                "external": {"glc": glc, "ace": ace, "o2": o2},
                "exchange": base["exchange"],
                "global": base["global"],
                "fluxes": base["fluxes"],
            }
            upd = p.next_update(1.0, s)
            return (
                jnp.maximum(glc + upd["exchange"]["glc_exchange"], 0.0),
                jnp.maximum(ace + upd["exchange"]["ace_exchange"], 0.0),
                jnp.maximum(o2 + upd["exchange"]["o2_exchange"], 0.0),
            )

        glc, ace, o2 = jnp.asarray(10.0), jnp.asarray(0.0), jnp.asarray(1e4)
        ace_peak = 0.0
        saw_ace_consumption = False
        for _ in range(120):
            glc, new_ace, o2 = step(glc, ace, o2)
            if float(new_ace) < float(ace) - 1e-6:
                saw_ace_consumption = True
            ace = new_ace
            ace_peak = max(ace_peak, float(ace))
        assert float(glc) < 1e-3     # glucose exhausted
        assert ace_peak > 1e-3       # acetate transiently accumulated
        assert saw_ace_consumption   # then was re-consumed (diauxie)


# -- the reference-scale network (data-layer, VERDICT r2 item 2) --------------


def core_process(**over):
    cfg = {"network": "ecoli_core", "lp_leak": 1.5e-3, "lp_tol": 1e-4,
           "lp_iterations": 60}
    cfg.update(over)
    return FBAMetabolism(cfg)


def core_states(p, env):
    s = p.initial_state()
    for mol in p.external:
        s["external"][mol] = jnp.asarray(float(env.get(mol, 0.0)))
    return s


class TestSkeletonNetworkIsData:
    """The DEFAULT network is data too: core_skeleton_{species,reactions}
    .tsv must reconstruct the inline CORE_RFBA_NETWORK dict exactly (the
    dict stays as the documented in-code form and this equivalence pin)."""

    def test_tsv_equals_inline_dict(self):
        from lens_tpu.processes.fba_metabolism import (
            CORE_RFBA_NETWORK,
            FBAMetabolism,
        )

        a = FBAMetabolism()  # defaults -> "core_skeleton" via the loader
        b = FBAMetabolism({"network": CORE_RFBA_NETWORK})
        assert a.internal == b.internal
        assert a.external == b.external
        assert a.reactions == b.reactions
        for attr in (
            "stoichiometry", "lb", "ub", "objective",
            "exchange_matrix", "kms", "uptake_mask",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(a, attr)),
                np.asarray(getattr(b, attr)),
                err_msg=attr,
            )
        assert {j: r.source for j, r in a._rules.items()} == {
            j: r.source for j, r in b._rules.items()
        }


class TestEcoliCoreNetwork:
    """The 24-metabolite x 35-reaction Covert–Palsson-style network shipped
    as data (lens_tpu/data/ecoli_core_*.tsv) through data.load_rfba_network."""

    def test_loader_scale_and_wiring(self):
        from lens_tpu.data import load_rfba_network

        net = load_rfba_network("ecoli_core")
        assert len(net["internal"]) >= 20
        assert len(net["reactions"]) >= 30
        assert net["objective"] == "biomass"
        # spot-check a parsed row against the TSV source
        pts = net["reactions"]["glc_pts"]
        assert pts["stoich"] == {"PEP": -1.0, "G6P": 1.0, "PYR": 1.0}
        assert pts["exchanges"] == {"glc": 1.0}
        assert pts["km"] == 0.5
        # fractional multi-column exchange coupling survives the loader
        assert net["reactions"]["oxphos_nadh"]["exchanges"] == {"o2": 0.5}
        assert net["reactions"]["pdh"]["exchanges"] == {"co2": -1.0}

    def test_aerobic_growth_with_overflow(self):
        p = core_process()
        upd = p.next_update(1.0, core_states(p, {"glc": 10, "o2": 5, "nh4": 5}))
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        assert float(upd["fluxes"]["growth_rate"]) > 0.3
        # respiratory cap binds -> overflow acetate out, CO2 out, glc in
        assert float(upd["exchange"]["ace_exchange"]) > 1e-4
        assert float(upd["exchange"]["co2_exchange"]) > 1e-3
        assert float(upd["exchange"]["glc_exchange"]) < -1e-3

    def test_anaerobic_fermentation(self):
        p = core_process()
        upd = p.next_update(1.0, core_states(p, {"glc": 10, "nh4": 5}))
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        g = float(upd["fluxes"]["growth_rate"])
        assert 0.0 < g < 0.4          # grows, but slower than aerobically
        # mixed-acid products secreted
        assert float(upd["exchange"]["eth_exchange"]) > 1e-3
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("pfl")] > 1e-3      # anaerobic route
        assert v[p.reactions.index("pdh")] < 1e-2      # aerobic route off
        assert v[p.reactions.index("oxphos_nadh")] < 1e-2

    def test_acetate_growth_uses_glyoxylate_shunt(self):
        p = core_process()
        upd = p.next_update(1.0, core_states(p, {"ace": 10, "o2": 5, "nh4": 5}))
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        assert float(upd["fluxes"]["growth_rate"]) > 0.05
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("icl_mas")] > 1e-3  # shunt carries flux
        assert v[p.reactions.index("pck")] > 1e-3      # gluconeogenesis on

    def test_lactose_diauxie_repression(self):
        p = core_process()
        both = p.next_update(
            1.0, core_states(p, {"glc": 10, "lcts": 10, "o2": 5, "nh4": 5})
        )
        v = np.asarray(both["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("lcts_uptake")] < 1e-4  # repressed
        alone = p.next_update(
            1.0, core_states(p, {"lcts": 10, "o2": 5, "nh4": 5})
        )
        v2 = np.asarray(alone["fluxes"]["reaction_fluxes"])
        assert v2[p.reactions.index("lcts_uptake")] > 1e-3  # derepressed
        assert float(alone["fluxes"]["growth_rate"]) > 0.3

    def test_nitrogen_limitation(self):
        p = core_process()
        upd = p.next_update(1.0, core_states(p, {"glc": 10, "o2": 5}))
        # no ammonium -> no glutamate -> essentially no growth (the leak
        # relaxation admits O(lp_leak) phantom growth, nothing more)
        assert float(upd["fluxes"]["growth_rate"]) < 5e-3

    def test_starvation_infeasible_not_garbage(self):
        p = core_process()
        upd = p.next_update(1.0, core_states(p, {}))
        assert float(upd["fluxes"]["lp_converged"]) == 0.0
        assert float(upd["fluxes"]["growth_rate"]) == 0.0
        for mol in p.external:
            assert float(upd["exchange"][f"{mol}_exchange"]) == 0.0

    def test_batched_oracle_parity(self):
        """vmap the big-network solve over random environments and compare
        against scipy HiGHS on the IDENTICAL leak-relaxed LP."""
        import scipy.optimize

        p = core_process()
        rng = np.random.default_rng(7)
        n_env = 16
        envs = np.zeros((n_env, len(p.external)), np.float32)
        for i in range(n_env):
            for e, mol in enumerate(p.external):
                if rng.random() < 0.6:
                    envs[i, e] = rng.uniform(0.0, 12.0)

        lbub = jax.vmap(lambda e: p.regulated_bounds(e, 1.0))(
            jnp.asarray(envs)
        )
        from lens_tpu.ops.linprog import flux_balance

        sols = jax.vmap(
            lambda l, u: flux_balance(
                p.stoichiometry, p.objective, l, u,
                n_iter=60, tol=1e-4, leak=1.5e-3,
            )
        )(*lbub)

        S = np.asarray(p.stoichiometry)
        m = S.shape[0]
        S_aug = np.concatenate([S, np.eye(m)], axis=1)
        c_aug = np.concatenate([-np.asarray(p.objective), np.zeros(m)])
        n_conv = 0
        for i in range(n_env):
            lb = np.concatenate(
                [np.asarray(lbub[0][i]), -1.5e-3 * np.ones(m)]
            )
            ub = np.concatenate(
                [np.asarray(lbub[1][i]), 1.5e-3 * np.ones(m)]
            )
            ref = scipy.optimize.linprog(
                c_aug, A_eq=S_aug, b_eq=np.zeros(m),
                bounds=list(zip(lb, ub)), method="highs",
            )
            conv = bool(sols.converged[i])
            if ref.status != 0:
                assert not conv, f"env {i}: converged on infeasible LP"
                continue
            if conv:
                n_conv += 1
                np.testing.assert_allclose(
                    float(sols.objective[i]), -ref.fun, atol=5e-3,
                    err_msg=f"env {i}",
                )
        # float32 may fail to certify a few hard (heavily gated) boxes —
        # those report unconverged and the process zeroes them — but the
        # bulk must both converge and match the oracle.
        assert n_conv >= int(0.75 * n_env), f"only {n_conv}/{n_env} converged"

    def test_rfba_lattice_ecoli_core_end_to_end(self):
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {
                "capacity": 32,
                "shape": (8, 8),
                "division": True,
                "metabolism": {"network": "ecoli_core"},
            }
        )
        assert list(spatial.lattice.molecules) == list(
            ("glc", "lcts", "ace", "o2", "nh4", "co2", "eth")
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        glc0 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass0 = float(jnp.sum(jnp.where(
            ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
        )))
        ss, traj = spatial.run(ss, 20.0, 1.0, emit_every=20)
        glc1 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        mass1 = float(jnp.sum(jnp.where(
            ss.colony.alive, ss.colony.agents["global"]["mass"], 0.0
        )))
        assert glc1 < glc0
        assert mass1 > mass0
        assert bool(jnp.all(jnp.isfinite(ss.fields)))
        # per-agent convergence telemetry emitted for offline audit
        assert "lp_converged" in traj["fluxes"]

    def test_rfba_with_genome_expression_composite(self):
        """Config-3-shaped composite at reference scale: every agent runs
        the 24x35 LP AND a 32-gene stochastic expression model, coupled
        to the same lattice fields (lac genes and lcts_uptake co-switch)."""
        from lens_tpu.models.composites import rfba_lattice

        spatial, comp = rfba_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "metabolism": {"network": "ecoli_core"},
                "expression": {"genes": "ecoli_core"},
            }
        )
        assert "expression" in comp.processes
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        ss, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
        agents, alive = ss.colony.agents, ss.colony.alive
        assert float(jnp.sum(
            agents["counts"]["mrna"] * alive[:, None]
        )) > 0  # transcription happened
        conv = jnp.where(alive, agents["fluxes"]["lp_converged"], 1.0)
        assert float(jnp.mean(conv)) > 0.9  # LPs solving on the lattice
        assert bool(jnp.all(jnp.isfinite(ss.fields)))

    def test_media_shift_timeline_switches_pathways(self):
        """Glucose era -> lactose era via a media timeline on the
        ecoli_core lattice: after the shift the colony grows through the
        (derepressed) lactose route — the full diauxie machinery
        exercised through the data layer's core_* recipes."""
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "division": False,
                "motility": {"sigma": 0.0},
                "metabolism": {"network": "ecoli_core"},
            }
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(1))
        ss, traj = spatial.run_timeline(
            ss, "0 core_minimal, 10 core_lactose", 20.0, 1.0, emit_every=2
        )
        lcts = spatial.lattice.index("lcts")
        glc = spatial.lattice.index("glc")
        fields = np.asarray(traj["fields"])
        # pre-shift: glucose present, no lactose; post-shift: swapped
        assert fields[3, glc].mean() > 5.0 and fields[3, lcts].mean() == 0.0
        assert fields[6, glc].mean() == 0.0 and fields[6, lcts].mean() > 5.0
        # post-shift biology: the lactose route carries flux
        v = np.asarray(ss.colony.agents["fluxes"]["reaction_fluxes"])
        alive = np.asarray(ss.colony.alive)
        p = FBAMetabolism({"network": "ecoli_core"})
        lcts_flux = v[alive][:, p.reactions.index("lcts_uptake")]
        assert (lcts_flux > 1e-3).all()
        growth = np.asarray(ss.colony.agents["fluxes"]["growth_rate"])[alive]
        assert (growth > 0.1).all()


class TestWarmStartComposite:
    """The lp_state port threads the IPM warm start through the spatial
    composite: telemetry must show the iteration drop, and the biology
    must match a cold-start run (the hint cannot change what converged
    means — ops.linprog acceptance tests are identical)."""

    def _run(self, warm: bool):
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {
                "capacity": 32,
                "shape": (8, 8),
                "division": False,
                "motility": {"sigma": 0.0},
                "metabolism": {"lp_warm_start": warm},
            }
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(2))
        ss, traj = spatial.run(ss, 20.0, 1.0, emit_every=1)
        return ss, traj

    def test_iterations_drop_and_biology_matches(self):
        ss_w, traj_w = self._run(True)
        ss_c, traj_c = self._run(False)
        its = np.asarray(traj_w["fluxes"]["lp_iterations"])  # [T, N]
        alive = np.asarray(traj_w["alive"])
        # steady state after the first step: warm-started lanes need
        # strictly fewer iterations than the cold first step
        assert its[1:][alive[1:]].mean() < its[0][alive[0]].mean() - 1.0, (
            its.mean(axis=1)
        )
        # same biology to solver tolerance (LP optima agree to ~tol)
        m_w = np.asarray(traj_w["global"]["mass"])
        m_c = np.asarray(traj_c["global"]["mass"])
        np.testing.assert_allclose(m_w, m_c, rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(
            np.asarray(ss_w.fields), np.asarray(ss_c.fields),
            rtol=2e-3, atol=2e-3,
        )


# -- the TRUE e_coli_core (72 metabolites x 95 reactions, VERDICT r3 item 5) --


def full_process(**over):
    cfg = {"network": "ecoli_core_full", "lp_leak": 1.5e-3, "lp_tol": 1e-5,
           "lp_iterations": 45}
    cfg.update(over)
    return FBAMetabolism(cfg)


class TestEcoliCoreFullNetwork:
    """The canonical 72x95 e_coli_core as data (ecoli_core_full_*.tsv).

    The generator (.scratch/gen_ecoli_core_full.py) validated the
    UNTRANSLATED model against the published numbers (aerobic mu 0.8739,
    anaerobic 0.2117 secreting ac/etoh/for — exact matches under HiGHS);
    these tests pin the translated, runtime-format model: canonical-scale
    phenotypes through the float32 batched IPM, plus HiGHS parity on the
    identical LPs.
    """

    def test_loader_counts_and_canonical_content(self):
        from lens_tpu.data import load_rfba_network

        net = load_rfba_network("ecoli_core_full")
        assert len(net["internal"]) == 72          # 52 cytosolic + 20 pools
        assert len(net["external"]) == 17          # lattice fields
        # 75 canonical non-exchange + 33 exchange columns (20 EX split
        # into import/export pairs for fields, free columns for h/h2o/pi)
        assert len(net["reactions"]) == 108
        assert net["objective"] == "BIOMASS"
        pts = net["reactions"]["GLCpts"]
        assert pts["stoich"] == {"glc__D_e": -1.0, "pep": -1.0,
                                 "g6p": 1.0, "pyr": 1.0}
        # growth-associated maintenance in the biomass equation (59.81
        # ATP) and the pinned non-growth maintenance (0.839 scaled)
        assert net["reactions"]["BIOMASS"]["stoich"]["atp"] == -59.81
        lo, hi = net["reactions"]["ATPM"]["bounds"]
        assert abs(lo - 0.839) < 1e-6 and hi == 20.0
        # import split carries the MM km; export split does not
        assert net["reactions"]["glc_in"]["exchanges"] == {"glc": 1.0}
        assert net["reactions"]["glc_in"]["km"] == 0.5
        assert net["reactions"]["ace_out"]["exchanges"] == {"ace": -1.0}

    def test_aerobic_growth_matches_canonical(self):
        p = full_process()
        upd = p.next_update(
            1.0, core_states(p, {"glc": 10, "o2": 50, "nh4": 50})
        )
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        g = float(upd["fluxes"]["growth_rate"])
        # canonical mu 0.8739 x 0.1 scale x MM saturation, affine-
        # corrected for fixed maintenance -> 0.0830; leak bias ~ +0.004
        assert 0.078 < g < 0.093, g
        assert float(upd["exchange"]["glc_exchange"]) < -0.05   # uptake
        assert float(upd["exchange"]["co2_exchange"]) > 0.05    # respiration

    def test_anaerobic_mixed_acid_fermentation(self):
        p = full_process()
        upd = p.next_update(
            1.0, core_states(p, {"glc": 10, "nh4": 50})
        )
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        g = float(upd["fluxes"]["growth_rate"])
        # canonical anaerobic mu 0.2117 x 0.1 x saturation ~ 0.0202
        assert 0.016 < g < 0.024, g
        # the canonical product trio is secreted
        assert float(upd["exchange"]["ace_exchange"]) > 0.01
        assert float(upd["exchange"]["etoh_exchange"]) > 0.01
        assert float(upd["exchange"]["for_exchange"]) > 0.05
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("PFL")] > 0.1       # anaerobic route
        assert v[p.reactions.index("CYTBD")] < 1e-2    # no respiration

    def test_fructose_grows_like_glucose_when_derepressed(self):
        p = full_process()
        both = p.next_update(
            1.0, core_states(p, {"glc": 10, "fru": 10, "o2": 50, "nh4": 50})
        )
        v = np.asarray(both["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("fru_in")] < 1e-4   # repressed by glc
        alone = p.next_update(
            1.0, core_states(p, {"fru": 10, "o2": 50, "nh4": 50})
        )
        ga = float(alone["fluxes"]["growth_rate"])
        assert 0.078 < ga < 0.093, ga                  # same entry point

    def test_acetate_growth_uses_glyoxylate_shunt(self):
        p = full_process()
        upd = p.next_update(
            1.0, core_states(p, {"ace": 10, "o2": 50, "nh4": 50})
        )
        assert float(upd["fluxes"]["lp_converged"]) == 1.0
        assert float(upd["fluxes"]["growth_rate"]) > 0.008
        v = np.asarray(upd["fluxes"]["reaction_fluxes"])
        assert v[p.reactions.index("ICL")] > 0.01
        assert v[p.reactions.index("MALS")] > 0.01

    def test_nitrogen_limitation_full(self):
        p = full_process()
        upd = p.next_update(1.0, core_states(p, {"glc": 10, "o2": 50}))
        assert float(upd["fluxes"]["growth_rate"]) < 5e-3

    def test_batched_oracle_parity_full(self):
        """Random environments through the float32 batched IPM vs HiGHS
        on the IDENTICAL leak-relaxed 72x180 LP."""
        import scipy.optimize

        p = full_process()
        rng = np.random.default_rng(11)
        n_env = 12
        envs = np.zeros((n_env, len(p.external)), np.float32)
        for i in range(n_env):
            for e, mol in enumerate(p.external):
                if rng.random() < 0.5:
                    envs[i, e] = rng.uniform(0.0, 20.0)

        lbub = jax.vmap(lambda e: p.regulated_bounds(e, 1.0))(
            jnp.asarray(envs)
        )
        from lens_tpu.ops.linprog import flux_balance

        sols = jax.vmap(
            lambda l, u: flux_balance(
                p.stoichiometry, p.objective, l, u,
                n_iter=45, tol=1e-5, leak=1.5e-3,
            )
        )(*lbub)

        S = np.asarray(p.stoichiometry)
        m = S.shape[0]
        S_aug = np.concatenate([S, np.eye(m)], axis=1)
        c_aug = np.concatenate([-np.asarray(p.objective), np.zeros(m)])
        n_conv = 0
        for i in range(n_env):
            lb = np.concatenate(
                [np.asarray(lbub[0][i]), -1.5e-3 * np.ones(m)]
            )
            ub = np.concatenate(
                [np.asarray(lbub[1][i]), 1.5e-3 * np.ones(m)]
            )
            ref = scipy.optimize.linprog(
                c_aug, A_eq=S_aug, b_eq=np.zeros(m),
                bounds=list(zip(lb, ub)), method="highs",
            )
            conv = bool(sols.converged[i])
            if ref.status != 0:
                assert not conv, f"env {i}: converged on infeasible LP"
                continue
            if conv:
                n_conv += 1
                np.testing.assert_allclose(
                    float(sols.objective[i]), -ref.fun, atol=5e-3,
                    err_msg=f"env {i}",
                )
        assert n_conv >= int(0.75 * n_env), f"only {n_conv}/{n_env}"

    def test_full_gene_table_loads(self):
        from lens_tpu.processes.genome_expression import GenomeExpression

        expr = GenomeExpression({"genes": "ecoli_core_full"})
        assert len(expr.genes) >= 130
        # operon rules read lattice fields only
        p = full_process()
        assert set(expr.rule_species) <= set(p.external)

    def test_rfba_lattice_full_composite(self):
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "division": False,
                "motility": {"sigma": 0.0},
                "metabolism": {"network": "ecoli_core_full"},
            }
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        glc0 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        ss, traj = spatial.run(ss, 10.0, 1.0, emit_every=5)
        glc1 = float(jnp.sum(ss.fields[spatial.lattice.index("glc")]))
        assert glc1 < glc0
        assert bool(jnp.all(jnp.isfinite(ss.fields)))
        m = np.asarray(traj["global"]["mass"])
        alive = np.asarray(traj["alive"])
        assert (m[-1][alive[-1]] > m[0][alive[-1]]).all()


    def test_full_network_anaerobic_shift_timeline(self):
        """Aerobic -> anaerobic era via a media timeline on the FULL
        network (full_* recipes): after oxygen disappears the colony
        switches to mixed-acid fermentation — PFL carries flux and
        formate/ethanol land in the lattice."""
        from lens_tpu.models.composites import rfba_lattice

        spatial, _ = rfba_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "division": False,
                "motility": {"sigma": 0.0},
                "metabolism": {"network": "ecoli_core_full"},
            }
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(1))
        ss, traj = spatial.run_timeline(
            ss, "0 full_aerobic_glucose, 10 full_anaerobic_glucose",
            20.0, 1.0, emit_every=2,
        )
        fields = np.asarray(traj["fields"])
        o2 = spatial.lattice.index("o2")
        formate = spatial.lattice.index("for")
        assert fields[3, o2].mean() > 2.0       # aerobic era (minus uptake)
        assert fields[6, o2].mean() < 0.5       # media shift took
        assert fields[-1, formate].mean() > 1e-3  # fermentation products
        v = np.asarray(ss.colony.agents["fluxes"]["reaction_fluxes"])
        alive = np.asarray(ss.colony.alive)
        p = full_process()
        assert (v[alive][:, p.reactions.index("PFL")] > 0.05).all()
        growth = np.asarray(ss.colony.agents["fluxes"]["growth_rate"])[alive]
        assert (growth > 0.01).all()            # still growing, slower
