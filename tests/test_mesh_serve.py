"""Mesh-sharded serving (round 13): whole-device failover and the
merge-on-recover sharded WAL.

The contract (docs/serving.md, "Mesh serving & device failover"):
``SimServer(mesh=N)`` places one resident lane pool per device behind
one host scheduler; per-request bits are placement-independent, so a
request's streamed bytes are identical served on any shard, any mesh
size, solo or co-batched. A device that dies — a ``FaultPlan``
``device_down`` declaration, the device watchdog, or an operator call
— becomes a RECOVERABLE EVENT: the shard is quarantined, its snapshots
rehydrate from spills onto survivors, and its requests re-queue under
their original ids, ending bitwise where a never-faulted run would
have. The WAL is one framed-JSON file per shard with a global ``seq``
stamp; merged replay equals a single-WAL replay of the same appends.

The in-process tests need simulated devices — tests/conftest.py
already forces 8 for the whole suite, and the run_tests.sh mesh batch
sets the flag explicitly for conftest-less contexts; the ``needs_mesh``
guard skips rather than errors anywhere neither applies. The
subprocess drills set their own environment and the WAL/merge tests
need no devices at all.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from lens_tpu.serve import (
    DONE,
    FAILED,
    ScenarioRequest,
    ServeWal,
    SimServer,
)
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.wal import shard_wal_name
from lens_tpu.utils.dicts import flatten_paths

N_DEVICES = jax.device_count()
needs_mesh = pytest.mark.skipif(
    N_DEVICES < 4,
    reason="needs >=4 devices: run under XLA_FLAGS="
    "--xla_force_host_platform_device_count=8 (run_tests.sh mesh "
    "batch)",
)


def _flat(tree):
    return {
        "/".join(map(str, p)): np.asarray(v)
        for p, v in flatten_paths(tree)
    }


def _assert_bitwise(got, ref, label=""):
    got, ref = _flat(got), _flat(ref)
    assert set(got) == set(ref), label
    for k in ref:
        assert got[k].tobytes() == ref[k].tobytes(), f"{label}: {k}"


def _solo_oracle(seeds, horizon, composite="toggle_colony", **kw):
    """Single-device, one-lane, one-at-a-time — the bitwise oracle."""
    kw.setdefault("capacity", 16)
    kw.setdefault("window", 8)
    srv = SimServer.single_bucket(composite, lanes=1, **kw)
    out = {}
    for s in seeds:
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=s, horizon=horizon,
        ))
        srv.run_until_idle(max_ticks=500)
        out[s] = srv.result(rid)
    srv.close()
    return out


class TestShardedWal:
    """The merge-on-recover protocol — no devices needed."""

    def _events(self, wal):
        return [
            (e["event"], e.get("rid"))
            for e in wal.events
            if e.get("event") != "server_begin"
        ]

    def test_appends_route_to_shard_files(self, tmp_path):
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=3)
        wal.append({"event": "submit", "rid": "req-000000"})
        wal.append({"event": "retire", "rid": "req-000000"}, shard=2)
        wal.append({"event": "streamed", "rid": "req-000000"}, shard=2)
        wal.append({"event": "submit", "rid": "req-000001"})
        wal.append({"event": "retire", "rid": "req-000001"}, shard=1)
        wal.close()
        for k in range(3):
            assert os.path.exists(str(tmp_path / shard_wal_name(k)))
        # shard 2's file holds exactly its two events
        from lens_tpu.emit.log import JsonFrameLog

        solo = JsonFrameLog(str(tmp_path / shard_wal_name(2)))
        assert [
            e["event"] for e in solo.events
            if e.get("event") != "server_begin"
        ] == ["retire", "streamed"]
        solo.close()

    def test_merged_order_is_total_append_order(self, tmp_path):
        """Events interleaved across shards replay in exactly the
        order the scheduler appended them — the seq stamp's job."""
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=4)
        appended = []
        for i in range(20):
            ev = {"event": f"ev{i}", "rid": f"req-{i:06d}"}
            wal.append(ev, shard=i % 4)
            appended.append((ev["event"], ev["rid"]))
        wal.close()
        wal2 = ServeWal(str(tmp_path / "serve.wal"), n_shards=4)
        assert self._events(wal2) == appended
        wal2.close()

    def test_merge_equals_single_wal_reference(self, tmp_path):
        """The same append sequence through N shard files and through
        one file replays identically (same events, same order, same
        seq stamps) — multi-WAL recovery IS single-WAL recovery."""
        multi = ServeWal(str(tmp_path / "m" / "serve.wal"), n_shards=3)
        single = ServeWal(str(tmp_path / "s" / "serve.wal"))
        for i in range(12):
            ev = {"event": "retire", "rid": f"req-{i:06d}", "n": i}
            multi.append(ev, shard=i % 3)
            single.append(ev)
        multi.close()
        single.close()
        m = ServeWal(str(tmp_path / "m" / "serve.wal"), n_shards=3)
        s = ServeWal(str(tmp_path / "s" / "serve.wal"))
        strip = lambda wal: [
            {k: v for k, v in e.items() if k != "shard"}
            for e in wal.events
            if e.get("event") != "server_begin"
        ]
        assert strip(m) == strip(s)
        m.close()
        s.close()

    def test_torn_tail_on_one_shard_loses_only_that_event(
        self, tmp_path
    ):
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=2)
        wal.append({"event": "submit", "rid": "req-000000"})
        wal.append({"event": "retire", "rid": "req-000000"}, shard=1)
        wal.append({"event": "submit", "rid": "req-000001"})
        wal.close()
        # kill mid-append on shard 1's log: torn tail frame
        with open(str(tmp_path / shard_wal_name(1)), "ab") as f:
            f.write(b"LENS-torn-frame")
        wal2 = ServeWal(str(tmp_path / "serve.wal"), n_shards=2)
        assert self._events(wal2) == [
            ("submit", "req-000000"),
            ("retire", "req-000000"),
            ("submit", "req-000001"),
        ]
        # appends after the truncation keep the global order
        wal2.append({"event": "retire", "rid": "req-000001"}, shard=1)
        assert self._events(wal2)[-1] == ("retire", "req-000001")
        wal2.close()

    def test_interleaved_retire_streamed_across_shards(self, tmp_path):
        """The DONE-needs-streamed recovery rule depends on relative
        order across SHARD FILES; the merge must preserve it."""
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=3)
        wal.append({"event": "submit", "rid": "req-000000"})
        wal.append({"event": "submit", "rid": "req-000001"})
        wal.append(
            {"event": "retire", "rid": "req-000000", "status": "done"},
            shard=1,
        )
        wal.append(
            {"event": "retire", "rid": "req-000001", "status": "done"},
            shard=2,
        )
        wal.append({"event": "streamed", "rid": "req-000001"}, shard=2)
        wal.append({"event": "streamed", "rid": "req-000000"}, shard=1)
        wal.close()
        wal2 = ServeWal(str(tmp_path / "serve.wal"), n_shards=3)
        kinds = self._events(wal2)
        assert kinds.index(("retire", "req-000001")) \
            < kinds.index(("streamed", "req-000001"))
        assert kinds.index(("streamed", "req-000001")) \
            < kinds.index(("streamed", "req-000000"))
        wal2.close()

    def test_begin_fingerprint_verified_per_shard_file(self, tmp_path):
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=2)
        wal.begin("fp-aaaa", {"toggle_colony": {}})
        wal.close()
        wal2 = ServeWal(str(tmp_path / "serve.wal"), n_shards=2)
        wal2.begin("fp-aaaa", {"toggle_colony": {}})  # same: fine
        with pytest.raises(ValueError, match="fingerprint"):
            wal2.begin("fp-bbbb", {"toggle_colony": {}})
        wal2.close()

    def test_narrower_reopen_still_merges_all_shards(self, tmp_path):
        """A 1-shard server over a 4-shard recover_dir must still see
        every shard's events (mesh resize across recovery is legal)."""
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=4)
        for i in range(8):
            wal.append(
                {"event": "retire", "rid": f"req-{i:06d}"}, shard=i % 4
            )
        wal.close()
        narrow = ServeWal(str(tmp_path / "serve.wal"), n_shards=1)
        assert len(self._events(narrow)) == 8
        narrow.close()

    def test_legacy_unstamped_events_sort_first(self, tmp_path):
        """A pre-round-13 WAL (no seq stamps) replays in file order
        ahead of any new stamped appends."""
        from lens_tpu.emit.log import JsonFrameLog

        legacy = JsonFrameLog(str(tmp_path / "serve.wal"))
        legacy.append({"event": "submit", "rid": "req-000000"})
        legacy.append({"event": "retire", "rid": "req-000000"})
        legacy.close()
        wal = ServeWal(str(tmp_path / "serve.wal"), n_shards=2)
        wal.append({"event": "submit", "rid": "req-000001"}, shard=1)
        assert self._events(wal) == [
            ("submit", "req-000000"),
            ("retire", "req-000000"),
            ("submit", "req-000001"),
        ]
        wal.close()


class TestRestoreTreeDevice:
    """checkpoint.restore_tree re-pins a spill onto a chosen device
    (the failover satellite) — meaningful at any device count."""

    def test_restore_lands_on_requested_device(self, tmp_path):
        from lens_tpu.checkpoint import restore_tree, save_tree

        state = {
            "a": jax.numpy.arange(6.0),
            "b": {"c": jax.numpy.arange(3)},
        }
        path = str(tmp_path / "spill")
        save_tree(path, state)
        target = jax.devices()[-1]
        back = restore_tree(path, device=target)
        for leaf in jax.tree.leaves(back):
            assert leaf.devices() == {target}
        _assert_bitwise(back, state)

    def test_default_placement_unchanged(self, tmp_path):
        from lens_tpu.checkpoint import restore_tree, save_tree

        state = {"a": jax.numpy.arange(4.0)}
        path = str(tmp_path / "spill")
        save_tree(path, state)
        _assert_bitwise(restore_tree(path), state)


@needs_mesh
class TestMeshServing:
    def test_solo_equals_cobatched_across_shards(self):
        """The determinism contract survives placement: requests
        co-batched across 4 devices stream the same bytes as solo
        single-device runs — including the stochastic composite."""
        for composite, kw in (
            ("toggle_colony", dict(capacity=16, window=8)),
            ("hybrid_cell", dict(capacity=8, window=4)),
        ):
            horizon = 16.0
            seeds = list(range(6))
            ref = _solo_oracle(seeds, horizon, composite, **kw)
            srv = SimServer.single_bucket(
                composite, lanes=2, mesh=4, **kw
            )
            rids = {
                s: srv.submit(ScenarioRequest(
                    composite=composite, seed=s, horizon=horizon,
                ))
                for s in seeds
            }
            srv.run_until_idle(max_ticks=500)
            used = {srv.tickets[r].shard for r in rids.values()}
            assert len(used) > 1, "requests never spread across shards"
            for s, rid in rids.items():
                _assert_bitwise(
                    srv.result(rid), ref[s], f"{composite} seed {s}"
                )
            assert srv.metrics()["retraces"] == 0
            srv.close()

    def test_prefix_fork_lands_on_owner_shard(self):
        """The shard-keyed snapshot store routes forks to the device
        that owns the cached prefix tree (device-local scatter)."""
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=2, window=8, mesh=4,
        )
        first = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=3, horizon=24.0,
            prefix={"horizon": 8.0},
            overrides={"global": {"volume": 1.1}},
        ))
        srv.run_until_idle(max_ticks=500)
        owner = srv.snapshots.shard_of(srv.tickets[first].prefix_key)
        assert owner is not None
        # later forks of the same prefix hit the cache and admit on
        # the owning shard (free lanes exist everywhere)
        forks = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=3, horizon=24.0,
                prefix={"horizon": 8.0},
                overrides={"global": {"volume": 1.2 + 0.1 * i}},
            ))
            for i in range(2)
        ]
        srv.run_until_idle(max_ticks=500)
        for rid in forks:
            t = srv.tickets[rid]
            assert t.status == DONE
            assert t.shard == owner
        c = srv.metrics()["counters"]
        assert c["prefix_hits"] == 2
        srv.close()

    def test_per_shard_gauges(self):
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=2, window=8, mesh=4,
        )
        for s in range(8):
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=8.0,
            ))
        srv.run_until_idle(max_ticks=500)
        snap = srv.metrics()
        assert len(snap["shards"]) == 4
        assert snap["quarantined_devices"] == 0
        for k, row in enumerate(snap["shards"]):
            assert row["shard"] == k
            assert row["lanes_total"] == 2
            assert not row["quarantined"]
            assert row["windows"] >= 1  # every shard served something
            assert {
                "occupancy", "diverged", "snapshot_bytes",
                "snapshots_resident", "device",
            } <= set(row)
        # the same gauges ride status() for any request
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=99, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=200)
        assert len(srv.status(rid)["server"]["shards"]) == 4
        srv.close()

    def test_check_finite_quarantines_one_lane_not_the_device(self):
        """Lane quarantine and device quarantine compose: a NaN lane
        on shard k fails only its request; the shard keeps serving."""
        faults = FaultPlan([
            {"kind": "nan", "request": "req-000001", "after_steps": 8},
        ])
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=2, window=8, mesh=2,
            check_finite="window", faults=faults,
        )
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=32.0,
            ))
            for s in range(4)
        ]
        srv.run_until_idle(max_ticks=500)
        statuses = [srv.status(r)["status"] for r in rids]
        assert statuses.count(FAILED) == 1
        assert statuses.count(DONE) == 3
        snap = srv.metrics()
        assert snap["quarantined_devices"] == 0
        assert snap["counters"]["diverged"] == 1
        assert sum(s["diverged"] for s in snap["shards"]) == 1
        srv.close()


@needs_mesh
class TestDeviceFailover:
    def test_kill_one_device_drill(self):
        """The headline: a device declared down mid-load loses no
        requests — displaced work re-queues under original ids onto
        survivors and streams bytes bitwise equal to no-fault solo
        runs."""
        horizon = 24.0
        seeds = list(range(8))
        ref = _solo_oracle(seeds, horizon)
        faults = FaultPlan([
            {"kind": "device_down", "shard": 1, "occurrence": 2},
        ])
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=2, window=8, mesh=4,
            faults=faults,
        )
        rids = {
            s: srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=horizon,
            ))
            for s in seeds
        }
        srv.run_until_idle(max_ticks=1000)
        snap = srv.metrics()
        assert snap["quarantined_devices"] == 1
        assert snap["shards"][1]["quarantined"]
        assert snap["counters"]["requeued"] >= 1
        assert snap["lanes_total"] == 6  # dead shard's 2 lanes gone
        for s, rid in rids.items():
            assert srv.status(rid)["status"] == DONE
            assert srv.tickets[rid].shard != 1
            _assert_bitwise(srv.result(rid), ref[s], f"seed {s}")
        # the drained device never schedules again
        more = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=77, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=200)
        assert srv.tickets[more].shard != 1
        srv.close()

    def test_retry_after_excludes_quarantined_lanes(self):
        """A half-dead mesh must not advertise capacity it cannot
        schedule: the backpressure hint re-derives from surviving
        lanes only."""
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8, mesh=2,
            pipeline="off",
        )
        for s in range(6):
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=64.0,
            ))
        srv.tick()  # both lanes busy, 4 queued
        srv.tick()  # a measured window rate exists
        assert srv.metrics()["lanes_total"] == 2
        healthy = srv._retry_after()
        srv.quarantine_device(1, reason="test")
        assert srv.metrics()["lanes_total"] == 1
        assert srv.metrics()["quarantined_devices"] == 1
        # same backlog, half the lanes: the hint must grow
        assert srv._retry_after() > healthy
        srv.run_until_idle(max_ticks=1000)
        srv.close()

    def test_device_watchdog_quarantines_hung_shard(self):
        """A shard whose window output never polls ready within
        device_watchdog_s is quarantined and its request completes on
        a survivor. (Pipelined: the synchronous path blocks through
        every window inline, so it has no un-observed dispatches for
        the watchdog to time — by construction.)"""
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8, mesh=2,
            device_watchdog_s=0.05,
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=64.0,
        ))
        srv.tick()  # dispatches on some shard
        victim = srv.tickets[rid].shard
        # simulate the hang: the completion poll never turns ready
        srv._window_ready = lambda shard: False
        time.sleep(0.06)
        srv.tick()  # watchdog fires, device quarantined, requeued
        assert srv.metrics()["quarantined_devices"] == 1
        assert victim in srv._quarantined
        del srv._window_ready  # the survivor is healthy
        srv.run_until_idle(max_ticks=500)
        assert srv.status(rid)["status"] == DONE
        assert srv.tickets[rid].shard != victim
        srv.close()

    def test_hold_rehydrates_from_spill_onto_survivor(self, tmp_path):
        """A held snapshot whose device dies rehydrates from its
        durable spill onto a surviving device; the resubmit chain
        stays bitwise (stochastic composite, so equality means the
        exact bits came back)."""
        def chain(out, wal, down):
            srv = SimServer.single_bucket(
                "hybrid_cell", capacity=8, lanes=1, window=4, mesh=4,
                out_dir=str(out), sink="log", recover_dir=str(wal),
            )
            parent = srv.submit(ScenarioRequest(
                composite="hybrid_cell", seed=3, horizon=8.0,
                hold_state=True,
            ))
            srv.run_until_idle(max_ticks=300)
            pt = srv.tickets[parent]
            if down:
                owner = srv.snapshots.shard_of(pt.held_key)
                srv.quarantine_device(owner, reason="test")
                assert srv.snapshots.shard_of(pt.held_key) != owner
            cont = srv.resubmit(parent, 8.0)
            srv.run_until_idle(max_ticks=300)
            assert srv.status(cont)["status"] == DONE
            data = {
                os.path.basename(p): open(p, "rb").read()
                for p in glob.glob(os.path.join(str(out), "*.lens"))
            }
            srv.close()
            return data

        ref = chain(tmp_path / "ref", tmp_path / "ref_wal", down=False)
        got = chain(tmp_path / "cr", tmp_path / "cr_wal", down=True)
        assert got == ref

    def test_hold_without_spill_is_lost_descriptively(self):
        """No recover_dir = no spill: quarantining the owner loses the
        held bits, and resubmit refuses instead of recomputing
        silently-different state."""
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8, mesh=2,
        )
        parent = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=3, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=300)
        owner = srv.snapshots.shard_of(srv.tickets[parent].held_key)
        srv.quarantine_device(owner, reason="test")
        with pytest.raises(ValueError, match="no final state"):
            srv.resubmit(parent, 8.0)
        assert srv.snapshots.refs_total() == 0
        srv.close()

    def test_displaced_continuation_rearms_from_rehydrated_spill(
        self, tmp_path
    ):
        """Kill the device while a continuation is RUNNING on it: the
        continuation re-queues, re-pins the rehydrated spill, and the
        chain ends bitwise equal to an undisturbed one."""
        def chain(out, wal, down):
            srv = SimServer.single_bucket(
                "hybrid_cell", capacity=8, lanes=1, window=4, mesh=2,
                out_dir=str(out), sink="log", recover_dir=str(wal),
                pipeline="off",
            )
            parent = srv.submit(ScenarioRequest(
                composite="hybrid_cell", seed=5, horizon=8.0,
                hold_state=True,
            ))
            srv.run_until_idle(max_ticks=300)
            cont = srv.resubmit(parent, 16.0)
            srv.tick()  # continuation admitted + one window ran
            ct = srv.tickets[cont]
            if down:
                assert ct.status == "running"
                srv.quarantine_device(ct.shard, reason="test")
            srv.run_until_idle(max_ticks=300)
            assert srv.status(cont)["status"] == DONE
            data = {
                os.path.basename(p): open(p, "rb").read()
                for p in glob.glob(os.path.join(str(out), "*.lens"))
            }
            srv.close()
            return data

        ref = chain(tmp_path / "ref", tmp_path / "ref_wal", down=False)
        got = chain(tmp_path / "cr", tmp_path / "cr_wal", down=True)
        assert got == ref

    def test_prefix_forks_survive_owner_death(self):
        """Forks whose cached prefix died with its device (no spill)
        re-resolve: a fresh prefix run on a survivor, same bytes."""
        horizon, prefix_h = 24.0, 8.0
        mk = lambda seed_off: ScenarioRequest(
            composite="toggle_colony", seed=3, horizon=horizon,
            prefix={"horizon": prefix_h},
            overrides={"global": {"volume": 1.1 + 0.1 * seed_off}},
        )
        # reference: no faults, single device
        ref_srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8,
        )
        refs = {}
        for i in range(3):
            rid = ref_srv.submit(mk(i))
            ref_srv.run_until_idle(max_ticks=500)
            refs[i] = ref_srv.result(rid)
        ref_srv.close()

        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8, mesh=2,
        )
        first = srv.submit(mk(0))
        srv.run_until_idle(max_ticks=500)
        _assert_bitwise(srv.result(first), refs[0], "first fork")
        owner = srv.snapshots.shard_of(srv.tickets[first].prefix_key)
        srv.quarantine_device(owner, reason="test")
        later = [srv.submit(mk(i)) for i in (1, 2)]
        srv.run_until_idle(max_ticks=500)
        for i, rid in zip((1, 2), later):
            assert srv.status(rid)["status"] == DONE
            _assert_bitwise(srv.result(rid), refs[i], f"fork {i}")
        # the re-run prefix was a MISS (the cached tree died)
        assert srv.metrics()["counters"]["prefix_misses"] == 2
        srv.close()

    def test_all_devices_down_fails_fast(self):
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=1, window=8, mesh=2,
            pipeline="off",
        )
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=64.0,
            ))
            for s in range(3)
        ]
        srv.tick()
        srv.quarantine_device(0, reason="test")
        srv.quarantine_device(1, reason="test")
        srv.run_until_idle(max_ticks=50)
        for rid in rids:
            st = srv.status(rid)
            assert st["status"] == FAILED
            assert "quarantined" in st["error"]
        with pytest.raises(ValueError, match="quarantined"):
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=9, horizon=8.0,
            ))
        srv.close()


@needs_mesh
@pytest.mark.slow
class TestKillOneDeviceExhaustive:
    """The exhaustive drill: every victim device, several kill times,
    under load — every request completes, bytes pinned against the
    solo oracle."""

    @pytest.mark.parametrize("victim", [0, 1, 2, 3])
    @pytest.mark.parametrize("occurrence", [1, 2, 4])
    def test_down_any_device_any_time(self, victim, occurrence):
        horizon = 24.0
        seeds = list(range(8))
        ref = _solo_oracle(seeds, horizon)
        srv = SimServer.single_bucket(
            "toggle_colony", capacity=16, lanes=2, window=8, mesh=4,
            faults=FaultPlan([{
                "kind": "device_down", "shard": victim,
                "occurrence": occurrence,
            }]),
        )
        rids = {
            s: srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=horizon,
            ))
            for s in seeds
        }
        srv.run_until_idle(max_ticks=1000)
        assert srv.metrics()["quarantined_devices"] == 1
        for s, rid in rids.items():
            assert srv.status(rid)["status"] == DONE
            _assert_bitwise(
                srv.result(rid), ref[s],
                f"victim {victim} occ {occurrence} seed {s}",
            )
        srv.close()


# -- subprocess drills: real processes, real SIGKILLs, own env -----------


def _run_cli(args, cwd, expect_kill=False, timeout=300):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    proc = subprocess.run(
        [sys.executable, "-m", "lens_tpu", "serve", *args],
        cwd=cwd, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}"
        )
    return proc


def _lens_bytes(out_dir):
    return {
        os.path.basename(p): open(p, "rb").read()
        for p in glob.glob(os.path.join(str(out_dir), "*.lens"))
    }


@pytest.fixture(scope="module")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


_MESH_REQS = [
    {"seed": 1, "horizon": 24.0, "hold_state": True},
    {"seed": 2, "horizon": 24.0, "prefix": {"horizon": 8.0},
     "overrides": {"global": {"volume": 1.1}}},
    {"seed": 3, "horizon": 16.0},
    {"seed": 4, "horizon": 16.0},
]


def _mesh_kill_roundtrip(tmp_path, repo_root, seam, composite,
                         extra_flags=()):
    """SIGKILL a real 4-device serve process at ``seam``, recover over
    the same dir, and return (reference bytes, recovered bytes)."""
    reqs = tmp_path / "reqs.json"
    reqs.write_text(json.dumps(_MESH_REQS))
    base = [
        "--composite", composite, "--capacity", "8", "--lanes", "1",
        "--window", "4", "--mesh", "4", "--requests", str(reqs),
        *extra_flags,
    ]
    tag = seam.replace(".", "_")
    ref_out = tmp_path / f"ref_{tag}"
    _run_cli(
        base + ["--out-dir", str(ref_out),
                "--recover-dir", str(tmp_path / f"ref_wal_{tag}")],
        repo_root,
    )
    out = tmp_path / f"out_{tag}"
    wal = tmp_path / f"wal_{tag}"
    faults = tmp_path / f"faults_{tag}.json"
    faults.write_text(json.dumps([{"kind": "kill", "at": seam}]))
    _run_cli(
        base + ["--out-dir", str(out), "--recover-dir", str(wal),
                "--faults", str(faults)],
        repo_root, expect_kill=True,
    )
    # the killed multi-shard server left per-shard WALs to merge
    assert os.path.exists(str(wal / "serve.wal"))
    _run_cli(
        base + ["--out-dir", str(out), "--recover-dir", str(wal)],
        repo_root,
    )
    return _lens_bytes(ref_out), _lens_bytes(out)


@pytest.mark.slow
class TestMultiShardRecovery:
    """A SIGKILLed MULTI-SHARD server recovers from its merged
    per-shard WALs byte-equal to an uninterrupted run. Slow tier:
    three real CLI subprocesses (~a minute of jax startups) — the
    quick signal for the same machinery is the in-process failover
    drills above plus test_recovery's single-WAL SIGKILL roundtrip;
    the WAL merge ordering itself is unit-pinned in TestShardedWal."""

    def test_sigkill_mesh_recovers_bitwise(self, tmp_path, repo_root):
        ref, got = _mesh_kill_roundtrip(
            tmp_path, repo_root, "retired.walled", "toggle_colony"
        )
        assert ref, "reference run produced no logs?"
        assert set(ref) <= set(got)
        for name, data in ref.items():
            assert got[name] == data, f"{name} differs after recovery"


@pytest.mark.slow
class TestMultiShardRecoveryExhaustive:
    """SIGKILL the 4-device server at every CLI-reachable kill seam,
    stochastic composite — the mesh extension of the round-12 sweep."""

    @pytest.mark.parametrize(
        "seam",
        ["submit.walled", "admitted", "window.dispatched",
         "hold.spilled", "streamed.walled"],
    )
    def test_kill_everywhere_recovers_bitwise(
        self, tmp_path, repo_root, seam
    ):
        ref, got = _mesh_kill_roundtrip(
            tmp_path, repo_root, seam, "hybrid_cell",
            extra_flags=("--check-finite", "window"),
        )
        assert ref, "reference run produced no logs?"
        for name, data in ref.items():
            assert got[name] == data, f"{name} differs after {seam}"
