"""The multi-tenant HTTP front door (lens_tpu.frontdoor).

Four contract families (docs/serving.md, "Front door"):

- **Tenant policy is plain Python**: WDRR weights, strict
  interactive-over-batch ordering, token buckets, quotas, and the
  priority-aware serve queue are pinned deterministically with fake
  clocks and no sockets.
- **HTTP semantics**: submit/status/stream/cancel round trips, 400
  bodies carrying machine-readable field paths, 401/403/404 tenancy
  isolation, 429 + Retry-After honored by a retrying client, 503
  while draining.
- **Bytes**: an SSE record stream's decoded frames are BYTE-IDENTICAL
  to the request's ``.lens`` log — including the stochastic composite
  on a 2-device mesh with the pipeline on (the serving determinism
  contract surviving the hop over HTTP).
- **Fairness**: a flooding tenant cannot stall the interactive class
  beyond a bounded number of windows (starvation-freedom), pinned
  both at the scheduler level and end-to-end over HTTP.
"""

import http.client
import json
import os
import threading
import time

import pytest

from lens_tpu.frontdoor import (
    AuthError,
    Authenticator,
    Entry,
    FrontDoor,
    TenantConfig,
    TenantQueueFull,
    TenantScheduler,
    TokenBucket,
    decode_record_events,
    load_tenants,
)
from lens_tpu.serve import (
    INTERACTIVE,
    ScenarioRequest,
    SimServer,
)
from lens_tpu.serve.batcher import RequestQueue, Ticket


def _entry(rid, tenant, priority="batch", request=None):
    return Entry(rid=rid, tenant=tenant, priority=priority,
                 request=request)


class _Clock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# tenant policy (jax-free, deterministic)
# ---------------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_rate(self):
        clock = _Clock()
        b = TokenBucket(rate=2.0, burst=2, clock=clock)
        assert b.take() == 0.0
        assert b.take() == 0.0
        wait = b.take()
        assert wait == pytest.approx(0.5)
        clock.t += 0.5
        assert b.take() == 0.0

    def test_refill_caps_at_burst(self):
        clock = _Clock()
        b = TokenBucket(rate=10.0, burst=3, clock=clock)
        clock.t += 100.0
        for _ in range(3):
            assert b.take() == 0.0
        assert b.take() > 0.0


class TestTenantScheduler:
    def _sched(self, **tenants):
        table = {
            name: TenantConfig(name=name, **cfg)
            for name, cfg in tenants.items()
        }
        return TenantScheduler(table, clock=_Clock())

    def test_wdrr_respects_weights(self):
        s = self._sched(a={"weight": 2.0}, b={"weight": 1.0})
        for i in range(12):
            s.push(_entry(f"a{i}", "a"))
            s.push(_entry(f"b{i}", "b"))
        first = [s.pop().tenant for _ in range(9)]
        # 2:1 share for a over any window of the drain
        assert first.count("a") == 6
        assert first.count("b") == 3

    def test_interactive_strictly_before_batch(self):
        s = self._sched(a={}, b={})
        s.push(_entry("a0", "a", "batch"))
        s.push(_entry("a1", "a", "batch"))
        s.push(_entry("b0", "b", INTERACTIVE))
        order = [s.pop().rid for _ in range(3)]
        assert order[0] == "b0"  # interactive first despite arriving last
        assert order[1:] == ["a0", "a1"]

    def test_fifo_within_tenant_class(self):
        s = self._sched(a={})
        for i in range(5):
            s.push(_entry(f"a{i}", "a"))
        assert [s.pop().rid for _ in range(5)] == \
            [f"a{i}" for i in range(5)]

    def test_queue_depth_rejects(self):
        s = self._sched(a={"queue_depth": 2})
        s.push(_entry("a0", "a"))
        s.push(_entry("a1", "a"))
        with pytest.raises(TenantQueueFull) as e:
            s.push(_entry("a2", "a"), retry_after=1.5)
        assert e.value.retry_after == 1.5
        assert e.value.tenant == "a"

    def test_throttle_quota_counts_queued_and_inflight(self):
        s = self._sched(a={"max_inflight": 2})
        assert s.throttle("a") == (None, 0.0)
        s.push(_entry("a0", "a"))
        s.note_submitted("a")
        reason, wait = s.throttle("a")
        assert reason is not None and "quota" in reason
        s.note_finished("a")
        s.pop()
        assert s.throttle("a") == (None, 0.0)

    def test_throttle_rate_limit_hints_retry(self):
        s = self._sched(a={"rate": 2.0, "burst": 1})
        assert s.throttle("a") == (None, 0.0)
        reason, wait = s.throttle("a")
        assert reason is not None and "rate" in reason
        assert wait == pytest.approx(0.5)

    def test_push_front_keeps_turn(self):
        s = self._sched(a={}, b={})
        s.push(_entry("a0", "a"))
        s.push(_entry("b0", "b"))
        e = s.pop()
        s.push_front(e)
        assert s.pop().rid == e.rid  # refused by the server: same turn

    def test_cancel_removes_queued(self):
        s = self._sched(a={})
        s.push(_entry("a0", "a"))
        s.push(_entry("a1", "a"))
        assert s.cancel("a0").rid == "a0"
        assert s.cancel("a0") is None
        assert s.pop().rid == "a1"

    def test_flood_cannot_starve_other_tenant(self):
        """The WDRR bound: with equal weights, a tenant flooding 100
        requests cannot push the other below every-other-admission."""
        s = self._sched(flood={}, small={})
        for i in range(100):
            s.push(_entry(f"f{i}", "flood"))
        s.push(_entry("s0", "small"))
        s.push(_entry("s1", "small"))
        first4 = [s.pop().tenant for _ in range(4)]
        assert first4.count("small") == 2

    def test_load_tenants_forms(self, tmp_path):
        table = load_tenants(
            {"tenants": [{"name": "a", "weight": 2.0},
                         {"name": "b", "api_key": "kb"}]}
        )
        assert set(table) == {"a", "b"}
        path = tmp_path / "tenants.json"
        path.write_text(json.dumps(
            {"tenants": [{"name": "x", "rate": 5.0}]}
        ))
        assert load_tenants(str(path))["x"].rate == 5.0
        # inline JSON (the CLI --tenants form) works without a file
        inline = load_tenants(
            '{"tenants": [{"name": "inline", "weight": 3.0}]}'
        )
        assert inline["inline"].weight == 3.0
        with pytest.raises(ValueError, match="duplicate tenant"):
            load_tenants([{"name": "a"}, {"name": "a"}])
        with pytest.raises(ValueError, match="share an api_key"):
            load_tenants([{"name": "a", "api_key": "k"},
                          {"name": "b", "api_key": "k"}])
        with pytest.raises(ValueError, match="unknown keys"):
            load_tenants([{"name": "a", "weigth": 1.0}])


class TestAuthenticator:
    def _auth(self):
        return Authenticator({
            "keyed": TenantConfig(name="keyed", api_key="secret"),
            "open": TenantConfig(name="open"),
        })

    def test_bearer_key_resolves(self):
        a = self._auth()
        cfg = a.resolve({"authorization": "Bearer secret"})
        assert cfg.name == "keyed"
        cfg = a.resolve({"x-api-key": "secret"})
        assert cfg.name == "keyed"

    def test_unknown_key_401(self):
        with pytest.raises(AuthError) as e:
            self._auth().resolve({"authorization": "Bearer nope"})
        assert e.value.status == 401

    def test_open_tenant_by_name(self):
        assert self._auth().resolve({"x-tenant": "open"}).name == "open"

    def test_keyed_tenant_needs_its_key(self):
        with pytest.raises(AuthError) as e:
            self._auth().resolve({"x-tenant": "keyed"})
        assert e.value.status == 403

    def test_key_for_other_tenant_403(self):
        with pytest.raises(AuthError) as e:
            self._auth().resolve({
                "authorization": "Bearer secret", "x-tenant": "open",
            })
        assert e.value.status == 403

    def test_no_credentials_single_open_tenant(self):
        # exactly one open tenant = the anonymous tier
        assert self._auth().resolve({}).name == "open"
        two_open = Authenticator({
            "a": TenantConfig(name="a"),
            "b": TenantConfig(name="b"),
        })
        with pytest.raises(AuthError) as e:
            two_open.resolve({})  # ambiguous: must name one
        assert e.value.status == 401
        keyed_only = Authenticator({
            "k": TenantConfig(name="k", api_key="kk"),
        })
        with pytest.raises(AuthError) as e:
            keyed_only.resolve({})
        assert e.value.status == 401


class TestPriorityQueue:
    """The serve-side half of the priority lane: RequestQueue.take
    admits interactive ahead of batch, FIFO within a class, and an
    all-default queue is the round-14 FIFO pass bit for bit."""

    def _tickets(self, specs):
        return [
            Ticket(rid, ScenarioRequest("c", priority=prio))
            for rid, prio in specs
        ]

    def test_interactive_admitted_first(self):
        q = RequestQueue(10)
        for t in self._tickets(
            [("b0", "batch"), ("b1", "batch"), ("i0", INTERACTIVE)]
        ):
            q.push(t, 0.0)
        taken = q.take(lambda t: "c", {"c": 2})
        assert [t.request_id for t in taken] == ["i0", "b0"]
        assert [t.request_id for t in q] == ["b1"]

    def test_default_stream_is_fifo(self):
        q = RequestQueue(10)
        for t in self._tickets([(f"r{i}", "batch") for i in range(6)]):
            q.push(t, 0.0)
        taken = q.take(lambda t: "c", {"c": 4})
        assert [t.request_id for t in taken] == \
            ["r0", "r1", "r2", "r3"]
        assert [t.request_id for t in q] == ["r4", "r5"]

    def test_skipped_interactive_keeps_position(self):
        q = RequestQueue(10)
        i0, b0 = self._tickets([("i0", INTERACTIVE), ("b0", "batch")])
        i0.waiting = True  # a fork waiting on its prefix
        q.push(i0, 0.0)
        q.push(b0, 0.0)
        taken = q.take(
            lambda t: "c", {"c": 2}, ready=lambda t: not t.waiting
        )
        assert [t.request_id for t in taken] == ["b0"]
        assert [t.request_id for t in q] == ["i0"]


# ---------------------------------------------------------------------------
# HTTP integration
# ---------------------------------------------------------------------------

_TENANTS = [
    {"name": "acme", "api_key": "acme-key", "weight": 2.0},
    {"name": "beta", "api_key": "beta-key", "weight": 1.0},
    {"name": "limited", "api_key": "lim-key", "rate": 1.0,
     "burst": 1, "max_inflight": 3, "queue_depth": 4},
]


class _Client:
    """Tiny keep-alive HTTP client for the tests."""

    def __init__(self, port, key=None, tenant=None):
        self.conn = http.client.HTTPConnection(
            "127.0.0.1", port, timeout=60
        )
        self.headers = {}
        if key:
            self.headers["Authorization"] = f"Bearer {key}"
        if tenant:
            self.headers["X-Tenant"] = tenant

    def request(self, method, path, body=None):
        self.conn.request(
            method, path,
            body=json.dumps(body) if body is not None else None,
            headers=self.headers,
        )
        r = self.conn.getresponse()
        raw = r.read()
        try:
            payload = json.loads(raw)
        except (ValueError, UnicodeDecodeError):
            payload = raw
        return r.status, payload, dict(r.getheaders())

    def submit(self, body):
        return self.request("POST", "/v1/requests", body)

    def wait(self, rid, statuses=("done",), timeout=60.0):
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout:
            code, st, _ = self.request("GET", f"/v1/requests/{rid}")
            assert code == 200, st
            if st["status"] in statuses:
                return st
            time.sleep(0.02)
        raise AssertionError(
            f"{rid} never reached {statuses}; last: {st}"
        )

    def stream(self, rid):
        """Read one whole SSE stream body (through the end event)."""
        self.conn.request(
            "GET", f"/v1/requests/{rid}/stream", headers=self.headers
        )
        r = self.conn.getresponse()
        assert r.status == 200
        body = r.read()  # http.client de-chunks to EOF-of-stream
        return decode_record_events(body)

    def close(self):
        self.conn.close()


@pytest.fixture(scope="module")
def door(tmp_path_factory):
    out = str(tmp_path_factory.mktemp("frontdoor_out"))
    server = SimServer.single_bucket(
        "minimal_ode", capacity=4, lanes=2, window=4,
        sink="log", out_dir=out, sink_errors="request",
    )
    fd = FrontDoor(server, tenants=_TENANTS, own_server=True)
    fd.start()
    yield fd
    fd.close()


class TestFrontDoorHTTP:
    def test_submit_status_stream_roundtrip(self, door):
        c = _Client(door.port, key="acme-key")
        code, sub, _ = c.submit({"seed": 11, "horizon": 8.0})
        assert code == 202 and sub["tenant"] == "acme"
        rid = sub["rid"]
        st = c.wait(rid)
        assert st["steps_done"] == 8
        assert st["timing"]["admitted"] is not None
        assert st["timing"]["last_streamed"] is not None
        raw, end = c.stream(rid)
        assert end["status"] == "done" and end["error"] is None
        with open(os.path.join(door.server.out_dir, f"{rid}.lens"),
                  "rb") as f:
            assert raw == f.read()  # SSE bytes == log file, bitwise
        c.close()

    def test_validation_error_carries_field_path(self, door):
        c = _Client(door.port, key="acme-key")
        cases = [
            ({"seed": 1, "horizon": 8.0, "emit": {"every": 0}},
             "emit.every"),
            ({"seed": 1, "horizon": 8.0, "emit": {"path": []}},
             "emit.path"),
            ({"seed": 1, "horizon": 8.0, "prefix": {}},
             "prefix.horizon"),
            ({"seed": 1, "horizon": 7.3}, "horizon"),
            ({"seed": 1, "horizon": 8.0, "priority": "urgent"},
             "priority"),
            ({"seed": 1, "horizon": 8.0,
              "overrides": {"cell": {"nope": 1.0}}}, "overrides"),
            ({"seed": 1, "horizont": 8.0}, "horizont"),
        ]
        for body, path in cases:
            code, err, _ = c.submit(body)
            assert code == 400, (body, err)
            assert err["path"] == path, (body, err)
            assert err["error"]
        c.close()

    def test_auth_and_tenant_isolation(self, door):
        anon = _Client(door.port)
        code, err, _ = anon.submit({"seed": 1, "horizon": 8.0})
        assert code == 401
        wrong = _Client(door.port, key="wrong-key")
        code, err, _ = wrong.submit({"seed": 1, "horizon": 8.0})
        assert code == 401
        acme = _Client(door.port, key="acme-key")
        code, err, _ = acme.submit(
            {"seed": 1, "horizon": 8.0, "tenant": "beta"}
        )
        assert code == 403  # cannot submit as someone else
        code, sub, _ = acme.submit({"seed": 12, "horizon": 8.0})
        assert code == 202
        rid = sub["rid"]
        beta = _Client(door.port, key="beta-key")
        code, _err, _ = beta.request("GET", f"/v1/requests/{rid}")
        assert code == 404  # foreign rids are invisible, not 403
        code, _err, _ = beta.request("DELETE", f"/v1/requests/{rid}")
        assert code == 404
        acme.wait(rid)
        for c in (anon, wrong, acme, beta):
            c.close()

    def test_unknown_rid_and_route(self, door):
        c = _Client(door.port, key="acme-key")
        code, _, _ = c.request("GET", "/v1/requests/req-999999")
        assert code == 404
        code, _, _ = c.request("GET", "/v2/nope")
        assert code == 404
        code, _, _ = c.request("PUT", "/v1/requests/req-000000")
        assert code == 405
        c.close()

    def test_429_retry_after_honored_by_retrying_client(self, door):
        """The throttle contract end to end: a burst past the token
        bucket gets 429 + Retry-After; sleeping the hinted time and
        retrying succeeds (the 'healthy client' loop)."""
        c = _Client(door.port, key="lim-key")
        results = []
        for i in range(3):
            results.append(c.submit({"seed": 100 + i, "horizon": 4.0}))
        codes = [code for code, _, _ in results]
        assert 429 in codes, codes  # burst=1: the follow-ups throttle
        throttled = next(
            (payload, headers)
            for code, payload, headers in results if code == 429
        )
        payload, headers = throttled
        assert payload["tenant"] == "limited"
        retry_after = float(headers["Retry-After"])
        assert retry_after > 0
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            time.sleep(retry_after)
            code, sub, headers = c.submit(
                {"seed": 103, "horizon": 4.0}
            )
            if code == 202:
                break
            assert code == 429
            retry_after = float(headers["Retry-After"])
        assert code == 202  # the retrying client got through
        for _, payload, _ in results:
            if isinstance(payload, dict) and "rid" in payload:
                c.wait(payload["rid"])
        c.wait(sub["rid"])
        c.close()

    def test_tenant_counters_surface_everywhere(self, door):
        """Satellite: per-tenant admitted/rejected/throttled/
        streamed_bytes in metrics()/status()/prometheus."""
        snap = door.server.metrics()
        assert "acme" in snap["tenants"]
        row = snap["tenants"]["acme"]
        assert row["admitted"] >= 1
        assert row["streamed_bytes"] > 0  # the roundtrip test streamed
        assert snap["tenants"]["limited"]["throttled"] >= 1
        c = _Client(door.port, key="acme-key")
        code, text, _ = c.request("GET", "/metrics")
        text = text.decode() if isinstance(text, bytes) else str(text)
        assert 'lens_serve_tenant_admitted_total{tenant="acme"}' in text
        assert 'lens_serve_tenant_throttled_total{tenant="limited"}' \
            in text
        code, status, _ = c.request("GET", "/v1/status")
        assert code == 200
        assert status["frontdoor"]["tenants"]["acme"]["weight"] == 2.0
        code, health, _ = c.request("GET", "/healthz")
        assert code == 200 and health["status"] == "ok"
        assert health["lanes_total"] == 2
        c.close()

    def test_cancel_mid_stream(self, door):
        """Open a stream on a long request, cancel it mid-flight: the
        stream terminates with an end event carrying the cancelled
        status, and the lane is reclaimed."""
        c = _Client(door.port, key="acme-key")
        code, sub, _ = c.submit({"seed": 21, "horizon": 400.0})
        assert code == 202
        rid = sub["rid"]
        got = {}

        def read_stream():
            s = _Client(door.port, key="acme-key")
            try:
                got["raw"], got["end"] = s.stream(rid)
            finally:
                s.close()

        reader = threading.Thread(target=read_stream)
        reader.start()
        # wait until the request is actually running, then cancel
        c.wait(rid, statuses=("running",))
        code, out, _ = c.request("DELETE", f"/v1/requests/{rid}")
        assert code == 200
        reader.join(timeout=60)
        assert not reader.is_alive(), "stream never terminated"
        assert got["end"]["status"] == "cancelled"
        st = c.wait(rid, statuses=("cancelled",))
        # partial records stream byte-identically too
        path = os.path.join(door.server.out_dir, f"{rid}.lens")
        with open(path, "rb") as f:
            assert got["raw"] == f.read()
        c.close()

    def test_cancel_while_queued_at_front_door(self, tmp_path):
        """A rid still waiting in the tenant scheduler (server queue
        full behind a long run) cancels at the door without ever
        touching the server, and its stream ends with the cancelled
        status."""
        out = str(tmp_path / "door_queue_out")
        server = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=1, window=4,
            sink="log", out_dir=out, queue_depth=1,
        )
        fd = FrontDoor(server, own_server=True).start()
        try:
            c = _Client(fd.port)
            rids = []
            for i in range(4):
                code, sub, _ = c.submit(
                    {"seed": i, "horizon": 400.0}
                )
                assert code == 202
                rids.append(sub["rid"])
            # 1 lane + server queue depth 1: the tail rids are still
            # at the front door (a 400-step run holds the lane)
            code, st, _ = c.request("GET", f"/v1/requests/{rids[-1]}")
            assert st["status"] == "queued"
            assert st.get("stage") == "frontdoor"
            code, out_p, _ = c.request(
                "DELETE", f"/v1/requests/{rids[-1]}"
            )
            assert code == 200 and out_p["status"] == "cancelled"
            assert rids[-1] not in server.tickets  # never submitted
            raw, end = c.stream(rids[-1])
            assert end["status"] == "cancelled" and raw == b""
            for rid in rids[:-1]:
                c.request("DELETE", f"/v1/requests/{rid}")
            for rid in rids[:-1]:
                c.wait(rid, statuses=("cancelled", "done"))
            c.close()
        finally:
            fd.close()

    def test_draining_returns_503_with_retry_after(self, door):
        door._draining = True
        try:
            c = _Client(door.port, key="acme-key")
            code, err, headers = c.submit({"seed": 41, "horizon": 8.0})
            assert code == 503
            assert float(headers["Retry-After"]) > 0
            code, health, _ = c.request("GET", "/healthz")
            assert code == 503 and health["status"] == "draining"
            c.close()
        finally:
            door._draining = False

    def test_requires_log_sink(self):
        srv = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=1, window=4
        )
        try:
            with pytest.raises(ValueError, match="sink='log'"):
                FrontDoor(srv)
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# fairness end to end
# ---------------------------------------------------------------------------


class TestFairness:
    def test_interactive_class_not_starved_by_flood(
        self, tmp_path
    ):
        """Starvation-freedom, end to end over HTTP: tenant 'flood'
        back-fills the server with batch work; tenant 'fast' then
        submits interactive requests. Every interactive request must
        be admitted ahead of the still-queued flood (bounded by
        lanes-in-flight, not by the flood's backlog)."""
        out = str(tmp_path / "fair_out")
        server = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4,
            sink="log", out_dir=out, queue_depth=64,
        )
        fd = FrontDoor(
            server,
            tenants=[
                {"name": "flood", "api_key": "fk"},
                {"name": "fast", "api_key": "ik"},
            ],
            own_server=True,
        ).start()
        try:
            flood = _Client(fd.port, key="fk")
            fast = _Client(fd.port, key="ik")
            flood_rids = []
            for i in range(24):
                code, sub, _ = flood.submit(
                    {"seed": i, "horizon": 64.0}
                )
                assert code == 202
                flood_rids.append(sub["rid"])
            fast_rids = []
            for i in range(3):
                code, sub, _ = fast.submit(
                    {"seed": 100 + i, "horizon": 8.0,
                     "priority": "interactive"}
                )
                assert code == 202
                fast_rids.append(sub["rid"])
            for rid in fast_rids + flood_rids:
                (fast if rid in fast_rids else flood).wait(
                    rid, timeout=300
                )
            # admission stamps tell the story: every interactive
            # request must hit a lane before the flood's tail (the
            # flood holds ~24 x 16 windows of work across 2 lanes;
            # the interactive class may wait out at most the runs
            # already ON a lane, never the queued backlog)
            admitted = {
                rid: server.tickets[rid].admitted_at
                for rid in flood_rids + fast_rids
            }
            flood_order = sorted(
                admitted[rid] for rid in flood_rids
            )
            worst_fast = max(admitted[rid] for rid in fast_rids)
            assert worst_fast < flood_order[12], (
                "interactive requests were admitted behind the "
                "flooding tenant's backlog"
            )
            # and the flood still made progress afterwards (no
            # reverse starvation)
            assert all(
                server.tickets[rid].status == "done"
                for rid in flood_rids
            )
            flood.close()
            fast.close()
        finally:
            fd.close()


# ---------------------------------------------------------------------------
# bytes under stress: stochastic composite, pipeline on, mesh=2
# ---------------------------------------------------------------------------


class TestStreamBytesStochastic:
    def test_sse_equals_log_bitwise_stochastic_mesh(self, tmp_path):
        """The headline byte pin from the issue: a stochastic
        composite (hybrid_cell: tau-leap Gillespie), pipeline on,
        mesh=2 — the SSE-fetched record stream of every request is
        byte-identical to its on-disk log."""
        out = str(tmp_path / "mesh_out")
        server = SimServer.single_bucket(
            "hybrid_cell", capacity=16, lanes=2, window=8,
            sink="log", out_dir=out, pipeline="on", mesh=2,
        )
        fd = FrontDoor(server, own_server=True).start()
        try:
            c = _Client(fd.port)
            rids = []
            for seed in (3, 5, 9):
                code, sub, _ = c.submit(
                    {"seed": seed, "horizon": 16.0}
                )
                assert code == 202
                rids.append(sub["rid"])
            for rid in rids:
                c.wait(rid, timeout=180)
            for rid in rids:
                raw, end = c.stream(rid)
                assert end["status"] == "done"
                with open(os.path.join(out, f"{rid}.lens"),
                          "rb") as f:
                    disk = f.read()
                assert raw == disk, f"{rid}: SSE bytes != log bytes"
                assert len(raw) > 0
            c.close()
        finally:
            fd.close()


# ---------------------------------------------------------------------------
# scoped sink failures (the chaos-row prerequisite)
# ---------------------------------------------------------------------------


class TestSinkErrorScoping:
    def test_request_scoped_sink_error_fails_one_request(
        self, tmp_path
    ):
        """sink_errors='request': an injected io_error on one
        request's sink fails THAT request (FAILED, error recorded)
        while its co-batched neighbour completes and the server stays
        healthy — the multi-tenant front-door policy. (The default
        'fatal' contract is pinned in tests/test_faults.py.)"""
        from lens_tpu.serve import FaultPlan

        plan = FaultPlan(
            [{"kind": "io_error", "request": "req-000000"}]
        )
        out = str(tmp_path / "sink_out")
        srv = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4,
            sink="log", out_dir=out, faults=plan,
            sink_errors="request",
        )
        with srv:
            bad = srv.submit(ScenarioRequest(
                composite="minimal_ode", seed=1, horizon=16.0,
            ))
            good = srv.submit(ScenarioRequest(
                composite="minimal_ode", seed=2, horizon=16.0,
            ))
            srv.run_until_idle(max_ticks=200)
            assert srv.status(bad)["status"] == "failed"
            assert "sink failure" in srv.status(bad)["error"]
            assert srv.status(good)["status"] == "done"
            # the healthy request's result is intact and complete
            from lens_tpu.emit.log import read_records
            recs = list(read_records(srv.result(good)))
            assert len(recs) >= 2  # header + segments
            snap = srv.metrics()
            assert snap["counters"]["sink_failed"] == 1

    def test_stream_of_sink_failed_request_terminates(self, tmp_path):
        """The torn stream is FINAL: an SSE stream open on a request
        whose sink failed must end (status failed + the error), not
        poll forever for appends that can never come — the front-door
        chaos bench leans on this."""
        from lens_tpu.serve import FaultPlan

        plan = FaultPlan(
            [{"kind": "io_error", "request": "req-000000"}]
        )
        out = str(tmp_path / "sink_stream_out")
        srv = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4,
            sink="log", out_dir=out, faults=plan,
            sink_errors="request",
        )
        fd = FrontDoor(srv, own_server=True).start()
        try:
            c = _Client(fd.port)
            code, sub, _ = c.submit({"seed": 1, "horizon": 16.0})
            assert code == 202
            rid = sub["rid"]
            raw, end = c.stream(rid)  # must terminate
            assert end["status"] == "failed"
            assert "sink failure" in end["error"]
            c.close()
        finally:
            fd.close()

    def test_sync_path_scopes_too(self, tmp_path):
        from lens_tpu.serve import FaultPlan

        plan = FaultPlan(
            [{"kind": "io_error", "request": "req-000000"}]
        )
        out = str(tmp_path / "sink_sync_out")
        srv = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4,
            sink="log", out_dir=out, faults=plan,
            sink_errors="request", pipeline="off",
        )
        with srv:
            bad = srv.submit(ScenarioRequest(
                composite="minimal_ode", seed=1, horizon=16.0,
            ))
            good = srv.submit(ScenarioRequest(
                composite="minimal_ode", seed=2, horizon=16.0,
            ))
            srv.run_until_idle(max_ticks=200)
            assert srv.status(bad)["status"] == "failed"
            assert srv.status(good)["status"] == "done"
