"""Fault injection + lane quarantine + the serve watchdog (round 12).

The chaos pins of ISSUE 10's tentpole: NaN injected into one lane
fails ONLY that request while co-batched lanes' streamed bytes are
bitwise unchanged vs a no-fault run; a hung streamer handoff expires
via the watchdog instead of wedging ``tick()``; injected sink I/O
errors propagate through the existing stream-error contract; and the
deterministic :class:`~lens_tpu.serve.faults.FaultPlan` behind all of
it replays identically.
"""

import contextlib
import time

import jax
import numpy as np
import pytest

from lens_tpu.serve import (
    DONE,
    FaultPlan,
    QueueFull,
    ScenarioRequest,
    SimServer,
    SimulationDiverged,
    WatchdogTimeout,
)
from lens_tpu.serve.faults import KILL_SEAMS


def _toggle_server(**kw):
    kw.setdefault("lanes", 4)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket("toggle_colony", **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


class TestFaultPlan:
    """The harness itself: deterministic, seeded, validated."""

    def test_occurrence_counting_is_deterministic(self):
        plan = FaultPlan([
            {"kind": "stall", "occurrence": 3, "seconds": 0.0},
        ])
        fired = [bool(plan.fire("stream.window")) for _ in range(5)]
        assert fired == [False, False, True, False, False]

    def test_occurrence_zero_fires_every_match(self):
        plan = FaultPlan([{"kind": "stall", "occurrence": 0}])
        assert all(bool(plan.fire("stream.window")) for _ in range(4))

    def test_request_and_step_filters(self):
        plan = FaultPlan([
            {"kind": "nan", "request": "req-000001", "after_steps": 16},
        ])
        assert not plan.poison("req-000000", 100)  # wrong request
        assert not plan.poison("req-000001", 8)    # too early
        assert plan.poison("req-000001", 16)       # fires once
        assert not plan.poison("req-000001", 24)   # spent

    def test_seeded_probabilistic_replays_identically(self):
        def draw(seed):
            plan = FaultPlan(
                [{"kind": "stall", "occurrence": 0, "p": 0.5}],
                seed=seed,
            )
            return [bool(plan.fire("stream.window")) for _ in range(32)]

        a, b = draw(7), draw(7)
        assert a == b            # same seed, same chaos
        assert any(a) and not all(a)  # actually probabilistic
        assert draw(8) != a      # a different seed is different chaos

    def test_from_spec_forms_and_validation(self, tmp_path):
        import json

        assert not FaultPlan.from_spec(None)
        plan = FaultPlan.from_spec(
            {"seed": 3, "faults": [{"kind": "stall"}]}
        )
        assert plan.seed == 3 and len(plan.faults) == 1
        path = tmp_path / "faults.json"
        path.write_text(json.dumps([{"kind": "kill",
                                     "at": "window.dispatched"}]))
        assert len(FaultPlan.from_spec(str(path)).faults) == 1
        with pytest.raises(ValueError, match="unknown kind"):
            FaultPlan([{"kind": "explode"}])
        with pytest.raises(ValueError, match="kill seam"):
            FaultPlan([{"kind": "kill", "at": "nowhere"}])
        with pytest.raises(ValueError, match="unknown keys"):
            FaultPlan([{"kind": "stall", "surprise": 1}])
        with pytest.raises(ValueError, match="fires at seam"):
            FaultPlan([{"kind": "nan", "at": "sink.append"}])
        with pytest.raises(ValueError, match="unknown fault-plan"):
            FaultPlan.from_spec({"faults": [], "extra": 1})

    def test_kill_seams_are_the_documented_set(self):
        # docs/serving.md lists these; a rename must update both
        assert KILL_SEAMS == (
            "submit.walled", "resubmit.walled", "admitted",
            "window.dispatched", "hold.spilled", "retired.walled",
            "streamed.walled", "result.tmp_written", "result.renamed",
            "result.cached",
        )


class TestQuarantine:
    """check_finite="window": a poisoned lane fails only its request."""

    def _serve_logged(self, out_dir, faults, pipeline="on"):
        srv = _toggle_server(
            out_dir=str(out_dir), sink="log",
            check_finite="window", faults=faults, pipeline=pipeline,
        )
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=24.0,
            ))
            for s in (1, 2, 3)
        ]
        srv.run_until_idle(max_ticks=200)
        paths = {r: srv.status(r)["result_path"] for r in rids}
        statuses = {r: srv.status(r)["status"] for r in rids}
        counters = srv.metrics()["counters"]
        errors = {r: srv.status(r)["error"] for r in rids}
        return srv, rids, paths, statuses, counters, errors

    @pytest.mark.parametrize("pipeline", ["on", "off"])
    def test_nan_fails_only_poisoned_request_bitwise(
        self, tmp_path, pipeline
    ):
        """THE quarantine pin: the poisoned request alone fails with a
        descriptive SimulationDiverged; the co-batched requests'
        streamed BYTES are identical to a no-fault run's."""
        plan = FaultPlan([
            {"kind": "nan", "request": "req-000001", "after_steps": 8},
        ])
        srv_f, rids, paths_f, st_f, c_f, err_f = self._serve_logged(
            tmp_path / "faulty", plan, pipeline
        )
        srv_c, _, paths_c, st_c, c_c, _ = self._serve_logged(
            tmp_path / "clean", None, pipeline
        )
        assert st_f[rids[1]] == "failed"
        assert st_f[rids[0]] == st_f[rids[2]] == DONE
        assert c_f["diverged"] == 1 and c_c["diverged"] == 0
        assert "SimulationDiverged" in err_f[rids[1]]
        assert "reclaimed" in err_f[rids[1]]
        with pytest.raises(SimulationDiverged, match="non-finite"):
            srv_f.result(rids[1])
        for rid in (rids[0], rids[2]):
            with open(paths_f[rid], "rb") as a, \
                    open(paths_c[rid], "rb") as b:
                assert a.read() == b.read()  # bitwise, whole file
        srv_f.close()
        srv_c.close()

    def test_default_off_is_round_11_behavior(self):
        """check_finite="off" (the default): the same injected NaN
        sails through — no check program, no status change (the
        garbage is the client's problem, exactly as before round 12)."""
        plan = FaultPlan([
            {"kind": "nan", "request": "req-000000", "after_steps": 8},
        ])
        srv = _toggle_server(faults=plan)  # check_finite defaults off
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=24.0,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(rid)["status"] == DONE
        ts = srv.result(rid)  # no SimulationDiverged raised
        assert np.isnan(
            np.asarray(ts["cell"]["protein_u"])
        ).any()  # the poison really flowed through
        assert srv.metrics()["counters"]["diverged"] == 0
        srv.close()

    def test_final_window_divergence_flips_done_to_failed(self):
        """The one-window detection lag can land AFTER the lane
        retired DONE: the flip path — status becomes failed, result()
        still raises, a held snapshot is never left extendable."""
        plan = FaultPlan([
            # horizon 16, window 8: poison before the SECOND (final)
            # window, so retirement and detection race
            {"kind": "nan", "request": "req-000000", "after_steps": 8},
        ])
        srv = _toggle_server(
            lanes=2, check_finite="window", faults=plan
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(rid)["status"] == "failed"
        with pytest.raises(SimulationDiverged):
            srv.result(rid)
        with pytest.raises(ValueError, match="only DONE"):
            srv.resubmit(rid, 8.0)  # flipped to failed: not extendable
        # and the poisoned hold itself was dropped (no pin leaked)
        assert srv.snapshots.refs_total() == 0
        srv.close()

    def test_quarantined_lane_serves_the_next_request(self):
        """Quarantine reclaims the lane: a subsequent request admitted
        into the (stale-NaN) lane is built fresh and runs clean."""
        plan = FaultPlan([
            {"kind": "nan", "request": "req-000000", "after_steps": 8},
        ])
        srv = _toggle_server(
            lanes=1, check_finite="window", faults=plan
        )
        bad = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=400.0,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(bad)["status"] == "failed"
        ok = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=16.0,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(ok)["status"] == DONE
        assert not np.isnan(
            np.asarray(srv.result(ok)["cell"]["protein_u"])
        ).any()
        assert srv.metrics()["counters"]["diverged"] == 1
        srv.close()


class TestWatchdog:
    def test_stalled_stream_raises_instead_of_wedging(self):
        """A streamer stalled past the watchdog raises WatchdogTimeout
        from tick() in bounded time — previously an unbounded wedge
        behind the backpressure wait."""
        plan = FaultPlan([
            {"kind": "stall", "occurrence": 0, "seconds": 0.8},
        ])
        srv = _toggle_server(
            lanes=1, window=4, watchdog_s=0.2, stream_queue=1,
            faults=plan,
        )
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=400.0,
        ))
        t0 = time.perf_counter()
        with pytest.raises(WatchdogTimeout, match="stalled"):
            for _ in range(50):
                srv.tick()
        assert time.perf_counter() - t0 < 5.0  # bounded, not wedged
        with contextlib.suppress(WatchdogTimeout):
            srv.close()

    def test_injected_sink_io_error_propagates(self):
        """The io_error seam rides the existing stream-error contract:
        the failure parks on the stream thread and raises at the next
        scheduler call; close() re-raises without masking."""
        plan = FaultPlan([{"kind": "io_error", "request": "req-000000"}])
        srv = _toggle_server(lanes=1, window=4, faults=plan)
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        with pytest.raises(OSError, match="injected"):
            srv.run_until_idle(max_ticks=100)
        with pytest.raises(OSError, match="injected"):
            srv.close()

    def test_injected_sink_io_error_sync_path(self):
        """pipeline="off": the same seam raises inline from tick()."""
        plan = FaultPlan([{"kind": "io_error", "request": "req-000000"}])
        srv = _toggle_server(
            lanes=1, window=4, pipeline="off", faults=plan
        )
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        with pytest.raises(OSError, match="injected"):
            srv.run_until_idle(max_ticks=100)
        srv.close()


class TestDeadlineStreamRace:
    def test_expiry_after_handoff_delivers_partials_exactly_once(self):
        """A request expired AFTER its window was handed to the
        background streamer still delivers that window's records
        exactly once: the injected stall holds the window in the
        streamer while the deadline fires, the TIMEOUT close queues
        BEHIND the pending appends, and result() returns the partial
        rows once — no loss, no duplication."""
        plan = FaultPlan([
            {"kind": "stall", "occurrence": 1, "seconds": 0.5},
        ])
        srv = _toggle_server(
            lanes=1, window=4, stream_queue=1, faults=plan
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=400.0,
            deadline=0.25,
        ))
        srv.tick()  # admit + window 1 -> handed to the (stalled) streamer
        assert srv.status(rid)["status"] == "running"
        time.sleep(0.3)  # the deadline passes while the window streams
        srv.tick()  # expiry sweep: TIMEOUT, lane reclaimed
        assert srv.status(rid)["status"] == "timeout"
        partial = srv.result(rid)
        times = np.asarray(partial["__times__"])
        assert times.shape[0] == 4          # window 1's rows, exactly
        assert np.array_equal(times, np.arange(1.0, 5.0))  # once each
        srv.close()


class TestOccupancyRetryAfter:
    def test_hint_scales_with_queued_work_not_queue_length(self):
        """QueueFull.retry_after is derived from the backlog's actual
        remaining WINDOWS (occupancy mirrors + queued horizons), so a
        queue of one long request hints a proportionally longer wait
        than a queue of one short one — same queue LENGTH."""

        def hint(horizon):
            srv = _toggle_server(lanes=1, window=8, queue_depth=1)
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=1, horizon=horizon,
            ))
            with pytest.raises(QueueFull) as exc:
                srv.submit(ScenarioRequest(
                    composite="toggle_colony", seed=2, horizon=8.0,
                ))
            srv.close()
            return exc.value.retry_after

        short, long = hint(8.0), hint(800.0)
        assert short > 0
        assert long > 5 * short  # 100 queued windows vs 1

    def test_hint_counts_time_to_the_next_free_lane(self):
        """With every lane busy, the hint includes windows until the
        EARLIEST lane frees (read off the host-mirrored counters)."""
        srv = _toggle_server(lanes=1, window=8, queue_depth=1)
        running = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=800.0,
        ))
        srv.tick()  # admitted: lane busy, ~99 windows left
        assert srv.status(running)["status"] == "running"
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=8.0,
        ))
        with pytest.raises(QueueFull) as exc:
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=3, horizon=8.0,
            ))
        # >= ~90 windows to the free lane at the measured window rate;
        # just pin it clears a plain one-window hint by a wide margin
        assert exc.value.retry_after > 10 * \
            srv._metrics.avg_window_seconds()
        srv.cancel(running)
        srv.run_until_idle(max_ticks=100)
        srv.close()
