"""SpatialColony: gather/scatter exchange, conservation, motility (config 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.environment import Lattice, SpatialColony
from lens_tpu.processes.mm_transport import (
    BrownianMotility,
    MichaelisMentenTransport,
)


def make_spatial(
    capacity=64,
    n_alive=64,
    shape=(32, 32),
    sigma=0.5,
    d=2.0,
    yield_=1.0,
    k_consume=0.0,
    seed=0,
):
    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport(
                {"yield_": yield_, "k_consume": k_consume}
            ),
            "motility": BrownianMotility(
                {"sigma": sigma, "domain": (float(shape[0]), float(shape[1]))}
            ),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
        },
    )
    colony = Colony(comp, capacity)
    lattice = Lattice(
        molecules=["glucose"],
        shape=shape,
        size=(float(shape[0]), float(shape[1])),
        diffusion=d,
        initial=10.0,
        timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange")),
        },
        location_path=("boundary", "location"),
    )
    ss = spatial.initial_state(n_alive, jax.random.PRNGKey(seed))
    return spatial, ss


def test_agents_deplete_local_field():
    spatial, ss = make_spatial(d=0.0, sigma=0.0)  # no diffusion, no movement
    ss2, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
    f = np.asarray(ss2.fields[0])
    assert f.min() < 10.0 - 0.5  # occupied bins drained
    assert f.max() <= 10.0 + 1e-5  # nothing created


def test_mass_conservation_field_plus_internal():
    """With yield=1, k_consume=0: field loss == total internal pool."""
    spatial, ss = make_spatial(yield_=1.0, k_consume=0.0, sigma=0.3)
    total0 = float(spatial.total_field_mass(ss)[0])
    ss2, _ = spatial.run(ss, 20.0, 1.0, emit_every=20)
    total1 = float(spatial.total_field_mass(ss2)[0])
    internal = float(
        jnp.sum(
            ss2.colony.agents["cell"]["glucose_internal"]
            * ss2.colony.alive
        )
    )
    np.testing.assert_allclose(total0 - total1, internal, rtol=1e-3)


def test_dead_rows_do_not_uptake():
    spatial, ss = make_spatial(capacity=64, n_alive=0, d=0.0, sigma=0.0)
    ss2, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
    np.testing.assert_allclose(np.asarray(ss2.fields), 10.0, rtol=1e-6)


def test_motility_moves_and_stays_in_domain():
    spatial, ss = make_spatial(sigma=1.0)
    loc0 = np.asarray(ss.colony.agents["boundary"]["location"])
    ss2, _ = spatial.run(ss, 20.0, 1.0, emit_every=20)
    loc1 = np.asarray(ss2.colony.agents["boundary"]["location"])
    assert np.any(np.abs(loc1 - loc0) > 0.1)
    assert loc1.min() >= 0.0 and loc1.max() <= 32.0


def test_diffusion_refills_depleted_bins():
    spatial, ss = make_spatial(d=2.0, sigma=0.0)
    ss2, _ = spatial.run(ss, 30.0, 1.0, emit_every=30)
    f = np.asarray(ss2.fields[0])
    # with diffusion on, drained bins pull from neighbors: the field stays
    # smoother than the no-diffusion case
    spatial0, ss0 = make_spatial(d=0.0, sigma=0.0)
    ss0b, _ = spatial0.run(ss0, 30.0, 1.0, emit_every=30)
    f0 = np.asarray(ss0b.fields[0])
    assert f.std() < f0.std()


def test_run_is_jittable_and_emits_fields():
    spatial, ss = make_spatial(capacity=32, n_alive=32, shape=(16, 16))
    run = jax.jit(lambda s: spatial.run(s, 5.0, 1.0, emit_every=5))
    ss2, traj = run(ss)
    assert traj["fields"].shape == (1, 1, 16, 16)
    assert bool(jnp.all(jnp.isfinite(traj["fields"])))


def test_bad_wiring_raises():
    spatial, _ = make_spatial(capacity=8, n_alive=8, shape=(16, 16))
    with pytest.raises(ValueError):
        SpatialColony(
            spatial.colony,
            spatial.lattice,
            field_ports={"glucose": (("nope",), ("boundary", "exchange", "x"))},
        )
    with pytest.raises(ValueError):
        SpatialColony(
            spatial.colony,
            spatial.lattice,
            field_ports={"lactose": (("boundary", "external", "glucose"),
                                     ("boundary", "exchange", "glucose_exchange"))},
        )


def test_division_places_daughters_apart():
    """Under zero motility, daughters must still separate (the `offset`
    location divider) — round-1 co-located them forever."""
    from lens_tpu.core.state import DIVISION_SEPARATION_UM
    from lens_tpu.processes.growth import DivideTrigger, Growth

    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport({"vmax": 0.0}),
            "motility": BrownianMotility({"sigma": 0.0}),
            "growth": Growth({"rate": 0.5}),  # fast: divides in a few steps
            "divide_trigger": DivideTrigger(),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )
    colony = Colony(comp, 8, division_trigger=("global", "divide"))
    lattice = Lattice(
        molecules=["glucose"], shape=(16, 16), size=(16.0, 16.0),
        diffusion=0.0, initial=10.0, timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange")),
        },
        location_path=("boundary", "location"),
    )
    ss = spatial.initial_state(
        1, jax.random.PRNGKey(0),
        locations=np.broadcast_to(
            np.asarray([8.0, 8.0], np.float32), (8, 2)
        ).copy(),
    )
    for _ in range(4):
        ss = spatial.step(ss, 1.0)
        if int(jnp.sum(ss.colony.alive)) >= 2:
            break
    alive = np.asarray(ss.colony.alive)
    assert alive.sum() == 2, "expected exactly one division"
    locs = np.asarray(ss.colony.agents["boundary"]["location"])[alive]
    sep = np.linalg.norm(locs[0] - locs[1])
    np.testing.assert_allclose(sep, DIVISION_SEPARATION_UM, rtol=1e-5)


def test_exact_conservation_with_division_and_motility():
    """Regression (caught in verify): division used to zero the exchange
    accumulator before the field was debited, and the scatter hit the
    post-motility bin — both created mass. Field + internal pool must be
    exactly constant (float32 tolerance) through division epochs."""
    from lens_tpu.processes.growth import DivideTrigger, Growth

    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport(
                {"yield_": 1.0, "k_consume": 0.0, "vmax": 0.4}
            ),
            "motility": BrownianMotility({"sigma": 0.4, "domain": (16.0, 16.0)}),
            "growth": Growth({"rate": 0.03}),
            "divide": DivideTrigger({"threshold": 2.0}),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
            "growth": {"global": ("global",)},
            "divide": {"global": ("global",)},
        },
    )
    colony = Colony(comp, capacity=64, division_trigger=("global", "divide"))
    lattice = Lattice(
        molecules=["glucose"], shape=(16, 16), size=(16.0, 16.0),
        diffusion=1.0, initial=10.0, timestep=1.0,
    )
    spatial = SpatialColony(
        colony, lattice,
        field_ports={"glucose": (("boundary", "external", "glucose"),
                                 ("boundary", "exchange", "glucose_exchange"))},
    )
    ss = spatial.initial_state(4, jax.random.PRNGKey(7))
    total0 = float(spatial.total_field_mass(ss)[0])
    ss2, _ = spatial.run(ss, 120.0, 1.0, emit_every=120)
    assert int(jnp.sum(ss2.colony.alive)) > 8  # divisions happened
    total1 = float(spatial.total_field_mass(ss2)[0])
    internal = float(
        jnp.sum(ss2.colony.agents["cell"]["glucose_internal"] * ss2.colony.alive)
    )
    np.testing.assert_allclose(total0, total1 + internal, rtol=2e-5)


class TestLysis:
    """Death with lysis conserves mass: a dying cell's pool returns to
    its lattice bin through the ordinary exchange path."""

    def _build(self, lysis):
        from lens_tpu.models import ecoli_lattice

        death = {"when": "above", "threshold": 0.5}
        if lysis is not None:
            death["lysis"] = lysis
        spatial, _ = ecoli_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "motility": {"sigma": 0.0},
                # yield_=1, k_consume=0: pool units == field mM, nothing
                # drains — cells eat until the bloat death fires
                "transport": {"yield_": 1.0, "k_consume": 0.0},
                "initial_glucose": 2.0,
                "death": death,
            }
        )
        return spatial

    def _run(self, spatial):
        ss = spatial.initial_state(16, jax.random.PRNGKey(0))
        ss, traj = jax.jit(lambda s: spatial.run(s, 40.0, 1.0))(ss)
        fields_t = np.asarray(traj["fields"]).sum(axis=(1, 2, 3))
        pools = np.asarray(traj["cell"]["glucose_internal"])
        alive = np.asarray(traj["alive"])
        live_pool_t = (pools * alive).sum(axis=1)
        return ss, fields_t, live_pool_t, alive

    def test_lysis_conserves_total_mass(self):
        spatial = self._build(lysis=1.0)
        ss, fields_t, live_pool_t, alive = self._run(spatial)
        assert alive[-1].sum() == 0          # everyone bloated and died
        total0 = fields_t[0] + live_pool_t[0]
        np.testing.assert_allclose(
            fields_t + live_pool_t, total0, rtol=1e-5
        )
        # after the last death everything is back in the field
        np.testing.assert_allclose(fields_t[-1], total0, rtol=1e-5)

    def test_without_lysis_the_pool_is_lost(self):
        spatial = self._build(lysis=None)
        ss, fields_t, live_pool_t, alive = self._run(spatial)
        assert alive[-1].sum() == 0
        # the hoarded pools died with their cells: the field ends LIGHTER
        assert fields_t[-1] < fields_t[0] - 0.4
