"""SpatialColony: gather/scatter exchange, conservation, motility (config 2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.environment import Lattice, SpatialColony
from lens_tpu.processes.mm_transport import (
    BrownianMotility,
    MichaelisMentenTransport,
)


def make_spatial(
    capacity=64,
    n_alive=64,
    shape=(32, 32),
    sigma=0.5,
    d=2.0,
    yield_=1.0,
    k_consume=0.0,
    seed=0,
    coupling="fused",
    locations=None,
):
    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport(
                {"yield_": yield_, "k_consume": k_consume}
            ),
            "motility": BrownianMotility(
                {"sigma": sigma, "domain": (float(shape[0]), float(shape[1]))}
            ),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
        },
    )
    colony = Colony(comp, capacity)
    lattice = Lattice(
        molecules=["glucose"],
        shape=shape,
        size=(float(shape[0]), float(shape[1])),
        diffusion=d,
        initial=10.0,
        timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange")),
        },
        location_path=("boundary", "location"),
        coupling=coupling,
    )
    ss = spatial.initial_state(
        n_alive, jax.random.PRNGKey(seed), locations=locations
    )
    return spatial, ss


def test_agents_deplete_local_field():
    spatial, ss = make_spatial(d=0.0, sigma=0.0)  # no diffusion, no movement
    ss2, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
    f = np.asarray(ss2.fields[0])
    assert f.min() < 10.0 - 0.5  # occupied bins drained
    assert f.max() <= 10.0 + 1e-5  # nothing created


def test_mass_conservation_field_plus_internal():
    """With yield=1, k_consume=0: field loss == total internal pool."""
    spatial, ss = make_spatial(yield_=1.0, k_consume=0.0, sigma=0.3)
    total0 = float(spatial.total_field_mass(ss)[0])
    ss2, _ = spatial.run(ss, 20.0, 1.0, emit_every=20)
    total1 = float(spatial.total_field_mass(ss2)[0])
    internal = float(
        jnp.sum(
            ss2.colony.agents["cell"]["glucose_internal"]
            * ss2.colony.alive
        )
    )
    np.testing.assert_allclose(total0 - total1, internal, rtol=1e-3)


def test_dead_rows_do_not_uptake():
    spatial, ss = make_spatial(capacity=64, n_alive=0, d=0.0, sigma=0.0)
    ss2, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
    np.testing.assert_allclose(np.asarray(ss2.fields), 10.0, rtol=1e-6)


def test_motility_moves_and_stays_in_domain():
    spatial, ss = make_spatial(sigma=1.0)
    loc0 = np.asarray(ss.colony.agents["boundary"]["location"])
    ss2, _ = spatial.run(ss, 20.0, 1.0, emit_every=20)
    loc1 = np.asarray(ss2.colony.agents["boundary"]["location"])
    assert np.any(np.abs(loc1 - loc0) > 0.1)
    assert loc1.min() >= 0.0 and loc1.max() <= 32.0


def test_diffusion_refills_depleted_bins():
    spatial, ss = make_spatial(d=2.0, sigma=0.0)
    ss2, _ = spatial.run(ss, 30.0, 1.0, emit_every=30)
    f = np.asarray(ss2.fields[0])
    # with diffusion on, drained bins pull from neighbors: the field stays
    # smoother than the no-diffusion case
    spatial0, ss0 = make_spatial(d=0.0, sigma=0.0)
    ss0b, _ = spatial0.run(ss0, 30.0, 1.0, emit_every=30)
    f0 = np.asarray(ss0b.fields[0])
    assert f.std() < f0.std()


def test_run_is_jittable_and_emits_fields():
    spatial, ss = make_spatial(capacity=32, n_alive=32, shape=(16, 16))
    run = jax.jit(lambda s: spatial.run(s, 5.0, 1.0, emit_every=5))
    ss2, traj = run(ss)
    assert traj["fields"].shape == (1, 1, 16, 16)
    assert bool(jnp.all(jnp.isfinite(traj["fields"])))


def test_bad_wiring_raises():
    spatial, _ = make_spatial(capacity=8, n_alive=8, shape=(16, 16))
    with pytest.raises(ValueError):
        SpatialColony(
            spatial.colony,
            spatial.lattice,
            field_ports={"glucose": (("nope",), ("boundary", "exchange", "x"))},
        )
    with pytest.raises(ValueError):
        SpatialColony(
            spatial.colony,
            spatial.lattice,
            field_ports={"lactose": (("boundary", "external", "glucose"),
                                     ("boundary", "exchange", "glucose_exchange"))},
        )


def test_division_places_daughters_apart():
    """Under zero motility, daughters must still separate (the `offset`
    location divider) — round-1 co-located them forever."""
    from lens_tpu.core.state import DIVISION_SEPARATION_UM
    from lens_tpu.processes.growth import DivideTrigger, Growth

    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport({"vmax": 0.0}),
            "motility": BrownianMotility({"sigma": 0.0}),
            "growth": Growth({"rate": 0.5}),  # fast: divides in a few steps
            "divide_trigger": DivideTrigger(),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )
    colony = Colony(comp, 8, division_trigger=("global", "divide"))
    lattice = Lattice(
        molecules=["glucose"], shape=(16, 16), size=(16.0, 16.0),
        diffusion=0.0, initial=10.0, timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange")),
        },
        location_path=("boundary", "location"),
    )
    ss = spatial.initial_state(
        1, jax.random.PRNGKey(0),
        locations=np.broadcast_to(
            np.asarray([8.0, 8.0], np.float32), (8, 2)
        ).copy(),
    )
    for _ in range(4):
        ss = spatial.step(ss, 1.0)
        if int(jnp.sum(ss.colony.alive)) >= 2:
            break
    alive = np.asarray(ss.colony.alive)
    assert alive.sum() == 2, "expected exactly one division"
    locs = np.asarray(ss.colony.agents["boundary"]["location"])[alive]
    sep = np.linalg.norm(locs[0] - locs[1])
    np.testing.assert_allclose(sep, DIVISION_SEPARATION_UM, rtol=1e-5)


def test_exact_conservation_with_division_and_motility():
    """Regression (caught in verify): division used to zero the exchange
    accumulator before the field was debited, and the scatter hit the
    post-motility bin — both created mass. Field + internal pool must be
    exactly constant (float32 tolerance) through division epochs."""
    from lens_tpu.processes.growth import DivideTrigger, Growth

    comp = Compartment(
        processes={
            "transport": MichaelisMentenTransport(
                {"yield_": 1.0, "k_consume": 0.0, "vmax": 0.4}
            ),
            "motility": BrownianMotility({"sigma": 0.4, "domain": (16.0, 16.0)}),
            "growth": Growth({"rate": 0.03}),
            "divide": DivideTrigger({"threshold": 2.0}),
        },
        topology={
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
            "growth": {"global": ("global",)},
            "divide": {"global": ("global",)},
        },
    )
    colony = Colony(comp, capacity=64, division_trigger=("global", "divide"))
    lattice = Lattice(
        molecules=["glucose"], shape=(16, 16), size=(16.0, 16.0),
        diffusion=1.0, initial=10.0, timestep=1.0,
    )
    spatial = SpatialColony(
        colony, lattice,
        field_ports={"glucose": (("boundary", "external", "glucose"),
                                 ("boundary", "exchange", "glucose_exchange"))},
    )
    ss = spatial.initial_state(4, jax.random.PRNGKey(7))
    total0 = float(spatial.total_field_mass(ss)[0])
    ss2, _ = spatial.run(ss, 120.0, 1.0, emit_every=120)
    assert int(jnp.sum(ss2.colony.alive)) > 8  # divisions happened
    total1 = float(spatial.total_field_mass(ss2)[0])
    internal = float(
        jnp.sum(ss2.colony.agents["cell"]["glucose_internal"] * ss2.colony.alive)
    )
    np.testing.assert_allclose(total0, total1 + internal, rtol=2e-5)


class TestLysis:
    """Death with lysis conserves mass: a dying cell's pool returns to
    its lattice bin through the ordinary exchange path."""

    def _build(self, lysis):
        from lens_tpu.models import ecoli_lattice

        death = {"when": "above", "threshold": 0.5}
        if lysis is not None:
            death["lysis"] = lysis
        spatial, _ = ecoli_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "motility": {"sigma": 0.0},
                # yield_=1, k_consume=0: pool units == field mM, nothing
                # drains — cells eat until the bloat death fires
                "transport": {"yield_": 1.0, "k_consume": 0.0},
                "initial_glucose": 2.0,
                "death": death,
            }
        )
        return spatial

    def _run(self, spatial):
        ss = spatial.initial_state(16, jax.random.PRNGKey(0))
        ss, traj = jax.jit(lambda s: spatial.run(s, 40.0, 1.0))(ss)
        fields_t = np.asarray(traj["fields"]).sum(axis=(1, 2, 3))
        pools = np.asarray(traj["cell"]["glucose_internal"])
        alive = np.asarray(traj["alive"])
        live_pool_t = (pools * alive).sum(axis=1)
        return ss, fields_t, live_pool_t, alive

    def test_lysis_conserves_total_mass(self):
        spatial = self._build(lysis=1.0)
        ss, fields_t, live_pool_t, alive = self._run(spatial)
        assert alive[-1].sum() == 0          # everyone bloated and died
        total0 = fields_t[0] + live_pool_t[0]
        np.testing.assert_allclose(
            fields_t + live_pool_t, total0, rtol=1e-5
        )
        # after the last death everything is back in the field
        np.testing.assert_allclose(fields_t[-1], total0, rtol=1e-5)

    def test_without_lysis_the_pool_is_lost(self):
        spatial = self._build(lysis=None)
        ss, fields_t, live_pool_t, alive = self._run(spatial)
        assert alive[-1].sum() == 0
        # the hoarded pools died with their cells: the field ends LIGHTER
        assert fields_t[-1] < fields_t[0] - 0.4


# -- the fused coupling path (round 7: CouplingPlan one-pass gather/scatter) --


def _assert_trees_equal(a, b, msg=""):
    fa = sorted(
        jax.tree_util.tree_flatten_with_path(a)[0], key=lambda kv: str(kv[0])
    )
    fb = sorted(
        jax.tree_util.tree_flatten_with_path(b)[0], key=lambda kv: str(kv[0])
    )
    assert len(fa) == len(fb)
    for (pa, la), (pb, lb) in zip(fa, fb):
        np.testing.assert_array_equal(
            np.asarray(la), np.asarray(lb), err_msg=f"{msg} at {pa}"
        )


class TestFusedCoupling:
    """coupling="fused" (the CouplingPlan one-pass step) against
    coupling="reference" (the original three-message oracle)."""

    def test_knob_validation(self):
        spatial, _ = make_spatial(capacity=8, n_alive=8, shape=(16, 16))
        with pytest.raises(ValueError, match="coupling"):
            SpatialColony(
                spatial.colony, spatial.lattice,
                field_ports=spatial.field_ports, coupling="nope",
            )

    def test_fused_matches_reference_bitwise(self):
        """Full dynamics — motility, division, shared bins — must agree
        BITWISE: the fused path reorders no float op (same fold order in
        the scatters, same division expression in the gather)."""
        outs = {}
        for coupling in ("fused", "reference"):
            spatial, ss = make_spatial(
                capacity=64, n_alive=16, sigma=0.4, coupling=coupling
            )
            outs[coupling] = spatial.run(ss, 20.0, 1.0, emit_every=5)
        _assert_trees_equal(
            outs["fused"], outs["reference"], "fused vs reference"
        )

    def test_sense_only_port_parity(self):
        """A sense-only port (exchange=None) must read the RAW bin value
        on both paths — the fused path reads it off the single gather
        before the occupancy division, the reference issues a second
        gather — while consuming ports see the shared view. Co-located
        agents make the two views genuinely different."""
        from lens_tpu.processes.chemotaxis import MWCChemoreceptor

        def build(coupling):
            comp = Compartment(
                processes={
                    "receptor": MWCChemoreceptor(
                        {"molecule": "asp", "external_default": 0.1}
                    ),
                    "transport": MichaelisMentenTransport(
                        {"molecule": "glucose", "external_default": 1.0}
                    ),
                    "motility": BrownianMotility({"sigma": 0.3}),
                },
                topology={
                    "receptor": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                    },
                    "transport": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                        "exchange": ("boundary", "exchange"),
                    },
                    "motility": {"boundary": ("boundary",)},
                },
            )
            lattice = Lattice(
                molecules=["glucose", "asp"], shape=(16, 16),
                size=(16.0, 16.0), diffusion=1.0, initial=5.0, timestep=1.0,
            )
            spatial = SpatialColony(
                Colony(comp, 32), lattice,
                field_ports={
                    "glucose": (
                        ("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange"),
                    ),
                    "asp": (("boundary", "external", "asp"), None),
                },
                coupling=coupling,
            )
            # everyone in one bin: occupancy 32, so shared != raw by 32x
            locs = np.broadcast_to(
                np.asarray([8.0, 8.0], np.float32), (32, 2)
            ).copy()
            ss = spatial.initial_state(
                32, jax.random.PRNGKey(2), locations=locs
            )
            return spatial.run(ss, 10.0, 1.0, emit_every=10)

        _assert_trees_equal(build("fused"), build("reference"), "sense-only")
        # and the sense-only port really saw the RAW value at first
        # gather: raw 5.0, shared would be 5/32
        out, _ = build("fused")
        asp = np.asarray(out.colony.agents["boundary"]["external"]["asp"])
        assert asp.min() > 1.0  # raw-scale, not occupancy-divided

    def test_mass_conservation_shared_bins_fused(self):
        """share_bins=True under the fused path: field + live internal
        pools stay exactly constant through co-located uptake (the
        shared gather caps collective uptake at the bin content)."""
        locs = np.broadcast_to(
            np.asarray([3.0, 3.0], np.float32), (64, 2)
        ).copy()  # all 64 agents split ONE bin
        spatial, ss = make_spatial(
            sigma=0.0, d=0.0, coupling="fused", locations=locs
        )
        total0 = float(spatial.total_field_mass(ss)[0])
        ss2, _ = spatial.run(ss, 30.0, 1.0, emit_every=30)
        total1 = float(spatial.total_field_mass(ss2)[0])
        internal = float(
            jnp.sum(
                ss2.colony.agents["cell"]["glucose_internal"]
                * ss2.colony.alive
            )
        )
        np.testing.assert_allclose(total0, total1 + internal, rtol=1e-5)
        f = np.asarray(ss2.fields[0])
        assert f.min() >= 0.0

    def test_dead_rows_neither_gather_nor_scatter(self):
        """Mask hygiene on the fused path: dead rows keep their local
        port values (no gather overwrite) and contribute nothing to the
        fields (no scatter), even parked on live agents' bins."""
        locs = np.zeros((64, 2), np.float32)
        locs[:8] = [4.0, 4.0]   # live rows
        locs[8:] = [12.0, 12.0]  # dead rows parked on a distinct bin
        spatial, ss = make_spatial(
            n_alive=8, sigma=0.0, d=0.0, coupling="fused", locations=locs
        )
        # poison the dead rows' exchange accumulators: a masked scatter
        # must ignore them
        agents = ss.colony.agents
        ex = agents["boundary"]["exchange"]["glucose_exchange"]
        poisoned = jnp.where(ss.colony.alive, ex, 123.0)
        agents = {
            **agents,
            "boundary": {
                **agents["boundary"],
                "exchange": {
                    **agents["boundary"]["exchange"],
                    "glucose_exchange": poisoned,
                },
            },
        }
        ss = ss._replace(colony=ss.colony._replace(agents=agents))
        local0 = np.asarray(
            ss.colony.agents["boundary"]["external"]["glucose"]
        )
        ss2, _ = spatial.run(ss, 10.0, 1.0, emit_every=10)
        local1 = np.asarray(
            ss2.colony.agents["boundary"]["external"]["glucose"]
        )
        alive = np.asarray(ss2.colony.alive)
        # dead rows: the gather never overwrote their local view
        np.testing.assert_array_equal(local1[~alive], local0[~alive])
        f = np.asarray(ss2.fields[0])
        # the dead rows' bin (12, 12) never saw their poison (+123/step
        # would be unmissable); the live bin drained
        np.testing.assert_allclose(f[12, 12], 10.0, rtol=1e-6)
        assert f[4, 4] < 10.0 - 0.5

    def _run_both(self, spatial, ss):
        from lens_tpu.parallel.mesh import (
            make_mesh,
            mesh_shardings,
            spatial_pspecs,
        )
        from lens_tpu.parallel.runner import ShardedSpatialColony

        ref = spatial.run(ss, 8.0, 1.0, emit_every=4)
        mesh = make_mesh(n_agents=4, n_space=2)
        sharded = ShardedSpatialColony(spatial, mesh)
        ss_sharded = jax.device_put(
            ss, mesh_shardings(mesh, spatial_pspecs(ss))
        )
        return ref, sharded.run(ss_sharded, 8.0, 1.0, emit_every=4)

    def test_sharded_fused_bitwise_equals_unsharded_fused(self):
        """The shard_map fused path must reproduce the unsharded fused
        trajectory BITWISE for deterministic dynamics, in the two
        regimes where bitwise equality is structurally guaranteed:

        - shared bins under pure sensing — the occupancy collective is a
          psum of integer-valued counts (exact in any grouping), so the
          occupancy-divided gather must match to the bit;
        - single-occupant bins with real uptake — each bin's psum'd
          exchange delta gains only exact +0 terms from other shards.

        What is NOT claimed: bins where several agents' nonzero fluxes
        accumulate are grouped per shard before the psum, which is a
        different (valid) float association than the unsharded row fold
        — inherent to the collective, shared with the reference sharded
        path since round 2, and covered allclose in tests/test_parallel.
        Diffusion is pinned off: the halo stencil is its own
        (allclose-tested) numerics story; this test isolates the
        coupling's collectives."""
        # regime 1: all 64 agents split one bin, zero uptake
        locs = np.broadcast_to(
            np.asarray([5.0, 5.0], np.float32), (64, 2)
        ).copy()
        spatial, ss = make_spatial(
            sigma=0.0, d=0.0, coupling="fused", locations=locs
        )
        # post-construction config mutation: run()'s cache key
        # fingerprints process configs, so the next window re-traces
        spatial.colony.compartment.processes["transport"].config["vmax"] = 0.0
        ref, out = self._run_both(spatial, ss)
        _assert_trees_equal(out, ref, "sharded fused, shared-bin sensing")
        # occupancy sharing really happened: every agent saw 10/64
        shared = np.asarray(
            out[0].colony.agents["boundary"]["external"]["glucose"]
        )
        np.testing.assert_allclose(shared, 10.0 / 64.0, rtol=1e-6)

        # regime 2: distinct bins, real uptake
        locs = np.stack(
            [
                0.5 + (np.arange(64, dtype=np.float32) % 8) * 4.0,
                0.5 + (np.arange(64, dtype=np.float32) // 8) * 4.0,
            ],
            axis=1,
        )
        spatial, ss = make_spatial(
            sigma=0.0, d=0.0, coupling="fused", locations=locs
        )
        ref, out = self._run_both(spatial, ss)
        _assert_trees_equal(out, ref, "sharded fused, per-bin uptake")
        assert float(np.asarray(out[0].fields[0]).min()) < 10.0 - 0.5


def test_native_scatter_matches_xla_bitwise():
    """The native coupling kernel (when the toolchain built it) and the
    XLA scatter must be bit-for-bit interchangeable — same left fold in
    row order over duplicate indices."""
    from lens_tpu.ops import scatter as sc

    key = jax.random.PRNGKey(0)
    idx = jax.random.randint(key, (500,), 0, 64).astype(jnp.int32)
    upd = jax.random.uniform(key, (3, 500), dtype=jnp.float32)
    base = jax.random.uniform(jax.random.fold_in(key, 1), (3, 64))
    via_dispatch = np.asarray(sc.scatter_add_2d(base, idx, upd))
    via_xla = np.asarray(base.at[:, idx].add(upd))
    np.testing.assert_array_equal(via_dispatch, via_xla)
    if not sc.native_scatter_ready():
        pytest.skip("native scatter kernel unavailable (XLA fallback ran)")
