"""Updater/divider semantics — the subtlest part of the contract surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.core.state import UPDATERS, apply_update, divide_state


def test_accumulate_default():
    state = {"store": {"x": jnp.float32(1.0)}}
    out = apply_update(state, {"store": {"x": 2.0}})
    assert float(out["store"]["x"]) == 3.0
    # original untouched (pure)
    assert float(state["store"]["x"]) == 1.0


def test_set_and_null_updaters():
    state = {"a": jnp.float32(5.0), "b": jnp.float32(5.0)}
    out = apply_update(
        state, {"a": 1.0, "b": 1.0},
        updaters={("a",): "set", ("b",): "null"},
    )
    assert float(out["a"]) == 1.0
    assert float(out["b"]) == 5.0


def test_nonnegative_accumulate_clips():
    state = {"x": jnp.float32(1.0)}
    out = apply_update(state, {"x": -10.0}, updaters={("x",): "nonnegative_accumulate"})
    assert float(out["x"]) == 0.0


def test_unknown_path_raises():
    with pytest.raises(KeyError):
        apply_update({"a": jnp.float32(0.0)}, {"missing": 1.0})


def test_apply_update_under_jit():
    updaters = {("x",): "accumulate", ("y",): "set"}

    @jax.jit
    def step(state):
        return apply_update(state, {"x": 1.0, "y": 9.0}, updaters)

    out = step({"x": jnp.float32(0.0), "y": jnp.float32(0.0)})
    assert float(out["x"]) == 1.0
    assert float(out["y"]) == 9.0


def test_divide_split_copy_zero():
    state = {
        "mass": jnp.float32(2.0),
        "conc": jnp.float32(7.0),
        "clock": jnp.float32(3.0),
    }
    dividers = {("mass",): "split", ("conc",): "copy", ("clock",): "zero"}
    a, b = divide_state(state, jax.random.PRNGKey(0), dividers)
    assert float(a["mass"]) == 1.0 and float(b["mass"]) == 1.0
    assert float(a["conc"]) == 7.0 and float(b["conc"]) == 7.0
    assert float(a["clock"]) == 0.0 and float(b["clock"]) == 0.0


def test_divide_binomial_conserves_counts():
    n = jnp.float32(10000.0)
    a, b = divide_state(
        {"counts": n}, jax.random.PRNGKey(1), {("counts",): "binomial"}
    )
    total = float(a["counts"]) + float(b["counts"])
    assert total == 10000.0
    # roughly half each (4 sigma ~ 200)
    assert abs(float(a["counts"]) - 5000.0) < 250.0


def test_divide_binomial_small_counts_exact():
    """The divider must be a true binomial, not a normal approximation:
    for n=1 the daughters split 1/0 or 0/1 with p=1/2 each — a clipped
    normal piles excess mass on the boundaries instead."""
    trials = 400
    keys = jax.random.split(jax.random.PRNGKey(0), trials)
    a, b = jax.vmap(
        lambda k: divide_state(
            {"n": jnp.float32(1.0)}, k, {("n",): "binomial"}
        )
    )(keys)
    av = np.asarray(a["n"])
    bv = np.asarray(b["n"])
    assert set(zip(av.tolist(), bv.tolist())) <= {(1.0, 0.0), (0.0, 1.0)}
    ones = int(av.sum())
    # p=0.5 within 5 sigma (sigma=10 for 400 trials)
    assert abs(ones - trials / 2) < 50, ones


def test_binomial_half_distribution():
    """The hand-rolled VMA-safe sampler (core.state._binomial_half) is a
    true Binomial(n, 1/2): check the full pmf at n=6 against exact
    probabilities, and mean/variance in the normal-approximation regime."""
    import numpy as np
    from scipy import stats

    from lens_tpu.core.state import _binomial_half

    keys = jax.random.split(jax.random.PRNGKey(42), 4000)
    draws = jax.vmap(
        lambda k: _binomial_half(k, jnp.float32(6.0))
    )(keys)
    counts = np.bincount(np.asarray(draws, np.int64), minlength=7)
    expected = stats.binom.pmf(np.arange(7), 6, 0.5) * 4000
    chi2 = float(((counts - expected) ** 2 / expected).sum())
    # 6 dof; P(chi2 > 22.5) ~ 0.1%
    assert chi2 < 22.5, (chi2, counts)

    big = jax.vmap(
        lambda k: _binomial_half(k, jnp.float32(10000.0))
    )(keys)
    big = np.asarray(big)
    assert abs(big.mean() - 5000.0) < 4 * 50.0 / np.sqrt(4000)
    assert abs(big.std() - 50.0) < 5.0
    np.testing.assert_allclose(big, np.round(big))  # integral


def test_divide_offset_separates_locations():
    from lens_tpu.core.state import DIVISION_SEPARATION_UM

    loc = jnp.asarray([10.0, 20.0], jnp.float32)
    a, b = divide_state(
        {"loc": loc}, jax.random.PRNGKey(7), {("loc",): "offset"}
    )
    import numpy as np

    sep = np.linalg.norm(np.asarray(a["loc"]) - np.asarray(b["loc"]))
    np.testing.assert_allclose(sep, DIVISION_SEPARATION_UM, rtol=1e-5)
    # midpoint is the parent location
    np.testing.assert_allclose(
        (np.asarray(a["loc"]) + np.asarray(b["loc"])) / 2,
        np.asarray(loc),
        rtol=1e-5,
    )


def test_updater_registry_complete():
    for name in ("accumulate", "nonnegative_accumulate", "set", "null"):
        assert name in UPDATERS
