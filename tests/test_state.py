"""Updater/divider semantics — the subtlest part of the contract surface."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.core.state import UPDATERS, apply_update, divide_state


def test_accumulate_default():
    state = {"store": {"x": jnp.float32(1.0)}}
    out = apply_update(state, {"store": {"x": 2.0}})
    assert float(out["store"]["x"]) == 3.0
    # original untouched (pure)
    assert float(state["store"]["x"]) == 1.0


def test_set_and_null_updaters():
    state = {"a": jnp.float32(5.0), "b": jnp.float32(5.0)}
    out = apply_update(
        state, {"a": 1.0, "b": 1.0},
        updaters={("a",): "set", ("b",): "null"},
    )
    assert float(out["a"]) == 1.0
    assert float(out["b"]) == 5.0


def test_nonnegative_accumulate_clips():
    state = {"x": jnp.float32(1.0)}
    out = apply_update(state, {"x": -10.0}, updaters={("x",): "nonnegative_accumulate"})
    assert float(out["x"]) == 0.0


def test_unknown_path_raises():
    with pytest.raises(KeyError):
        apply_update({"a": jnp.float32(0.0)}, {"missing": 1.0})


def test_apply_update_under_jit():
    updaters = {("x",): "accumulate", ("y",): "set"}

    @jax.jit
    def step(state):
        return apply_update(state, {"x": 1.0, "y": 9.0}, updaters)

    out = step({"x": jnp.float32(0.0), "y": jnp.float32(0.0)})
    assert float(out["x"]) == 1.0
    assert float(out["y"]) == 9.0


def test_divide_split_copy_zero():
    state = {
        "mass": jnp.float32(2.0),
        "conc": jnp.float32(7.0),
        "clock": jnp.float32(3.0),
    }
    dividers = {("mass",): "split", ("conc",): "copy", ("clock",): "zero"}
    a, b = divide_state(state, jax.random.PRNGKey(0), dividers)
    assert float(a["mass"]) == 1.0 and float(b["mass"]) == 1.0
    assert float(a["conc"]) == 7.0 and float(b["conc"]) == 7.0
    assert float(a["clock"]) == 0.0 and float(b["clock"]) == 0.0


def test_divide_binomial_conserves_counts():
    n = jnp.float32(10000.0)
    a, b = divide_state(
        {"counts": n}, jax.random.PRNGKey(1), {("counts",): "binomial"}
    )
    total = float(a["counts"]) + float(b["counts"])
    assert total == 10000.0
    # roughly half each (4 sigma ~ 200)
    assert abs(float(a["counts"]) - 5000.0) < 250.0


def test_updater_registry_complete():
    for name in ("accumulate", "nonnegative_accumulate", "set", "null"):
        assert name in UPDATERS
