"""The sweep subsystem: spaces, objectives, ledger, driver backends.

The load-bearing contracts, in this repo's bitwise culture:

- same spec + sweep seed => the SAME trial list (params and per-trial
  PRNG seeds), on any host, resumed or not;
- a server-backend trial's trajectory/objective is BITWISE what a solo
  serve request with the same seed/overrides produces (inherited from
  serve's co-batching determinism);
- a killed sweep resumes from the ledger, re-runs ONLY unfinished
  trials, and its final table is bitwise identical to an uninterrupted
  run's;
- successive halving finds the same top trial as exhaustive
  full-horizon evaluation on a monotone objective, with survivors
  EXTENDED through serve's hold_state/resubmit (never rerun).
"""

import json
import os

import numpy as np
import pytest

from lens_tpu.sweep import (
    GridSpace,
    LatinHypercubeSpace,
    MemoryLedger,
    Objective,
    RandomSpace,
    TrialLedger,
    run_sweep,
    rung_steps,
    space_from_spec,
    spec_fingerprint,
    stack_overrides,
    trial_seed,
)
from lens_tpu.sweep.ledger import TRIAL_DONE

#: Dose grid with a strictly monotone final-glucose-uptake response
#: (verified by TestServerBackend.test_race_objectives_monotone).
DOSES = [0.2, 0.5, 1.0, 2.0, 5.0]


def _spec(**kw):
    spec = {
        "composite": "minimal_ode",
        "space": {
            "kind": "grid",
            "params": {
                "environment/glucose_external": {"grid": DOSES},
            },
        },
        "horizon": 16.0,
        "objective": {
            "path": "cell/glucose_internal",
            "reduction": "final_live_sum",
            "mode": "max",
        },
        "capacity": 4,
        "backend": {"kind": "server", "lanes": 2, "window": 4},
    }
    spec.update(kw)
    return spec


class _Kill(Exception):
    """Stand-in for a mid-sweep crash in the resume tests."""


def _killer_after(n):
    count = [0]

    def on_trial(index, event):
        count[0] += 1
        if count[0] >= n:
            raise _Kill

    return on_trial


class TestSpaces:
    def test_grid_enumerates_cartesian_product_in_order(self):
        space = GridSpace({
            "a/x": {"grid": [1.0, 2.0]},
            "b": {"grid": [10.0, 20.0, 30.0]},
        })
        assert space.n_trials == 6
        trials = space.trials(0)
        assert [t.index for t in trials] == list(range(6))
        # first param slowest, row-major
        assert [t.params["a/x"] for t in trials] == [1, 1, 1, 2, 2, 2]
        assert [t.params["b"] for t in trials] == [10, 20, 30] * 2
        # override trees nest on the path separator
        assert trials[0].overrides() == {"a": {"x": 1.0}, "b": 10.0}

    def test_trials_are_deterministic_functions_of_seed(self):
        spec = {"kind": "random", "n_trials": 6, "params": {
            "p": {"low": 0.1, "high": 10.0, "scale": "log"},
            "q": {"low": -1.0, "high": 1.0},
        }}
        a = space_from_spec(spec).trials(7)
        b = space_from_spec(spec).trials(7)
        assert a == b
        c = space_from_spec(spec).trials(8)
        assert a != c
        # per-trial sim seeds come from (sweep_seed, index) alone
        assert [t.seed for t in a] == [trial_seed(7, i) for i in range(6)]

    def test_random_trial_i_stable_under_widening(self):
        """Growing n_trials must EXTEND the trial list, not reshuffle it
        (a widened sweep keeps its resume ledger valid)."""
        spec = {"kind": "random", "params": {
            "p": {"low": 0.1, "high": 10.0, "scale": "log"},
        }}
        small = space_from_spec({**spec, "n_trials": 4}).trials(3)
        big = space_from_spec({**spec, "n_trials": 16}).trials(3)
        assert big[:4] == small
        for t in big:
            assert 0.1 <= t.params["p"] <= 10.0

    def test_lhs_stratifies_every_dimension(self):
        n = 8
        space = LatinHypercubeSpace(
            {"p": {"low": 2.0, "high": 10.0},
             "q": {"low": 1.0, "high": 100.0, "scale": "log"}},
            n_trials=n,
        )
        trials = space.trials(5)
        assert space.trials(5) == trials  # whole-design determinism
        # invert each scale back to u in [0,1): exactly one sample per
        # stratum [k/n, (k+1)/n) per dimension
        u_p = [(t.params["p"] - 2.0) / 8.0 for t in trials]
        u_q = [
            np.log(t.params["q"] / 1.0) / np.log(100.0) for t in trials
        ]
        for u in (u_p, u_q):
            assert sorted(int(x * n) for x in u) == list(range(n))

    def test_stack_overrides_shapes(self):
        trials = GridSpace(
            {"a/x": {"grid": [1.0, 2.0, 3.0]}}
        ).trials(0)
        tree = stack_overrides(trials)
        np.testing.assert_array_equal(tree["a"]["x"], [1.0, 2.0, 3.0])

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="params"):
            space_from_spec({"kind": "grid"})
        with pytest.raises(ValueError, match="n_trials"):
            space_from_spec({"kind": "random", "params": {
                "p": {"low": 0, "high": 1}}})
        with pytest.raises(ValueError, match="unknown space kind"):
            space_from_spec({"kind": "bayes", "params": {}, "n_trials": 1})
        with pytest.raises(ValueError, match="positive bounds"):
            RandomSpace(
                {"p": {"low": -1.0, "high": 1.0, "scale": "log"}}, 2
            ).trials(0)
        with pytest.raises(ValueError, match="must exceed"):
            RandomSpace({"p": {"low": 2.0, "high": 1.0}}, 2)
        with pytest.raises(ValueError, match="non-empty"):
            GridSpace({"p": {"grid": []}})


class TestObjective:
    TS = {
        "alive": np.array([[1, 1, 0], [1, 0, 0]], bool),
        "x": np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]]),
        "__times__": np.array([1.0, 2.0]),
    }

    @pytest.mark.parametrize("reduction,expected", [
        ("final_live_sum", 4.0),
        ("final_live_mean", 4.0),
        ("final_sum", 15.0),
        ("final_mean", 5.0),
        ("mean", 3.5),
        ("max", 6.0),
        ("min", 1.0),
        ("final_alive_count", 1.0),
    ])
    def test_reductions(self, reduction, expected):
        assert Objective("x", reduction).value(self.TS) == expected

    def test_truncation_scores_a_prefix(self):
        """up_to_time is how halving scores a rung from a partial
        stream: only emits at time <= the rung horizon count."""
        obj = Objective("x", "final_live_sum")
        assert obj.value(self.TS, up_to_time=1.0) == 3.0  # 1 + 2
        assert obj.value(self.TS, up_to_time=5.0) == 4.0
        with pytest.raises(ValueError, match="no emitted rows"):
            obj.value(self.TS, up_to_time=0.5)

    def test_emit_paths_cover_exactly_what_the_reduction_reads(self):
        assert Objective("a/b", "final_live_sum").emit_paths() == [
            "a/b", "alive",
        ]
        assert Objective("a/b", "final_sum").emit_paths() == ["a/b"]
        assert Objective(
            "alive", "final_alive_count"
        ).emit_paths() == ["alive"]

    def test_rank_modes_and_deterministic_ties(self):
        values = {0: 2.0, 1: 5.0, 2: 5.0, 3: 1.0}
        assert Objective("x", mode="max").rank(values) == [1, 2, 0, 3]
        assert Objective("x", mode="min").rank(values) == [3, 0, 1, 2]

    def test_validation(self):
        with pytest.raises(ValueError, match="unknown reduction"):
            Objective("x", "median")
        with pytest.raises(ValueError, match="unknown mode"):
            Objective("x", mode="argmax")
        with pytest.raises(ValueError, match="'path'"):
            Objective.from_spec({"reduction": "mean"})


class TestLedger:
    def test_replay_roundtrip(self, tmp_path):
        p = str(tmp_path / "sweep.ledger")
        with TrialLedger(p) as led:
            led.begin("fp1", {"n_trials": 3})
            led.append({"event": "trial_rung", "trial": 0, "rung": 0,
                        "objective": 1.5})
            led.append({"event": "trial_stopped", "trial": 1, "rung": 0,
                        "objective": 0.5})
            led.append({"event": TRIAL_DONE, "trial": 0,
                        "objective": 2.5, "status": "done"})
        replayed = TrialLedger(p)
        assert replayed.meta["fingerprint"] == "fp1"
        assert replayed.rungs == {0: {0: 1.5}}
        assert set(replayed.stopped) == {1}
        assert replayed.done[0]["objective"] == 2.5
        assert replayed.terminal(0) and replayed.terminal(1)
        assert not replayed.terminal(2)
        replayed.close()

    def test_torn_tail_frame_is_dropped_and_truncated(self, tmp_path):
        p = str(tmp_path / "sweep.ledger")
        with TrialLedger(p) as led:
            led.begin("fp1", {})
            led.append({"event": TRIAL_DONE, "trial": 0,
                        "objective": 1.0, "status": "done"})
        size = os.path.getsize(p)
        with open(p, "ab") as f:  # a kill mid-append: torn tail frame
            from lens_tpu.emit.log import frame

            f.write(frame(b'{"event": "trial_done", "trial": 1}')[:-3])
        replayed = TrialLedger(p)
        assert set(replayed.done) == {0}  # tail dropped, prefix intact
        # reopening TRUNCATED the torn bytes, so appends from the
        # resumed run land on a clean frame boundary — a SECOND replay
        # must read everything (a raw append-after-torn-tail would CRC-
        # poison every event the resume wrote)
        assert os.path.getsize(p) == size
        replayed.append({"event": TRIAL_DONE, "trial": 2,
                         "objective": 2.0, "status": "done"})
        replayed.close()
        again = TrialLedger(p)
        assert set(again.done) == {0, 2}
        again.close()

    def test_fingerprint_guard_refuses_a_changed_spec(self, tmp_path):
        p = str(tmp_path / "sweep.ledger")
        with TrialLedger(p) as led:
            led.begin("fp1", {})
        led = TrialLedger(p)
        led.begin("fp1", {})  # same sweep: fine
        with pytest.raises(ValueError, match="fingerprint"):
            led.begin("fp2", {})
        led.close()
        assert spec_fingerprint({"a": 1}) != spec_fingerprint({"a": 2})

    def test_memory_ledger_same_interface(self):
        led = MemoryLedger()
        led.begin("fp", {})
        led.append({"event": TRIAL_DONE, "trial": 4, "objective": 1.0,
                    "status": "done"})
        assert led.terminal(4) and not led.terminal(0)
        led.close()


class TestRungSteps:
    def test_geometric_snapped_capped(self):
        assert rung_steps(4, 2, 16, 1) == [4, 8, 16]
        # snapping UP to the emit grid, dedup, final always max_steps
        assert rung_steps(3, 2, 24, 4) == [4, 8, 12, 24]
        assert rung_steps(20, 3, 16, 1) == [16]

    def test_validation(self):
        with pytest.raises(ValueError, match="eta"):
            rung_steps(4, 1, 16, 1)
        with pytest.raises(ValueError, match="min_horizon"):
            rung_steps(0, 2, 16, 1)


class TestServerBackend:
    def test_race_objectives_monotone_and_best(self, tmp_path):
        res = run_sweep(_spec(), out_dir=str(tmp_path / "s"))
        assert [r["status"] for r in res.table] == ["done"] * len(DOSES)
        objs = [r["objective"] for r in res.table]
        assert all(np.diff(objs) > 0), objs  # monotone in dose
        assert res.best["trial"] == len(DOSES) - 1
        assert res.metrics["server"]["counters"]["retired"] >= len(DOSES)
        # the table landed on disk, atomically
        table_path = str(tmp_path / "s" / "sweep_result.json")
        assert res.path == table_path
        with open(table_path) as f:
            assert len(json.load(f)["table"]) == len(DOSES)
        assert not os.path.exists(table_path + ".tmp")

    def test_trial_bitwise_equals_solo_serve_request(self):
        """THE determinism contract: a sweep trial's trajectory is the
        solo request's bits — scheduling (and the sweep around it)
        changed nothing."""
        from lens_tpu.serve import ScenarioRequest, SimServer

        spec = _spec()
        server = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4
        )
        res = run_sweep(spec, server=server)
        target = space_from_spec(spec["space"]).trials(0)[2]
        rid = server.submit(ScenarioRequest(
            composite="minimal_ode",
            seed=target.seed,
            horizon=spec["horizon"],
            overrides=target.overrides(),
            emit={"paths": ["cell/glucose_internal", "alive"]},
        ))
        server.run_until_idle(max_ticks=200)
        solo = server.result(rid)
        swept = res.timeseries[2]
        np.testing.assert_array_equal(
            solo["__times__"], swept["__times__"]
        )
        np.testing.assert_array_equal(
            np.asarray(solo["cell"]["glucose_internal"]),
            np.asarray(swept["cell"]["glucose_internal"]),
        )
        server.close()

    def test_emit_spec_streams_only_objective_paths(self):
        res = run_sweep(_spec())
        ts = res.timeseries[0]
        leaves = {k for k in ts if k != "__times__"}
        assert leaves == {"cell", "alive"}
        assert set(ts["cell"]) == {"glucose_internal"}

    def test_kill_and_resume_reruns_only_unfinished(self, tmp_path):
        full = run_sweep(_spec(), out_dir=str(tmp_path / "full"))
        kill_dir = str(tmp_path / "killed")
        with pytest.raises(_Kill):
            run_sweep(_spec(), out_dir=kill_dir,
                      on_trial=_killer_after(2))
        resumed = run_sweep(_spec(), out_dir=kill_dir, resume=True)
        # only the 3 unfinished trials were re-simulated
        assert resumed.metrics["server"]["counters"]["submitted"] == 3
        for a, b in zip(full.table, resumed.table):
            assert a["status"] == b["status"]
            assert a["objective"] == b["objective"]  # bitwise

    def test_resume_guards(self, tmp_path):
        out = str(tmp_path / "s")
        run_sweep(_spec(), out_dir=out)
        with pytest.raises(ValueError, match="resume=True"):
            run_sweep(_spec(), out_dir=out)  # refuse silent reuse
        with pytest.raises(ValueError, match="fingerprint"):
            run_sweep(_spec(seed=1), out_dir=out, resume=True)
        # resume of a COMPLETE sweep re-runs nothing
        res = run_sweep(_spec(), out_dir=out, resume=True)
        assert res.metrics["server"]["counters"]["submitted"] == 0

    def test_fingerprint_is_param_order_sensitive(self, tmp_path):
        """Trial enumeration follows params insertion order (grid
        product order, per-param draw order), so a spec with the SAME
        params merely re-keyed in another order is a different sweep —
        sort_keys canonicalization must not launder it through the
        resume guard."""
        params = {
            "environment/glucose_external": {"grid": [0.5, 1.0]},
            "cell/glucose_internal": {"grid": [0.0, 0.1]},
        }
        reordered = dict(reversed(list(params.items())))
        spec_a = _spec(space={"kind": "grid", "params": params})
        spec_b = _spec(space={"kind": "grid", "params": reordered})
        out = str(tmp_path / "s")
        run_sweep(spec_a, out_dir=out)
        with pytest.raises(ValueError, match="fingerprint"):
            run_sweep(spec_b, out_dir=out, resume=True)

    def test_spec_validation(self):
        with pytest.raises(ValueError, match="unknown sweep spec keys"):
            run_sweep(_spec(horizons=3.0))
        with pytest.raises(ValueError, match="missing"):
            run_sweep({"composite": "minimal_ode"})
        with pytest.raises(ValueError, match="unknown backend kind"):
            run_sweep(_spec(backend={"kind": "slurm"}))


class TestEnsembleBackend:
    def test_matches_server_backend_ranking(self):
        server = run_sweep(_spec())
        ens = run_sweep(_spec(backend={"kind": "ensemble"}))
        s_obj = [r["objective"] for r in server.table]
        e_obj = [r["objective"] for r in ens.table]
        # same physics modulo vmap-vs-solo op fusion (last-ulp); the
        # ranking — what a search consumes — is identical
        np.testing.assert_allclose(e_obj, s_obj, rtol=1e-5)
        assert ens.best["trial"] == server.best["trial"]
        assert ens.metrics["backend"] == "ensemble"

    def test_chunked_run_is_reproducible_and_chunk_invariant(self):
        a = run_sweep(_spec(backend={"kind": "ensemble", "batch": 2}))
        b = run_sweep(_spec(backend={"kind": "ensemble", "batch": 2}))
        objs = lambda r: [row["objective"] for row in r.table]
        assert objs(a) == objs(b)  # bitwise run-to-run
        c = run_sweep(_spec(backend={"kind": "ensemble", "batch": 5}))
        np.testing.assert_allclose(objs(c), objs(a), rtol=1e-5)

    def test_kill_and_resume_mid_chunk_bitwise(self, tmp_path):
        spec = _spec(backend={"kind": "ensemble", "batch": 2})
        full = run_sweep(spec, out_dir=str(tmp_path / "full"))
        kill_dir = str(tmp_path / "killed")
        with pytest.raises(_Kill):
            run_sweep(spec, out_dir=kill_dir, on_trial=_killer_after(3))
        resumed = run_sweep(spec, out_dir=kill_dir, resume=True)
        # the partially-recorded chunk re-ran WHOLE (same composition),
        # so every objective is bitwise the uninterrupted run's
        assert [r["objective"] for r in resumed.table] == [
            r["objective"] for r in full.table
        ]
        # fully-done chunks were skipped: only chunks 2 and 3 re-ran
        assert resumed.metrics["chunks_run"] == 2

    def test_asha_is_server_only(self):
        with pytest.raises(ValueError, match="no early stopping"):
            run_sweep(_spec(
                backend={"kind": "ensemble"},
                asha={"min_horizon": 4.0},
            ))


class TestSuccessiveHalving:
    ASHA = {"min_horizon": 4.0, "eta": 2}

    def test_finds_exhaustive_top_trial_on_monotone_objective(self):
        exhaustive = run_sweep(_spec())
        halved = run_sweep(_spec(asha=self.ASHA))
        assert halved.best["trial"] == exhaustive.best["trial"]
        assert (
            halved.best["objective"] == exhaustive.best["objective"]
        )  # the winner ran the same full horizon, bitwise

    def test_halving_schedule_and_extension_accounting(self):
        res = run_sweep(_spec(asha=self.ASHA))
        by_status = {}
        for r in res.table:
            by_status.setdefault(r["status"], []).append(r)
        # rungs [4, 8, 16]: 5 -> keep 2 (3 stopped at rung 0) -> keep 1
        # (1 stopped at rung 1) -> 1 done
        assert len(by_status["done"]) == 1
        assert len(by_status["stopped"]) == 4
        assert sorted(
            r["rung"] for r in by_status["stopped"]
        ) == [0, 0, 0, 1]
        # stopped trials carry their rung-horizon objective
        assert all(
            r["objective"] is not None for r in by_status["stopped"]
        )
        counters = res.metrics["server"]["counters"]
        # survivors EXTENDED via hold_state/resubmit: 2 promotions at
        # rung 0 + 1 at rung 1; nothing was ever re-run from scratch
        assert counters["resubmitted"] == 3
        assert counters["submitted"] == len(DOSES)

    def test_kill_and_resume_reproduces_decisions(self, tmp_path):
        spec = _spec(asha=self.ASHA)
        full = run_sweep(spec, out_dir=str(tmp_path / "full"))
        kill_dir = str(tmp_path / "killed")
        killed = False
        try:
            # terminal events are sparse under halving (one DONE here),
            # so kill on the FIRST one to leave rung state mid-flight
            run_sweep(spec, out_dir=kill_dir, on_trial=_killer_after(1))
        except _Kill:
            killed = True
        assert killed
        resumed = run_sweep(spec, out_dir=kill_dir, resume=True)
        for a, b in zip(full.table, resumed.table):
            assert a["status"] == b["status"]
            assert a.get("rung") == b.get("rung")
            assert a["objective"] == b["objective"]


def _replay_filtered(src_dir, dst_dir, drop):
    """Reconstruct a partial ledger — a sweep killed at a precise event
    boundary — by replaying a finished sweep's events minus ``drop``."""
    from lens_tpu.sweep.ledger import LEDGER_NAME

    src = TrialLedger(os.path.join(src_dir, "sweep.ledger"))
    events = list(src.events)
    src.close()
    os.makedirs(dst_dir, exist_ok=True)
    dst = TrialLedger(os.path.join(dst_dir, LEDGER_NAME))
    for ev in events:
        if not drop(ev):
            dst.append(ev)
    dst.close()


class TestHalvingResumeEdges:
    """Kills landing BETWEEN ledger appends of one halving decision:
    resume must re-derive the original run's decisions exactly."""

    ASHA = {"min_horizon": 4.0, "eta": 2}

    def test_kill_between_final_rung_and_done_finishes_from_ledger(
        self, tmp_path
    ):
        """The final rung's TRIAL_RUNG is fsynced before TRIAL_DONE; a
        kill in that window leaves a fully-simulated winner with no
        terminal event. Its final-rung objective IS the full-horizon
        objective, so resume finishes it from the ledger — nothing
        re-simulates."""
        spec = _spec(asha=self.ASHA)
        full_dir = str(tmp_path / "full")
        full = run_sweep(spec, out_dir=full_dir)
        winner = full.best["trial"]
        kill_dir = str(tmp_path / "killed")
        _replay_filtered(
            full_dir, kill_dir,
            drop=lambda ev: ev["event"] == TRIAL_DONE
            and ev["trial"] == winner,
        )
        resumed = run_sweep(spec, out_dir=kill_dir, resume=True)
        assert resumed.metrics["server"]["counters"]["submitted"] == 0
        for a, b in zip(full.table, resumed.table):
            assert (a["status"], a["objective"]) == (
                b["status"], b["objective"],
            )

    def test_kill_mid_cut_re_derives_the_original_cohort(self, tmp_path):
        """A kill after 2 of rung 0's 3 TRIAL_STOPPED appends: the
        resumed cut must rank the ORIGINAL 5-trial cohort (keep 2),
        not the 3 not-yet-stopped trials (which would keep 1 and stop
        a trial the original run promoted)."""
        spec = _spec(asha=self.ASHA)
        full_dir = str(tmp_path / "full")
        full = run_sweep(spec, out_dir=full_dir)
        kill_dir = str(tmp_path / "killed")
        stops = [0]

        def drop(ev):
            kind = ev["event"]
            if kind == "sweep_begin":
                return False
            if kind == "trial_rung" and ev["rung"] == 0:
                return False
            if kind == "trial_stopped" and ev["rung"] == 0:
                stops[0] += 1
                return stops[0] > 2  # the third stop never landed
            return True  # nothing past rung 0 landed either

        _replay_filtered(full_dir, kill_dir, drop)
        resumed = run_sweep(spec, out_dir=kill_dir, resume=True)
        for a, b in zip(full.table, resumed.table):
            assert a["status"] == b["status"]
            assert a.get("rung") == b.get("rung")
            assert a["objective"] == b["objective"]

    def test_failed_trial_replayed_from_ledger_is_never_ranked(
        self, tmp_path
    ):
        """A FAILED trial carries objective None; on resume it must be
        excluded from halving cohorts instead of crashing the ranking."""
        spec = _spec(asha=self.ASHA)
        full_dir = str(tmp_path / "full")
        run_sweep(spec, out_dir=full_dir)
        kill_dir = str(tmp_path / "killed")
        _replay_filtered(
            full_dir, kill_dir, drop=lambda ev: ev.get("trial") == 0
        )
        led = TrialLedger(os.path.join(kill_dir, "sweep.ledger"))
        led.append({
            "event": TRIAL_DONE, "trial": 0, "seed": 0,
            "objective": None, "status": "failed", "steps": 0,
        })
        led.close()
        resumed = run_sweep(spec, out_dir=kill_dir, resume=True)
        assert resumed.table[0]["status"] == "failed"
        assert resumed.best is not None
        assert resumed.best["trial"] == len(DOSES) - 1


class TestSaveAndLoadMany:
    def test_save_trajectories_roundtrip_via_load_many(self, tmp_path):
        from lens_tpu.analysis import load_many

        out = str(tmp_path / "s")
        res = run_sweep(_spec(save_trajectories=True), out_dir=out)
        trials_dir = os.path.join(out, "trials")
        loaded = load_many(trials_dir)
        assert sorted(loaded) == [
            f"trial_{i:05d}" for i in range(len(DOSES))
        ]
        for i in range(len(DOSES)):
            got = loaded[f"trial_{i:05d}"]
            np.testing.assert_array_equal(
                got["cell"]["glucose_internal"],
                res.timeseries[i]["cell"]["glucose_internal"],
            )
            np.testing.assert_array_equal(
                got["__time__"], res.timeseries[i]["__times__"]
            )

    def test_load_many_tolerates_ragged_fleets(self, tmp_path):
        from lens_tpu.analysis import load_many

        out = str(tmp_path / "s")
        run_sweep(_spec(save_trajectories=True), out_dir=out)
        trials_dir = os.path.join(out, "trials")
        # torn tail on one log (killed writer): its only segment record
        # is lost, so the log is skipped — with a warning, not a crash
        torn = os.path.join(trials_dir, "trial_00001.lens")
        size = os.path.getsize(torn)
        with open(torn, "r+b") as f:
            f.truncate(size - 7)
        # an empty log (trial admitted, killed pre-emit): skipped
        open(os.path.join(trials_dir, "trial_00099.lens"), "wb").close()
        # corrupt magic mid-file: warned, skipped
        bad = os.path.join(trials_dir, "trial_00098.lens")
        with open(bad, "wb") as f:
            f.write(b"\x00" * 64)
        with pytest.warns(UserWarning):
            loaded = load_many(trials_dir)
        assert "trial_00001" not in loaded
        assert "trial_00099" not in loaded
        assert "trial_00098" not in loaded
        assert len(loaded) == len(DOSES) - 1
        assert "alive" in loaded["trial_00002"]

    def test_load_many_requires_a_directory(self, tmp_path):
        from lens_tpu.analysis import load_many

        with pytest.raises(NotADirectoryError):
            load_many(str(tmp_path / "nope"))


class TestSharedWarmup:
    """Round 11: a spec-level ``warmup`` block makes every trial (and
    every ASHA first rung) fork ONE warmed snapshot through serve's
    prefix cache instead of re-simulating the warmup per trial."""

    def _warm_spec(self, **kw):
        spec = _spec(warmup={"horizon": 8.0})
        spec.update(kw)
        return spec

    def test_warmup_sweep_runs_one_prefix_for_all_trials(self):
        res = run_sweep(self._warm_spec())
        assert [r["status"] for r in res.table] == ["done"] * len(DOSES)
        c = res.metrics["server"]["counters"]
        assert c["prefix_misses"] == 1          # the warmup ran ONCE
        assert c["prefix_coalesced"] + c["prefix_hits"] == len(DOSES) - 1
        assert c["prefix_forks"] == len(DOSES)  # every trial forked it
        assert res.metrics["server"]["retraces"] == 0
        # the divergent dose still lands per trial: monotone response
        objs = [r["objective"] for r in res.table]
        assert all(np.diff(objs) > 0), objs
        # emitted trajectories cover ONLY the suffix
        times = np.asarray(res.timeseries[0]["__times__"])
        assert times[0] > 8.0 and times[-1] == 16.0

    def test_warmup_trial_bitwise_equals_solo_fork(self):
        """A warmed trial is bitwise the solo prefixed request — the
        serve fork contract carried through the sweep layer."""
        from lens_tpu.serve import ScenarioRequest, SimServer

        spec = self._warm_spec()
        server = SimServer.single_bucket(
            "minimal_ode", capacity=4, lanes=2, window=4
        )
        res = run_sweep(spec, server=server)
        target = space_from_spec(spec["space"]).trials(0)[2]
        rid = server.submit(ScenarioRequest(
            composite="minimal_ode",
            seed=0,  # the warmup seed (spec seed), not the trial's
            horizon=16.0,
            overrides=target.overrides(),
            prefix={"horizon": 8.0},
            emit={"paths": ["cell/glucose_internal", "alive"]},
        ))
        server.run_until_idle(max_ticks=200)
        solo = server.result(rid)
        swept = res.timeseries[2]
        np.testing.assert_array_equal(
            solo["__times__"], swept["__times__"]
        )
        np.testing.assert_array_equal(
            np.asarray(solo["cell"]["glucose_internal"]),
            np.asarray(swept["cell"]["glucose_internal"]),
        )
        server.close()

    def test_warmup_with_asha_forks_the_first_rung(self):
        res = run_sweep(self._warm_spec(
            asha={"min_horizon": 12.0, "eta": 2}
        ))
        statuses = {r["status"] for r in res.table}
        assert statuses <= {"done", "stopped"}
        c = res.metrics["server"]["counters"]
        assert c["prefix_misses"] == 1
        assert c["prefix_forks"] == len(DOSES)
        # survivors extended via resubmit as before, never re-warmed
        assert c["resubmitted"] >= 1

    def test_warmup_kill_and_resume_bitwise(self, tmp_path):
        full = run_sweep(self._warm_spec(),
                         out_dir=str(tmp_path / "full"))
        kill_dir = str(tmp_path / "killed")
        with pytest.raises(_Kill):
            run_sweep(self._warm_spec(), out_dir=kill_dir,
                      on_trial=_killer_after(2))
        resumed = run_sweep(self._warm_spec(), out_dir=kill_dir,
                            resume=True)
        for a, b in zip(full.table, resumed.table):
            assert a["status"] == b["status"]
            assert a["objective"] == b["objective"]  # bitwise

    def test_warmup_changes_the_resume_fingerprint(self, tmp_path):
        out = str(tmp_path / "s")
        run_sweep(_spec(), out_dir=out)
        with pytest.raises(ValueError, match="fingerprint"):
            run_sweep(self._warm_spec(), out_dir=out, resume=True)

    def test_warmupless_canonical_has_no_warmup_key(self):
        """Compat pin: a spec without ``warmup`` must canonicalize to
        the same fields as before round 11, or every pre-existing
        ledger's fingerprint guard would refuse a legitimate resume."""
        from lens_tpu.sweep.driver import SweepSpec

        assert "warmup" not in SweepSpec.from_mapping(
            _spec()
        ).canonical()
        assert SweepSpec.from_mapping(
            self._warm_spec()
        ).canonical()["warmup"] == {"horizon": 8.0}

    def test_warmup_validation(self):
        with pytest.raises(ValueError, match="shorter than"):
            run_sweep(self._warm_spec(warmup={"horizon": 16.0}))
        with pytest.raises(ValueError, match="needs a 'horizon'"):
            run_sweep(self._warm_spec(warmup={}))
        with pytest.raises(ValueError, match="unknown warmup keys"):
            run_sweep(self._warm_spec(
                warmup={"horizon": 8.0, "nope": 1}
            ))
        with pytest.raises(ValueError, match="first asha rung"):
            run_sweep(self._warm_spec(
                asha={"min_horizon": 8.0, "eta": 2}
            ))
        with pytest.raises(ValueError, match="server"):
            run_sweep(self._warm_spec(
                backend={"kind": "ensemble", "batch": 4}
            ))
