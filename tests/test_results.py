"""The durable result cache (serve/results.py): a CDN for simulations.

Round 18's determinism dividend: a request's ``.lens`` log is a pure
function of its bytes-relevant coordinates, so a completed log filed
under the request's content address serves every later identical
submission whole — zero device windows, zero lanes. Pinned here:

- **Addressing**: spelling-level aliases (override dict order, folded
  emit defaults, int-vs-float horizon) share one fingerprint;
  scheduling-only keys (deadline, tenant, priority) never touch it;
  bytes-relevant differences always split it.
- **Disk protocol**: tmp+rename publication, sidecar-attested scans,
  torn entries ignored, peer refresh, LRU GC — the tiers.py idioms.
- **Replay**: a hit's spliced log is byte-equal to the log the hitting
  request's own cold run writes (header re-minted, body verbatim).
- **Crash**: SIGKILL between the payload write and the sidecar leaves
  no entry that could serve; recovery re-runs and re-files bitwise.
"""

import glob
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from lens_tpu.emit.log import (
    encode_record,
    frame,
    iter_frames,
    make_header,
)
from lens_tpu.serve import DONE, QUEUED, RUNNING, ScenarioRequest, SimServer
from lens_tpu.serve.metrics import request_timing_row
from lens_tpu.serve.results import (
    RESULT_META,
    ResultCache,
    log_config,
    request_fingerprint,
)
from lens_tpu.serve.server import _request_to_json


def _fp(mapping):
    req = ScenarioRequest.from_mapping(mapping)
    return request_fingerprint(_request_to_json(req))


BASE = {"composite": "toggle_colony", "seed": 7, "horizon": 32.0}


class TestFingerprint:
    """One meaning, one content address."""

    def test_alias_spellings_share_fingerprint(self):
        ref = _fp({
            **BASE,
            "overrides": {"global": {"volume": 1.1},
                          "cell": {"protein": 2.0}},
        })
        aliases = [
            # int horizon spells the same float
            {**BASE, "horizon": 32,
             "overrides": {"global": {"volume": 1.1},
                           "cell": {"protein": 2.0}}},
            # override tree built in the other insertion order
            {**BASE,
             "overrides": {"cell": {"protein": 2.0},
                           "global": {"volume": 1.1}}},
            # a fully-default emit block folds away
            {**BASE, "emit": {"every": 1},
             "overrides": {"global": {"volume": 1.1},
                           "cell": {"protein": 2.0}}},
            {**BASE, "emit": {"every": 1, "paths": []},
             "overrides": {"global": {"volume": 1.1},
                           "cell": {"protein": 2.0}}},
        ]
        for alias in aliases:
            assert _fp(alias) == ref, alias

    def test_scheduling_keys_never_touch_the_address(self):
        ref = _fp(BASE)
        for extra in (
            {"deadline": 5.0},
            {"tenant": "acme"},
            {"priority": "interactive"},
        ):
            assert _fp({**BASE, **extra}) == ref, extra

    def test_bytes_relevant_differences_split_the_address(self):
        ref = _fp(BASE)
        assert _fp({**BASE, "seed": 8}) != ref
        assert _fp({**BASE, "horizon": 16.0}) != ref
        assert _fp({**BASE, "emit": {"every": 2}}) != ref
        assert _fp({**BASE, "n_agents": 2}) != ref
        # leaf dtype is deliberately NOT folded: it can change the
        # simulated bits, so int-vs-float leaves stay distinct keys
        assert _fp({**BASE, "overrides": {"g": {"v": 1}}}) \
            != _fp({**BASE, "overrides": {"g": {"v": 1.0}}})


def _donor(tmp_path, rid="req-000042", nrec=3):
    """A synthetic .lens log: header + ``nrec`` rid-free records."""
    cfg = {"composite": "toggle_colony", "seed": 1}
    path = str(tmp_path / f"{rid}.lens")
    with open(path, "wb") as f:
        f.write(frame(encode_record(make_header(rid, cfg))))
        for i in range(nrec):
            f.write(frame(encode_record({"x": np.arange(4) + i})))
    return path, cfg


class TestDiskProtocol:
    """tmp+rename publication, sidecar-attested scans, peer refresh."""

    def test_put_publishes_payload_then_sidecar(self, tmp_path):
        src, _ = _donor(tmp_path)
        cache = ResultCache(str(tmp_path / "res"))
        assert cache.put("f" * 64, src, request={"composite": "t"})
        assert len(cache) == 1
        assert cache.total_bytes() == os.path.getsize(src)
        names = sorted(os.listdir(cache.dir))
        assert not [n for n in names if ".tmp" in n]
        assert any(n.endswith(".lens") for n in names)
        assert any(n.endswith(".meta.json") for n in names)
        # idempotent per content address
        assert not cache.put("f" * 64, src)
        assert cache.stored == 1

    def test_scan_adopts_complete_entries_only(self, tmp_path):
        src, _ = _donor(tmp_path)
        d = str(tmp_path / "res")
        cache = ResultCache(d)
        cache.put("a" * 64, src)
        # torn states a crash can leave: payload without sidecar
        # (kill after rename), sidecar without payload (kill
        # mid-evict), and a bare tmp file (kill before rename)
        with open(os.path.join(d, "res_" + "b" * 32 + ".lens"),
                  "wb") as f:
            f.write(b"orphan payload")
        with open(os.path.join(
            d, "res_" + "c" * 32 + ".lens.meta.json"
        ), "w") as f:
            json.dump({"fingerprint": "c" * 64, "nbytes": 7}, f)
        with open(os.path.join(
            d, "res_" + "d" * 32 + ".lens.tmp-12345"
        ), "wb") as f:
            f.write(b"half a payload")
        fresh = ResultCache(d)
        assert len(fresh) == 1 and ("a" * 64) in fresh

    def test_refresh_adopts_a_peer_published_entry(self, tmp_path):
        src, _ = _donor(tmp_path)
        d = str(tmp_path / "res")
        mine = ResultCache(d)
        peer = ResultCache(d)
        peer.put("a" * 64, src)
        assert ("a" * 64) not in mine  # scanned before the peer wrote
        assert mine.refresh("a" * 64)
        assert ("a" * 64) in mine
        assert not mine.refresh("f" * 64)  # honest miss stays a miss

    def test_serve_splices_header_keeps_body_verbatim(self, tmp_path):
        src, cfg = _donor(tmp_path, nrec=4)
        cache = ResultCache(str(tmp_path / "res"))
        fp = "a" * 64
        cache.put(fp, src)
        dst = str(tmp_path / "hit" / "req-000077.lens")
        assert cache.serve(fp, "req-000077", cfg, dst)
        got = list(iter_frames(dst))
        ref = list(iter_frames(src))
        assert got[1:] == ref[1:]  # every body frame byte-equal
        from lens_tpu.emit.log import decode_record
        header = decode_record(got[0])["__header__"]
        assert str(np.asarray(header["experiment_id"])) == "req-000077"
        assert cache.hits == 1

    def test_vanished_donor_degrades_to_a_forgotten_miss(self, tmp_path):
        src, cfg = _donor(tmp_path)
        cache = ResultCache(str(tmp_path / "res"))
        fp = "a" * 64
        cache.put(fp, src)
        os.remove(cache._path(fp))  # a peer's eviction won the race
        dst = str(tmp_path / "req-000001.lens")
        assert not cache.serve(fp, "req-000001", cfg, dst)
        assert fp not in cache and cache.misses == 1
        assert not os.path.exists(dst)

    def test_gc_evicts_lru_first(self, tmp_path):
        src, cfg = _donor(tmp_path)
        size = os.path.getsize(src)
        cache = ResultCache(str(tmp_path / "res"))
        for c in "abc":
            cache.put(c * 64, src)
        # touch "a": "b" becomes the LRU victim
        assert cache.serve(
            "a" * 64, "req-000001", cfg, str(tmp_path / "t.lens")
        )
        evicted = cache.gc(2 * size)
        assert evicted == ["b" * 64]
        assert cache.evictions == 1 and len(cache) == 2
        assert not glob.glob(os.path.join(cache.dir, "*b" * 16 + "*"))

    def test_budget_evicts_at_put(self, tmp_path):
        src, _ = _donor(tmp_path)
        size = os.path.getsize(src)
        cache = ResultCache(
            str(tmp_path / "res"), budget_bytes=2 * size + size // 2
        )
        for c in "abc":
            cache.put(c * 64, src)
        assert len(cache) == 2 and ("a" * 64) not in cache
        with pytest.raises(ValueError, match="budget_bytes"):
            ResultCache(str(tmp_path / "res2"), budget_bytes=0)

    def test_bucket_fingerprint_guard(self, tmp_path):
        d = str(tmp_path / "res")
        ResultCache(d, fingerprint="aaaa")
        ResultCache(d, fingerprint="aaaa")  # same config: fine
        with pytest.raises(ValueError, match="fingerprint"):
            ResultCache(d, fingerprint="bbbb")
        ResultCache(d, fingerprint=None)  # inspection mode skips
        assert os.path.exists(os.path.join(d, RESULT_META))


def _server(tmp_path, tag, **kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    kw.setdefault("sink", "log")
    kw.setdefault("out_dir", str(tmp_path / f"{tag}_out"))
    return SimServer.single_bucket("toggle_colony", **kw)


def _lens(path):
    with open(path, "rb") as f:
        return f.read()


class TestServerCacheHit:
    """submit short-circuits admission whole on a durable hit."""

    def _reference(self, tmp_path):
        """The same request served twice COLD (no cache): what each
        rid's own solo run writes."""
        ref = _server(tmp_path, "ref")
        a = ref.submit(dict(BASE))
        b = ref.submit(dict(BASE))
        ref.run_until_idle(max_ticks=300)
        out = {r: _lens(ref.status(r)["result_path"]) for r in (a, b)}
        ref.close()
        return out

    def test_hit_is_terminal_windowless_and_bitwise(self, tmp_path):
        ref = self._reference(tmp_path)
        srv = _server(
            tmp_path, "cdn", result_cache_mb=64,
            recover_dir=str(tmp_path / "cdn_wal"),
        )
        r1 = srv.submit(dict(BASE))
        srv.run_until_idle(max_ticks=300)
        cold_windows = srv.metrics()["counters"]["windows"]
        r2 = srv.submit(dict(BASE))
        # terminal at submit: no tick ran, no lane, no device window
        st = srv.status(r2)
        assert st["status"] == DONE
        assert st["steps_done"] == st["horizon_steps"]
        m = srv.metrics()
        assert m["counters"]["windows"] == cold_windows
        assert m["counters"]["result_hits"] == 1
        assert m["counters"]["device_seconds_saved"] > 0
        assert m["result_entries"] == 1 and m["result_bytes"] > 0
        # the spliced log is byte-equal to r2's own cold solo run
        assert _lens(st["result_path"]) == ref[r2]
        assert _lens(srv.status(r1)["result_path"]) == ref[r1]
        # satellite: the timing table stays complete for a ticket
        # that never touched a lane (admitted/first_window honestly
        # None, no AttributeError)
        row = request_timing_row(srv.tickets[r2], 0.0)
        assert row["admitted"] is None and row["first_window"] is None
        assert row["last_streamed"] is not None
        srv.close()

    def test_restart_serves_warm_with_zero_windows(self, tmp_path):
        wal = str(tmp_path / "wal")
        srv = _server(
            tmp_path, "warm", result_cache_mb=64, recover_dir=wal,
        )
        r1 = srv.submit(dict(BASE))
        srv.run_until_idle(max_ticks=300)
        cold_path = srv.status(r1)["result_path"]
        srv.close()
        srv2 = _server(
            tmp_path, "warm", result_cache_mb=64, recover_dir=wal,
        )
        r = srv2.submit(dict(BASE))
        assert srv2.status(r)["status"] == DONE
        m = srv2.metrics()["counters"]
        assert m["windows"] == 0 and m["result_hits"] == 1
        # body equality, frame by frame (headers differ only in rid)
        got = list(iter_frames(srv2.status(r)["result_path"]))
        ref = list(iter_frames(cold_path))
        assert got[1:] == ref[1:]
        srv2.close()

    def test_hold_state_requests_bypass_the_cache(self, tmp_path):
        srv = _server(
            tmp_path, "hold", result_cache_mb=64,
            recover_dir=str(tmp_path / "hold_wal"),
        )
        srv.submit(dict(BASE))
        srv.run_until_idle(max_ticks=300)
        r = srv.submit({**BASE, "hold_state": True})
        # a hold must run its own lane: its product includes a pinned
        # device snapshot no cached log carries
        assert srv.status(r)["status"] in (QUEUED, RUNNING)
        srv.run_until_idle(max_ticks=300)
        assert srv.status(r)["status"] == DONE
        assert srv.metrics()["counters"]["result_hits"] == 0
        srv.close()


class TestCacheCLI:
    """``python -m lens_tpu cache <dir>``: inspect + --max-mb GC."""

    def _dir_with_entries(self, tmp_path):
        src, _ = _donor(tmp_path)
        cache = ResultCache(str(tmp_path / "res"))
        cache.put("a" * 64, src,
                  request={"composite": "toggle_colony",
                           "horizon": 32.0})
        cache.put("b" * 64, src)
        return cache.dir, os.path.getsize(src)

    def _cli(self, *args):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        return subprocess.run(
            [sys.executable, "-m", "lens_tpu", "cache", *args],
            capture_output=True, text=True, timeout=240, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
        )

    def test_table_json_and_gc(self, tmp_path):
        d, size = self._dir_with_entries(tmp_path)
        proc = self._cli(d)
        assert proc.returncode == 0, proc.stderr
        assert "a" * 16 in proc.stdout
        assert "toggle_colony" in proc.stdout
        proc = self._cli(d, "--json")
        assert proc.returncode == 0, proc.stderr
        rows = json.loads(proc.stdout)["entries"]
        assert {r["fingerprint"] for r in rows} == \
            {"a" * 64, "b" * 64}
        # GC down to one entry's worth of bytes
        proc = self._cli(d, "--max-mb", str(1.5 * size / 2**20))
        assert proc.returncode == 0, proc.stderr
        assert len(ResultCache(d)) == 1


_CLI_REQS = [
    {"seed": 1, "horizon": 16.0},
    {"seed": 2, "horizon": 16.0},
]


def _run_serve(args, cwd, expect_kill=False, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "lens_tpu", "serve", *args],
        cwd=cwd, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}"
        )
    return proc


def _result_kill_drill(tmp_path, repo_root, seam):
    """SIGKILL a real serve process at a result-cache publication
    seam, recover over the same dirs, and require (a) the final logs
    bitwise equal to an uninterrupted run's and (b) every entry the
    cache dir holds is complete and servable — a kill can leave
    orphans the scan ignores, never a torn entry that could serve."""
    reqs = tmp_path / "reqs.json"
    reqs.write_text(json.dumps(_CLI_REQS))
    base = [
        "--composite", "toggle_colony", "--capacity", "8",
        "--lanes", "2", "--window", "4", "--requests", str(reqs),
        "--result-cache-mb", "64",
    ]
    tag = seam.replace(".", "_")
    ref_out = tmp_path / f"ref_{tag}"
    _run_serve(
        base + ["--out-dir", str(ref_out),
                "--recover-dir", str(tmp_path / f"ref_wal_{tag}")],
        repo_root,
    )
    out = tmp_path / f"out_{tag}"
    wal = tmp_path / f"wal_{tag}"
    faults = tmp_path / f"faults_{tag}.json"
    faults.write_text(json.dumps([{"kind": "kill", "at": seam}]))
    _run_serve(
        base + ["--out-dir", str(out), "--recover-dir", str(wal),
                "--faults", str(faults)],
        repo_root, expect_kill=True,
    )
    _run_serve(
        base + ["--out-dir", str(out), "--recover-dir", str(wal)],
        repo_root,
    )
    ref = {
        os.path.basename(p): _lens(p)
        for p in glob.glob(os.path.join(str(ref_out), "*.lens"))
    }
    assert ref
    for name, data in ref.items():
        assert _lens(os.path.join(str(out), name)) == data, name
    cache = ResultCache(str(wal / "results"))
    for row in cache.entries():
        dst = str(tmp_path / f"probe_{tag}_{row['name']}")
        assert cache.serve(
            row["fingerprint"], "req-999999",
            {"composite": "toggle_colony"}, dst,
        ), f"adopted entry {row['fingerprint']} is torn"


@pytest.fixture(scope="module")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestResultKillDrill:
    """The quick-tier representative: kill with the payload still at
    its tmp name — the scan must adopt nothing torn."""

    def test_kill_mid_publication_recovers_bitwise(
        self, tmp_path, repo_root
    ):
        _result_kill_drill(tmp_path, repo_root, "result.tmp_written")


@pytest.mark.slow
class TestResultKillDrillExhaustive:
    """Every result-publication seam (the recovery suite's chaos
    discipline, extended to the round-18 protocol)."""

    @pytest.mark.parametrize(
        "seam", ["result.tmp_written", "result.renamed", "result.cached"]
    )
    def test_kill_everywhere_recovers_bitwise(
        self, tmp_path, repo_root, seam
    ):
        _result_kill_drill(tmp_path, repo_root, seam)
