"""Compartment engine: wiring, stepping, scan, emit, divide."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.core.engine import Compartment
from lens_tpu.core.process import Deriver, Process


class Source(Process):
    """Adds `rate * dt` to store variable x."""

    name = "source"
    defaults = {"rate": 1.0}

    def ports_schema(self):
        return {"pool": {"x": {"_default": 0.0, "_divider": "split"}}}

    def next_update(self, timestep, states):
        return {"pool": {"x": self.config["rate"] * timestep}}


class Decay(Process):
    name = "decay"
    defaults = {"k": 0.5}

    def ports_schema(self):
        return {"pool": {"x": {"_default": 0.0}}}

    def next_update(self, timestep, states):
        return {"pool": {"x": -self.config["k"] * states["pool"]["x"] * timestep}}


class Doubler(Deriver):
    """Sets y = 2*x (derived bookkeeping)."""

    name = "doubler"

    def ports_schema(self):
        return {
            "pool": {
                "x": {"_default": 0.0},
                "y": {"_default": 0.0, "_updater": "set", "_divider": "copy"},
            }
        }

    def next_update(self, timestep, states):
        return {"pool": {"y": 2.0 * states["pool"]["x"]}}


def make_compartment():
    return Compartment(
        processes={"source": Source(), "decay": Decay(), "doubler": Doubler()},
        topology={
            "source": {"pool": ("cell",)},
            "decay": {"pool": ("cell",)},
            "doubler": {"pool": ("cell",)},
        },
    )


def test_initial_state_from_schema():
    comp = make_compartment()
    state = comp.initial_state()
    assert float(state["cell"]["x"]) == 0.0
    assert float(state["cell"]["y"]) == 0.0


def test_processes_see_prestep_state():
    """Both mechanistic processes must see the same pre-step state."""
    comp = make_compartment()
    state = comp.initial_state({"cell": {"x": 10.0}})
    out = comp.step(state, 1.0)
    # source adds 1.0; decay removes 0.5*10 (NOT 0.5*11)
    np.testing.assert_allclose(float(out["cell"]["x"]), 10.0 + 1.0 - 5.0)
    # deriver sees merged state
    np.testing.assert_allclose(float(out["cell"]["y"]), 2.0 * 6.0)


def test_run_matches_repeated_step():
    comp = make_compartment()
    state = comp.initial_state()
    manual = state
    for _ in range(10):
        manual = comp.step(manual, 0.5)
    final, traj = comp.run(state, 5.0, 0.5)
    np.testing.assert_allclose(
        float(final["cell"]["x"]), float(manual["cell"]["x"]), rtol=1e-6
    )
    assert traj["cell"]["x"].shape == (10,)


def test_run_jits_and_emit_every():
    comp = make_compartment()
    state = comp.initial_state()
    run = jax.jit(lambda s: comp.run(s, 4.0, 0.5, emit_every=4))
    final, traj = run(state)
    assert traj["cell"]["x"].shape == (2,)


def test_missing_topology_raises():
    with pytest.raises(ValueError):
        Compartment(processes={"source": Source()}, topology={})


def test_conflicting_updaters_raise():
    class SetterOnX(Process):
        name = "setter"

        def ports_schema(self):
            return {"pool": {"x": {"_default": 0.0, "_updater": "set"}}}

        def next_update(self, timestep, states):
            return {"pool": {"x": 0.0}}

    with pytest.raises(ValueError):
        Compartment(
            processes={"source": Source(), "setter": SetterOnX()},
            topology={"source": {"pool": ("cell",)}, "setter": {"pool": ("cell",)}},
        )


def test_divide_uses_declared_dividers():
    comp = make_compartment()
    state = comp.initial_state({"cell": {"x": 4.0, "y": 8.0}})
    a, b = comp.divide(state, jax.random.PRNGKey(0))
    assert float(a["cell"]["x"]) == 2.0  # split
    assert float(a["cell"]["y"]) == 8.0  # copy (deriver-declared)


def test_step_is_vmappable():
    comp = make_compartment()
    state = comp.initial_state()
    batched = jax.tree.map(lambda x: jnp.broadcast_to(x, (16,)), state)
    out = jax.vmap(lambda s: comp.step(s, 1.0))(batched)
    assert out["cell"]["x"].shape == (16,)


class TestStandaloneHarness:
    """The reference's per-process __main__ dev harness (SURVEY.md §3.4):
    any registered process runs alone with identity wiring and renders
    its timeseries."""

    def test_run_standalone_deterministic(self):
        from lens_tpu.processes.mm_transport import MichaelisMentenTransport
        from lens_tpu.processes.standalone import run_standalone

        final, traj = run_standalone(
            MichaelisMentenTransport(), total_time=50.0
        )
        import numpy as np

        g = np.asarray(traj["internal"]["glucose_internal"])
        assert g.shape[0] == 50
        assert np.isfinite(g).all() and g[-1] > g[0]

    def test_run_standalone_stochastic(self):
        from lens_tpu.processes.standalone import run_standalone
        from lens_tpu.processes.stochastic_expression import (
            StochasticExpression,
        )

        import numpy as np

        _, traj = run_standalone(StochasticExpression(), total_time=60.0)
        m = np.asarray(traj["counts"]["mrna"])
        assert m.shape[0] == 60 and (m >= 0).all() and m.max() > 0

    def test_demo_cli_renders_plot(self, tmp_path, capsys):
        import os

        from lens_tpu.__main__ import main

        rc = main(
            [
                "demo", "growth", "--time", "30",
                "--out-dir", str(tmp_path),
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "plot:" in out
        plot = out.split("plot:")[1].strip()
        assert os.path.getsize(plot) > 1000

    def test_demo_unknown_process(self):
        import pytest

        from lens_tpu.processes.standalone import demo

        with pytest.raises(KeyError, match="unknown process"):
            demo("nope")
