"""Property-based tests (hypothesis) for the contract surface.

SURVEY.md §4 directs the rebuild to be STRONGER than the reference's
thin per-file tests; these pin the core invariants over randomized
inputs instead of hand-picked examples: update-merge algebra, division
conservation for every divider, and the regulation-rule compiler
against a Python-evaluated oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from lens_tpu.core.state import DIVIDERS, UPDATERS, apply_update, divide_state
from lens_tpu.utils.regulation_logic import compile_rule

# allow_subnormal=False: XLA flushes subnormals to zero, so e.g. half of
# a subnormal is 0.0 — a float32 artifact, not a conservation bug
finite = st.floats(
    min_value=-1e6, max_value=1e6, allow_nan=False, allow_infinity=False,
    width=32, allow_subnormal=False,
)
positive = st.floats(
    min_value=0.0, max_value=1e6, allow_nan=False, allow_infinity=False,
    width=32, allow_subnormal=False,
)


class TestUpdaterAlgebra:
    @given(v=finite, d1=finite, d2=finite)
    @settings(max_examples=50, deadline=None)
    def test_accumulate_is_additive_and_commutative(self, v, d1, d2):
        up = UPDATERS["accumulate"]
        a = up(up(jnp.float32(v), jnp.float32(d1)), jnp.float32(d2))
        b = up(up(jnp.float32(v), jnp.float32(d2)), jnp.float32(d1))
        # commutative up to float32 rounding: the worst case is a couple
        # of ulps at the largest intermediate magnitude (catastrophic
        # cancellation), so the tolerance must scale with the inputs
        scale = max(1.0, abs(v), abs(d1), abs(d2))
        np.testing.assert_allclose(
            float(a), float(b), rtol=1e-5, atol=1e-6 * scale
        )

    @given(v=finite, d=finite)
    @settings(max_examples=50, deadline=None)
    def test_nonnegative_accumulate_floor(self, v, d):
        out = float(UPDATERS["nonnegative_accumulate"](
            jnp.float32(v), jnp.float32(d)
        ))
        assert out >= 0.0
        if v + d >= 0:
            np.testing.assert_allclose(out, np.float32(v) + np.float32(d),
                                       rtol=1e-6, atol=1e-6)

    @given(v=finite, d=finite)
    @settings(max_examples=50, deadline=None)
    def test_set_and_null_are_projections(self, v, d):
        assert float(UPDATERS["set"](jnp.float32(v), jnp.float32(d))) == (
            np.float32(d)
        )
        assert float(UPDATERS["null"](jnp.float32(v), jnp.float32(d))) == (
            np.float32(v)
        )

    @given(v=finite, d=finite)
    @settings(max_examples=30, deadline=None)
    def test_apply_update_routes_by_declared_updater(self, v, d):
        state = {"a": {"x": jnp.float32(v), "y": jnp.float32(v)}}
        update = {"a": {"x": jnp.float32(d), "y": jnp.float32(d)}}
        out = apply_update(
            state, update,
            {("a", "x"): "accumulate", ("a", "y"): "set"},
        )
        np.testing.assert_allclose(
            float(out["a"]["x"]), np.float32(v) + np.float32(d),
            rtol=1e-6, atol=1e-6,
        )
        assert float(out["a"]["y"]) == np.float32(d)


class TestDividerConservation:
    @given(v=positive, seed=st.integers(0, 2**31 - 1))
    @settings(max_examples=40, deadline=None)
    def test_split_and_binomial_conserve(self, v, seed):
        key = jax.random.PRNGKey(seed)
        a, b = DIVIDERS["split"](jnp.float32(v), key)
        np.testing.assert_allclose(
            float(a) + float(b), np.float32(v), rtol=1e-6, atol=1e-30
        )
        n = float(jnp.round(jnp.float32(v) % 10000))
        a, b = DIVIDERS["binomial"](jnp.float32(n), key)
        np.testing.assert_allclose(float(a) + float(b), n, rtol=1e-6)
        assert 0.0 <= float(a) <= n

    @given(v=finite, seed=st.integers(0, 2**31 - 1))
    @settings(max_examples=40, deadline=None)
    def test_copy_zero_identities(self, v, seed):
        key = jax.random.PRNGKey(seed)
        a, b = DIVIDERS["copy"](jnp.float32(v), key)
        assert float(a) == float(b) == np.float32(v)
        a, b = DIVIDERS["zero"](jnp.float32(v), key)
        assert float(a) == float(b) == 0.0

    @given(
        x=st.floats(0, 1000, allow_nan=False, width=32),
        y=st.floats(0, 1000, allow_nan=False, width=32),
        seed=st.integers(0, 2**31 - 1),
    )
    @settings(max_examples=40, deadline=None)
    def test_offset_preserves_midpoint_and_separation(self, x, y, seed):
        from lens_tpu.core.state import DIVISION_SEPARATION_UM

        key = jax.random.PRNGKey(seed)
        loc = jnp.asarray([x, y], jnp.float32)
        a, b = DIVIDERS["offset"](loc, key)
        np.testing.assert_allclose(
            np.asarray((a + b) / 2.0), np.asarray(loc), rtol=1e-4, atol=1e-3
        )
        np.testing.assert_allclose(
            float(jnp.linalg.norm(a - b)), DIVISION_SEPARATION_UM,
            rtol=1e-4,
        )

    @given(mass=positive, conc=finite, clock=finite,
           seed=st.integers(0, 2**31 - 1))
    @settings(max_examples=30, deadline=None)
    def test_divide_state_tree(self, mass, conc, clock, seed):
        state = {
            "mass": jnp.float32(mass),
            "conc": jnp.float32(conc),
            "clock": jnp.float32(clock),
        }
        a, b = divide_state(
            state, jax.random.PRNGKey(seed),
            {("mass",): "split", ("conc",): "copy", ("clock",): "zero"},
        )
        np.testing.assert_allclose(
            float(a["mass"]) + float(b["mass"]), np.float32(mass),
            rtol=1e-6, atol=1e-30,
        )
        assert float(a["conc"]) == float(b["conc"]) == np.float32(conc)
        assert float(a["clock"]) == float(b["clock"]) == 0.0


# a tiny random-expression generator for the rule grammar
names = st.sampled_from(["glc", "lcts", "o2", "nh4"])


@st.composite
def rule_exprs(draw, depth=0):
    if depth >= 3 or draw(st.booleans()):
        return draw(names)
    kind = draw(st.sampled_from(["not", "and", "or", "paren"]))
    if kind == "not":
        return f"not {draw(rule_exprs(depth + 1))}"
    if kind == "paren":
        return f"({draw(rule_exprs(depth + 1))})"
    return (
        f"{draw(rule_exprs(depth + 1))} {kind} {draw(rule_exprs(depth + 1))}"
    )


class TestRegulationRulesOracle:
    @given(
        expr=rule_exprs(),
        glc=st.floats(0, 2, width=32, allow_nan=False),
        lcts=st.floats(0, 2, width=32, allow_nan=False),
        o2=st.floats(0, 2, width=32, allow_nan=False),
        nh4=st.floats(0, 2, width=32, allow_nan=False),
    )
    @settings(max_examples=80, deadline=None)
    def test_compiled_rule_matches_python_eval(self, expr, glc, lcts, o2, nh4):
        threshold = 0.5
        env = {"glc": glc, "lcts": lcts, "o2": o2, "nh4": nh4}
        rule = compile_rule(expr, threshold=threshold)
        got = bool(float(rule({k: jnp.float32(v) for k, v in env.items()})))
        expect = bool(
            eval(  # noqa: S307 — oracle over a generated, closed grammar
                expr, {"__builtins__": {}},
                {k: (v > threshold) for k, v in env.items()},
            )
        )
        assert got == expect, (expr, env)


class TestLinprogPinnedPresolve:
    """Random gating patterns through the pinned-column presolve
    (ops.linprog): regulation pins lb = ub = 0 on arbitrary reaction
    subsets, and the masked barrier must keep matching HiGHS on
    whatever survives — including reporting infeasibility honestly
    when the gating strands the equality constraints.

    ONE jitted solver for all examples (eager linprog_box re-traces its
    while_loop per call; dozens of throwaway compiles per hypothesis
    run needlessly churn the XLA CPU compiler).
    """

    _solver = None

    @classmethod
    def solver(cls):
        if cls._solver is None:
            from functools import partial

            from lens_tpu.ops.linprog import linprog_box

            cls._solver = jax.jit(
                partial(linprog_box, n_iter=60, tol=1e-5)
            )
        return cls._solver

    @given(seed=st.integers(0, 10_000))
    @settings(max_examples=12, deadline=None)
    def test_random_pinning_matches_highs(self, seed):
        import scipy.optimize

        rng = np.random.default_rng(seed)
        m, r = 4, 12
        A = rng.normal(size=(m, r))
        lb = -rng.uniform(0.5, 3.0, size=r)
        ub = rng.uniform(0.5, 3.0, size=r)
        x0 = rng.uniform(0.25, 0.75, size=r) * (ub - lb) + lb
        b = A @ x0
        c = rng.normal(size=r)
        # pin a random subset at a feasible-agnostic value (0 if inside
        # the box, else the nearer bound) — the rFBA gating shape
        pinned = rng.random(r) < 0.4
        pin_val = np.clip(0.0, lb, ub)
        lb = np.where(pinned, pin_val, lb)
        ub = np.where(pinned, pin_val, ub)

        ref = scipy.optimize.linprog(
            c, A_eq=A, b_eq=b, bounds=list(zip(lb, ub)), method="highs"
        )
        res = self.solver()(
            jnp.asarray(c, jnp.float32), jnp.asarray(A, jnp.float32),
            jnp.asarray(b, jnp.float32), jnp.asarray(lb, jnp.float32),
            jnp.asarray(ub, jnp.float32),
        )
        if ref.status != 0:
            assert not bool(res.converged), (
                "f32 solver claimed convergence on a HiGHS-infeasible LP"
            )
            return
        # feasible per HiGHS -> the solver must actually solve it (not
        # vacuously report unconverged; measured 154/154 over seeds
        # 0..199 at these sizes)
        assert bool(res.converged), "f32 solver failed a feasible pinned LP"
        scale = 1.0 + abs(ref.fun)
        assert abs(float(res.objective) - ref.fun) / scale < 2e-3
        x = np.asarray(res.x)
        np.testing.assert_allclose(x[pinned], pin_val[pinned], atol=1e-6)
        assert np.all(x >= lb - 1e-4) and np.all(x <= ub + 1e-4)
