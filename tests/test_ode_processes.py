"""ODE processes (configs 0, 1) vs scipy oracles — the correctness anchor.

SURVEY.md §4: numerical parity tests against a small pure-scipy oracle of
each BASELINE.json config.
"""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.integrate import odeint as scipy_odeint

from lens_tpu.core.engine import Compartment
from lens_tpu.processes.glucose_pts import GlucosePTS
from lens_tpu.processes.toggle_switch import ToggleSwitch


def glucose_compartment(config=None):
    return Compartment(
        processes={"transport": GlucosePTS(config)},
        topology={
            "transport": {
                "internal": ("cell",),
                "external": ("boundary",),
                "exchange": ("exchange",),
            }
        },
    )


def test_config0_single_agent_vs_scipy():
    """Config 0: single agent, 2-species glucose ODE, 100 sim-sec."""
    comp = glucose_compartment()
    state = comp.initial_state()
    final, traj = comp.run(state, 100.0, 1.0)

    c = GlucosePTS.defaults

    def rhs(y, t):
        g_ext, g_int = y
        uptake = c["vmax"] * g_ext / (c["km"] + g_ext)
        return [-uptake * c["density"], uptake - c["k_consume"] * g_int]

    ref = scipy_odeint(rhs, [10.0, 0.0], np.linspace(0.0, 100.0, 101))[-1]
    np.testing.assert_allclose(
        float(final["boundary"]["glucose_external"]), ref[0], rtol=1e-4
    )
    np.testing.assert_allclose(
        float(final["cell"]["glucose_internal"]), ref[1], rtol=1e-4
    )
    # exchange accumulates net secretion: negative of the total drawdown
    np.testing.assert_allclose(
        float(final["exchange"]["glucose_flux"]),
        ref[0] - 10.0,
        rtol=1e-4,
    )
    assert traj["cell"]["glucose_internal"].shape == (100,)


def test_toggle_switch_bistability():
    """The switch must latch to the arm favored by initial conditions."""
    comp = Compartment(
        processes={"switch": ToggleSwitch()},
        topology={"switch": {"internal": ("cell",)}},
    )
    # U-favored start (defaults) -> protein_u high, protein_v low
    final_u, _ = comp.run(comp.initial_state(), 50.0, 1.0)
    assert float(final_u["cell"]["protein_u"]) > 5 * float(
        final_u["cell"]["protein_v"]
    )
    # mirrored start -> latches the other way
    flipped = comp.initial_state(
        {"cell": {"mrna_u": 0.1, "protein_u": 0.1, "mrna_v": 0.5, "protein_v": 2.0}}
    )
    final_v, _ = comp.run(flipped, 50.0, 1.0)
    assert float(final_v["cell"]["protein_v"]) > 5 * float(
        final_v["cell"]["protein_u"]
    )


def test_toggle_switch_vs_scipy():
    c = ToggleSwitch.defaults

    def rhs(y, t):
        m_u, p_u, m_v, p_v = y
        hill = lambda p: c["alpha"] / (1.0 + (p / c["k"]) ** c["n_hill"])
        return [
            hill(p_v) - c["d_m"] * m_u,
            c["k_t"] * m_u - c["d_p"] * p_u,
            hill(p_u) - c["d_m"] * m_v,
            c["k_t"] * m_v - c["d_p"] * p_v,
        ]

    comp = Compartment(
        processes={"switch": ToggleSwitch()},
        topology={"switch": {"internal": ("cell",)}},
    )
    final, _ = comp.run(comp.initial_state(), 20.0, 1.0)
    ref = scipy_odeint(rhs, [0.5, 2.0, 0.1, 0.1], np.linspace(0, 20.0, 201))[-1]
    got = [
        float(final["cell"][k])
        for k in ("mrna_u", "protein_u", "mrna_v", "protein_v")
    ]
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=1e-4)


def test_vmapped_colony_step():
    """1k-agent toggle-switch colony — one vmapped engine step (config 1 core)."""
    comp = Compartment(
        processes={"switch": ToggleSwitch()},
        topology={"switch": {"internal": ("cell",)}},
    )
    n = 1024
    state = comp.initial_state()
    key = jax.random.PRNGKey(0)
    batched = jax.tree.map(
        lambda x: x
        * jax.random.uniform(key, (n,), minval=0.5, maxval=1.5).astype(x.dtype),
        state,
    )
    step = jax.jit(jax.vmap(lambda s: comp.step(s, 1.0)))
    out = step(batched)
    assert out["cell"]["protein_u"].shape == (n,)
    assert bool(jnp.all(jnp.isfinite(out["cell"]["protein_u"])))


def test_process_registry_populated():
    """Regression: @register must actually be applied (caught in verify)."""
    from lens_tpu.processes import process_registry

    assert "glucose_pts" in process_registry
    assert "toggle_switch" in process_registry
    assert process_registry["glucose_pts"] is GlucosePTS
