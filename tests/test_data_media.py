"""Data layer loaders + media maker + timelines (SURVEY.md §2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.data import load_json, load_table, load_tsv
from lens_tpu.environment.lattice import Lattice
from lens_tpu.environment.media import (
    fields_from_media,
    make_media,
    media_recipes,
    parse_timeline,
    timeline_segments,
)


class TestDataLayer:
    def test_load_json_recipes(self):
        recipes = load_json("media_recipes.json")
        assert "minimal" in recipes
        assert recipes["minimal"]["glucose"] == 10.0

    def test_load_tsv_parses_types(self):
        rows = load_tsv("kinetic_parameters.tsv")
        assert len(rows) > 5
        row = rows[0]
        assert row["process"] == "glucose_pts"
        assert isinstance(row["value"], float)

    def test_load_table_collapse(self):
        rows = load_tsv("kinetic_parameters.tsv")
        glucose_rows = [r for r in rows if r["process"] == "glucose_pts"]
        assert {r["parameter"]: r["value"] for r in glucose_rows}["km"] == 0.5


class TestMakeMedia:
    def test_named_recipe(self):
        media = make_media("minimal")
        assert media == {"glucose": 10.0}

    def test_overrides(self):
        media = make_media("minimal", {"glucose": 2.0, "lactose": 1.0})
        assert media == {"glucose": 2.0, "lactose": 1.0}

    def test_literal_dict(self):
        assert make_media({"x": 1}) == {"x": 1.0}

    def test_unknown_recipe_raises(self):
        with pytest.raises(KeyError, match="unknown media recipe"):
            make_media("nope")

    def test_recipes_are_copies(self):
        a = make_media("minimal")
        a["glucose"] = 0.0
        assert media_recipes()["minimal"]["glucose"] == 10.0


class TestTimeline:
    def test_parse_string(self):
        events = parse_timeline("0 minimal, 500 minimal_lactose")
        assert len(events) == 2
        assert events[0][0] == 0.0
        assert events[1][1]["lactose"] == 10.0

    def test_parse_sequence_with_dicts(self):
        events = parse_timeline([(0, {"glucose": 1.0}), (100, "blank")])
        assert events[1][1] == {}

    def test_must_start_at_zero(self):
        with pytest.raises(ValueError, match="must start at t=0"):
            parse_timeline("100 minimal")

    def test_duplicate_times_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            parse_timeline([(0, "minimal"), (0, "blank")])

    def test_segments(self):
        events = parse_timeline("0 minimal, 500 minimal_lactose")
        segs = timeline_segments(events, 800.0)
        assert [(s, d) for s, d, _ in segs] == [(0.0, 500.0), (500.0, 300.0)]
        # events beyond total_time are dropped
        segs = timeline_segments(events, 400.0)
        assert len(segs) == 1 and segs[0][1] == 400.0

    def test_segments_start_time(self):
        """Event times are ABSOLUTE: a continuation covering [250, 500)
        of a t=400 shift gets [250,400) on the old media + [400,500) on
        the new — checkpointed/segmented runs must not restart timelines."""
        events = parse_timeline("0 minimal, 400 minimal_lactose")
        segs = timeline_segments(events, 250.0, start_time=250.0)
        assert [(s, d) for s, d, _ in segs] == [(250.0, 150.0), (400.0, 100.0)]
        assert segs[0][2] == events[0][1]   # still minimal before 400
        assert segs[1][2] == events[1][1]   # lactose from 400
        # a continuation entirely within one media epoch: one segment,
        # whose start is NOT an event time (callers must not reset fields)
        segs = timeline_segments(events, 100.0, start_time=100.0)
        assert [(s, d) for s, d, _ in segs] == [(100.0, 100.0)]

    def test_fields_from_media(self):
        lattice = Lattice(
            molecules=["glucose", "lactose"], shape=(8, 8), timestep=1.0
        )
        fields = fields_from_media(lattice, {"lactose": 3.0})
        assert fields.shape == (2, 8, 8)
        assert float(fields[0].max()) == 0.0  # glucose absent -> 0
        assert float(fields[1].min()) == 3.0


class TestTimelineRun:
    def test_media_switch_resets_fields(self):
        """run_timeline resets fields at segment boundaries: glucose is
        drawn down in segment 1, replenished by the t=8 media event."""
        from lens_tpu.models.composites import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {
                "capacity": 16,
                "shape": (4, 4),
                "size": (4.0, 4.0),
                "diffusion": 0.0,
                "initial_glucose": 10.0,
                "division": False,
                "transport": {"vmax": 1.0},
            }
        )
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        final, traj = spatial.run_timeline(
            ss,
            [(0, {"glucose": 10.0}), (8, {"glucose": 10.0})],
            16.0,
            1.0,
        )
        fields = np.asarray(traj["fields"])  # [16, 1, 4, 4]
        assert fields.shape[0] == 16
        mass = fields.sum(axis=(1, 2, 3))
        # drawdown within segment 1...
        assert mass[7] < mass[0]
        # ...then the media reset restores the full field at t=8
        assert mass[8] > mass[7]
