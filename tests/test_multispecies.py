"""Mixed-species colonies: distinct process sets on one shared lattice.

The round-1 gap (VERDICT "missing #6"): config 4's "mixed-species" was
per-agent rate overrides on ONE process set. These tests pin the real
thing — two subcolonies with different process sets, coupled only through
the shared fields — including cross-species shared-bin conservation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.environment import Lattice, MultiSpeciesColony, SpatialColony
from lens_tpu.models.composites import (
    composite_registry,
    mixed_species_lattice,
)
from lens_tpu.processes.mm_transport import (
    BrownianMotility,
    MichaelisMentenTransport,
)


def small_mixed(capacity=32, shape=(16, 16), division=True, extra=None):
    cfg = {
        "capacity": {"ecoli": capacity, "scavenger": capacity},
        "shape": shape,
        "size": (float(shape[0]), float(shape[1])),
        "diffusion": {"glucose": 1.0, "acetate": 1.0},
        "timestep": 1.0,
        "division": division,
    }
    if extra:
        cfg.update(extra)
    return mixed_species_lattice(cfg)


class TestMixedSpecies:
    def test_distinct_process_sets(self):
        multi, comps = small_mixed()
        assert "expression" in comps["scavenger"].processes
        assert "expression" not in comps["ecoli"].processes
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0)
        )
        # schemas differ: only the scavenger carries Gillespie counts
        assert "counts" in ms.species["scavenger"].agents
        assert "counts" not in ms.species["ecoli"].agents

    def test_one_jitted_step_advances_both(self):
        multi, _ = small_mixed(division=False)
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(1)
        )
        out = jax.jit(lambda s: multi.step(s, 1.0))(ms)
        n = multi.n_alive(out)
        assert int(n["ecoli"]) == 8 and int(n["scavenger"]) == 8
        # each species consumed ITS molecule
        glc0 = float(multi.total_field_mass(ms)[0])
        ace0 = float(multi.total_field_mass(ms)[1])
        glc1 = float(multi.total_field_mass(out)[0])
        ace1 = float(multi.total_field_mass(out)[1])
        assert glc1 < glc0
        assert ace1 < ace0

    def test_mass_conservation_across_species(self):
        """field + internal pools conserved per molecule, with both
        species eating, moving, and dividing."""
        multi, _ = small_mixed(
            extra={
                "ecoli": {
                    "transport": {"yield_": 1.0, "k_consume": 0.0},
                    "growth": {"rate": 0.05},
                },
                "scavenger": {
                    "transport": {
                        "molecule": "acetate",
                        "yield_": 1.0,
                        "k_consume": 0.0,
                    },
                    "growth": {"rate": 0.05},
                },
            }
        )
        ms = multi.initial_state(
            {"ecoli": 12, "scavenger": 12}, jax.random.PRNGKey(2)
        )

        def total(ms, mol_idx, species, pool):
            field = float(multi.total_field_mass(ms)[mol_idx])
            cs = ms.species[species]
            internal = float(
                jnp.sum(cs.agents["cell"][pool] * cs.alive)
            )
            return field + internal

        glc0 = total(ms, 0, "ecoli", "glucose_internal")
        ace0 = total(ms, 1, "scavenger", "acetate_internal")
        out, _ = multi.run(ms, 20.0, 1.0, emit_every=20)
        glc1 = total(out, 0, "ecoli", "glucose_internal")
        ace1 = total(out, 1, "scavenger", "acetate_internal")
        n = multi.n_alive(out)
        assert int(n["ecoli"]) > 12, "expected ecoli divisions"
        np.testing.assert_allclose(glc1, glc0, rtol=1e-4)
        np.testing.assert_allclose(ace1, ace0, rtol=1e-4)

    def test_cross_species_bin_sharing_no_overdraw(self):
        """Two species co-located in one nearly-empty bin must split it
        (combined occupancy), not each take the whole content."""
        lattice = Lattice(
            molecules=["glucose"],
            shape=(4, 4),
            size=(4.0, 4.0),
            diffusion=0.0,
            initial=0.1,          # scarce
            timestep=1.0,
        )

        def greedy_species():
            comp = Compartment(
                processes={
                    # vmax far above the bin content: uptake would
                    # overdraw without sharing
                    "transport": MichaelisMentenTransport(
                        {"vmax": 10.0, "km": 1e-6, "yield_": 1.0,
                         "k_consume": 0.0}
                    ),
                    "motility": BrownianMotility({"sigma": 0.0}),
                },
                topology={
                    "transport": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                        "exchange": ("boundary", "exchange"),
                    },
                    "motility": {"boundary": ("boundary",)},
                },
            )
            return SpatialColony(
                Colony(comp, 4),
                lattice,
                field_ports={
                    "glucose": (
                        ("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange"),
                    )
                },
                location_path=("boundary", "location"),
            )

        multi = MultiSpeciesColony(
            species={"a": greedy_species(), "b": greedy_species()},
            lattice=lattice,
        )
        same_bin = np.zeros((4, 2), np.float32)
        same_bin[:] = [1.5, 1.5]
        ms = multi.initial_state(
            {"a": 1, "b": 1},
            jax.random.PRNGKey(3),
            locations={"a": same_bin, "b": same_bin},
        )
        total0 = float(multi.total_field_mass(ms)[0])
        out = multi.step(ms, 1.0)
        pools = sum(
            float(jnp.sum(out.species[s].agents["cell"]["glucose_internal"]
                          * out.species[s].alive))
            for s in ("a", "b")
        )
        total1 = float(multi.total_field_mass(out)[0]) + pools
        np.testing.assert_allclose(total1, total0, rtol=1e-5)
        # and the bin was actually drained cooperatively (both got half)
        pa = float(out.species["a"].agents["cell"]["glucose_internal"][0])
        pb = float(out.species["b"].agents["cell"]["glucose_internal"][0])
        np.testing.assert_allclose(pa, pb, rtol=1e-5)
        assert pa > 0

    def test_divisions_stay_within_species(self):
        multi, _ = small_mixed(
            extra={
                "ecoli": {"growth": {"rate": 0.2}},
                "scavenger": {"growth": {"rate": 0.0}},
            }
        )
        ms = multi.initial_state(
            {"ecoli": 4, "scavenger": 4}, jax.random.PRNGKey(4)
        )
        out, _ = multi.run(ms, 10.0, 1.0, emit_every=10)
        n = multi.n_alive(out)
        assert int(n["ecoli"]) > 4
        assert int(n["scavenger"]) == 4

    def test_registry_and_emits(self):
        assert "mixed_species_lattice" in composite_registry
        multi, _ = small_mixed(division=False)
        ms = multi.initial_state(
            {"ecoli": 4, "scavenger": 4}, jax.random.PRNGKey(5)
        )
        _, traj = multi.run(ms, 4.0, 1.0, emit_every=2)
        assert "fields" in traj
        assert "alive" in traj["ecoli"]
        assert traj["scavenger"]["alive"].shape[0] == 2  # two emit frames

    def test_lattice_identity_validated(self):
        multi, _ = small_mixed()
        other = Lattice(molecules=["glucose", "acetate"], shape=(16, 16),
                        size=(16.0, 16.0), timestep=1.0)
        sp = next(iter(multi.species.values()))
        with pytest.raises(ValueError, match="share one"):
            MultiSpeciesColony(species={"x": sp}, lattice=other)


class TestMultiSpeciesTimeline:
    """Media timelines on the shared multi-species lattice: one
    run_media_timeline helper drives all three colony forms."""

    def build(self):
        from lens_tpu.models.composites import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {
                "capacity": {"ecoli": 8, "scavenger": 8},
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "ecoli": {"motility": {"sigma": 0.0}},
                "scavenger": {"motility": {"sigma": 0.0}},
            }
        )
        return multi

    def test_media_shift_resets_shared_fields(self):
        import jax

        multi = self.build()
        ms = multi.initial_state(
            {"ecoli": 4, "scavenger": 4}, jax.random.PRNGKey(0)
        )
        ms, traj = multi.run_timeline(
            ms, "0 minimal, 6 minimal_low_glucose", 12.0, 1.0, emit_every=2
        )
        glc = multi.lattice.index("glucose")
        fields = np.asarray(traj["fields"])
        assert fields[1, glc].mean() > 5.0      # glucose era
        assert fields[3, glc].mean() < 1.0      # reset to 0.5 mM era
        # both species' trajectories keep flowing through the shift
        for name in ("ecoli", "scavenger"):
            assert np.asarray(traj[name]["alive"]).shape[0] == 6

    def test_experiment_runs_multi_timeline(self):
        from lens_tpu.experiment import Experiment

        with Experiment(
            {
                "composite": "mixed_species_lattice",
                "config": {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                    "ecoli": {"motility": {"sigma": 0.0}},
                    "scavenger": {"motility": {"sigma": 0.0}},
                },
                "n_agents": {"ecoli": 4, "scavenger": 4},
                "total_time": 12.0,
                "checkpoint_every": 6.0,   # segment boundary ON the event
                "timeline": "0 minimal, 6 minimal_low_glucose",
            }
        ) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        glc = exp.multi.lattice.index("glucose")
        fields = np.asarray(ts["fields"])
        assert fields[2, glc].mean() > 5.0
        assert fields[-1, glc].mean() < 1.0
        assert int(np.asarray(state.species["ecoli"].alive).sum()) == 4


class TestRfbaCrossFeeding:
    """Network-scale syntrophy: the rFBA species' overflow acetate is the
    scavenger's ONLY food source."""

    def _build(self):
        from lens_tpu.models.composites import rfba_cross_feeding

        return rfba_cross_feeding(
            {
                "capacity": {"ecoli": 8, "scavenger": 8},
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "ecoli": {"motility": {"sigma": 0.0}},
                "scavenger": {"motility": {"sigma": 0.0}},
            }
        )

    def test_overflow_feeds_the_scavenger(self):
        import jax

        multi, _ = self._build()
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0)
        )
        ace_idx = multi.lattice.molecules.index("ace")
        assert float(ms.fields[ace_idx].sum()) == 0.0  # empty at start
        ms, traj = jax.jit(
            lambda s: multi.run(s, 30.0, 1.0, emit_every=10)
        )(ms)
        # the rFBA species overflowed: acetate appeared in the field
        ace_field = np.asarray(traj["fields"])[:, ace_idx]
        assert ace_field.sum(axis=(1, 2))[-1] > 0.0
        # ...and the scavenger ate some of it (internal pool grew from 0)
        pool = np.asarray(
            ms.species["scavenger"].agents["cell"]["ace_internal"]
        )
        alive = np.asarray(ms.species["scavenger"].alive)
        assert float(pool[alive].max()) > 0.0
        # glucose only fell (the rFBA species ate it)
        glc_idx = multi.lattice.molecules.index("glc")
        glc_series = np.asarray(traj["fields"])[:, glc_idx].sum(axis=(1, 2))
        assert glc_series[-1] < glc_series[0]

    def test_runs_through_experiment_layer(self):
        from lens_tpu.experiment import Experiment

        with Experiment(
            {
                "composite": "rfba_cross_feeding",
                "config": {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                },
                "n_agents": {"ecoli": 4, "scavenger": 4},
                "total_time": 10.0,
                "emit_every": 5,
            }
        ) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        assert int(np.asarray(exp.n_alive(state))) == 8
        assert np.isfinite(np.asarray(ts["fields"])).all()

    def test_scavenger_starvation_tracks_food_supply(self):
        """Death wired to the food pool (('cell','die') via topology):
        scavengers with a small boot yolk survive while the rFBA species
        overflows acetate, and starve to extinction without it."""
        import jax

        from lens_tpu.models.composites import rfba_cross_feeding

        def build():
            return rfba_cross_feeding(
                {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                    "ecoli": {"motility": {"sigma": 0.0}},
                    "scavenger": {
                        "motility": {"sigma": 0.0},
                        "death": {},
                    },
                }
            )

        multi, _ = build()
        assert multi.species["scavenger"].colony.death_trigger == (
            "cell", "die",
        )
        yolk = {"scavenger": {"cell": {"ace_internal": 0.05}}}

        # fed: overflow keeps the pool above the starvation threshold
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0),
            overrides=yolk,
        )
        ms, _ = jax.jit(lambda s: multi.run(s, 80.0, 1.0, emit_every=80))(ms)
        fed_alive = int(np.asarray(ms.species["scavenger"].alive).sum())
        assert fed_alive == 8

        # starved: no E. coli, no acetate ever — the yolk drains and the
        # whole scavenger population dies
        multi2, _ = build()
        ms2 = multi2.initial_state(
            {"ecoli": 0, "scavenger": 8}, jax.random.PRNGKey(0),
            overrides=yolk,
        )
        ms2, traj2 = jax.jit(
            lambda s: multi2.run(s, 80.0, 1.0, emit_every=20)
        )(ms2)
        starved = np.asarray(traj2["scavenger"]["alive"]).sum(axis=1)
        assert starved[-1] == 0
        assert (np.diff(starved) <= 0).all()

    def test_scavenger_lysis_recycles_acetate(self):
        """Death with lysis in the multi-species form: a starving
        scavenger's acetate pool returns to the SHARED field, where any
        survivor (or the rFBA species' regulation) can see it."""
        import jax

        from lens_tpu.models.composites import rfba_cross_feeding

        multi, _ = rfba_cross_feeding(
            {
                "capacity": {"ecoli": 8, "scavenger": 8},
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "ecoli": {"motility": {"sigma": 0.0}},
                "scavenger": {
                    "motility": {"sigma": 0.0},
                    # no consumption drain: the yolk persists until death,
                    # and the bloat trigger fires once overflow feeds the
                    # pool past the threshold — lysis then returns BOTH
                    # the yolk and the eaten overflow to the shared field
                    "transport": {"k_consume": 0.0},
                    "death": {"when": "above", "threshold": 0.08,
                              "lysis": 1.0},
                },
            }
        )
        yolk = {"scavenger": {"cell": {"ace_internal": 0.05}}}
        ms = multi.initial_state(
            {"ecoli": 8, "scavenger": 8}, jax.random.PRNGKey(0),
            overrides=yolk,
        )
        ace = multi.lattice.molecules.index("ace")
        ms, traj = jax.jit(
            lambda s: multi.run(s, 60.0, 1.0, emit_every=10)
        )(ms)
        scav_alive = np.asarray(traj["scavenger"]["alive"]).sum(axis=1)
        assert scav_alive[-1] < 8  # overflow fed them past the threshold
        # every dead scavenger's pool went back to the field, not into a
        # frozen row: dead rows' pools read (post-lysis) zero
        pools = np.asarray(ms.species["scavenger"].agents["cell"]["ace_internal"])
        dead = ~np.asarray(ms.species["scavenger"].alive)
        assert (pools[dead] <= 1e-6).all()
        # and the shared acetate field holds the recycled mass (overflow
        # secretion + returned yolks) — strictly more than overflow alone
        # would leave if the yolks had been deleted with the rows
        assert float(np.asarray(ms.fields[ace]).sum()) > 0.0

    def test_default_death_config_does_not_kill_at_boot(self):
        """death: {} must be survivable out of the box: boot cells get a
        default yolk (5x threshold) so the starvation trigger cannot
        fire before the first meal."""
        import jax

        from lens_tpu.models.composites import rfba_cross_feeding

        multi, _ = rfba_cross_feeding(
            {
                "capacity": {"ecoli": 4, "scavenger": 4},
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "scavenger": {"death": {}},
            }
        )
        ms = multi.initial_state(
            {"ecoli": 4, "scavenger": 4}, jax.random.PRNGKey(0)
        )
        pool0 = np.asarray(ms.species["scavenger"].agents["cell"]["ace_internal"])
        assert (pool0[:4] >= 0.05 - 1e-9).all()  # the yolk
        ms = jax.jit(lambda s: multi.step(s, 1.0))(ms)
        assert int(np.asarray(ms.species["scavenger"].alive).sum()) == 4


class TestFusedCouplingMultiSpecies:
    """coupling="fused" vs "reference" for the mixed-species step: one
    flat bin map + combined occupancy + one exchange segment-sum across
    ALL species must be bitwise the per-molecule oracle."""

    def _build(self, coupling):
        from lens_tpu.models.composites import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {
                "capacity": {"ecoli": 32, "scavenger": 32},
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "ecoli": {"growth": {"rate": 0.05}},
                "coupling": coupling,
            }
        )
        return multi

    def test_fused_matches_reference_bitwise(self):
        outs = {}
        for coupling in ("fused", "reference"):
            multi = self._build(coupling)
            assert multi.coupling == coupling
            for sp in multi.species.values():
                assert sp.coupling == coupling
            ms = multi.initial_state(
                {"ecoli": 12, "scavenger": 8}, jax.random.PRNGKey(11)
            )
            outs[coupling] = multi.run(ms, 20.0, 1.0, emit_every=5)
        fa = sorted(
            jax.tree_util.tree_flatten_with_path(outs["fused"])[0],
            key=lambda kv: str(kv[0]),
        )
        fb = sorted(
            jax.tree_util.tree_flatten_with_path(outs["reference"])[0],
            key=lambda kv: str(kv[0]),
        )
        assert len(fa) == len(fb)
        for (pa, la), (pb, lb) in zip(fa, fb):
            np.testing.assert_array_equal(
                np.asarray(la), np.asarray(lb), err_msg=str(pa)
            )
        # the run genuinely exercised dynamics: divisions happened
        alive = sum(
            int(np.asarray(cs.alive).sum())
            for cs in outs["fused"][0].species.values()
        )
        assert alive > 20
