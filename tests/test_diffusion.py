"""Diffusion stencil: conservation, physics, numpy parity, Pallas parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.ops.diffusion import (
    _tile_rows,
    diffuse,
    diffuse_pallas,
    diffuse_pallas_tiled,
    diffuse_xla,
    stable_substeps,
)


def numpy_ftcs(f, alpha, n):
    """Brute-force reference stencil (edge-clamped Neumann)."""
    f = np.array(f, dtype=np.float64)
    for _ in range(n):
        up = np.concatenate([f[:, :1, :], f[:, :-1, :]], axis=1)
        down = np.concatenate([f[:, 1:, :], f[:, -1:, :]], axis=1)
        left = np.concatenate([f[:, :, :1], f[:, :, :-1]], axis=2)
        right = np.concatenate([f[:, :, 1:], f[:, :, -1:]], axis=2)
        f = f + alpha[:, None, None] * (up + down + left + right - 4 * f)
    return f


def make_field(h=32, w=32, m=2, seed=0):
    key = jax.random.PRNGKey(seed)
    return jax.random.uniform(key, (m, h, w), minval=0.0, maxval=10.0)


def test_mass_conservation():
    f = make_field()
    alpha = jnp.array([0.2, 0.1])
    out = diffuse_xla(f, alpha, 50)
    np.testing.assert_allclose(
        np.asarray(jnp.sum(out, axis=(1, 2))),
        np.asarray(jnp.sum(f, axis=(1, 2))),
        rtol=1e-5,
    )


def test_matches_numpy_reference():
    f = make_field()
    alpha = np.array([0.2, 0.05])
    out = diffuse_xla(f, jnp.asarray(alpha, jnp.float32), 10)
    ref = numpy_ftcs(np.asarray(f), alpha, 10)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_point_source_spreads_symmetrically():
    h = w = 33
    f = jnp.zeros((1, h, w)).at[0, 16, 16].set(100.0)
    out = diffuse_xla(f, jnp.array([0.25]), 40)
    a = np.asarray(out[0])
    # symmetric in all four directions
    np.testing.assert_allclose(a[16 - 5, 16], a[16 + 5, 16], rtol=1e-5)
    np.testing.assert_allclose(a[16, 16 - 5], a[16, 16 + 5], rtol=1e-5)
    np.testing.assert_allclose(a[16 - 3, 16], a[16, 16 - 3], rtol=1e-5)
    # peak decays
    assert a[16, 16] < 100.0
    assert a.min() >= 0.0


def test_uniform_field_is_fixed_point():
    f = jnp.full((1, 16, 16), 3.7)
    out = diffuse_xla(f, jnp.array([0.2]), 25)
    np.testing.assert_allclose(np.asarray(out), 3.7, rtol=1e-6)


def test_pallas_interpret_matches_xla():
    f = make_field(h=16, w=16)
    alpha = jnp.array([0.2, 0.1])
    a = diffuse_xla(f, alpha, 8)
    b = diffuse_pallas(f, alpha, 8, interpret=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


class TestTiledKernel:
    """Halo-overlap row tiling (the beyond-VMEM Pallas path): the valid
    center of every tile must match the untiled stencil exactly — the
    halo equals the substep count, so staleness never reaches it, and
    mirror extension reproduces the edge-clamped Neumann boundary."""

    def test_matches_xla_divisible(self):
        f = make_field(h=64, w=16)
        alpha = jnp.array([0.2, 0.1])
        a = diffuse_xla(f, alpha, 5)
        b = diffuse_pallas_tiled(f, alpha, 5, tile_h=16, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_matches_xla_ragged_height(self):
        """h not a multiple of tile_h: the last tile overhangs into
        mirrored rows that the final slice discards."""
        f = make_field(h=40, w=24, m=3, seed=2)
        alpha = jnp.array([0.22, 0.05, 0.13])
        a = diffuse_xla(f, alpha, 6)
        b = diffuse_pallas_tiled(f, alpha, 6, tile_h=16, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_single_tile_degenerates_cleanly(self):
        f = make_field(h=32, w=16, m=1)
        alpha = jnp.array([0.19])
        a = diffuse_xla(f, alpha, 4)
        b = diffuse_pallas_tiled(f, alpha, 4, tile_h=32, interpret=True)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)

    def test_mass_conservation(self):
        f = make_field(h=48, w=16)
        alpha = jnp.array([0.2, 0.1])
        out = diffuse_pallas_tiled(f, alpha, 8, tile_h=16, interpret=True)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(out, axis=(1, 2))),
            np.asarray(jnp.sum(f, axis=(1, 2))),
            rtol=1e-5,
        )

    def test_tile_sizer_and_guards(self):
        # 1024-wide f32: padded row = 4 KiB; budget 14 MiB / 6 slabs
        t = _tile_rows(4096, 1024, 27, 4)
        assert t is not None and t % 8 == 0
        assert (t + 2 * 27) * 1024 * 4 * 6 <= 14 * 1024 * 1024
        # halo too large for the field height -> explicit error
        f = make_field(h=16, w=16, m=1)
        with pytest.raises(ValueError, match="halo"):
            diffuse_pallas_tiled(f, jnp.array([0.1]), 16, tile_h=8,
                                 interpret=True)

    def test_dispatch_names(self):
        f = make_field(h=40, w=16, m=1)
        out = diffuse(f, jnp.array([0.2]), 4, impl="pallas_tiled_interpret")
        ref = diffuse(f, jnp.array([0.2]), 4, impl="xla")
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_vmem_guard():
    """`auto` must not route slabs beyond the VMEM budget to the Pallas
    kernel. The budget models the kernel's REAL working set (~6 slabs:
    in/out blocks + the four shifted stencil copies — measured 23.8 MiB
    of scoped VMEM for a 4 MiB slab on v5e), so 1024^2 f32 must NOT fit."""
    from lens_tpu.ops.diffusion import (
        _VMEM_BUDGET_BYTES,
        _VMEM_KERNEL_SLABS,
        _fits_vmem,
    )

    ok = jnp.zeros((1, 256, 256), jnp.float32)
    too_big = jnp.zeros((1, 1024, 1024), jnp.float32)  # 6 * 4 MiB > 14 MiB
    assert _fits_vmem(ok)
    assert not _fits_vmem(too_big)
    # padding to the (8, 128) tile is accounted for: 608x1000 pads to
    # 608x1024, which crosses the budget though the raw slab squeaks under
    padded = jnp.zeros((1, 608, 1000), jnp.float32)
    assert _VMEM_KERNEL_SLABS * 608 * 1000 * 4 <= _VMEM_BUDGET_BYTES
    assert _VMEM_KERNEL_SLABS * 608 * 1024 * 4 > _VMEM_BUDGET_BYTES
    assert not _fits_vmem(padded)


def test_dispatch_and_stability_helper():
    assert stable_substeps(0.0, 1.0, 1.0) == 1
    # alpha = 600*1/25 = 24 -> needs >= 24/0.225 ~ 107 substeps
    n = stable_substeps(600.0, 1.0, 5.0)
    assert 600.0 * 1.0 / 25.0 / n <= 0.25
    f = make_field(m=1)
    out = diffuse(f, jnp.array([0.2]), 4, impl="xla")
    assert out.shape == f.shape
