"""The ``python -m lens_tpu`` CLI: argument parsing + command smoke runs.

The command surface is the repo's outermost contract (the reference's
control/boot scripts, reconstructed SURVEY.md §3.1) and was previously
untested end to end. Parsing tests are jax-free and instant; the smoke
runs drive the real ``main()`` on the tiniest composites — ``run``,
``serve``, and ``sweep`` each produce their documented artifacts.
"""

import json
import os

import pytest

from lens_tpu.__main__ import _build_parser, _validate_run_args, main


class TestParsing:
    def test_run_defaults_and_overrides(self):
        args = _build_parser().parse_args(
            ["run", "--composite", "toggle_colony", "--time", "50",
             "--n-agents", "3", "--emitter", "log",
             "--out-dir", "out/x"]
        )
        assert args.command == "run"
        assert args.composite == "toggle_colony"
        assert args.time == 50.0
        assert args.n_agents == 3
        assert args.emitter == "log"

    def test_run_n_agents_accepts_per_species_json(self):
        args = _build_parser().parse_args(
            ["run", "--n-agents", '{"ecoli": 4, "scavenger": 2}']
        )
        assert args.n_agents == {"ecoli": 4, "scavenger": 2}

    def test_run_mesh_spec(self):
        args = _build_parser().parse_args(["run", "--mesh", "4x2"])
        assert args.mesh == {"agents": 4, "space": 2}
        with pytest.raises(SystemExit):
            _build_parser().parse_args(["run", "--mesh", "axb"])

    def test_validate_rejects_bad_flag_combinations(self):
        # auto-expand without segments would silently do nothing
        args = _build_parser().parse_args(
            ["run", "--auto-expand", "0.3"]
        )
        with pytest.raises(SystemExit, match="checkpoint-every"):
            _validate_run_args(args)
        # replicate-overrides needs the scan axis
        args = _build_parser().parse_args(
            ["run", "--replicate-overrides", '{"global": {"volume": [1]}}']
        )
        with pytest.raises(SystemExit, match="--replicates"):
            _validate_run_args(args)
        args = _build_parser().parse_args(
            ["run", "--replicates", "2", "--replicate-overrides", "not json"]
        )
        with pytest.raises(SystemExit, match="not valid JSON"):
            _validate_run_args(args)

    def test_serve_args(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "reqs.json", "--lanes", "8",
             "--window", "16", "--queue-depth", "7"]
        )
        assert args.command == "serve"
        assert (args.lanes, args.window, args.queue_depth) == (8, 16, 7)
        # pipeline knobs default on with depth 2, per-window flush
        assert (args.pipeline, args.stream_queue, args.flush_every) \
            == ("on", 2, 1)
        with pytest.raises(SystemExit):  # --requests is required
            _build_parser().parse_args(["serve"])

    def test_serve_pipeline_args(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json", "--pipeline", "off",
             "--stream-queue", "4", "--flush-every", "8"]
        )
        assert args.pipeline == "off"
        assert args.stream_queue == 4
        assert args.flush_every == 8
        with pytest.raises(SystemExit):  # only on|off
            _build_parser().parse_args(
                ["serve", "--requests", "r.json", "--pipeline", "maybe"]
            )

    def test_serve_snapshot_budget_arg(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.snapshot_budget_mb == 256.0
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--snapshot-budget-mb", "16.5"]
        )
        assert args.snapshot_budget_mb == 16.5

    def test_serve_fault_tolerance_args(self):
        """Round 12: quarantine / watchdog / WAL / fault-plan flags."""
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.check_finite == "off"      # bitwise r11 default
        assert args.watchdog is None
        assert args.recover_dir is None
        assert args.faults is None
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--check-finite", "window", "--watchdog", "2.5",
             "--recover-dir", "out/wal", "--faults", "faults.json"]
        )
        assert args.check_finite == "window"
        assert args.watchdog == 2.5
        assert args.recover_dir == "out/wal"
        assert args.faults == "faults.json"
        with pytest.raises(SystemExit):  # only off|window
            _build_parser().parse_args(
                ["serve", "--requests", "r.json",
                 "--check-finite", "sometimes"]
            )

    def test_sweep_args(self):
        args = _build_parser().parse_args(
            ["sweep", "--spec", "sweep.json", "--out-dir", "out/s",
             "--resume", "--save-trajectories"]
        )
        assert args.command == "sweep"
        assert args.spec == "sweep.json"
        assert args.resume and args.save_trajectories
        with pytest.raises(SystemExit):  # --spec is required
            _build_parser().parse_args(["sweep"])

    def test_unknown_command_rejected(self):
        with pytest.raises(SystemExit):
            _build_parser().parse_args(["deploy"])


class TestListCommand:
    def test_lists_registries(self, capsys):
        assert main(["list"]) == 0
        out = capsys.readouterr().out
        assert "toggle_colony" in out
        assert "log" in out


class TestRunCommand:
    def test_run_smoke_writes_emit_log(self, tmp_path, capsys):
        out = str(tmp_path / "exp")
        rc = main([
            "run", "--composite", "minimal_ode", "--time", "4",
            "--capacity", "4", "--emitter", "log", "--out-dir", out,
            "--quiet",
        ])
        assert rc == 0
        assert "done:" in capsys.readouterr().out
        assert os.path.exists(os.path.join(out, "emit.lens"))


class TestServeCommand:
    def test_serve_smoke_writes_results_and_meta(self, tmp_path, capsys):
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([
            {"seed": 1, "horizon": 8.0},
            {"seed": 2, "horizon": 16.0,
             "emit": {"paths": ["alive"]}},
        ]))
        out = str(tmp_path / "served")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 2 requests" in printed
        assert "done=2" in printed
        assert os.path.exists(os.path.join(out, "server_meta.json"))
        lens = [f for f in os.listdir(out) if f.endswith(".lens")]
        assert len(lens) == 2
        # the pipelined default surfaces its gauges in the summary
        assert "device_busy=" in printed

    def test_serve_smoke_prefix_requests(self, tmp_path, capsys):
        """Requests declaring a shared prefix fork one cached snapshot
        (round 11); the summary line surfaces the cache counters."""
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([
            {"seed": 1, "horizon": 8.0, "prefix": {"horizon": 4.0}},
            {"seed": 1, "horizon": 8.0, "prefix": {"horizon": 4.0},
             "overrides": {"cell": {"glucose_internal": 0.2}}},
        ]))
        out = str(tmp_path / "served_prefix")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--snapshot-budget-mb", "32",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 2 requests" in printed
        assert "done=2" in printed
        assert "prefix cache:" in printed
        assert "misses=1" in printed
        with open(os.path.join(out, "server_meta.json")) as f:
            meta = json.load(f)
        assert meta["counters"]["prefix_misses"] == 1
        assert meta["counters"]["prefix_forks"] == 2

    def test_serve_smoke_pipeline_off(self, tmp_path, capsys):
        """The synchronous knob serves the same request list and writes
        the same artifacts (the debugging path stays usable end to
        end)."""
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([{"seed": 1, "horizon": 8.0}]))
        out = str(tmp_path / "served_sync")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4", "--pipeline", "off",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        assert "served 1 requests" in capsys.readouterr().out
        assert os.path.exists(os.path.join(out, "server_meta.json"))


class TestServeEagerValidation:
    """Round 12 satellite: malformed request JSON fails at submit with
    a descriptive SystemExit — not a FAILED ticket from deep inside
    admission compile, and never a half-served list."""

    def _serve(self, tmp_path, reqs, extra=()):
        path = tmp_path / "reqs.json"
        path.write_text(json.dumps(reqs))
        return main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(path),
            "--out-dir", str(tmp_path / "served"), *extra,
        ])

    def test_unknown_request_key_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown request keys"):
            self._serve(tmp_path, [{"seed": 1, "horizont": 8.0}])

    def test_unknown_override_path_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="not a schema variable"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "overrides": {"cell": {"glucose_internol": 0.2}}},
            ])

    def test_malformed_emit_block_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown emit keys"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "emit": {"path": ["alive"]}},  # 'paths', not 'path'
            ])
        with pytest.raises(SystemExit, match="list of path-prefix"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0, "emit": {"paths": "alive"}},
            ])

    def test_malformed_prefix_block_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown prefix keys"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "prefix": {"horizon": 4.0, "override": {}}},
            ])
        with pytest.raises(SystemExit, match="prefix override path"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "prefix": {"horizon": 4.0,
                            "overrides": {"cell": {"nope": 1.0}}}},
            ])

    def test_out_of_range_n_agents_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="bucket capacity"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0, "n_agents": 99},
            ])

    def test_bad_faults_plan_rejected(self, tmp_path):
        bad = tmp_path / "faults.json"
        bad.write_text(json.dumps([{"kind": "explode"}]))
        with pytest.raises(SystemExit, match="unknown kind"):
            self._serve(
                tmp_path, [{"seed": 1, "horizon": 8.0}],
                extra=("--faults", str(bad)),
            )

    def test_sweep_inherits_eager_validation(self, tmp_path):
        """The sweep's server backend submits through the same eager
        checks: a bad override path in the space fails the FIRST
        submit descriptively, not an admission compile later."""
        from lens_tpu.sweep import run_sweep

        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_externol": {"grid": [0.5, 1.0]},
            }},
            "horizon": 8.0,
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        with pytest.raises(ValueError, match="not a schema variable"):
            run_sweep(spec)


class TestServeRecoveryFlags:
    def test_serve_writes_wal_when_recover_dir_given(
        self, tmp_path, capsys
    ):
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([{"seed": 1, "horizon": 8.0}]))
        out = str(tmp_path / "served")
        wal = str(tmp_path / "wal")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
            "--recover-dir", wal, "--check-finite", "window",
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 1 requests" in printed
        assert os.path.exists(os.path.join(wal, "serve.wal"))
        assert "serve.wal" in printed
        # a second invocation over the same dirs recovers: everything
        # already finished, so it submits nothing and reports the
        # replayed request as done
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
            "--recover-dir", wal,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "recovered 1 request(s)" in printed
        assert "done=1" in printed


class TestSweepCommand:
    def _spec(self, tmp_path):
        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_external": {"grid": [0.5, 1.0, 2.0]},
            }},
            "horizon": 8.0,
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        path = tmp_path / "sweep.json"
        path.write_text(json.dumps(spec))
        return str(path)

    def test_sweep_smoke_writes_table_and_ledger(self, tmp_path, capsys):
        out = str(tmp_path / "sweep")
        rc = main([
            "sweep", "--spec", self._spec(tmp_path), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "sweep: 3 trials (done=3)" in printed
        assert "best: trial 2" in printed
        with open(os.path.join(out, "sweep_result.json")) as f:
            table = json.load(f)
        assert len(table["table"]) == 3
        assert table["best"]["trial"] == 2
        assert os.path.exists(os.path.join(out, "sweep.ledger"))
        # a complete sweep resumes as a no-op, same exit code
        rc = main([
            "sweep", "--spec", self._spec(tmp_path), "--out-dir", out,
            "--resume", "--quiet",
        ])
        assert rc == 0
        assert "done=3" in capsys.readouterr().out

    def test_sweep_warmup_spec_through_cli(self, tmp_path, capsys):
        """A spec-level warmup block rides the CLI unchanged: trials
        share one warmed snapshot (docs/sweeps.md, 'Shared warmup')."""
        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_external": {"grid": [0.5, 1.0, 2.0]},
            }},
            "horizon": 8.0,
            "warmup": {"horizon": 4.0},
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        path = tmp_path / "warm.json"
        path.write_text(json.dumps(spec))
        out = str(tmp_path / "warm_sweep")
        rc = main(["sweep", "--spec", str(path), "--out-dir", out])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "sweep: 3 trials (done=3)" in printed
        assert "best: trial 2" in printed
        with open(os.path.join(out, "sweep_result.json")) as f:
            table = json.load(f)
        assert table["spec"]["warmup"] == {"horizon": 4.0}

    def test_sweep_save_trajectories_needs_out_dir(self, tmp_path):
        with pytest.raises(SystemExit, match="out-dir"):
            main(["sweep", "--spec", self._spec(tmp_path),
                  "--save-trajectories"])

    def test_sweep_resume_needs_out_dir(self, tmp_path):
        """--resume without the ledger directory must refuse, not
        silently re-run everything against an in-memory ledger."""
        with pytest.raises(SystemExit, match="out-dir"):
            main(["sweep", "--spec", self._spec(tmp_path), "--resume"])

    def test_sweep_rejects_non_object_spec(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("[1, 2, 3]")
        with pytest.raises(SystemExit, match="JSON object"):
            main(["sweep", "--spec", str(bad)])
