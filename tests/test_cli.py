"""The ``python -m lens_tpu`` CLI: argument parsing + command smoke runs.

The command surface is the repo's outermost contract (the reference's
control/boot scripts, reconstructed SURVEY.md §3.1) and was previously
untested end to end. Parsing tests are jax-free and instant; the smoke
runs drive the real ``main()`` on the tiniest composites — ``run``,
``serve``, and ``sweep`` each produce their documented artifacts.
"""

import json
import os

import pytest

from lens_tpu.__main__ import _build_parser, _validate_run_args, main


class TestParsing:
    def test_run_defaults_and_overrides(self):
        args = _build_parser().parse_args(
            ["run", "--composite", "toggle_colony", "--time", "50",
             "--n-agents", "3", "--emitter", "log",
             "--out-dir", "out/x"]
        )
        assert args.command == "run"
        assert args.composite == "toggle_colony"
        assert args.time == 50.0
        assert args.n_agents == 3
        assert args.emitter == "log"

    def test_run_n_agents_accepts_per_species_json(self):
        args = _build_parser().parse_args(
            ["run", "--n-agents", '{"ecoli": 4, "scavenger": 2}']
        )
        assert args.n_agents == {"ecoli": 4, "scavenger": 2}

    def test_run_mesh_spec(self):
        args = _build_parser().parse_args(["run", "--mesh", "4x2"])
        assert args.mesh == {"agents": 4, "space": 2}
        with pytest.raises(SystemExit):
            _build_parser().parse_args(["run", "--mesh", "axb"])

    def test_validate_rejects_bad_flag_combinations(self):
        # auto-expand without segments would silently do nothing
        args = _build_parser().parse_args(
            ["run", "--auto-expand", "0.3"]
        )
        with pytest.raises(SystemExit, match="checkpoint-every"):
            _validate_run_args(args)
        # replicate-overrides needs the scan axis
        args = _build_parser().parse_args(
            ["run", "--replicate-overrides", '{"global": {"volume": [1]}}']
        )
        with pytest.raises(SystemExit, match="--replicates"):
            _validate_run_args(args)
        args = _build_parser().parse_args(
            ["run", "--replicates", "2", "--replicate-overrides", "not json"]
        )
        with pytest.raises(SystemExit, match="not valid JSON"):
            _validate_run_args(args)

    def test_serve_args(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "reqs.json", "--lanes", "8",
             "--window", "16", "--queue-depth", "7"]
        )
        assert args.command == "serve"
        assert (args.lanes, args.window, args.queue_depth) == (8, 16, 7)
        # pipeline knobs default on with depth 2, per-window flush
        assert (args.pipeline, args.stream_queue, args.flush_every) \
            == ("on", 2, 1)
        with pytest.raises(SystemExit):  # --requests is required
            _build_parser().parse_args(["serve"])

    def test_serve_pipeline_args(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json", "--pipeline", "off",
             "--stream-queue", "4", "--flush-every", "8"]
        )
        assert args.pipeline == "off"
        assert args.stream_queue == 4
        assert args.flush_every == 8
        with pytest.raises(SystemExit):  # only on|off
            _build_parser().parse_args(
                ["serve", "--requests", "r.json", "--pipeline", "maybe"]
            )

    def test_serve_snapshot_budget_arg(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.snapshot_budget_mb == 256.0
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--snapshot-budget-mb", "16.5"]
        )
        assert args.snapshot_budget_mb == 16.5

    def test_serve_tier_and_warm_args(self):
        # defaults: tiers and warming off (the round-15 serve shape)
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.host_budget_mb is None
        assert args.tier_dir is None
        assert args.warm is False
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--host-budget-mb", "64", "--tier-dir", "/tmp/tier",
             "--warm"]
        )
        assert args.host_budget_mb == 64.0
        assert args.tier_dir == "/tmp/tier"
        assert args.warm is True
        # frontdoor shares the server knob set, warming included
        args = _build_parser().parse_args(
            ["frontdoor", "--host-budget-mb", "8", "--warm"]
        )
        assert args.host_budget_mb == 8.0 and args.warm is True

    def test_serve_fault_tolerance_args(self):
        """Round 12: quarantine / watchdog / WAL / fault-plan flags."""
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.check_finite == "off"      # bitwise r11 default
        assert args.watchdog is None
        assert args.recover_dir is None
        assert args.faults is None
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--check-finite", "window", "--watchdog", "2.5",
             "--recover-dir", "out/wal", "--faults", "faults.json"]
        )
        assert args.check_finite == "window"
        assert args.watchdog == 2.5
        assert args.recover_dir == "out/wal"
        assert args.faults == "faults.json"
        with pytest.raises(SystemExit):  # only off|window
            _build_parser().parse_args(
                ["serve", "--requests", "r.json",
                 "--check-finite", "sometimes"]
            )

    def test_serve_sink_errors_arg(self):
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json"]
        )
        assert args.sink_errors == "fatal"  # round-14 default
        args = _build_parser().parse_args(
            ["serve", "--requests", "r.json",
             "--sink-errors", "request"]
        )
        assert args.sink_errors == "request"
        with pytest.raises(SystemExit):  # only fatal|request
            _build_parser().parse_args(
                ["serve", "--requests", "r.json",
                 "--sink-errors", "shrug"]
            )

    def test_frontdoor_args(self):
        """Round 15: the HTTP front door subcommand (docs/serving.md,
        'Front door') — bucket + server knobs shared with serve, plus
        the HTTP/tenancy flags."""
        args = _build_parser().parse_args(["frontdoor"])
        assert args.command == "frontdoor"
        assert (args.host, args.port) == ("127.0.0.1", 8080)
        assert args.tenants is None
        assert args.out_dir == "out/frontdoor"
        assert args.drain_grace is None
        # multi-tenant default: sink errors scoped to one request
        assert args.sink_errors == "request"
        # the shared serve knobs ride along with their serve defaults
        assert (args.lanes, args.window, args.queue_depth) == (4, 32, 64)
        assert (args.pipeline, args.snapshot_budget_mb) == ("on", 256.0)
        args = _build_parser().parse_args([
            "frontdoor", "--composite", "minimal_ode", "--port", "0",
            "--host", "0.0.0.0", "--tenants", "tenants.json",
            "--lanes", "8", "--mesh", "2", "--drain-grace", "30",
            "--recover-dir", "out/wal",
        ])
        assert args.port == 0
        assert args.host == "0.0.0.0"
        assert args.tenants == "tenants.json"
        assert (args.lanes, args.mesh, args.drain_grace) == (8, 2, 30.0)
        assert args.recover_dir == "out/wal"

    def test_sweep_args(self):
        args = _build_parser().parse_args(
            ["sweep", "--spec", "sweep.json", "--out-dir", "out/s",
             "--resume", "--save-trajectories"]
        )
        assert args.command == "sweep"
        assert args.spec == "sweep.json"
        assert args.resume and args.save_trajectories
        with pytest.raises(SystemExit):  # --spec is required
            _build_parser().parse_args(["sweep"])

    def test_unknown_command_rejected(self):
        with pytest.raises(SystemExit):
            _build_parser().parse_args(["deploy"])


class TestListCommand:
    def test_lists_registries(self, capsys):
        assert main(["list"]) == 0
        out = capsys.readouterr().out
        assert "toggle_colony" in out
        assert "log" in out


class TestRunCommand:
    def test_run_smoke_writes_emit_log(self, tmp_path, capsys):
        out = str(tmp_path / "exp")
        rc = main([
            "run", "--composite", "minimal_ode", "--time", "4",
            "--capacity", "4", "--emitter", "log", "--out-dir", out,
            "--quiet",
        ])
        assert rc == 0
        assert "done:" in capsys.readouterr().out
        assert os.path.exists(os.path.join(out, "emit.lens"))


class TestServeCommand:
    def test_serve_smoke_writes_results_and_meta(self, tmp_path, capsys):
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([
            {"seed": 1, "horizon": 8.0},
            {"seed": 2, "horizon": 16.0,
             "emit": {"paths": ["alive"]}},
        ]))
        out = str(tmp_path / "served")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 2 requests" in printed
        assert "done=2" in printed
        assert os.path.exists(os.path.join(out, "server_meta.json"))
        lens = [f for f in os.listdir(out) if f.endswith(".lens")]
        assert len(lens) == 2
        # the pipelined default surfaces its gauges in the summary
        assert "device_busy=" in printed

    def test_serve_smoke_prefix_requests(self, tmp_path, capsys):
        """Requests declaring a shared prefix fork one cached snapshot
        (round 11); the summary line surfaces the cache counters."""
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([
            {"seed": 1, "horizon": 8.0, "prefix": {"horizon": 4.0}},
            {"seed": 1, "horizon": 8.0, "prefix": {"horizon": 4.0},
             "overrides": {"cell": {"glucose_internal": 0.2}}},
        ]))
        out = str(tmp_path / "served_prefix")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--snapshot-budget-mb", "32",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 2 requests" in printed
        assert "done=2" in printed
        assert "prefix cache:" in printed
        assert "misses=1" in printed
        with open(os.path.join(out, "server_meta.json")) as f:
            meta = json.load(f)
        assert meta["counters"]["prefix_misses"] == 1
        assert meta["counters"]["prefix_forks"] == 2

    def test_serve_smoke_pipeline_off(self, tmp_path, capsys):
        """The synchronous knob serves the same request list and writes
        the same artifacts (the debugging path stays usable end to
        end)."""
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([{"seed": 1, "horizon": 8.0}]))
        out = str(tmp_path / "served_sync")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4", "--pipeline", "off",
            "--requests", str(reqs), "--out-dir", out,
        ])
        assert rc == 0
        assert "served 1 requests" in capsys.readouterr().out
        assert os.path.exists(os.path.join(out, "server_meta.json"))


class TestServeEagerValidation:
    """Round 12 satellite: malformed request JSON fails at submit with
    a descriptive SystemExit — not a FAILED ticket from deep inside
    admission compile, and never a half-served list."""

    def _serve(self, tmp_path, reqs, extra=()):
        path = tmp_path / "reqs.json"
        path.write_text(json.dumps(reqs))
        return main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(path),
            "--out-dir", str(tmp_path / "served"), *extra,
        ])

    def test_unknown_request_key_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown request keys"):
            self._serve(tmp_path, [{"seed": 1, "horizont": 8.0}])

    def test_unknown_override_path_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="not a schema variable"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "overrides": {"cell": {"glucose_internol": 0.2}}},
            ])

    def test_malformed_emit_block_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown emit keys"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "emit": {"path": ["alive"]}},  # 'paths', not 'path'
            ])
        with pytest.raises(SystemExit, match="list of path-prefix"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0, "emit": {"paths": "alive"}},
            ])

    def test_malformed_prefix_block_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="unknown prefix keys"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "prefix": {"horizon": 4.0, "override": {}}},
            ])
        with pytest.raises(SystemExit, match="prefix override path"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0,
                 "prefix": {"horizon": 4.0,
                            "overrides": {"cell": {"nope": 1.0}}}},
            ])

    def test_out_of_range_n_agents_rejected(self, tmp_path):
        with pytest.raises(SystemExit, match="bucket capacity"):
            self._serve(tmp_path, [
                {"seed": 1, "horizon": 8.0, "n_agents": 99},
            ])

    def test_bad_faults_plan_rejected(self, tmp_path):
        bad = tmp_path / "faults.json"
        bad.write_text(json.dumps([{"kind": "explode"}]))
        with pytest.raises(SystemExit, match="unknown kind"):
            self._serve(
                tmp_path, [{"seed": 1, "horizon": 8.0}],
                extra=("--faults", str(bad)),
            )

    def test_sweep_inherits_eager_validation(self, tmp_path):
        """The sweep's server backend submits through the same eager
        checks: a bad override path in the space fails the FIRST
        submit descriptively, not an admission compile later."""
        from lens_tpu.sweep import run_sweep

        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_externol": {"grid": [0.5, 1.0]},
            }},
            "horizon": 8.0,
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        with pytest.raises(ValueError, match="not a schema variable"):
            run_sweep(spec)


class TestFromMappingFieldPaths:
    """Round-15 satellite: ``ScenarioRequest.from_mapping`` rejects
    every malformed block with a machine-readable field path (the
    front door's structured 400 body) — one case per branch. Jax-free:
    the batcher is plain Python."""

    def _path_of(self, mapping):
        from lens_tpu.serve.batcher import (
            RequestValidationError,
            ScenarioRequest,
        )

        with pytest.raises(RequestValidationError) as e:
            ScenarioRequest.from_mapping(mapping)
        assert str(e.value)  # always a human message too
        return e.value.path

    def test_unknown_key(self):
        assert self._path_of({"composite": "c", "horizont": 1.0}) \
            == "horizont"

    def test_bad_scalar_fields(self):
        assert self._path_of({"composite": 7}) == "composite"
        assert self._path_of({"composite": "c", "seed": "x"}) == "seed"
        assert self._path_of({"composite": "c", "seed": True}) == "seed"
        assert self._path_of({"composite": "c", "horizon": "soon"}) \
            == "horizon"
        assert self._path_of({"composite": "c", "deadline": []}) \
            == "deadline"
        assert self._path_of({"composite": "c", "hold_state": 1}) \
            == "hold_state"
        assert self._path_of({"composite": "c", "tenant": 5}) \
            == "tenant"
        assert self._path_of({"composite": "c", "priority": "vip"}) \
            == "priority"
        assert self._path_of({"composite": "c", "overrides": [1]}) \
            == "overrides"
        assert self._path_of({"composite": "c", "n_agents": "many"}) \
            == "n_agents"

    def test_emit_block_branches(self):
        assert self._path_of({"composite": "c", "emit": "alive"}) \
            == "emit"
        assert self._path_of(
            {"composite": "c", "emit": {"path": ["alive"]}}
        ) == "emit.path"
        assert self._path_of(
            {"composite": "c", "emit": {"every": 0}}
        ) == "emit.every"
        assert self._path_of(
            {"composite": "c", "emit": {"every": "all"}}
        ) == "emit.every"
        assert self._path_of(
            {"composite": "c", "emit": {"paths": "alive"}}
        ) == "emit.paths"
        assert self._path_of(
            {"composite": "c", "emit": {"paths": [1, 2]}}
        ) == "emit.paths"

    def test_prefix_block_branches(self):
        assert self._path_of({"composite": "c", "prefix": 4.0}) \
            == "prefix"
        assert self._path_of(
            {"composite": "c", "prefix": {"horizont": 4.0}}
        ) == "prefix.horizont"
        assert self._path_of({"composite": "c", "prefix": {}}) \
            == "prefix.horizon"
        assert self._path_of(
            {"composite": "c", "prefix": {"horizon": "early"}}
        ) == "prefix.horizon"
        assert self._path_of(
            {"composite": "c",
             "prefix": {"horizon": 4.0, "overrides": [1]}}
        ) == "prefix.overrides"

    def test_valid_mapping_roundtrips(self):
        from lens_tpu.serve.batcher import ScenarioRequest

        r = ScenarioRequest.from_mapping({
            "composite": "c", "seed": 3, "horizon": 8.0,
            "emit": {"paths": ["alive"], "every": 2},
            "prefix": {"horizon": 4.0, "overrides": {}},
            "tenant": "acme", "priority": "interactive",
        })
        assert (r.tenant, r.priority) == ("acme", "interactive")


class TestServeDrain:
    """Round-15 satellite: SIGTERM on a mid-flight ``serve`` drains —
    stops accepting list entries, finishes in-flight requests, closes
    streamer/sinks cleanly and writes server_meta.json — instead of
    relying on crash recovery. Pinned with a real subprocess kill."""

    def test_sigterm_drains_cleanly(self, tmp_path):
        import signal
        import subprocess
        import sys
        import time

        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps(
            [{"seed": i, "horizon": 400.0} for i in range(30)]
        ))
        out = tmp_path / "served"
        env = dict(
            os.environ, JAX_PLATFORMS="cpu",
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "lens_tpu", "serve",
             "--composite", "minimal_ode", "--capacity", "4",
             "--lanes", "2", "--window", "4", "--queue-depth", "4",
             "--requests", str(reqs), "--out-dir", str(out)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        # signal once the server is demonstrably mid-flight (first
        # result log exists), while most of the list is unsubmitted
        # behind the depth-4 queue
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if out.exists() and any(
                f.suffix == ".lens" for f in out.iterdir()
            ):
                break
            if proc.poll() is not None:
                raise AssertionError(
                    f"serve exited early:\n{proc.stdout.read()}"
                )
            time.sleep(0.05)
        else:
            proc.kill()
            raise AssertionError("server never started serving")
        proc.send_signal(signal.SIGTERM)
        stdout, _ = proc.communicate(timeout=180)
        assert proc.returncode == 0, stdout
        assert "drain: caught signal" in stdout
        assert "drain: stopped accepting" in stdout
        assert "never submitted" in stdout
        assert "served" in stdout
        # clean close: the meta sidecar landed and every submitted
        # request has its log; the unsubmitted tail has none
        assert (out / "server_meta.json").exists(), stdout
        with open(out / "server_meta.json") as f:
            meta = json.load(f)
        submitted = meta["counters"]["submitted"]
        assert 0 < submitted < 30
        assert meta["counters"]["retired"] == submitted
        lens = [f for f in out.iterdir() if f.suffix == ".lens"]
        assert len(lens) == submitted


class TestServeRecoveryFlags:
    def test_serve_writes_wal_when_recover_dir_given(
        self, tmp_path, capsys
    ):
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps([{"seed": 1, "horizon": 8.0}]))
        out = str(tmp_path / "served")
        wal = str(tmp_path / "wal")
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
            "--recover-dir", wal, "--check-finite", "window",
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "served 1 requests" in printed
        assert os.path.exists(os.path.join(wal, "serve.wal"))
        assert "serve.wal" in printed
        # a second invocation over the same dirs recovers: everything
        # already finished, so it submits nothing and reports the
        # replayed request as done
        rc = main([
            "serve", "--composite", "minimal_ode", "--capacity", "4",
            "--lanes", "2", "--window", "4",
            "--requests", str(reqs), "--out-dir", out,
            "--recover-dir", wal,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "recovered 1 request(s)" in printed
        assert "done=1" in printed


class TestFrontDoorCommand:
    """``python -m lens_tpu frontdoor``: end-to-end subprocess smoke —
    serve over HTTP, then SIGTERM drains gracefully (exit 0, meta
    written, per-tenant summary printed)."""

    def test_frontdoor_smoke_with_sigterm_drain(self, tmp_path):
        import http.client
        import signal
        import subprocess
        import sys
        import time

        out = tmp_path / "fd_out"
        tenants = tmp_path / "tenants.json"
        tenants.write_text(json.dumps({"tenants": [
            {"name": "acme", "api_key": "ak", "weight": 2.0},
            {"name": "pub"},
        ]}))
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(
            [sys.executable, "-m", "lens_tpu", "frontdoor",
             "--composite", "minimal_ode", "--capacity", "4",
             "--lanes", "2", "--window", "4", "--port", "0",
             "--tenants", str(tenants), "--out-dir", str(out)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env=env,
        )
        try:
            port = None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if "listening on" in line:
                    port = int(line.rsplit(":", 1)[1])
                    break
                if proc.poll() is not None:
                    raise AssertionError(
                        f"frontdoor exited early:\n"
                        f"{line}{proc.stdout.read()}"
                    )
            assert port, "never printed the listen port"
            conn = http.client.HTTPConnection(
                "127.0.0.1", port, timeout=60
            )
            conn.request(
                "POST", "/v1/requests",
                body=json.dumps({"seed": 3, "horizon": 8.0}),
                headers={"Authorization": "Bearer ak"},
            )
            r = conn.getresponse()
            sub = json.loads(r.read())
            assert r.status == 202 and sub["tenant"] == "acme"
            rid = sub["rid"]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                conn.request(
                    "GET", f"/v1/requests/{rid}",
                    headers={"Authorization": "Bearer ak"},
                )
                st = json.loads(conn.getresponse().read())
                if st["status"] == "done":
                    break
                time.sleep(0.05)
            assert st["status"] == "done", st
            conn.request("GET", "/healthz")
            health = json.loads(conn.getresponse().read())
            assert "acme" in health["frontdoor"]["tenants"]
            conn.close()
        finally:
            proc.send_signal(signal.SIGTERM)
            stdout, _ = proc.communicate(timeout=120)
        assert proc.returncode == 0, stdout
        assert "drain: caught signal" in stdout
        assert "drained: submitted=1" in stdout
        assert "tenant acme: admitted=1" in stdout
        assert (out / "server_meta.json").exists()
        with open(out / "server_meta.json") as f:
            meta = json.load(f)
        assert meta["tenants"]["acme"]["admitted"] == 1


class TestSweepCommand:
    def _spec(self, tmp_path):
        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_external": {"grid": [0.5, 1.0, 2.0]},
            }},
            "horizon": 8.0,
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        path = tmp_path / "sweep.json"
        path.write_text(json.dumps(spec))
        return str(path)

    def test_sweep_smoke_writes_table_and_ledger(self, tmp_path, capsys):
        out = str(tmp_path / "sweep")
        rc = main([
            "sweep", "--spec", self._spec(tmp_path), "--out-dir", out,
        ])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "sweep: 3 trials (done=3)" in printed
        assert "best: trial 2" in printed
        with open(os.path.join(out, "sweep_result.json")) as f:
            table = json.load(f)
        assert len(table["table"]) == 3
        assert table["best"]["trial"] == 2
        assert os.path.exists(os.path.join(out, "sweep.ledger"))
        # a complete sweep resumes as a no-op, same exit code
        rc = main([
            "sweep", "--spec", self._spec(tmp_path), "--out-dir", out,
            "--resume", "--quiet",
        ])
        assert rc == 0
        assert "done=3" in capsys.readouterr().out

    def test_sweep_warmup_spec_through_cli(self, tmp_path, capsys):
        """A spec-level warmup block rides the CLI unchanged: trials
        share one warmed snapshot (docs/sweeps.md, 'Shared warmup')."""
        spec = {
            "composite": "minimal_ode",
            "space": {"kind": "grid", "params": {
                "environment/glucose_external": {"grid": [0.5, 1.0, 2.0]},
            }},
            "horizon": 8.0,
            "warmup": {"horizon": 4.0},
            "objective": {"path": "cell/glucose_internal",
                          "reduction": "final_live_sum", "mode": "max"},
            "capacity": 4,
            "backend": {"kind": "server", "lanes": 2, "window": 4},
        }
        path = tmp_path / "warm.json"
        path.write_text(json.dumps(spec))
        out = str(tmp_path / "warm_sweep")
        rc = main(["sweep", "--spec", str(path), "--out-dir", out])
        assert rc == 0
        printed = capsys.readouterr().out
        assert "sweep: 3 trials (done=3)" in printed
        assert "best: trial 2" in printed
        with open(os.path.join(out, "sweep_result.json")) as f:
            table = json.load(f)
        assert table["spec"]["warmup"] == {"horizon": 4.0}

    def test_sweep_save_trajectories_needs_out_dir(self, tmp_path):
        with pytest.raises(SystemExit, match="out-dir"):
            main(["sweep", "--spec", self._spec(tmp_path),
                  "--save-trajectories"])

    def test_sweep_resume_needs_out_dir(self, tmp_path):
        """--resume without the ledger directory must refuse, not
        silently re-run everything against an in-memory ledger."""
        with pytest.raises(SystemExit, match="out-dir"):
            main(["sweep", "--spec", self._spec(tmp_path), "--resume"])

    def test_sweep_rejects_non_object_spec(self, tmp_path):
        bad = tmp_path / "bad.json"
        bad.write_text("[1, 2, 3]")
        with pytest.raises(SystemExit, match="JSON object"):
            main(["sweep", "--spec", str(bad)])
