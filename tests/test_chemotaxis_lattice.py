"""The signature integration: run/tumble cells climbing a real gradient.

Unit tests of receptor/motor/motility live in test_chemotaxis.py; this
exercises the composed chemotaxis_lattice model — the rebuild of the
reference's chemotaxis-cell-on-lattice experiment — and asserts the
emergent behavior the whole pathway exists for: a population biased UP
an attractant gradient (temporal sensing -> longer up-gradient runs),
not just finite trajectories.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.models.composites import chemotaxis_lattice


def _gradient_state(spatial, receptor, n_cells, start_col_um, peak_mM, key):
    """Initial state: frozen linear attractant ramp along columns, cells
    pre-adapted to their local concentration with randomized headings."""
    h, w = spatial.lattice.shape
    cap = spatial.colony.capacity
    local_c = peak_mM * start_col_um / spatial.lattice.size[1]
    ss = spatial.initial_state(
        n_cells,
        key,
        locations=_spread_locations(spatial, n_cells, start_col_um),
        overrides={
            "boundary": {
                "heading": np.asarray(
                    np.random.default_rng(0).uniform(0, 2 * np.pi, cap),
                    np.float32,
                ),
            },
            "cell": {
                "methyl": float(receptor.adapted_methyl(local_c)),
            },
        },
    )
    ramp = jnp.linspace(0.0, peak_mM, w)[None, None, :]  # [1, 1, W]
    fields = jnp.broadcast_to(ramp, (1, h, w)).astype(ss.fields.dtype)
    return ss._replace(fields=fields)


def _spread_locations(spatial, n_cells, start_col_um):
    h_um = spatial.lattice.size[0]
    rows = np.linspace(20.0, h_um - 20.0, n_cells)
    cols = np.full(n_cells, start_col_um)
    cap = spatial.colony.capacity
    out = np.zeros((cap, 2), np.float32)
    out[:n_cells, 0] = rows
    out[:n_cells, 1] = cols
    return out


class TestGradientClimbing:
    def test_population_climbs_the_gradient(self):
        """Mean displacement along the gradient beats cross-gradient drift."""
        n = 192
        spatial, comp = chemotaxis_lattice(
            {
                "capacity": 256,
                "shape": (32, 32),
                "diffusion": 0.0,          # frozen ramp: clean signal
                "transport": {"vmax": 0.0},  # no consumption either
                "division": False,
                "motility": {"speed": 8.0},
            }
        )
        ss = _gradient_state(
            spatial, comp.processes["receptor"], n,
            start_col_um=80.0, peak_mM=0.5,
            key=jax.random.PRNGKey(42),
        )
        loc0 = np.asarray(
            ss.colony.agents["boundary"]["location"][:n]
        )
        ss, _ = spatial.run(ss, 60.0, 1.0, emit_every=60)
        loc1 = np.asarray(
            ss.colony.agents["boundary"]["location"][:n]
        )
        d_col = float(np.mean(loc1[:, 1] - loc0[:, 1]))  # along gradient
        d_row = float(np.mean(loc1[:, 0] - loc0[:, 0]))  # across gradient
        # biased climb: clearly positive and dominant over lateral drift
        assert d_col > 15.0, (d_col, d_row)
        assert abs(d_row) < d_col / 2, (d_col, d_row)
        # the ramp really was frozen (no diffusion, no consumption):
        # final field must equal the initial linear column profile
        w = spatial.lattice.shape[1]
        ramp = jnp.broadcast_to(
            jnp.linspace(0.0, 0.5, w)[None, :], ss.fields.shape[1:]
        )
        assert float(jnp.max(jnp.abs(ss.fields[0] - ramp))) < 1e-6

    def test_no_gradient_no_net_drift(self):
        """Uniform field: the same machinery produces no directional bias."""
        n = 192
        spatial, _ = chemotaxis_lattice(
            {
                "capacity": 256,
                "shape": (32, 32),
                "diffusion": 0.0,
                "transport": {"vmax": 0.0},
                "division": False,
                "motility": {"speed": 8.0},
            }
        )
        ss = spatial.initial_state(
            n,
            jax.random.PRNGKey(7),
            locations=_spread_locations(spatial, n, 160.0),
            overrides={
                "boundary": {
                    "heading": np.asarray(
                        np.random.default_rng(1).uniform(
                            0, 2 * np.pi, spatial.colony.capacity
                        ),
                        np.float32,
                    ),
                }
            },
        )
        loc0 = np.asarray(ss.colony.agents["boundary"]["location"][:n])
        ss, _ = spatial.run(ss, 60.0, 1.0, emit_every=60)
        loc1 = np.asarray(ss.colony.agents["boundary"]["location"][:n])
        d_col = float(np.mean(loc1[:, 1] - loc0[:, 1]))
        assert abs(d_col) < 12.0, d_col


class TestCompositeSurface:
    def test_registered_and_experiment_runnable(self):
        from lens_tpu.experiment import Experiment
        from lens_tpu.models.composites import composite_registry

        assert "chemotaxis_lattice" in composite_registry
        with Experiment(
            {
                "composite": "chemotaxis_lattice",
                "config": {"capacity": 64, "shape": (16, 16)},
                "n_agents": 8,
                "total_time": 10.0,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(jax.device_get(exp.n_alive(state)))) >= 8
            ts = exp.emitter.timeseries()
            assert np.isfinite(
                np.asarray(ts["cell"]["chemoreceptor_activity"])
            ).all()
