"""Config 3: the wcEcoli-minimal composite — metabolism + expression +
division at 256 agents (BASELINE.json configs[3])."""

import jax
import numpy as np

from lens_tpu.experiment import Experiment


class TestMinimalWcecoli:
    def test_grows_expresses_and_divides(self):
        with Experiment(
            {
                "composite": "minimal_wcecoli",
                "n_agents": 256,
                "capacity": 1024,
                "total_time": 400.0,
                "emit_every": 50,
                # a batch-culture glucose pool to grow through (the
                # composite has no lattice; substrate is an initial pool)
                "overrides": {"metabolites": {"glc": 50.0}},
            }
        ) as exp:
            state = exp.run()
            n = int(np.asarray(jax.device_get(exp.n_alive(state))))
            assert n > 256, n  # the population divided

            ts = exp.emitter.timeseries()
            alive = np.asarray(ts["alive"]).astype(bool)
            mass = np.asarray(ts["global"]["mass"])
            # live-cell mass grew before the first divisions
            assert mass[1][alive[1]].mean() > mass[0][alive[0]].mean()
            # expression machinery is being produced and stays finite
            rnap = np.asarray(ts["counts"]["rnap"])
            assert np.isfinite(rnap).all()
            assert rnap[-1][alive[-1]].mean() > rnap[0][alive[0]].mean()
            # metabolism telemetry present (config 3 is the composite-
            # machinery exerciser: several stores, one merged state)
            assert np.isfinite(
                np.asarray(ts["fluxes"]["reaction_fluxes"])
            ).all()

    def test_registered(self):
        from lens_tpu.models.composites import composite_registry

        assert "minimal_wcecoli" in composite_registry
