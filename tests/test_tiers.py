"""Tiered snapshot store + speculative warming (round 16).

The contracts, in this repo's bitwise culture:

- paging is invisible to results: a demote/promote round-trip through
  any tier returns the exact bytes that went in, so a fork seeded from
  a host- or disk-resident snapshot is BITWISE the fork a device hit
  would have produced (which is itself bitwise the tail of a cold solo
  run — round 11's pin, inherited);
- the disk tier is durable: a server killed (or simply gone) and
  rebuilt over the same directory serves repeat prefixes from disk —
  zero prefix misses, >0 disk-tier hits, same bytes;
- speculative warming changes WORK PLACEMENT only: warmed serving is
  bitwise unwarmed serving, warm lanes are preempted the moment a
  client wants the lane, and a preempted-then-resumed warm run's
  snapshot equals an uninterrupted one's.
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from lens_tpu.serve import (
    DONE,
    ScenarioRequest,
    SimServer,
    SnapshotStore,
    TieredSnapshotStore,
)
from lens_tpu.serve.snapshots import DEVICE, DISK, HOST
from lens_tpu.serve.tiers import TIER_META


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _tail(ts, n):
    return jax.tree.map(lambda x: np.asarray(x)[-n:], ts)


def _state(nbytes=800, fill=0.0):
    return {"x": jnp.full(nbytes // 4, float(fill), jnp.float32)}


def _toggle_server(**kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket("toggle_colony", **kw)


class TestTieredStoreUnit:
    """Pure store mechanics: demotion order, promotion, durability."""

    def test_device_overflow_demotes_lru_to_host(self):
        store = TieredSnapshotStore(
            budget_bytes=2000, host_budget_bytes=4000
        )
        for i in range(3):  # 800 each: the third insert demotes ONE
            store.put(("k", i), _state(fill=i))
        assert store.tier_of(("k", 0)) == HOST  # LRU went down first
        assert store.tier_of(("k", 1)) == DEVICE
        assert store.tier_of(("k", 2)) == DEVICE
        stats = store.tier_stats()
        assert stats["tiers"][DEVICE]["demotions"] == 1
        assert stats["tiers"][HOST]["entries"] == 1
        assert len(store) == 3  # nothing evicted, only demoted

    def test_host_overflow_cascades_to_disk(self, tmp_path):
        store = TieredSnapshotStore(
            budget_bytes=2000, host_budget_bytes=800,
            dir=str(tmp_path / "tier"),
        )
        for i in range(4):
            store.put(("k", i), _state(fill=i))
        tiers = {i: store.tier_of(("k", i)) for i in range(4)}
        assert tiers == {0: DISK, 1: HOST, 2: DEVICE, 3: DEVICE}
        entry_dirs = [
            p for p in os.listdir(tmp_path / "tier")
            if p.startswith("snap_") and not p.endswith(".meta.json")
        ]
        assert len(entry_dirs) == 1  # the disk entry's spill landed
        assert store.tier_stats()["tiers"][HOST]["demotions"] == 1

    def test_fetch_promotes_bitwise_from_every_tier(self, tmp_path):
        store = TieredSnapshotStore(
            budget_bytes=900, host_budget_bytes=900,
            dir=str(tmp_path / "tier"),
        )
        originals = {}
        for i in range(3):
            originals[i] = _state(fill=10 + i)
            store.put(("k", i), originals[i])
        assert store.tier_of(("k", 0)) == DISK
        assert store.tier_of(("k", 1)) == HOST
        assert store.tier_of(("k", 2)) == DEVICE
        for i in (0, 1, 2):
            got = store.fetch(("k", i))
            assert _leaves_equal(got, originals[i])
        stats = store.tier_stats()["tiers"]
        # every fetch promoted from a lower tier (each promotion
        # cascades colder entries down, so the exact source tiers
        # shift — the TOTAL is what the budget math guarantees)
        assert stats[DISK]["promotions"] >= 1
        assert (
            stats[HOST]["promotions"] + stats[DISK]["promotions"] == 3
        )

    def test_pinned_entries_demote_but_never_drop(self, tmp_path):
        store = TieredSnapshotStore(
            budget_bytes=900, host_budget_bytes=0,
            dir=str(tmp_path / "tier"),
        )
        pinned = _state(fill=7)
        store.put(("pin",), pinned, pin=True)
        store.put(("cache", 0), _state(fill=8))
        # unpinned entries page first: the cache entry demoted
        # straight to disk (host tier disabled), the pinned one stays
        assert store.tier_of(("cache", 0)) == DISK
        assert store.tier_of(("pin",)) == DEVICE
        # but pins do NOT anchor an entry to device RAM the way they
        # anchored it to existence: under pressure from another pin,
        # the LRU pinned entry demotes too — refs intact, bits intact
        store.put(("pin", 2), _state(fill=9), pin=True)
        assert store.tier_of(("pin",)) == DISK
        assert store.refs(("pin",)) == 1
        assert _leaves_equal(store.fetch(("pin",)), pinned)
        store.release(("pin",))
        store.release(("pin", 2))

    def test_no_lower_tier_keeps_round15_eviction(self):
        # host tier off, no dir: the tiered store must degrade to the
        # flat store's behavior exactly — evict unpinned, keep pinned
        store = TieredSnapshotStore(budget_bytes=2000)
        store.put(("pin", 0), _state(), pin=True)
        store.put(("pin", 1), _state(), pin=True)
        assert store.put(("cache", 0), _state()) == 1
        assert ("cache", 0) not in store
        assert store.rejected == 1
        assert ("pin", 0) in store and ("pin", 1) in store

    def test_oversized_put_counts_rejected(self):
        # the round-16 satellite: the silent drop is now counted, on
        # the flat store too
        store = SnapshotStore(budget_bytes=100)
        assert store.put(("big",), _state(800)) == 1
        assert len(store) == 0
        assert store.rejected == 1
        assert store.tier_stats()["rejected"] == 1

    def test_compat_mode_disk_is_spill_only(self, tmp_path):
        # demote_to_disk=False (a plain recover_dir): budget pressure
        # must NOT page to disk — only explicit persist/adopt touches
        # it, and eviction behaves like round 15
        store = TieredSnapshotStore(
            budget_bytes=900, dir=str(tmp_path / "tier"),
            demote_to_disk=False,
        )
        store.put(("k", 0), _state(fill=1))
        store.put(("k", 1), _state(fill=2))
        assert ("k", 0) not in store  # evicted, not paged
        name = store.persist(("k", 1))
        assert os.path.isdir(tmp_path / "tier" / name)
        # a PINNED spilled hold keeps round-15 residency under budget
        # pressure: it overshoots and stays device-resident (no
        # silent restore_tree on a later resubmit's latency path)
        store.put(("pin",), _state(fill=3), pin=True)
        store.persist(("pin",))
        store.put(("k", 2), _state(fill=4))
        assert store.tier_of(("pin",)) == DEVICE
        store.release(("pin",))
        # a fresh compat-mode store does NOT scan-adopt
        again = TieredSnapshotStore(
            budget_bytes=900, dir=str(tmp_path / "tier"),
            demote_to_disk=False,
        )
        assert ("k", 1) not in again

    def test_scan_adopts_content_addressed_entries_only(self, tmp_path):
        from lens_tpu.serve.snapshots import snapshot_key

        tier = str(tmp_path / "tier")
        store = TieredSnapshotStore(
            budget_bytes=0, host_budget_bytes=0, dir=tier,
        )
        ck = snapshot_key("bucket", 3, 1, {"g": {"x": 1.0}}, 8)
        content = _state(fill=3)
        store.put(ck, content)  # budget 0: demotes straight to disk
        assert store.tier_of(ck) == DISK
        held = _state(fill=4)
        store.put(("held", "req-000001"), held, pin=True)
        store.persist(("held", "req-000001"))

        fresh = TieredSnapshotStore(
            budget_bytes=0, host_budget_bytes=0, dir=tier,
        )
        # the content-addressed entry came back, durable
        assert fresh.tier_of(ck) == DISK
        assert _leaves_equal(fresh.fetch(ck), content)
        # the per-request held key did NOT (a new server's rid space
        # would collide with it); WAL replay is its only way back
        assert ("held", "req-000001") not in fresh
        fresh.adopt(
            ("held", "req-000001"),
            store._entries[("held", "req-000001")].disk_name,
            pin=True,
        )
        assert _leaves_equal(
            fresh.fetch(("held", "req-000001")), held
        )

    def test_adopt_missing_spill_raises(self, tmp_path):
        store = TieredSnapshotStore(dir=str(tmp_path / "tier"))
        with pytest.raises(FileNotFoundError, match="missing"):
            store.adopt(("k",), "snap_nope")

    def test_fingerprint_mismatch_refused(self, tmp_path):
        tier = str(tmp_path / "tier")
        TieredSnapshotStore(dir=tier, fingerprint="aaaa")
        with pytest.raises(ValueError, match="fingerprint"):
            TieredSnapshotStore(dir=tier, fingerprint="bbbb")
        assert os.path.exists(os.path.join(tier, TIER_META))

    def test_device_lost_demotes_durable_entries(self, tmp_path):
        store = TieredSnapshotStore(dir=str(tmp_path / "tier"))
        store.put(("durable",), _state(fill=1), pin=True, shard=1)
        store.persist(("durable",))
        store.put(("volatile",), _state(fill=2), pin=True, shard=1)
        store.put(("elsewhere",), _state(fill=3), shard=0)
        lost = store.device_lost(1)
        assert lost == [(("volatile",), 1)]
        assert store.tier_of(("durable",)) == DISK
        assert store.refs(("durable",)) == 1  # pins survive demotion
        assert store.tier_of(("elsewhere",)) == DEVICE

    def test_refcounts_exact_across_paging(self, tmp_path):
        store = TieredSnapshotStore(
            budget_bytes=0, host_budget_bytes=0,
            dir=str(tmp_path / "tier"),
        )
        store.put(("k",), _state(), pin=True)
        assert store.tier_of(("k",)) == DISK
        store.acquire(("k",))
        assert store.refs(("k",)) == 2
        store.release(("k",))
        store.release(("k",))
        with pytest.raises(RuntimeError, match="double release"):
            store.release(("k",))
        assert store.refs_total() == 0


class TestTieredServing:
    """The store under the server: paging must be invisible to bits."""

    PREFIX = 8.0
    HORIZON = 16.0

    def _fork(self, seed, volume=None):
        return ScenarioRequest(
            composite="toggle_colony",
            seed=seed,
            horizon=self.HORIZON,
            prefix={
                "horizon": self.PREFIX,
                "overrides": {"global": {"volume": 1.05}},
            },
            overrides=(
                {"global": {"volume": volume}} if volume else {}
            ),
        )

    def test_demoted_prefix_hits_promote_bitwise(self, tmp_path):
        # ~668-byte toggle snapshots; ~1 KiB device and host budgets
        # hold ONE each — three distinct prefixes force constant
        # paging across all three tiers, and nothing may be lost
        srv = _toggle_server(
            snapshot_budget_mb=0.001, host_budget_mb=0.001,
            tier_dir=str(tmp_path / "tier"),
        )
        first = {
            s: srv.submit(self._fork(s)) for s in (1, 2, 3)
        }
        srv.run_until_idle(max_ticks=500)
        repeat = {
            s: srv.submit(self._fork(s)) for s in (1, 2, 3)
        }
        srv.run_until_idle(max_ticks=500)
        m = srv.metrics()
        assert m["counters"]["prefix_hits"] == 3  # repeats all hit
        tiers = m["snapshot_tiers"]
        # at least one repeat was served from a demoted tier and
        # promoted back (budget fits one: two of three MUST page)
        assert (
            tiers[HOST]["promotions"] + tiers[DISK]["promotions"] > 0
            or tiers[HOST]["hits"] + tiers[DISK]["hits"] > 0
        )
        for s in (1, 2, 3):
            assert _leaves_equal(
                srv.result(first[s]), srv.result(repeat[s])
            )
        # pure fork (no divergent overrides): the suffix is bitwise
        # the tail of a cold solo run under the prefix overrides
        solo_srv = _toggle_server()
        solo = solo_srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=self.HORIZON,
            overrides={"global": {"volume": 1.05}},
        ))
        solo_srv.run_until_idle(max_ticks=200)
        suffix_rows = int(self.HORIZON - self.PREFIX)
        assert _leaves_equal(
            srv.result(repeat[1]),
            _tail(solo_srv.result(solo), suffix_rows),
        )
        solo_srv.close()
        srv.close()

    def test_tiers_off_is_the_flat_store(self):
        off = _toggle_server()
        assert type(off.snapshots) is SnapshotStore
        off.close()
        on = _toggle_server(host_budget_mb=1)
        assert isinstance(on.snapshots, TieredSnapshotStore)
        on.close()

    def test_disk_tier_survives_crash_and_restart(self, tmp_path):
        tier = str(tmp_path / "tier")
        kw = dict(
            snapshot_budget_mb=0, host_budget_mb=0, tier_dir=tier,
        )
        srv = _toggle_server(**kw)
        a = srv.submit(self._fork(5, volume=1.1))
        srv.run_until_idle(max_ticks=200)
        ref = srv.result(a)
        if srv._streamer is not None:
            srv._streamer.drain()
        del srv  # crash: no close, the disk tier must not care

        srv2 = _toggle_server(**kw)
        b = srv2.submit(self._fork(5, volume=1.1))
        srv2.run_until_idle(max_ticks=200)
        m = srv2.metrics()
        assert m["counters"]["prefix_misses"] == 0
        assert m["counters"]["prefix_hits"] == 1
        assert m["snapshot_tiers"][DISK]["hits"] == 1
        assert _leaves_equal(ref, srv2.result(b))
        srv2.close()

    def test_changed_bucket_config_refuses_stale_tier_dir(
        self, tmp_path
    ):
        tier = str(tmp_path / "tier")
        srv = _toggle_server(host_budget_mb=1, tier_dir=tier)
        srv.close()
        with pytest.raises(ValueError, match="fingerprint"):
            _toggle_server(
                host_budget_mb=1, tier_dir=tier, capacity=32
            )

    def test_metrics_surface(self, tmp_path):
        srv = _toggle_server(
            snapshot_budget_mb=0, host_budget_mb=0,
            tier_dir=str(tmp_path / "tier"),
        )
        rid = srv.submit(self._fork(1))
        srv.run_until_idle(max_ticks=200)
        assert srv.status(rid)["status"] == DONE
        snap = srv.metrics()
        assert set(snap["snapshot_tiers"]) == {DEVICE, HOST, DISK}
        gauges = srv.status(rid)["server"]["snapshots"]
        assert "tiers" in gauges and "warm" in gauges
        text = srv.prometheus_metrics()
        assert 'lens_serve_snapshot_tier_bytes{tier="disk"}' in text
        assert "lens_serve_snapshot_rejected_total" in text
        srv.close()


class TestWarming:
    """Speculative warming: placement only, never bits, never delay."""

    def test_prewarm_then_client_hit_bitwise(self):
        srv = _toggle_server()
        wid = srv.prewarm(
            composite="toggle_colony", seed=7, horizon=8.0
        )
        assert wid is not None
        srv.run_until_idle(max_ticks=200)
        req = ScenarioRequest(
            composite="toggle_colony", seed=7, horizon=16.0,
            prefix={"horizon": 8.0},
            overrides={"global": {"volume": 1.1}},
        )
        rid = srv.submit(req)
        srv.run_until_idle(max_ticks=200)
        c = srv.metrics()["counters"]
        assert c["warm_submitted"] == 1 and c["warm_completed"] == 1
        assert c["prefix_misses"] == 0
        assert c["prefix_hits"] == 1 and c["warm_hits"] == 1
        warm_result = srv.result(rid)
        srv.close()
        # bitwise: warming never touches results
        cold = _toggle_server()
        rid0 = cold.submit(req)
        cold.run_until_idle(max_ticks=200)
        assert _leaves_equal(warm_result, cold.result(rid0))
        cold.close()

    def test_prewarm_is_idempotent_and_coalesces(self):
        srv = _toggle_server()
        assert srv.prewarm(
            composite="toggle_colony", seed=7, horizon=8.0
        ) is not None
        # second prewarm of an in-flight key: no second run
        assert srv.prewarm(
            composite="toggle_colony", seed=7, horizon=8.0
        ) is None
        # a client submit meanwhile coalesces onto the warm run
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=7, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        srv.run_until_idle(max_ticks=200)
        c = srv.metrics()["counters"]
        assert srv.status(rid)["status"] == DONE
        assert c["warm_submitted"] == 1
        assert c["prefix_coalesced"] == 1 and c["warm_hits"] == 1
        # resident now: prewarming again is a no-op
        assert srv.prewarm(
            composite="toggle_colony", seed=7, horizon=8.0
        ) is None
        srv.close()

    def test_prewarm_promotes_demoted_entry(self):
        # budget fits ONE snapshot: running prefix B demotes A; a
        # prewarm of A is then the prefetch path — promote, not re-run
        srv = _toggle_server(
            snapshot_budget_mb=0.001, host_budget_mb=0.01,
        )
        spec_a = dict(composite="toggle_colony", seed=1, horizon=8.0)
        for seed in (1, 2):
            rid = srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=seed, horizon=16.0,
                prefix={"horizon": 8.0},
            ))
            srv.run_until_idle(max_ticks=200)
        base = srv.metrics()["counters"]
        assert srv.prewarm(spec_a) is None  # promoted, no run needed
        c = srv.metrics()["counters"]
        assert c["warm_submitted"] == base["warm_submitted"]
        assert (
            srv.metrics()["snapshot_tiers"][HOST]["promotions"] > 0
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        srv.run_until_idle(max_ticks=200)
        c = srv.metrics()["counters"]
        assert c["warm_hits"] == base["warm_hits"] + 1
        assert srv.status(rid)["status"] == DONE
        srv.close()

    def test_preemption_yields_to_clients_and_resumes_bitwise(self):
        srv = _toggle_server(lanes=1, window=4)
        wid = srv.prewarm(
            composite="toggle_colony", seed=11, horizon=64.0
        )
        srv.tick()
        srv.tick()  # the warm run owns the only lane now
        cid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=12, horizon=8.0,
        ))
        srv.tick()  # preemption + client admission happen this tick
        assert srv.tickets[cid].status == "running"
        srv.run_until_idle(max_ticks=500)
        c = srv.metrics()["counters"]
        assert srv.status(cid)["status"] == DONE
        assert c["warm_preempted"] >= 1
        assert srv.tickets[wid].status == DONE  # resumed and finished
        resumed = srv.snapshots.fetch(srv.tickets[wid].content_key)

        clean_srv = _toggle_server(lanes=1, window=4)
        w2 = clean_srv.prewarm(
            composite="toggle_colony", seed=11, horizon=64.0
        )
        clean_srv.run_until_idle(max_ticks=500)
        clean = clean_srv.snapshots.fetch(
            clean_srv.tickets[w2].content_key
        )
        assert _leaves_equal(resumed, clean)
        srv.close()
        clean_srv.close()

    def test_coalesced_fork_promotes_queued_warm_run(self):
        """A client fork depending on a STILL-QUEUED warm run must not
        wait for scrap lanes behind later client traffic: the warm
        ticket moves into the client queue (where a plain miss's
        internal run would be) the moment the fork coalesces."""
        srv = _toggle_server(lanes=1, window=4)
        blocker = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=32.0,
        ))
        srv.tick()  # blocker owns the only lane
        wid = srv.prewarm(
            composite="toggle_colony", seed=2, horizon=8.0
        )
        assert any(t.request_id == wid for t in srv._warm_queue)
        fork = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        # promoted: out of the warm queue, into the client FIFO
        assert not any(t.request_id == wid for t in srv._warm_queue)
        assert any(t.request_id == wid for t in srv.queue)
        srv.run_until_idle(max_ticks=500)
        assert srv.status(fork)["status"] == DONE
        assert srv.status(blocker)["status"] == DONE
        c = srv.metrics()["counters"]
        assert c["prefix_coalesced"] == 1 and c["warm_hits"] == 1
        srv.close()

    def test_flat_store_exports_no_tier_rows(self):
        srv = _toggle_server()
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(rid)["status"] == DONE
        assert srv.metrics()["snapshot_tiers"] == {}
        assert "snapshot_tier_" not in srv.prometheus_metrics()
        srv.close()

    def test_preempted_warm_capture_voided_on_device_loss(self):
        """A preempted warm ticket's on-device progress capture lives
        in ONE device's memory; quarantining that device must void
        the capture (restart from scratch on a survivor), like every
        other failover path does for carry state."""
        srv = _toggle_server(lanes=1, window=4, mesh=2)
        wid = srv.prewarm(
            composite="toggle_colony", seed=21, horizon=32.0
        )
        srv.tick()
        srv.tick()  # warm running on some shard
        w = srv.tickets[wid]
        shard = w.shard
        # force a preemption: one client per lane of every shard
        blockers = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=30 + i, horizon=16.0,
            ))
            for i in range(2)
        ]
        srv.tick()
        assert w in srv._warm_queue and w.carry_shard == shard
        srv.quarantine_device(shard, reason="test")
        assert w.carry_state is None and w.steps_done == 0
        srv.run_until_idle(max_ticks=500)
        for b in blockers:
            assert srv.status(b)["status"] == DONE
        assert srv.tickets[wid].status == DONE  # re-ran on survivor
        # and the snapshot equals an unfaulted run's
        snap = srv.snapshots.fetch(w.content_key)
        ref_srv = _toggle_server(lanes=1, window=4)
        w2 = ref_srv.prewarm(
            composite="toggle_colony", seed=21, horizon=32.0
        )
        ref_srv.run_until_idle(max_ticks=500)
        assert _leaves_equal(
            snap, ref_srv.snapshots.fetch(ref_srv.tickets[w2].content_key)
        )
        srv.close()
        ref_srv.close()

    def test_prewarm_validates_like_submit(self):
        srv = _toggle_server()
        with pytest.raises(ValueError, match="composite"):
            srv.prewarm(composite="nope", seed=1, horizon=8.0)
        with pytest.raises(ValueError, match="horizon"):
            srv.prewarm(
                composite="toggle_colony", seed=1, horizon=0.3
            )
        with pytest.raises(ValueError, match="prewarm keys"):
            srv.prewarm(
                composite="toggle_colony", seed=1, horizon=8.0,
                hold_state=True,
            )
        with pytest.raises(ValueError, match="prewarm needs"):
            srv.prewarm(horizon=8.0)  # composite missing
        srv.close()

    def test_frontdoor_repeated_shape_prewarms(self, tmp_path):
        from lens_tpu.frontdoor import FrontDoor

        srv = _toggle_server(
            out_dir=str(tmp_path / "out"), sink="log"
        )
        fd = FrontDoor(srv, warm=True)  # never started: unit-level
        req = ScenarioRequest(
            composite="toggle_colony", seed=4, horizon=16.0,
            prefix={"horizon": 8.0},
        )
        fd._note_prefix("acme", req)
        with fd._lock:
            fd._prewarm_popular_step()
        assert srv.metrics()["counters"]["warm_submitted"] == 0
        fd._note_prefix("acme", req)  # second sighting: popular
        with fd._lock:
            fd._prewarm_popular_step()
        assert srv.metrics()["counters"]["warm_submitted"] == 1
        assert fd._warmed_idle  # one-shape plan drained in one step
        srv.run_until_idle(max_ticks=200)
        rid = srv.submit(req)
        srv.run_until_idle(max_ticks=200)
        c = srv.metrics()["counters"]
        assert srv.status(rid)["status"] == DONE
        assert c["warm_hits"] == 1 and c["prefix_misses"] == 0
        srv.close()

    def test_sweep_backend_warm_scores_speculative_hits(self, tmp_path):
        from lens_tpu.sweep import run_sweep

        spec = {
            "composite": "toggle_colony",
            "space": {
                "kind": "random", "n_trials": 4,
                "params": {
                    "global/volume": {"low": 0.9, "high": 1.2},
                },
            },
            "seed": 0, "horizon": 16.0, "capacity": 8,
            "objective": {
                "path": "global/volume",
                "reduction": "final_live_sum", "mode": "max",
            },
            "backend": {
                "kind": "server", "lanes": 2, "window": 4,
                "warm": True,
            },
            "warmup": {"horizon": 8.0, "seed": 3},
        }
        res = run_sweep(spec, out_dir=str(tmp_path / "sweep"))
        assert all(r["status"] == "done" for r in res.table)
        c = res.metrics["server"]["counters"]
        assert c["warm_submitted"] == 1
        assert c["warm_hits"] > 0  # trials rode the speculative run
        # and bits match the unwarmed sweep
        spec_cold = dict(spec, backend={
            "kind": "server", "lanes": 2, "window": 4,
        })
        cold = run_sweep(spec_cold, out_dir=str(tmp_path / "cold"))
        warm_t = {r["trial"]: r["objective"] for r in res.table}
        cold_t = {r["trial"]: r["objective"] for r in cold.table}
        assert warm_t == cold_t


# -- restart-warm through a REAL SIGKILL (the acceptance drill) ----------


def _run_cli(args, cwd, expect_kill=False, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "lens_tpu", "serve", *args],
        cwd=cwd, env=env, capture_output=True, text=True,
        timeout=timeout,
    )
    if expect_kill:
        assert proc.returncode == -signal.SIGKILL, (
            f"expected SIGKILL, got rc={proc.returncode}\n"
            f"stdout: {proc.stdout}\nstderr: {proc.stderr}"
        )
    else:
        assert proc.returncode == 0, (
            f"rc={proc.returncode}\nstdout: {proc.stdout}\n"
            f"stderr: {proc.stderr}"
        )
    return proc


def _lens_records(out_dir):
    """Each client log's RECORD frame payloads, in submission order
    (client rids ascend with list position either way). The header
    frame is dropped: it embeds the request id, and a warm server
    mints DIFFERENT rids than a cold one (prefix hits launch no
    internal tickets, so the id sequence compresses) — the records
    are the bits the determinism contract pins."""
    from lens_tpu.emit.log import iter_frames

    return [
        list(iter_frames(os.path.join(out_dir, name)))[1:]
        for name in sorted(os.listdir(out_dir))
        if name.endswith(".lens")
    ]


@pytest.fixture(scope="module")
def repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestRestartWarmSigkill:
    """SIGKILL a tier-serving server mid-workload, restart it over the
    same directories, and pin the acceptance claims: the re-run is
    bitwise an uninterrupted run, and a THIRD, fresh-WAL invocation of
    the same repeat traffic serves its prefixes from the disk tier —
    zero misses, >0 disk hits, same bytes."""

    REQS = [
        {"seed": 5, "horizon": 16.0, "prefix": {"horizon": 8.0},
         "overrides": {"global": {"volume": 1.1}}},
        {"seed": 5, "horizon": 16.0, "prefix": {"horizon": 8.0},
         "overrides": {"global": {"volume": 1.2}}},
        {"seed": 6, "horizon": 16.0, "prefix": {"horizon": 8.0}},
    ]

    def test_sigkill_restart_serves_warm_disk_hits(
        self, tmp_path, repo_root
    ):
        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps(self.REQS))
        base = [
            "--composite", "toggle_colony", "--capacity", "8",
            "--lanes", "2", "--window", "4", "--requests", str(reqs),
            # device+host budgets 0: every snapshot pages to disk the
            # moment it is published, so the tier is populated well
            # before the kill
            "--snapshot-budget-mb", "0", "--host-budget-mb", "0",
        ]
        ref_out = tmp_path / "ref_out"
        _run_cli(
            base + ["--out-dir", str(ref_out),
                    "--tier-dir", str(tmp_path / "ref_tier"),
                    "--recover-dir", str(tmp_path / "ref_wal")],
            repo_root,
        )
        ref = _lens_records(str(ref_out))

        tier = tmp_path / "tier"
        out, wal = tmp_path / "out", tmp_path / "wal"
        faults = tmp_path / "faults.json"
        faults.write_text(json.dumps(
            [{"kind": "kill", "at": "retired.walled"}]
        ))
        crashed = base + [
            "--out-dir", str(out), "--tier-dir", str(tier),
            "--recover-dir", str(wal),
        ]
        _run_cli(
            crashed + ["--faults", str(faults)],
            repo_root, expect_kill=True,
        )
        # restart over the same dirs: WAL recovery + disk-tier warmth
        _run_cli(crashed, repo_root)
        assert _lens_records(str(out)) == ref

        # repeat traffic against the SURVIVING tier dir (fresh WAL and
        # out dir — this server never computed these prefixes): every
        # prefix must come from disk
        out3, wal3 = tmp_path / "out3", tmp_path / "wal3"
        _run_cli(
            base + ["--out-dir", str(out3), "--tier-dir", str(tier),
                    "--recover-dir", str(wal3)],
            repo_root,
        )
        assert _lens_records(str(out3)) == ref
        meta = json.load(open(out3 / "server_meta.json"))
        assert meta["counters"]["prefix_misses"] == 0
        assert meta["counters"]["prefix_hits"] >= 1
        assert meta["snapshot_tiers"]["disk"]["hits"] >= 1
