"""Test configuration: force a pure 8-device virtual CPU mesh.

Two environment problems are handled here, both before any jax backend
initializes:

1. This box injects an ``axon`` (TPU-tunnel) PJRT hook into every python
   process (sitecustomize via PYTHONPATH, gated on PALLAS_AXON_POOL_IPS)
   which forces ``jax_platforms="axon,cpu"``; when the tunnel relay is
   down, axon backend init blocks the whole suite in a retry loop. The
   env var ``JAX_PLATFORMS=cpu`` does NOT override the hook, but setting
   the jax *config* after import does — the plugin stays registered but
   is never initialized, so nothing dials the relay.
2. Multi-chip sharding is tested without real chips by exposing 8 virtual
   host devices (SURVEY.md §4: the TPU-native analogue of "multi-node
   without a real cluster").

A persistent compilation cache keeps re-runs fast on this 1-core box.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")

import jax  # noqa: E402  (import after env setup is the whole point)

jax.config.update("jax_platforms", "cpu")
