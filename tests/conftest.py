"""Test configuration: force a pure 8-device virtual CPU mesh.

Two environment problems are handled here, both before any jax backend
initializes:

1. This box injects an ``axon`` (TPU-tunnel) PJRT hook into every python
   process (sitecustomize via PYTHONPATH, gated on PALLAS_AXON_POOL_IPS)
   which forces ``jax_platforms="axon,cpu"``; when the tunnel relay is
   down, axon backend init blocks the whole suite in a retry loop. The
   env var ``JAX_PLATFORMS=cpu`` does NOT override the hook, but setting
   the jax *config* after import does — the plugin stays registered but
   is never initialized, so nothing dials the relay.
2. Multi-chip sharding is tested without real chips by exposing 8 virtual
   host devices (SURVEY.md §4: the TPU-native analogue of "multi-node
   without a real cluster").

A persistent compilation cache keeps re-runs fast on this 1-core box.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# NO persistent compilation cache for the suite. Three independent
# full-suite segfaults (2026-07-30/31) traced into the persistent
# cache's executable (de)serialization — one mid-READ of a torn entry
# in the shared dir, one mid-WRITE into a FRESH per-session dir —
# always on the large sharded executables, and only in long-lived
# processes. The in-memory jit cache fully covers a test session;
# cross-run compile reuse is not worth a crashing suite. (Examples and
# benches keep their shared dir: their long compiles benefit and their
# executables have not exhibited the crash.)
os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
os.environ.pop("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", None)

import jax  # noqa: E402  (import after env setup is the whole point)

jax.config.update("jax_platforms", "cpu")

# jax < 0.6 ships shard_map under jax.experimental only (and has no
# jax.P alias); the suite (and the sharded runners, via
# utils.platform.shard_map_fn) must run on both.
if not hasattr(jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _shard_map

    jax.shard_map = _shard_map
if not hasattr(jax, "P"):
    jax.P = jax.sharding.PartitionSpec
