"""serve/metrics.py in isolation (round-14 satellite).

Before round 14 ``ServerMetrics`` was exercised only incidentally
through ``test_serve.py``'s end-to-end flows; these are the direct
contracts — percentile edge cases, gauge recompute-at-call semantics,
per-shard aggregation pass-through, the reset-vs-observe race, and the
registry/export surfaces — that the serving and bench layers lean on.
No jax, no server: plain objects.
"""

import json
import os
import threading
import time

import pytest

from lens_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    percentiles,
)
from lens_tpu.serve.batcher import ScenarioRequest, Ticket
from lens_tpu.serve.metrics import (
    ServerMetrics,
    request_timing_row,
    write_server_meta,
)


class TestPercentiles:
    def test_empty_yields_none_not_zero(self):
        out = percentiles([])
        assert out == {"p50": None, "p95": None, "p99": None}

    def test_single_sample_is_every_percentile(self):
        out = percentiles([0.25])
        assert out["p50"] == out["p95"] == out["p99"] == 0.25

    def test_two_samples_interpolate(self):
        out = percentiles([0.0, 1.0])
        assert out["p50"] == pytest.approx(0.5)
        assert out["p95"] == pytest.approx(0.95)

    def test_order_independent(self):
        a = percentiles([3.0, 1.0, 2.0])
        b = percentiles([1.0, 2.0, 3.0])
        assert a == b
        assert a["p50"] == 2.0


class TestInstruments:
    def test_counter_monotone(self):
        c = Counter("x")
        c.inc()
        c.inc(4)
        assert c.value == 5

    def test_gauge_set_vs_computed(self):
        g = Gauge("g")
        g.set(3)
        assert g.read() == 3
        box = {"v": 0}
        g2 = Gauge("g2", fn=lambda: box["v"])
        box["v"] = 7
        assert g2.read() == 7  # recomputed at call, not at set time
        box["v"] = 9
        assert g2.read() == 9

    def test_histogram_list_ergonomics(self):
        h = Histogram("h")
        for v in (3.0, 1.0, 2.0):
            h.observe(v)
        assert len(h) == 3
        assert sorted(h) == [1.0, 2.0, 3.0]
        assert h.tail(2) == [1.0, 2.0]
        assert h.percentiles()["p50"] == 2.0
        assert h.count == 3 and h.sum == 6.0
        h.clear()
        assert len(h) == 0
        assert h.count == 3  # lifetime count survives the reset
        assert h.percentiles()["p50"] is None

    def test_registry_idempotent_and_kind_clash(self):
        reg = MetricsRegistry()
        assert reg.counter("a") is reg.counter("a")
        reg.histogram("h")
        with pytest.raises(ValueError, match="different instrument"):
            reg.counter("h")

    def test_registry_sample_shape(self):
        reg = MetricsRegistry()
        reg.counter("c").inc(2)
        reg.gauge("g", fn=lambda: 1.5)
        reg.histogram("h").observe(0.1)
        point = reg.sample()
        assert point["counters"] == {"c": 2}
        assert point["gauges"] == {"g": 1.5}
        assert point["histograms"]["h"]["count"] == 1
        assert point["histograms"]["h"]["p50"] == pytest.approx(0.1)

    def test_prometheus_exposition_format(self):
        reg = MetricsRegistry(namespace="t")
        reg.counter("jobs", "jobs done").inc(3)
        reg.gauge("depth", fn=lambda: 4)
        reg.gauge("label", fn=lambda: "not-a-number")
        h = reg.histogram("lat")
        h.observe(1.0)
        text = reg.prometheus_text()
        assert "# TYPE t_jobs_total counter" in text
        assert "t_jobs_total 3" in text
        assert "t_depth 4" in text
        assert "t_label" not in text  # non-numeric gauges stay out
        assert 't_lat{quantile="0.5"} 1.0' in text
        assert "t_lat_count 1" in text


class TestServerMetrics:
    def test_counters_property_is_a_copy(self):
        m = ServerMetrics()
        m.inc("submitted", 2)
        snap = m.counters
        snap["submitted"] = 999
        assert m.counters["submitted"] == 2

    def test_occupancy_none_until_first_window(self):
        m = ServerMetrics()
        assert m.occupancy() is None
        m.inc("lane_windows_busy", 3)
        m.inc("lane_windows_total", 4)
        assert m.occupancy() == pytest.approx(0.75)

    def test_gauges_recompute_at_call(self):
        """The metrics() contract: a gauge read reflects NOW — the
        registry gauge and the snapshot both track the live
        attribute."""
        m = ServerMetrics()
        m.queue_depth = 5
        assert m.registry.gauges["queue_depth"].read() == 5
        assert m.snapshot()["queue_depth"] == 5
        m.queue_depth = 1
        assert m.registry.gauges["queue_depth"].read() == 1

    def test_avg_window_seconds_default_then_windowed(self):
        m = ServerMetrics()
        assert m.avg_window_seconds(default=0.3) == 0.3
        for _ in range(40):
            m.observe_window(1.0)
        m.observe_window(3.0)  # inside the 32-sample tail
        assert 1.0 < m.avg_window_seconds() < 1.1

    def test_per_shard_gauges_pass_through(self):
        m = ServerMetrics()
        m.shards = [
            {"shard": 0, "lanes_busy": 2, "windows": 7,
             "quarantined": False},
            {"shard": 1, "lanes_busy": 0, "windows": 3,
             "quarantined": True},
        ]
        m.quarantined_devices = 1
        snap = m.snapshot()
        assert snap["quarantined_devices"] == 1
        assert [s["shard"] for s in snap["shards"]] == [0, 1]
        # the snapshot's rows are copies, not aliases
        snap["shards"][0]["lanes_busy"] = 99
        assert m.shards[0]["lanes_busy"] == 2
        text = m.prometheus_text()
        assert 'lens_serve_shard_windows{shard="0"} 7' in text
        assert 'lens_serve_shard_quarantined{shard="1"} 1' in text

    def test_stream_sample_derived_gauges(self):
        m = ServerMetrics()
        assert m.device_busy_fraction() is None
        # two back-to-back windows, device busy the whole span
        m.observe_stream(0.0, 1.0, 1.2)
        m.observe_stream(1.0, 2.0, 2.2)
        assert m.device_busy_fraction() == pytest.approx(2.0 / 2.2)
        assert m.host_gap_seconds() == pytest.approx([0.2, 0.2])
        assert m.stream_lag_seconds() == pytest.approx([1.2, 1.2])

    def test_reset_keeps_counters_drops_samples(self):
        m = ServerMetrics()
        m.inc("retired", 3)
        m.observe_request(0.1, 0.5)
        m.observe_window(0.2)
        m.observe_stream(0.0, 0.1, 0.2)
        m.observe_stall(0.05)
        m.reset_samples()
        assert m.counters["retired"] == 3
        assert m.snapshot()["latency_seconds"]["p50"] is None
        assert len(m.window_seconds) == 0
        assert m.stream_samples == []
        assert m.stalls == 0

    def test_reset_races_concurrent_observers_safely(self):
        """The round-14 race fix: percentile reads and resets are
        atomic against stream-thread observations — hammer all three
        from threads and every read must be well-formed."""
        m = ServerMetrics()
        stop = threading.Event()
        errors = []

        def observe():
            while not stop.is_set():
                m.observe_request(0.01, 0.02)
                m.observe_stream(0.0, 0.1, 0.2)

        def churn():
            try:
                for _ in range(300):
                    m.reset_samples()
                    snap = m.snapshot()
                    lat = snap["latency_seconds"]["p50"]
                    assert lat is None or lat == pytest.approx(0.02)
                    busy = snap["device_busy_fraction"]
                    assert busy is None or 0.0 <= busy <= 1.0
            except BaseException as e:  # surfaced to the main thread
                errors.append(e)
            finally:
                stop.set()

        workers = [threading.Thread(target=observe) for _ in range(2)]
        reader = threading.Thread(target=churn)
        for t in workers:
            t.start()
        reader.start()
        reader.join()
        for t in workers:
            t.join(timeout=5)
        assert not errors

    def test_snapshot_keys_are_the_stable_surface(self):
        # bench_serve / the CLI / server_meta all index these keys; a
        # rename is an API break and must be deliberate
        snap = ServerMetrics().snapshot()
        assert {
            "counters", "queue_depth", "lanes_busy", "lanes_total",
            "occupancy", "retraces", "snapshots_resident",
            "snapshot_bytes", "shards", "quarantined_devices",
            "uptime_seconds", "avg_window_seconds", "latency_seconds",
            "wait_seconds", "device_busy_fraction", "host_gap_seconds",
            "stream_lag_seconds", "stream_stall_seconds",
            "stream_stalls",
        } <= set(snap)


class TestRequestTimingRows:
    def _ticket(self, **kw):
        t = Ticket(
            request_id="req-000007",
            request=ScenarioRequest(composite="x", horizon=8.0),
        )
        for k, v in kw.items():
            setattr(t, k, v)
        return t

    def test_row_relativizes_against_t0(self):
        t = self._ticket(
            status="done", shard=1, steps_done=8,
            submitted_at=10.0, admitted_at=10.5, first_window_at=10.6,
            streamed_at=11.0, finished_at=10.9,
        )
        row = request_timing_row(t, t0=10.0)
        assert row["rid"] == "req-000007"
        assert row["queued"] == 0.0
        assert row["admitted"] == 0.5
        assert row["first_window"] == pytest.approx(0.6)
        assert row["last_streamed"] == 1.0
        assert row["retired"] == pytest.approx(0.9)
        assert row["shard"] == 1 and row["steps_done"] == 8

    def test_never_admitted_rows_carry_nones(self):
        row = request_timing_row(
            self._ticket(status="failed", error="boom",
                         submitted_at=3.0),
            t0=1.0,
        )
        assert row["queued"] == 2.0
        assert row["admitted"] is None
        assert row["first_window"] is None
        assert row["last_streamed"] is None
        assert row["error"] == "boom"

    def test_write_server_meta_embeds_the_table(self, tmp_path):
        m = ServerMetrics()
        m.inc("retired")
        rows = [request_timing_row(
            self._ticket(status="done", submitted_at=time.perf_counter()),
            t0=m._t0,
        )]
        path = write_server_meta(
            str(tmp_path), {"bucket": {}}, m, requests=rows
        )
        meta = json.load(open(path))
        assert meta["counters"]["retired"] == 1
        assert meta["requests"][0]["rid"] == "req-000007"
        assert os.path.basename(path) == "server_meta.json"

    def test_write_server_meta_without_table_stays_compatible(
        self, tmp_path
    ):
        path = write_server_meta(str(tmp_path), {}, ServerMetrics())
        assert "requests" not in json.load(open(path))
