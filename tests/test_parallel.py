"""Distributed path: mesh, halo diffusion, sharded colony step.

Runs on the conftest's 8 virtual CPU devices — the multi-chip analogue of
the reference's (nonexistent) multi-node tests (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lens_tpu.environment import Lattice
from lens_tpu.models import ecoli_lattice
from lens_tpu.ops.diffusion import diffuse_xla
from lens_tpu.parallel import (
    ShardedSpatialColony,
    diffuse_halo,
    make_mesh,
)
from lens_tpu.parallel.mesh import spatial_pspecs, mesh_shardings


def make_flagship(capacity=64, shape=(32, 32), division=True, motility=True):
    cfg = {
        "capacity": capacity,
        "shape": shape,
        "size": (float(shape[0]), float(shape[1])),
        "diffusion": 2.0,
        "timestep": 1.0,
        "division": division,
    }
    if not motility:
        cfg["motility"] = {"sigma": 0.0}
    return ecoli_lattice(cfg)[0]


def test_halo_diffusion_matches_xla():
    """Sharded stencil == unsharded stencil, same Neumann boundaries."""
    mesh = make_mesh(n_agents=1, n_space=4)
    key = jax.random.PRNGKey(0)
    fields = jax.random.uniform(key, (3, 32, 16), minval=0.0, maxval=10.0)
    alpha = jnp.asarray([0.05, 0.1, 0.2])

    expected = diffuse_xla(fields, alpha, n_substeps=7)
    sharded = jax.jit(
        jax.shard_map(
            lambda f: diffuse_halo(f, alpha, 7, "space", 4),
            mesh=mesh,
            in_specs=(P(None, "space", None),),
            out_specs=P(None, "space", None),
        )
    )(fields)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(expected), rtol=1e-6)
    # mass conserved by the halo path too
    np.testing.assert_allclose(
        np.asarray(jnp.sum(sharded, axis=(1, 2))),
        np.asarray(jnp.sum(fields, axis=(1, 2))),
        rtol=1e-5,
    )


def test_sharded_matches_unsharded_deterministic():
    """With deterministic biology (no motility, no division), the 4x2-mesh
    trajectory equals the single-device trajectory."""
    spatial = make_flagship(division=False, motility=False)
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)

    ss0 = spatial.initial_state(64, jax.random.PRNGKey(1))
    ref, ref_emits = spatial.run(ss0, 8.0, 1.0, emit_every=4)

    ss0_sharded = jax.device_put(
        ss0, mesh_shardings(mesh, spatial_pspecs(ss0))
    )
    out, emits = sharded.run(ss0_sharded, 8.0, 1.0, emit_every=4)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref.colony.agents), jax.tree.leaves(out.colony.agents)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref_emits), jax.tree.leaves(emits)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )


def test_sharded_division_and_conservation():
    """Full stochastic run on the mesh: agents divide per shard, mass
    (field + internal pools) stays conserved, nothing goes non-finite."""
    # fast growth so divisions actually happen in a short test
    spatial = ecoli_lattice(
        {
            "capacity": 128,
            "shape": (32, 32),
            "size": (32.0, 32.0),
            "diffusion": 2.0,
            "timestep": 1.0,
            "growth": {"rate": 0.05},
            "transport": {"yield_": 1.0, "k_consume": 0.0},
        }
    )[0]
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)
    ss = sharded.initial_state(60, jax.random.PRNGKey(2))

    total0 = float(spatial.total_field_mass(ss)[0]) + float(
        jnp.sum(
            ss.colony.agents["cell"]["glucose_internal"] * ss.colony.alive
        )
    )
    n0 = int(jnp.sum(ss.colony.alive))
    out, _ = sharded.run(ss, 20.0, 1.0, emit_every=20)
    n1 = int(jnp.sum(out.colony.alive))
    total1 = float(spatial.total_field_mass(out)[0]) + float(
        jnp.sum(
            out.colony.agents["cell"]["glucose_internal"] * out.colony.alive
        )
    )
    assert n1 > n0, "expected divisions on the mesh"
    assert np.isfinite(
        np.asarray(jax.tree.leaves(out.colony.agents)[0])
    ).all()
    np.testing.assert_allclose(total1, total0, rtol=1e-4)


def test_sharded_chemotaxis_matches_unsharded():
    """Sense-only FieldPort (exchange=None) on the sharded runner.

    Regression for two round-1 bugs: (a) the sharded scatter crashed on
    ``exchange=None`` ports; (b) the sharded gather skipped the
    raw-vs-shared split, so sense-only ports saw occupancy-divided
    concentrations sharded but raw unsharded. Deterministic biology
    (receptor adaptation + MM consumption, zero-sigma motility, no
    division) with deliberately co-located agents — trajectories must be
    equal across paths.
    """
    from lens_tpu.colony.colony import Colony
    from lens_tpu.core.engine import Compartment
    from lens_tpu.environment.spatial import SpatialColony
    from lens_tpu.processes.chemotaxis import MWCChemoreceptor
    from lens_tpu.processes.mm_transport import (
        BrownianMotility,
        MichaelisMentenTransport,
    )

    comp = Compartment(
        processes={
            "receptor": MWCChemoreceptor(
                {"molecule": "asp", "external_default": 0.1}
            ),
            "transport": MichaelisMentenTransport(
                {"molecule": "glucose", "external_default": 1.0}
            ),
            "motility": BrownianMotility({"sigma": 0.0}),
        },
        topology={
            "receptor": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
            },
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
        },
    )
    colony = Colony(comp, capacity=64)
    lattice = Lattice(
        molecules=["glucose", "asp"],
        shape=(16, 16),
        size=(16.0, 16.0),
        diffusion=1.0,
        initial={"glucose": 1.0, "asp": 0.1},
        timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (
                ("boundary", "external", "glucose"),
                ("boundary", "exchange", "glucose_exchange"),
            ),
            # sense-only: read the attractant, never consume it
            "asp": (("boundary", "external", "asp"), None),
        },
        location_path=("boundary", "location"),
    )
    # co-locate agents in pairs so shared-bin occupancy actually divides
    pair_rows = np.repeat(np.linspace(0.5, 15.5, 32), 2)
    locations = np.stack(
        [pair_rows, np.full(64, 7.5, np.float32)], axis=1
    ).astype(np.float32)
    ss0 = spatial.initial_state(64, jax.random.PRNGKey(3), locations=locations)
    # gradient on the sensed molecule so receptor dynamics are non-trivial
    h, w = lattice.shape
    asp = jnp.broadcast_to(jnp.linspace(0.0, 0.5, w)[None, :], (h, w))
    fields = ss0.fields.at[lattice.index("asp")].set(asp)
    ss0 = ss0._replace(fields=fields)

    ref, _ = spatial.run(ss0, 8.0, 1.0, emit_every=8)

    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)
    ss0_sharded = jax.device_put(ss0, mesh_shardings(mesh, spatial_pspecs(ss0)))
    out, _ = sharded.run(ss0_sharded, 8.0, 1.0, emit_every=8)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref.colony.agents), jax.tree.leaves(out.colony.agents)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )


def test_mesh_validation():
    mesh = make_mesh(n_agents=4, n_space=2)
    spatial = make_flagship(capacity=66)  # 66 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        ShardedSpatialColony(spatial, mesh)


# -- mixed species on the mesh ------------------------------------------------


def make_two_species(capacity=32, shape=(16, 16), division=False):
    """Two DISTINCT deterministic process sets on one lattice: species
    ``a`` consumes glucose; species ``b`` consumes acetate AND senses
    glucose through a sense-only port (exchange=None). Zero-sigma
    motility so trajectories are deterministic."""
    from lens_tpu.colony.colony import Colony
    from lens_tpu.core.engine import Compartment
    from lens_tpu.environment.multispecies import MultiSpeciesColony
    from lens_tpu.environment.spatial import SpatialColony
    from lens_tpu.processes.chemotaxis import MWCChemoreceptor
    from lens_tpu.processes.growth import DivideTrigger, Growth
    from lens_tpu.processes.mm_transport import (
        BrownianMotility,
        MichaelisMentenTransport,
    )

    lattice = Lattice(
        molecules=["glucose", "acetate"],
        shape=shape,
        size=(float(shape[0]), float(shape[1])),
        diffusion=1.0,
        initial={"glucose": 10.0, "acetate": 5.0},
        timestep=1.0,
    )

    def build(processes, topology, ports):
        comp = Compartment(processes=processes, topology=topology)
        colony = Colony(
            comp,
            capacity=capacity,
            division_trigger=("global", "divide") if division else None,
        )
        return SpatialColony(
            colony, lattice, field_ports=ports,
            location_path=("boundary", "location"),
        )

    growth_cfg = {"rate": 0.04} if division else {}
    a_procs = {
        "transport": MichaelisMentenTransport(
            {"molecule": "glucose", "yield_": 1.0, "k_consume": 0.0}
        ),
        "motility": BrownianMotility({"sigma": 0.0}),
    }
    a_topo = {
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
        "motility": {"boundary": ("boundary",)},
    }
    b_procs = {
        "transport": MichaelisMentenTransport(
            {"molecule": "acetate", "vmax": 0.05, "yield_": 1.0,
             "k_consume": 0.0, "external_default": 5.0}
        ),
        "receptor": MWCChemoreceptor(
            {"molecule": "glucose", "external_default": 10.0}
        ),
        "motility": BrownianMotility({"sigma": 0.0}),
    }
    b_topo = {
        "transport": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
            "exchange": ("boundary", "exchange"),
        },
        "receptor": {
            "external": ("boundary", "external"),
            "internal": ("cell",),
        },
        "motility": {"boundary": ("boundary",)},
    }
    if division:
        for procs, topo in ((a_procs, a_topo), (b_procs, b_topo)):
            procs["growth"] = Growth(growth_cfg)
            procs["divide_trigger"] = DivideTrigger({})
            topo["growth"] = {"global": ("global",)}
            topo["divide_trigger"] = {"global": ("global",)}

    a = build(
        a_procs, a_topo,
        {
            "glucose": (
                ("boundary", "external", "glucose"),
                ("boundary", "exchange", "glucose_exchange"),
            )
        },
    )
    b = build(
        b_procs, b_topo,
        {
            "acetate": (
                ("boundary", "external", "acetate"),
                ("boundary", "exchange", "acetate_exchange"),
            ),
            # sense-only: b reads glucose, never consumes it
            "glucose": (("boundary", "external", "glucose"), None),
        },
    )
    return MultiSpeciesColony(species={"a": a, "b": b}, lattice=lattice)


def test_sharded_multispecies_matches_unsharded():
    """VERDICT r2 item 1: the mixed-species flagship on a 4x2 mesh equals
    the single-device trajectory. Cross-species co-located agents
    exercise combined occupancy; species b's sense-only glucose port
    exercises the raw-vs-shared gather split across species."""
    from lens_tpu.parallel import ShardedMultiSpeciesColony
    from lens_tpu.parallel.mesh import multispecies_pspecs

    multi = make_two_species()
    # co-locate one agent of EACH species per bin along a row, so the
    # combined (cross-species) occupancy in shared bins is 2
    rows = np.linspace(0.5, 15.5, 32).astype(np.float32)
    locs = np.stack([rows, np.full(32, 7.5, np.float32)], axis=1)
    ms0 = multi.initial_state(
        {"a": 32, "b": 32},
        jax.random.PRNGKey(7),
        locations={"a": locs, "b": locs},
    )
    ref, ref_emits = multi.run(ms0, 8.0, 1.0, emit_every=4)

    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedMultiSpeciesColony(multi, mesh)
    ms0_sharded = jax.device_put(
        ms0, mesh_shardings(mesh, multispecies_pspecs(ms0))
    )
    out, emits = sharded.run(ms0_sharded, 8.0, 1.0, emit_every=4)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
    for name in multi.species:
        for ref_leaf, leaf in zip(
            jax.tree.leaves(ref.species[name].agents),
            jax.tree.leaves(out.species[name].agents),
        ):
            np.testing.assert_allclose(
                np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
            )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref_emits), jax.tree.leaves(emits)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )


def test_sharded_multispecies_division_and_conservation():
    """Full mixed-species run on the mesh with division: both species
    divide per shard, every molecule's (field + internal-pool) mass is
    conserved, nothing goes non-finite."""
    from lens_tpu.parallel import ShardedMultiSpeciesColony

    multi = make_two_species(capacity=64, division=True)
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedMultiSpeciesColony(multi, mesh)
    ms = sharded.initial_state(
        {"a": 24, "b": 24}, jax.random.PRNGKey(8)
    )

    def mass(state, mol, species, pool):
        m = float(jnp.sum(state.fields[multi.lattice.index(mol)]))
        cs = state.species[species]
        return m + float(
            jnp.sum(cs.agents["cell"][pool] * cs.alive)
        )

    g0 = mass(ms, "glucose", "a", "glucose_internal")
    a0 = mass(ms, "acetate", "b", "acetate_internal")
    n0 = {k: int(jnp.sum(ms.species[k].alive)) for k in multi.species}
    out, _ = sharded.run(ms, 25.0, 1.0, emit_every=25)
    n1 = {k: int(jnp.sum(out.species[k].alive)) for k in multi.species}
    assert n1["a"] > n0["a"], "species a should divide on the mesh"
    assert n1["b"] > n0["b"], "species b should divide on the mesh"
    for name in multi.species:
        for leaf in jax.tree.leaves(out.species[name].agents):
            assert np.isfinite(np.asarray(leaf)).all()
    np.testing.assert_allclose(
        mass(out, "glucose", "a", "glucose_internal"), g0, rtol=1e-4
    )
    np.testing.assert_allclose(
        mass(out, "acetate", "b", "acetate_internal"), a0, rtol=1e-4
    )


def test_sharded_division_with_binomial_divider():
    """Regression: jax.random.binomial's internal while_loop is not
    VMA-safe under shard_map, so division of binomial-divided counts
    leaves (stochastic expression's molecule counts) used to fail to
    trace on the mesh. The flagship mixed-species config exercises it."""
    from lens_tpu.models import mixed_species_lattice
    from lens_tpu.parallel import ShardedMultiSpeciesColony

    multi, _ = mixed_species_lattice(
        {
            "capacity": {"ecoli": 32, "scavenger": 32},
            "shape": (16, 16),
            "size": (16.0, 16.0),
            "ecoli": {"growth": {"rate": 0.04}},
            "scavenger": {"growth": {"rate": 0.04}},
        }
    )
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedMultiSpeciesColony(multi, mesh)
    ms = sharded.initial_state(
        {"ecoli": 12, "scavenger": 12}, jax.random.PRNGKey(11)
    )
    n0 = {k: int(jnp.sum(ms.species[k].alive)) for k in multi.species}
    out, _ = sharded.run(ms, 25.0, 1.0, emit_every=25)
    n1 = {k: int(jnp.sum(out.species[k].alive)) for k in multi.species}
    assert n1["scavenger"] > n0["scavenger"]
    counts = out.species["scavenger"].agents["counts"]
    for leaf in jax.tree.leaves(counts):
        arr = np.asarray(leaf)
        assert np.isfinite(arr).all()
        # binomial-divided counts stay integral through division
        np.testing.assert_allclose(arr, np.round(arr))


def test_multispecies_mesh_validation():
    from lens_tpu.parallel import ShardedMultiSpeciesColony

    multi = make_two_species(capacity=30)  # 30 % 4 != 0
    mesh = make_mesh(n_agents=4, n_space=2)
    with pytest.raises(ValueError, match="species 'a'.*divisible"):
        ShardedMultiSpeciesColony(multi, mesh)


def test_sharded_division_backlog_bound(monkeypatch=None):
    """VERDICT r4 item 7: quantify the per-shard division-pool divergence.

    Division is per-shard by design (free rows never cross a shard
    boundary), so a SATURATED shard suppresses divisions even while
    other shards have room — the one place sharded biology can diverge
    from unsharded. This test pins both sides of the story:

    - STRIPED init (the default): synchronized growth keeps every
      shard's pool equally loaded, and the global ``division_backlog``
      trajectory is IDENTICAL to the unsharded run's (bound: zero
      divergence) through three full division waves to saturation.
    - CONTIGUOUS init: the same population packed into one shard shows
      nonzero backlog from the first wave while the unsharded run's is
      still zero — exactly why ``stripe`` is the default.
    """
    cfg = {
        "capacity": 64,
        "shape": (8, 8),
        "size": (8.0, 8.0),
        "diffusion": 2.0,
        "timestep": 1.0,
        "division": True,
        "motility": {"sigma": 0.0},
        "growth": {"rate": 0.05},   # volume doubles every ~14 s
    }
    spatial = ecoli_lattice(cfg)[0]
    key = jax.random.PRNGKey(5)

    _, ref_emits = spatial.run(
        spatial.initial_state(8, key), 50.0, 1.0, emit_every=1
    )
    ref_backlog = np.asarray(ref_emits["division_backlog"])
    ref_alive = np.asarray(ref_emits["alive"]).sum(axis=1)
    # capacity 64 >= the 8 -> 64 growth: unsharded never suppresses
    assert (ref_backlog == 0).all()
    assert ref_alive[-1] == 64

    mesh = make_mesh(n_agents=8, n_space=1)
    sharded = ShardedSpatialColony(ecoli_lattice(cfg)[0], mesh)

    striped = sharded.initial_state(8, key, stripe=True)
    _, emits = sharded.run(striped, 50.0, 1.0, emit_every=1)
    striped_backlog = np.asarray(emits["division_backlog"])
    striped_alive = np.asarray(emits["alive"]).sum(axis=1)
    np.testing.assert_array_equal(striped_backlog, ref_backlog)
    np.testing.assert_array_equal(striped_alive, ref_alive)

    contiguous = sharded.initial_state(8, key, stripe=False)
    _, emits = sharded.run(contiguous, 50.0, 1.0, emit_every=1)
    cont_backlog = np.asarray(emits["division_backlog"])
    cont_alive = np.asarray(emits["alive"]).sum(axis=1)
    # the packed shard saturates immediately: suppression is visible in
    # the backlog counter AND in the stunted population
    assert cont_backlog.max() >= 8
    assert cont_alive[-1] < ref_alive[-1]


def test_sharded_full_network_rfba_matches_unsharded():
    """The flagship biology x the parallel machinery: a colony of
    72x95-network rFBA agents (warm-started IPM per agent per step,
    lp_state threaded through the sharded rows) on a 4x2 mesh must
    reproduce the unsharded trajectory — fields to float tolerance,
    per-agent growth telemetry included."""
    from lens_tpu.models.composites import rfba_lattice

    def build():
        spatial, _ = rfba_lattice(
            {
                "capacity": 16,
                "shape": (8, 8),
                "division": False,
                "motility": {"sigma": 0.0},
                "metabolism": {"network": "ecoli_core_full"},
            }
        )
        return spatial

    spatial = build()
    ss0 = spatial.initial_state(16, jax.random.PRNGKey(4))
    ref, ref_emits = spatial.run(ss0, 6.0, 1.0, emit_every=3)

    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(build(), mesh)
    ss0_sharded = jax.device_put(
        ss0, mesh_shardings(mesh, spatial_pspecs(ss0))
    )
    out, emits = sharded.run(ss0_sharded, 6.0, 1.0, emit_every=3)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(emits["fluxes"]["growth_rate"]),
        np.asarray(ref_emits["fluxes"]["growth_rate"]),
        rtol=1e-3, atol=1e-4,
    )
    # every agent's LP converged on both paths
    assert float(np.asarray(emits["fluxes"]["lp_converged"]).min()) == 1.0
    assert float(np.asarray(ref_emits["fluxes"]["lp_converged"]).min()) == 1.0


# -- replicate-parallel ensembles -------------------------------------------


class TestShardedEnsemble:
    """The replicate axis sharded over the mesh: zero collectives, and
    the program must be bitwise the single-device Ensemble program."""

    def _toggle_ensemble(self, r=8, n=16):
        from lens_tpu.colony import Colony, Ensemble
        from lens_tpu.models.composites import toggle_colony

        colony = Colony(toggle_colony({}), capacity=n)
        return Ensemble(colony, r)

    def test_sharded_matches_unsharded_bitwise(self):
        from lens_tpu.parallel import ShardedEnsemble

        ens = self._toggle_ensemble()
        key = jax.random.PRNGKey(0)
        ref_final, ref_traj = ens.run(
            ens.initial_state(16, key=key), 10.0, 1.0, emit_every=5
        )

        sharded = ShardedEnsemble(ens)
        states = sharded.initial_state(16, key=key)
        # the replicate axis really is split across all 8 devices
        assert len(states.alive.sharding.device_set) == 8
        final, traj = sharded.run(states, 10.0, 1.0, emit_every=5)
        for la, lb in zip(jax.tree.leaves(final), jax.tree.leaves(ref_final)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        for la, lb in zip(jax.tree.leaves(traj), jax.tree.leaves(ref_traj)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_sharded_timeline_matches_unsharded(self):
        from lens_tpu.colony import Ensemble
        from lens_tpu.parallel import ShardedEnsemble

        spatial, _ = ecoli_lattice(
            {"capacity": 16, "shape": (8, 8), "size": (8.0, 8.0),
             "division": False, "motility": {"sigma": 0.0}}
        )
        ens = Ensemble(spatial, 8)
        key = jax.random.PRNGKey(1)
        timeline = "0 minimal, 4 minimal_low_glucose"
        ref_final, ref_traj = ens.run_timeline(
            ens.initial_state(4, key=key), timeline, 8.0, 1.0
        )
        sharded = ShardedEnsemble(ens)
        final, traj = sharded.run_timeline(
            sharded.initial_state(4, key=key), timeline, 8.0, 1.0
        )
        np.testing.assert_array_equal(
            np.asarray(final.fields), np.asarray(ref_final.fields)
        )
        np.testing.assert_array_equal(
            np.asarray(traj["fields"]), np.asarray(ref_traj["fields"])
        )

    def test_indivisible_replicates_rejected(self):
        from lens_tpu.parallel import ShardedEnsemble

        ens = self._toggle_ensemble(r=6)
        with pytest.raises(ValueError, match="does not divide"):
            ShardedEnsemble(ens)


def test_sharded_death_matches_unsharded():
    """Death is shard-local (one mask update per block): a starving
    sharded colony tracks the unsharded trajectory exactly, and freed
    rows stay within their shard's division pool."""
    def build():
        return ecoli_lattice(
            {
                "capacity": 32,
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "division": False,
                "motility": {"sigma": 0.0},
                # almost no glucose: pools drain, everyone starves
                "initial_glucose": 0.001,
                "death": {"threshold": 0.02},
            }
        )[0]

    spatial = build()
    key = jax.random.PRNGKey(0)
    yolk = {"cell": {"glucose_internal": jnp.full(32, 0.05)}}
    ss0 = spatial.initial_state(32, key, overrides=yolk)
    ref, ref_traj = spatial.run(ss0, 30.0, 1.0, emit_every=10)
    ref_alive = np.asarray(ref_traj["alive"]).sum(axis=1)
    assert ref_alive[-1] == 0 and ref_alive[0] > 0  # they did starve

    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(build(), mesh)
    ss0_sharded = jax.device_put(
        ss0, mesh_shardings(mesh, spatial_pspecs(ss0))
    )
    out, traj = sharded.run(ss0_sharded, 30.0, 1.0, emit_every=10)
    np.testing.assert_array_equal(
        np.asarray(traj["alive"]), np.asarray(ref_traj["alive"])
    )
    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
