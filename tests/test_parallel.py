"""Distributed path: mesh, halo diffusion, sharded colony step.

Runs on the conftest's 8 virtual CPU devices — the multi-chip analogue of
the reference's (nonexistent) multi-node tests (SURVEY.md §4).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from lens_tpu.environment import Lattice
from lens_tpu.models import ecoli_lattice
from lens_tpu.ops.diffusion import diffuse_xla
from lens_tpu.parallel import (
    ShardedSpatialColony,
    diffuse_halo,
    make_mesh,
)
from lens_tpu.parallel.mesh import spatial_pspecs, mesh_shardings


def make_flagship(capacity=64, shape=(32, 32), division=True, motility=True):
    cfg = {
        "capacity": capacity,
        "shape": shape,
        "size": (float(shape[0]), float(shape[1])),
        "diffusion": 2.0,
        "timestep": 1.0,
        "division": division,
    }
    if not motility:
        cfg["motility"] = {"sigma": 0.0}
    return ecoli_lattice(cfg)[0]


def test_halo_diffusion_matches_xla():
    """Sharded stencil == unsharded stencil, same Neumann boundaries."""
    mesh = make_mesh(n_agents=1, n_space=4)
    key = jax.random.PRNGKey(0)
    fields = jax.random.uniform(key, (3, 32, 16), minval=0.0, maxval=10.0)
    alpha = jnp.asarray([0.05, 0.1, 0.2])

    expected = diffuse_xla(fields, alpha, n_substeps=7)
    sharded = jax.jit(
        jax.shard_map(
            lambda f: diffuse_halo(f, alpha, 7, "space", 4),
            mesh=mesh,
            in_specs=(P(None, "space", None),),
            out_specs=P(None, "space", None),
        )
    )(fields)
    np.testing.assert_allclose(np.asarray(sharded), np.asarray(expected), rtol=1e-6)
    # mass conserved by the halo path too
    np.testing.assert_allclose(
        np.asarray(jnp.sum(sharded, axis=(1, 2))),
        np.asarray(jnp.sum(fields, axis=(1, 2))),
        rtol=1e-5,
    )


def test_sharded_matches_unsharded_deterministic():
    """With deterministic biology (no motility, no division), the 4x2-mesh
    trajectory equals the single-device trajectory."""
    spatial = make_flagship(division=False, motility=False)
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)

    ss0 = spatial.initial_state(64, jax.random.PRNGKey(1))
    ref, ref_emits = spatial.run(ss0, 8.0, 1.0, emit_every=4)

    ss0_sharded = jax.device_put(
        ss0, mesh_shardings(mesh, spatial_pspecs(ss0))
    )
    out, emits = sharded.run(ss0_sharded, 8.0, 1.0, emit_every=4)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref.colony.agents), jax.tree.leaves(out.colony.agents)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref_emits), jax.tree.leaves(emits)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )


def test_sharded_division_and_conservation():
    """Full stochastic run on the mesh: agents divide per shard, mass
    (field + internal pools) stays conserved, nothing goes non-finite."""
    # fast growth so divisions actually happen in a short test
    spatial = ecoli_lattice(
        {
            "capacity": 128,
            "shape": (32, 32),
            "size": (32.0, 32.0),
            "diffusion": 2.0,
            "timestep": 1.0,
            "growth": {"rate": 0.05},
            "transport": {"yield_": 1.0, "k_consume": 0.0},
        }
    )[0]
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)
    ss = sharded.initial_state(60, jax.random.PRNGKey(2))

    total0 = float(spatial.total_field_mass(ss)[0]) + float(
        jnp.sum(
            ss.colony.agents["cell"]["glucose_internal"] * ss.colony.alive
        )
    )
    n0 = int(jnp.sum(ss.colony.alive))
    out, _ = sharded.run(ss, 20.0, 1.0, emit_every=20)
    n1 = int(jnp.sum(out.colony.alive))
    total1 = float(spatial.total_field_mass(out)[0]) + float(
        jnp.sum(
            out.colony.agents["cell"]["glucose_internal"] * out.colony.alive
        )
    )
    assert n1 > n0, "expected divisions on the mesh"
    assert np.isfinite(
        np.asarray(jax.tree.leaves(out.colony.agents)[0])
    ).all()
    np.testing.assert_allclose(total1, total0, rtol=1e-4)


def test_sharded_chemotaxis_matches_unsharded():
    """Sense-only FieldPort (exchange=None) on the sharded runner.

    Regression for two round-1 bugs: (a) the sharded scatter crashed on
    ``exchange=None`` ports; (b) the sharded gather skipped the
    raw-vs-shared split, so sense-only ports saw occupancy-divided
    concentrations sharded but raw unsharded. Deterministic biology
    (receptor adaptation + MM consumption, zero-sigma motility, no
    division) with deliberately co-located agents — trajectories must be
    equal across paths.
    """
    from lens_tpu.colony.colony import Colony
    from lens_tpu.core.engine import Compartment
    from lens_tpu.environment.spatial import SpatialColony
    from lens_tpu.processes.chemotaxis import MWCChemoreceptor
    from lens_tpu.processes.mm_transport import (
        BrownianMotility,
        MichaelisMentenTransport,
    )

    comp = Compartment(
        processes={
            "receptor": MWCChemoreceptor(
                {"molecule": "asp", "external_default": 0.1}
            ),
            "transport": MichaelisMentenTransport(
                {"molecule": "glucose", "external_default": 1.0}
            ),
            "motility": BrownianMotility({"sigma": 0.0}),
        },
        topology={
            "receptor": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
            },
            "transport": {
                "external": ("boundary", "external"),
                "internal": ("cell",),
                "exchange": ("boundary", "exchange"),
            },
            "motility": {"boundary": ("boundary",)},
        },
    )
    colony = Colony(comp, capacity=64)
    lattice = Lattice(
        molecules=["glucose", "asp"],
        shape=(16, 16),
        size=(16.0, 16.0),
        diffusion=1.0,
        initial={"glucose": 1.0, "asp": 0.1},
        timestep=1.0,
    )
    spatial = SpatialColony(
        colony,
        lattice,
        field_ports={
            "glucose": (
                ("boundary", "external", "glucose"),
                ("boundary", "exchange", "glucose_exchange"),
            ),
            # sense-only: read the attractant, never consume it
            "asp": (("boundary", "external", "asp"), None),
        },
        location_path=("boundary", "location"),
    )
    # co-locate agents in pairs so shared-bin occupancy actually divides
    pair_rows = np.repeat(np.linspace(0.5, 15.5, 32), 2)
    locations = np.stack(
        [pair_rows, np.full(64, 7.5, np.float32)], axis=1
    ).astype(np.float32)
    ss0 = spatial.initial_state(64, jax.random.PRNGKey(3), locations=locations)
    # gradient on the sensed molecule so receptor dynamics are non-trivial
    h, w = lattice.shape
    asp = jnp.broadcast_to(jnp.linspace(0.0, 0.5, w)[None, :], (h, w))
    fields = ss0.fields.at[lattice.index("asp")].set(asp)
    ss0 = ss0._replace(fields=fields)

    ref, _ = spatial.run(ss0, 8.0, 1.0, emit_every=8)

    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)
    ss0_sharded = jax.device_put(ss0, mesh_shardings(mesh, spatial_pspecs(ss0)))
    out, _ = sharded.run(ss0_sharded, 8.0, 1.0, emit_every=8)

    np.testing.assert_allclose(
        np.asarray(out.fields), np.asarray(ref.fields), rtol=1e-5, atol=1e-6
    )
    for ref_leaf, leaf in zip(
        jax.tree.leaves(ref.colony.agents), jax.tree.leaves(out.colony.agents)
    ):
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(ref_leaf), rtol=1e-5, atol=1e-6
        )


def test_mesh_validation():
    mesh = make_mesh(n_agents=4, n_space=2)
    spatial = make_flagship(capacity=66)  # 66 % 4 != 0
    with pytest.raises(ValueError, match="divisible"):
        ShardedSpatialColony(spatial, mesh)
