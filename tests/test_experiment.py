"""Experiment layer: registry boot, segmented runs, checkpoint/resume, CLI."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.emit import RamEmitter
from lens_tpu.experiment import Experiment


class TestExperiment:
    def test_colony_experiment_runs_and_emits(self):
        with Experiment(
            {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 64,
                "total_time": 30.0,
                "emit_every": 10,
            }
        ) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        assert ts["cell"]["protein_u"].shape == (3, 64)
        np.testing.assert_allclose(ts["__time__"], [10.0, 20.0, 30.0])

    def test_spatial_experiment_runs(self):
        with Experiment(
            {
                "composite": "ecoli_lattice",
                "config": {
                    "capacity": 16,
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                },
                "n_agents": 8,
                "total_time": 5.0,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(exp.n_alive(state))) == 8
            ts = exp.emitter.timeseries()
        assert ts["fields"].shape == (5, 1, 8, 8)

    def test_unknown_composite_raises(self):
        with pytest.raises(ValueError, match="unknown composite"):
            Experiment({"composite": "nope"})

    def test_division_grows_population(self):
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.01}},
                "n_agents": 2,
                "capacity": 64,
                "total_time": 120.0,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(exp.n_alive(state))) > 2


class TestReplicatesExperiment:
    """'replicates' runs colony.Ensemble through the config-driven layer."""

    def test_colony_replicates_emit_fan_layout(self):
        with Experiment(
            {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 16,
                "total_time": 20.0,
                "emit_every": 10,
                "replicates": 3,
            }
        ) as exp:
            state = exp.run()
            assert state.alive.shape == (3, 16)
            assert int(np.asarray(exp.n_alive(state))) == 3 * 4
            ts = exp.emitter.timeseries()
        assert ts["cell"]["protein_u"].shape == (2, 3, 16)  # [T, R, N]

    def test_replicate_overrides_scan_through_config(self):
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.02}},
                "n_agents": 1,
                "capacity": 16,
                "total_time": 40.0,
                "emit_every": 40,
                "replicates": 3,
                "replicate_overrides": {
                    "global": {"volume": [1.0, 1.4, 1.9]}
                },
            }
        ) as exp:
            state = exp.run()
        pops = np.asarray(state.alive).sum(axis=1)
        assert pops[2] >= pops[0] and pops[2] > 1

    def test_log_header_provenance_and_scan_autoplot(self, tmp_path):
        """The log header records the FULL experiment config, and
        `analyze` derives the dose-response plot from it without the
        user re-supplying the scanned values."""
        import os

        from lens_tpu.analysis import load, report

        log = str(tmp_path / "scan.lens")
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.02}},
                "n_agents": 1,
                "capacity": 16,
                "total_time": 20.0,
                "emit_every": 10,
                "replicates": 3,
                "replicate_overrides": {
                    "global": {"volume": [1.0, 1.4, 1.9]}
                },
                "emitter": {"type": "log", "path": log},
            }
        ) as exp:
            exp.run()
        header, _ = load(log)
        assert header["config"]["composite"] == "grow_divide"
        assert header["config"]["replicate_overrides"]["global"][
            "volume"
        ] == [1.0, 1.4, 1.9]
        written = report(log, out_dir=str(tmp_path / "plots"))
        assert "scan_response" in written
        assert os.path.getsize(written["scan_response"]) > 1000

    def test_resume_keeps_original_provenance(self, tmp_path):
        """A resume appends its own header; the log must still report the
        CREATING run's config (first header wins), so the scan auto-plot
        survives resumes that don't re-pass replicate_overrides."""
        from lens_tpu.analysis import load, report

        log = str(tmp_path / "scan.lens")

        def cfg(total, overrides):
            return {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.02}},
                "n_agents": 1,
                "capacity": 16,
                "total_time": total,
                "emit_every": 10,
                "replicates": 3,
                "replicate_overrides": overrides,
                "emitter": {"type": "log", "path": log},
                "checkpoint_dir": str(tmp_path / "ckpt"),
                "checkpoint_every": 10.0,
            }

        scan = {"global": {"volume": [1.0, 1.4, 1.9]}}
        with Experiment(cfg(20.0, scan)) as exp:
            exp.run()
        with Experiment(cfg(40.0, {})) as exp:  # resume WITHOUT the scan
            exp.resume()
        header, ts = load(log)
        assert header["config"]["replicate_overrides"] == {
            "global": {"volume": [1.0, 1.4, 1.9]}
        }
        assert ts["alive"].shape[0] == 4  # 20s + 20s of emits
        written = report(log, out_dir=str(tmp_path / "plots"))
        assert "scan_response" in written

    def test_replicates_checkpoint_resume_bitwise(self, tmp_path):
        def cfg(base, total):
            return {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 16,
                "total_time": total,
                "checkpoint_dir": str(base / "ckpt"),
                "checkpoint_every": 10.0,
                "emitter": {"type": "null"},
                "replicates": 2,
            }

        with Experiment(cfg(tmp_path / "a", 40.0)) as exp:
            full = exp.run()
        with Experiment(cfg(tmp_path / "b", 20.0)) as exp:
            exp.run()
        with Experiment(cfg(tmp_path / "b", 40.0)) as exp:
            resumed = exp.resume()
        for la, lb in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_multispecies_replicates_run(self):
        with Experiment(
            {
                "composite": "mixed_species_lattice",
                "config": {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                },
                "n_agents": {"ecoli": 4, "scavenger": 4},
                "total_time": 4.0,
                "emit_every": 2,
                "replicates": 2,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(exp.n_alive(state))) >= 2 * 8
            ts = exp.emitter.timeseries()
        assert ts["fields"].shape[:2] == (2, 2)  # [T, R, ...]

    def test_resume_replicates_mismatch_fails_loudly(self, tmp_path):
        """Resuming an ensemble checkpoint with the wrong (or no)
        replicates/capacity config must raise at restore, not explode
        (or silently mis-step) inside jit."""

        def cfg(**kw):
            base = {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 16,
                "total_time": 20.0,
                "checkpoint_dir": str(tmp_path / "ckpt"),
                "checkpoint_every": 10.0,
                "emitter": {"type": "null"},
                "replicates": 2,
            }
            base.update(kw)
            return base

        with Experiment(cfg()) as exp:
            exp.run()
        with Experiment(cfg(total_time=40.0, replicates=None)) as exp:
            with pytest.raises(ValueError, match="does not set 'replicates'"):
                exp.resume()
        with Experiment(cfg(total_time=40.0, replicates=3)) as exp:
            with pytest.raises(ValueError, match="replicates=3"):
                exp.resume()
        # capacity edits ADOPT the checkpoint (state is authoritative,
        # same semantics as unreplicated runs): resume continues at the
        # checkpointed 16 rows, not the config's 32
        with Experiment(cfg(total_time=40.0, capacity=32)) as exp:
            resumed = exp.resume()
        assert resumed.alive.shape == (2, 16)
        assert exp.colony.capacity == 16
        assert exp.ensemble.sim is exp.colony

    def test_multispecies_replicates_resume(self, tmp_path):
        """The capacity-adoption probe must read the ROW axis (last), not
        the replicate axis, for every species."""

        def cfg(total):
            return {
                "composite": "mixed_species_lattice",
                "config": {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                },
                "n_agents": {"ecoli": 4, "scavenger": 4},
                "total_time": total,
                "emit_every": 2,
                "replicates": 2,
                "checkpoint_dir": str(tmp_path / "ckpt"),
                "checkpoint_every": 2.0,
                "emitter": {"type": "null"},
            }

        with Experiment(cfg(4.0)) as exp:
            exp.run()
        with Experiment(cfg(8.0)) as exp:
            state = exp.resume()
        assert state.species["ecoli"].alive.shape == (2, 8)
        assert exp._state_step(state) == 8

    def test_replicates_with_timeline(self):
        """Media timelines vmap over the replicate axis: every replicate
        sees the same media shift, and replicate r equals a solo
        run_timeline with that replicate's key."""
        cfg = {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 16,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "motility": {"sigma": 0.0},
            },
            "n_agents": 8,
            "total_time": 8.0,
            "timeline": "0 minimal, 4 minimal_low_glucose",
            "seed": 3,
            "replicates": 2,
        }
        with Experiment(cfg) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        fields = np.asarray(ts["fields"])  # [T=8, R=2, 1, 8, 8]
        assert fields.shape[:2] == (8, 2)
        # both replicates see the shift: pre-shift minimal (10 mM),
        # post-shift reset to 0.5 mM
        assert (fields[3].mean(axis=(1, 2, 3)) > 5.0).all()
        assert (fields[4].mean(axis=(1, 2, 3)) < 1.0).all()

        # replicate 0 == solo run_timeline with replicate 0's key
        from lens_tpu.models import ecoli_lattice as _el

        spatial, _ = _el(dict(cfg["config"]))
        keys = jax.random.split(jax.random.PRNGKey(3), 2)
        solo0 = spatial.initial_state(8, keys[0])
        _, solo_traj = spatial.run_timeline(
            solo0, cfg["timeline"], 8.0, 1.0
        )
        np.testing.assert_allclose(
            fields[:, 0], np.asarray(solo_traj["fields"]),
            rtol=1e-6, atol=1e-6,
        )

    def test_replicate_mesh_through_config(self):
        """mesh={'replicates': N} splits the replicate axis over N
        devices and stays equal to the unsharded replicates run."""

        def cfg(mesh=None):
            return {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 16,
                "total_time": 10.0,
                "emit_every": 5,
                "replicates": 8,
                "mesh": mesh,
            }

        with Experiment(cfg()) as exp:
            ref = exp.run()
            ref_ts = exp.emitter.timeseries()
        with Experiment(cfg({"replicates": 8})) as exp:
            assert exp.ensemble_runner is not None
            state = exp.run()
            assert len(state.alive.sharding.device_set) == 8
            ts = exp.emitter.timeseries()
        for la, lb in zip(jax.tree.leaves(ref), jax.tree.leaves(state)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))
        np.testing.assert_array_equal(
            np.asarray(ref_ts["cell"]["protein_u"]),
            np.asarray(ts["cell"]["protein_u"]),
        )

    def test_replicate_mesh_resume_stays_sharded_and_bitwise(self, tmp_path):
        def cfg(base, total, mesh=None):
            return {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 16,
                "total_time": total,
                "checkpoint_dir": str(base / "ckpt"),
                "checkpoint_every": 10.0,
                "emitter": {"type": "null"},
                "replicates": 8,
                "mesh": mesh,
            }

        mesh = {"replicates": 8}
        with Experiment(cfg(tmp_path / "a", 40.0, mesh)) as exp:
            full = exp.run()
        with Experiment(cfg(tmp_path / "b", 20.0, mesh)) as exp:
            exp.run()
        with Experiment(cfg(tmp_path / "b", 40.0, mesh)) as exp:
            resumed = exp.resume()
        # the resumed run kept the 8-way replicate split
        assert len(resumed.alive.sharding.device_set) == 8
        for la, lb in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_replicates_auto_expand_grows_every_replicate(self, tmp_path):
        """Capacity growth composes with the replicate axis: every
        replicate's colony expands (shared capacity, tightest pool
        decides), divisions are never suppressed, and lineage ids stay
        unique per replicate."""
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.05}},
                "n_agents": 6,
                "capacity": 8,
                "total_time": 60.0,
                "checkpoint_every": 5.0,
                "auto_expand": {"free_frac": 0.3, "factor": 2},
                "replicates": 2,
                "checkpoint_dir": str(tmp_path / "ckpt"),
            }
        ) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        alive = np.asarray(state.alive)  # [R, rows]
        assert alive.shape[0] == 2 and alive.shape[1] > 8
        assert (alive.sum(axis=1) >= 4 * 6).all()  # every replicate 4x'd
        assert (np.asarray(ts["division_backlog"]) == 0).all()
        ids = np.asarray(state.agents["lineage"]["cell_id"])
        for r in range(2):
            live = ids[r][alive[r]]
            assert len(np.unique(live)) == len(live)
        # resume adopts the expanded capacity (sidecar) and re-wraps the
        # ensemble: continuing to a longer horizon keeps growing cleanly
        cfg2 = {
            "composite": "grow_divide",
            "config": {"growth": {"rate": 0.05}},
            "n_agents": 6,
            "capacity": 8,
            "total_time": 70.0,
            "checkpoint_every": 5.0,
            "auto_expand": {"free_frac": 0.3, "factor": 2},
            "replicates": 2,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "emitter": {"type": "null"},
        }
        with Experiment(cfg2) as exp:
            resumed = exp.resume()
        r_alive = np.asarray(resumed.alive)
        assert r_alive.shape[1] >= alive.shape[1]
        assert (r_alive.sum(axis=1) >= alive.sum(axis=1)).all()

    def test_replicate_mesh_auto_expand_matches_unsharded(self, tmp_path):
        """auto_expand on a replicate MESH takes the device-local pad
        (ShardedEnsemble.expanded — no host gather, multi-host-safe) and
        must be BITWISE the unsharded ensemble's host-path expansion."""

        def cfg(mesh):
            return {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.05}},
                "n_agents": 6,
                "capacity": 8,
                "total_time": 60.0,
                "checkpoint_every": 5.0,
                "auto_expand": {"free_frac": 0.3, "factor": 2},
                "replicates": 8,
                "emitter": {"type": "null"},
                "mesh": mesh,
                "seed": 7,
            }

        with Experiment(cfg(None)) as exp:
            ref = exp.run()
        with Experiment(cfg({"replicates": 8})) as exp:
            out = exp.run()
        assert int(out.alive.shape[1]) > 8  # expansion actually fired
        assert len(out.alive.sharding.device_set) == 8  # still split
        for la, lb in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_gates_raise_at_construction(self):
        with pytest.raises(ValueError, match="needs 'replicates' set"):
            Experiment(
                {"composite": "toggle_colony", "mesh": {"replicates": 8}}
            )
        with pytest.raises(ValueError, match="mesh replicates must be"):
            Experiment(
                {
                    "composite": "toggle_colony",
                    "replicates": 8,
                    "mesh": {"replicates": 0},
                }
            )
        with pytest.raises(ValueError, match="int >= 1"):
            Experiment({"composite": "toggle_colony", "replicates": 0})
        with pytest.raises(ValueError, match="int >= 1"):
            Experiment({"composite": "toggle_colony", "replicates": 2.5})
        with pytest.raises(ValueError, match="replicate-parallel"):
            Experiment(
                {
                    "composite": "toggle_colony",
                    "replicates": 2,
                    "mesh": {"agents": 4},
                }
            )
        # replicate meshes are composite-agnostic: multi-species builds
        # (the agent/space mesh gate must NOT catch them)
        with Experiment(
            {
                "composite": "mixed_species_lattice",
                "config": {
                    "capacity": {"ecoli": 8, "scavenger": 8},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                },
                "n_agents": {"ecoli": 4, "scavenger": 4},
                "replicates": 2,
                "mesh": {"replicates": 2},
            }
        ) as exp:
            assert exp.ensemble_runner is not None
        base = {"composite": "toggle_colony", "replicates": 2}
        with pytest.raises(ValueError, match="needs a lattice composite"):
            Experiment(dict(base, timeline="0 minimal"))
        with pytest.raises(ValueError, match="multi-species"):
            Experiment(
                {
                    "composite": "mixed_species_lattice",
                    "config": {
                        "capacity": {"ecoli": 8, "scavenger": 8},
                        "shape": (8, 8),
                        "size": (8.0, 8.0),
                    },
                    "replicates": 2,
                    "auto_expand": {"free_frac": 0.2},
                }
            )
        with pytest.raises(ValueError, match="replicate_overrides without"):
            Experiment(
                {
                    "composite": "toggle_colony",
                    "replicate_overrides": {"global": {"volume": [1.0]}},
                }
            )


class TestCheckpointResume:
    def config(self, tmp_path, total_time):
        return {
            "composite": "toggle_colony",
            "n_agents": 4,
            "capacity": 32,
            "total_time": total_time,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "checkpoint_every": 10.0,
            "emitter": {"type": "null"},
        }

    def test_resume_is_bitwise_identical(self, tmp_path):
        # uninterrupted 40s run
        with Experiment(self.config(tmp_path / "a", 40.0)) as exp:
            full = exp.run()
        # interrupted: 20s now...
        with Experiment(self.config(tmp_path / "b", 20.0)) as exp:
            exp.run()
        # ...then a FRESH Experiment resumes to 40s total
        cfg = self.config(tmp_path / "b", 40.0)
        with Experiment(cfg) as exp:
            resumed = exp.resume()
        np.testing.assert_array_equal(
            np.asarray(full.agents["cell"]["protein_u"]),
            np.asarray(resumed.agents["cell"]["protein_u"]),
        )
        np.testing.assert_array_equal(
            np.asarray(full.key), np.asarray(resumed.key)
        )
        assert int(full.step) == int(resumed.step)

    def test_resume_no_checkpoint_raises(self, tmp_path):
        cfg = self.config(tmp_path, 10.0)
        cfg["checkpoint_dir"] = None
        with Experiment(cfg) as exp:
            with pytest.raises(ValueError, match="needs checkpoint_dir"):
                exp.resume()

    def test_resume_past_total_time_is_noop(self, tmp_path):
        with Experiment(self.config(tmp_path, 20.0)) as exp:
            exp.run()
        with Experiment(self.config(tmp_path, 20.0)) as exp:
            state = exp.resume()
        assert int(state.step) == 20


class TestLPSolverSidecar:
    """Switching lp_solver changes the packed lp_state warm-vector
    LENGTH, so a solver-switched resume used to die as an opaque shape
    mismatch deep in restore. The colony_meta.json sidecar now records
    the solver and resume fails loudly BEFORE restore (ADVICE r5 #3)."""

    def config(self, tmp_path, total_time, solver=None):
        metab = {"lp_solver": solver} if solver else {}
        return {
            "composite": "rfba_lattice",
            "config": {"capacity": 16, "shape": (8, 8), "metabolism": metab},
            "n_agents": 4,
            "total_time": total_time,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "checkpoint_every": 2.0,
            "emitter": {"type": "null"},
        }

    def test_sidecar_records_solver_and_mismatch_fails_loudly(
        self, tmp_path
    ):
        import json as json_mod

        with Experiment(self.config(tmp_path, 4.0)) as exp:
            exp.run()
        meta = json_mod.load(
            open(tmp_path / "ckpt" / "colony_meta.json")
        )
        assert meta["lp_solvers"] == {"metabolism": "ipm"}
        with Experiment(self.config(tmp_path, 8.0, solver="pdlp")) as exp:
            with pytest.raises(ValueError, match="lp_solver mismatch"):
                exp.resume()
        # the matching solver still resumes
        with Experiment(self.config(tmp_path, 6.0)) as exp:
            state = exp.resume()
        assert int(state.colony.step) == 6


class TestCheckpointer:
    def test_colony_state_roundtrip(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        colony = Colony(grow_divide(), capacity=16)
        cs = colony.initial_state(4)
        ck = Checkpointer(str(tmp_path))
        ck.save(cs, 0)
        restored = ck.restore()
        assert type(restored).__name__ == "ColonyState"
        np.testing.assert_array_equal(
            np.asarray(cs.alive), np.asarray(restored.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(cs.agents["global"]["volume"]),
            np.asarray(restored.agents["global"]["volume"]),
        )

    def test_latest_step_selection(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        colony = Colony(grow_divide(), capacity=8)
        cs = colony.initial_state(2)
        ck = Checkpointer(str(tmp_path))
        ck.save(cs, 5)
        ck.save(cs._replace(step=cs.step + 7), 12)
        assert ck.steps() == [5, 12]
        assert int(ck.restore().step) == 7


class TestCLI:
    def test_list_command(self, capsys):
        from lens_tpu.__main__ import main

        assert main(["list"]) == 0
        out = capsys.readouterr().out
        assert "toggle_colony" in out
        assert "ecoli_lattice" in out
        assert "log" in out

    def test_run_command_with_log_emitter(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        out_dir = str(tmp_path / "exp")
        rc = main(
            [
                "run",
                "--composite",
                "grow_divide",
                "--n-agents",
                "2",
                "--capacity",
                "16",
                "--time",
                "20",
                "--emitter",
                "log",
                "--out-dir",
                out_dir,
                "--checkpoint-every",
                "10",
                "--quiet",
            ]
        )
        assert rc == 0
        assert "done:" in capsys.readouterr().out
        from lens_tpu.analysis import load

        header, ts = load(f"{out_dir}/emit.lens")
        assert ts["global"]["volume"].shape[0] == 20
        from lens_tpu.checkpoint import Checkpointer

        assert Checkpointer(f"{out_dir}/checkpoints").steps() == [10, 20]


class TestMeshTimeline:
    """config 'mesh' + 'timeline' combined (VERDICT r2 item 7): media
    shifts reset the sharded fields at segment boundaries."""

    def base_config(self, mesh=None):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 16,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "motility": {"sigma": 0.0},
            },
            "n_agents": 8,
            "total_time": 8.0,
            "timeline": "0 minimal, 4 minimal_low_glucose",
            "seed": 3,
            "mesh": mesh,
        }

    def test_sharded_media_shift_runs_and_resets_fields(self):
        with Experiment(self.base_config({"agents": 4, "space": 2})) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        fields = np.asarray(ts["fields"])  # [8, 1, 8, 8]
        assert fields.shape[0] == 8
        # segment 1 starts from minimal (10 mM); segment 2 resets to
        # 0.5 mM — the post-shift mean must drop by ~an order of magnitude
        assert fields[3].mean() > 5.0
        assert fields[4].mean() < 1.0

    def test_checkpointed_timeline_continues_not_restarts(self, tmp_path):
        """Regression: checkpoint segments used to restart the timeline
        at t=0 (re-resetting fields every segment and never reaching
        later events). With absolute event times: the t=6 shift happens
        during checkpoint segment 2, and the segment boundary at t=4
        does NOT reset the depleting field."""
        cfg = self.base_config({"agents": 4, "space": 2})
        cfg["timeline"] = "0 minimal, 6 minimal_low_glucose"
        cfg["checkpoint_dir"] = str(tmp_path / "ck")
        cfg["checkpoint_every"] = 4.0
        with Experiment(cfg) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        fields = np.asarray(ts["fields"])  # emits at t=1..8
        assert fields.shape[0] == 8
        # segment boundary (t=4): glucose keeps depleting monotonically
        # from the t=0 reset — no re-reset to 10 mM
        means = fields[:, 0].mean(axis=(1, 2))
        assert means[4] <= means[3] + 1e-5
        assert means[3] < 10.0
        # the t=6 event fires inside segment 2: drop to 0.5 mM
        assert means[5] > 5.0  # still minimal at t=6's emit... (t=5 emit)
        assert means[6] < 1.0  # first emit after the shift

    def test_sharded_timeline_matches_unsharded(self):
        with Experiment(self.base_config(None)) as exp:
            ref_state = exp.run()
            ref = exp.emitter.timeseries()
        with Experiment(self.base_config({"agents": 4, "space": 2})) as exp:
            out_state = exp.run()
            out = exp.emitter.timeseries()
        np.testing.assert_allclose(
            np.asarray(out["fields"]), np.asarray(ref["fields"]),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.fields), np.asarray(ref_state.fields),
            rtol=1e-5, atol=1e-6,
        )


class TestShardedCheckpointResume:
    """Checkpoint/resume THROUGH the sharded runner: preemption recovery
    must work for mesh runs, not just single-program ones."""

    def config(self, tmp_path, total_time):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 32,
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "division": False,
                "motility": {"sigma": 0.0},
            },
            "n_agents": 16,
            "total_time": total_time,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "checkpoint_every": 4.0,
            "emitter": {"type": "null"},
            "mesh": {"agents": 4, "space": 2},
            "seed": 9,
        }

    def test_sharded_resume_bitwise(self, tmp_path):
        with Experiment(self.config(tmp_path / "a", 8.0)) as exp:
            full = exp.run()
        with Experiment(self.config(tmp_path / "b", 4.0)) as exp:
            exp.run()
        with Experiment(self.config(tmp_path / "b", 8.0)) as exp:
            resumed = exp.resume()
        np.testing.assert_array_equal(
            np.asarray(full.fields), np.asarray(resumed.fields)
        )
        # tree.map pins the tree STRUCTURE too — a restore that dropped a
        # sub-dict would fail here, not silently truncate a zip
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            full.colony.agents,
            resumed.colony.agents,
        )
        np.testing.assert_array_equal(
            np.asarray(full.colony.alive), np.asarray(resumed.colony.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(full.colony.key), np.asarray(resumed.colony.key)
        )
        assert int(full.colony.step) == int(resumed.colony.step)


class TestAutoExpand:
    """Segment-boundary capacity growth (VERDICT r3 item 4): colonies can
    actually GROW, like the reference's unbounded process spawning
    (SURVEY.md §3.3), by re-allocating at 2x when free rows run low."""

    def growth_config(self, **over):
        cfg = {
            "composite": "grow_divide",
            # doubling every ~14 s: all rows divide in sync, the hardest
            # case for free-row headroom
            "config": {"growth": {"rate": 0.05}},
            "n_agents": 6,
            "capacity": 8,
            "total_time": 60.0,
            "checkpoint_every": 5.0,   # segments = expansion checkpoints
            "auto_expand": {"free_frac": 0.3, "factor": 2},
        }
        cfg.update(over)
        return cfg

    def test_population_multiplies_without_backlog(self):
        with Experiment(self.growth_config()) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        alive0, alive1 = 6, int(np.asarray(exp.n_alive(state)))
        assert alive1 >= 4 * alive0, alive1          # >= 4x growth
        assert int(state.alive.shape[0]) > 8          # capacity actually grew
        # division was NEVER suppressed for lack of rows
        backlog = np.asarray(ts["division_backlog"])
        assert (backlog == 0).all(), backlog
        # emitted trajectory stacked across the capacity jumps (padded to
        # the largest EMITTED capacity; a final-boundary expansion can
        # leave the state one factor ahead of the last emit)
        assert 8 < ts["alive"].shape[1] <= int(state.alive.shape[0])
        # alive cells carry unique lineage ids (expansion preserved the
        # id watermark)
        ids = np.asarray(state.agents["lineage"]["cell_id"])[
            np.asarray(state.alive)
        ]
        assert len(np.unique(ids)) == len(ids)

    def test_pre_expansion_trajectory_bitwise_unchanged(self):
        with Experiment(self.growth_config()) as exp:
            exp.run()
            ts_grown = exp.emitter.timeseries()
        with Experiment(
            self.growth_config(auto_expand=None, total_time=5.0)
        ) as exp:
            exp.run()
            ts_fixed = exp.emitter.timeseries()
        t = ts_fixed["alive"].shape[0]
        np.testing.assert_array_equal(
            ts_grown["alive"][:t, :8], ts_fixed["alive"]
        )
        np.testing.assert_array_equal(
            ts_grown["global"]["volume"][:t, :8],
            ts_fixed["global"]["volume"],
        )

    def test_max_capacity_caps_growth(self):
        with Experiment(
            self.growth_config(
                auto_expand={"free_frac": 0.3, "factor": 2,
                             "max_capacity": 16}
            )
        ) as exp:
            state = exp.run()
        assert int(state.alive.shape[0]) == 16

    def test_resume_after_expansion_matches_uninterrupted(self, tmp_path):
        cfg_a = self.growth_config(
            checkpoint_dir=str(tmp_path / "a"), emitter={"type": "null"}
        )
        with Experiment(cfg_a) as exp:
            full = exp.run()
        # interrupted at 30 s (after at least one expansion)...
        cfg_b = self.growth_config(
            checkpoint_dir=str(tmp_path / "b"), emitter={"type": "null"},
            total_time=30.0,
        )
        with Experiment(cfg_b) as exp:
            mid = exp.run()
        assert int(mid.alive.shape[0]) > 8   # expansion happened pre-resume
        # ...then a FRESH Experiment adopts the bigger checkpoint
        cfg_c = self.growth_config(
            checkpoint_dir=str(tmp_path / "b"), emitter={"type": "null"}
        )
        with Experiment(cfg_c) as exp:
            resumed = exp.resume()
            assert exp.colony.capacity == int(resumed.alive.shape[0])
        np.testing.assert_array_equal(
            np.asarray(full.alive), np.asarray(resumed.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(full.agents["global"]["volume"]),
            np.asarray(resumed.agents["global"]["volume"]),
        )
        np.testing.assert_array_equal(
            np.asarray(full.agents["lineage"]["cell_id"]),
            np.asarray(resumed.agents["lineage"]["cell_id"]),
        )
        np.testing.assert_array_equal(
            np.asarray(full.key), np.asarray(resumed.key)
        )

    def test_expanded_ids_stay_above_watermark(self):
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        colony = Colony(
            grow_divide(), capacity=4, division_trigger=("global", "divide")
        )
        cs = colony.initial_state(3, key=jax.random.PRNGKey(0))
        # force a division round pre-expansion
        cs = cs._replace(
            agents=dict(
                cs.agents,
                **{"global": dict(cs.agents["global"],
                                  divide=jnp.ones(4, jnp.float32))},
            )
        )
        cs = colony.step_division(cs)
        # mirror Colony.step: the counter increments after division, so a
        # boundary state's last minting used step value step-1 (the
        # watermark formula in Colony.expanded assumes boundary states)
        cs = cs._replace(step=cs.step + 1)
        pre_ids = np.asarray(cs.agents["lineage"]["cell_id"])[
            np.asarray(cs.alive)
        ]
        watermark = colony.id_offset + (int(cs.step) + 1) * 2 * colony.capacity
        assert pre_ids.max() < watermark
        grown, cs2 = colony.expanded(cs, 2)
        assert int(cs2.alive.shape[0]) == 8
        # alive rows and step/key survive the expansion untouched
        np.testing.assert_array_equal(
            np.asarray(cs2.alive[:4]), np.asarray(cs.alive)
        )
        assert int(cs2.step) == int(cs.step)
        # a division at the NEW stride mints ids strictly above every id
        # the old colony could have minted
        cs2 = cs2._replace(
            agents=dict(
                cs2.agents,
                **{"global": dict(cs2.agents["global"],
                                  divide=jnp.ones(8, jnp.float32))},
            )
        )
        cs3 = grown.step_division(cs2)
        new_mask = np.asarray(cs3.alive) & ~np.isin(
            np.asarray(cs3.agents["lineage"]["cell_id"]), pre_ids
        )
        new_ids = np.asarray(cs3.agents["lineage"]["cell_id"])[new_mask]
        assert new_ids.size and new_ids.min() >= watermark
        all_ids = np.asarray(cs3.agents["lineage"]["cell_id"])[
            np.asarray(cs3.alive)
        ]
        assert len(np.unique(all_ids)) == len(all_ids)


class TestPipelinedEmission:
    """Segment emission is pipelined one deep (single host): the records
    an experiment produces must be IDENTICAL to the unpipelined baseline
    — same order, same values — regardless of segmentation."""

    def test_segmented_equals_single_segment(self):
        def run(checkpoint_every):
            with Experiment(
                {
                    "composite": "toggle_colony",
                    "n_agents": 4,
                    "capacity": 16,
                    "total_time": 24.0,
                    "checkpoint_every": checkpoint_every,
                }
            ) as exp:
                exp.run()
                return exp.emitter.timeseries()

        one = run(None)       # single segment: nothing to pipeline
        many = run(6.0)       # 4 segments: 3 pipelined emits + final
        assert one.keys() == many.keys()
        np.testing.assert_array_equal(one["__time__"], many["__time__"])
        np.testing.assert_array_equal(
            np.asarray(one["cell"]["protein_u"]),
            np.asarray(many["cell"]["protein_u"]),
        )
        np.testing.assert_array_equal(
            np.asarray(one["alive"]), np.asarray(many["alive"])
        )


class TestAutoExpandWithMesh:
    """auto_expand composes with a (single-host) device mesh: fresh rows
    are dealt evenly across agent shards, so the sharded expanded run
    tracks the unsharded one and never starves a shard's division pool."""

    def growth_config(self, mesh):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 32,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": True,
                "motility": {"sigma": 0.0},
                "growth": {"rate": 0.05},
            },
            "n_agents": 8,
            "total_time": 45.0,
            "checkpoint_every": 5.0,
            "auto_expand": {"free_frac": 0.3, "factor": 2},
            "mesh": mesh,
            "seed": 11,
        }

    def test_sharded_expansion_tracks_unsharded(self):
        with Experiment(self.growth_config(None)) as exp:
            ref_state = exp.run()
            ref_ts = exp.emitter.timeseries()
        with Experiment(self.growth_config({"agents": 4, "space": 1})) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
            assert exp.runner is not None
            assert exp.colony.capacity == int(state.colony.alive.shape[0])
        assert int(state.colony.alive.shape[0]) > 32      # expanded
        # same growth curve and zero backlog on both paths (rows are
        # permuted differently, so compare aggregates, not rows)
        np.testing.assert_array_equal(
            np.asarray(ts["alive"]).sum(axis=1),
            np.asarray(ref_ts["alive"]).sum(axis=1),
        )
        assert (np.asarray(ts["division_backlog"]) == 0).all()
        assert (np.asarray(ref_ts["division_backlog"]) == 0).all()
        np.testing.assert_array_equal(
            np.asarray(state.colony.alive).sum(),
            np.asarray(ref_state.colony.alive).sum(),
        )
        # lineage ids stay unique through sharded expansion
        ids = np.asarray(state.colony.agents["lineage"]["cell_id"])[
            np.asarray(state.colony.alive)
        ]
        assert len(np.unique(ids)) == len(ids)

    def test_on_mesh_expansion_bitwise_equals_gather_path(self):
        """The shard-local on-device expansion (multi-host-safe: no host
        gather, no collectives) is BITWISE the state the old
        ``device_get -> Colony.expanded -> interleave_expanded_rows ->
        device_put`` sequence produced — end-appended padding composed
        with the interleave permutation IS the per-shard layout
        ``[old block | block's fresh rows]``."""
        from lens_tpu.models.composites import ecoli_lattice
        from lens_tpu.parallel import ShardedSpatialColony
        from lens_tpu.parallel.mesh import (
            AGENTS_AXIS,
            expand_colony_rows_on_mesh,
            interleave_expanded_rows,
            make_mesh,
        )

        spatial, _ = ecoli_lattice(
            {
                "capacity": 32,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": True,
                "growth": {"rate": 0.05},
                "motility": {"sigma": 0.0},
            }
        )
        mesh = make_mesh(4, 1)
        runner = ShardedSpatialColony(spatial, mesh)
        state = runner.initial_state(8, jax.random.PRNGKey(3))
        state = runner.run(state, 10.0, 1.0, emit_every=10)[0]

        old_cap = spatial.colony.capacity
        n_blocks = mesh.shape[AGENTS_AXIS]
        # reference: the old host-side sequence
        host = jax.device_get(state)
        sp_ref, grown_ref = spatial.expanded(host, 2)
        ref = interleave_expanded_rows(grown_ref.colony, old_cap, n_blocks)
        # the on-device shard-local path
        step_now = int(np.asarray(jax.device_get(state.colony.step)))
        grown_colony = spatial.colony.expanded_meta(step_now, 2)
        new = expand_colony_rows_on_mesh(
            state.colony, grown_colony, old_cap, mesh
        )
        assert grown_colony.capacity == sp_ref.colony.capacity
        assert grown_colony.id_offset == sp_ref.colony.id_offset
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            ref.agents,
            new.agents,
        )
        np.testing.assert_array_equal(
            np.asarray(ref.alive), np.asarray(new.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(ref.key), np.asarray(new.key)
        )
        assert int(np.asarray(new.step)) == step_now
        # the new path keeps the mesh sharding without a re-place
        assert new.agents["lineage"]["cell_id"].sharding.is_equivalent_to(
            jax.NamedSharding(mesh, jax.P(AGENTS_AXIS)), ndim=1
        )


class TestHeterogeneousDivergence:
    """VERDICT r4 task 5: shard divergence under heterogeneous growth.

    Division pools are shard-local; with INHERITED growth-rate
    heterogeneity (Growth per_agent_rates + the copy divider) a fast
    lineage concentrates in its founder's shard — daughters recycle rows
    locally — and saturates that pool while other shards hold free rows.
    Synchronized/phase-staggered growth does NOT diverge (equal rates
    equalize division rates; measured zero divergence), so this is THE
    adversarial regime. The segment-boundary rebalance (config
    ``rebalance``, default on) re-deals rows when backlog and free rows
    coexist; divergence then collapses to a one-segment transient.
    """

    RATES = np.full(128, 0.03, np.float32)
    RATES[0] = RATES[8] = 0.09  # striped rows 0,8 -> shard 0's founders

    def config(self, mesh, rebalance=True):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 128,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "diffusion": 2.0,
                "timestep": 1.0,
                "division": True,
                "motility": {"sigma": 0.0},
                "growth": {"rate": 0.03, "per_agent_rates": True},
            },
            "overrides": {"global": {"growth_rate": self.RATES}},
            "n_agents": 16,
            "total_time": 65.0,
            "checkpoint_every": 5.0,
            "rebalance": rebalance,
            "mesh": mesh,
            "seed": 5,
        }

    def run(self, cfg):
        with Experiment(cfg) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        return (
            np.asarray(ts["alive"]).sum(axis=1),
            np.asarray(ts["division_backlog"]),
        )

    def test_rebalance_collapses_material_divergence(self):
        u_alive, u_bl = self.run(self.config(None))
        r_alive, _ = self.run(self.config({"agents": 8, "space": 1}))
        n_alive, n_bl = self.run(
            self.config({"agents": 8, "space": 1}, rebalance=False)
        )
        # without rebalance the divergence is MATERIAL: the fast lineage
        # starves at 16 rows (its shard's pool) while unsharded grows on
        # (measured 56-cell / 52% peak deficit)
        assert (u_alive - n_alive).max() >= 40
        # ...and its backlog fires while the unsharded run's is still 0
        assert n_bl[u_bl == 0].max() >= 16
        # with the segment-boundary rebalance the deficit is at most a
        # one-segment transient (suppression can only happen between
        # boundaries), and the population fully catches up
        assert (u_alive - r_alive).max() <= 16
        assert r_alive[-1] == u_alive[-1] == 128


class TestCLIAutoExpand:
    def test_run_command_with_auto_expand(self, capsys):
        from lens_tpu.__main__ import main

        rc = main(
            [
                "run",
                "--composite", "grow_divide",
                "--config", '{"growth": {"rate": 0.05}}',
                "--n-agents", "6",
                "--capacity", "8",
                "--time", "30",
                "--checkpoint-every", "5",
                "--auto-expand", "0.3",
                "--quiet",
            ]
        )
        assert rc == 0
        out = capsys.readouterr().out
        assert "done:" in out


class TestMultiSpeciesExperiment:
    """Config-4 composites through the L5 layer: the Experiment runs,
    emits, checkpoints, auto-expands, and resumes MIXED-SPECIES colonies
    the same way it does single-species ones."""

    def config(self, tmp_path=None, **over):
        cfg = {
            "composite": "mixed_species_lattice",
            "config": {
                "capacity": {"ecoli": 8, "scavenger": 8},
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "ecoli": {"motility": {"sigma": 0.0},
                          "growth": {"rate": 0.05}},
                "scavenger": {"motility": {"sigma": 0.0},
                              "growth": {"rate": 0.02}},
            },
            "n_agents": {"ecoli": 6, "scavenger": 4},
            "total_time": 30.0,
            "checkpoint_every": 5.0,
            "auto_expand": {"free_frac": 0.3, "factor": 2},
            "seed": 7,
        }
        if tmp_path is not None:
            cfg["checkpoint_dir"] = str(tmp_path / "ckpt")
            cfg["emitter"] = {"type": "null"}
        cfg.update(over)
        return cfg

    def test_runs_emits_and_expands_per_species(self):
        with Experiment(self.config()) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        # ecoli (fast divider) outgrew its 8 rows and expanded; the
        # population actually multiplied
        caps = {n: int(cs.alive.shape[0]) for n, cs in state.species.items()}
        assert caps["ecoli"] > 8, caps
        alive = {n: int(np.asarray(cs.alive).sum())
                 for n, cs in state.species.items()}
        assert alive["ecoli"] >= 4 * 6 - 4, alive   # ~2 doublings
        # emitted per-species subtrees stacked across the capacity jump
        assert ts["ecoli"]["alive"].shape[1] >= 8
        assert (np.asarray(ts["ecoli"]["division_backlog"]) == 0).all()
        assert "fields" in ts

    def test_mesh_runs_multi_species_and_matches_unsharded(self):
        """Config 'mesh' + a multi-species composite wires the
        ShardedMultiSpeciesColony runner through the L5 layer. On a
        deterministic variant (no division, sigma=0, stochastic
        expression off) the sharded Experiment must reproduce the
        unsharded one exactly."""
        def cfg(mesh):
            return {
                "composite": "mixed_species_lattice",
                "config": {
                    "capacity": {"ecoli": 16, "scavenger": 16},
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                    "ecoli": {"motility": {"sigma": 0.0}},
                    "scavenger": {"motility": {"sigma": 0.0},
                                  "expression": None},
                },
                "n_agents": {"ecoli": 16, "scavenger": 16},
                "total_time": 10.0,
                "seed": 7,
                # stripe off: row-for-row comparability to unsharded
                "mesh": dict(mesh, stripe=False) if mesh else None,
            }

        with Experiment(cfg(None)) as exp:
            ref = exp.run()
        with Experiment(cfg({"agents": 4, "space": 2})) as exp:
            assert exp.runner is not None
            out = exp.run()
        np.testing.assert_allclose(
            np.asarray(out.fields), np.asarray(ref.fields),
            rtol=1e-5, atol=1e-6,
        )
        for name in ref.species:
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
                ),
                ref.species[name].agents,
                out.species[name].agents,
            )

    def test_mesh_auto_expand_grows_per_species_shard_locally(self):
        """auto_expand composes with the multi-species mesh: each growing
        species pads shard-locally on device (no host gather), fast
        species expand while slow ones keep their capacity, populations
        multiply, and lineage ids stay unique."""
        cfg = self.config(mesh={"agents": 4, "space": 2})
        # capacities divisible by the 4 agent shards at every factor
        cfg["config"]["capacity"] = {"ecoli": 8, "scavenger": 8}
        with Experiment(cfg) as exp:
            state = exp.run()
            assert exp.runner is not None
        caps = {n: int(cs.alive.shape[0]) for n, cs in state.species.items()}
        assert caps["ecoli"] > 8, caps
        alive = {n: int(np.asarray(cs.alive).sum())
                 for n, cs in state.species.items()}
        assert alive["ecoli"] >= 4 * 6 - 4, alive
        # expanded state kept the mesh split on the agent axis
        assert len(state.species["ecoli"].alive.sharding.device_set) >= 4
        for n, cs in state.species.items():
            ids = np.asarray(cs.agents["lineage"]["cell_id"])[
                np.asarray(cs.alive)
            ]
            assert len(np.unique(ids)) == len(ids), n

    def test_checkpoint_resume_after_expansion(self, tmp_path):
        with Experiment(self.config(tmp_path)) as exp:
            full = exp.run()
        cfg_b = self.config(tmp_path, total_time=15.0)
        cfg_b["checkpoint_dir"] = str(tmp_path / "b")
        with Experiment(cfg_b) as exp:
            mid = exp.run()
        assert int(mid.species["ecoli"].alive.shape[0]) > 8
        cfg_c = dict(cfg_b, total_time=30.0)
        with Experiment(cfg_c) as exp:
            resumed = exp.resume()
            caps = {n: sp.colony.capacity
                    for n, sp in exp.multi.species.items()}
            assert caps["ecoli"] == int(
                resumed.species["ecoli"].alive.shape[0]
            )
        for name in full.species:
            np.testing.assert_array_equal(
                np.asarray(full.species[name].alive),
                np.asarray(resumed.species[name].alive),
                err_msg=name,
            )
            np.testing.assert_array_equal(
                np.asarray(full.species[name].agents["global"]["volume"]),
                np.asarray(resumed.species[name].agents["global"]["volume"]),
                err_msg=name,
            )

    def test_multi_species_rebalance_redeal_fires_per_species(self):
        """The per-species re-deal path with the gate actually FIRING:
        one species' alive rows packed into a single shard block with
        every row triggered (backlog > 0, free > 0) gets re-dealt
        across shards; the other species (gate quiet) is untouched
        bitwise."""
        from lens_tpu.utils.dicts import get_path, set_path

        cfg = self.config(mesh={"agents": 4, "space": 2})
        cfg["config"]["capacity"] = {"ecoli": 16, "scavenger": 16}
        with Experiment(cfg) as exp:
            state = exp.initial_state()
            ecoli = state.species["ecoli"]
            # all 4 alive rows in shard block 0 (16 rows / 4 shards),
            # all triggered to divide -> starved pool, global free rows
            alive = np.zeros(16, bool)
            alive[:4] = True
            trig_path = exp.multi.species["ecoli"].colony.division_trigger
            agents = set_path(
                ecoli.agents,
                trig_path,
                jnp.ones_like(get_path(ecoli.agents, trig_path)),
            )
            st = state._replace(
                species=dict(
                    state.species,
                    ecoli=ecoli._replace(
                        agents=agents, alive=jnp.asarray(alive)
                    ),
                )
            )
            out = exp._maybe_rebalance(st)
        per_block = np.asarray(out.species["ecoli"].alive).reshape(4, 4)
        assert (per_block.sum(axis=1) == 1).all(), per_block
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            st.species["scavenger"],
            out.species["scavenger"],
        )

    def test_sharded_checkpoint_resume_after_expansion(self, tmp_path):
        """The newly-reachable intersection: mesh + multi-species +
        auto_expand + checkpoint. Resume adopts the sidecar capacities,
        rebuilds the ShardedMultiSpeciesColony around the grown multi
        (stale wrap = colliding lineage ids), and the resumed run's
        lifecycle invariants hold."""
        def cfg(base, total):
            c = self.config(tmp_path, total_time=total,
                            mesh={"agents": 4, "space": 2})
            c["checkpoint_dir"] = str(base)
            return c

        with Experiment(cfg(tmp_path / "a", 15.0)) as exp:
            mid = exp.run()
        assert int(mid.species["ecoli"].alive.shape[0]) > 8  # expanded
        with Experiment(cfg(tmp_path / "a", 30.0)) as exp:
            resumed = exp.resume()
            assert exp.runner is not None
            caps = {n: sp.colony.capacity
                    for n, sp in exp.multi.species.items()}
            assert caps["ecoli"] == int(
                resumed.species["ecoli"].alive.shape[0]
            )
        for n, cs in resumed.species.items():
            alive = np.asarray(cs.alive)
            assert alive.sum() >= np.asarray(mid.species[n].alive).sum(), n
            ids = np.asarray(cs.agents["lineage"]["cell_id"])[alive]
            assert len(np.unique(ids)) == len(ids), n

    def test_scalar_n_agents_rejected(self):
        with pytest.raises(ValueError, match="per-species dict"):
            with Experiment(self.config(n_agents=4)) as exp:
                exp.initial_state()


class TestRebalanceGateCopyDivider:
    """ADVICE r5 #4: the segment-boundary rebalance must gate on
    SUPPRESSED divisions (a triggered shard with an exhausted pool), not
    on any alive row with trigger > 0 — a copy-style divider leaves the
    trigger set on BOTH daughters after a successful division, and the
    old gate then re-dealt the whole colony at every boundary for as
    long as any free row existed anywhere."""

    @staticmethod
    def _register():
        from lens_tpu.colony import Colony
        from lens_tpu.core.engine import Compartment
        from lens_tpu.core.process import Deriver
        from lens_tpu.environment import Lattice, SpatialColony
        from lens_tpu.models.composites import (
            composite_registry,
            register_composite,
        )
        from lens_tpu.processes.mm_transport import (
            BrownianMotility,
            MichaelisMentenTransport,
        )

        if "copy_trigger_lattice" in composite_registry:
            return

        class StickyDivideFlag(Deriver):
            """Declares a division flag with the COPY divider and never
            rewrites it: once set (initial-state override), a lineage
            divides every step and both daughters stay triggered."""

            name = "sticky_divide_flag"

            def ports_schema(self):
                return {
                    "global": {
                        "divide": {
                            "_default": 0.0,
                            "_divider": "copy",
                            "_emit": False,
                        }
                    }
                }

            def next_update(self, timestep, states):
                return {}

        @register_composite
        def copy_trigger_lattice(config=None):
            comp = Compartment(
                processes={
                    "transport": MichaelisMentenTransport({}),
                    "motility": BrownianMotility({"sigma": 0.0}),
                    "sticky": StickyDivideFlag(),
                },
                topology={
                    "transport": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                        "exchange": ("boundary", "exchange"),
                    },
                    "motility": {"boundary": ("boundary",)},
                    "sticky": {"global": ("global",)},
                },
            )
            colony = Colony(
                comp, capacity=64, division_trigger=("global", "divide")
            )
            lattice = Lattice(
                molecules=["glucose"], shape=(8, 8), size=(8.0, 8.0),
                diffusion=1.0, initial=10.0, timestep=1.0,
            )
            spatial = SpatialColony(
                colony, lattice,
                field_ports={
                    "glucose": (
                        ("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange"),
                    )
                },
            )
            return spatial, comp

    def _run(self, monkeypatch, n_agents, stripe):
        self._register()
        import lens_tpu.parallel.mesh as mesh_mod

        calls = []
        real = mesh_mod.rebalance_colony_rows

        def spy(cs, n_blocks):
            calls.append(n_blocks)
            return real(cs, n_blocks)

        monkeypatch.setattr(mesh_mod, "rebalance_colony_rows", spy)
        cfg = {
            "composite": "copy_trigger_lattice",
            "n_agents": n_agents,
            "overrides": {
                "global": {"divide": np.ones(64, np.float32)}
            },
            "total_time": 6.0,
            "checkpoint_every": 2.0,
            "mesh": {"agents": 4, "space": 1, "stripe": stripe},
            "seed": 1,
        }
        with Experiment(cfg) as exp:
            state = exp.run()
        return calls, int(np.asarray(state.colony.alive).sum())

    def test_surviving_trigger_with_local_free_rows_does_not_redeal(
        self, monkeypatch
    ):
        # striped founders: every shard divides into its OWN free rows,
        # so triggers survive each division (copy divider) but nothing
        # is ever suppressed -> the gate must stay silent
        calls, alive = self._run(monkeypatch, n_agents=4, stripe=True)
        assert calls == [], "spurious re-deal on a copy-style divider"
        assert alive == 64  # population actually multiplied to capacity

    def test_genuinely_starved_shard_still_fires(self, monkeypatch):
        # contiguous founders fill shard 0's whole block: its divisions
        # are ALL suppressed while other shards sit empty -> the gate
        # must fire at the first boundary
        calls, alive = self._run(monkeypatch, n_agents=16, stripe=False)
        assert len(calls) >= 1, "starved shard did not trigger a re-deal"
        assert alive == 64
