"""Experiment layer: registry boot, segmented runs, checkpoint/resume, CLI."""

import jax
import numpy as np
import pytest

from lens_tpu.emit import RamEmitter
from lens_tpu.experiment import Experiment


class TestExperiment:
    def test_colony_experiment_runs_and_emits(self):
        with Experiment(
            {
                "composite": "toggle_colony",
                "n_agents": 4,
                "capacity": 64,
                "total_time": 30.0,
                "emit_every": 10,
            }
        ) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        assert ts["cell"]["protein_u"].shape == (3, 64)
        np.testing.assert_allclose(ts["__time__"], [10.0, 20.0, 30.0])

    def test_spatial_experiment_runs(self):
        with Experiment(
            {
                "composite": "ecoli_lattice",
                "config": {
                    "capacity": 16,
                    "shape": (8, 8),
                    "size": (8.0, 8.0),
                    "division": False,
                },
                "n_agents": 8,
                "total_time": 5.0,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(exp.n_alive(state))) == 8
            ts = exp.emitter.timeseries()
        assert ts["fields"].shape == (5, 1, 8, 8)

    def test_unknown_composite_raises(self):
        with pytest.raises(ValueError, match="unknown composite"):
            Experiment({"composite": "nope"})

    def test_division_grows_population(self):
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": 0.01}},
                "n_agents": 2,
                "capacity": 64,
                "total_time": 120.0,
            }
        ) as exp:
            state = exp.run()
            assert int(np.asarray(exp.n_alive(state))) > 2


class TestCheckpointResume:
    def config(self, tmp_path, total_time):
        return {
            "composite": "toggle_colony",
            "n_agents": 4,
            "capacity": 32,
            "total_time": total_time,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "checkpoint_every": 10.0,
            "emitter": {"type": "null"},
        }

    def test_resume_is_bitwise_identical(self, tmp_path):
        # uninterrupted 40s run
        with Experiment(self.config(tmp_path / "a", 40.0)) as exp:
            full = exp.run()
        # interrupted: 20s now...
        with Experiment(self.config(tmp_path / "b", 20.0)) as exp:
            exp.run()
        # ...then a FRESH Experiment resumes to 40s total
        cfg = self.config(tmp_path / "b", 40.0)
        with Experiment(cfg) as exp:
            resumed = exp.resume()
        np.testing.assert_array_equal(
            np.asarray(full.agents["cell"]["protein_u"]),
            np.asarray(resumed.agents["cell"]["protein_u"]),
        )
        np.testing.assert_array_equal(
            np.asarray(full.key), np.asarray(resumed.key)
        )
        assert int(full.step) == int(resumed.step)

    def test_resume_no_checkpoint_raises(self, tmp_path):
        cfg = self.config(tmp_path, 10.0)
        cfg["checkpoint_dir"] = None
        with Experiment(cfg) as exp:
            with pytest.raises(ValueError, match="needs checkpoint_dir"):
                exp.resume()

    def test_resume_past_total_time_is_noop(self, tmp_path):
        with Experiment(self.config(tmp_path, 20.0)) as exp:
            exp.run()
        with Experiment(self.config(tmp_path, 20.0)) as exp:
            state = exp.resume()
        assert int(state.step) == 20


class TestCheckpointer:
    def test_colony_state_roundtrip(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        colony = Colony(grow_divide(), capacity=16)
        cs = colony.initial_state(4)
        ck = Checkpointer(str(tmp_path))
        ck.save(cs, 0)
        restored = ck.restore()
        assert type(restored).__name__ == "ColonyState"
        np.testing.assert_array_equal(
            np.asarray(cs.alive), np.asarray(restored.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(cs.agents["global"]["volume"]),
            np.asarray(restored.agents["global"]["volume"]),
        )

    def test_latest_step_selection(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer
        from lens_tpu.colony.colony import Colony
        from lens_tpu.models.composites import grow_divide

        colony = Colony(grow_divide(), capacity=8)
        cs = colony.initial_state(2)
        ck = Checkpointer(str(tmp_path))
        ck.save(cs, 5)
        ck.save(cs._replace(step=cs.step + 7), 12)
        assert ck.steps() == [5, 12]
        assert int(ck.restore().step) == 7


class TestCLI:
    def test_list_command(self, capsys):
        from lens_tpu.__main__ import main

        assert main(["list"]) == 0
        out = capsys.readouterr().out
        assert "toggle_colony" in out
        assert "ecoli_lattice" in out
        assert "log" in out

    def test_run_command_with_log_emitter(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        out_dir = str(tmp_path / "exp")
        rc = main(
            [
                "run",
                "--composite",
                "grow_divide",
                "--n-agents",
                "2",
                "--capacity",
                "16",
                "--time",
                "20",
                "--emitter",
                "log",
                "--out-dir",
                out_dir,
                "--checkpoint-every",
                "10",
                "--quiet",
            ]
        )
        assert rc == 0
        assert "done:" in capsys.readouterr().out
        from lens_tpu.analysis import load

        header, ts = load(f"{out_dir}/emit.lens")
        assert ts["global"]["volume"].shape[0] == 20
        from lens_tpu.checkpoint import Checkpointer

        assert Checkpointer(f"{out_dir}/checkpoints").steps() == [10, 20]


class TestMeshTimeline:
    """config 'mesh' + 'timeline' combined (VERDICT r2 item 7): media
    shifts reset the sharded fields at segment boundaries."""

    def base_config(self, mesh=None):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 16,
                "shape": (8, 8),
                "size": (8.0, 8.0),
                "division": False,
                "motility": {"sigma": 0.0},
            },
            "n_agents": 8,
            "total_time": 8.0,
            "timeline": "0 minimal, 4 minimal_low_glucose",
            "seed": 3,
            "mesh": mesh,
        }

    def test_sharded_media_shift_runs_and_resets_fields(self):
        with Experiment(self.base_config({"agents": 4, "space": 2})) as exp:
            state = exp.run()
            ts = exp.emitter.timeseries()
        fields = np.asarray(ts["fields"])  # [8, 1, 8, 8]
        assert fields.shape[0] == 8
        # segment 1 starts from minimal (10 mM); segment 2 resets to
        # 0.5 mM — the post-shift mean must drop by ~an order of magnitude
        assert fields[3].mean() > 5.0
        assert fields[4].mean() < 1.0

    def test_checkpointed_timeline_continues_not_restarts(self, tmp_path):
        """Regression: checkpoint segments used to restart the timeline
        at t=0 (re-resetting fields every segment and never reaching
        later events). With absolute event times: the t=6 shift happens
        during checkpoint segment 2, and the segment boundary at t=4
        does NOT reset the depleting field."""
        cfg = self.base_config({"agents": 4, "space": 2})
        cfg["timeline"] = "0 minimal, 6 minimal_low_glucose"
        cfg["checkpoint_dir"] = str(tmp_path / "ck")
        cfg["checkpoint_every"] = 4.0
        with Experiment(cfg) as exp:
            exp.run()
            ts = exp.emitter.timeseries()
        fields = np.asarray(ts["fields"])  # emits at t=1..8
        assert fields.shape[0] == 8
        # segment boundary (t=4): glucose keeps depleting monotonically
        # from the t=0 reset — no re-reset to 10 mM
        means = fields[:, 0].mean(axis=(1, 2))
        assert means[4] <= means[3] + 1e-5
        assert means[3] < 10.0
        # the t=6 event fires inside segment 2: drop to 0.5 mM
        assert means[5] > 5.0  # still minimal at t=6's emit... (t=5 emit)
        assert means[6] < 1.0  # first emit after the shift

    def test_sharded_timeline_matches_unsharded(self):
        with Experiment(self.base_config(None)) as exp:
            ref_state = exp.run()
            ref = exp.emitter.timeseries()
        with Experiment(self.base_config({"agents": 4, "space": 2})) as exp:
            out_state = exp.run()
            out = exp.emitter.timeseries()
        np.testing.assert_allclose(
            np.asarray(out["fields"]), np.asarray(ref["fields"]),
            rtol=1e-5, atol=1e-6,
        )
        np.testing.assert_allclose(
            np.asarray(out_state.fields), np.asarray(ref_state.fields),
            rtol=1e-5, atol=1e-6,
        )


class TestShardedCheckpointResume:
    """Checkpoint/resume THROUGH the sharded runner: preemption recovery
    must work for mesh runs, not just single-program ones."""

    def config(self, tmp_path, total_time):
        return {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 32,
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "division": False,
                "motility": {"sigma": 0.0},
            },
            "n_agents": 16,
            "total_time": total_time,
            "checkpoint_dir": str(tmp_path / "ckpt"),
            "checkpoint_every": 4.0,
            "emitter": {"type": "null"},
            "mesh": {"agents": 4, "space": 2},
            "seed": 9,
        }

    def test_sharded_resume_bitwise(self, tmp_path):
        with Experiment(self.config(tmp_path / "a", 8.0)) as exp:
            full = exp.run()
        with Experiment(self.config(tmp_path / "b", 4.0)) as exp:
            exp.run()
        with Experiment(self.config(tmp_path / "b", 8.0)) as exp:
            resumed = exp.resume()
        np.testing.assert_array_equal(
            np.asarray(full.fields), np.asarray(resumed.fields)
        )
        # tree.map pins the tree STRUCTURE too — a restore that dropped a
        # sub-dict would fail here, not silently truncate a zip
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            full.colony.agents,
            resumed.colony.agents,
        )
        np.testing.assert_array_equal(
            np.asarray(full.colony.alive), np.asarray(resumed.colony.alive)
        )
        np.testing.assert_array_equal(
            np.asarray(full.colony.key), np.asarray(resumed.colony.key)
        )
        assert int(full.colony.step) == int(resumed.colony.step)
