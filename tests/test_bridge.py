"""The host bridge (CellSimulation protocol), surrogates, and timers."""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.bridge import CompartmentSimulation, HostExchangeLoop
from lens_tpu.core.engine import Compartment
from lens_tpu.environment.lattice import Lattice
from lens_tpu.processes import MichaelisMentenTransport
from lens_tpu.surrogates import ConstantUptakeSurrogate, GrowDivideSurrogate
from lens_tpu.utils.timers import PhaseTimer


def small_lattice(**kw):
    defaults = dict(
        molecules=["glucose"],
        shape=(8, 8),
        size=(8.0, 8.0),
        diffusion=1.0,
        initial=10.0,
        timestep=1.0,
    )
    defaults.update(kw)
    return Lattice(**defaults)


class TestHostLoop:
    def test_uptake_surrogate_depletes_field(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(ConstantUptakeSurrogate(uptake_per_s=0.5), (4.0, 4.0))
        m0 = float(loop.fields.sum())
        loop.run(10.0)
        m1 = float(loop.fields.sum())
        np.testing.assert_allclose(m0 - m1, 5.0, rtol=1e-4)

    def test_division_handshake(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(GrowDivideSurrogate(volume=1.9, rate=0.05), (4.0, 4.0))
        parent = loop.agents[0].sim
        loop.run(3.0)  # 1.9 * e^{0.15} > 2 -> divides
        assert len(loop.agents) == 2
        assert parent.finalized
        va = loop.agents[0].sim.volume
        vb = loop.agents[1].sim.volume
        np.testing.assert_allclose(va, vb)
        assert va < 1.9
        # daughters placed apart — by the same separation the colony fast
        # path's `offset` divider uses (one cell length)
        from lens_tpu.core.state import DIVISION_SEPARATION_UM

        sep = np.linalg.norm(
            loop.agents[0].location - loop.agents[1].location
        )
        np.testing.assert_allclose(sep, DIVISION_SEPARATION_UM, rtol=1e-6)

    def test_population_growth_over_generations(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(GrowDivideSurrogate(volume=1.0, rate=0.05), (2.0, 2.0))
        loop.run(50.0)  # ~3.6 doublings
        assert len(loop.agents) >= 4


class TestCompartmentSimulation:
    """The adapter must reproduce the device path's behavior (the two
    paths implement the same exchange-window semantics)."""

    def make_sim(self):
        comp = Compartment(
            processes={"transport": MichaelisMentenTransport()},
            topology={
                "transport": {
                    "external": ("boundary", "external"),
                    "internal": ("cell",),
                    "exchange": ("boundary", "exchange"),
                }
            },
        )
        return CompartmentSimulation(
            comp,
            field_ports={
                "glucose": (
                    ("boundary", "external", "glucose"),
                    ("boundary", "exchange", "glucose_exchange"),
                )
            },
        )

    def test_protocol_cycle(self):
        sim = self.make_sim()
        sim.apply_outer_update({"glucose": 10.0})
        sim.run_incremental(5.0)
        update = sim.generate_inner_update()
        assert update["exchange"]["glucose"] < 0  # net uptake
        assert update["divide"] is False
        # exchange accumulator was drained
        assert sim.generate_inner_update()["exchange"]["glucose"] == 0.0

    def test_host_loop_matches_device_path(self):
        """One agent, same model: HostExchangeLoop vs SpatialColony."""
        from lens_tpu.colony.colony import Colony
        from lens_tpu.environment.spatial import SpatialColony
        from lens_tpu.processes import Growth

        def make_comp():
            return Compartment(
                processes={"transport": MichaelisMentenTransport()},
                topology={
                    "transport": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                        "exchange": ("boundary", "exchange"),
                    }
                },
            )

        # host path
        loop = HostExchangeLoop(small_lattice(diffusion=0.0))
        loop.add_agent(
            CompartmentSimulation(
                make_comp(),
                field_ports={
                    "glucose": (
                        ("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange"),
                    )
                },
            ),
            (4.5, 4.5),
        )
        loop.run(10.0)
        host_mass = float(loop.fields.sum())

        # device path: same model, but location is a schema leaf there —
        # reuse the compartment plus a location-owning dummy via overrides
        comp = make_comp()
        # add location through a motility process-free override: SpatialColony
        # requires the path in the schema, so wire BrownianMotility with
        # sigma=0 (exactly zero displacement)
        from lens_tpu.processes import BrownianMotility

        comp2 = Compartment(
            processes={
                "transport": MichaelisMentenTransport(),
                "motility": BrownianMotility({"sigma": 0.0}),
            },
            topology={
                "transport": {
                    "external": ("boundary", "external"),
                    "internal": ("cell",),
                    "exchange": ("boundary", "exchange"),
                },
                "motility": {"boundary": ("boundary",)},
            },
        )
        colony = Colony(comp2, capacity=1)
        spatial = SpatialColony(
            colony,
            small_lattice(diffusion=0.0),
            field_ports={
                "glucose": (
                    ("boundary", "external", "glucose"),
                    ("boundary", "exchange", "glucose_exchange"),
                )
            },
        )
        ss = spatial.initial_state(
            1, jax.random.PRNGKey(0),
            locations=np.asarray([[4.5, 4.5]], np.float32),
        )
        ss, _ = spatial.run(ss, 10.0, 1.0)
        device_mass = float(ss.fields.sum())
        np.testing.assert_allclose(host_mass, device_mass, rtol=1e-5)


class TestTimers:
    def test_phase_timer_accumulates(self):
        timer = PhaseTimer()
        x = jax.numpy.ones((64, 64))
        for _ in range(3):
            with timer.phase("matmul", fence=x):
                x = x @ x
        s = timer.summary()
        assert s["matmul"]["calls"] == 3
        assert s["matmul"]["total_s"] > 0
        assert "matmul" in timer.report()

    def test_timed_returns_result(self):
        timer = PhaseTimer()
        out = timer.timed("add", lambda a, b: a + b, 1.0, 2.0)
        assert out == 3.0
        assert timer.summary()["add"]["calls"] == 1


class TestExternalSnapshotAdapter:
    """VERDICT r2 item 8: the CellSimulation protocol proven against an
    external snapshot-API model that NEVER touches Compartment — a pure
    numpy fake with the wcEcoli-style surface (set_media / advance_to /
    get_snapshot / divide_snapshot)."""

    class FakeWholeCell:
        """Pure-numpy external model: eats glucose at a media-dependent
        rate, accumulates mass, divides at 2x birth mass. Accounts
        exchange CUMULATIVELY since birth, as snapshot models do."""

        def __init__(self, snapshot=None):
            snap = snapshot or {}
            self.time = float(snap.get("time", 0.0))
            self.mass = float(snap.get("mass", 1.0))
            self.birth_mass = float(snap.get("birth_mass", self.mass))
            self.glc_total = float(snap.get("glc_total", 0.0))
            self.media = {"glucose": 0.0}
            self.closed = False

        def set_media(self, media):
            self.media = dict(media)

        def advance_to(self, t):
            dt = t - self.time
            rate = 0.2 * self.media.get("glucose", 0.0)
            eaten = rate * dt
            self.mass += 0.5 * eaten
            self.glc_total -= eaten  # net secretion convention
            self.time = t

        def get_snapshot(self):
            return {
                "time": self.time,
                "mass": self.mass,
                "birth_mass": self.birth_mass,
                "glc_total": self.glc_total,
                "exchange_totals": {"glucose": self.glc_total},
                "volume": self.mass,
                "ready_to_divide": self.mass >= 2.0 * self.birth_mass,
            }

        def divide_snapshot(self):
            half = self.mass / 2.0
            d = {
                "time": self.time,
                "mass": half,
                "birth_mass": half,
                "glc_total": 0.0,  # daughters restart their accounting
            }
            return dict(d), dict(d)

        def close(self):
            self.closed = True

    def build_loop(self, n=4):
        from lens_tpu.bridge import ExternalSnapshotAdapter, HostExchangeLoop
        from lens_tpu.environment.lattice import Lattice

        lattice = Lattice(
            molecules=["glucose"], shape=(8, 8), size=(8.0, 8.0),
            diffusion=1.0, initial=8.0, timestep=1.0,
        )
        loop = HostExchangeLoop(lattice, exchange_window=1.0)
        factory = self.FakeWholeCell
        for k in range(n):
            loop.add_agent(
                ExternalSnapshotAdapter(factory(), factory),
                location=(2.0 + k, 4.0),
            )
        return loop

    def test_growth_division_and_mass_balance(self):
        loop = self.build_loop()
        glc0 = float(jnp.sum(loop.fields))
        mass0 = sum(
            a.sim.model.mass for a in loop.agents
        )
        loop.run(30.0)
        n1 = len(loop.agents)
        assert n1 > 4, "external model should have divided"
        # every agent is an adapter around the fake (no Compartment)
        from lens_tpu.bridge import ExternalSnapshotAdapter

        for a in loop.agents:
            assert isinstance(a.sim, ExternalSnapshotAdapter)
            assert isinstance(a.sim.model, self.FakeWholeCell)
        # mass balance: field glucose lost = 2x mass gained (yield 0.5)
        glc1 = float(jnp.sum(loop.fields))
        mass1 = sum(a.sim.model.mass for a in loop.agents)
        np.testing.assert_allclose(
            glc0 - glc1, 2.0 * (mass1 - mass0), rtol=1e-4
        )
        # lineage recorded through the host handshake
        parents = [a.parent_id for a in loop.agents if a.parent_id]
        assert parents, "division should record parent ids"

    def test_cumulative_exchange_differencing(self):
        """The adapter converts since-birth totals into per-window deltas:
        two consecutive windows must each debit only their own uptake."""
        loop = self.build_loop(n=1)
        loop.step()
        glc_after_1 = float(jnp.sum(loop.fields))
        loop.step()
        glc_after_2 = float(jnp.sum(loop.fields))
        d1 = 64 * 8.0 - glc_after_1
        d2 = glc_after_1 - glc_after_2
        # consumption continues every window (not double-debited, not zero)
        assert d1 > 1e-3 and d2 > 1e-3
        assert d2 < 2 * d1  # sane magnitude, no cumulative re-application

    def test_parent_finalized_on_division(self):
        loop = self.build_loop(n=1)
        parent_model = loop.agents[0].sim.model
        loop.run(12.0)  # divides ~t=10 (mass 1 -> 2 at 0.8/s uptake rate)
        assert len(loop.agents) >= 2
        assert parent_model.closed  # finalize() reached the external model


class TestChemotaxisSurrogate:
    def test_runs_up_the_gradient(self):
        """Population of run/tumble surrogates drifts toward the high-
        attractant side of a static gradient (diffusion off)."""
        from lens_tpu.surrogates import ChemotaxisSurrogate

        lattice = Lattice(
            molecules=["glucose"], shape=(16, 16), size=(16.0, 16.0),
            diffusion=0.0, initial=0.0, timestep=1.0,
        )
        loop = HostExchangeLoop(lattice, exchange_window=1.0)
        # static linear gradient along the column axis
        import jax.numpy as jnp2

        grad = jnp2.broadcast_to(
            jnp2.linspace(0.0, 10.0, 16)[None, :], (16, 16)
        )
        loop.fields = loop.fields.at[0].set(grad)
        n = 24
        for k in range(n):
            sim = ChemotaxisSurrogate(
                location=(0.5 + (15.0 * k) / n, 2.0), speed=0.8, seed=k,
                domain=(16.0, 16.0),
            )
            loop.add_agent(sim, sim.location)
        x0 = np.mean([a.location[1] for a in loop.agents])
        loop.run(40.0)
        x1 = np.mean([a.location[1] for a in loop.agents])
        assert x1 > x0 + 2.0, (x0, x1)
        # the host loop kept agents inside the domain
        for a in loop.agents:
            assert (a.location >= 0).all() and (a.location <= 16.0).all()
