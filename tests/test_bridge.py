"""The host bridge (CellSimulation protocol), surrogates, and timers."""

import jax
import numpy as np

from lens_tpu.bridge import CompartmentSimulation, HostExchangeLoop
from lens_tpu.core.engine import Compartment
from lens_tpu.environment.lattice import Lattice
from lens_tpu.processes import MichaelisMentenTransport
from lens_tpu.surrogates import ConstantUptakeSurrogate, GrowDivideSurrogate
from lens_tpu.utils.timers import PhaseTimer


def small_lattice(**kw):
    defaults = dict(
        molecules=["glucose"],
        shape=(8, 8),
        size=(8.0, 8.0),
        diffusion=1.0,
        initial=10.0,
        timestep=1.0,
    )
    defaults.update(kw)
    return Lattice(**defaults)


class TestHostLoop:
    def test_uptake_surrogate_depletes_field(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(ConstantUptakeSurrogate(uptake_per_s=0.5), (4.0, 4.0))
        m0 = float(loop.fields.sum())
        loop.run(10.0)
        m1 = float(loop.fields.sum())
        np.testing.assert_allclose(m0 - m1, 5.0, rtol=1e-4)

    def test_division_handshake(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(GrowDivideSurrogate(volume=1.9, rate=0.05), (4.0, 4.0))
        parent = loop.agents[0].sim
        loop.run(3.0)  # 1.9 * e^{0.15} > 2 -> divides
        assert len(loop.agents) == 2
        assert parent.finalized
        va = loop.agents[0].sim.volume
        vb = loop.agents[1].sim.volume
        np.testing.assert_allclose(va, vb)
        assert va < 1.9
        # daughters placed apart — by the same separation the colony fast
        # path's `offset` divider uses (one cell length)
        from lens_tpu.core.state import DIVISION_SEPARATION_UM

        sep = np.linalg.norm(
            loop.agents[0].location - loop.agents[1].location
        )
        np.testing.assert_allclose(sep, DIVISION_SEPARATION_UM, rtol=1e-6)

    def test_population_growth_over_generations(self):
        loop = HostExchangeLoop(small_lattice())
        loop.add_agent(GrowDivideSurrogate(volume=1.0, rate=0.05), (2.0, 2.0))
        loop.run(50.0)  # ~3.6 doublings
        assert len(loop.agents) >= 4


class TestCompartmentSimulation:
    """The adapter must reproduce the device path's behavior (the two
    paths implement the same exchange-window semantics)."""

    def make_sim(self):
        comp = Compartment(
            processes={"transport": MichaelisMentenTransport()},
            topology={
                "transport": {
                    "external": ("boundary", "external"),
                    "internal": ("cell",),
                    "exchange": ("boundary", "exchange"),
                }
            },
        )
        return CompartmentSimulation(
            comp,
            field_ports={
                "glucose": (
                    ("boundary", "external", "glucose"),
                    ("boundary", "exchange", "glucose_exchange"),
                )
            },
        )

    def test_protocol_cycle(self):
        sim = self.make_sim()
        sim.apply_outer_update({"glucose": 10.0})
        sim.run_incremental(5.0)
        update = sim.generate_inner_update()
        assert update["exchange"]["glucose"] < 0  # net uptake
        assert update["divide"] is False
        # exchange accumulator was drained
        assert sim.generate_inner_update()["exchange"]["glucose"] == 0.0

    def test_host_loop_matches_device_path(self):
        """One agent, same model: HostExchangeLoop vs SpatialColony."""
        from lens_tpu.colony.colony import Colony
        from lens_tpu.environment.spatial import SpatialColony
        from lens_tpu.processes import Growth

        def make_comp():
            return Compartment(
                processes={"transport": MichaelisMentenTransport()},
                topology={
                    "transport": {
                        "external": ("boundary", "external"),
                        "internal": ("cell",),
                        "exchange": ("boundary", "exchange"),
                    }
                },
            )

        # host path
        loop = HostExchangeLoop(small_lattice(diffusion=0.0))
        loop.add_agent(
            CompartmentSimulation(
                make_comp(),
                field_ports={
                    "glucose": (
                        ("boundary", "external", "glucose"),
                        ("boundary", "exchange", "glucose_exchange"),
                    )
                },
            ),
            (4.5, 4.5),
        )
        loop.run(10.0)
        host_mass = float(loop.fields.sum())

        # device path: same model, but location is a schema leaf there —
        # reuse the compartment plus a location-owning dummy via overrides
        comp = make_comp()
        # add location through a motility process-free override: SpatialColony
        # requires the path in the schema, so wire BrownianMotility with
        # sigma=0 (exactly zero displacement)
        from lens_tpu.processes import BrownianMotility

        comp2 = Compartment(
            processes={
                "transport": MichaelisMentenTransport(),
                "motility": BrownianMotility({"sigma": 0.0}),
            },
            topology={
                "transport": {
                    "external": ("boundary", "external"),
                    "internal": ("cell",),
                    "exchange": ("boundary", "exchange"),
                },
                "motility": {"boundary": ("boundary",)},
            },
        )
        colony = Colony(comp2, capacity=1)
        spatial = SpatialColony(
            colony,
            small_lattice(diffusion=0.0),
            field_ports={
                "glucose": (
                    ("boundary", "external", "glucose"),
                    ("boundary", "exchange", "glucose_exchange"),
                )
            },
        )
        ss = spatial.initial_state(
            1, jax.random.PRNGKey(0),
            locations=np.asarray([[4.5, 4.5]], np.float32),
        )
        ss, _ = spatial.run(ss, 10.0, 1.0)
        device_mass = float(ss.fields.sum())
        np.testing.assert_allclose(host_mass, device_mass, rtol=1e-5)


class TestTimers:
    def test_phase_timer_accumulates(self):
        timer = PhaseTimer()
        x = jax.numpy.ones((64, 64))
        for _ in range(3):
            with timer.phase("matmul", fence=x):
                x = x @ x
        s = timer.summary()
        assert s["matmul"]["calls"] == 3
        assert s["matmul"]["total_s"] > 0
        assert "matmul" in timer.report()

    def test_timed_returns_result(self):
        timer = PhaseTimer()
        out = timer.timed("add", lambda a, b: a + b, 1.0, 2.0)
        assert out == 3.0
        assert timer.summary()["add"]["calls"] == 1
