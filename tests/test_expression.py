"""Gene-expression suite: transcription/translation/degradation/complexation.

The deterministic expression processes (SURVEY.md §2 "Gene expression
processes") against closed-form/scipy expectations, plus the regulated
transcription path.
"""

import jax
import jax.numpy as jnp
import numpy as np
from scipy.integrate import odeint as scipy_odeint

from lens_tpu.core.engine import Compartment
from lens_tpu.processes.expression import (
    Complexation,
    Degradation,
    Transcription,
    Translation,
)


def expression_compartment(regulation=None, repressors=None):
    return Compartment(
        processes={
            "transcription": Transcription(
                {
                    "rates": {"mrna": 0.5},
                    "regulation": regulation or {},
                    "repressors": repressors or {},
                }
            ),
            "translation": Translation({"pairs": {"protein": ("mrna", 0.1)}}),
            "degradation": Degradation(
                {"rates": {"mrna": 0.05, "protein": 0.01}}
            ),
        },
        topology={
            "transcription": {"counts": ("counts",)},
            "translation": {"counts": ("counts",)},
            "degradation": {"counts": ("counts",)},
        },
    )


def test_central_dogma_vs_scipy():
    """mRNA -> protein with decay matches the 2-species linear ODE."""
    comp = expression_compartment()
    final, traj = comp.run(comp.initial_state(), 200.0, 0.5)

    def rhs(y, t):
        m, p = y
        return [0.5 - 0.05 * m, 0.1 * m - 0.01 * p]

    ref = scipy_odeint(rhs, [0.0, 0.0], np.linspace(0, 200.0, 401))[-1]
    np.testing.assert_allclose(
        float(final["counts"]["mrna"]), ref[0], rtol=0.05
    )
    np.testing.assert_allclose(
        float(final["counts"]["protein"]), ref[1], rtol=0.05
    )


def test_steady_state_mrna():
    """mRNA steady state = synthesis/decay = 0.5/0.05 = 10."""
    comp = expression_compartment()
    final, _ = comp.run(comp.initial_state(), 2000.0, 1.0)
    np.testing.assert_allclose(float(final["counts"]["mrna"]), 10.0, rtol=0.02)


def test_boolean_regulation_shuts_off_gene():
    comp = expression_compartment(regulation={"mrna": "not repressor"})
    state = comp.initial_state({"counts": {"repressor": 5.0}})
    final, _ = comp.run(state, 100.0, 1.0)
    assert float(final["counts"]["mrna"]) == 0.0

    state_on = comp.initial_state({"counts": {"repressor": 0.0}})
    final_on, _ = comp.run(state_on, 100.0, 1.0)
    assert float(final_on["counts"]["mrna"]) > 5.0


def test_hill_repression_reduces_synthesis():
    free = expression_compartment()
    repressed = expression_compartment(
        repressors={"mrna": ("repressor", 10.0, 2.0)}
    )
    f_final, _ = free.run(free.initial_state(), 100.0, 1.0)
    r_state = repressed.initial_state({"counts": {"repressor": 100.0}})
    r_final, _ = repressed.run(r_state, 100.0, 1.0)
    assert (
        float(r_final["counts"]["mrna"]) < 0.1 * float(f_final["counts"]["mrna"])
    )


def test_complexation_conserves_subunits():
    comp = Compartment(
        processes={
            "complexation": Complexation(
                {
                    "reactions": {
                        "dimer": {
                            "subunits": {"a": 1, "b": 2},
                            "k_on": 1e-3,
                            "k_off": 1e-4,
                        }
                    }
                }
            )
        },
        topology={"complexation": {"counts": ("counts",)}},
    )
    state = comp.initial_state({"counts": {"a": 100.0, "b": 200.0}})
    final, _ = comp.run(state, 500.0, 1.0)
    a = float(final["counts"]["a"])
    b = float(final["counts"]["b"])
    d = float(final["counts"]["dimer"])
    assert d > 1.0  # reaction actually ran
    np.testing.assert_allclose(a + d, 100.0, rtol=1e-4)
    np.testing.assert_allclose(b + 2 * d, 200.0, rtol=1e-4)


def test_complexation_never_negative():
    comp = Compartment(
        processes={
            "complexation": Complexation(
                {
                    "reactions": {
                        "cplx": {
                            "subunits": {"a": 1, "b": 1},
                            "k_on": 10.0,  # aggressive: would overshoot
                            "k_off": 0.0,
                        }
                    }
                }
            )
        },
        topology={"complexation": {"counts": ("counts",)}},
    )
    state = comp.initial_state({"counts": {"a": 3.0, "b": 1000.0}})
    final, _ = comp.run(state, 10.0, 1.0)
    assert float(final["counts"]["a"]) >= 0.0
    assert float(final["counts"]["b"]) >= 0.0


def test_expression_vmaps_over_agents():
    comp = expression_compartment()
    single = comp.initial_state()
    stacked = jax.tree.map(
        lambda x: jnp.broadcast_to(x, (16,) + x.shape), single
    )
    stepped = jax.vmap(lambda s: comp.step(s, 1.0))(stacked)
    assert stepped["counts"]["mrna"].shape == (16,)
    assert float(stepped["counts"]["mrna"][0]) > 0.0


def test_complexation_shared_subunit_joint_clamp():
    """Two reactions draining the same subunit must not jointly overdraw
    it (regression: per-reaction clamping alone fabricates complex mass)."""
    comp = Compartment(
        processes={
            "complexation": Complexation(
                {
                    "reactions": {
                        "c1": {
                            "subunits": {"a": 1, "b": 1},
                            "k_on": 10.0,
                            "k_off": 0.0,
                        },
                        "c2": {
                            "subunits": {"a": 1, "d": 1},
                            "k_on": 10.0,
                            "k_off": 0.0,
                        },
                    }
                }
            )
        },
        topology={"complexation": {"counts": ("counts",)}},
    )
    state = comp.initial_state(
        {"counts": {"a": 3.0, "b": 1000.0, "d": 1000.0}}
    )
    final, _ = comp.run(state, 20.0, 1.0)
    a = float(final["counts"]["a"])
    c1 = float(final["counts"]["c1"])
    c2 = float(final["counts"]["c2"])
    assert a >= 0.0
    # total 'a' is conserved: free + bound-in-c1 + bound-in-c2 == 3
    np.testing.assert_allclose(a + c1 + c2, 3.0, rtol=1e-4)


# -- genome-scale expression from the gene table (VERDICT r2 item 2) ----------


class TestGenomeExpression:
    def _proc(self, **over):
        from lens_tpu.processes.genome_expression import GenomeExpression

        cfg = {"genes": "ecoli_core"}
        cfg.update(over)
        return GenomeExpression(cfg)

    def test_table_loads_tens_of_genes(self):
        p = self._proc()
        assert len(p.genes) >= 30
        assert "lacZ" in p.genes and "gapA" in p.genes
        # rule species collected from every gene's regulation rule
        assert set(p.rule_species) == {"glc", "lcts", "o2"}

    def test_stationary_means_per_gene(self):
        """Run one cell long enough to equilibrate; every UNREGULATED
        gene's mRNA mean ~ k_tx/d_m and protein mean ~ k_tx k_tl/(d_m d_p)
        (lac genes etc. are gated off in the default 0-concentration env)."""
        import jax

        p = self._proc(substeps=5)
        s = p.initial_state()
        # aerobic glucose environment: glc+o2 rules on, lac rules off
        s["external"]["glc"] = jnp.asarray(10.0)
        s["external"]["o2"] = jnp.asarray(5.0)

        @jax.jit
        def run(s, key):
            def body(carry, k):
                s = carry
                upd = p.next_update(1.0, s, key=k)
                counts = {
                    mol: jnp.maximum(s["counts"][mol] + d, 0.0)
                    for mol, d in upd["counts"].items()
                }
                s = dict(s, counts=counts)
                return s, s["counts"]["mrna"]

            keys = jax.random.split(key, 600)
            return jax.lax.scan(body, s, keys)

        final, mrna_traj = run(s, jax.random.PRNGKey(0))
        # average the last 300 steps across time as a stand-in ensemble
        tail = np.asarray(mrna_traj[300:])
        k_tx = np.asarray(final["rates"]["k_tx"])
        d_m = np.asarray(final["rates"]["d_m"])
        gate_on = np.ones(len(p.genes), bool)
        for i, _ in p._rules.items():
            # under glc+o2: "not glc"/"not glc and lcts" rules are off
            gate_on[i] = p.genes[i] in (
                "ptsG", "cyoA", "cyoB", "nuoA", "sdhA", "sucA", "fumA",
            )
        expect = k_tx / d_m
        got = tail.mean(axis=0)
        # stochastic: accept 3-sigma-ish band around the Poisson mean
        for i in np.nonzero(gate_on)[0]:
            assert abs(got[i] - expect[i]) < max(1.5, 4 * np.sqrt(expect[i] / 300)), (
                p.genes[i], got[i], expect[i]
            )
        # gated genes transcribe nothing
        for i in p._rules:
            if not gate_on[i]:
                assert got[i] < 0.5, (p.genes[i], got[i])

    def test_lac_operon_follows_environment(self):
        import jax

        p = self._proc()
        s = p.initial_state()
        s["external"]["lcts"] = jnp.asarray(10.0)  # lactose, no glucose
        key = jax.random.PRNGKey(1)
        lacz = p.genes.index("lacZ")

        @jax.jit
        def step(state, i):
            upd = p.next_update(
                1.0, state, key=jax.random.fold_in(key, i)
            )
            counts = {
                mol: jnp.maximum(state["counts"][mol] + d, 0.0)
                for mol, d in upd["counts"].items()
            }
            return dict(state, counts=counts)

        for i in range(50):
            s = step(s, jnp.asarray(i))
        assert float(s["counts"]["mrna"][lacz]) >= 0.0
        assert float(jnp.sum(s["counts"]["mrna"])) > 0
        # induced: lacZ transcribed
        assert float(s["counts"]["protein"][lacz]) > 0

        # add glucose -> catabolite repression shuts lac off
        s["external"]["glc"] = jnp.asarray(10.0)
        upd = p.next_update(1.0, s, key=jax.random.fold_in(key, 99))
        # transcription propensity gated: mRNA can only decay now
        assert float(upd["counts"]["mrna"][lacz]) <= 0.0

    def test_vmap_and_division_integrality(self):
        import jax
        from lens_tpu.core.state import divide_state

        p = self._proc()
        s = p.initial_state()
        n = 8
        stacked = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (n,) + jnp.shape(x)), s
        )
        keys = jax.random.split(jax.random.PRNGKey(2), n)
        upd = jax.vmap(lambda st, k: p.next_update(1.0, st, key=k))(
            stacked, keys
        )
        assert upd["counts"]["mrna"].shape == (n, len(p.genes))
        # counts leaves split binomially and stay integral
        s2 = dict(s)
        s2["counts"] = {
            "mrna": jnp.full(len(p.genes), 7.0),
            "protein": jnp.full(len(p.genes), 101.0),
        }
        dividers = {
            ("counts", "mrna"): "binomial",
            ("counts", "protein"): "binomial",
        }
        a, b = divide_state(
            {"counts": s2["counts"]}, jax.random.PRNGKey(3), dividers
        )
        np.testing.assert_allclose(
            np.asarray(a["counts"]["mrna"]) + np.asarray(b["counts"]["mrna"]),
            7.0,
        )
        for leaf in (a["counts"]["protein"], b["counts"]["protein"]):
            arr = np.asarray(leaf)
            np.testing.assert_allclose(arr, np.round(arr))
