"""Units, rate laws, and the regulation-rule compiler."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.utils import rate_laws, units
from lens_tpu.utils.regulation_logic import compile_rule


class TestUnits:
    def test_count_concentration_roundtrip(self):
        counts = units.millimolar_to_counts(1.0, 1.0)
        np.testing.assert_allclose(counts, 6.02214076e5, rtol=1e-6)
        np.testing.assert_allclose(
            units.counts_to_millimolar(counts, 1.0), 1.0, rtol=1e-6
        )

    def test_volume_mass_roundtrip(self):
        v = units.volume_from_mass(660.0)
        np.testing.assert_allclose(units.mass_from_volume(v), 660.0, rtol=1e-6)

    def test_doubling_time(self):
        rate = units.doubling_time_to_rate(1200.0)
        np.testing.assert_allclose(np.exp(rate * 1200.0), 2.0, rtol=1e-6)


class TestRateLaws:
    def test_michaelis_menten_half_saturation(self):
        np.testing.assert_allclose(
            rate_laws.michaelis_menten(0.5, 1.0, 0.5), 0.5, rtol=1e-5
        )

    def test_negative_substrate_clamped(self):
        assert float(rate_laws.michaelis_menten(-1.0, 1.0, 0.5)) == 0.0
        assert float(rate_laws.first_order(0.1, -5.0)) == 0.0

    def test_hill_limits(self):
        assert float(rate_laws.hill(100.0, 1.0, 1.0, 4.0)) > 0.99
        assert float(rate_laws.hill_repression(100.0, 1.0, 1.0, 4.0)) < 0.01

    def test_competitive_inhibition_reduces_rate(self):
        base = float(rate_laws.michaelis_menten(1.0, 1.0, 0.5))
        inhibited = float(
            rate_laws.competitive_inhibition(1.0, 10.0, 1.0, 0.5, 1.0)
        )
        assert inhibited < base

    def test_mass_action(self):
        np.testing.assert_allclose(
            rate_laws.mass_action(2.0, 3.0, 4.0), 24.0, rtol=1e-6
        )


class TestRegulationLogic:
    def test_presence(self):
        rule = compile_rule("glc")
        assert float(rule({"glc": jnp.asarray(1.0)})) == 1.0
        assert float(rule({"glc": jnp.asarray(0.0)})) == 0.0

    def test_not(self):
        rule = compile_rule("not glc")
        assert float(rule({"glc": jnp.asarray(1.0)})) == 0.0
        assert float(rule({"glc": jnp.asarray(0.0)})) == 1.0

    def test_and_or_parens(self):
        rule = compile_rule("a and (b or not c)")
        env = lambda a, b, c: {  # noqa: E731
            "a": jnp.asarray(a),
            "b": jnp.asarray(b),
            "c": jnp.asarray(c),
        }
        assert float(rule(env(1.0, 1.0, 1.0))) == 1.0
        assert float(rule(env(1.0, 0.0, 1.0))) == 0.0
        assert float(rule(env(1.0, 0.0, 0.0))) == 1.0
        assert float(rule(env(0.0, 1.0, 0.0))) == 0.0

    def test_comparison(self):
        rule = compile_rule("glc > 2.5")
        assert float(rule({"glc": jnp.asarray(3.0)})) == 1.0
        assert float(rule({"glc": jnp.asarray(2.0)})) == 0.0

    def test_case_insensitive_keywords_preserve_names(self):
        rule = compile_rule("NOT GlcX")
        assert rule.names == ("GlcX",)
        assert float(rule({"GlcX": jnp.asarray(0.0)})) == 1.0

    def test_vectorized_under_vmap(self):
        rule = compile_rule("a and not b")
        a = jnp.asarray([1.0, 1.0, 0.0])
        b = jnp.asarray([0.0, 1.0, 0.0])
        out = jax.vmap(lambda a, b: rule({"a": a, "b": b}))(a, b)
        np.testing.assert_array_equal(np.asarray(out), [1.0, 0.0, 0.0])

    def test_jit_compatible(self):
        rule = compile_rule("x > 1 and not y")
        f = jax.jit(lambda x, y: rule({"x": x, "y": y}))
        assert float(f(jnp.asarray(2.0), jnp.asarray(0.0))) == 1.0

    def test_empty_rule_is_on(self):
        assert float(compile_rule("")({})) == 1.0

    def test_missing_species_raises(self):
        rule = compile_rule("missing_thing")
        with pytest.raises(KeyError):
            rule({})

    def test_garbage_raises(self):
        with pytest.raises(ValueError):
            compile_rule("a and and b")
