"""Multi-host serving cluster (round 17): locality-aware routing,
work-stealing, whole-host failover.

The contract (docs/serving.md, "Cluster serving"): a ``ClusterServer``
routes requests across one serve worker per host; per-request bits are
host-independent (each worker is a full ``SimServer``, and the serving
determinism contract makes results a pure function of the request), so
a request's streamed bytes are identical wherever it runs — including
after a steal or a whole-host failover re-queues it. A host that dies
mid-load loses no admitted work: its per-host WAL is read back and
every unfinished request re-queues onto survivors under its original
id, spill-backed snapshots re-adopting from the shared tier directory.

Tiers here: pure-logic tests (protocol framing, WAL classification,
withdraw/adopt semantics, the wal dump CLI) run everywhere; in-process
simulated-host clusters (LocalHost — same op dispatch, no process
spawns) carry the quick routing/stealing/failover signal; the REAL
drills — subprocess workers, real SIGKILLs, bitwise oracle pins at 2
and 4 hosts — are slow-marked to protect the tier-1 time budget
(run_tests.sh runs them in the cluster batch).
"""

import glob
import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from lens_tpu.cluster import ClusterServer, HostDown
from lens_tpu.cluster.protocol import (
    encode_error,
    raise_error,
    recv_msg,
    rpc,
    send_msg,
)
from lens_tpu.cluster.worker import ID_SPAN, _offset_ids
from lens_tpu.serve import (
    DONE,
    FAILED,
    QueueFull,
    RequestValidationError,
    ScenarioRequest,
    ServeWal,
    SimServer,
)
from lens_tpu.serve.batcher import MIGRATED, QUEUED
from lens_tpu.serve.faults import FaultPlan
from lens_tpu.serve.wal import classify_events, read_events, unfinished

BUCKET = {"capacity": 16, "lanes": 2, "window": 8}


def _cluster(tmp_path, hosts=2, local=True, lanes=2, **kw):
    kw.setdefault("worker", {"pipeline": "off"})
    return ClusterServer(
        {"toggle_colony": {**BUCKET, "lanes": lanes}},
        hosts=hosts,
        cluster_dir=str(tmp_path / "cluster"),
        local=local,
        **kw,
    )


def _req(seed, horizon=16.0, **kw):
    return ScenarioRequest(
        composite="toggle_colony", seed=seed, horizon=horizon, **kw
    )


# -- protocol (no jax, no servers) -------------------------------------------


class TestProtocol:
    def test_roundtrip_over_socketpair(self):
        a, b = socket.socketpair()
        try:
            send_msg(a, {"op": "ping", "x": [1, 2, 3]})
            assert recv_msg(b) == {"op": "ping", "x": [1, 2, 3]}
            send_msg(b, {"ok": True, "y": "z"})
            assert recv_msg(a)["y"] == "z"
        finally:
            a.close()
            b.close()

    def test_rpc_raises_typed_errors(self):
        a, b = socket.socketpair()
        try:
            import threading

            def server():
                msg = recv_msg(b)
                send_msg(b, encode_error(
                    QueueFull(3.5, 7) if msg["op"] == "full"
                    else RequestValidationError("bad", path="emit.every")
                ))

            t = threading.Thread(target=server)
            t.start()
            with pytest.raises(QueueFull) as e:
                rpc(a, "full", timeout=5)
            t.join()
            assert e.value.retry_after == 3.5
            assert e.value.depth == 7
            t = threading.Thread(target=server)
            t.start()
            with pytest.raises(RequestValidationError) as e:
                rpc(a, "validate", timeout=5)
            t.join()
            assert e.value.path == "emit.every"
        finally:
            a.close()
            b.close()

    def test_unknown_error_type_becomes_runtime_error(self):
        with pytest.raises(RuntimeError, match="Weird: boom"):
            raise_error({"error_type": "Weird", "error": "boom"})

    def test_oversized_frame_refused(self):
        import struct

        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack(">I", 2**30))
            with pytest.raises(ConnectionError, match="exceeds"):
                recv_msg(b)
        finally:
            a.close()
            b.close()

    def test_peer_close_mid_frame(self):
        a, b = socket.socketpair()
        a.sendall(b"\x00\x00")
        a.close()
        try:
            with pytest.raises(ConnectionError):
                recv_msg(b)
        finally:
            b.close()


# -- WAL classification + dump CLI (no servers) ------------------------------


def _wal_events(tmp_path, events):
    wal = ServeWal(str(tmp_path / "serve.wal"))
    for ev in events:
        wal.append(ev)
    wal.close()
    return str(tmp_path)


class TestWalClassify:
    EVENTS = [
        {"event": "submit", "rid": "req-000000",
         "request": {"composite": "toggle_colony", "seed": 1,
                     "horizon": 8.0}},
        {"event": "submit", "rid": "req-000001",
         "request": {"composite": "toggle_colony", "seed": 2,
                     "horizon": 8.0, "hold_state": True}},
        {"event": "retire", "rid": "req-000000", "status": "done",
         "steps": 8},
        {"event": "streamed", "rid": "req-000000"},
        {"event": "retire", "rid": "req-000001", "status": "done",
         "steps": 8},
        {"event": "hold", "rid": "req-000001", "key": ["k"],
         "name": "snap_x"},
        {"event": "submit", "rid": "req-000002",
         "request": {"composite": "toggle_colony", "seed": 3,
                     "horizon": 8.0}},
    ]

    def test_classify_and_unfinished(self):
        order, recs, retired, streamed, holds, released = (
            classify_events(self.EVENTS)
        )
        assert order == ["req-000000", "req-000001", "req-000002"]
        assert set(recs) == set(order)
        assert retired["req-000000"]["status"] == "done"
        assert "req-000000" in streamed
        assert holds["req-000001"]["name"] == "snap_x"
        # req-000001 retired DONE but never attested streamed: it must
        # re-run; req-000002 never retired at all
        assert unfinished(order, retired, streamed) == [
            "req-000001", "req-000002",
        ]

    def test_migrated_retire_is_finished(self):
        events = self.EVENTS + [
            {"event": "retire", "rid": "req-000002",
             "status": MIGRATED, "steps": 0},
        ]
        order, recs, retired, streamed, *_ = classify_events(events)
        # a stolen request must never be re-run by failover: it lives
        # on another host now
        assert unfinished(order, retired, streamed) == ["req-000001"]

    def test_read_events_merges_dir(self, tmp_path):
        d = _wal_events(tmp_path, self.EVENTS)
        events = read_events(d)
        assert [e["event"] for e in events if e["event"] != "server_begin"] \
            == [e["event"] for e in self.EVENTS]
        assert all("seq" in e for e in events)

    def test_read_events_missing(self, tmp_path):
        with pytest.raises(FileNotFoundError):
            read_events(str(tmp_path / "nope"))

    def test_wal_cli_dump(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        d = _wal_events(tmp_path, self.EVENTS)
        assert main(["wal", d]) == 0
        out = capsys.readouterr().out
        assert "submit" in out and "req-000001" in out
        assert "hold_state" in out      # submit detail
        assert "status=done" in out     # retire detail
        assert "spill=snap_x" in out    # hold detail

    def test_wal_cli_rid_filter_follows_ancestry(
        self, tmp_path, capsys
    ):
        from lens_tpu.__main__ import main

        events = self.EVENTS + [
            {"event": "resubmit", "rid": "req-000009",
             "parent": "req-000001", "extra_horizon": 8.0},
        ]
        d = _wal_events(tmp_path, events)
        assert main(["wal", d, "--rid", "req-000009"]) == 0
        out = capsys.readouterr().out
        assert "req-000009" in out
        assert "req-000001" in out      # the parent rides along
        assert "req-000000" not in out  # unrelated rid filtered

    def test_wal_cli_json(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        d = _wal_events(tmp_path, self.EVENTS)
        assert main(["wal", d, "--json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert len(data) == 1
        kinds = [e["event"] for e in data[0]["events"]]
        assert "submit" in kinds and "hold" in kinds

    def test_wal_cli_no_wal(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        assert main(["wal", str(tmp_path)]) == 2


class TestHostDownFault:
    def test_occurrence_counts_per_host(self):
        plan = FaultPlan([
            {"kind": "host_down", "host": 1, "occurrence": 2},
        ])
        assert not plan.host_down(0)
        assert not plan.host_down(1)
        assert plan.host_down(1)
        assert not plan.host_down(1)

    def test_host_key_rejected_elsewhere(self):
        with pytest.raises(ValueError, match="only applies"):
            FaultPlan([{"kind": "nan", "host": 0}])

    def test_request_filter_rejected(self):
        with pytest.raises(ValueError, match="failure domain"):
            FaultPlan([{"kind": "host_down", "request": "req-000001"}])


class TestReviewRegressions:
    """Pins for review findings: each of these was a real bug once."""

    def test_poll_timeout_is_a_miss_not_hostdown(self, monkeypatch):
        """socket.timeout subclasses OSError: the health poll must let
        it propagate (one counted miss, tolerated heartbeat_misses
        times) instead of converting it to an immediate HostDown."""
        from lens_tpu.cluster import router as router_mod

        h = router_mod.RemoteHost.__new__(router_mod.RemoteHost)
        router_mod._Host.__init__(h, 0, "/nonexistent")
        h.health_sock = object()
        h.heartbeat_s = 0.01
        h._desynced = False

        def slow_rpc(*a, **kw):
            raise socket.timeout("health poll timed out")

        monkeypatch.setattr(router_mod, "rpc", slow_rpc)
        with pytest.raises(socket.timeout):
            h.poll()

    def test_local_worker_faults_spec_injects(self, tmp_path):
        """local=True converts a worker faults spec exactly like the
        subprocess entry does, instead of silently dropping it."""
        srv = _cluster(
            tmp_path, hosts=1,
            worker={
                "pipeline": "off",
                "faults": {
                    "seed": 7,
                    "faults": [{"kind": "nan", "occurrence": 99}],
                },
            },
        )
        try:
            plan = srv.hosts[0].core.server.faults
            assert isinstance(plan, FaultPlan)
            assert plan.seed == 7
            assert [f.kind for f in plan.faults] == ["nan"]
        finally:
            srv.close()

    def test_idle_publish_version_stable(self, tmp_path):
        """An idle worker's snapshot version must settle so router
        polls come back ``unchanged`` instead of reshipping the full
        ticket table every heartbeat."""
        with _cluster(tmp_path, hosts=1) as srv:
            rid = srv.submit(_req(1, horizon=8.0))
            srv.run_until_idle(max_ticks=300)
            assert srv.status(rid)["status"] == DONE
            core = srv.hosts[0].core
            v = core._published["version"]
            # idle ticks past the refresh cadence rebuild the snapshot
            # but must not bump the version while nothing changed
            core._published_at -= core.IDLE_PUBLISH_EVERY_S + 1
            core.tick_once()
            assert core._published["version"] == v
            reply = core.handle_health({"op": "poll", "since": v})
            assert reply.get("unchanged") is True

    def test_poll_resync_after_late_reply(self):
        """A health reply landing after the poll timeout must not
        leave the stream desynchronized: the next poll drains the
        stale frame and reads its own reply."""
        import threading

        from lens_tpu.cluster import router as router_mod

        a, b = socket.socketpair()
        h = router_mod.RemoteHost.__new__(router_mod.RemoteHost)
        router_mod._Host.__init__(h, 0, "/nonexistent")
        h.health_sock = a
        h.heartbeat_s = 0.2
        h._desynced = False

        def worker():
            n = 0
            try:
                while True:
                    recv_msg(b)
                    n += 1
                    if n == 1:
                        time.sleep(0.6)  # past heartbeat_s
                    send_msg(b, {"ok": True, "version": n})
            except (OSError, ValueError):
                pass

        threading.Thread(target=worker, daemon=True).start()
        try:
            with pytest.raises(socket.timeout):
                h.poll()
            time.sleep(0.8)  # the late reply lands in the buffer
            assert h._desynced
            reply = h.poll()
            assert reply["version"] == 2
            assert not h._desynced
        finally:
            a.close()
            b.close()

    def test_rerun_over_cluster_dir_resumes(self, tmp_path):
        """A second ClusterServer over the same cluster_dir mirrors
        the WAL-known work (tickets + recovered count) and mints rids
        PAST it — a colliding req-000000 would share the first run's
        ticket slot and its shared out/ log file."""
        with _cluster(tmp_path, hosts=1) as srv:
            done_rid = srv.submit(_req(1, horizon=8.0))
            srv.run_until_idle(max_ticks=300)
            assert srv.status(done_rid)["status"] == DONE
            queued_rid = srv.submit(_req(2, horizon=8.0))
            # close with it still queued: the WAL knows the submit,
            # no retire — a rerun must re-queue it
            data = open(srv.result(done_rid), "rb").read()
        with _cluster(tmp_path, hosts=1) as srv2:
            assert srv2.recovered == 1  # the queued one re-queued
            assert srv2.status(done_rid)["status"] == DONE
            assert open(srv2.result(done_rid), "rb").read() == data
            assert queued_rid in srv2.tickets
            fresh = srv2.submit(_req(3, horizon=8.0))
            assert fresh not in (done_rid, queued_rid)
            srv2.run_until_idle(max_ticks=600)
            for rid in (queued_rid, fresh):
                assert srv2.status(rid)["status"] == DONE

    def test_cli_forwards_worker_knobs(self, monkeypatch, tmp_path):
        """serve --hosts N forwards every worker-level CLI flag
        (mesh, check_finite, watchdog, worker faults, ...) into the
        ClusterServer's worker= kwargs."""
        import lens_tpu.cluster as cluster_pkg
        from lens_tpu.__main__ import _build_cluster, _build_parser

        captured = {}

        def fake_cluster(*a, **kw):
            captured.update(kw)
            return "cluster-sentinel"

        monkeypatch.setattr(cluster_pkg, "ClusterServer", fake_cluster)
        faults_path = tmp_path / "faults.json"
        faults_path.write_text(json.dumps({
            "seed": 3,
            "faults": [
                {"kind": "host_down", "host": 0, "occurrence": 1},
                {"kind": "nan", "occurrence": 99},
            ],
        }))
        args = _build_parser().parse_args([
            "serve", "--requests", str(tmp_path / "r.json"),
            "--hosts", "2", "--mesh", "2",
            "--check-finite", "window", "--watchdog", "30",
            "--faults", str(faults_path),
            "--out-dir", str(tmp_path / "c"),
        ])
        assert _build_cluster(args) == "cluster-sentinel"
        worker = captured["worker"]
        assert worker["mesh"] == 2
        assert worker["check_finite"] == "window"
        assert worker["watchdog_s"] == 30.0
        # the fault spec splits: host_down stays at the router, the
        # rest ride to the workers
        assert [f["kind"] for f in worker["faults"]["faults"]] \
            == ["nan"]
        assert [f.kind for f in captured["faults"].faults] \
            == ["host_down"]


class TestOffsetIds:
    class _Stub:
        def __init__(self, tickets):
            self.tickets = tickets
            self.skipped = None
            stub = self

            class Q:
                def skip_ids(self, n):
                    stub.skipped = n

            self.queue = Q()

    def test_offset_applies(self):
        s = self._Stub({"req-000004": None})
        _offset_ids(s, ID_SPAN)
        assert s.skipped == ID_SPAN

    def test_never_moves_backwards(self):
        s = self._Stub({f"req-{ID_SPAN + 17:06d}": None})
        _offset_ids(s, ID_SPAN)
        assert s.skipped == ID_SPAN + 18


# -- withdraw / adopt on a real SimServer ------------------------------------


class TestWithdrawAdopt:
    def test_withdraw_only_clean_queued(self, tmp_path):
        srv = SimServer.single_bucket(
            "toggle_colony", **{**BUCKET, "lanes": 1},
            pipeline="off",
            out_dir=str(tmp_path / "out"), sink="log",
            recover_dir=str(tmp_path / "wal"),
        )
        rids = [srv.submit(_req(s, horizon=32.0)) for s in range(3)]
        srv.tick()  # rids[0] running, rest queued
        with pytest.raises(ValueError, match="not queued"):
            srv.withdraw(rids[0])
        payload = srv.withdraw(rids[2])
        assert payload["seed"] == 2
        assert srv.tickets[rids[2]].status == MIGRATED
        # the WAL knows: this host's own recovery (and any failover
        # over this WAL) treats the rid as finished here
        events = [
            e for e in srv._wal.events
            if e.get("rid") == rids[2] and e["event"] == "retire"
        ]
        assert events and events[0]["status"] == MIGRATED
        assert srv.metrics()["counters"]["stolen"] == 1
        srv.run_until_idle(max_ticks=300)
        srv.close()

    def test_adopt_displaced_requeues_bitwise(self, tmp_path):
        """A survivor adopting a dead host's WAL re-runs the request
        to the same bytes the dead host would have produced."""
        out = tmp_path / "out"
        a = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(out), sink="log",
            recover_dir=str(tmp_path / "wal_a"),
        )
        ra = a.submit(_req(5, horizon=16.0))
        events = list(a._wal.events)
        # host A "dies" before running anything; read its WAL
        b = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(out), sink="log",
            recover_dir=str(tmp_path / "wal_b"),
        )
        adopted = b.adopt_displaced(events, [ra])
        assert adopted == [ra]
        assert b.metrics()["counters"]["adopted"] == 1
        b.run_until_idle(max_ticks=300)
        assert b.status(ra)["status"] == DONE
        got = open(b.result(ra), "rb").read()
        # reference: the same request run start-to-finish on one host
        ref_srv = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(tmp_path / "ref"), sink="log",
        )
        ref_srv.queue.skip_ids(int(ra.rsplit("-", 1)[1]))
        rr = ref_srv.submit(_req(5, horizon=16.0))
        assert rr == ra
        ref_srv.run_until_idle(max_ticks=300)
        ref = open(ref_srv.result(rr), "rb").read()
        assert got == ref
        # the adoption is WAL'd on B: B's own recovery now owns it
        assert any(
            e.get("rid") == ra and e["event"] == "submit"
            for e in b._wal.events
        )
        ref_srv.close()
        b.close()
        a.close()

    def test_adopt_finished_materializes_without_rerun(self, tmp_path):
        """A rid the WAL attests FINISHED adopts as a terminal ticket
        over its existing log — no lane ever runs it again."""
        out = tmp_path / "out"
        a = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(out), sink="log",
            recover_dir=str(tmp_path / "wal_a"),
        )
        ra = a.submit(_req(5, horizon=16.0))
        a.run_until_idle(max_ticks=300)
        assert a.status(ra)["status"] == DONE
        data = open(a.result(ra), "rb").read()
        events = list(a._wal.events)
        b = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(out), sink="log",
            recover_dir=str(tmp_path / "wal_b"),
        )
        windows_before = b.metrics()["counters"]["windows"]
        b.adopt_displaced(events, [ra])
        assert b.status(ra)["status"] == DONE
        assert b.result(ra) == os.path.join(str(out), f"{ra}.lens")
        b.run_until_idle(max_ticks=50)
        assert b.metrics()["counters"]["windows"] == windows_before
        assert open(b.result(ra), "rb").read() == data
        b.close()
        a.close()

    def test_adopt_duplicate_refused(self, tmp_path):
        srv = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(tmp_path / "out"), sink="log",
            recover_dir=str(tmp_path / "wal"),
        )
        rid = srv.submit(_req(1))
        with pytest.raises(ValueError, match="duplicate"):
            srv.adopt_displaced(list(srv._wal.events), [rid])
        srv.run_until_idle(max_ticks=300)
        srv.close()

    def test_adopt_unknown_rid_refused(self, tmp_path):
        srv = SimServer.single_bucket(
            "toggle_colony", **BUCKET, pipeline="off",
            out_dir=str(tmp_path / "out"), sink="log",
            recover_dir=str(tmp_path / "wal"),
        )
        with pytest.raises(ValueError, match="no submit records"):
            srv.adopt_displaced([], ["req-000042"])
        srv.close()


# -- in-process simulated-host clusters (LocalHost) --------------------------


class TestLocalCluster:
    def test_routes_and_completes_across_hosts(self, tmp_path):
        with _cluster(tmp_path, hosts=2) as srv:
            rids = [srv.submit(_req(s)) for s in range(4)]
            srv.run_until_idle(max_ticks=500)
            hosts = set()
            for rid in rids:
                st = srv.status(rid)
                assert st["status"] == DONE
                path = srv.result(rid)
                assert os.path.exists(path)
                hosts.add(srv.tickets[rid].host)
            # least-loaded routing spreads an even load over both
            assert hosts == {0, 1}
            snap = srv.metrics()
            assert snap["hosts_alive"] == 2
            assert snap["lanes_total"] == 4  # 2 hosts x 2 lanes
            assert snap["counters"]["retired"] >= 4

    def test_work_stealing_rebalances_pinned_skew(self, tmp_path):
        with _cluster(
            tmp_path, hosts=2, lanes=1, steal_threshold=2,
        ) as srv:
            rids = [
                srv.submit(_req(s, horizon=24.0), host=0)
                for s in range(6)
            ]
            srv.run_until_idle(max_ticks=800)
            snap = srv.metrics()
            assert snap["counters"]["router_stolen"] >= 1
            assert {srv.tickets[r].host for r in rids} == {0, 1}
            for rid in rids:
                assert srv.status(rid)["status"] == DONE
            # donor's WAL marks the stolen rids MIGRATED — they can
            # never be double-run by a later failover of host 0
            events = read_events(srv.hosts[0].wal_dir)
            _, _, retired, *_ = classify_events(events)
            stolen = [
                r for r in rids
                if retired.get(r, {}).get("status") == MIGRATED
            ]
            assert len(stolen) == snap["counters"]["router_stolen"]

    def test_host_down_failover_completes_everything(self, tmp_path):
        with _cluster(
            tmp_path, hosts=2,
            faults=FaultPlan([
                {"kind": "host_down", "host": 1, "occurrence": 2},
            ]),
        ) as srv:
            rids = [srv.submit(_req(s, horizon=24.0)) for s in range(6)]
            srv.run_until_idle(max_ticks=1000)
            snap = srv.metrics()
            assert snap["hosts_down"] == [1]
            assert snap["counters"]["router_requeued"] >= 1
            for rid in rids:
                assert srv.status(rid)["status"] == DONE
                assert srv.tickets[rid].host == 0
            # a re-queued request's stream epoch bumped (SSE reset)
            requeued = [
                r for r in rids if srv.tickets[r]._fail_epochs
            ]
            assert len(requeued) == snap["counters"]["router_requeued"]
            assert all(srv.tickets[r].requeues >= 1 for r in requeued)
            # the drained host never schedules again
            more = srv.submit(_req(77, horizon=8.0))
            srv.run_until_idle(max_ticks=300)
            assert srv.tickets[more].host == 0


@pytest.mark.slow
class TestLocalClusterSlow:
    def test_prefix_locality_and_spill(self, tmp_path):
        """Forks of one prefix stick to the owning host; once that
        host backs up past steal_threshold, later forks fall back to
        the least-loaded host and re-resolve there."""
        with _cluster(
            tmp_path, hosts=2, lanes=1, steal_threshold=3,
        ) as srv:
            prefix = {"horizon": 8.0}
            first = srv.submit(_req(
                3, horizon=16.0, prefix=prefix,
                overrides={"global": {"volume": 1.05}},
            ))
            owner = srv.tickets[first].host
            second = srv.submit(_req(
                3, horizon=16.0, prefix=prefix,
                overrides={"global": {"volume": 1.10}},
            ))
            assert srv.tickets[second].host == owner  # locality
            # back the owner up past the threshold: next fork spills
            for s in range(4):
                srv.submit(_req(40 + s, horizon=32.0), host=owner)
            spilled = srv.submit(_req(
                3, horizon=16.0, prefix=prefix,
                overrides={"global": {"volume": 1.20}},
            ))
            assert srv.tickets[spilled].host != owner
            srv.run_until_idle(max_ticks=2000)
            for rid in (first, second, spilled):
                assert srv.status(rid)["status"] == DONE

    def test_failover_bitwise_vs_single_host_oracle(self, tmp_path):
        """LocalHost kill drill, bytes pinned: every displaced request
        re-runs on the survivor to the exact bytes a 1-host no-fault
        cluster produces (same router mint, same headers)."""
        reqs = [dict(seed=s, horizon=24.0) for s in range(5)] + [
            dict(seed=7, horizon=24.0, prefix={"horizon": 8.0},
                 overrides={"global": {"volume": 1.1}}),
            dict(seed=8, horizon=16.0, hold_state=True),
        ]
        with ClusterServer(
            {"toggle_colony": BUCKET}, hosts=1,
            cluster_dir=str(tmp_path / "oracle"), local=True,
            worker={"pipeline": "off"},
        ) as oracle:
            orids = [
                oracle.submit(_req(**r)) for r in reqs
            ]
            oracle.run_until_idle(max_ticks=2000)
            ref = {
                r: open(oracle.result(r), "rb").read() for r in orids
            }
        with _cluster(
            tmp_path, hosts=2,
            faults=FaultPlan([
                {"kind": "host_down", "host": 1, "occurrence": 3},
            ]),
        ) as srv:
            rids = [srv.submit(_req(**r)) for r in reqs]
            assert rids == orids
            srv.run_until_idle(max_ticks=2000)
            assert srv.metrics()["hosts_down"] == [1]
            for rid in rids:
                assert srv.status(rid)["status"] == DONE
                got = open(srv.result(rid), "rb").read()
                assert got == ref[rid], f"{rid} differs"

    def test_one_host_cluster_equals_simserver_records(self, tmp_path):
        """Cluster mode at 1 host serves the same records a plain
        SimServer does (headers differ only in the request id — the
        router and a solo server mint internal prefix ids
        differently, deliberately)."""
        from lens_tpu.emit.log import decode_record, iter_frames

        reqs = [dict(seed=s, horizon=16.0) for s in range(3)] + [
            dict(seed=7, horizon=16.0, prefix={"horizon": 8.0},
                 overrides={"global": {"volume": 1.1}}),
        ]
        with ClusterServer(
            {"toggle_colony": BUCKET}, hosts=1,
            cluster_dir=str(tmp_path / "c"), local=True,
            worker={"pipeline": "off"},
        ) as cluster:
            crids = [cluster.submit(_req(**r)) for r in reqs]
            cluster.run_until_idle(max_ticks=1000)
            cpaths = {r: cluster.result(r) for r in crids}
            solo = SimServer.single_bucket(
                "toggle_colony", **BUCKET, pipeline="off",
                out_dir=str(tmp_path / "solo"), sink="log",
            )
            srids = [solo.submit(_req(**r)) for r in reqs]
            solo.run_until_idle(max_ticks=1000)
            for crid, srid in zip(crids, srids):
                cf = list(iter_frames(cpaths[crid]))
                sf = list(iter_frames(solo.result(srid)))
                assert cf[1:] == sf[1:], f"{crid}: records differ"
                ch = decode_record(cf[0])["__header__"]
                sh = decode_record(sf[0])["__header__"]
                assert str(ch.pop("experiment_id")) == crid
                assert str(sh.pop("experiment_id")) == srid
                assert {k: v.tolist() for k, v in ch.items()} == \
                    {k: v.tolist() for k, v in sh.items()}
            solo.close()

    def test_resubmit_survives_host_death(self, tmp_path):
        """A held DONE parent whose host dies re-homes through the
        shared tier (spill re-adopted, terminal ticket materialized)
        and its resubmit continuation runs on the survivor bitwise
        equal to an undisturbed chain."""
        from lens_tpu.emit.log import iter_frames

        with ClusterServer(
            {"toggle_colony": BUCKET}, hosts=1,
            cluster_dir=str(tmp_path / "oracle"), local=True,
            worker={"pipeline": "off"},
        ) as oracle:
            p = oracle.submit(_req(3, horizon=16.0, hold_state=True))
            oracle.run_until_idle(max_ticks=500)
            c = oracle.resubmit(p, 16.0)
            oracle.run_until_idle(max_ticks=500)
            ref_parent = open(oracle.result(p), "rb").read()
            ref_cont_rid = c
            ref_cont = list(iter_frames(oracle.result(c)))
        with _cluster(tmp_path, hosts=2) as srv:
            p2 = srv.submit(
                _req(3, horizon=16.0, hold_state=True), host=1
            )
            srv.run_until_idle(max_ticks=500)
            assert srv.status(p2)["status"] == DONE
            assert p2 == p
            srv.down_host(1, reason="test")  # operator kill+failover
            assert not srv.hosts[1].alive
            assert srv.tickets[p2].host == 0
            assert srv.status(p2)["status"] == DONE  # materialized
            c2 = srv.resubmit(p2, 16.0)
            # survivor host 0's internal mint matches the 1-host
            # oracle's, so the continuation rid (and its log header)
            # compare exactly
            assert c2 == ref_cont_rid
            srv.run_until_idle(max_ticks=500)
            assert srv.status(c2)["status"] == DONE
            assert open(srv.result(p2), "rb").read() == ref_parent
            assert list(iter_frames(srv.result(c2))) == ref_cont

    def test_cancel_in_limbo_and_queue_view(self, tmp_path):
        with _cluster(tmp_path, hosts=2, lanes=1) as srv:
            rids = [
                srv.submit(_req(s, horizon=64.0), host=0)
                for s in range(4)
            ]
            assert len(srv.queue) >= 1
            assert srv.queue.max_depth == 2 * 64
            # cancel a queued request through the router
            st = srv.cancel(rids[3])
            assert st in ("cancelled", "queued", "running")
            srv.run_until_idle(max_ticks=1000)
            done = sum(
                1 for r in rids
                if srv.status(r)["status"] == DONE
            )
            assert done >= 3

    def test_frontdoor_over_cluster(self, tmp_path):
        """The front door runs unchanged over the cluster backend:
        submit/status/stream/healthz span hosts transparently, and
        /healthz carries host identity + serving state."""
        import base64
        import http.client

        from lens_tpu.frontdoor import FrontDoor

        with _cluster(tmp_path, hosts=2) as srv:
            fd = FrontDoor(srv, port=0).start()
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", fd.port, timeout=60
                )
                body = json.dumps({
                    "composite": "toggle_colony", "seed": 3,
                    "horizon": 16.0,
                })
                conn.request("POST", "/v1/requests", body=body)
                resp = conn.getresponse()
                assert resp.status == 202
                rid = json.loads(resp.read())["rid"]
                deadline = time.monotonic() + 120
                while time.monotonic() < deadline:
                    conn.request("GET", f"/v1/requests/{rid}")
                    resp = conn.getresponse()
                    row = json.loads(resp.read())
                    if row["status"] == DONE:
                        break
                    time.sleep(0.05)
                assert row["status"] == DONE
                assert row.get("host") in (0, 1)
                # healthz: serving state + per-host identity
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                hz = json.loads(resp.read())
                assert resp.status == 200
                assert hz["state"] == "serving"
                assert [
                    h["host"] for h in hz["cluster"]["hosts"]
                ] == [0, 1]
                assert all(
                    h["state"] == "serving"
                    for h in hz["cluster"]["hosts"]
                )
                # the SSE stream concatenates to the log bytes
                from lens_tpu.frontdoor.streams import (
                    decode_record_events,
                )

                conn.request(
                    "GET", f"/v1/requests/{rid}/stream"
                )
                resp = conn.getresponse()
                streamed, end = decode_record_events(resp.read())
                assert end["status"] == DONE
                path = srv.result(rid)
                assert streamed == open(path, "rb").read()
                # /metrics exposition carries host labels end to end
                conn.request("GET", "/metrics")
                resp = conn.getresponse()
                text = resp.read().decode()
                assert 'lens_cluster_host_up{host="0"} 1' in text
                assert 'lens_cluster_host_up{host="1"} 1' in text
            finally:
                fd.close()

    def test_healthz_draining_has_retry_after(self, tmp_path):
        import http.client
        import threading

        from lens_tpu.frontdoor import FrontDoor

        with _cluster(tmp_path, hosts=2) as srv:
            fd = FrontDoor(srv, port=0).start()
            try:
                fd._draining = True
                conn = http.client.HTTPConnection(
                    "127.0.0.1", fd.port, timeout=30
                )
                conn.request("GET", "/healthz")
                resp = conn.getresponse()
                assert resp.status == 503
                assert resp.getheader("Retry-After") is not None
                hz = json.loads(resp.read())
                assert hz["state"] == "draining"
            finally:
                fd._draining = False
                fd.close()


# -- the real drills: subprocess workers, real SIGKILLs ----------------------


_DRILL_REQS = [dict(seed=s, horizon=24.0) for s in range(6)] + [
    dict(seed=7, horizon=24.0, prefix={"horizon": 8.0},
         overrides={"global": {"volume": 1.1}}),
    dict(seed=8, horizon=16.0, hold_state=True),
]


def _oracle_bytes(tmp_path):
    """The single-host no-fault oracle: a 1-host cluster (identical
    router id mint, so files compare byte for byte, headers
    included)."""
    with ClusterServer(
        {"toggle_colony": BUCKET}, hosts=1,
        cluster_dir=str(tmp_path / "oracle"), local=True,
        worker={"pipeline": "off"},
    ) as oracle:
        rids = [oracle.submit(_req(**r)) for r in _DRILL_REQS]
        oracle.run_until_idle(max_ticks=2000)
        return rids, {
            r: open(oracle.result(r), "rb").read() for r in rids
        }


def _kill_one_host_drill(tmp_path, hosts, victim, occurrence):
    """Spawn a real cluster, SIGKILL one worker mid-load via the
    host_down fault, and pin every request's bytes against the
    single-host no-fault oracle."""
    orids, ref = _oracle_bytes(tmp_path)
    with ClusterServer(
        {"toggle_colony": {**BUCKET, "lanes": 1}},
        hosts=hosts,
        cluster_dir=str(tmp_path / f"c{hosts}"),
        faults=FaultPlan([{
            "kind": "host_down", "host": victim,
            "occurrence": occurrence,
        }]),
    ) as srv:
        rids = [srv.submit(_req(**r)) for r in _DRILL_REQS]
        assert rids == orids
        srv.run_until_idle(max_ticks=200000)
        snap = srv.metrics()
        assert snap["hosts_down"] == [victim]
        # the victim was REALLY killed (SIGKILL, not a flag)
        h = srv.hosts[victim]
        assert h.proc.poll() == -signal.SIGKILL
        for rid in rids:
            st = srv.status(rid)
            assert st["status"] == DONE, (rid, st)
            t = srv.tickets[rid]
            # a ticket still attributed to the victim must have
            # finished AND streamed durably before the kill; anything
            # unfinished was displaced to a survivor
            assert t.host != victim or t.streamed_at is not None
            got = open(srv.result(rid), "rb").read()
            assert got == ref[rid], f"{rid} differs after failover"
        return snap


@pytest.mark.slow
class TestKillOneHostDrill:
    """The acceptance headline: kill one REAL worker process at 2 and
    4 simulated hosts; every non-faulted request completes and its
    streamed bytes equal the single-host no-fault oracle."""

    def test_two_hosts(self, tmp_path):
        snap = _kill_one_host_drill(
            tmp_path, hosts=2, victim=1, occurrence=3
        )
        assert snap["counters"]["router_requeued"] >= 1
        assert snap["hosts_alive"] == 1

    def test_four_hosts(self, tmp_path):
        snap = _kill_one_host_drill(
            tmp_path, hosts=4, victim=2, occurrence=3
        )
        assert snap["hosts_alive"] == 3


@pytest.mark.slow
class TestRemoteClusterSlow:
    def test_heartbeat_loss_sigstop(self, tmp_path):
        """A wedged (not dead) worker: SIGSTOP stops it answering
        health polls; after heartbeat_misses the router declares it
        down, SIGKILLs it, and fails its work over."""
        with ClusterServer(
            {"toggle_colony": {**BUCKET, "lanes": 1}},
            hosts=2,
            cluster_dir=str(tmp_path / "c"),
            heartbeat_s=0.5, heartbeat_misses=2,
        ) as srv:
            rids = [srv.submit(_req(s, horizon=48.0))
                    for s in range(4)]
            victim = 1
            os.kill(srv.hosts[victim].proc.pid, signal.SIGSTOP)
            srv.run_until_idle(max_ticks=200000)
            assert not srv.hosts[victim].alive
            for rid in rids:
                assert srv.status(rid)["status"] == DONE
                assert srv.tickets[rid].host != victim

    def test_worker_sigkill_detected_without_faultplan(self, tmp_path):
        """An out-of-band kill (the OOM killer's shape) is caught by
        the process/connection monitors, not just the fault seam."""
        with ClusterServer(
            {"toggle_colony": {**BUCKET, "lanes": 1}},
            hosts=2, cluster_dir=str(tmp_path / "c"),
        ) as srv:
            rids = [srv.submit(_req(s, horizon=32.0))
                    for s in range(4)]
            srv.tick()
            os.kill(srv.hosts[0].proc.pid, signal.SIGKILL)
            srv.run_until_idle(max_ticks=200000)
            assert srv.metrics()["hosts_down"] == [0]
            for rid in rids:
                assert srv.status(rid)["status"] == DONE

    def test_cli_cluster_serve(self, tmp_path, capsys):
        """python -m lens_tpu serve --hosts 2 end to end, including
        the wal dump CLI over the cluster dir afterwards."""
        from lens_tpu.__main__ import main

        reqs = tmp_path / "reqs.json"
        reqs.write_text(json.dumps(
            [{"seed": s, "horizon": 16.0} for s in range(4)]
        ))
        out = tmp_path / "cl"
        rc = main([
            "serve", "--composite", "toggle_colony",
            "--capacity", "16", "--lanes", "1", "--window", "8",
            "--hosts", "2", "--requests", str(reqs),
            "--out-dir", str(out),
        ])
        assert rc == 0
        text = capsys.readouterr().out
        assert "cluster 2 hosts" in text
        assert len(glob.glob(str(out / "out" / "*.lens"))) == 4
        assert (out / "cluster_meta.json").exists()
        assert main(["wal", str(out)]) == 0
        dump = capsys.readouterr().out
        assert "host00" in dump and "host01" in dump
