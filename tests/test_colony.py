"""Colony layer: stacking, alive-mask, division-as-row-activation.

The hard parts list (SURVEY.md §7): division with fixed shapes, capacity
preallocation, mask hygiene, determinism.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.colony import Colony
from lens_tpu.core.engine import Compartment
from lens_tpu.processes.growth import DivideTrigger, Growth
from lens_tpu.processes.toggle_switch import ToggleSwitch

GROW_RATE = 0.01  # fast-growing test cells: doubling time ~69.3 s


def growth_colony(capacity, n_alive=1, threshold=2.0):
    comp = Compartment(
        processes={
            "growth": Growth({"rate": GROW_RATE}),
            "divide_trigger": DivideTrigger({"threshold": threshold}),
        },
        topology={
            "growth": {"global": ("global",)},
            "divide_trigger": {"global": ("global",)},
        },
    )
    colony = Colony(comp, capacity, division_trigger=("global", "divide"))
    return colony, colony.initial_state(n_alive)


def test_initial_state_shapes_and_mask():
    colony, cs = growth_colony(capacity=8, n_alive=3)
    assert cs.agents["global"]["volume"].shape == (8,)
    np.testing.assert_array_equal(
        np.asarray(cs.alive), [True] * 3 + [False] * 5
    )


def test_growth_without_division():
    colony, cs = growth_colony(capacity=4, n_alive=1, threshold=1e9)
    cs2, _ = colony.run(cs, 50.0, 1.0)
    v = float(cs2.agents["global"]["volume"][0])
    np.testing.assert_allclose(v, np.exp(GROW_RATE * 50.0), rtol=1e-4)
    assert int(colony.n_alive(cs2)) == 1


def test_dead_rows_frozen():
    colony, cs = growth_colony(capacity=4, n_alive=2, threshold=1e9)
    cs2, _ = colony.run(cs, 10.0, 1.0)
    # dead rows keep their untouched default volume
    v = np.asarray(cs2.agents["global"]["volume"])
    assert v[2] == 1.0 and v[3] == 1.0
    assert v[0] > 1.0 and v[1] > 1.0


def test_division_doubles_population_and_conserves_volume():
    colony, cs = growth_colony(capacity=16, n_alive=1)
    # volume hits 2.0 at t = ln(2)/rate ~ 69.3s -> first division at step 70
    step = jax.jit(lambda c: colony.step(c, 1.0))
    for _ in range(75):
        cs = step(cs)
    assert int(colony.n_alive(cs)) == 2
    v = np.asarray(cs.agents["global"]["volume"])[np.asarray(cs.alive)]
    # each daughter got half of just-over-2.0, then grew a little
    assert all(0.9 < x < 1.2 for x in v)
    # divide flag cleared on both daughters (divider 'zero' + deriver resets)
    d = np.asarray(cs.agents["global"]["divide"])[np.asarray(cs.alive)]
    assert all(x == 0.0 for x in d)


def test_population_growth_exponential():
    colony, cs = growth_colony(capacity=64, n_alive=1)
    cs2, _ = colony.run(cs, 300.0, 1.0, emit_every=300)
    # ~4.3 doublings in 300s: expect 16-32 cells, well under capacity
    n = int(colony.n_alive(cs2))
    assert 16 <= n <= 32
    # all alive volumes in [1, 2.2)
    v = np.asarray(cs2.agents["global"]["volume"])[np.asarray(cs2.alive)]
    assert v.min() >= 0.9 and v.max() < 2.2


def test_capacity_clamp_no_overflow():
    colony, cs = growth_colony(capacity=4, n_alive=1)
    cs2, _ = colony.run(cs, 400.0, 1.0, emit_every=400)
    assert int(colony.n_alive(cs2)) == 4
    # suppressed parents keep growing past threshold rather than crashing
    v = np.asarray(cs2.agents["global"]["volume"])
    assert np.all(np.isfinite(v))


def test_determinism_same_seed():
    colony, cs = growth_colony(capacity=16, n_alive=1)
    a, _ = colony.run(cs, 100.0, 1.0, emit_every=100)
    b, _ = colony.run(cs, 100.0, 1.0, emit_every=100)
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def test_initial_state_is_strong_typed_no_recompile():
    """Initial states must have the SAME aval signature as evolved states
    (no weak-typed leaves): the round-1 benches were silently recompiling
    the whole window on their first post-warm-up call — config 3's
    "throughput" was ~3.5k/s of compile time against a real ~10M/s."""
    from lens_tpu.models.composites import minimal_wcecoli

    comp = minimal_wcecoli({})
    colony = Colony(comp, 64, division_trigger=("global", "divide"))
    st = colony.initial_state(
        16, key=jax.random.PRNGKey(0),
        overrides={"metabolites": {"glc": 50.0}},
    )
    for path, leaf in jax.tree_util.tree_leaves_with_path(st):
        assert not getattr(leaf, "weak_type", False), path

    step = jax.jit(lambda s: colony.step(s, 1.0))
    out = step(st)
    sig = lambda tree: [
        (l.shape, l.dtype, getattr(l, "weak_type", False))
        for l in jax.tree.leaves(tree)
    ]
    assert sig(out) == sig(st)
    jax.block_until_ready(step(out))
    assert step._cache_size() == 1, "evolved state forced a recompile"


def test_emit_reports_division_backlog_at_capacity():
    """A full colony suppresses divisions; the emit slice must say so
    (saturation telemetry — critical on sharded colonies whose per-shard
    free pools can starve locally)."""
    colony, cs = growth_colony(capacity=2, n_alive=2, threshold=2.0)
    cs2, _ = colony.run(cs, 80.0, 1.0)  # both rows want to divide by t~70
    assert int(colony.n_alive(cs2)) == 2  # no free rows: suppressed
    emit = colony.emit(cs2)
    assert int(emit["free_rows"]) == 0
    assert int(emit["division_backlog"]) == 2
    # and a colony with headroom reports no backlog after dividing
    colony2, cs_b = growth_colony(capacity=8, n_alive=1, threshold=2.0)
    cs_b2, _ = colony2.run(cs_b, 80.0, 1.0)
    assert int(colony2.emit(cs_b2)["division_backlog"]) == 0


def test_emit_trajectory_contains_alive():
    colony, cs = growth_colony(capacity=8, n_alive=1)
    _, traj = colony.run(cs, 100.0, 1.0, emit_every=50)
    assert traj["alive"].shape == (2, 8)
    assert traj["global"]["volume"].shape == (2, 8)
    # divide flag is _emit False -> excluded
    assert "divide" not in traj["global"]


def test_bad_trigger_path_raises():
    comp = Compartment(
        processes={"growth": Growth()},
        topology={"growth": {"global": ("global",)}},
    )
    with pytest.raises(ValueError):
        Colony(comp, 4, division_trigger=("global", "nope"))


def test_per_agent_overrides():
    colony, _ = growth_colony(capacity=4, n_alive=4, threshold=1e9)
    cs = colony.initial_state(
        4, overrides={"global": {"volume": jnp.array([1.0, 2.0, 3.0, 4.0])}}
    )
    np.testing.assert_array_equal(
        np.asarray(cs.agents["global"]["volume"]), [1.0, 2.0, 3.0, 4.0]
    )
    with pytest.raises(KeyError):
        colony.initial_state(4, overrides={"global": {"typo": 1.0}})


def test_config1_toggle_colony_1k():
    """Config 1: 1k-agent toggle-switch colony, no lattice, one jitted run."""
    comp = Compartment(
        processes={"switch": ToggleSwitch()},
        topology={"switch": {"internal": ("cell",)}},
    )
    colony = Colony(comp, capacity=1024)
    cs = colony.initial_state(1024)
    cs2, traj = jax.jit(lambda c: colony.run(c, 10.0, 1.0, emit_every=10))(cs)
    assert traj["cell"]["protein_u"].shape == (1, 1024)
    assert bool(jnp.all(jnp.isfinite(cs2.agents["cell"]["protein_u"])))


def test_division_backlog_counts_suppressed_divisions():
    """VERDICT r2 weak #2: the `division_backlog` emit must count parents
    whose division was suppressed for lack of a free row — the telemetry
    that makes per-shard capacity saturation observable."""
    # 4 rows, all alive, all past the division threshold: zero free rows
    colony, cs = growth_colony(capacity=4, n_alive=4)
    cs = cs._replace(
        agents={
            **cs.agents,
            "global": {
                **cs.agents["global"],
                "volume": jnp.full(4, 3.0),
            },
        }
    )
    cs = colony.step(cs, 1.0)  # trigger set by deriver; division suppressed
    emit = colony.emit(cs)
    assert int(emit["division_backlog"]) == 4
    assert int(emit["free_rows"]) == 0
    assert int(jnp.sum(cs.alive)) == 4  # nobody divided

    # same cells with free rows: every division lands, backlog clears
    colony2, cs2 = growth_colony(capacity=8, n_alive=4)
    cs2 = cs2._replace(
        agents={
            **cs2.agents,
            "global": {
                **cs2.agents["global"],
                "volume": jnp.full(8, 3.0),
            },
        }
    )
    cs2 = colony2.step(cs2, 1.0)
    emit2 = colony2.emit(cs2)
    assert int(jnp.sum(cs2.alive)) == 8
    assert int(emit2["division_backlog"]) == 0
    assert int(emit2["free_rows"]) == 0


def test_division_backlog_per_shard_visibility():
    """On the mesh, backlog is nonzero while OTHER shards still have free
    rows — the sharded-vs-unsharded biology divergence the emit exists to
    surface. Contiguous initial alive rows saturate shard 0's pool."""
    from lens_tpu.models import ecoli_lattice
    from lens_tpu.parallel import ShardedSpatialColony, make_mesh

    spatial = ecoli_lattice(
        {
            "capacity": 64,
            "shape": (16, 16),
            "size": (16.0, 16.0),
            "growth": {"rate": 0.05},
            "transport": {"yield_": 1.0, "k_consume": 0.0},
        }
    )[0]
    mesh = make_mesh(n_agents=4, n_space=2)
    sharded = ShardedSpatialColony(spatial, mesh)
    # stripe=False: rows 0..15 fill shard 0 exactly (64 rows / 4 shards)
    ss = sharded.initial_state(16, jax.random.PRNGKey(5), stripe=False)
    out, traj = sharded.run(ss, 30.0, 1.0, emit_every=5)
    backlog = np.asarray(traj["division_backlog"])
    free = np.asarray(traj["free_rows"])
    # at some emit, divisions were suppressed (shard 0 full) while free
    # rows existed globally (shards 1-3 empty)
    assert ((backlog > 0) & (free > 0)).any(), (backlog, free)

    # the DEFAULT striped layout avoids exactly this artifact: same
    # scenario, founders dealt round-robin, so every shard has pool room
    ss2 = sharded.initial_state(16, jax.random.PRNGKey(5))
    per_shard = np.asarray(ss2.colony.alive).reshape(4, 16).sum(axis=1)
    np.testing.assert_array_equal(per_shard, [4, 4, 4, 4])
    out2, traj2 = sharded.run(ss2, 30.0, 1.0, emit_every=5)
    assert not (
        (np.asarray(traj2["division_backlog"]) > 0)
        & (np.asarray(traj2["free_rows"]) > 0)
    ).any()
    # and more of the population fits before global saturation
    assert int(np.asarray(traj2["alive"])[-1].sum()) >= int(
        np.asarray(traj["alive"])[-1].sum()
    )


class TestDeath:
    """The other half of the lifecycle: the death trigger clears alive
    bits, frozen rows stop evolving, and freed rows RECYCLE into the
    division pool."""

    def _death_colony(self, capacity=8, n_alive=4, rate=-0.02, **death):
        from lens_tpu.models.composites import grow_divide

        comp = grow_divide(
            {"growth": {"rate": rate}, "death": dict(death)}
        )
        return Colony(
            comp,
            capacity=capacity,
            division_trigger=("global", "divide"),
            death_trigger=("global", "die"),
        )

    def test_starvation_kills_and_freezes(self):
        colony = self._death_colony()  # shrinking cells, die below 0.5
        cs = colony.initial_state(4, key=jax.random.PRNGKey(0))
        cs, traj = jax.jit(lambda s: colony.run(s, 60.0, 1.0))(cs)
        alive_t = np.asarray(traj["alive"]).sum(axis=1)
        assert alive_t[0] == 4 and alive_t[-1] == 0  # everyone starved
        assert (np.diff(alive_t) <= 0).all()         # death is monotone here
        # dead rows froze at (just below) the death threshold — volume
        # keeps decaying only while alive
        vols = np.asarray(traj["global"]["volume"])  # [T, N]
        death_step = (np.asarray(traj["alive"])[:, 0]).argmin()
        np.testing.assert_array_equal(
            vols[death_step:, 0], vols[death_step, 0]
        )

    def test_freed_rows_recycle_into_division(self):
        """At FULL capacity, a death frees the row a waiting parent then
        claims: births continue only because deaths recycle capacity."""
        from lens_tpu.models.composites import grow_divide

        comp = grow_divide(
            {"growth": {"rate": 0.05},
             # die of old age: volume > 1.9 (just below the 2.0 division
             # threshold would block division; above it culls POST-division
             # parents' siblings) — use a bloat death at 2.5 so divisions
             # at 2.0 still happen and big laggards die
             "death": {"when": "above", "threshold": 2.5}}
        )
        colony = Colony(
            comp, capacity=4,
            division_trigger=("global", "divide"),
            death_trigger=("global", "die"),
        )
        # full colony with STAGGERED volumes: divisions are suppressed
        # (no free rows) until the bloat death culls the biggest cell,
        # whose row the next-biggest (already past the division
        # threshold) then claims — identical volumes would synchronize
        # death and kill the whole colony in one step instead
        cs = colony.initial_state(
            4,
            overrides={"global": {"volume": jnp.asarray([1.0, 1.2, 1.4, 1.6])}},
            key=jax.random.PRNGKey(0),
        )
        cs, traj = jax.jit(lambda s: colony.run(s, 40.0, 1.0))(cs)
        alive_t = np.asarray(traj["alive"]).sum(axis=1)
        vols = np.asarray(traj["global"]["volume"])
        live_vols = np.where(np.asarray(traj["alive"]), vols, np.nan)
        # deaths happened (population dipped) AND divisions reused the
        # freed rows (fresh volume-1.0 cells appeared after the dip)
        assert alive_t.min() < 4
        t_dip = alive_t.argmin()
        assert np.nanmin(live_vols[t_dip:]) <= 1.1
        # no live cell ever exceeds the death threshold by more than one
        # step's growth
        assert np.nanmax(live_vols) < 2.5 * np.exp(0.05)

    def test_death_beats_division_same_step(self):
        """A row with both triggers set dies (and does not divide)."""
        from lens_tpu.core.process import Deriver

        class AlwaysBoth(Deriver):
            name = "always_both_trigger"
            defaults = {}

            def ports_schema(self):
                return {
                    "global": {
                        "divide": {"_default": 1.0, "_updater": "set",
                                   "_divider": "zero"},
                        "die": {"_default": 1.0, "_updater": "set",
                                "_divider": "zero"},
                    },
                }

            def next_update(self, timestep, states):
                return {"global": {"divide": jnp.float32(1.0),
                                   "die": jnp.float32(1.0)}}

        comp = Compartment(
            processes={"both": AlwaysBoth()},
            topology={"both": {"global": ("global",)}},
        )
        colony = Colony(
            comp, capacity=8,
            division_trigger=("global", "divide"),
            death_trigger=("global", "die"),
        )
        cs = colony.initial_state(4, key=jax.random.PRNGKey(0))
        cs = colony.step(cs, 1.0)
        assert int(np.asarray(cs.alive).sum()) == 0  # all died, none divided

    def test_experiment_starvation_run(self):
        from lens_tpu.experiment import Experiment

        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": -0.02}, "death": {}},
                "n_agents": 6,
                "capacity": 16,
                "total_time": 60.0,
                "emit_every": 10,
            }
        ) as exp:
            state = exp.run()
            assert exp.colony.death_trigger == ("global", "die")
        assert int(np.asarray(exp.n_alive(state))) == 0


class TestLifespanAnalysis:
    def test_episode_reconstruction_with_recycled_rows(self):
        """A recycled row (cell dies, a daughter later claims the slot)
        yields TWO episodes; survivors have open-ended lifespans."""
        from lens_tpu.analysis import lifespan_table

        alive = np.array(
            [
                [1, 1, 0],
                [1, 1, 0],
                [0, 1, 1],   # row 0 died; row 2 born
                [0, 1, 1],
                [1, 1, 1],   # row 0 RECYCLED (new cell)
            ],
            dtype=bool,
        )
        ts = {"alive": alive, "__time__": np.arange(5) * 10.0}
        eps = lifespan_table(ts)
        by_row = {}
        for e in eps:
            by_row.setdefault(e["row"], []).append(e)
        assert len(by_row[0]) == 2                      # two episodes
        first, second = by_row[0]
        assert first["t_born"] == 0.0 and first["t_died"] == 20.0
        assert first["lifespan"] == 20.0
        assert second["t_born"] == 40.0 and second["lifespan"] is None
        assert by_row[1][0]["lifespan"] is None          # never died
        assert by_row[2][0]["t_born"] == 20.0

    def test_division_splits_episodes_without_alive_gap(self):
        """Daughter A replaces the parent IN PLACE (no alive gap, fresh
        cell_id): the run must split at the id change — the parent's
        episode ends by division (no lifespan), the daughter's begins."""
        from lens_tpu.analysis import lifespan_table

        alive = np.ones((5, 1), dtype=bool)
        alive[4, 0] = False  # the daughter dies at the end
        lineage = {"cell_id": np.array([[0], [0], [10], [10], [10]])}
        ts = {
            "alive": alive,
            "lineage": lineage,
            "__time__": np.arange(5) * 10.0,
        }
        eps = lifespan_table(ts)
        assert len(eps) == 2
        parent, daughter = eps
        assert parent["cell_id"] == 0 and parent["divided"]
        assert parent["t_born"] == 0.0 and parent["lifespan"] is None
        assert daughter["cell_id"] == 10 and not daughter["divided"]
        assert daughter["t_born"] == 20.0 and daughter["lifespan"] == 20.0

    def test_report_adds_lifespans_on_death(self, tmp_path):
        import os

        from lens_tpu.analysis import report
        from lens_tpu.emit import LogEmitter
        from lens_tpu.experiment import Experiment

        log = str(tmp_path / "death.lens")
        with Experiment(
            {
                "composite": "grow_divide",
                "config": {"growth": {"rate": -0.02}, "death": {}},
                "n_agents": 6,
                "capacity": 16,
                "total_time": 60.0,
                "emit_every": 5,
                "emitter": {"type": "log", "path": log},
            }
        ) as exp:
            exp.run()
        written = report(log, out_dir=str(tmp_path / "plots"))
        assert "lifespans" in written
        assert os.path.getsize(written["lifespans"]) > 1000
