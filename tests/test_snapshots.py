"""Prefix-cached scenario forking: the snapshot store and its serving
semantics.

Round 11's determinism contract, in this repo's bitwise culture:

- a forked suffix is BITWISE what the corresponding tail of a solo
  full run from t=0 produces — including the stochastic hybrid_cell
  composite, across admission orders, with the pipeline on;
- cache hit, cache miss, and post-eviction fallback all produce the
  same bits (the cache changes WORK, never results);
- refcounts are exact: no double-free, no leak at ``close()``; LRU
  eviction respects the byte budget and never touches pinned entries.
"""

import numpy as np
import pytest

import jax

from lens_tpu.serve import (
    DONE,
    QUEUED,
    QueueFull,
    ScenarioRequest,
    SimServer,
    SnapshotStore,
    snapshot_key,
)
from lens_tpu.serve.snapshots import (
    overrides_fingerprint,
    tree_nbytes,
)


def _toggle_server(**kw):
    kw.setdefault("lanes", 4)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket("toggle_colony", **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


def _tail(ts, n):
    """The last n rows of every leaf of a timeseries tree."""
    return jax.tree.map(lambda x: np.asarray(x)[-n:], ts)


class TestSnapshotKey:
    """The content address: same content -> same key, any change ->
    a different key."""

    def test_key_is_stable_and_order_insensitive(self):
        a = snapshot_key("b", 3, 1, {"g": {"x": 1.0, "y": 2.0}}, 10)
        b = snapshot_key("b", 3, 1, {"g": {"y": 2.0, "x": 1.0}}, 10)
        assert a == b
        assert snapshot_key("b", 3, 1, {}, 10) == snapshot_key(
            "b", 3, 1, None, 10
        )

    def test_key_distinguishes_every_coordinate(self):
        base = snapshot_key("b", 3, 1, {"g": {"x": 1.0}}, 10)
        assert snapshot_key("c", 3, 1, {"g": {"x": 1.0}}, 10) != base
        assert snapshot_key("b", 4, 1, {"g": {"x": 1.0}}, 10) != base
        assert snapshot_key("b", 3, 2, {"g": {"x": 1.0}}, 10) != base
        assert snapshot_key("b", 3, 1, {"g": {"x": 1.5}}, 10) != base
        assert snapshot_key("b", 3, 1, {"g": {"x": 1.0}}, 11) != base

    def test_value_fingerprint_sees_dtype_shape_bytes(self):
        f32 = overrides_fingerprint({"x": np.float32(1.0)})
        f64 = overrides_fingerprint({"x": np.float64(1.0)})
        assert f32 != f64
        flat = overrides_fingerprint({"x": np.zeros(4)})
        grid = overrides_fingerprint({"x": np.zeros((2, 2))})
        assert flat != grid

    def test_per_species_n_agents(self):
        a = snapshot_key("b", 0, {"e": 2, "s": 1}, {}, 4)
        b = snapshot_key("b", 0, {"s": 1, "e": 2}, {}, 4)
        assert a == b
        assert snapshot_key("b", 0, {"e": 1, "s": 1}, {}, 4) != a


class TestSnapshotStore:
    """Refcounting, byte budget, LRU — pure host-side unit tests."""

    def _state(self, nbytes=800, fill=0.0):
        return {"x": np.full(nbytes // 8, fill, np.float64)}

    def test_put_get_and_accounting(self):
        store = SnapshotStore()
        st = self._state()
        assert store.put(("k", 1), st) == 0
        assert ("k", 1) in store and len(store) == 1
        assert store.resident_bytes() == tree_nbytes(st) == 800
        assert store.state(("k", 1)) is st
        with pytest.raises(KeyError):
            store.state(("k", 2))

    def test_lru_eviction_respects_budget_and_order(self):
        store = SnapshotStore(budget_bytes=2000)
        for i in range(3):  # 800 each: third insert must evict ONE
            store.put(("k", i), self._state())
        assert len(store) == 2 and store.resident_bytes() <= 2000
        assert ("k", 0) not in store  # least recently used went first
        store.state(("k", 1))  # touch 1: now 2 is the LRU victim
        store.put(("k", 3), self._state())
        assert ("k", 1) in store and ("k", 2) not in store

    def test_pinned_entries_are_never_evicted(self):
        store = SnapshotStore(budget_bytes=2000)
        store.put(("pin", 0), self._state(), pin=True)
        store.put(("pin", 1), self._state(), pin=True)
        evicted = store.put(("cache", 0), self._state())
        # the unpinned newcomer is the only evictable entry: it is the
        # one not retained; the pinned working set stays whole
        assert evicted == 1
        assert ("pin", 0) in store and ("pin", 1) in store
        assert ("cache", 0) not in store
        store.release(("pin", 0))
        store.put(("cache", 1), self._state())  # now 0 can make room
        assert ("pin", 0) not in store and ("cache", 1) in store

    def test_oversized_unpinned_entry_is_not_retained(self):
        store = SnapshotStore(budget_bytes=100)
        assert store.put(("big", 0), self._state(800)) == 1
        assert len(store) == 0
        # pinned inserts always land: the budget governs the cache,
        # not the client's explicit working set
        store.put(("big", 1), self._state(800), pin=True)
        assert ("big", 1) in store

    def test_refcounts_exact_no_double_free(self):
        store = SnapshotStore()
        store.put(("k",), self._state())
        store.acquire(("k",))
        store.acquire(("k",))
        assert store.refs_total() == 2
        store.release(("k",))
        store.release(("k",))
        assert store.refs_total() == 0
        with pytest.raises(RuntimeError, match="double release"):
            store.release(("k",))
        with pytest.raises(KeyError):
            store.release(("nope",))

    def test_put_existing_key_keeps_incumbent_state(self):
        store = SnapshotStore()
        first = self._state(fill=1.0)
        store.put(("k",), first)
        store.put(("k",), self._state(fill=2.0), pin=True)
        # content-addressed: same key = same bits by contract, so the
        # incumbent stays and simply absorbs the pin
        assert store.state(("k",)) is first
        assert store.refs_total() == 1

    def test_drop_and_clear(self):
        store = SnapshotStore()
        store.put(("a",), self._state())
        store.put(("b",), self._state(), pin=True)
        store.drop(("a",))
        assert ("a",) not in store
        with pytest.raises(RuntimeError, match="pinned"):
            store.drop(("b",))
        store.drop(("missing",))  # no-op
        store.clear()
        assert len(store) == 0 and store.resident_bytes() == 0


class TestForkDeterminism:
    """Forked-suffix bitwise == solo full run from t=0."""

    def _solo(self, srv, seed, horizon, composite):
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=seed, horizon=horizon
        ))
        srv.run_until_idle(max_ticks=400)
        return srv.result(rid)

    def test_fork_suffix_bitwise_equals_solo_tail_stochastic(self):
        """hybrid_cell (tau-leap Gillespie), pipeline on, forks
        co-batched with unrelated traffic in shuffled orders: the
        cached-prefix fork must reproduce the solo run's suffix rows
        exactly — times AND bits."""
        composite = "hybrid_cell"
        srv = SimServer.single_bucket(
            composite, lanes=4, window=8, capacity=16
        )
        ref = self._solo(srv, 3, 32.0, composite)
        srv.close()

        fork = {
            "seed": 3, "horizon": 32.0, "prefix": {"horizon": 24.0}
        }
        noise = [
            {"seed": 7, "horizon": 16.0},
            {"seed": 11, "horizon": 8.0},
        ]
        for order in ([fork] + noise, noise + [fork]):
            srv = SimServer.single_bucket(
                composite, lanes=4, window=8, capacity=16
            )
            target = None
            for sub in order:
                rid = srv.submit(
                    ScenarioRequest(composite=composite, **sub)
                )
                if "prefix" in sub:
                    target = rid
            srv.run_until_idle(max_ticks=400)
            out = srv.result(target)
            np.testing.assert_array_equal(
                out["__times__"], np.asarray(ref["__times__"])[-8:]
            )
            assert _leaves_equal(out, _tail(ref, 8))
            srv.close()

    def test_hit_miss_and_post_eviction_fallback_bitwise_equal(self):
        fork = dict(
            composite="toggle_colony", seed=5, horizon=16.0,
            prefix={"horizon": 8.0},
        )
        srv = _toggle_server()
        a = srv.submit(ScenarioRequest(**fork))  # cold: miss
        srv.run_until_idle(max_ticks=100)
        b = srv.submit(ScenarioRequest(**fork))  # warm: hit
        srv.run_until_idle(max_ticks=100)
        ra, rb = srv.result(a), srv.result(b)
        c = srv.metrics()["counters"]
        assert c["prefix_misses"] == 1 and c["prefix_hits"] == 1
        assert c["prefix_forks"] == 2
        assert _leaves_equal(ra, rb)
        srv.close()

        # budget 0: every prefix snapshot is evicted on arrival, so
        # EVERY fork takes the miss/fallback path — bits must not care
        srv0 = _toggle_server(snapshot_budget_mb=0)
        x = srv0.submit(ScenarioRequest(**fork))
        srv0.run_until_idle(max_ticks=100)
        y = srv0.submit(ScenarioRequest(**fork))
        srv0.run_until_idle(max_ticks=100)
        c = srv0.metrics()["counters"]
        assert c["prefix_misses"] == 2 and c["prefix_hits"] == 0
        assert c["snapshot_evictions"] >= 2
        assert srv0.metrics()["snapshots_resident"] == 0
        assert _leaves_equal(srv0.result(x), ra)
        assert _leaves_equal(srv0.result(y), ra)
        srv0.close()

    def test_coalesced_forks_share_one_prefix_run(self):
        """N concurrent submitters of one prefix: exactly one miss,
        N-1 coalesced waiters, N forks — and identical bits."""
        srv = _toggle_server(lanes=4)
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=9, horizon=16.0,
                prefix={"horizon": 8.0},
            ))
            for _ in range(4)
        ]
        srv.run_until_idle(max_ticks=200)
        c = srv.metrics()["counters"]
        assert c["prefix_misses"] == 1
        assert c["prefix_coalesced"] == 3
        assert c["prefix_forks"] == 4
        assert c["prefix_hits"] == 0
        results = [srv.result(r) for r in rids]
        for other in results[1:]:
            assert _leaves_equal(results[0], other)
        assert srv.metrics()["retraces"] == 0
        srv.close()

    def test_divergent_overrides_fork_at_the_fork_point(self):
        """Two forks of one prefix with different interventions: the
        override lands at the fork (not t=0), both runs share the
        prefix, and each fork's bits are reproducible from a cold
        cache (the miss path re-derives them)."""

        def run(server):
            subs = [
                dict(
                    composite="toggle_colony", seed=2, horizon=16.0,
                    prefix={"horizon": 8.0},
                    # stay under toggle's division trigger (volume 2.0
                    # divides the cell right back on the first step)
                    overrides={"global": {"volume": v}},
                )
                for v in (1.6, 0.5)
            ]
            rids = [server.submit(ScenarioRequest(**s)) for s in subs]
            server.run_until_idle(max_ticks=200)
            return [server.result(r) for r in rids]

        srv = _toggle_server()
        hi, lo = run(srv)
        # the intervention took hold AT the fork: first suffix row
        # reflects one step of dynamics from the overridden value
        v_hi = np.asarray(hi["global"]["volume"])[0, 0]
        v_lo = np.asarray(lo["global"]["volume"])[0, 0]
        assert v_hi > 1.5 and v_lo < 0.75
        assert srv.metrics()["counters"]["prefix_misses"] == 1
        srv.close()

        cold = _toggle_server()  # fresh store: both re-derive via miss
        hi2, lo2 = run(cold)
        assert _leaves_equal(hi, hi2) and _leaves_equal(lo, lo2)
        cold.close()

    def test_fork_parity_with_pipeline_off(self):
        fork = dict(
            composite="toggle_colony", seed=4, horizon=16.0,
            prefix={"horizon": 8.0},
            overrides={"global": {"volume": 1.4}},
        )
        out = {}
        for mode in ("on", "off"):
            srv = _toggle_server(pipeline=mode)
            rid = srv.submit(ScenarioRequest(**fork))
            srv.run_until_idle(max_ticks=100)
            out[mode] = srv.result(rid)
            srv.close()
        assert _leaves_equal(out["on"], out["off"])

    def test_fork_on_lattice_and_multispecies_buckets(self):
        """apply_overrides at the fork point covers all three colony
        forms: the lattice (SpatialState) and per-species
        (MultiSpeciesState) wrappers fork bitwise like the bare one."""
        cases = [
            ("ecoli_lattice", {"capacity": 8, "shape": (8, 8)}, {}),
            (
                "mixed_species_lattice",
                {
                    "capacity": {"ecoli": 4, "scavenger": 4},
                    "shape": (8, 8),
                },
                {"ecoli": {"cell": {"glucose_internal": 1.5}}},
            ),
        ]
        for composite, config, overrides in cases:
            srv = SimServer.single_bucket(
                composite, config=config, lanes=2, window=4
            )
            solo = srv.submit(ScenarioRequest(
                composite=composite, seed=1, horizon=8.0
            ))
            fork = srv.submit(ScenarioRequest(
                composite=composite, seed=1, horizon=8.0,
                prefix={"horizon": 4.0},
            ))
            srv.run_until_idle(max_ticks=100)
            assert srv.status(fork)["status"] == DONE, (
                composite, srv.status(fork)["error"]
            )
            assert _leaves_equal(
                srv.result(fork), _tail(srv.result(solo), 4)
            )
            if overrides:
                div = srv.submit(ScenarioRequest(
                    composite=composite, seed=1, horizon=8.0,
                    prefix={"horizon": 4.0}, overrides=overrides,
                ))
                srv.run_until_idle(max_ticks=100)
                assert srv.status(div)["status"] == DONE, \
                    srv.status(div)["error"]
                assert not _leaves_equal(
                    srv.result(div), srv.result(fork)
                )
            srv.close()

    def test_emit_every_subsample_grid_continues_the_prefix(self):
        """A fork's every-k emit phase counts from t=0 (the prefix's
        rows), exactly like the solo run it must match."""
        srv = _toggle_server(window=8)
        solo = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=6, horizon=24.0,
            emit={"every": 4},
        ))
        fork = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=6, horizon=24.0,
            prefix={"horizon": 8.0}, emit={"every": 4},
        ))
        srv.run_until_idle(max_ticks=100)
        ref, out = srv.result(solo), srv.result(fork)
        np.testing.assert_array_equal(
            out["__times__"], [12.0, 16.0, 20.0, 24.0]
        )
        assert _leaves_equal(out, _tail(ref, 4))
        srv.close()


class TestHeldStateStore:
    """hold_state through the content-addressed store: N-forkable
    parents, content reuse, exact refcounts."""

    def test_pure_held_state_serves_prefix_hits(self):
        """A hold_state run's final state IS a content-addressed
        snapshot: a later request declaring that run as its prefix
        hits the cache — zero extra prefix simulation."""
        srv = _toggle_server()
        parent = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=8, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        cont = srv.resubmit(parent, 8.0)
        fork = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=8, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        srv.run_until_idle(max_ticks=100)
        c = srv.metrics()["counters"]
        assert c["prefix_hits"] == 1 and c["prefix_misses"] == 0
        # the fork and the resubmit continuation are the same suffix
        assert _leaves_equal(srv.result(cont), srv.result(fork))
        srv.close()

    def test_refcounts_exact_and_no_leak_at_close(self):
        srv = _toggle_server(lanes=2)
        parent = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.snapshots.refs_total() == 1  # the parent's pin
        c1 = srv.resubmit(parent, 8.0)
        assert srv.snapshots.refs_total() == 2  # + queued carry pin
        srv.run_until_idle(max_ticks=100)
        # carry released at scatter; the continuation (hold_state
        # inherited from the parent request) now pins its OWN snapshot
        assert srv.status(c1)["status"] == DONE
        assert srv.snapshots.refs_total() == 2
        srv.release_state(parent)
        srv.release_state(c1)
        assert srv.snapshots.refs_total() == 0
        srv.release_state(parent)  # idempotent: hold already dropped
        srv.close()
        assert len(srv.snapshots) == 0

    def test_close_releases_outstanding_holds(self):
        srv = _toggle_server(lanes=2)
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.snapshots.refs_total() == 1
        srv.close()  # must not raise: the pin is released, store cleared
        assert srv.snapshots.refs_total() == 0

    def test_resubmit_rejected_by_queue_full_leaves_parent_extendable(self):
        """Regression pin (round 11): a QueueFull continuation must
        leave the parent's held state intact and re-extendable, with
        no dangling snapshot ref."""
        srv = _toggle_server(lanes=1, queue_depth=1)
        parent = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=3, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        refs_before = srv.snapshots.refs_total()
        blocker = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=4, horizon=8.0,
        ))
        with pytest.raises(QueueFull):
            srv.resubmit(parent, 8.0)
        assert srv.snapshots.refs_total() == refs_before  # no leak
        assert srv.metrics()["counters"]["rejected"] == 1
        srv.run_until_idle(max_ticks=100)  # drain the blocker
        cont = srv.resubmit(parent, 8.0)  # still extendable
        srv.run_until_idle(max_ticks=100)
        assert srv.status(cont)["status"] == DONE
        assert srv.status(cont)["steps_done"] == 16
        assert srv.status(blocker)["status"] == DONE
        srv.close()


class TestPrefixValidationAndFailure:
    def test_prefix_validation(self):
        srv = _toggle_server()
        base = dict(composite="toggle_colony", seed=0, horizon=16.0)
        with pytest.raises(ValueError, match="shorter"):
            srv.submit(ScenarioRequest(**base, prefix={"horizon": 16.0}))
        with pytest.raises(ValueError, match="not a positive multiple"):
            srv.submit(ScenarioRequest(**base, prefix={"horizon": 8.5}))
        with pytest.raises(ValueError, match="needs a 'horizon'"):
            srv.submit(ScenarioRequest(**base, prefix={}))
        with pytest.raises(ValueError, match="unknown prefix keys"):
            srv.submit(ScenarioRequest(
                **base, prefix={"horizon": 8.0, "nope": 1}
            ))
        srv.close()

    def test_failed_prefix_run_fails_every_coalesced_fork(self):
        """Admission-time prefix failure (a value SHAPE error — path
        typos are rejected eagerly at submit since round 12) fails
        every coalesced waiter with the cause."""
        srv = _toggle_server()
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=0, horizon=16.0,
                prefix={
                    "horizon": 8.0,
                    # capacity is 16: a 3-row per-agent override fails
                    # the prefix run's admission build
                    "overrides": {"global": {"volume": np.ones(3)}},
                },
            ))
            for _ in range(2)
        ]
        ok = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        srv.run_until_idle(max_ticks=100)
        for rid in rids:
            st = srv.status(rid)
            assert st["status"] == "failed"
            assert "leading dim" in st["error"]
        assert srv.status(ok)["status"] == DONE  # pool unharmed
        srv.close()

    def test_bad_divergent_overrides_fail_fork_not_snapshot(self):
        srv = _toggle_server()
        bad = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=16.0,
            prefix={"horizon": 8.0},
            overrides={"global": {"volume": np.ones(3)}},
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(bad)["status"] == "failed"
        assert "leading dim" in srv.status(bad)["error"]
        # the prefix snapshot itself was computed and cached: a good
        # fork of the same prefix now hits
        good = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=16.0,
            prefix={"horizon": 8.0},
            overrides={"global": {"volume": 1.2}},
        ))
        srv.run_until_idle(max_ticks=100)
        assert srv.status(good)["status"] == DONE
        c = srv.metrics()["counters"]
        assert c["prefix_hits"] == 1 and c["prefix_misses"] == 1
        assert srv.snapshots.refs_total() == 0
        srv.close()

    def test_close_mid_prefix_fails_waiters_with_cause(self):
        """``close()`` during an in-flight coalesced prefix run: every
        waiting fork fails FAST with a clear cause (not left QUEUED
        forever, reading as pending to a client holding its id), and
        the snapshot store ends at zero refs — close() itself raises
        on any refcount imbalance, so a clean close IS the leak pin."""
        srv = _toggle_server(lanes=1)  # the prefix occupies the lane
        forks = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=5, horizon=64.0,
                prefix={"horizon": 32.0},
            ))
            for _ in range(2)
        ]
        srv.tick()  # internal prefix run admitted; forks still waiting
        srv.close()  # raises on pin imbalance; must not here
        for rid in forks:
            st = srv.status(rid)
            assert st["status"] == "failed"
            assert "closed while the shared prefix" in st["error"]
            with pytest.raises(ValueError, match="never admitted"):
                srv.result(rid)
        assert srv.snapshots.refs_total() == 0

    def test_cancelled_waiting_fork_leaves_the_rest_healthy(self):
        """Cancel a fork while it waits on an in-flight prefix: it
        retires CANCELLED, the prefix still lands, the surviving fork
        forks it, and no snapshot ref leaks."""
        srv = _toggle_server(lanes=1)  # the prefix occupies the lane
        keep = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=5, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        doomed = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=5, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        assert srv.cancel(doomed) == "cancelled"
        srv.run_until_idle(max_ticks=100)
        assert srv.status(keep)["status"] == DONE
        assert srv.status(doomed)["status"] == "cancelled"
        c = srv.metrics()["counters"]
        assert c["prefix_misses"] == 1 and c["prefix_forks"] == 1
        assert srv.snapshots.refs_total() == 0
        srv.close()

    def test_cancel_after_prefix_lands_drops_the_waiters_seed(self):
        """Cancel a fork AFTER the prefix run resolved it (it holds an
        unscattered carry_state seed while queued for a lane): the
        terminal ticket must not keep the device tree alive — that
        memory is invisible to the store's byte accounting."""
        srv = _toggle_server(lanes=1)
        keep = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=5, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        doomed = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=5, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        t = srv.tickets[doomed]
        for _ in range(100):
            if t.carry_state is not None or t.status != QUEUED:
                break
            srv.tick()
        assert t.carry_state is not None and t.status == QUEUED, (
            "test needs the resolved-but-unadmitted window; widen "
            "max ticks or shrink the lane count if this trips"
        )
        assert srv.cancel(doomed) == "cancelled"
        assert t.carry_state is None
        srv.run_until_idle(max_ticks=100)
        assert srv.status(keep)["status"] == DONE
        assert srv.snapshots.refs_total() == 0
        srv.close()

    def test_status_and_meta_surface_snapshot_gauges(self, tmp_path):
        import json
        import os

        out = str(tmp_path / "serve")
        srv = _toggle_server(out_dir=out, sink="log")
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=0, horizon=16.0,
            prefix={"horizon": 8.0},
        ))
        srv.run_until_idle(max_ticks=100)
        gauges = srv.status(rid)["server"]["snapshots"]
        assert gauges["misses"] == 1 and gauges["forks"] == 1
        assert gauges["resident"] == 1
        assert gauges["resident_bytes"] > 0
        srv.close()
        with open(os.path.join(out, "server_meta.json")) as f:
            meta = json.load(f)
        assert meta["counters"]["prefix_misses"] == 1
        assert meta["counters"]["prefix_forks"] == 1
        assert "snapshot_bytes" in meta
