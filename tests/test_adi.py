"""ADI diffusion: tridiagonal solver, scheme physics, lattice integration.

The backward-Euler-split scheme (ops/adi.py — deliberately NOT
Peaceman–Rachford: positivity is load-bearing) replaces ~27
stability-limited FTCS substeps with two tridiagonal solves per window.
These tests pin: the associative-scan Thomas solver against numpy's
dense solve; the scheme's conservation/positivity/symmetry/fixed-point
physics; its agreement with a dense-substep FTCS oracle; first-order
convergence in dt; and the lattice's ``impl="adi"`` wiring end to end.
"""

import jax
import jax.numpy as jnp
import numpy as np
from hypothesis import given, settings
from hypothesis import strategies as st

from lens_tpu.ops.adi import (
    adi_plan,
    diffuse_adi,
    solve_tridiag,
    thomas_factors,
)
from lens_tpu.ops.diffusion import diffuse_xla


def tridiag_dense(r: float, n: int) -> np.ndarray:
    """Dense (I - r L) with clamped-Neumann 1D Laplacian L."""
    if n == 1:
        return np.ones((1, 1))  # L of a length-1 axis is the zero operator
    a = np.zeros((n, n))
    for i in range(n):
        a[i, i] = 1.0 + 2.0 * r
        if i > 0:
            a[i, i - 1] = -r
        if i < n - 1:
            a[i, i + 1] = -r
    a[0, 0] = 1.0 + r
    a[-1, -1] = 1.0 + r
    return a


class TestTridiagSolver:
    def test_matches_dense_solve_both_axes(self):
        rng = np.random.default_rng(0)
        n_h, n_w, m = 24, 17, 2
        rs = np.asarray([0.7, 3.2])
        d = jnp.asarray(rng.normal(size=(m, n_h, n_w)).astype(np.float32))

        # along H (axis 1)
        x = solve_tridiag(thomas_factors(rs, n_h), d, axis=1)
        for k in range(m):
            dense = tridiag_dense(rs[k], n_h)
            ref = np.linalg.solve(dense, np.asarray(d[k], np.float64))
            np.testing.assert_allclose(
                np.asarray(x[k]), ref, rtol=5e-5, atol=5e-5
            )

        # along W (axis 2)
        x = solve_tridiag(thomas_factors(rs, n_w), d, axis=2)
        for k in range(m):
            dense = tridiag_dense(rs[k], n_w)
            ref = np.linalg.solve(
                dense, np.asarray(d[k], np.float64).T
            ).T
            np.testing.assert_allclose(
                np.asarray(x[k]), ref, rtol=5e-5, atol=5e-5
            )

    def test_length_one_axis_is_identity(self):
        """The clamped Laplacian of a length-1 axis is the zero operator,
        so the solve must return its input unchanged (degenerate 1xW
        lattices must not lose mass)."""
        d = jnp.asarray(np.arange(8, dtype=np.float32).reshape(1, 1, 8))
        x = solve_tridiag(thomas_factors(np.asarray([3.0]), 1), d, axis=1)
        np.testing.assert_allclose(np.asarray(x), np.asarray(d), rtol=1e-6)

    def test_large_r_stays_stable(self):
        """Diagonally dominant system: the affine-scan products contract,
        so big alpha (the whole point of ADI) cannot blow up."""
        rng = np.random.default_rng(1)
        d = jnp.asarray(rng.uniform(0, 10, size=(1, 256, 8)).astype(np.float32))
        x = solve_tridiag(thomas_factors(np.asarray([50.0]), 256), d, axis=1)
        assert bool(jnp.isfinite(x).all())
        dense = tridiag_dense(50.0, 256)
        ref = np.linalg.solve(dense, np.asarray(d[0], np.float64))
        np.testing.assert_allclose(np.asarray(x[0]), ref, rtol=1e-4, atol=1e-4)


class TestTridiagProperty:
    """Property-based: the affine-scan Thomas solver equals numpy's dense
    solve for arbitrary (r, n, rhs) within float32 tolerance."""

    @settings(max_examples=25, deadline=None)
    @given(
        r=st.floats(min_value=0.01, max_value=20.0),
        n=st.integers(min_value=1, max_value=40),
        seed=st.integers(min_value=0, max_value=2**16),
    )
    def test_solver_matches_dense(self, r, n, seed):
        rng = np.random.default_rng(seed)
        d = jnp.asarray(rng.normal(size=(1, n, 3)).astype(np.float32))
        x = solve_tridiag(thomas_factors(np.asarray([r]), n), d, axis=1)
        ref = np.linalg.solve(
            tridiag_dense(r, n), np.asarray(d[0], np.float64)
        )
        np.testing.assert_allclose(np.asarray(x[0]), ref, rtol=2e-4, atol=2e-4)


class TestScheme:
    def field(self, h=32, w=32, m=2, seed=0):
        key = jax.random.PRNGKey(seed)
        f = jax.random.uniform(key, (m, h, w), minval=0.0, maxval=10.0)
        # smooth once so the oracle comparison is not dominated by the
        # highest spatial frequency (where any scheme's error peaks)
        return diffuse_xla(f, jnp.full((m,), 0.2), 10)

    def test_uniform_fixed_point(self):
        plan = adi_plan(np.asarray([6.0]), 16, 16)
        f = jnp.full((1, 16, 16), 3.7)
        out = diffuse_adi(f, plan)
        np.testing.assert_allclose(np.asarray(out), 3.7, rtol=1e-5)

    def test_mass_conservation(self):
        plan = adi_plan(np.asarray([6.0, 1.5]), 32, 32)
        f = self.field()
        out = f
        for _ in range(5):
            out = diffuse_adi(out, plan)
        np.testing.assert_allclose(
            np.asarray(jnp.sum(out, axis=(1, 2))),
            np.asarray(jnp.sum(f, axis=(1, 2))),
            rtol=1e-5,
        )
        assert bool(jnp.isfinite(out).all())

    def test_point_source_symmetry(self):
        plan = adi_plan(np.asarray([2.0]), 33, 33)
        f = jnp.zeros((1, 33, 33)).at[0, 16, 16].set(100.0)
        out = diffuse_adi(f, plan)
        a = np.asarray(out[0])
        np.testing.assert_allclose(a[16 - 4, 16], a[16 + 4, 16], rtol=1e-4)
        np.testing.assert_allclose(a[16, 16 - 4], a[16, 16 + 4], rtol=1e-4)
        # x/y symmetric too (the split factors commute, so axis order
        # cannot bias one source at the center of a square domain)
        np.testing.assert_allclose(a[16 - 3, 16], a[16, 16 - 3], rtol=1e-3)
        assert a[16, 16] < 100.0

    def test_positivity_on_secretion_spike(self):
        """THE reason the scheme is backward-Euler split, not classical
        Peaceman-Rachford: a point secretion spike (the framework's
        normal input via apply_exchanges) must never diffuse into
        negative concentrations, at any alpha. PR's explicit half goes
        negative at r > 0.5 (measured -13.97 on this exact input at
        r = 3); the M-matrix solves cannot."""
        plan = adi_plan(np.asarray([6.0]), 33, 33)
        f = jnp.zeros((1, 33, 33)).at[0, 16, 16].set(100.0)
        out = diffuse_adi(f, plan)
        assert float(jnp.min(out)) >= 0.0
        np.testing.assert_allclose(float(jnp.sum(out)), 100.0, rtol=1e-5)

    def test_matches_dense_ftcs_oracle(self):
        """One ADI window at glucose-like alpha=6 vs near-exact dense
        FTCS (alpha split over 600 substeps): the splitting error on a
        smooth field is bounded."""
        alpha = np.asarray([6.0, 1.5])
        f = self.field()
        plan = adi_plan(alpha, 32, 32)
        adi_out = diffuse_adi(f, plan)
        n_dense = 600
        ref = diffuse_xla(f, jnp.asarray(alpha / n_dense, jnp.float32), n_dense)
        err = float(
            jnp.max(jnp.abs(adi_out - ref)) / (jnp.max(jnp.abs(ref)) + 1e-9)
        )
        # first-order splitting error of the backward-Euler form — the
        # positivity trade (see module docstring); still far below
        # biological parameter noise for nutrient fields
        assert err < 0.08, f"ADI vs dense-FTCS relative error {err:.4f}"

    def test_first_order_in_dt(self):
        """Halving the step (two ADI applications at alpha/2) should cut
        the error vs the dense oracle by ~2x (backward-Euler split is
        first-order); accept >1.5x to keep the test robust."""
        alpha = np.asarray([6.0])
        f = self.field(m=1, seed=3)
        n_dense = 1200
        ref = diffuse_xla(f, jnp.asarray(alpha / n_dense, jnp.float32), n_dense)

        one = diffuse_adi(f, adi_plan(alpha, 32, 32))
        half_plan = adi_plan(alpha / 2.0, 32, 32)
        two = diffuse_adi(diffuse_adi(f, half_plan), half_plan)

        e1 = float(jnp.max(jnp.abs(one - ref)))
        e2 = float(jnp.max(jnp.abs(two - ref)))
        assert e2 < e1 / 1.5, (e1, e2)


class TestSpikeDistributed:
    """SPIKE distributed ADI (parallel.adi_spike): the sharded solve must
    equal the unsharded one up to float rounding — the whole point of the
    substructuring decomposition."""

    def _mesh(self, n):
        from jax.sharding import Mesh

        devices = np.array(jax.devices()[:n])
        return Mesh(devices, ("space",))

    def test_sharded_solve_matches_unsharded(self):
        """Random fields AND a secretion spike adjacent to a shard
        boundary, through ONE compiled sharded solver (8 shards,
        h_local=4): equality with the unsharded solve, conservation,
        positivity, and interface mass transfer."""
        from jax.sharding import PartitionSpec as P

        from lens_tpu.parallel.adi_spike import diffuse_adi_sharded, spike_plan
        from lens_tpu.ops.adi import adi_plan, diffuse_adi

        n_shards = 8
        m, h, w = 2, 32, 16
        alpha = np.asarray([6.0, 1.3])
        plan = spike_plan(alpha, h, w, n_shards)
        local_plan = adi_plan(alpha, h, w)
        solver = jax.jit(
            jax.shard_map(
                lambda f: diffuse_adi_sharded(f, plan, "space"),
                mesh=self._mesh(n_shards),
                in_specs=P(None, "space", None),
                out_specs=P(None, "space", None),
            )
        )

        fields = jax.random.uniform(
            jax.random.PRNGKey(0), (m, h, w), minval=0.0, maxval=10.0
        )
        sharded = solver(fields)
        np.testing.assert_allclose(
            np.asarray(sharded),
            np.asarray(diffuse_adi(fields, local_plan)),
            rtol=2e-4, atol=2e-4,
        )
        # conservation + positivity survive the decomposition
        np.testing.assert_allclose(
            np.asarray(jnp.sum(sharded, axis=(1, 2))),
            np.asarray(jnp.sum(fields, axis=(1, 2))),
            rtol=1e-5,
        )

        # a point spike on row 3 — the LAST row of shard 0 (h_local=4):
        # the interface correction must carry mass across the boundary
        spike = jnp.zeros((m, h, w)).at[0, 3, 8].set(100.0)
        out = solver(spike)  # same compiled program, second input
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(diffuse_adi(spike, local_plan)),
            rtol=2e-4, atol=2e-4,
        )
        assert float(jnp.min(out)) >= -1e-6
        assert float(jnp.sum(out[:, 4:, :])) > 1.0  # crossed the boundary

    def test_sharded_colony_with_adi(self):
        """ShardedSpatialColony honors lattice.impl='adi' end to end and
        matches the unsharded ADI colony on a deterministic config."""
        from lens_tpu.models import ecoli_lattice
        from lens_tpu.parallel import ShardedSpatialColony, make_mesh

        def build():
            spatial, _ = ecoli_lattice(
                {
                    "capacity": 32,
                    "shape": (16, 16),
                    "size": (160.0, 160.0),
                    "division": False,
                    "motility": {"sigma": 0.0},
                }
            )
            spatial.lattice.impl = "adi"
            return spatial

        spatial = build()
        ss = spatial.initial_state(16, jax.random.PRNGKey(3))
        ref = spatial.step(ss, 1.0)
        for _ in range(3):
            ref = spatial.step(ref, 1.0)

        sharded = ShardedSpatialColony(build(), make_mesh(n_agents=4, n_space=2))
        s0 = sharded.initial_state(
            16, jax.random.PRNGKey(3), stripe=False,
            locations=get_loc(ss),
        )
        out = s0
        for _ in range(4):
            out = sharded.step(out, 1.0)
        np.testing.assert_allclose(
            np.asarray(out.fields), np.asarray(ref.fields),
            rtol=5e-4, atol=5e-4,
        )


    def test_small_four_shard_single_channel(self):
        """The geometries the merged test no longer covers: 4 shards,
        m=1, eager (un-jitted) shard_map, tiny field — cheap compile."""
        from jax.sharding import PartitionSpec as P

        from lens_tpu.parallel.adi_spike import diffuse_adi_sharded, spike_plan
        from lens_tpu.ops.adi import adi_plan, diffuse_adi

        n_shards, h, w = 4, 16, 8
        alpha = np.asarray([4.0])
        fields = jax.random.uniform(
            jax.random.PRNGKey(5), (1, h, w), minval=0.0, maxval=5.0
        )
        plan = spike_plan(alpha, h, w, n_shards)
        out = jax.shard_map(
            lambda f: diffuse_adi_sharded(f, plan, "space"),
            mesh=self._mesh(n_shards),
            in_specs=P(None, "space", None),
            out_specs=P(None, "space", None),
        )(fields)
        np.testing.assert_allclose(
            np.asarray(out),
            np.asarray(diffuse_adi(fields, adi_plan(alpha, h, w))),
            rtol=2e-4, atol=2e-4,
        )

    def test_sharded_multispecies_with_adi(self):
        """The mixed-species runner shares the same _diffuse_strip
        dispatch: deterministic config, sharded ADI == unsharded ADI."""
        from lens_tpu.models import mixed_species_lattice
        from lens_tpu.parallel import ShardedMultiSpeciesColony, make_mesh
        from lens_tpu.parallel.mesh import mesh_shardings, multispecies_pspecs

        def build():
            multi, _ = mixed_species_lattice(
                {
                    "capacity": {"ecoli": 16, "scavenger": 16},
                    "shape": (16, 16),
                    "size": (16.0, 16.0),
                    "division": False,
                    "ecoli": {"motility": {"sigma": 0.0}},
                    "scavenger": {"motility": {"sigma": 0.0},
                                  "expression": None},
                }
            )
            multi.lattice.impl = "adi"
            return multi

        multi = build()
        ms0 = multi.initial_state(
            {"ecoli": 16, "scavenger": 16}, jax.random.PRNGKey(1)
        )
        ref = multi.step(ms0, 1.0)

        mesh = make_mesh(n_agents=4, n_space=2)
        sharded = ShardedMultiSpeciesColony(build(), mesh)
        ms0_sharded = jax.device_put(
            ms0, mesh_shardings(mesh, multispecies_pspecs(ms0))
        )
        out = sharded.step(ms0_sharded, 1.0)
        np.testing.assert_allclose(
            np.asarray(out.fields), np.asarray(ref.fields),
            rtol=5e-4, atol=5e-4,
        )


def get_loc(ss):
    from lens_tpu.utils.dicts import get_path

    return get_path(ss.colony.agents, ("boundary", "location"))


class TestLatticeIntegration:
    def test_lattice_adi_impl(self):
        from lens_tpu.environment.lattice import Lattice

        ftcs = Lattice(["glc"], shape=(32, 32), size=(320.0, 320.0),
                       diffusion=600.0)
        adi = Lattice(["glc"], shape=(32, 32), size=(320.0, 320.0),
                      diffusion=600.0, impl="adi")
        bump = ftcs.initial_fields().at[0, 10:22, 10:22].add(5.0)
        # smooth the step discontinuity first: splitting error lives in
        # the highest spatial frequencies, and a raw step function is all
        # of them — one FTCS window makes the comparison about the
        # schemes, not the discontinuity
        f = ftcs.step_fields(bump)
        out_f = ftcs.step_fields(f)
        out_a = jax.jit(adi.step_fields)(f)
        # same mass, closely matching fields (schemes differ at O(dt^2))
        np.testing.assert_allclose(
            float(jnp.sum(out_a)), float(jnp.sum(f)), rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(out_a), np.asarray(out_f), rtol=0.05, atol=0.2
        )

    def test_spatial_colony_runs_with_adi(self):
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 32, "shape": (16, 16), "size": (16.0, 16.0)}
        )
        spatial.lattice.impl = "adi"
        ss = spatial.initial_state(8, jax.random.PRNGKey(0))
        out, _ = jax.jit(
            lambda s: spatial.run(s, 8.0, 1.0, emit_every=8)
        )(ss)
        assert int(jnp.sum(out.colony.alive)) >= 8
        assert bool(jnp.isfinite(out.fields).all())
