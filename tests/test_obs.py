"""Tracing + metrics time-series (round 14, docs/observability.md).

Pins for the observability tentpole:

- the :class:`~lens_tpu.obs.trace.Tracer` round-trips span/instant
  events through the framed log, is thread-safe, and converts to
  structurally valid Chrome trace-event JSON;
- a served workload with ``trace_dir`` produces a span log covering
  EVERY request stage — queue wait, admission, window dispatch, device
  compute, streamer flush, retirement — including a prefix fork, a
  hold spill, a FaultPlan-injected device quarantine with its
  requeues, and a WAL recovery replay;
- tracing is purely observational: traced results are bitwise equal to
  untraced results, and a server without ``trace_dir`` writes nothing;
- bounded-time failure messages (``SimulationDiverged``,
  ``WatchdogTimeout`` via ``result``) name the failing request's last
  completed stage and tick;
- ``metrics_interval_s`` samples the registry into a ``metrics.jsonl``
  ring, ``prometheus_metrics()`` exposes the pull format, and
  ``server_meta.json`` carries the per-request timing table.
"""

import json
import os
import threading

import jax
import numpy as np
import pytest

from lens_tpu.obs import (
    MetricsRing,
    NullTracer,
    TRACE_NAME,
    Tracer,
    chrome_trace,
    read_trace,
)
from lens_tpu.serve import (
    DONE,
    FaultPlan,
    ScenarioRequest,
    SimServer,
    SimulationDiverged,
)


def _toggle_server(**kw):
    kw.setdefault("lanes", 2)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket("toggle_colony", **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


class TestTracer:
    def test_span_instant_roundtrip(self, tmp_path):
        path = str(tmp_path / "t.trace")
        tr = Tracer(path)
        t0 = tr.now()
        tr.emit_span("work", t0, t0 + 0.5, track="scheduler",
                     rid="req-0", tick=3)
        tr.instant("mark", track="scheduler", shard=1)
        with tr.span("ctx", track="scheduler", tick=4):
            pass
        tr.close()
        events = read_trace(path)
        assert [e["ev"] for e in events] == ["span", "instant", "span"]
        span = events[0]
        assert span["name"] == "work"
        assert span["dur"] == pytest.approx(0.5)
        assert span["args"] == {"rid": "req-0", "tick": 3}
        assert events[1]["args"]["shard"] == 1
        assert events[2]["name"] == "ctx"

    def test_buffered_until_flush(self, tmp_path):
        # the hot path never flushes per event; flush() makes the
        # events visible without closing
        path = str(tmp_path / "t.trace")
        tr = Tracer(path)
        tr.instant("a")
        tr.flush()
        assert len(read_trace(path)) == 1
        tr.close()

    def test_thread_safety(self, tmp_path):
        path = str(tmp_path / "t.trace")
        tr = Tracer(path)

        def emit(k):
            for i in range(100):
                tr.instant(f"t{k}", i=i)

        threads = [
            threading.Thread(target=emit, args=(k,)) for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tr.close()
        events = read_trace(path)
        assert len(events) == 400  # no torn frames, no lost events
        assert tr.events_emitted == 400

    def test_null_tracer_is_falsy_noop(self):
        tr = NullTracer()
        assert not tr
        tr.emit_span("x", 0.0, 1.0)
        tr.instant("y")
        with tr.span("z"):
            pass
        tr.flush()
        tr.close()

    def test_emits_after_close_are_dropped(self, tmp_path):
        # the stream thread may race close(); late events must neither
        # crash nor corrupt the file
        tr = Tracer(str(tmp_path / "t.trace"))
        tr.close()
        tr.instant("late")
        tr.emit_span("late", 0.0, 1.0)


class TestChromeConversion:
    def _events(self):
        return [
            {"ev": "span", "name": "window.device", "track": "device:0",
             "ts": 0.0, "dur": 0.01, "args": {"tick": 1}},
            {"ev": "span", "name": "queue.wait", "track": "requests",
             "ts": 0.001, "dur": 0.5, "aid": "req-0",
             "args": {"rid": "req-0"}},
            {"ev": "span", "name": "queue.wait", "track": "requests",
             "ts": 0.002, "dur": 0.4, "aid": "req-1",
             "args": {"rid": "req-1"}},
            {"ev": "instant", "name": "retire", "track": "scheduler",
             "ts": 0.6, "args": {"rid": "req-0"}},
        ]

    def test_structure_is_valid_trace_event_json(self):
        out = chrome_trace(self._events())
        assert set(out) == {"traceEvents", "displayTimeUnit"}
        json.dumps(out)  # serializable
        phases = {}
        for e in out["traceEvents"]:
            assert {"name", "ph", "pid", "tid"} <= set(e)
            if e["ph"] != "M":
                assert "ts" in e
            phases[e["ph"]] = phases.get(e["ph"], 0) + 1
        # one X complete event, two async pairs, one instant, metadata
        assert phases["X"] == 1
        assert phases["b"] == 2 and phases["e"] == 2  # balanced pairs
        assert phases["i"] == 1
        assert phases["M"] >= 4  # process + thread names

    def test_tracks_become_named_threads(self):
        out = chrome_trace(self._events())
        names = {
            e["args"]["name"]
            for e in out["traceEvents"]
            if e["ph"] == "M" and e["name"] == "thread_name"
        }
        assert {"device:0", "requests", "scheduler"} <= names

    def test_timestamps_are_microseconds(self):
        out = chrome_trace(self._events())
        x = next(e for e in out["traceEvents"] if e["ph"] == "X")
        assert x["ts"] == pytest.approx(0.0)
        assert x["dur"] == pytest.approx(10_000)  # 0.01 s


class TestServeTracing:
    def test_trace_covers_every_request_stage(self, tmp_path):
        """The acceptance workload: plain requests, a shared-prefix
        fork pair (miss + coalesce + hit), and a hold_state spill under
        recover_dir — every stage named in the span taxonomy appears,
        and the log converts to valid Chrome JSON."""
        d = str(tmp_path / "obs")
        srv = _toggle_server(
            out_dir=d, sink="log", trace_dir=d,
            recover_dir=str(tmp_path / "wal"),
        )
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        # two concurrent submitters of one prefix: miss + coalesce
        fork = dict(
            composite="toggle_colony", seed=2, horizon=24.0,
            prefix={"horizon": 8.0},
        )
        srv.submit(ScenarioRequest(**fork))
        srv.submit(ScenarioRequest(
            **{**fork, "overrides": {"global": {"volume": 1.2}}}
        ))
        srv.run_until_idle(max_ticks=300)
        # a third prefix submit AFTER the snapshot landed: a hit
        srv.submit(ScenarioRequest(
            **{**fork, "overrides": {"global": {"volume": 1.4}}}
        ))
        # a hold_state request: retirement spills under recover_dir
        hold = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=3, horizon=16.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=300)
        assert srv.status(hold)["status"] == DONE
        srv.close()

        events = read_trace(os.path.join(d, TRACE_NAME))
        names = {e["name"] for e in events}
        assert {
            "queue.wait", "admit", "window.dispatch", "window.device",
            "window.stream", "retire", "prefix.miss",
            "prefix.coalesced", "prefix.hit", "hold.spill",
            "snapshot.put", "wal.sync",
        } <= names
        # correlation payload: every queue.wait names its request and
        # is an async span (aid) so overlapping waits render correctly
        waits = [e for e in events if e["name"] == "queue.wait"]
        assert all("rid" in e["args"] and e["aid"] for e in waits)
        out = chrome_trace(events)
        json.dumps(out)
        assert any(e["ph"] == "b" for e in out["traceEvents"])
        assert any(e["ph"] == "X" for e in out["traceEvents"])

    def test_trace_quarantine_and_requeue(self, tmp_path):
        """A FaultPlan device_down drill on a 2-device mesh leaves the
        quarantine, the injected fault, and every displaced request's
        requeue on the timeline — and every request still completes."""
        d = str(tmp_path / "obs")
        srv = _toggle_server(
            lanes=2, mesh=2, trace_dir=d,
            faults=FaultPlan([{"kind": "device_down", "shard": 1}]),
        )
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=16.0,
            ))
            for s in range(4)
        ]
        srv.run_until_idle(max_ticks=300)
        statuses = [srv.status(r)["status"] for r in rids]
        assert statuses == [DONE] * 4
        assert srv.metrics()["counters"]["requeued"] >= 1
        srv.close()
        events = read_trace(os.path.join(d, TRACE_NAME))
        names = {e["name"] for e in events}
        assert {"fault.injected", "device.quarantined",
                "request.requeued"} <= names
        q = next(e for e in events if e["name"] == "device.quarantined")
        assert q["args"]["shard"] == 1
        # the requeued requests' device spans name the surviving shard
        rq = [e for e in events if e["name"] == "request.requeued"]
        assert all(e["args"]["shard"] == 1 for e in rq)

    def test_recovery_replay_span(self, tmp_path):
        """A server recovering a WAL emits a recovery.replay span."""
        wal = str(tmp_path / "wal")
        out = str(tmp_path / "out")
        srv = _toggle_server(out_dir=out, sink="log", recover_dir=wal)
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        # close with the request still queued: the WAL knows it,
        # nothing retired it — recovery must re-queue it
        srv.close()
        d = str(tmp_path / "trace2")
        srv2 = _toggle_server(
            out_dir=out, sink="log", recover_dir=wal, trace_dir=d,
        )
        assert srv2.recovered == 1
        srv2.run_until_idle(max_ticks=200)
        srv2.close()
        events = read_trace(os.path.join(d, TRACE_NAME))
        replay = [e for e in events if e["name"] == "recovery.replay"]
        assert len(replay) == 1 and replay[0]["ev"] == "span"

    def test_traced_bitwise_equals_untraced(self, tmp_path):
        """Tracing + metrics sampling observe, never perturb: the
        streamed results are byte-identical with both armed."""
        req = dict(composite="toggle_colony", seed=9, horizon=24.0)
        plain = _toggle_server()
        r0 = plain.submit(ScenarioRequest(**req))
        plain.run_until_idle(max_ticks=200)
        want = plain.result(r0)
        plain.close()
        traced = _toggle_server(
            trace_dir=str(tmp_path / "t"), metrics_interval_s=0.0,
        )
        r1 = traced.submit(ScenarioRequest(**req))
        traced.run_until_idle(max_ticks=200)
        got = traced.result(r1)
        traced.close()
        assert _leaves_equal(want, got)

    def test_sync_pipeline_traces_the_same_tracks(self, tmp_path):
        """pipeline="off" emits the same window.device/window.stream
        spans from the scheduler thread, so a sync trace renders on
        the same timeline tracks as a pipelined one."""
        d = str(tmp_path / "obs")
        srv = _toggle_server(pipeline="off", trace_dir=d)
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.close()
        events = read_trace(os.path.join(d, TRACE_NAME))
        names = {e["name"] for e in events}
        assert {"window.device", "window.stream", "retire"} <= names
        tracks = {e["track"] for e in events}
        assert "device:0" in tracks and "streamer" in tracks

    def test_no_trace_dir_writes_nothing(self, tmp_path):
        srv = _toggle_server(out_dir=str(tmp_path), sink="log")
        assert not srv.trace
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.close()
        assert not os.path.exists(str(tmp_path / TRACE_NAME))
        assert not os.path.exists(str(tmp_path / "metrics.jsonl"))

    def test_diverged_error_names_stage_and_tick(self):
        """Satellite: a bounded-time failure says where progress
        stopped — the SimulationDiverged message carries the ticket's
        last completed stage and the detection tick."""
        srv = _toggle_server(
            check_finite="window",
            faults=FaultPlan([{
                "kind": "nan", "request": "req-000000",
            }]),
        )
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=32.0,
        ))
        srv.run_until_idle(max_ticks=200)
        with pytest.raises(SimulationDiverged) as err:
            srv.result(rid)
        msg = str(err.value)
        assert "last completed stage" in msg
        assert "window dispatched" in msg
        assert "detected at tick" in msg
        srv.close()


class TestMetricsTimeSeries:
    def test_metrics_jsonl_ring_sampling(self, tmp_path):
        d = str(tmp_path / "obs")
        srv = _toggle_server(trace_dir=d, metrics_interval_s=0.0)
        for s in range(3):
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=16.0,
            ))
        srv.run_until_idle(max_ticks=200)
        srv.close()
        path = os.path.join(d, "metrics.jsonl")
        points = [json.loads(l) for l in open(path) if l.strip()]
        assert len(points) >= 2
        ts = [p["t"] for p in points]
        assert ts == sorted(ts)
        # counters are monotone through the series; the close-time
        # point carries the final values
        retired = [p["counters"]["retired"] for p in points]
        assert retired == sorted(retired)
        assert retired[-1] == 3
        last = points[-1]
        assert "queue_depth" in last["gauges"]
        assert "latency_seconds" in last["histograms"]
        assert "lag" in last["stream"]

    def test_metrics_interval_needs_somewhere_to_write(self):
        with pytest.raises(ValueError, match="metrics_interval_s"):
            _toggle_server(metrics_interval_s=1.0)

    def test_prometheus_pull(self):
        srv = _toggle_server()
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=16.0,
        ))
        srv.run_until_idle(max_ticks=100)
        text = srv.prometheus_metrics()
        srv.close()
        assert "# TYPE lens_serve_submitted_total counter" in text
        assert "lens_serve_submitted_total 1" in text
        assert "# TYPE lens_serve_queue_depth gauge" in text
        assert "# TYPE lens_serve_latency_seconds summary" in text
        assert 'quantile="0.5"' in text
        assert rid is not None

    def test_ring_rotation_bounds_the_file(self, tmp_path):
        ring = MetricsRing(str(tmp_path / "m.jsonl"), max_records=10)
        for i in range(35):
            ring.append({"i": i})
        recs = ring.records()
        ring.close()
        assert len(recs) <= 20  # never more than 2x the bound
        assert recs[-1]["i"] == 34  # newest always survives
        assert recs[0]["i"] >= 15  # oldest rewritten away


class TestRequestTimingTable:
    def test_server_meta_gains_per_request_rows(self, tmp_path):
        out = str(tmp_path / "serve")
        srv = _toggle_server(out_dir=out, sink="log")
        rids = [
            srv.submit(ScenarioRequest(
                composite="toggle_colony", seed=s, horizon=16.0,
            ))
            for s in range(2)
        ]
        srv.run_until_idle(max_ticks=100)
        srv.close()
        meta = json.load(open(os.path.join(out, "server_meta.json")))
        rows = {r["rid"]: r for r in meta["requests"]}
        assert set(rows) == set(rids)
        for r in rows.values():
            assert r["status"] == DONE
            # lifecycle order: queued <= admitted <= first window <=
            # last streamed; retired is bookkeeping and may precede
            # the final stream under the pipeline
            assert r["queued"] <= r["admitted"] <= r["first_window"]
            assert r["first_window"] <= r["last_streamed"]
            assert r["retired"] is not None
            assert r["steps_done"] == 16

    def test_internal_prefix_runs_stay_out_of_the_table(self, tmp_path):
        out = str(tmp_path / "serve")
        srv = _toggle_server(out_dir=out, sink="log")
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=24.0,
            prefix={"horizon": 8.0},
        ))
        srv.run_until_idle(max_ticks=200)
        srv.close()
        meta = json.load(open(os.path.join(out, "server_meta.json")))
        assert [r["rid"] for r in meta["requests"]] == [rid]


class TestTraceCli:
    def test_trace_subcommand_renders_chrome_json(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        d = str(tmp_path / "obs")
        srv = _toggle_server(trace_dir=d)
        srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.close()
        out = str(tmp_path / "trace.json")
        assert main(["trace", d, "--out", out]) == 0
        rendered = json.load(open(out))
        assert rendered["traceEvents"]
        stdout = capsys.readouterr().out
        assert "chrome trace" in stdout

    def test_trace_subcommand_missing_log(self, tmp_path, capsys):
        from lens_tpu.__main__ import main

        assert main(["trace", str(tmp_path)]) == 2
        assert "no span log" in capsys.readouterr().err


class TestSweepTrialSpans:
    def test_sweep_emits_per_trial_spans(self, tmp_path):
        from lens_tpu.sweep import run_sweep

        d = str(tmp_path / "obs")
        spec = {
            "composite": "toggle_colony",
            "space": {"kind": "grid", "params": {
                "global/volume": {"grid": [1.0, 1.2, 1.4]},
            }},
            "horizon": 16.0,
            "objective": {"path": "global/volume",
                          "reduce": "final_mean"},
            "backend": {"kind": "server", "lanes": 2, "window": 8,
                        "trace_dir": d},
        }
        result = run_sweep(spec)
        assert all(r["status"] == "done" for r in result.table)
        events = read_trace(os.path.join(d, TRACE_NAME))
        trials = [e for e in events if e["name"] == "trial"]
        assert {e["args"]["trial"] for e in trials} == {0, 1, 2}
        assert all(e["aid"] == f"trial-{e['args']['trial']}"
                   for e in trials)
        assert all(e["args"]["status"] == "done" for e in trials)
