"""The serve layer: continuous batching over vmapped lanes.

The load-bearing property, in this repo's bitwise culture: a request's
emitted trajectory is IDENTICAL served solo or co-batched with arbitrary
other requests, across admission orders — per-request PRNG keys,
elementwise lane masking, no cross-lane reduction in the serve path.
Plus the queueing semantics around it: bounded-queue backpressure,
deadline expiry with lane reclamation, cancellation, and the
reader-while-writer streaming contract of ``tail_records``.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.emit.log import encode_record, frame, tail_records
from lens_tpu.serve import (
    CANCELLED,
    DONE,
    QueueFull,
    TIMEOUT,
    LanePool,
    ScenarioRequest,
    SimServer,
)


def _toggle_server(**kw):
    kw.setdefault("lanes", 4)
    kw.setdefault("window", 8)
    kw.setdefault("capacity", 16)
    return SimServer.single_bucket("toggle_colony", **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(la, lb)
    )


class TestTailRecords:
    """Incremental reads that tolerate a concurrently-appending writer."""

    def _record(self, i):
        return {"x": np.arange(3) + i, "meta": {"i": np.asarray(i)}}

    def test_tail_reads_and_resumes(self, tmp_path):
        p = str(tmp_path / "log.lens")
        frames = [frame(encode_record(self._record(i))) for i in range(3)]
        with open(p, "wb") as f:
            f.write(frames[0])
        recs, off = tail_records(p, 0)
        assert len(recs) == 1 and off == len(frames[0])
        np.testing.assert_array_equal(recs[0]["x"], np.arange(3))
        # nothing new: same offset back, no records
        recs, off2 = tail_records(p, off)
        assert recs == [] and off2 == off
        with open(p, "ab") as f:
            f.write(frames[1])
            f.write(frames[2])
        recs, off3 = tail_records(p, off)
        assert len(recs) == 2
        assert off3 == sum(len(fr) for fr in frames)
        assert int(recs[1]["meta"]["i"]) == 2

    def test_tail_stops_at_partial_frame_and_resumes(self, tmp_path):
        """A half-written frame (the writer mid-append) is left alone;
        once the writer completes it, the SAME offset yields it."""
        p = str(tmp_path / "log.lens")
        fr = frame(encode_record(self._record(0)))
        for cut in (3, len(fr) - 1):  # torn header / torn payload
            with open(p, "wb") as f:
                f.write(fr)
                f.write(fr[:cut])
            recs, off = tail_records(p, 0)
            assert len(recs) == 1 and off == len(fr)
            with open(p, "ab") as f:
                f.write(fr[cut:])
            recs, off = tail_records(p, off)
            assert len(recs) == 1 and off == 2 * len(fr)

    def test_tail_raises_on_corruption(self, tmp_path):
        p = str(tmp_path / "log.lens")
        fr = bytearray(frame(encode_record(self._record(0))))
        fr[-1] ^= 0xFF  # flip a payload byte: CRC mismatch
        with open(p, "wb") as f:
            f.write(bytes(fr))
        with pytest.raises(ValueError, match="CRC"):
            tail_records(p, 0)
        with open(p, "wb") as f:
            f.write(b"\x00" * 16 + b"junk")
        with pytest.raises(ValueError, match="magic"):
            tail_records(p, 0)

    def test_tail_rejects_negative_offset(self, tmp_path):
        p = str(tmp_path / "log.lens")
        open(p, "wb").close()
        with pytest.raises(ValueError, match="offset"):
            tail_records(p, -1)


class TestCheckpointerCrashSafety:
    """save = write tmp + rename; torn saves can never become latest."""

    def test_save_leaves_no_tmp_and_roundtrips(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path / "ck"))
        ck.save({"a": jnp.arange(3)}, 5)
        assert ck.steps() == [5]
        assert not [
            n for n in os.listdir(ck.directory) if ".tmp" in n
        ]
        np.testing.assert_array_equal(
            np.asarray(ck.restore()["a"]), np.arange(3)
        )

    def test_stale_tmp_dir_is_ignored_and_overwritten(self, tmp_path):
        """A killed run's leftover ``step_<n>.tmp-save`` is invisible to
        steps()/restore() and silently replaced by the next save."""
        from lens_tpu.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path / "ck"))
        ck.save({"a": jnp.arange(3)}, 5)
        stale = os.path.join(ck.directory, "step_9.tmp-save")
        os.makedirs(stale)
        with open(os.path.join(stale, "junk"), "w") as f:
            f.write("torn")
        assert ck.steps() == [5]
        assert ck.latest_step() == 5  # NOT the torn 9
        ck.save({"a": jnp.arange(4)}, 9)
        assert ck.steps() == [5, 9]
        np.testing.assert_array_equal(
            np.asarray(ck.restore()["a"]), np.arange(4)
        )
        assert not [
            n for n in os.listdir(ck.directory) if ".tmp" in n
        ]

    def test_save_force_false_refuses_overwrite(self, tmp_path):
        from lens_tpu.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path / "ck"))
        ck.save({"a": jnp.arange(3)}, 1)
        with pytest.raises(FileExistsError):
            ck.save({"a": jnp.arange(4)}, 1, force=False)


class TestLanePool:
    """The lane mechanics under the server: masks, admission, windows."""

    def _pool(self, lanes=3, window=8, emit_every=1):
        from lens_tpu.experiment import build_model

        sim = build_model("toggle_colony", {}, capacity=8).sim
        return LanePool(
            sim, n_lanes=lanes, window_steps=window, emit_every=emit_every
        )

    def test_heterogeneous_horizons_freeze_lanes(self):
        pool = self._pool(lanes=3, window=8)
        pool.admit(0, seed=1, horizon_steps=3)
        pool.admit(2, seed=2, horizon_steps=20)
        before, traj = pool.run_window()
        np.testing.assert_array_equal(before, [3, 0, 20])
        after = np.asarray(jax.device_get(pool.remaining))
        np.testing.assert_array_equal(after, [0, 0, 12])
        # lane 0 ran 3 steps then froze: its step counter pins that
        steps = np.asarray(traj["global"]["volume"])  # [8, 3]
        assert steps.shape[0] == 8
        assert pool.valid_emits(3) == 3
        assert pool.valid_emits(0) == 0
        assert pool.valid_emits(20) == 8

    def test_frozen_lane_state_is_bitwise_stable(self):
        pool = self._pool(lanes=2, window=4)
        pool.admit(0, seed=7, horizon_steps=4)
        pool.run_window()  # lane 0 finishes exactly at the boundary
        frozen = jax.device_get(jax.tree.map(lambda x: x[0], pool.states))
        pool.admit(1, seed=9, horizon_steps=8)
        pool.run_window()
        pool.run_window()
        still = jax.device_get(jax.tree.map(lambda x: x[0], pool.states))
        assert _leaves_equal(frozen, still)

    def test_single_trace_across_admissions_and_windows(self):
        pool = self._pool(lanes=2, window=4)
        for seed, lane in [(1, 0), (2, 1), (3, 0)]:
            pool.admit(lane, seed=seed, horizon_steps=4)
            pool.run_window()
        assert pool.retraces() == 0

    def test_admit_validates(self):
        pool = self._pool(lanes=2)
        with pytest.raises(IndexError):
            pool.admit(5, seed=0, horizon_steps=4)
        with pytest.raises(ValueError):
            pool.admit(0, seed=0, horizon_steps=0)
        with pytest.raises(ValueError):
            LanePool(pool.sim, 2, window_steps=8, emit_every=3)


class TestCoBatchingDeterminism:
    """THE serving contract: solo == co-batched, bitwise, any order."""

    def _serve(self, submissions, target_seed, composite="hybrid_cell",
               **kw):
        kw.setdefault("lanes", 4)
        kw.setdefault("window", 8)
        kw.setdefault("capacity", 16)
        srv = SimServer.single_bucket(composite, **kw)
        target = None
        for sub in submissions:
            rid = srv.submit(
                ScenarioRequest(composite=composite, **sub)
            )
            if sub.get("seed") == target_seed:
                target = rid
        srv.run_until_idle(max_ticks=200)
        out = srv.result(target)
        assert srv.status(target)["status"] == DONE
        srv.close()
        return out

    def test_solo_vs_cobatched_bitwise_stochastic(self):
        """hybrid_cell (tau-leap Gillespie per agent): the stochastic
        composite is where cross-lane PRNG leakage would show."""
        target = {"seed": 3, "horizon": 24.0}
        solo = self._serve([target], 3)
        cob = self._serve(
            [
                {"seed": 7, "horizon": 8.0},
                target,
                {"seed": 11, "horizon": 40.0},
                {"seed": 5, "horizon": 16.0},
                {"seed": 9, "horizon": 24.0},
                {"seed": 13, "horizon": 8.0},
            ],
            3,
        )
        assert _leaves_equal(solo, cob)

    def test_parity_across_admission_orders(self):
        """Same co-batch, shuffled submission order -> the target lands
        in different lanes at different ticks; bits must not care."""
        subs = [
            {"seed": s, "horizon": float(h)}
            for s, h in [(3, 24), (1, 8), (2, 32), (4, 16)]
        ]
        ref = self._serve(subs, 3)
        for order in ([1, 2, 3, 0], [3, 2, 1, 0]):
            out = self._serve([subs[i] for i in order], 3)
            assert _leaves_equal(ref, out)

    def test_parity_with_per_request_overrides(self):
        """Per-request param overrides ride the lane as data; each
        request keeps its own physics, and the target's bits hold."""
        composite = "toggle_colony"
        target = {
            "seed": 3,
            "horizon": 16.0,
            "overrides": {"global": {"volume": 1.3}},
        }
        solo = self._serve([target], 3, composite=composite)
        cob = self._serve(
            [
                {"seed": 1, "horizon": 16.0,
                 "overrides": {"global": {"volume": 0.7}}},
                target,
                {"seed": 2, "horizon": 8.0,
                 "overrides": {"global": {"volume": 2.1}}},
            ],
            3,
            composite=composite,
        )
        assert _leaves_equal(solo, cob)
        # and the override actually took: volume trajectory starts high
        assert np.asarray(solo["global"]["volume"])[:, 0].max() >= 1.3


class TestHoldStateResubmit:
    """Extension contract: a hold_state request resubmitted K times is
    bitwise ONE request with the summed horizon — the mechanism sweep
    successive-halving rungs ride (survivors extend, never rerun)."""

    def _stitch(self, parts):
        return jax.tree.map(
            lambda *xs: np.concatenate([np.asarray(x) for x in xs]),
            *parts,
        )

    def test_resubmit_chain_is_bitwise_one_long_request(self):
        srv = SimServer.single_bucket(
            "hybrid_cell", lanes=4, window=8, capacity=16
        )
        # one-shot reference and the chain's first leg share the server
        # (and a seed): same bits per the co-batching contract
        one_shot = srv.submit(ScenarioRequest(
            composite="hybrid_cell", seed=3, horizon=24.0
        ))
        rid = srv.submit(ScenarioRequest(
            composite="hybrid_cell", seed=3, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=200)
        parts = [srv.result(rid)]
        for _ in range(2):
            rid = srv.resubmit(rid, extra_horizon=8.0)
            srv.run_until_idle(max_ticks=200)
            parts.append(srv.result(rid))
        assert srv.status(rid)["parent"] is not None
        assert srv.metrics()["counters"]["resubmitted"] == 2
        chained = self._stitch(parts)
        ref = srv.result(one_shot)
        np.testing.assert_array_equal(
            chained["__times__"], ref["__times__"]
        )
        assert _leaves_equal(chained, ref)
        srv.close()

    def test_resubmit_validates_and_is_n_forkable(self):
        """Round 11 retired the exactly-once restriction: the held
        state lives refcounted in the snapshot store, so one parent can
        be extended/forked any number of times until release_state."""
        srv = _toggle_server(lanes=2)
        plain = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0
        ))
        held = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=2, horizon=8.0,
            hold_state=True,
        ))
        with pytest.raises(ValueError, match="only DONE"):
            srv.resubmit(held, 8.0)
        srv.run_until_idle(max_ticks=100)
        with pytest.raises(ValueError, match="no final state"):
            srv.resubmit(plain, 8.0)  # not submitted with hold_state
        with pytest.raises(ValueError, match="not a positive multiple"):
            srv.resubmit(held, 0.25)  # off the step grid
        # N continuations from ONE parent, all bitwise-identical twins
        conts = [srv.resubmit(held, 8.0) for _ in range(3)]
        srv.run_until_idle(max_ticks=100)
        for cont in conts:
            assert srv.status(cont)["status"] == DONE
            assert srv.status(cont)["steps_done"] == 16
        results = [srv.result(c) for c in conts]
        for other in results[1:]:
            assert _leaves_equal(results[0], other)
        # dropping the hold ends the parent's extendability
        srv.release_state(held)
        with pytest.raises(ValueError, match="no final state"):
            srv.resubmit(held, 8.0)
        srv.close()

    def test_release_state_drops_held_state(self):
        srv = _toggle_server(lanes=2)
        rid = srv.submit(ScenarioRequest(
            composite="toggle_colony", seed=1, horizon=8.0,
            hold_state=True,
        ))
        srv.run_until_idle(max_ticks=100)
        srv.release_state(rid)  # the halving-loser path
        with pytest.raises(ValueError, match="no final state"):
            srv.resubmit(rid, 8.0)
        srv.close()


class TestMultiSpeciesBucket:
    def test_default_n_agents_fans_out_per_species(self):
        """A multi-species bucket must serve requests that omit
        n_agents: the int default fans out to one agent per species
        (regression: a bare int crashed MultiSpeciesColony's
        per-species initial_state and FAILED every such request)."""
        srv = SimServer.single_bucket(
            "mixed_species_lattice",
            config={
                "capacity": {"ecoli": 8, "scavenger": 8},
                "shape": (8, 8),
            },
            lanes=2,
            window=4,
        )
        rid = srv.submit(
            ScenarioRequest(
                composite="mixed_species_lattice", seed=1, horizon=8.0
            )
        )
        srv.run_until_idle(max_ticks=50)
        st = srv.status(rid)
        assert st["status"] == DONE, st
        ts = srv.result(rid)
        # one founder per species, alive from the first emit
        assert int(np.asarray(ts["ecoli"]["alive"])[0].sum()) == 1
        assert int(np.asarray(ts["scavenger"]["alive"])[0].sum()) == 1
        srv.close()


class TestBackpressureAndLifecycle:
    def test_full_queue_rejects_with_retry_after(self):
        srv = _toggle_server(lanes=1, queue_depth=2)
        for s in range(2):
            srv.submit(
                ScenarioRequest(composite="toggle_colony", seed=s,
                                horizon=8.0)
            )
        with pytest.raises(QueueFull) as exc:
            srv.submit(
                ScenarioRequest(composite="toggle_colony", seed=9,
                                horizon=8.0)
            )
        assert exc.value.retry_after > 0
        assert srv.metrics()["counters"]["rejected"] == 1
        # the backlog still drains normally after the reject
        srv.run_until_idle(max_ticks=100)
        assert srv.metrics()["counters"]["retired"] == 2
        srv.close()

    def test_submit_validates(self):
        srv = _toggle_server()
        with pytest.raises(ValueError, match="no bucket"):
            srv.submit(ScenarioRequest(composite="nope"))
        with pytest.raises(ValueError, match="multiple"):
            srv.submit(
                ScenarioRequest(composite="toggle_colony", horizon=8.5)
            )
        srv.close()

    def test_unknown_override_path_rejected_at_submit(self):
        """Round 12: unknown override paths fail EAGERLY at submit with
        a descriptive error (the round-8 behavior — a FAILED ticket
        from deep inside the admission build — made the typo invisible
        until the request was already queued)."""
        srv = _toggle_server()
        with pytest.raises(ValueError, match="not_a_variable"):
            srv.submit(
                ScenarioRequest(
                    composite="toggle_colony",
                    horizon=8.0,
                    overrides={"global": {"not_a_variable": 1.0}},
                )
            )
        # same eager check guards the prefix block's shared overrides
        with pytest.raises(ValueError, match="prefix override"):
            srv.submit(
                ScenarioRequest(
                    composite="toggle_colony",
                    horizon=16.0,
                    prefix={
                        "horizon": 8.0,
                        "overrides": {"global": {"nope": 1.0}},
                    },
                )
            )
        srv.close()

    def test_bad_override_shape_fails_request_not_server(self):
        """Value SHAPES still validate at admission (they need the
        built state): a wrong per-agent leading dim fails only the one
        request, and the server keeps serving."""
        import numpy as np

        srv = _toggle_server()
        bad = srv.submit(
            ScenarioRequest(
                composite="toggle_colony",
                horizon=8.0,
                # capacity is 16; a 3-row per-agent override cannot fit
                overrides={"global": {"volume": np.ones(3)}},
            )
        )
        ok = srv.submit(
            ScenarioRequest(composite="toggle_colony", horizon=8.0)
        )
        srv.run_until_idle(max_ticks=50)
        assert srv.status(bad)["status"] == "failed"
        assert "leading dim" in srv.status(bad)["error"]
        assert srv.status(ok)["status"] == DONE
        srv.close()

    def test_queued_deadline_expires_without_admission(self):
        srv = _toggle_server(lanes=1)
        long = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=1,
                            horizon=64.0)
        )
        doomed = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=2,
                            horizon=8.0, deadline=0.0)
        )
        srv.run_until_idle(max_ticks=100)
        assert srv.status(long)["status"] == DONE
        assert srv.status(doomed)["status"] == TIMEOUT
        assert srv.metrics()["counters"]["timeouts"] == 1
        with pytest.raises(ValueError, match="never admitted"):
            srv.result(doomed)
        srv.close()

    def test_running_deadline_reclaims_lane_keeps_partial(self):
        srv = _toggle_server(lanes=1, window=4)
        rid = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=1,
                            horizon=400.0, deadline=0.3)
        )
        srv.tick()  # admit + first window
        assert srv.status(rid)["status"] == "running"
        time.sleep(0.35)
        srv.tick()  # expiry sweep reclaims the lane
        assert srv.status(rid)["status"] == TIMEOUT
        assert srv.metrics()["lanes_busy"] == 0
        partial = srv.result(rid)
        assert 0 < len(partial["__times__"]) < 400
        # the freed lane serves the next request normally
        nxt = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=2,
                            horizon=8.0)
        )
        srv.run_until_idle(max_ticks=50)
        assert srv.status(nxt)["status"] == DONE
        srv.close()

    def test_cancel_queued_and_running(self):
        srv = _toggle_server(lanes=1, window=4)
        running = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=1,
                            horizon=64.0)
        )
        queued = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=2,
                            horizon=8.0)
        )
        assert srv.cancel(queued) == CANCELLED
        srv.tick()
        assert srv.status(running)["status"] == "running"
        srv.cancel(running)
        srv.tick()
        assert srv.status(running)["status"] == CANCELLED
        snap = srv.metrics()
        assert snap["lanes_busy"] == 0
        assert snap["counters"]["cancelled"] == 2
        srv.close()


class TestEmitSpecAndMetrics:
    def test_emit_paths_filter(self):
        srv = _toggle_server()
        rid = srv.submit(
            ScenarioRequest(
                composite="toggle_colony", horizon=8.0,
                emit={"paths": ["alive", "global"]},
            )
        )
        srv.run_until_idle(max_ticks=50)
        ts = srv.result(rid)
        assert set(ts) == {"alive", "global", "__times__"}
        srv.close()

    def test_emit_every_subsamples_on_request_grid(self):
        srv = _toggle_server(window=8)
        rid = srv.submit(
            ScenarioRequest(
                composite="toggle_colony", horizon=24.0,
                emit={"every": 4},
            )
        )
        srv.run_until_idle(max_ticks=50)
        ts = srv.result(rid)
        np.testing.assert_array_equal(
            ts["__times__"], [4.0, 8.0, 12.0, 16.0, 20.0, 24.0]
        )
        assert ts["alive"].shape[0] == 6
        srv.close()

    def test_metrics_accounting_consistent(self):
        srv = _toggle_server(lanes=2)
        n = 5
        for s in range(n):
            srv.submit(
                ScenarioRequest(composite="toggle_colony", seed=s,
                                horizon=16.0)
            )
        srv.run_until_idle(max_ticks=100)
        snap = srv.metrics()
        c = snap["counters"]
        assert c["submitted"] == c["admitted"] == c["retired"] == n
        assert c["lane_windows_busy"] <= c["lane_windows_total"]
        assert snap["occupancy"] > 0
        assert snap["retraces"] == 0
        assert snap["latency_seconds"]["p50"] is not None
        # status() surfaces the same live gauges per request
        rid = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=77,
                            horizon=16.0)
        )
        gauges = srv.status(rid)["server"]
        assert gauges["queue_depth"] == 1  # not yet ticked into a lane
        assert gauges["lanes_total"] == 2
        srv.run_until_idle(max_ticks=100)
        srv.close()

    def test_server_meta_sidecar(self, tmp_path):
        out = str(tmp_path / "serve")
        srv = _toggle_server(out_dir=out, sink="log")
        srv.submit(
            ScenarioRequest(composite="toggle_colony", horizon=8.0)
        )
        srv.run_until_idle(max_ticks=50)
        srv.close()
        import json

        with open(os.path.join(out, "server_meta.json")) as f:
            meta = json.load(f)
        assert meta["counters"]["retired"] == 1
        assert "toggle_colony" in meta["config"]


class TestStreamingResults:
    def test_reader_tails_while_server_writes(self, tmp_path):
        """The log sink + tail_records = streaming: records become
        visible window by window, and the stream's concatenation equals
        the final read."""
        out = str(tmp_path / "serve")
        srv = _toggle_server(lanes=1, window=4, out_dir=out, sink="log")
        rid = srv.submit(
            ScenarioRequest(composite="toggle_colony", seed=1,
                            horizon=16.0)
        )
        srv.tick()  # admit + window 1: the log now exists
        path = srv.status(rid)["result_path"]
        offset, batches = 0, []
        recs, offset = tail_records(path, offset)
        batches.append(len(recs))
        while srv.tick() or len(srv.queue):
            recs, offset = tail_records(path, offset)
            batches.append(len(recs))
        srv.close()
        recs, offset = tail_records(path, offset)
        batches.append(len(recs))
        # incremental: more than one nonempty batch, not one big read
        assert sum(1 for b in batches if b) >= 2
        # header + 4 windows of 4 emits each
        assert sum(batches) == 5
        from lens_tpu.emit.log import read_experiment

        header, records = read_experiment(path)
        assert header["config"]["seed"] == 1
        assert len(records) == 16  # segments expand to per-step records
        np.testing.assert_array_equal(
            np.sort(np.asarray([float(r["__time__"]) for r in records])),
            np.arange(1.0, 17.0),
        )


class TestReusedOutDir:
    def test_result_logs_do_not_inherit_stale_records(self, tmp_path):
        """Request ids restart at req-000000 per server, so a reused
        out_dir collides paths; each request must own a FRESH log
        (regression: LogEmitter's append mode silently interleaved a
        previous server's records into the new request's stream)."""
        out = str(tmp_path / "serve")

        def run_once(horizon):
            srv = _toggle_server(lanes=1, window=4, out_dir=out,
                                 sink="log")
            rid = srv.submit(
                ScenarioRequest(composite="toggle_colony", seed=1,
                                horizon=horizon)
            )
            srv.run_until_idle(max_ticks=50)
            path = srv.status(rid)["result_path"]
            srv.close()
            return path

        first = run_once(16.0)
        second = run_once(8.0)
        assert first == second  # same id, same path — the collision
        from lens_tpu.emit.log import read_experiment

        _, records = read_experiment(second)
        assert len(records) == 8  # ONLY the second request's steps


@pytest.mark.slow
class TestServeSoak:
    """Sustained load: hundreds of heterogeneous requests through a
    small pool, with spot-checked bitwise parity against solo serves."""

    def test_soak_many_requests(self):
        rng = np.random.default_rng(0)
        n = 300
        srv = _toggle_server(lanes=8, window=8, queue_depth=32)
        horizons = rng.choice([8.0, 16.0, 24.0, 40.0], size=n)
        pending = [
            ScenarioRequest(
                composite="toggle_colony", seed=int(i),
                horizon=float(horizons[i]),
            )
            for i in range(n)
        ]
        ids = {}
        i = 0
        while i < len(pending) or len(srv.queue) or srv.metrics()["lanes_busy"]:
            while i < len(pending):
                try:
                    ids[i] = srv.submit(pending[i])
                except QueueFull:
                    break  # back off: tick to drain, then resubmit
                i += 1
            srv.tick()
        srv.run_until_idle(max_ticks=1000)
        snap = srv.metrics()
        c = snap["counters"]
        assert len(ids) == n
        assert c["retired"] == c["admitted"] == n
        assert c["rejected"] >= 1  # the bounded queue really pushed back
        assert snap["retraces"] == 0
        for probe in (0, 137, 299):
            st = srv.status(ids[probe])
            assert st["status"] == DONE
            assert st["steps_done"] == int(horizons[probe])
        # spot-check parity: re-serve three requests solo, compare bits
        for probe in (5, 111, 250):
            got = srv.result(ids[probe])
            solo_srv = _toggle_server(lanes=8, window=8)
            rid = solo_srv.submit(pending[probe])
            solo_srv.run_until_idle(max_ticks=200)
            solo = solo_srv.result(rid)
            solo_srv.close()
            assert _leaves_equal(got, solo)
        srv.close()
