"""Multi-host control plane, exercised the single-process way.

True multi-host needs real hosts; what can be pinned down here
(SURVEY.md §4's "multi-node without a real cluster" tier) is everything
that does not require a second process: single-host no-op bring-up,
coordinator IO guards, global mesh construction over the 8 virtual
devices, and state distribution producing correctly sharded arrays that
feed the sharded runner unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lens_tpu.parallel import (
    ShardedSpatialColony,
    cluster_identity,
    coordinator_only,
    distribute,
    global_mesh,
    initialize,
    is_coordinator,
)
from lens_tpu.parallel import distributed as dist_mod
from lens_tpu.parallel.distributed import place_like
from lens_tpu.parallel.mesh import AGENTS_AXIS, SPACE_AXIS, spatial_pspecs


class TestBringup:
    def test_single_host_initialize_is_noop(self, monkeypatch):
        # Opt-in discipline: even pod-like env vars (the tunneled bench
        # chip exports TPU_WORKER_HOSTNAMES) must not trigger a handshake
        # without an explicit coordinator address or LENS_TPU_DISTRIBUTED.
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("LENS_TPU_DISTRIBUTED", raising=False)
        monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "host0,host1")
        assert initialize() is False
        assert jax.process_count() == 1

    def test_initialize_idempotent_when_attached(self, monkeypatch):
        """Repeat calls after a successful handshake never
        re-handshake (experiment retries call initialize() freely):
        with the attached flag set, jax.distributed.initialize must
        not be reached at all."""
        monkeypatch.setattr(dist_mod, "_initialized", True)

        def boom(**kw):  # pragma: no cover - the assertion IS no call
            raise AssertionError("re-handshake attempted")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        # single process: attached-but-alone reports False (not
        # distributed), without touching the runtime again
        assert initialize() is False
        assert initialize("somewhere:1234") is False

    def test_initialize_repeat_noop_unattached(self, monkeypatch):
        """The no-op single-host path is itself idempotent: any number
        of calls without opt-in neither handshake nor flip state."""
        monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
        monkeypatch.delenv("LENS_TPU_DISTRIBUTED", raising=False)
        for _ in range(3):
            assert initialize() is False
        assert dist_mod._initialized is False

    def test_coordinator_identity_single_host(self):
        assert is_coordinator()

    def test_coordinator_only_runs_on_process0(self):
        calls = []

        @coordinator_only
        def emit(x):
            calls.append(x)
            return x

        assert emit(7) == 7
        assert calls == [7]


class TestClusterIdentity:
    def test_explicit_pair_wins(self):
        assert cluster_identity(2, 4) == (2, 4)

    def test_defaults_to_runtime_single_process(self):
        assert cluster_identity() == (0, 1)

    def test_half_specified_refused(self):
        with pytest.raises(ValueError, match="both"):
            cluster_identity(host_id=1)
        with pytest.raises(ValueError, match="both"):
            cluster_identity(n_hosts=4)

    def test_out_of_range_refused(self):
        with pytest.raises(ValueError, match="out of range"):
            cluster_identity(4, 4)


class TestPlaceLike:
    def test_single_process_is_device_put(self):
        """place_like on one host is a plain device_put: values
        round-trip exactly and land with the requested sharding."""
        mesh = global_mesh(n_agents=4, n_space=2)
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec(AGENTS_AXIS))
        leaf = np.arange(16, dtype=np.float32).reshape(16)
        placed = place_like(leaf, sharding)
        assert placed.sharding == sharding
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(placed)), leaf
        )

    def test_replicated_scalar(self):
        mesh = global_mesh(n_agents=4, n_space=2)
        from jax.sharding import NamedSharding, PartitionSpec

        sharding = NamedSharding(mesh, PartitionSpec())
        placed = place_like(np.float32(3.5), sharding)
        assert float(placed) == 3.5


class TestGlobalMesh:
    def test_shape_and_axis_names(self):
        mesh = global_mesh(n_agents=4, n_space=2)
        assert mesh.shape[AGENTS_AXIS] == 4
        assert mesh.shape[SPACE_AXIS] == 2

    def test_defaults_to_all_devices(self):
        mesh = global_mesh(n_space=2)
        assert mesh.shape[AGENTS_AXIS] * mesh.shape[SPACE_AXIS] == len(
            jax.devices()
        )

    def test_too_many_devices_raises(self):
        import pytest

        with pytest.raises(ValueError):
            global_mesh(n_agents=64, n_space=64)


class TestDistribute:
    def test_state_shards_feed_sharded_runner(self):
        from lens_tpu.models.composites import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 64, "shape": (32, 32), "motility": {"sigma": 0.0}}
        )
        mesh = global_mesh(n_agents=4, n_space=2)
        runner = ShardedSpatialColony(spatial, mesh)

        # Host-side full-size construction, then explicit distribution —
        # the multi-host startup path (single-host it's a device_put).
        host_state = spatial.initial_state(16, jax.random.PRNGKey(0))
        ss = distribute(host_state, mesh, spatial_pspecs(host_state))

        alive = ss.colony.alive
        assert alive.sharding.spec == spatial_pspecs(host_state).colony.alive
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(alive)),
            np.asarray(jax.device_get(host_state.colony.alive)),
        )

        stepped = runner.step(ss, 1.0)
        assert bool(jnp.all(jnp.isfinite(stepped.fields)))


class TestExperimentMesh:
    def test_experiment_runs_sharded(self):
        """mesh config routes the experiment through the sharded runner and
        produces the same trajectory as the unsharded path (deterministic
        composite: motility off)."""
        from lens_tpu.experiment import Experiment

        base = {
            "composite": "ecoli_lattice",
            "config": {
                "capacity": 64,
                "shape": (32, 32),
                "motility": {"sigma": 0.0},
            },
            "n_agents": 16,
            "total_time": 5.0,
            "emitter": {"type": "ram"},
        }
        with Experiment(base) as exp:
            exp.run()
            plain = exp.emitter.timeseries()
        # stripe=False: row-for-row comparison against the unsharded run
        # (the default striping permutes rows, which is biology-neutral
        # but breaks positional equality)
        with Experiment(
            {**base, "mesh": {"agents": 4, "space": 2, "stripe": False}}
        ) as exp:
            assert exp.runner is not None
            exp.run()
            sharded = exp.emitter.timeseries()
        np.testing.assert_allclose(
            np.asarray(plain["alive"]),
            np.asarray(sharded["alive"]),
        )
        np.testing.assert_allclose(
            np.asarray(plain["fields"]),
            np.asarray(sharded["fields"]),
            atol=1e-5,
        )

    def test_mesh_requires_spatial(self):
        import pytest

        from lens_tpu.experiment import Experiment

        with pytest.raises(ValueError, match="spatial"):
            Experiment(
                {"composite": "grow_divide", "mesh": {"agents": 8}}
            )
