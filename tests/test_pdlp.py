"""First-order PDLP solver vs the scipy HiGHS oracle and the dense IPM.

``ops.pdlp`` is the beyond-dense scaling step (SURVEY.md §2 "wcEcoli
bridge" direction): correctness is pinned the same way ``ops.linprog``'s
is — independent CPU oracle on randomized problems, agreement with the
IPM on the packaged FBA networks, plus structural tests (vmap batching,
warm starts, infeasibility, early-exit determinism).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize

from lens_tpu.ops.linprog import flux_balance
from lens_tpu.ops.pdlp import (
    PDLPWarm,
    flux_balance_pdlp,
    pack_warm_pdlp,
    pdlp_box,
    unpack_warm_pdlp,
    warm_size_pdlp,
)


def random_feasible_lp(rng, m=4, r=9):
    A = rng.normal(size=(m, r))
    lb = -rng.uniform(0.5, 3.0, size=r)
    ub = rng.uniform(0.5, 3.0, size=r)
    x0 = rng.uniform(0.25, 0.75, size=r) * (ub - lb) + lb
    b = A @ x0
    c = rng.normal(size=r)
    return c, A, b, lb, ub


def oracle(c, A, b, lb, ub):
    res = scipy.optimize.linprog(
        c, A_eq=A, b_eq=b, bounds=list(zip(lb, ub)), method="highs"
    )
    assert res.success, res.message
    return res


def network_problem(name):
    """(S, objective, lb, ub) for a packaged FBA network in a glucose-rich
    aerobic environment (same base as bench_lp_sizes.py)."""
    from lens_tpu.processes.fba_metabolism import FBAMetabolism

    p = FBAMetabolism({"network": name})
    base = {"glc": 10.0, "o2": 50.0, "nh4": 50.0, "ace": 2.0}
    env = jnp.asarray(
        [base.get(mol, 0.0) for mol in p.external], jnp.float32
    )
    lb, ub = p.regulated_bounds(env, 1.0)
    return p.stoichiometry, p.objective, lb, ub


class TestAgainstScipy:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_problems_match_highs(self, seed):
        rng = np.random.default_rng(seed)
        c, A, b, lb, ub = random_feasible_lp(rng)
        ref = oracle(c, A, b, lb, ub)
        res = pdlp_box(
            jnp.asarray(c), jnp.asarray(A), jnp.asarray(b),
            jnp.asarray(lb), jnp.asarray(ub), tol=1e-5,
        )
        assert bool(res.converged), (res.primal_residual, res.dual_gap)
        scale = 1.0 + abs(ref.fun)
        assert abs(float(res.objective) - ref.fun) / scale < 5e-4
        np.testing.assert_allclose(A @ np.asarray(res.x), b, atol=2e-3)
        assert np.all(np.asarray(res.x) >= lb - 1e-4)
        assert np.all(np.asarray(res.x) <= ub + 1e-4)

    def test_pure_box_lp(self):
        # No equalities: optimum at the bound selected by the sign of c.
        c = jnp.asarray([1.0, -2.0, 0.5])
        res = pdlp_box(
            c, jnp.zeros((0, 3)), jnp.zeros((0,)),
            jnp.asarray([-1.0, -1.0, -1.0]), jnp.asarray([2.0, 2.0, 2.0]),
        )
        assert bool(res.converged)
        np.testing.assert_allclose(
            np.asarray(res.x), [-1.0, 2.0, -1.0], atol=1e-3
        )

    def test_inverted_box_reports_not_converged(self):
        # lb > ub is an infeasible problem, not a clampable one — the
        # solver must not report success on the silently pinned version
        res = pdlp_box(
            jnp.asarray([1.0, 1.0]),
            jnp.asarray([[1.0, -1.0]]),
            jnp.asarray([0.0]),
            jnp.asarray([0.0, 2.0]),
            jnp.asarray([1.0, 1.0]),  # ub[1] < lb[1]
            n_iter=512,
        )
        assert not bool(res.converged)
        assert float(res.warm.flag) == 0.0

    def test_infeasible_reports_not_converged(self):
        # x1 + x2 = 10 with 0 <= x <= 1: unsatisfiable.
        res = pdlp_box(
            jnp.asarray([1.0, 1.0]),
            jnp.asarray([[1.0, 1.0]]),
            jnp.asarray([10.0]),
            jnp.zeros(2),
            jnp.ones(2),
            n_iter=1024,
        )
        assert not bool(res.converged)
        assert float(res.primal_residual) > 0.1


class TestFBANetworks:
    """Agreement with the dense IPM on the packaged FBA networks — the
    crossover bench (bench_lp_scale.py) assumes the two solvers answer
    the same question at their shared tolerances."""

    @pytest.mark.parametrize("name", ["core_skeleton", "ecoli_core"])
    def test_matches_ipm_objective(self, name):
        S, obj, lb, ub = network_problem(name)
        ipm = flux_balance(S, obj, lb, ub, n_iter=45, tol=1e-5)
        pd = flux_balance_pdlp(S, obj, lb, ub, n_iter=16384, tol=1e-5)
        assert bool(ipm.converged) and bool(pd.converged), (
            ipm.converged, pd.converged, pd.primal_residual, pd.dual_gap,
        )
        scale = 1.0 + abs(float(ipm.objective))
        assert (
            abs(float(pd.objective) - float(ipm.objective)) / scale < 2e-3
        )

    def test_vmap_batches_over_bounds(self):
        S, obj, lb, ub = network_problem("core_skeleton")
        scales = jnp.asarray([0.5, 1.0, 2.0])
        sol = jax.vmap(
            lambda s: flux_balance_pdlp(S, obj, lb * s, ub * s, tol=1e-5)
        )(scales)
        assert bool(sol.converged.all()), np.asarray(sol.primal_residual)
        # FBA optima scale linearly with the box on this network
        objs = np.asarray(sol.objective)
        np.testing.assert_allclose(objs[1] * 0.5, objs[0], rtol=5e-3)
        np.testing.assert_allclose(objs[1] * 2.0, objs[2], rtol=5e-3)


class TestSparseMatvecs:
    """sparse="auto"/True: O(nnz) segment-sum matvecs must answer exactly
    the same question as the dense matmuls."""

    def test_sparse_matches_dense_on_core_network(self):
        S, obj, lb, ub = network_problem("ecoli_core")
        dense = flux_balance_pdlp(
            S, obj, lb, ub, n_iter=16384, tol=1e-5, sparse=False
        )
        sp = flux_balance_pdlp(
            S, obj, lb, ub, n_iter=16384, tol=1e-5, sparse=True
        )
        assert bool(dense.converged) and bool(sp.converged)
        scale = 1.0 + abs(float(dense.objective))
        assert (
            abs(float(sp.objective) - float(dense.objective)) / scale < 1e-3
        )

    def test_sparse_under_vmap_and_jit(self):
        S, obj, lb, ub = network_problem("core_skeleton")
        scales = jnp.asarray([0.5, 1.0, 2.0])
        sol = jax.jit(
            jax.vmap(
                lambda s: flux_balance_pdlp(
                    S, obj, lb * s, ub * s, tol=1e-5, sparse=True
                )
            )
        )(scales)
        assert bool(sol.converged.all())

    def test_sparse_true_rejects_traced_matrix(self):
        c = jnp.zeros(3)
        b = jnp.zeros(2)
        lo = -jnp.ones(3)
        hi = jnp.ones(3)
        with pytest.raises(ValueError, match="concrete"):
            jax.jit(
                lambda A: pdlp_box(c, A, b, lo, hi, sparse=True).x
            )(jnp.ones((2, 3)))


class TestProcessIntegration:
    """`lp_solver: "pdlp"` in FBAMetabolism: same phenotype as the IPM,
    warm state threaded in the PDLP layout."""

    def _stepped(self, solver, n_steps=3):
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        p = FBAMetabolism({
            "network": "ecoli_core", "lp_leak": 1.5e-3, "lp_tol": 1e-4,
            "lp_iterations": 60, "lp_solver": solver,
        })
        s = p.initial_state()
        env = {"glc": 10.0, "o2": 50.0, "nh4": 50.0}
        for mol in p.external:
            s["external"][mol] = jnp.asarray(float(env.get(mol, 0.0)))
        outs = []
        for _ in range(n_steps):
            u = p.next_update(1.0, s)
            s["lp_state"]["warm"] = u["lp_state"]["warm"]
            outs.append(u)
        return outs

    def test_pdlp_solver_matches_ipm_phenotype(self):
        ipm = self._stepped("ipm")
        pd = self._stepped("pdlp")
        for a, b in zip(ipm, pd):
            assert float(a["fluxes"]["lp_converged"]) == 1.0
            assert float(b["fluxes"]["lp_converged"]) == 1.0
            np.testing.assert_allclose(
                float(b["fluxes"]["growth_rate"]),
                float(a["fluxes"]["growth_rate"]),
                rtol=5e-3, atol=1e-4,
            )
        # warm threading pays: later steps exit far below the cold cap
        assert float(pd[-1]["fluxes"]["lp_iterations"]) < 0.5 * float(
            pd[0]["fluxes"]["lp_iterations"]
        )

    def test_solver_name_validated(self):
        from lens_tpu.processes.fba_metabolism import FBAMetabolism

        with pytest.raises(ValueError, match="lp_solver"):
            FBAMetabolism({"lp_solver": "simplex"})


class TestWarmStart:
    def test_warm_cuts_iterations(self):
        S, obj, lb, ub = network_problem("ecoli_core")
        cold = flux_balance_pdlp(S, obj, lb, ub, n_iter=16384, tol=1e-5)
        assert bool(cold.converged)
        # a small environment drift: 5% tighter uptake box
        warm = flux_balance_pdlp(
            S, obj, lb * 0.95, ub * 0.95, n_iter=16384, tol=1e-5,
            warm=cold.warm,
        )
        rewarm_cold = flux_balance_pdlp(
            S, obj, lb * 0.95, ub * 0.95, n_iter=16384, tol=1e-5,
        )
        assert bool(warm.converged) and bool(rewarm_cold.converged)
        assert int(warm.iterations) < int(rewarm_cold.iterations), (
            int(warm.iterations), int(rewarm_cold.iterations),
        )
        scale = 1.0 + abs(float(rewarm_cold.objective))
        assert (
            abs(float(warm.objective) - float(rewarm_cold.objective)) / scale
            < 2e-3
        )

    def test_flag_zero_reproduces_cold_bitwise(self):
        rng = np.random.default_rng(5)
        c, A, b, lb, ub = random_feasible_lp(rng)
        args = map(jnp.asarray, (c, A, b, lb, ub))
        c, A, b, lb, ub = args
        cold = pdlp_box(c, A, b, lb, ub)
        ignored = PDLPWarm(
            x=jnp.ones_like(c), y=jnp.zeros(A.shape[0]),
            omega=jnp.asarray(7.0), flag=jnp.asarray(0.0),
        )
        again = pdlp_box(c, A, b, lb, ub, warm=ignored)
        np.testing.assert_array_equal(np.asarray(cold.x), np.asarray(again.x))
        assert int(cold.iterations) == int(again.iterations)

    def test_pack_unpack_roundtrip(self):
        m, r = 4, 9
        ws = PDLPWarm(
            x=jnp.arange(r, dtype=jnp.float32),
            y=jnp.arange(r, r + m, dtype=jnp.float32),
            omega=jnp.asarray(2.5),
            flag=jnp.asarray(1.0),
        )
        vec = pack_warm_pdlp(ws)
        assert vec.shape == (warm_size_pdlp(m, r),)
        back = unpack_warm_pdlp(vec, m, r)
        for a, c2 in zip(ws, back):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(c2))
