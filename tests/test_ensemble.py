"""Replicate ensembles: N independent colonies in one program.

The replicate axis must behave like N separate runs: independent PRNG
streams, no cross-replicate coupling, deterministic for a fixed seed —
and the emitted trajectory gains a [T, R, ...] layout.
"""

import jax
import jax.numpy as jnp
import numpy as np

from lens_tpu.colony import Colony, Ensemble
from lens_tpu.models.composites import toggle_colony


def toggle_ensemble(r=4, n=16):
    colony = Colony(toggle_colony({}), capacity=n)
    return Ensemble(colony, r), colony


class TestEnsembleColony:
    def test_shapes_and_replicate_axis(self):
        ens, colony = toggle_ensemble()
        states = ens.initial_state(16, key=jax.random.PRNGKey(0))
        assert states.alive.shape == (4, 16)
        final, traj = jax.jit(
            lambda s: ens.run(s, 20.0, 1.0, emit_every=5)
        )(states)
        assert final.alive.shape == (4, 16)
        assert traj["alive"].shape == (4, 4, 16)  # [T, R, N]

    def test_replicates_diverge_stochastically(self):
        """Different replicate keys -> different stochastic trajectories
        (hybrid Gillespie cell; the deterministic toggle composite
        rightly produces IDENTICAL replicates, tested elsewhere)."""
        from lens_tpu.models.composites import hybrid_cell

        colony = Colony(hybrid_cell({}), capacity=16)
        ens = Ensemble(colony, 6)
        states = ens.initial_state(16, key=jax.random.PRNGKey(1))
        final, _ = jax.jit(lambda s: ens.run(s, 20.0, 1.0, emit_every=20))(
            states
        )
        # molecule counts across replicates should not be identical
        leaves = jax.tree.leaves(final.agents)
        assert any(
            len({np.asarray(leaf[i]).tobytes() for i in range(6)}) > 1
            for leaf in leaves
        )

    def test_deterministic_sim_replicates_coincide(self):
        """A deterministic composite's replicates are bitwise equal —
        the replicate axis itself adds no spurious randomness."""
        ens, _ = toggle_ensemble(r=3, n=8)
        final, _ = ens.run(
            ens.initial_state(8, key=jax.random.PRNGKey(2)), 10.0, 1.0,
            emit_every=10,
        )
        for leaf in jax.tree.leaves(final.agents):
            arr = np.asarray(leaf)
            for r in range(1, 3):
                np.testing.assert_array_equal(arr[r], arr[0])

    def test_deterministic_for_fixed_seed(self):
        ens, _ = toggle_ensemble()
        run = jax.jit(lambda s: ens.run(s, 10.0, 1.0, emit_every=10)[0])
        a = run(ens.initial_state(16, key=jax.random.PRNGKey(7)))
        b = run(ens.initial_state(16, key=jax.random.PRNGKey(7)))
        for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_matches_individual_runs(self):
        """Replicate r of the ensemble == a solo run with that replicate's
        key: vmap adds no coupling."""
        ens, colony = toggle_ensemble(r=3, n=8)
        key = jax.random.PRNGKey(3)
        states = ens.initial_state(8, key=key)
        final, _ = ens.run(states, 8.0, 1.0, emit_every=8)
        keys = jax.random.split(key, 3)
        for r in range(3):
            solo0 = colony.initial_state(8, key=keys[r])
            solo, _ = colony.run(solo0, 8.0, 1.0, emit_every=8)
            for le, ls in zip(
                jax.tree.leaves(jax.tree.map(lambda x: x[r], final)),
                jax.tree.leaves(solo),
            ):
                np.testing.assert_allclose(
                    np.asarray(le), np.asarray(ls), rtol=1e-6, atol=1e-6
                )


class TestParameterScan:
    """``replicate_overrides`` turns the replicate axis into a scan axis."""

    def test_scalar_scan_orders_division_times(self):
        """Bigger initial volume -> earlier first division; the scan axis
        carries a real, monotone parameter effect through the dynamics."""
        from lens_tpu.models.composites import grow_divide

        colony = Colony(
            grow_divide({"growth": {"rate": 0.02}}),
            capacity=16,
            division_trigger=("global", "divide"),
        )
        ens = Ensemble(colony, 3)
        vols = jnp.asarray([1.0, 1.4, 1.9])
        states = ens.initial_state(
            1,
            key=jax.random.PRNGKey(0),
            replicate_overrides={"global": {"volume": vols}},
        )
        np.testing.assert_allclose(
            np.asarray(states.agents["global"]["volume"][:, 0]), vols
        )
        _, traj = jax.jit(lambda s: ens.run(s, 40.0, 1.0))(states)
        alive = np.asarray(traj["alive"]).sum(axis=-1)  # [T, R]
        first_div = (alive > 1).argmax(axis=0)
        assert first_div[0] > first_div[1] > first_div[2]

    def test_scan_replicate_matches_solo_override(self):
        """Replicate r == a solo run constructed with the same override:
        the scan axis is exactly initial-condition substitution."""
        ens, colony = toggle_ensemble(r=3, n=8)
        key = jax.random.PRNGKey(5)
        vols = jnp.asarray([0.8, 1.0, 1.3])
        states = ens.initial_state(
            8, key=key,
            replicate_overrides={"global": {"volume": vols}},
        )
        final, _ = ens.run(states, 8.0, 1.0, emit_every=8)
        keys = jax.random.split(key, 3)
        for r in range(3):
            solo0 = colony.initial_state(
                8, overrides={"global": {"volume": vols[r]}}, key=keys[r]
            )
            solo, _ = colony.run(solo0, 8.0, 1.0, emit_every=8)
            for le, ls in zip(
                jax.tree.leaves(jax.tree.map(lambda x: x[r], final)),
                jax.tree.leaves(solo),
            ):
                np.testing.assert_allclose(
                    np.asarray(le), np.asarray(ls), rtol=1e-6, atol=1e-6
                )

    def test_per_replicate_wins_over_shared_override(self):
        ens, _ = toggle_ensemble(r=2, n=4)
        states = ens.initial_state(
            4,
            key=jax.random.PRNGKey(0),
            overrides={"global": {"volume": 5.0}},
            replicate_overrides={"global": {"volume": jnp.asarray([1.0, 2.0])}},
        )
        vols = np.asarray(states.agents["global"]["volume"])
        np.testing.assert_allclose(vols[0], 1.0)
        np.testing.assert_allclose(vols[1], 2.0)

    def test_scan_response_helpers(self, tmp_path):
        import os

        import pytest

        from lens_tpu.analysis import plot_scan_response, scan_response

        ens, _ = toggle_ensemble(r=3, n=8)
        vols = jnp.asarray([0.8, 1.0, 1.3])
        states = ens.initial_state(
            8, key=jax.random.PRNGKey(0),
            replicate_overrides={"global": {"volume": vols}},
        )
        _, traj = ens.run(states, 8.0, 1.0, emit_every=4)
        resp = scan_response(traj, ("global", "volume"))
        assert resp.shape == (3,)
        assert (np.diff(resp) > 0).all()  # bigger seed volume stays bigger
        p = plot_scan_response(
            traj, vols, ("global", "volume"),
            out_path=str(tmp_path / "scan.png"),
            value_label="initial volume (fL)",
        )
        assert os.path.getsize(p) > 1000
        with pytest.raises(ValueError, match="replicates"):
            plot_scan_response(traj, [1.0, 2.0], ("global", "volume"))

    def test_bad_leading_axis_rejected(self):
        import pytest

        ens, _ = toggle_ensemble(r=4, n=8)
        with pytest.raises(ValueError, match="n_replicates=4"):
            ens.initial_state(
                8,
                key=jax.random.PRNGKey(0),
                replicate_overrides={
                    "global": {"volume": jnp.asarray([1.0, 2.0])}
                },
            )


class TestEnsembleSpatial:
    def test_spatial_ensemble_with_division(self):
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {
                "capacity": 32,
                "shape": (16, 16),
                "size": (16.0, 16.0),
                "growth": {"rate": 0.05},
            }
        )
        ens = Ensemble(spatial, 4)
        states = ens.initial_state(4, key=jax.random.PRNGKey(0))
        assert states.fields.shape == (4, 1, 16, 16)
        final, traj = jax.jit(
            lambda s: ens.run(s, 30.0, 1.0, emit_every=10)
        )(states)
        counts = np.asarray(final.colony.alive).sum(axis=1)
        assert (counts > 4).all()  # every replicate divided
        assert traj["fields"].shape == (3, 4, 1, 16, 16)
        assert np.isfinite(np.asarray(traj["fields"])).all()
        # growth statistics across the replicate axis are the point:
        mean_pop = np.asarray(traj["alive"]).sum(axis=-1).mean(axis=1)
        assert mean_pop[-1] > mean_pop[0]

    def test_ensemble_analysis_fan(self, tmp_path):
        """analysis.ensemble_series + the fan chart consume [T, R, ...]
        trajectories straight from Ensemble.run."""
        import os

        from lens_tpu.analysis import ensemble_series, plot_ensemble_fan
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 32, "shape": (16, 16), "size": (16.0, 16.0),
             "growth": {"rate": 0.05}}
        )
        ens = Ensemble(spatial, 5)
        states = ens.initial_state(4, key=jax.random.PRNGKey(0))
        _, traj = jax.jit(lambda s: ens.run(s, 30.0, 1.0, emit_every=5))(
            states
        )
        counts = ensemble_series(traj)
        assert counts.shape == (6, 5)
        assert (counts[-1] >= counts[0]).all()
        vol = ensemble_series(traj, ("global", "volume"))
        assert vol.shape == (6, 5) and np.isfinite(vol).all()
        p = plot_ensemble_fan(
            traj, out_path=str(tmp_path / "fan.png")
        )
        assert os.path.getsize(p) > 1000
        # a flat [T, N] trajectory is rejected with guidance
        import pytest

        solo, straj = spatial.run(
            spatial.initial_state(4, jax.random.PRNGKey(1)), 5.0, 1.0,
            emit_every=5,
        )
        with pytest.raises(ValueError, match="Ensemble"):
            ensemble_series(straj)

    def test_report_handles_ensemble_logs(self, tmp_path):
        """`analyze` on an ensemble log renders the fan chart instead of
        crashing in the per-agent lineage/fields paths."""
        import os

        import numpy as _np

        from lens_tpu.analysis import report
        from lens_tpu.emit import LogEmitter
        from lens_tpu.models import ecoli_lattice

        spatial, _ = ecoli_lattice(
            {"capacity": 16, "shape": (8, 8), "size": (8.0, 8.0)}
        )
        ens = Ensemble(spatial, 3)
        _, traj = ens.run(
            ens.initial_state(4, key=jax.random.PRNGKey(0)), 6.0, 1.0,
            emit_every=2,
        )
        path = str(tmp_path / "emit.lens")
        with LogEmitter("ens-exp", path=path) as em:
            em.emit_trajectory(traj, times=_np.arange(1, 4) * 2.0)
        written = report(path, out_dir=str(tmp_path / "plots"))
        assert "ensemble_fan" in written
        assert os.path.getsize(written["ensemble_fan"]) > 1000
        assert "lineage" not in written and "field_snapshots" not in written

        # multi-species ensemble logs route per species, not crash
        from lens_tpu.models import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {"capacity": {"ecoli": 8, "scavenger": 8},
             "shape": (8, 8), "size": (8.0, 8.0)}
        )
        mens = Ensemble(multi, 3)
        _, mtraj = mens.run(
            mens.initial_state(
                {"ecoli": 4, "scavenger": 4}, key=jax.random.PRNGKey(1)
            ),
            4.0, 1.0, emit_every=2,
        )
        mpath = str(tmp_path / "m_emit.lens")
        with LogEmitter("mens-exp", path=mpath) as em:
            em.emit_trajectory(mtraj, times=_np.arange(1, 3) * 2.0)
        mw = report(mpath, out_dir=str(tmp_path / "mplots"))
        assert "ecoli.ensemble_fan" in mw and "scavenger.ensemble_fan" in mw
        assert "species_snapshots" not in mw

    def test_multispecies_ensemble(self):
        """The third colony form honors the protocol too."""
        from lens_tpu.models import mixed_species_lattice

        multi, _ = mixed_species_lattice(
            {"capacity": {"ecoli": 8, "scavenger": 8},
             "shape": (8, 8), "size": (8.0, 8.0)}
        )
        ens = Ensemble(multi, 3)
        states = ens.initial_state(
            {"ecoli": 4, "scavenger": 4}, key=jax.random.PRNGKey(0)
        )
        final, traj = jax.jit(
            lambda s: ens.run(s, 4.0, 1.0, emit_every=2)
        )(states)
        assert traj["fields"].shape[:2] == (2, 3)  # [T, R, ...]
        for name in ("ecoli", "scavenger"):
            assert np.asarray(final.species[name].alive).sum(axis=1).min() >= 4

    def test_protocol_guard(self):
        import pytest

        with pytest.raises(TypeError, match="colony-form protocol"):
            Ensemble(object(), 2)
        with pytest.raises(ValueError, match="n_replicates"):
            from lens_tpu.models import ecoli_lattice

            Ensemble(ecoli_lattice({"capacity": 8, "shape": (8, 8)})[0], 0)