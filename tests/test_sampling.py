"""Hybrid Poisson sampler: distributional pins per mean regime + the
exact-path/bitwise contracts the expression stack's resume flows need.

The regimes mirror ops.sampling's design:

- small (lam <= threshold): sequential CDF inversion — distributionally
  EXACT, pinned by chi-square p-values against the analytic pmf;
- large (lam > threshold): normal + Cornish–Fisher quantile — an
  approximation with a CALIBRATED error budget, pinned by a chi-square
  divergence BOUND (excess statistic per sample; measured peak ~7e-4
  just above the boundary, asserted < 2e-3) plus tight moment tests.
  Asserting a p-value there would be dishonest: with enough samples an
  approximation always fails an exactness test.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from scipy import stats

from lens_tpu.ops.gillespie import tau_leap_window
from lens_tpu.ops.sampling import (
    DEFAULT_THRESHOLD,
    inversion_trip_count,
    poisson_from_uniform,
    poisson_hybrid,
    sample_poisson,
    uniform_block,
)


def _draw(lam: float, n: int, seed: int) -> np.ndarray:
    key = jax.random.PRNGKey(seed)
    return np.asarray(
        jax.jit(lambda k: poisson_hybrid(k, jnp.full((n,), lam)))(key)
    )


def _chi2_vs_pmf(samples: np.ndarray, lam: float, min_expected=5.0):
    """(statistic, dof): observed counts vs the analytic Poisson pmf,
    tail-pooled so every bin has >= min_expected expected entries."""
    n = len(samples)
    kmax = int(stats.poisson.ppf(1.0 - 1e-9, lam)) + 2
    expected = stats.poisson.pmf(np.arange(kmax + 1), lam) * n
    observed = np.bincount(samples.astype(int), minlength=kmax + 1)
    observed = observed[: kmax + 1]
    obs_b, exp_b = [], []
    co = ce = 0.0
    for o, e in zip(observed, expected):
        co += o
        ce += e
        if ce >= min_expected:
            obs_b.append(co)
            exp_b.append(ce)
            co = ce = 0.0
    obs_b[-1] += co
    exp_b[-1] += ce
    obs_b, exp_b = np.asarray(obs_b), np.asarray(exp_b)
    exp_b *= n / exp_b.sum()
    return ((obs_b - exp_b) ** 2 / exp_b).sum(), len(obs_b) - 1


class TestSmallMeanRegime:
    """Below the threshold the sampler is exact inversion: hold it to
    full chi-square exactness against the analytic pmf."""

    @pytest.mark.parametrize("lam", [0.05, 0.5, 3.0, 8.0, 9.9])
    def test_chi_square_exact(self, lam):
        x = _draw(lam, 100_000, seed=int(lam * 10))
        stat, dof = _chi2_vs_pmf(x, lam)
        p = stats.chi2.sf(stat, dof)
        assert p > 1e-4, (lam, stat, dof, p)

    def test_zero_mean_is_zero(self):
        assert _draw(0.0, 4096, seed=0).max() == 0.0


class TestLargeMeanRegime:
    """Above the threshold the sampler is an approximation with a
    calibrated budget: bound the chi-square divergence per sample and
    hold moments to sampling noise."""

    @pytest.mark.parametrize("lam", [10.1, 12.0, 20.0, 50.0, 400.0])
    def test_divergence_bound(self, lam):
        n = 200_000
        x = _draw(lam, n, seed=int(lam))
        stat, dof = _chi2_vs_pmf(x, lam)
        divergence = max(stat - dof, 0.0) / n
        assert divergence < 2e-3, (lam, divergence)

    @pytest.mark.parametrize("lam", [10.1, 12.0, 20.0, 50.0, 400.0])
    def test_moments(self, lam):
        n = 200_000
        x = _draw(lam, n, seed=1000 + int(lam))
        se_mean = np.sqrt(lam / n)
        assert abs(x.mean() - lam) < 5 * se_mean, (lam, x.mean())
        # Poisson var = lam; var estimator se ~ lam * sqrt(2/n) (+skew)
        assert abs(x.var() - lam) < 8 * lam * np.sqrt(2.0 / n), (lam, x.var())


class TestRegimeBoundary:
    """The threshold is a config knob: both samplers must be usable on
    either side of it, and moving it moves which branch runs."""

    @pytest.mark.parametrize("threshold", [5.0, 10.0, 16.0])
    def test_mean_continuous_across_threshold(self, threshold):
        """No moment jump at the branch switch: means just below and
        just above the threshold both land on lam to sampling noise."""
        n = 200_000
        for lam in (threshold * 0.99, threshold * 1.01):
            key = jax.random.PRNGKey(int(threshold * 7))
            x = np.asarray(
                poisson_from_uniform(
                    uniform_block(key, (n,)), jnp.full((n,), lam), threshold
                )
            )
            assert abs(x.mean() - lam) < 5 * np.sqrt(lam / n), (
                threshold, lam, x.mean(),
            )

    def test_threshold_selects_branch(self):
        """Same uniforms, lam between the two thresholds: the small
        branch (inversion) and large branch (CF normal) are different
        transforms, so the samples must differ somewhere."""
        lam = jnp.full((4096,), 8.0)
        u = uniform_block(jax.random.PRNGKey(3), (4096,))
        small = poisson_from_uniform(u, lam, threshold=10.0)
        large = poisson_from_uniform(u, lam, threshold=4.0)
        assert not np.array_equal(np.asarray(small), np.asarray(large))
        # but they agree in distribution (both target Poisson(8))
        assert abs(float(small.mean()) - float(large.mean())) < 0.3

    def test_quantile_transform_is_monotone(self):
        u = jnp.linspace(0.001, 0.999, 4001)
        for lam in (0.5, 9.0, 40.0):
            x = np.asarray(poisson_from_uniform(u, jnp.full_like(u, lam)))
            assert (np.diff(x) >= 0).all(), lam

    def test_trip_count_covers_threshold_tail(self):
        k = inversion_trip_count(DEFAULT_THRESHOLD)
        assert stats.poisson.sf(k, DEFAULT_THRESHOLD) < 1e-12

    def test_threshold_beyond_exp_underflow_rejected(self):
        """float32 exp(-lam) underflows near lam ~ 87; past it the
        inversion branch would return the trip count deterministically.
        The knob must refuse, at the op AND at process construction."""
        from lens_tpu.processes.stochastic_expression import (
            StochasticExpression,
        )

        with pytest.raises(ValueError, match="threshold"):
            poisson_from_uniform(
                jnp.full((4,), 0.5), jnp.full((4,), 100.0), threshold=120.0
            )
        with pytest.raises(ValueError, match="threshold"):
            StochasticExpression({"sampler_threshold": 120.0})
        with pytest.raises(ValueError, match="threshold"):
            poisson_from_uniform(jnp.ones(2), jnp.ones(2), threshold=-1.0)


class TestExactPath:
    """sampler="exact" must be jax.random.poisson VERBATIM — the oracle
    and the RNG stream pre-fast-path checkpoints were recorded under."""

    def test_sample_poisson_exact_bitwise(self):
        key = jax.random.PRNGKey(11)
        lam = jnp.asarray([0.1, 2.0, 15.0, 200.0])
        got = sample_poisson(key, lam, sampler="exact")
        want = jax.random.poisson(key, lam).astype(jnp.float32)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_tau_leap_exact_bitwise_vs_pre_fast_path(self):
        """The exact window reproduces the ORIGINAL implementation
        (per-substep key split + jax.random.poisson) bit for bit."""
        stoich = jnp.asarray([[1.0, 0.0], [0.0, 1.0], [-1.0, 0.0]])
        prop = lambda x: jnp.stack([2.0 * jnp.ones(()), 0.5 * x[0], 0.3 * x[0]])
        key = jax.random.PRNGKey(5)
        counts = jnp.asarray([4.0, 0.0])

        def original(key, counts, timestep, n):
            tau = timestep / n
            keys = jax.random.split(key, n)

            def body(c, k):
                a = prop(c)
                ev = jax.random.poisson(k, jnp.maximum(a, 0.0) * tau)
                ev = ev.astype(jnp.float32)
                consumed = jnp.maximum(-stoich, 0.0)
                supportable = jnp.where(
                    consumed > 0,
                    c[None, :] / jnp.maximum(consumed, 1e-12),
                    jnp.inf,
                )
                ev = jnp.minimum(ev, jnp.floor(jnp.min(supportable, axis=1)))
                new = c + jnp.matmul(
                    ev, stoich, precision=jax.lax.Precision.HIGHEST
                )
                return jnp.maximum(new, 0.0), None

            return jax.lax.scan(body, counts, keys)[0]

        got = tau_leap_window(key, counts, stoich, prop, 4.0, 16,
                              sampler="exact")
        want = original(key, counts, 4.0, 16)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_unknown_sampler_rejected(self):
        with pytest.raises(ValueError, match="sampler"):
            sample_poisson(jax.random.PRNGKey(0), jnp.ones(3), sampler="nope")
        with pytest.raises(ValueError, match="sampler"):
            tau_leap_window(
                jax.random.PRNGKey(0), jnp.ones(1),
                jnp.asarray([[1.0]]), lambda x: x, 1.0, 2, sampler="typo",
            )


class TestHybridTauLeap:
    """The hybrid window holds the same physical contracts as the exact
    one: stationary moments, nonnegativity, vmap/jit compatibility."""

    def test_birth_death_stationary_moments(self):
        # 0 --k--> X --gamma--> 0; stationary X ~ Poisson(k/gamma) = 20
        k_rate, gamma = 8.0, 0.4
        stoich = jnp.asarray([[1.0], [-1.0]])
        prop = lambda x: jnp.stack([jnp.asarray(k_rate), gamma * x[0]])
        keys = jax.random.split(jax.random.PRNGKey(0), 2048)

        @jax.jit
        @jax.vmap
        def run(key):
            return tau_leap_window(
                key, jnp.asarray([0.0]), stoich, prop, 60.0, 240,
                sampler="hybrid",
            )[0]

        x = np.asarray(run(keys))
        assert abs(x.mean() - 20.0) < 0.5, x.mean()
        assert abs(x.var() - 20.0) < 2.5, x.var()

    def test_counts_stay_integral_and_nonnegative(self):
        stoich = jnp.asarray([[-3.0]])
        prop = lambda x: jnp.stack([10.0 * x[0]])
        keys = jax.random.split(jax.random.PRNGKey(2), 512)
        out = jax.vmap(
            lambda k: tau_leap_window(
                k, jnp.asarray([5.0]), stoich, prop, 4.0, 4,
                sampler="hybrid",
            )
        )(keys)
        arr = np.asarray(out)
        assert arr.min() >= 0.0
        np.testing.assert_array_equal(arr, np.round(arr))


class TestProcessKnobs:
    """The sampler knob reaches every expression process and the
    composite/experiment plumbing above them."""

    def test_stochastic_expression_hybrid_stationary(self):
        from lens_tpu.processes.stochastic_expression import (
            StochasticExpression,
        )

        proc = StochasticExpression({"d_p": 0.1})
        assert proc.config["sampler"] == "hybrid"
        state = proc.initial_state()
        key = jax.random.PRNGKey(0)

        @jax.jit
        def step(s, k):
            up = proc.next_update(1.0, s, key=k)
            return {
                "counts": {
                    n: jnp.maximum(s["counts"][n] + up["counts"][n], 0.0)
                    for n in s["counts"]
                },
                "rates": s["rates"],
            }

        keys = jax.random.split(key, 400)
        for k in keys:
            state = step(state, k)
        # stationary E[mrna] = k_tx/d_m = 5; one trajectory's late-time
        # value fluctuates but must be in the right ballpark and integral
        m = float(state["counts"]["mrna"])
        assert 0.0 <= m <= 30.0
        assert m == round(m)

    def test_composite_knob_threads_to_processes(self):
        from lens_tpu.models.composites import (
            hybrid_cell,
            mixed_species_lattice,
            toggle_colony,
        )

        comp = hybrid_cell({"sampler": "exact"})
        assert comp.processes["expression"].config["sampler"] == "exact"
        # explicit per-process sampler wins over the composite knob
        comp = hybrid_cell(
            {"sampler": "exact", "expression": {"sampler": "hybrid"}}
        )
        assert comp.processes["expression"].config["sampler"] == "hybrid"
        multi, comps = mixed_species_lattice(
            {"capacity": {"ecoli": 8, "scavenger": 8}, "shape": (8, 8),
             "sampler": "exact"}
        )
        scav = comps["scavenger"].processes["expression"]
        assert scav.config["sampler"] == "exact"
        tc = toggle_colony(
            {"sampler": "exact", "toggle_switch": {"method": "tau_leap"}}
        )
        assert tc.processes["toggle_switch"].config["sampler"] == "exact"

    def test_bad_sampler_fails_at_construction(self):
        from lens_tpu.processes.genome_expression import GenomeExpression
        from lens_tpu.processes.stochastic_expression import (
            StochasticExpression,
        )

        with pytest.raises(ValueError, match="sampler"):
            StochasticExpression({"sampler": "fast"})
        with pytest.raises(ValueError, match="sampler"):
            GenomeExpression({"sampler": "fast"})

    def test_toggle_tau_leap_is_stochastic_and_bistable_shape(self):
        from lens_tpu.processes.toggle_switch import ToggleSwitch

        proc = ToggleSwitch({"method": "tau_leap"})
        assert proc.stochastic
        state = {
            "internal": {
                "mrna_u": jnp.asarray(0.0),
                "protein_u": jnp.asarray(20.0),
                "mrna_v": jnp.asarray(0.0),
                "protein_v": jnp.asarray(0.0),
            }
        }
        up = proc.next_update(1.0, state, key=jax.random.PRNGKey(1))
        assert set(up["internal"]) == set(state["internal"])
        for v in up["internal"].values():
            assert np.isfinite(float(v))
        # the ODE default is untouched (and needs no key)
        det = ToggleSwitch({})
        assert not det.stochastic
        det.next_update(1.0, state)


class TestExactResume:
    """sampler="exact" checkpoints restore unchanged: the segmented
    resume is bitwise-identical to the uninterrupted run (the PRNG key
    lives in the state; the exact sampler consumes it exactly as the
    pre-fast-path code did)."""

    @pytest.mark.parametrize("sampler", ["exact", "hybrid"])
    def test_resume_bitwise(self, tmp_path, sampler):
        from lens_tpu.experiment import Experiment

        def cfg(total, ckpt_dir=None):
            c = {
                "composite": "hybrid_cell",
                "sampler": sampler,
                "n_agents": 8,
                "capacity": 32,
                "total_time": total,
                "emit_every": 10,
                "seed": 4,
            }
            if ckpt_dir is not None:
                c["checkpoint_dir"] = str(ckpt_dir)
                c["checkpoint_every"] = 10.0
            return c

        with Experiment(cfg(40.0)) as exp:
            full = exp.run()
        with Experiment(cfg(20.0, tmp_path / "ck")) as exp:
            exp.run()
        with Experiment(cfg(40.0, tmp_path / "ck")) as exp:
            resumed = exp.resume()
        for la, lb in zip(jax.tree.leaves(full), jax.tree.leaves(resumed)):
            np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))

    def test_sampler_switched_resume_fails_loudly(self, tmp_path):
        """The sidecar records the sampler; resuming under the other one
        would silently diverge, so it must fail with a descriptive
        error BEFORE restore."""
        from lens_tpu.experiment import Experiment

        def cfg(total, sampler):
            return {
                "composite": "hybrid_cell",
                "sampler": sampler,
                "n_agents": 4,
                "capacity": 16,
                "total_time": total,
                "seed": 0,
                "checkpoint_dir": str(tmp_path / "ck"),
                "checkpoint_every": 5.0,
                "emitter": {"type": "null"},
            }

        with Experiment(cfg(5.0, "exact")) as exp:
            exp.run()
        with Experiment(cfg(10.0, "hybrid")) as exp:
            with pytest.raises(ValueError, match="sampler mismatch"):
                exp.resume()

    def test_pre_round6_sidecar_defaults_to_exact(self, tmp_path):
        """A checkpoint whose sidecar predates the 'samplers' record was
        written by the exact stream (the only one that existed) — under
        the new hybrid default it must fail loudly, and resume cleanly
        once the config pins sampler="exact"."""
        import json

        from lens_tpu.experiment import Experiment

        def cfg(total, sampler=None):
            c = {
                "composite": "hybrid_cell",
                "n_agents": 4,
                "capacity": 16,
                "total_time": total,
                "seed": 0,
                "checkpoint_dir": str(tmp_path / "ck"),
                "checkpoint_every": 5.0,
                "emitter": {"type": "null"},
            }
            if sampler is not None:
                c["sampler"] = sampler
            return c

        with Experiment(cfg(5.0, sampler="exact")) as exp:
            exp.run()
        # simulate a pre-round-6 sidecar: strip the samplers record
        meta_path = tmp_path / "ck" / "colony_meta.json"
        meta = json.load(open(meta_path))
        del meta["samplers"]
        json.dump(meta, open(meta_path, "w"))
        with Experiment(cfg(10.0)) as exp:  # default -> hybrid
            with pytest.raises(ValueError, match="sampler mismatch"):
                exp.resume()
        with Experiment(cfg(10.0, sampler="exact")) as exp:
            state = exp.resume()
        assert int(state.step) == 10

    def test_toggle_tau_leap_counts_become_integral(self):
        """Fractional ODE-style initial counts are rounded at tau-leap
        entry: after one step the accumulated state is integral and
        stays integral (no permanent phantom half-molecule)."""
        from lens_tpu.models.composites import toggle_colony

        comp = toggle_colony({"toggle_switch": {"method": "tau_leap"}})
        state = comp.initial_state()  # mrna_u=0.5, protein_v=0.1, ...
        key = jax.random.PRNGKey(9)
        for i in range(5):
            key, sub = jax.random.split(key)
            state = comp.step(state, 1.0, key=sub)
        vals = np.asarray(
            [float(state["cell"][k]) for k in
             ("mrna_u", "protein_u", "mrna_v", "protein_v")]
        )
        np.testing.assert_array_equal(vals, np.round(vals))

    def test_sampler_knob_changes_trajectory_not_contract(self):
        """exact and hybrid draw from the SAME distributions through
        DIFFERENT key consumption: trajectories differ, physics holds."""
        from lens_tpu.experiment import Experiment

        outs = {}
        for sampler in ("exact", "hybrid"):
            with Experiment({
                "composite": "hybrid_cell",
                "sampler": sampler,
                "n_agents": 8,
                "capacity": 32,
                "total_time": 20.0,
                "emit_every": 20,
                "seed": 4,
            }) as exp:
                outs[sampler] = exp.run()
        pa = np.asarray(outs["exact"].agents["counts"]["protein"])
        pb = np.asarray(outs["hybrid"].agents["counts"]["protein"])
        assert not np.array_equal(pa, pb)
        np.testing.assert_array_equal(pa, np.round(pa))
        np.testing.assert_array_equal(pb, np.round(pb))
