"""Sweep benchmark: trials/s + lane occupancy vs one-at-a-time serving.

What the sweep layer buys over the pre-sweep workflow (run one
scenario, wait, run the next): both sides do IDENTICAL simulation work
— the same deterministic trial list, same composite, same horizon —
but the baseline drives a 1-lane server one request at a time
(submit, drain, submit), while the sweep drives an L-lane server
through ``lens_tpu.sweep.run_sweep`` with bounded in-flight
concurrency, so trials co-batch onto the resident vmapped window
program. The ratio is the sweep subsystem's throughput claim; lane
occupancy says how much of it the scheduler actually kept busy.

Protocol (same conventions as bench_serve.py): INTERLEAVED min-of-reps
— baseline and sweep alternate within each rep so this host's ±20%
wall-clock wander hits both alike, min taken across reps; servers are
built and warmed ONCE per configuration with warmup samples dropped,
so compiles never land in a timed phase. Three sweep sizes by default;
occupancy is computed from counter deltas over the measured phase only.

Writes ``BENCH_SWEEP_CPU_r09.json`` (or ``--out``).
"""

from __future__ import annotations

import argparse
import json
import time

import jax

from lens_tpu.serve import ScenarioRequest, SimServer
from lens_tpu.sweep import run_sweep, space_from_spec


def _sweep_spec(composite, capacity, n_trials, horizon, emit_every):
    """One deterministic spec per size: a random volume space (content
    is irrelevant to timing; random keeps the per-trial override
    distinct, like a real search)."""
    return {
        "composite": composite,
        "space": {
            "kind": "random",
            "n_trials": n_trials,
            "params": {
                "global/volume": {"low": 0.8, "high": 1.3},
            },
        },
        "seed": 0,
        "horizon": float(horizon),
        "emit_every": emit_every,
        "capacity": capacity,
        "objective": {
            "path": "global/volume",
            "reduction": "final_live_sum",
            "mode": "max",
        },
        "backend": {"kind": "server"},
    }


def _occupancy_delta(before, after):
    busy = (
        after["counters"]["lane_windows_busy"]
        - before["counters"]["lane_windows_busy"]
    )
    total = (
        after["counters"]["lane_windows_total"]
        - before["counters"]["lane_windows_total"]
    )
    return busy / max(total, 1)


def run_baseline(server, spec, trials) -> float:
    """One-at-a-time: each trial fully drains before the next submits —
    the pre-sweep workflow, on the same serving machinery so scheduler
    overhead cancels out of the comparison."""
    t0 = time.perf_counter()
    for t in trials:
        rid = server.submit(ScenarioRequest(
            composite=spec["composite"],
            seed=t.seed,
            horizon=spec["horizon"],
            overrides=t.overrides(),
            emit={"paths": ["global/volume", "alive"]},
        ))
        server.run_until_idle(max_ticks=100_000)
        assert server.status(rid)["status"] == "done"
    return time.perf_counter() - t0


def run_swept(server, spec) -> float:
    t0 = time.perf_counter()
    result = run_sweep(spec, server=server)
    assert all(r["status"] == "done" for r in result.table)
    return time.perf_counter() - t0


def bench_size(
    base_server, sweep_server, spec, n_trials, reps
) -> dict:
    trials = space_from_spec(spec["space"]).trials(spec["seed"])
    base_wall = sweep_wall = float("inf")
    occ0 = sweep_server.metrics()
    for _ in range(reps):
        base_wall = min(
            base_wall, run_baseline(base_server, spec, trials)
        )
        sweep_wall = min(sweep_wall, run_swept(sweep_server, spec))
    occ = _occupancy_delta(occ0, sweep_server.metrics())
    return {
        "n_trials": n_trials,
        "baseline_wall_s": round(base_wall, 4),
        "sweep_wall_s": round(sweep_wall, 4),
        "baseline_trials_per_s": round(n_trials / base_wall, 3),
        "sweep_trials_per_s": round(n_trials / sweep_wall, 3),
        "speedup": round(base_wall / sweep_wall, 3),
        "sweep_occupancy": round(occ, 4),
        "retraces": sweep_server.metrics()["retraces"],
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--composite", default="toggle_colony")
    # Defaults target the sweep's home regime on this 1-core CPU box:
    # many SMALL scenarios (an 8-row bucket ~ a single-cell trial),
    # sparse emission (the objective reads the final frame), horizons
    # long enough to amortize per-trial admission. Bigger buckets are
    # compute-bound on one core, where vmapped lanes cannot add FLOPs
    # — the speedup there comes back on accelerators, where idle lane
    # compute is genuinely parallel (see docs/sweeps.md).
    p.add_argument("--capacity", type=int, default=8)
    p.add_argument("--lanes", type=int, default=8)
    p.add_argument("--window", type=int, default=32)
    p.add_argument("--emit-every", type=int, default=32)
    p.add_argument(
        "--horizon-windows", type=int, default=12,
        help="trial horizon in windows",
    )
    p.add_argument(
        "--sizes", type=int, nargs="+", default=[16, 32, 64],
        help="sweep sizes (trials) to measure",
    )
    p.add_argument("--reps", type=int, default=5)
    p.add_argument("--out", default="BENCH_SWEEP_CPU_r09.json")
    args = p.parse_args()

    horizon = args.horizon_windows * args.window
    record = {
        "bench": "sweep",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "lanes": args.lanes,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon,
        "reps": args.reps,
        "protocol": "interleaved min-of-reps; shared warmed servers; "
        "baseline = same trials one-at-a-time on 1 lane",
        "sizes": [],
    }

    def make_server(lanes):
        srv = SimServer.single_bucket(
            args.composite,
            capacity=args.capacity,
            lanes=lanes,
            window=args.window,
            emit_every=args.emit_every,
            queue_depth=max(4 * args.lanes, 2 * max(args.sizes)),
        )
        # compile builder + admit + window once, outside every timed
        # phase (overrides match the sweep's structure so the jitted
        # solo builder is warm too)
        for s in range(lanes):
            srv.submit(ScenarioRequest(
                composite=args.composite, seed=s,
                horizon=float(args.window),
                overrides={"global": {"volume": 1.0}},
            ))
        srv.run_until_idle(max_ticks=1000)
        srv.reset_samples()
        return srv

    base_server = make_server(1)
    sweep_server = make_server(args.lanes)

    for n in args.sizes:
        spec = _sweep_spec(
            args.composite, args.capacity, n, horizon, args.emit_every
        )
        entry = bench_size(
            base_server, sweep_server, spec, n, args.reps
        )
        record["sizes"].append(entry)
        print(json.dumps(entry), flush=True)

    base_server.close()
    sweep_server.close()
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    worst = min(e["speedup"] for e in record["sizes"])
    print(
        f"worst sweep speedup over one-at-a-time at {args.lanes} "
        f"lanes: {worst:.2f}x"
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
