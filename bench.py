"""Headline benchmark: agent-steps/sec/chip on the flagship lattice colony.

Measures the BASELINE.json metric — "agent-steps/sec/chip (10k-agent
E. coli colony, dt=1s)" — on whatever accelerator jax's default backend
provides (the driver runs this on one real TPU chip). The model is the
config-2 flagship: Michaelis–Menten transport + growth + division +
Brownian motility on a 256x256 glucose diffusion lattice, 10,240 agents.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` is measured against the north-star target of 10,000
agent-steps/sec/chip.
"""

from __future__ import annotations

import json
import os
import time

NORTH_STAR = 10_000.0  # agent-steps/sec/chip (BASELINE.json north_star)


def main() -> None:
    import jax

    from lens_tpu.models import ecoli_lattice

    capacity = int(os.environ.get("BENCH_AGENTS", 10240))
    sim_seconds = float(os.environ.get("BENCH_SIM_SECONDS", 32.0))
    spatial, _ = ecoli_lattice({"capacity": capacity})

    ss = spatial.initial_state(capacity, jax.random.PRNGKey(0))

    @jax.jit
    def window(state):
        state, _ = spatial.run(state, sim_seconds, 1.0, emit_every=int(sim_seconds))
        return state

    # Warm-up: compile + one full window (also primes the persistent cache).
    ss = jax.block_until_ready(window(ss))

    t0 = time.perf_counter()
    ss = jax.block_until_ready(window(ss))
    elapsed = time.perf_counter() - t0

    agent_steps = capacity * sim_seconds  # dt=1s -> one agent-step per sim-sec
    value = agent_steps / elapsed
    print(
        json.dumps(
            {
                "metric": "agent-steps/sec/chip (10k-agent E. coli colony, dt=1s)",
                "value": round(value, 1),
                "unit": "agent-steps/sec/chip",
                "vs_baseline": round(value / NORTH_STAR, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
