"""Headline benchmark: agent-steps/sec/chip on the flagship lattice colony.

Measures the BASELINE.json metric — "agent-steps/sec/chip (10k-agent
E. coli colony, dt=1s)" — on whatever accelerator jax's default backend
provides (the driver runs this on one real TPU chip). The model is the
config-2 flagship: Michaelis–Menten transport + growth + division +
Brownian motility on a 256x256 glucose diffusion lattice, 10,240 agents.

Prints exactly one JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "backend": ...}

The reference publishes no numbers (BASELINE.json ``published: {}``), so
``vs_baseline`` is measured against the north-star target of 10,000
agent-steps/sec/chip.

Robustness (round-1 lesson): this box's ``axon`` TPU relay is flaky; a
dead relay makes backend init raise Unavailable or hang forever, and its
PJRT hook ignores ``JAX_PLATFORMS``. The *measurement* therefore runs in
a child subprocess with a bounded timeout — a hung relay can only burn
that timeout, never wedge the reporting process. If the accelerator
child fails or times out, a second child re-measures on the pinned CPU
backend (reported honestly via ``"backend"``, read from
``jax.default_backend()`` inside the measuring process). The parent
always prints one parseable JSON line and exits 0.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

NORTH_STAR = 10_000.0  # agent-steps/sec/chip (BASELINE.json north_star)
METRIC = "agent-steps/sec/chip (10k-agent E. coli colony, dt=1s)"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _emit(payload: dict) -> None:
    print(json.dumps(payload), flush=True)


def _measure() -> None:
    """Child-process mode: init a backend, measure, print one JSON line."""
    if os.environ.get("BENCH_FORCE_CPU"):
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax

    from lens_tpu.models import ecoli_lattice

    capacity = int(os.environ.get("BENCH_AGENTS", 10240))
    sim_seconds = float(os.environ.get("BENCH_SIM_SECONDS", 32.0))
    spatial, _ = ecoli_lattice({"capacity": capacity})

    ss = spatial.initial_state(capacity, jax.random.PRNGKey(0))

    @jax.jit
    def window(state):
        state, _ = spatial.run(state, sim_seconds, 1.0, emit_every=int(sim_seconds))
        return state

    # Warm-up: compile + one full window (also primes the persistent cache).
    ss = jax.block_until_ready(window(ss))

    t0 = time.perf_counter()
    ss = jax.block_until_ready(window(ss))
    elapsed = time.perf_counter() - t0

    agent_steps = capacity * sim_seconds  # dt=1s -> one agent-step per sim-sec
    value = agent_steps / elapsed
    _emit(
        {
            "metric": METRIC,
            "value": round(value, 1),
            "unit": "agent-steps/sec/chip",
            "vs_baseline": round(value / NORTH_STAR, 3),
            "backend": jax.default_backend(),
        }
    )


def _run_child(force_cpu: bool, timeout: float) -> dict:
    """Run ``bench.py --measure`` in a subprocess; parse its JSON line."""
    env = dict(os.environ)
    if force_cpu:
        env["BENCH_FORCE_CPU"] = "1"
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--measure"],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"measurement timed out after {timeout:.0f}s"}
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and "value" in row:
            return row
    tail = (r.stderr or r.stdout or "").strip().splitlines()[-3:]
    return {"error": f"rc={r.returncode}: " + " | ".join(tail)[:400]}


def main() -> None:
    # Only a HANG short-circuits to the CPU fallback: a probe that fails
    # fast costs nothing to re-run in the real accel child, which then
    # captures the genuine error text for `accel_error`.
    from lens_tpu.utils.platform import backend_probe_hangs

    if backend_probe_hangs(_env_float("BENCH_PROBE_TIMEOUT", 90.0)):
        row = {"error": "accelerator backend init hung (relay down?)"}
    else:
        row = _run_child(
            force_cpu=False, timeout=_env_float("BENCH_ACCEL_TIMEOUT", 900.0)
        )
    if "error" in row:
        accel_error = row["error"]
        row = _run_child(
            force_cpu=True, timeout=_env_float("BENCH_CPU_TIMEOUT", 900.0)
        )
        if "error" not in row:
            row["accel_error"] = accel_error[:300]
        else:
            row = {
                "metric": METRIC,
                "value": 0.0,
                "unit": "agent-steps/sec/chip",
                "vs_baseline": 0.0,
                "backend": "none",
                "error": f"accel: {accel_error[:200]}; cpu: {row['error'][:200]}",
            }
    _emit(row)


if __name__ == "__main__":
    if "--measure" in sys.argv:
        _measure()
        raise SystemExit(0)
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — contract: one JSON line, always
        _emit(
            {
                "metric": METRIC,
                "value": 0.0,
                "unit": "agent-steps/sec/chip",
                "vs_baseline": 0.0,
                "backend": "none",
                "error": f"{type(e).__name__}: {e}"[:500],
            }
        )
        raise SystemExit(0)
