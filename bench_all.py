"""Benchmark all five BASELINE.json configs; write a JSON report.

Supplementary to ``bench.py`` (the driver's one-line headline metric —
config 2). Each config runs as its BASELINE scenario on whatever backend
jax provides, measuring steady-state throughput after one warm-up window
(compile + cache). Output: one JSON object per line to stdout, plus
``BENCH_ALL.json`` with the full report.

    python bench_all.py                    # all configs
    python bench_all.py 0 4                # a subset
    python bench_all.py --sampler=exact 4  # pin the Poisson sampler
    python bench_all.py --coupling=reference 4  # pin the coupling impl

``--sampler=exact|hybrid`` threads the expression-stack sampler knob
(ops.sampling) into the composites that carry stochastic expression
(configs 3b/3p/3c/4) — the A/B lever for the round-6 hybrid-sampler
fast path. Default: composite defaults (hybrid since round 6). It also
reaches config 1's toggle_colony, where it is INERT under the default
ODE integrator (the toggle reads it only under method="tau_leap").

``--coupling=fused|reference`` threads the agent<->lattice coupling
implementation (environment.spatial CouplingPlan) into the lattice
configs (2/2e/3b/3p/3c/4/xf) — the A/B lever for the round-7 fused
coupling. Default: composite defaults (fused since round 7). Non-lattice
configs (0/1/3) carry no coupling and ignore it.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")

WINDOW_S = 32.0  # sim-seconds measured per config (dt = 1s)

#: set by --sampler=...; None = composite defaults
_SAMPLER: str | None = None

#: set by --coupling=...; None = composite defaults ("fused")
_COUPLING: str | None = None


def _knob_cfg() -> dict:
    """Composite-config fragment for every CLI A/B knob (--sampler,
    --coupling) — spread into each config's composite call so the
    levers reach every lattice/expression composite uniformly."""
    cfg = {"sampler": _SAMPLER} if _SAMPLER else {}
    if _COUPLING:
        cfg["coupling"] = _COUPLING
    return cfg


def _measure(build_window, n_agents):
    """build_window() -> (state, window_fn); returns agent-steps/sec."""
    import jax

    state, window = build_window()
    state = jax.block_until_ready(window(state))  # warm-up: compile + run
    t0 = time.perf_counter()
    jax.block_until_ready(window(state))
    elapsed = time.perf_counter() - t0
    return n_agents * WINDOW_S / elapsed, elapsed


def config_0():
    """Single agent, 2-species glucose ODE, 100 sim-sec (the CPU anchor)."""
    import jax

    from lens_tpu.models.composites import minimal_ode

    comp = minimal_ode({})
    state = comp.initial_state()
    window = jax.jit(lambda s: comp.run(s, 100.0, 1.0, emit_every=100)[0])
    state = jax.block_until_ready(window(state))  # warm-up
    t0 = time.perf_counter()
    jax.block_until_ready(window(state))
    elapsed = time.perf_counter() - t0
    return {
        "config": 0,
        "scenario": "1 agent, glucose ODE, 100 sim-sec",
        "metric": "wall seconds / 100 sim-sec",
        "value": round(elapsed, 4),
    }


def config_1():
    import jax

    from lens_tpu.colony.colony import Colony
    from lens_tpu.models.composites import toggle_colony

    n = 1024
    colony = Colony(toggle_colony(_knob_cfg()), capacity=n)

    def build():
        state = colony.initial_state(n, key=jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: colony.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, elapsed = _measure(build, n)
    return {
        "config": 1,
        "scenario": "1k-agent toggle-switch colony, no lattice",
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def config_2():
    import jax

    from lens_tpu.models.composites import ecoli_lattice

    n = 10240
    spatial, _ = ecoli_lattice({"capacity": n, **_knob_cfg()})

    def build():
        state = spatial.initial_state(n, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, elapsed = _measure(build, n)
    return {
        "config": 2,
        "scenario": "10k agents, 256x256 lattice, MM transport (headline)",
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def config_3():
    import jax

    from lens_tpu.colony.colony import Colony
    from lens_tpu.models.composites import minimal_wcecoli

    n = 256
    colony = Colony(
        minimal_wcecoli({}), capacity=1024,
        division_trigger=("global", "divide"),
    )

    def build():
        state = colony.initial_state(
            n, key=jax.random.PRNGKey(0),
            overrides={"metabolites": {"glc": 50.0}},
        )
        window = jax.jit(
            lambda s: colony.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, elapsed = _measure(build, n)
    return {
        "config": 3,
        "scenario": "wcEcoli-minimal composite, 256 agents, division",
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def _rfba_bench(key, n, metabolism, genes, scenario):
    """Shared scaffold for the rFBA configs (3b/3p/3c): one protocol —
    same warm-up, window, emit cadence — so the configs differ ONLY in
    the composite config, which is the comparison they exist to make."""
    import jax

    from lens_tpu.models.composites import rfba_lattice

    spatial, _ = rfba_lattice(
        {
            "capacity": n,
            "shape": (64, 64),
            "metabolism": metabolism,
            "expression": {"genes": genes},
            **_knob_cfg(),
        }
    )

    def build():
        state = spatial.initial_state(n, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, _ = _measure(build, n)
    return {
        "config": key,
        "scenario": scenario,
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def config_3b():
    """Config 3 at reference model scale: each agent solves the
    24-metabolite x 35-reaction ecoli_core regulated-FBA LP AND steps a
    32-gene stochastic expression model, every second, with division."""
    return _rfba_bench(
        "3b", 1024, {"network": "ecoli_core"}, "ecoli_core",
        "1k agents, ecoli_core rFBA LP (24x35, adaptive IPM, 45-iter "
        "cap) + 32-gene expression per agent per step, 64x64 lattice, "
        "division",
    )


def config_3p():
    """Config 3b with the first-order PDLP solver (lp_solver="pdlp",
    sparse segment-sum matvecs) instead of the dense IPM — the
    composite-level half of the bench_lp_scale crossover: on the MXU the
    batched [N,R]@[R,M] matmul form competes against batched small
    Cholesky factorizations at reference scale."""
    return _rfba_bench(
        "3p", 1024,
        {"network": "ecoli_core", "lp_solver": "pdlp"}, "ecoli_core",
        "config 3b biology with the first-order PDLP FBA solver "
        "(warm-started sparse PDHG per agent per step)",
    )


def config_3c():
    """Config 3b at FULL network scale: each agent solves the canonical
    e_coli_core LP (72 metabolites x 95 reactions) and steps the 285-gene
    expression table, every second, with division — the wcEcoli-direction
    frontier (VERDICT r4 missing #3). 256 agents: the per-agent cost is
    ~35x config 3b's, so the population is kept small enough that a CPU
    fallback run still finishes inside the queue's per-script budget."""
    return _rfba_bench(
        "3c", 256,
        {"network": "ecoli_core_full"}, "ecoli_core_full",
        "256 agents, FULL e_coli_core rFBA LP (72x95) + 285-gene "
        "expression per agent per step, 64x64 lattice, division",
    )


def config_4():
    """100k-cell MIXED-SPECIES colony: two distinct process sets (ODE
    kinetics vs hybrid Gillespie+ODE) on one 256x256 two-molecule lattice
    — the genuinely heterogeneous north-star scenario."""
    import jax

    from lens_tpu.models.composites import mixed_species_lattice

    n_each = 50_000
    multi, _ = mixed_species_lattice(
        {
            "capacity": {"ecoli": 51200, "scavenger": 51200},
            "shape": (256, 256),
            **_knob_cfg(),
        }
    )

    def build():
        state = multi.initial_state(
            {"ecoli": n_each, "scavenger": n_each}, jax.random.PRNGKey(0)
        )
        window = jax.jit(
            lambda s: multi.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, elapsed = _measure(build, 2 * n_each)
    return {
        "config": 4,
        "scenario": "100k mixed-species colony, 2 process sets, "
        "256x256 lattice (north star)",
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def config_xf():
    """Cross-feeding at network scale (rfba_cross_feeding): 1k exact-rFBA
    cells (core-carbon LP per cell per step) + 1k kinetic scavengers on
    one 64x64 lattice — the heterogeneous-biology frontier beyond
    BASELINE's configs (per-agent LP for half the population)."""
    import jax

    from lens_tpu.models.composites import rfba_cross_feeding

    n_each = 1024
    multi, _ = rfba_cross_feeding(
        {
            "capacity": {"ecoli": n_each, "scavenger": n_each},
            "shape": (64, 64),
            **_knob_cfg(),
        }
    )

    def build():
        state = multi.initial_state(
            {"ecoli": n_each, "scavenger": n_each}, jax.random.PRNGKey(0)
        )
        window = jax.jit(
            lambda s: multi.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    rate, elapsed = _measure(build, 2 * n_each)
    return {
        "config": "xf",
        "scenario": "rFBA cross-feeding: 1k LP cells + 1k scavengers, "
        "64x64 lattice (network-scale syntrophy)",
        "metric": "agent-steps/sec",
        "value": round(rate, 1),
    }


def config_2e():
    """Config 2 with DENSE emission: every step's emit slice is produced
    and materialized (the reference's every-step MongoDB emit pattern,
    SURVEY.md §3.5). The window returns the trajectory, so XLA cannot
    dead-code-eliminate the emit work; the gap to config 2 is the
    emission cost."""
    import jax

    from lens_tpu.models.composites import ecoli_lattice

    n = 10240
    spatial, _ = ecoli_lattice({"capacity": n, **_knob_cfg()})

    def build():
        state = spatial.initial_state(n, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, WINDOW_S, 1.0, emit_every=1)
        )
        return state, window

    import time

    state, window = build()
    state, traj = jax.block_until_ready(window(state))  # warm-up
    t0 = time.perf_counter()
    jax.block_until_ready(window(state))
    elapsed = time.perf_counter() - t0
    return {
        "config": "2e",
        "scenario": "config 2 with emit_every=1 (dense per-step emission, "
        "trajectory materialized)",
        "metric": "agent-steps/sec",
        "value": round(n * WINDOW_S / elapsed, 1),
    }


CONFIGS = {
    0: config_0,
    1: config_1,
    2: config_2,
    "2e": config_2e,
    3: config_3,
    "3b": config_3b,
    "3p": config_3p,
    "3c": config_3c,
    4: config_4,
    "xf": config_xf,
}


def _probe_backend(timeout: float = 180.0) -> str | None:
    """Backend platform name via a subprocess (a hung relay burns only the
    timeout), or None if init fails/times out."""
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired:
        return None
    if r.returncode == 0:
        for line in reversed(r.stdout.strip().splitlines()):
            if line.startswith("PLATFORM="):
                return line.split("=", 1)[1]
    return None


def main() -> None:
    # Backend robustness: probe in a subprocess; pin CPU if the
    # accelerator never comes up. (The probe-then-init window is racy —
    # bench.py, the driver artifact, measures in a timed child instead;
    # this supplementary report accepts the residual risk.)
    platform = _probe_backend()
    if platform is None or platform == "cpu":
        from lens_tpu.utils.platform import force_cpu_platform

        force_cpu_platform(1)

    import jax

    def _key(a: str):
        return int(a) if a.isdigit() else a

    global _SAMPLER, _COUPLING
    args = []
    for a in sys.argv[1:]:
        if a.startswith("--sampler="):
            _SAMPLER = a.split("=", 1)[1]
        elif a.startswith("--coupling="):
            _COUPLING = a.split("=", 1)[1]
        else:
            args.append(a)
    wanted = [_key(a) for a in args] or list(CONFIGS)
    report = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "sampler": _SAMPLER or "default",
        "coupling": _COUPLING or "default",
        "results": [],
    }
    for k in wanted:
        try:
            row = CONFIGS[k]()
        except Exception as e:  # noqa: BLE001 — report per-config, keep going
            row = {"config": k, "error": f"{type(e).__name__}: {e}"[:500]}
        report["results"].append(row)
        print(json.dumps(row), flush=True)
        # write incrementally so an interrupt never loses finished configs
        with open("BENCH_ALL.json", "w") as f:
            json.dump(report, f, indent=2)


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — always leave a parseable trail
        row = {"error": f"{type(e).__name__}: {e}"[:500]}
        print(json.dumps(row), flush=True)
        if not os.path.exists("BENCH_ALL.json"):
            with open("BENCH_ALL.json", "w") as f:
                json.dump({"backend": "none", "results": [row]}, f, indent=2)
        raise SystemExit(0)
