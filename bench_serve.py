"""Serving benchmark: req/s + latency vs the bare Ensemble.run ceiling.

Measures what the serve layer costs over the raw device program it
wraps. Three kinds of record, written to ``BENCH_SERVE_CPU_r10.json``
(or ``--out``):

1. **Saturation A/B** (per lane count L, per pipeline mode): the bare
   ceiling — an ``Ensemble(sim, L).run`` of the same composite for the
   same steps, in row-steps/s — against the served throughput with
   every lane occupied for the whole measurement (N = fill_rounds * L
   equal-horizon requests, so lanes retire and refill in lockstep and
   occupancy stays 1.0 until the drain tail). ``served_over_ceiling``
   is the acceptance ratio: everything the scheduler adds shows up as
   the gap to 1.0. Round 10 reports PIPELINED vs SYNC rows
   interleaved (same warmed servers alternating per rep), plus the
   new ``device_busy_fraction`` and stream-lag/host-gap columns from
   the ``ServerMetrics`` stream samples — the direct measurement of
   how much of the r08 host gap the pipeline recovered.
2. **Offered-load sweep** (per L, pipelined): requests arriving at a
   paced rate (0.5x / 0.9x / 1.5x the measured saturated req/s),
   p50/p95/p99 request latency + queue wait per load, plus reject
   counts at the bounded queue — the latency-under-load curve a
   capacity planner reads.

Composite: ``toggle_colony`` (config-1 cell; deterministic, light
biology) — the point is to measure the SERVING machinery, not the
biology, so the cheapest real composite gives the most sensitive
ratio. Window/capacity are CLI-tunable for heavier sweeps.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import numpy as np

from lens_tpu.colony.ensemble import Ensemble
from lens_tpu.experiment import build_model
from lens_tpu.serve import QueueFull, ScenarioRequest, SimServer


def _make_server(composite, capacity, lanes, window, emit_every,
                 queue_depth, pipeline):
    return SimServer.single_bucket(
        composite,
        capacity=capacity,
        lanes=lanes,
        window=window,
        emit_every=emit_every,
        queue_depth=queue_depth,
        pipeline=pipeline,
    )


def _warm(srv, composite, lanes, window) -> None:
    """Compile the admit + window programs with a throwaway round, then
    drop its samples so the measured phase's latency percentiles and
    occupancy are not diluted by short warmup requests."""
    for s in range(lanes):
        srv.submit(ScenarioRequest(
            composite=composite, seed=s, horizon=float(window)
        ))
    srv.run_until_idle(max_ticks=100)
    srv.reset_samples()


def _occupancy_window(srv):
    c = srv.metrics()["counters"]
    return c["lane_windows_busy"], c["lane_windows_total"]


def _serve_round(srv, composite, n, horizon_steps, seed0):
    """Submit n equal-horizon requests, run to idle, return wall."""
    t0 = time.perf_counter()
    ids = [
        srv.submit(ScenarioRequest(
            composite=composite, seed=seed0 + i,
            horizon=float(horizon_steps),
        ))
        for i in range(n)
    ]
    srv.run_until_idle(max_ticks=100_000)
    wall = time.perf_counter() - t0
    assert all(srv.status(r)["status"] == "done" for r in ids)
    return wall


def saturation_point(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, fill_rounds: int,
    reps: int = 3,
):
    """The per-lane-count saturation record: ceiling vs served for BOTH
    pipeline modes, INTERLEAVED min-of-reps (this host's wall clock
    wanders ±20% — same protocol as bench_phases). Each rep times the
    bare ensemble ceiling, the pipelined server, and the synchronous
    server back to back on the same warmed programs.

    Ceiling: ``Ensemble.run`` at the serve bucket's exact shapes (same
    emit cadence, plus a ``device_get`` of the trajectory, so the
    device->host transfer the server also pays is inside the ceiling,
    not counted against serving).
    """
    sim = build_model(composite, {}, capacity=capacity).sim
    ens = Ensemble(sim, lanes)
    states = ens.initial_state(1, key=jax.random.PRNGKey(0))
    run = jax.jit(
        lambda s: ens.run(
            s, float(horizon_steps), 1.0, emit_every=emit_every
        )
    )
    jax.block_until_ready(run(states)[0])  # compile + warm

    n = fill_rounds * lanes
    depth = max(2 * n, 16)
    servers = {
        mode: _make_server(
            composite, capacity, lanes, window, emit_every, depth, mode
        )
        for mode in ("on", "off")
    }
    for srv in servers.values():
        _warm(srv, composite, lanes, window)
    base = {m: _occupancy_window(s) for m, s in servers.items()}

    ceiling_wall = float("inf")
    served_wall = {"on": float("inf"), "off": float("inf")}
    for rep in range(reps):
        t0 = time.perf_counter()
        final, traj = run(states)
        jax.device_get(traj)
        jax.block_until_ready(final)
        ceiling_wall = min(ceiling_wall, time.perf_counter() - t0)

        for mode, srv in servers.items():
            wall = _serve_round(
                srv, composite, n, horizon_steps,
                seed0=100 + rep * 2 * n + (0 if mode == "on" else n),
            )
            served_wall[mode] = min(served_wall[mode], wall)

    ceiling = lanes * capacity * horizon_steps / ceiling_wall
    rows = []
    for mode, srv in servers.items():
        snap = srv.metrics()
        busy0, total0 = base[mode]
        served = n * horizon_steps * capacity / served_wall[mode]
        lag = snap["stream_lag_seconds"]
        gap = snap["host_gap_seconds"]
        rows.append({
            "lanes": lanes,
            "pipeline": mode,
            "ceiling_row_steps_s": round(ceiling),
            "served_row_steps_s": round(served),
            "served_over_ceiling": round(served / ceiling, 4),
            "saturated_req_s": round(n / served_wall[mode], 2),
            "occupancy": (
                snap["counters"]["lane_windows_busy"] - busy0
            ) / max(snap["counters"]["lane_windows_total"] - total0, 1),
            "retraces": snap["retraces"],
            "device_busy_fraction": (
                None if snap["device_busy_fraction"] is None
                else round(snap["device_busy_fraction"], 4)
            ),
            "stream_lag_p50_s": lag["p50"],
            "host_gap_p50_s": gap["p50"],
            "stream_stalls": snap["stream_stalls"],
            "latency_s": snap["latency_seconds"],
        })
        srv.close()
    return rows


def offered_load(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, rate_req_s: float, n: int,
):
    """Pace ``n`` arrivals at ``rate_req_s``; tick between arrivals.
    Returns latency/wait percentiles + reject count. Rejected requests
    are retried until admitted (the client-backoff model), so every
    request's latency includes its backpressure delay. Pipelined (the
    serving default)."""
    srv = _make_server(
        composite, capacity, lanes, window, emit_every,
        queue_depth=2 * lanes, pipeline="on",
    )
    _warm(srv, composite, lanes, window)
    busy0, total0 = _occupancy_window(srv)

    interval = 1.0 / rate_req_s
    pending = [
        ScenarioRequest(
            composite=composite, seed=1000 + i,
            horizon=float(horizon_steps),
        )
        for i in range(n)
    ]
    rejects = 0
    t0 = time.perf_counter()
    next_arrival = t0
    i = 0
    while i < n:
        now = time.perf_counter()
        if now >= next_arrival:
            try:
                srv.submit(pending[i])
                i += 1
                next_arrival += interval
            except QueueFull:
                rejects += 1  # client retries at the next tick boundary
        srv.tick()
    srv.run_until_idle(max_ticks=100_000)
    wall = time.perf_counter() - t0
    snap = srv.metrics()
    srv.close()
    return {
        "offered_req_s": rate_req_s,
        "achieved_req_s": n / wall,
        "latency_s": snap["latency_seconds"],
        "queue_wait_s": snap["wait_seconds"],
        "rejects": rejects,
        "device_busy_fraction": snap["device_busy_fraction"],
        "stream_lag_p50_s": snap["stream_lag_seconds"]["p50"],
        "occupancy": (
            snap["counters"]["lane_windows_busy"] - busy0
        ) / max(snap["counters"]["lane_windows_total"] - total0, 1),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--composite", default="toggle_colony")
    # 256-row buckets: small enough to serve interactively, big enough
    # that the window's device work is representative (a 32-row bucket
    # measures Python dispatch, not serving — see the README of
    # BENCH_SERVE record for the overhead-dominated small-bucket point)
    p.add_argument("--capacity", type=int, default=256)
    p.add_argument("--window", type=int, default=64)
    p.add_argument("--emit-every", type=int, default=8)
    p.add_argument(
        "--lanes", type=int, nargs="+", default=[2, 4, 8]
    )
    p.add_argument(
        "--horizon-windows", type=int, default=6,
        help="request horizon in windows",
    )
    p.add_argument("--fill-rounds", type=int, default=4)
    p.add_argument("--sweep-n", type=int, default=48)
    p.add_argument("--out", default="BENCH_SERVE_CPU_r10.json")
    args = p.parse_args()

    horizon_steps = args.horizon_windows * args.window
    record = {
        "bench": "serve",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "saturation": [],
        "offered_load": [],
    }

    for lanes in args.lanes:
        rows = saturation_point(
            args.composite, args.capacity, lanes, args.window,
            args.emit_every, horizon_steps, args.fill_rounds,
        )
        for entry in rows:
            record["saturation"].append(entry)
            print(json.dumps(entry), flush=True)

        piped = next(r for r in rows if r["pipeline"] == "on")
        for frac in (0.5, 0.9, 1.5):
            sweep = offered_load(
                args.composite, args.capacity, lanes, args.window,
                args.emit_every, horizon_steps,
                rate_req_s=max(frac * piped["saturated_req_s"], 0.5),
                n=args.sweep_n,
            )
            sweep["lanes"] = lanes
            sweep["load_fraction"] = frac
            record["offered_load"].append(sweep)
            print(json.dumps(sweep), flush=True)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    for mode in ("on", "off"):
        worst = min(
            e["served_over_ceiling"]
            for e in record["saturation"] if e["pipeline"] == mode
        )
        print(f"worst served/ceiling (pipeline {mode}): {worst:.3f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
