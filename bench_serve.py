"""Serving benchmark: req/s + latency vs the bare Ensemble.run ceiling.

Measures what the serve layer costs over the raw device program it
wraps. Three kinds of record, written to ``BENCH_SERVE_CPU_r10.json``
(or ``--out``):

1. **Saturation A/B** (per lane count L, per pipeline mode): the bare
   ceiling — an ``Ensemble(sim, L).run`` of the same composite for the
   same steps, in row-steps/s — against the served throughput with
   every lane occupied for the whole measurement (N = fill_rounds * L
   equal-horizon requests, so lanes retire and refill in lockstep and
   occupancy stays 1.0 until the drain tail). ``served_over_ceiling``
   is the acceptance ratio: everything the scheduler adds shows up as
   the gap to 1.0. Round 10 reports PIPELINED vs SYNC rows
   interleaved (same warmed servers alternating per rep), plus the
   new ``device_busy_fraction`` and stream-lag/host-gap columns from
   the ``ServerMetrics`` stream samples — the direct measurement of
   how much of the r08 host gap the pipeline recovered.
2. **Offered-load sweep** (per L, pipelined): requests arriving at a
   paced rate (0.5x / 0.9x / 1.5x the measured saturated req/s),
   p50/p95/p99 request latency + queue wait per load, plus reject
   counts at the bounded queue — the latency-under-load curve a
   capacity planner reads.
3. **Prefix-fork A/B** (``--prefix`` mode, round 11, written to
   ``BENCH_FORK_CPU_r11.json``): N requests sharing a
   ``prefix_frac``-of-horizon scenario prefix, served cached (one
   coalesced prefix run + N forked suffixes through the snapshot
   store) vs uncached (every request re-simulates from t=0) —
   interleaved min-of-reps, a fresh prefix seed per rep so every
   cached round pays exactly one prefix run. Plus a warmup-sharing
   sweep A/B: the same trial list through ``lens_tpu.sweep`` with and
   without the spec's ``warmup`` block.

4. **Observability A/B** (``--trace`` mode, round 14, written to
   ``BENCH_OBS_CPU_r14.json``): the same saturated round with span
   tracing + every-tick metrics sampling on vs off — the overhead
   contract of docs/observability.md (on <= 2%, off bitwise equal,
   pinned by a byte-equal request on both servers).

5. **Front-door load + chaos** (``--frontdoor`` mode, round 15,
   written to ``BENCH_FRONTDOOR_CPU_r15.json``): the HTTP layer under
   1000 concurrent keep-alive clients split across 3 tenants — one
   interactive ("gold", weight 2), one batch ("silver"), one FLOODING
   ("flood": rate-limited + quota'd, submitting with 429-honoring
   retries) — recording per-tenant p50/p95/p99 submit→first-byte and
   submit→done over the SSE record stream, plus reject/throttle
   counts (the pushback must land on the flooding tenant ONLY). A
   second CHAOS row repeats the load on a mesh=2 server with a
   ``device_down`` + sink ``io_error`` FaultPlan injected mid-flight:
   the SLO is that every non-faulted request completes and every
   completed request's streamed bytes equal its on-disk log
   (docs/serving.md, "Front door").

Composite: ``toggle_colony`` (config-1 cell; deterministic, light
biology) — the point is to measure the SERVING machinery, not the
biology, so the cheapest real composite gives the most sensitive
ratio. Window/capacity are CLI-tunable for heavier sweeps.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

if "--frontdoor" in sys.argv and "xla_force_host_platform_device_" \
        "count" not in os.environ.get("XLA_FLAGS", ""):
    # the front-door chaos row runs mesh=2 (device_down failover
    # under HTTP load); simulate the devices on CPU
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()

if "--mesh" in sys.argv and "xla_force_host_platform_device_count" \
        not in os.environ.get("XLA_FLAGS", ""):
    # the mesh bench simulates devices on CPU; the flag must land
    # before jax initializes, so it cannot live behind argparse.
    # Consume digits only up to the first non-digit token — anything
    # after that belongs to OTHER flags (--window 128 must not force
    # 128 simulated devices)
    _sizes = []
    for _a in sys.argv[sys.argv.index("--mesh") + 1:]:
        if not _a.isdigit():
            break
        _sizes.append(int(_a))
    _n = max(_sizes or [8])
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + f" --xla_force_host_platform_device_count={_n}"
    ).strip()

import jax
import numpy as np

from lens_tpu.colony.ensemble import Ensemble
from lens_tpu.experiment import build_model
from lens_tpu.serve import QueueFull, ScenarioRequest, SimServer


def _make_server(composite, capacity, lanes, window, emit_every,
                 queue_depth, pipeline):
    return SimServer.single_bucket(
        composite,
        capacity=capacity,
        lanes=lanes,
        window=window,
        emit_every=emit_every,
        queue_depth=queue_depth,
        pipeline=pipeline,
    )


def _warm(srv, composite, lanes, window) -> None:
    """Compile the admit + window programs with a throwaway round, then
    drop its samples so the measured phase's latency percentiles and
    occupancy are not diluted by short warmup requests."""
    for s in range(lanes):
        srv.submit(ScenarioRequest(
            composite=composite, seed=s, horizon=float(window)
        ))
    srv.run_until_idle(max_ticks=100)
    srv.reset_samples()


def _occupancy_window(srv):
    c = srv.metrics()["counters"]
    return c["lane_windows_busy"], c["lane_windows_total"]


def _serve_round(srv, composite, n, horizon_steps, seed0):
    """Submit n equal-horizon requests, run to idle, return wall."""
    t0 = time.perf_counter()
    ids = [
        srv.submit(ScenarioRequest(
            composite=composite, seed=seed0 + i,
            horizon=float(horizon_steps),
        ))
        for i in range(n)
    ]
    srv.run_until_idle(max_ticks=100_000)
    wall = time.perf_counter() - t0
    assert all(srv.status(r)["status"] == "done" for r in ids)
    return wall


def saturation_point(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, fill_rounds: int,
    reps: int = 3,
):
    """The per-lane-count saturation record: ceiling vs served for BOTH
    pipeline modes, INTERLEAVED min-of-reps (this host's wall clock
    wanders ±20% — same protocol as bench_phases). Each rep times the
    bare ensemble ceiling, the pipelined server, and the synchronous
    server back to back on the same warmed programs.

    Ceiling: ``Ensemble.run`` at the serve bucket's exact shapes (same
    emit cadence, plus a ``device_get`` of the trajectory, so the
    device->host transfer the server also pays is inside the ceiling,
    not counted against serving).
    """
    sim = build_model(composite, {}, capacity=capacity).sim
    ens = Ensemble(sim, lanes)
    states = ens.initial_state(1, key=jax.random.PRNGKey(0))
    run = jax.jit(
        lambda s: ens.run(
            s, float(horizon_steps), 1.0, emit_every=emit_every
        )
    )
    jax.block_until_ready(run(states)[0])  # compile + warm

    n = fill_rounds * lanes
    depth = max(2 * n, 16)
    servers = {
        mode: _make_server(
            composite, capacity, lanes, window, emit_every, depth, mode
        )
        for mode in ("on", "off")
    }
    for srv in servers.values():
        _warm(srv, composite, lanes, window)
    base = {m: _occupancy_window(s) for m, s in servers.items()}

    ceiling_wall = float("inf")
    served_wall = {"on": float("inf"), "off": float("inf")}
    for rep in range(reps):
        t0 = time.perf_counter()
        final, traj = run(states)
        jax.device_get(traj)
        jax.block_until_ready(final)
        ceiling_wall = min(ceiling_wall, time.perf_counter() - t0)

        for mode, srv in servers.items():
            wall = _serve_round(
                srv, composite, n, horizon_steps,
                seed0=100 + rep * 2 * n + (0 if mode == "on" else n),
            )
            served_wall[mode] = min(served_wall[mode], wall)

    ceiling = lanes * capacity * horizon_steps / ceiling_wall
    rows = []
    for mode, srv in servers.items():
        snap = srv.metrics()
        busy0, total0 = base[mode]
        served = n * horizon_steps * capacity / served_wall[mode]
        lag = snap["stream_lag_seconds"]
        gap = snap["host_gap_seconds"]
        rows.append({
            "lanes": lanes,
            "pipeline": mode,
            "ceiling_row_steps_s": round(ceiling),
            "served_row_steps_s": round(served),
            "served_over_ceiling": round(served / ceiling, 4),
            "saturated_req_s": round(n / served_wall[mode], 2),
            "occupancy": (
                snap["counters"]["lane_windows_busy"] - busy0
            ) / max(snap["counters"]["lane_windows_total"] - total0, 1),
            "retraces": snap["retraces"],
            "device_busy_fraction": (
                None if snap["device_busy_fraction"] is None
                else round(snap["device_busy_fraction"], 4)
            ),
            "stream_lag_p50_s": lag["p50"],
            "host_gap_p50_s": gap["p50"],
            "stream_stalls": snap["stream_stalls"],
            "latency_s": snap["latency_seconds"],
        })
        srv.close()
    return rows


def offered_load(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, rate_req_s: float, n: int,
):
    """Pace ``n`` arrivals at ``rate_req_s``; tick between arrivals.
    Returns latency/wait percentiles + reject count. Rejected requests
    are retried until admitted (the client-backoff model), so every
    request's latency includes its backpressure delay. Pipelined (the
    serving default)."""
    srv = _make_server(
        composite, capacity, lanes, window, emit_every,
        queue_depth=2 * lanes, pipeline="on",
    )
    _warm(srv, composite, lanes, window)
    busy0, total0 = _occupancy_window(srv)

    interval = 1.0 / rate_req_s
    pending = [
        ScenarioRequest(
            composite=composite, seed=1000 + i,
            horizon=float(horizon_steps),
        )
        for i in range(n)
    ]
    rejects = 0
    t0 = time.perf_counter()
    next_arrival = t0
    i = 0
    while i < n:
        now = time.perf_counter()
        if now >= next_arrival:
            try:
                srv.submit(pending[i])
                i += 1
                next_arrival += interval
            except QueueFull:
                rejects += 1  # client retries at the next tick boundary
        srv.tick()
    srv.run_until_idle(max_ticks=100_000)
    wall = time.perf_counter() - t0
    snap = srv.metrics()
    srv.close()
    return {
        "offered_req_s": rate_req_s,
        "achieved_req_s": n / wall,
        "latency_s": snap["latency_seconds"],
        "queue_wait_s": snap["wait_seconds"],
        "rejects": rejects,
        "device_busy_fraction": snap["device_busy_fraction"],
        "stream_lag_p50_s": snap["stream_lag_seconds"]["p50"],
        "occupancy": (
            snap["counters"]["lane_windows_busy"] - busy0
        ) / max(snap["counters"]["lane_windows_total"] - total0, 1),
    }


def _prefix_counters(snap, base=None):
    """Prefix counters as deltas over a post-warmup ``base`` snapshot —
    counters survive ``reset_samples()``, so without the baseline the
    warmup fork's miss would contradict the recorded protocol."""
    c = snap["counters"]
    b = base["counters"] if base else {}
    return {
        "hits": c["prefix_hits"] - b.get("prefix_hits", 0),
        "misses": c["prefix_misses"] - b.get("prefix_misses", 0),
        "coalesced": (
            c["prefix_coalesced"] - b.get("prefix_coalesced", 0)
        ),
        "forks": c["prefix_forks"] - b.get("prefix_forks", 0),
        "evictions": (
            c["snapshot_evictions"] - b.get("snapshot_evictions", 0)
        ),
    }


def fork_ab(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, prefix_steps: int, n: int,
    reps: int,
):
    """Interleaved cached-vs-uncached fork A/B at one lane count.

    Cached round: ``n`` requests share one (seed, prefix) — exactly one
    prefix run (miss + n-1 coalesced) plus ``n`` forked suffixes, a
    fresh seed per rep so no rep inherits an earlier rep's snapshot.
    Uncached round: the same ``n`` requests without the prefix
    declaration — every one simulates its full horizon from t=0. Both
    rounds run on ONE warmed server (same compiled programs), walls are
    min-of-reps, and the floor ratio (prefix windows + suffix rounds,
    over full rounds) is reported beside the measurement.
    """
    srv = _make_server(
        composite, capacity, lanes, window, emit_every,
        queue_depth=max(4 * n, 16), pipeline="on",
    )
    _warm(srv, composite, lanes, window)
    # warm the fork path too: the fork-admit program (per override
    # structure) and the prefix machinery compile outside timing
    warm_rid = srv.submit(ScenarioRequest(
        composite=composite, seed=90_000,
        horizon=float(2 * window),
        prefix={"horizon": float(window)},
        overrides={"global": {"volume": 1.01}},
    ))
    srv.run_until_idle(max_ticks=1000)
    assert srv.status(warm_rid)["status"] == "done"
    srv.reset_samples()
    base = srv.metrics()

    def round_requests(seed0, with_prefix):
        return [
            ScenarioRequest(
                composite=composite,
                seed=seed0,
                horizon=float(horizon_steps),
                prefix=(
                    {"horizon": float(prefix_steps)}
                    if with_prefix else None
                ),
                overrides={"global": {"volume": 1.0 + 0.001 * i}},
            )
            for i in range(n)
        ]

    def run_round(requests):
        t0 = time.perf_counter()
        ids = [srv.submit(r) for r in requests]
        srv.run_until_idle(max_ticks=100_000)
        wall = time.perf_counter() - t0
        assert all(srv.status(r)["status"] == "done" for r in ids)
        return wall

    cached = uncached = float("inf")
    for rep in range(reps):
        seed0 = 50_000 + rep  # fresh prefix per rep: 1 miss, n-1 coalesced
        uncached = min(uncached, run_round(round_requests(seed0, False)))
        cached = min(cached, run_round(round_requests(seed0, True)))
    snap = srv.metrics()
    srv.close()
    suffix_steps = horizon_steps - prefix_steps
    rounds = -(-n // lanes)  # requests per lane-round, ceil
    floor = (
        (prefix_steps + rounds * suffix_steps)
        / (rounds * horizon_steps)
    )
    return {
        "lanes": lanes,
        "n_requests": n,
        "horizon_steps": horizon_steps,
        "prefix_steps": prefix_steps,
        "prefix_frac": round(prefix_steps / horizon_steps, 4),
        "uncached_wall_s": round(uncached, 4),
        "cached_wall_s": round(cached, 4),
        "cached_over_uncached": round(cached / uncached, 4),
        "floor_ratio": round(floor, 4),
        "counters": _prefix_counters(snap, base),
        "retraces": snap["retraces"],
    }


def warmup_sweep_ab(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, warmup_steps: int,
    n_trials: int, reps: int,
):
    """The sweep-layer claim: trials/s with the spec ``warmup`` block
    (every trial forks one warmed snapshot) vs the r09 path (every
    trial simulates its full horizon). Interleaved min-of-reps on one
    warmed server; a fresh warmup seed per rep keeps each warm rep
    honest (exactly one prefix run per sweep)."""
    from lens_tpu.sweep import run_sweep

    def spec(warm_seed=None):
        out = {
            "composite": composite,
            "space": {
                "kind": "random",
                "n_trials": n_trials,
                "params": {
                    "global/volume": {"low": 0.8, "high": 1.3},
                },
            },
            "seed": 0,
            "horizon": float(horizon_steps),
            "emit_every": emit_every,
            "capacity": capacity,
            "objective": {
                "path": "global/volume",
                "reduction": "final_live_sum",
                "mode": "max",
            },
            "backend": {"kind": "server"},
        }
        if warm_seed is not None:
            out["warmup"] = {
                "horizon": float(warmup_steps), "seed": warm_seed,
            }
        return out

    srv = SimServer.single_bucket(
        composite, capacity=capacity, lanes=lanes, window=window,
        emit_every=emit_every,
        queue_depth=max(4 * lanes, 2 * n_trials),
    )
    _warm(srv, composite, lanes, window)
    # compile the warm path (solo builder for the override structure,
    # fork admit, prefix run) outside every timed phase — on the SAME
    # server the timed reps use: the compiled programs live per
    # LanePool, so a throwaway server would warm nothing
    run_sweep(spec(warm_seed=1), server=srv)
    run_sweep(spec(), server=srv)
    srv.reset_samples()
    base0 = srv.metrics()["counters"]["prefix_misses"]

    def timed(s):
        t0 = time.perf_counter()
        result = run_sweep(s, server=srv)
        wall = time.perf_counter() - t0
        assert all(r["status"] == "done" for r in result.table)
        return wall

    nowarm = warm = float("inf")
    for rep in range(reps):
        nowarm = min(nowarm, timed(spec()))
        warm = min(warm, timed(spec(warm_seed=7_000 + rep)))
    snap = srv.metrics()
    srv.close()
    return {
        "n_trials": n_trials,
        "lanes": lanes,
        "horizon_steps": horizon_steps,
        "warmup_steps": warmup_steps,
        "nowarm_wall_s": round(nowarm, 4),
        "warm_wall_s": round(warm, 4),
        "nowarm_trials_per_s": round(n_trials / nowarm, 3),
        "warm_trials_per_s": round(n_trials / warm, 3),
        "speedup": round(nowarm / warm, 3),
        "prefix_misses_measured": (
            snap["counters"]["prefix_misses"] - base0
        ),
        "retraces": snap["retraces"],
    }


def faults_ab(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, fill_rounds: int, reps: int,
    tmp_root: str,
):
    """Interleaved A/B of the round-12 robustness knobs at one lane
    count: the same saturated round (N = fill_rounds * lanes
    equal-horizon requests) through four warmed servers — ``off``
    (round-11 behavior), ``check`` (``check_finite="window"``),
    ``wal`` (``recover_dir`` write-ahead logging + group-commit
    fsync), and ``both``. Each mode's wall is min-of-reps with the
    modes alternating per rep (this host's clock wanders ±20%);
    overheads are quoted against ``off``. The acceptance bar
    (ISSUE 10): ``both`` <= 5% at 8 lanes."""
    import os

    modes = {
        "off": {},
        "check": {"check_finite": "window"},
        "wal": {"recover_dir": os.path.join(tmp_root, f"wal_{lanes}")},
        "both": {
            "check_finite": "window",
            "recover_dir": os.path.join(tmp_root, f"both_{lanes}"),
        },
    }
    n = fill_rounds * lanes
    servers = {}
    for mode, extra in modes.items():
        out_dir = None
        sink = "ram"
        if "recover_dir" in extra:
            # the WAL path requires on-disk results (sink="log"), so
            # the wal rows also pay the result-log writes; the honest
            # comparison for THEM is the log-sink off row below
            out_dir = os.path.join(tmp_root, f"out_{mode}_{lanes}")
            sink = "log"
        servers[mode] = SimServer.single_bucket(
            composite, capacity=capacity, lanes=lanes, window=window,
            emit_every=emit_every, queue_depth=max(2 * n, 16),
            out_dir=out_dir, sink=sink, **extra,
        )
    # log-sink baseline so WAL overhead is measured against the same
    # sink (ram-vs-log would mis-bill the result-log writes to the WAL)
    servers["off_log"] = SimServer.single_bucket(
        composite, capacity=capacity, lanes=lanes, window=window,
        emit_every=emit_every, queue_depth=max(2 * n, 16),
        out_dir=os.path.join(tmp_root, f"out_off_log_{lanes}"),
        sink="log",
    )
    for srv in servers.values():
        _warm(srv, composite, lanes, window)

    walls = {mode: float("inf") for mode in servers}
    for rep in range(reps):
        for mode, srv in servers.items():
            wall = _serve_round(
                srv, composite, n, horizon_steps,
                seed0=100 + rep * len(servers) * n,
            )
            walls[mode] = min(walls[mode], wall)
    row = {
        "lanes": lanes,
        "n_requests": n,
        "horizon_steps": horizon_steps,
        "walls_s": {m: round(w, 4) for m, w in walls.items()},
        "served_row_steps_s": {
            m: round(n * horizon_steps * capacity / w)
            for m, w in walls.items()
        },
        # ram-sink knob cost (the in-process/bench serving shape)
        "check_overhead": round(walls["check"] / walls["off"] - 1, 4),
        # log-sink knob costs (the CLI/recovery serving shape)
        "wal_overhead": round(walls["wal"] / walls["off_log"] - 1, 4),
        "both_overhead": round(walls["both"] / walls["off_log"] - 1, 4),
        "diverged": servers["both"].metrics()["counters"]["diverged"],
        "retraces": max(
            s.metrics()["retraces"] for s in servers.values()
        ),
    }
    for srv in servers.values():
        srv.close()
    return row


def run_faults_bench(args) -> int:
    import tempfile

    horizon_steps = args.horizon_windows * args.window
    record = {
        "bench": "serve_faults",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "reps": args.reps,
        "protocol": "interleaved min-of-reps across warmed servers "
        "(off / check_finite=window / recover_dir WAL / both); "
        "check_overhead vs the ram-sink off server, wal/both vs a "
        "log-sink off server so result-log writes are not billed to "
        "the WAL",
        "faults_ab": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        for lanes in args.lanes:
            row = faults_ab(
                args.composite, args.capacity, lanes, args.window,
                args.emit_every, horizon_steps, args.fill_rounds,
                args.reps, tmp,
            )
            record["faults_ab"].append(row)
            print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    worst = max(e["both_overhead"] for e in record["faults_ab"])
    print(f"worst check+WAL overhead: {worst * 100:.1f}%")
    return 0


def run_prefix_bench(args) -> int:
    horizon_steps = args.horizon_windows * args.window
    prefix_windows = int(round(args.prefix_frac * args.horizon_windows))
    if not 0 < prefix_windows < args.horizon_windows:
        raise SystemExit(
            f"--prefix-frac {args.prefix_frac} snaps to "
            f"{prefix_windows} of {args.horizon_windows} windows; the "
            f"prefix needs at least one window and the suffix at "
            f"least one"
        )
    prefix_steps = prefix_windows * args.window
    record = {
        "bench": "serve_prefix_fork",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "reps": args.reps,
        "protocol": "interleaved cached-vs-uncached min-of-reps on one "
        "warmed server; fresh prefix seed per rep (each cached round "
        "pays exactly one prefix run)",
        "fork_ab": [],
        "warmup_sweep": [],
    }
    for lanes in args.lanes:
        # above one lane, keep several fill rounds of forks so the
        # suffix phase still exercises full occupancy (n == lanes
        # would make the floor 1.0: one round either way)
        n = max(args.fork_requests, 4 * lanes)
        row = fork_ab(
            args.composite, args.capacity, lanes, args.window,
            args.emit_every, horizon_steps, prefix_steps,
            n=n, reps=args.reps,
        )
        record["fork_ab"].append(row)
        print(json.dumps(row), flush=True)

    # the sweep A/B runs in the sweep's home regime (bench_sweep.py):
    # many small trials, objective-sized emission
    for n_trials in args.sweep_sizes:
        row = warmup_sweep_ab(
            args.composite, capacity=8, lanes=8, window=32,
            emit_every=32, horizon_steps=384, warmup_steps=288,
            n_trials=n_trials, reps=args.reps,
        )
        record["warmup_sweep"].append(row)
        print(json.dumps(row), flush=True)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    best = min(e["cached_over_uncached"] for e in record["fork_ab"])
    worst = max(e["cached_over_uncached"] for e in record["fork_ab"])
    print(f"fork A/B cached/uncached: best {best:.3f}, worst {worst:.3f}")
    if record["warmup_sweep"]:
        s = min(e["speedup"] for e in record["warmup_sweep"])
        print(f"worst warmup-sharing sweep speedup: {s:.2f}x")
    return 0


def trace_ab(
    composite: str, capacity: int, lanes: int, window: int,
    emit_every: int, horizon_steps: int, fill_rounds: int, reps: int,
    tmp_root: str,
):
    """Round-14 observability overhead A/B at one lane count: the same
    saturated round through two warmed servers — ``off`` (no tracing,
    the bitwise round-13 path) and ``trace`` (``trace_dir`` span
    tracing + ``metrics_interval_s=0`` sampling every tick, the
    worst-case observability load). Interleaved min-of-reps; the
    overhead column is the acceptance bar (docs/observability.md
    pins <= 2%). A bitwise pin rides along: one request served on each
    server must produce identical bytes — tracing observes, never
    perturbs."""
    import os

    n = fill_rounds * lanes
    trace_dir = os.path.join(tmp_root, f"trace_{lanes}")
    servers = {
        "off": SimServer.single_bucket(
            composite, capacity=capacity, lanes=lanes, window=window,
            emit_every=emit_every, queue_depth=max(2 * n, 16),
        ),
        "trace": SimServer.single_bucket(
            composite, capacity=capacity, lanes=lanes, window=window,
            emit_every=emit_every, queue_depth=max(2 * n, 16),
            trace_dir=trace_dir, metrics_interval_s=0.0,
        ),
    }
    for srv in servers.values():
        _warm(srv, composite, lanes, window)

    # bitwise pin: the same request on both servers, byte-equal
    pin = {}
    for mode, srv in servers.items():
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=77,
            horizon=float(horizon_steps),
        ))
        srv.run_until_idle(max_ticks=10_000)
        pin[mode] = _flat_bytes(srv.result(rid))
        srv.reset_samples()
    bitwise = pin["off"] == pin["trace"]

    walls = {mode: float("inf") for mode in servers}
    for rep in range(reps):
        for mode, srv in servers.items():
            wall = _serve_round(
                srv, composite, n, horizon_steps,
                seed0=100 + rep * len(servers) * n,
            )
            walls[mode] = min(walls[mode], wall)
    events = servers["trace"].trace.events_emitted
    retraces = max(s.metrics()["retraces"] for s in servers.values())
    for srv in servers.values():
        srv.close()
    ring = os.path.join(trace_dir, "metrics.jsonl")
    samples = sum(1 for _ in open(ring)) if os.path.exists(ring) else 0
    return {
        "lanes": lanes,
        "n_requests": n,
        "horizon_steps": horizon_steps,
        "walls_s": {m: round(w, 4) for m, w in walls.items()},
        "served_row_steps_s": {
            m: round(n * horizon_steps * capacity / w)
            for m, w in walls.items()
        },
        "trace_overhead": round(walls["trace"] / walls["off"] - 1, 4),
        "trace_events": events,
        "metrics_samples": samples,
        "bitwise_off_equals_traced": bool(bitwise),
        "retraces": retraces,
    }


def run_trace_bench(args) -> int:
    import tempfile

    horizon_steps = args.horizon_windows * args.window
    record = {
        "bench": "serve_trace",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "reps": args.reps,
        "protocol": "interleaved min-of-reps across two warmed "
        "servers (tracing+metrics-sampling off vs on, sampling every "
        "tick); overhead vs the off server; one request pinned "
        "byte-equal across both (tracing observes, never perturbs)",
        "trace_ab": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        for lanes in args.lanes:
            row = trace_ab(
                args.composite, args.capacity, lanes, args.window,
                args.emit_every, horizon_steps, args.fill_rounds,
                args.reps, tmp,
            )
            record["trace_ab"].append(row)
            print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    worst = max(e["trace_overhead"] for e in record["trace_ab"])
    ok = all(
        e["bitwise_off_equals_traced"] for e in record["trace_ab"]
    )
    print(
        f"worst tracing+metrics overhead: {worst * 100:.1f}% "
        f"(acceptance <= 2%); bitwise pins green: {ok}"
    )
    return 0 if ok else 1


def _flat_bytes(tree):
    """A result tree as {joined-path: bytes} for bitwise pins."""
    from lens_tpu.utils.dicts import flatten_paths

    return {
        "/".join(map(str, path)): np.asarray(value).tobytes()
        for path, value in flatten_paths(tree)
    }


def _solo_reference(composite, capacity, window, emit_every, seeds,
                    horizon_steps):
    """One request at a time on a single-device 1-lane server — the
    bitwise oracle the mesh rows pin against."""
    srv = SimServer.single_bucket(
        composite, capacity=capacity, lanes=1, window=window,
        emit_every=emit_every,
    )
    out = {}
    for seed in seeds:
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=seed,
            horizon=float(horizon_steps),
        ))
        srv.run_until_idle(max_ticks=10_000)
        out[seed] = _flat_bytes(srv.result(rid))
    srv.close()
    return out


def run_mesh_bench(args) -> int:
    """Round-13 mesh-serving scaling + failover drill: served
    agent-steps/s at N simulated devices (one lane pool per device,
    one host scheduler), each size pinned per shard against the
    single-device solo oracle, plus a kill-one-device chaos round per
    size (FaultPlan ``device_down`` mid-load; every request must
    still complete, bitwise equal to the no-fault oracle)."""
    from lens_tpu.serve.faults import FaultPlan

    sizes = [
        n for n in args.mesh if n <= jax.device_count()
    ]
    if sizes != list(args.mesh):
        print(
            f"note: only {jax.device_count()} devices attached; "
            f"running sizes {sizes}"
        )
    if not sizes:
        raise SystemExit(
            f"no requested mesh size fits the {jax.device_count()} "
            f"attached device(s); on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=N (or let the "
            f"bare --mesh flag default it)"
        )
    lanes = args.lanes[0] if args.lanes else 2  # lanes PER SHARD
    horizon_steps = args.horizon_windows * args.window
    pin_seeds = (3, 5, 7)
    oracle = _solo_reference(
        args.composite, args.capacity, args.window, args.emit_every,
        pin_seeds, horizon_steps,
    )
    record = {
        "bench": "serve-mesh",
        "backend": jax.default_backend(),
        "devices": jax.device_count(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "lanes_per_shard": lanes,
        "mesh": [],
        "failover": [],
    }

    for n_dev in sizes:
        srv = SimServer.single_bucket(
            args.composite, capacity=args.capacity, lanes=lanes,
            window=args.window, emit_every=args.emit_every,
            queue_depth=max(4 * n_dev * lanes, 64), mesh=n_dev,
        )
        _warm(srv, args.composite, n_dev * lanes, args.window)
        n = args.fill_rounds * n_dev * lanes
        wall = float("inf")
        for rep in range(args.reps):
            wall = min(wall, _serve_round(
                srv, args.composite, n, horizon_steps,
                seed0=1000 + rep * n,
            ))
        # per-shard solo==co-batched pin: the pin seeds ride one more
        # co-batched round (they spread across shards) and must match
        # the single-device solo oracle byte for byte
        rids = {
            seed: srv.submit(ScenarioRequest(
                composite=args.composite, seed=seed,
                horizon=float(horizon_steps),
            ))
            for seed in pin_seeds
        }
        filler = [
            srv.submit(ScenarioRequest(
                composite=args.composite, seed=9000 + i,
                horizon=float(horizon_steps),
            ))
            for i in range(n_dev * lanes - len(pin_seeds))
        ]
        srv.run_until_idle(max_ticks=100_000)
        pin_shards = sorted(
            {srv.tickets[r].shard for r in rids.values()}
        )
        pins_green = all(
            _flat_bytes(srv.result(rid)) == oracle[seed]
            for seed, rid in rids.items()
        ) and all(
            srv.status(r)["status"] == "done" for r in filler
        )
        snap = srv.metrics()
        row = {
            "mesh": n_dev,
            "lanes_total": n_dev * lanes,
            "requests": n,
            "served_row_steps_s": round(
                n * horizon_steps * args.capacity / wall
            ),
            "served_req_s": round(n / wall, 2),
            "occupancy": snap["occupancy"],
            "retraces": snap["retraces"],
            "pins_green": bool(pins_green),
            "pin_shards": pin_shards,
            "shards": snap["shards"],
        }
        record["mesh"].append(row)
        print(json.dumps(
            {k: row[k] for k in row if k != "shards"}
        ), flush=True)
        srv.close()

        # kill-one-device drill at this size: down shard 1 after its
        # second window, mid-load; every request must still complete
        # with oracle-equal bytes. A 1-device mesh has no survivor to
        # fail over to — downing its only shard correctly fails every
        # request, so the drill is meaningless there and skipped.
        if n_dev < 2:
            continue
        victim = 1
        drill = SimServer.single_bucket(
            args.composite, capacity=args.capacity, lanes=lanes,
            window=args.window, emit_every=args.emit_every,
            queue_depth=max(4 * n_dev * lanes, 64), mesh=n_dev,
            faults=FaultPlan([{
                "kind": "device_down", "shard": victim,
                "occurrence": 2,
            }]),
        )
        _warm(drill, args.composite, n_dev * lanes, args.window)
        t0 = time.perf_counter()
        drill_ids = {
            seed: drill.submit(ScenarioRequest(
                composite=args.composite, seed=seed,
                horizon=float(horizon_steps),
            ))
            for seed in pin_seeds
        }
        drill_ids.update({
            9100 + i: drill.submit(ScenarioRequest(
                composite=args.composite, seed=9100 + i,
                horizon=float(horizon_steps),
            ))
            for i in range(2 * n_dev * lanes - len(pin_seeds))
        })
        drill.run_until_idle(max_ticks=100_000)
        drill_wall = time.perf_counter() - t0
        dsnap = drill.metrics()
        all_done = all(
            drill.status(r)["status"] == "done"
            for r in drill_ids.values()
        )
        drill_pins = all(
            _flat_bytes(drill.result(drill_ids[seed])) == oracle[seed]
            for seed in pin_seeds
        )
        frow = {
            "mesh": n_dev,
            "victim_shard": victim,
            "requests": len(drill_ids),
            "wall_s": round(drill_wall, 3),
            "all_done": bool(all_done),
            "pins_green": bool(drill_pins),
            "requeued": dsnap["counters"]["requeued"],
            "quarantined_devices": dsnap["quarantined_devices"],
        }
        record["failover"].append(frow)
        print(json.dumps(frow), flush=True)
        drill.close()

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    base = record["mesh"][0]
    for row in record["mesh"]:
        scale = (
            row["served_row_steps_s"] / base["served_row_steps_s"]
            * base["mesh"] / row["mesh"]
        )
        print(
            f"mesh {row['mesh']}: {row['served_row_steps_s']} "
            f"row-steps/s (per-device efficiency vs {base['mesh']}-dev "
            f"baseline {scale:.2f}) pins_green={row['pins_green']}"
        )
    ok = all(
        r["pins_green"] for r in record["mesh"]
    ) and all(
        r["all_done"] and r["pins_green"] for r in record["failover"]
    )
    print(f"all pins green: {ok}")
    return 0 if ok else 1


# -- front-door load + chaos (round 15) -------------------------------------


class _FdClient:
    """Minimal asyncio HTTP/1.1 keep-alive client for the front-door
    bench: 1000 of these share one event loop, which is the cheapest
    way to BE 1000 concurrent clients on a small CPU box."""

    def __init__(self, host, port, headers=None):
        self.host = host
        self.port = port
        self.headers = dict(headers or {})
        self.reader = None
        self.writer = None

    async def connect(self):
        import asyncio

        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )

    async def _read_head(self):
        status = int(
            (await self.reader.readline()).split(b" ", 2)[1]
        )
        headers = {}
        while True:
            line = await self.reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode().partition(":")
            headers[name.strip().lower()] = value.strip()
        return status, headers

    async def request(self, method, path, body=None):
        payload = b"" if body is None else json.dumps(body).encode()
        head = [f"{method} {path} HTTP/1.1",
                f"Host: {self.host}:{self.port}"]
        head += [f"{k}: {v}" for k, v in self.headers.items()]
        if payload:
            head.append(f"Content-Length: {len(payload)}")
        self.writer.write(
            ("\r\n".join(head) + "\r\n\r\n").encode() + payload
        )
        await self.writer.drain()
        status, headers = await self._read_head()
        body_bytes = await self.reader.readexactly(
            int(headers.get("content-length", 0))
        )
        try:
            parsed = json.loads(body_bytes)
        except (ValueError, UnicodeDecodeError):
            parsed = body_bytes
        return status, parsed, headers

    async def stream(self, path):
        """Open an SSE record stream; returns (t_first_record, body
        bytes) — first-record wall stamp taken the moment the chunk
        carrying the first ``record`` event lands."""
        head = [f"GET {path} HTTP/1.1",
                f"Host: {self.host}:{self.port}"]
        head += [f"{k}: {v}" for k, v in self.headers.items()]
        self.writer.write(("\r\n".join(head) + "\r\n\r\n").encode())
        await self.writer.drain()
        status, headers = await self._read_head()
        assert status == 200, status
        body = b""
        t_first = None
        while True:
            size_line = await self.reader.readline()
            n = int(size_line.strip() or b"0", 16)
            if n == 0:
                await self.reader.readline()  # trailing CRLF
                return t_first, body
            chunk = await self.reader.readexactly(n)
            await self.reader.readexactly(2)  # CRLF
            if t_first is None and b"event: record" in chunk:
                t_first = time.perf_counter()
            body += chunk

    def close(self):
        if self.writer is not None:
            self.writer.close()


async def _fd_one_client(host, port, key, body, out, max_attempts=12):
    """One keep-alive client: submit (honoring 429 Retry-After with
    bounded retries), then consume the whole SSE record stream."""
    import asyncio

    c = _FdClient(host, port, {"Authorization": f"Bearer {key}"})
    row = {"ok": False, "throttled": 0, "rejected": 0, "rid": None,
           "status": None, "first_byte_s": None, "done_s": None,
           "raw": b""}
    out.append(row)
    try:
        await c.connect()
        t0 = time.perf_counter()
        attempts = 0
        while True:
            status, payload, headers = await c.request(
                "POST", "/v1/requests", body
            )
            if status == 202:
                break
            if status == 429:
                # the honest-backpressure loop: sleep the hint, retry
                row["throttled"] += 1
                attempts += 1
                if attempts >= max_attempts:
                    row["status"] = "gave_up"
                    return
                await asyncio.sleep(
                    min(float(headers.get("retry-after", 0.2)), 2.0)
                )
                continue
            row["status"] = f"http_{status}"
            row["rejected"] += 1
            return
        row["rid"] = payload["rid"]
        t_first, body_bytes = await c.stream(
            f"/v1/requests/{payload['rid']}/stream"
        )
        t_done = time.perf_counter()
        from lens_tpu.frontdoor import decode_record_events

        raw, end = decode_record_events(body_bytes)
        row["status"] = end["status"]
        row["ok"] = end["status"] == "done"
        row["raw"] = raw
        if t_first is not None:
            row["first_byte_s"] = t_first - t0
        row["done_s"] = t_done - t0
    finally:
        c.close()


def _fd_run_load(fd, plan):
    """Run one load plan ({tenant: (key, n_clients, request_body)})
    with every client concurrent on one event loop; returns
    {tenant: [rows]} and the wall seconds."""
    import asyncio

    results = {tenant: [] for tenant in plan}

    async def run():
        tasks = []
        for tenant, (key, n, body) in plan.items():
            for i in range(n):
                req = dict(body)
                req["seed"] = i
                tasks.append(asyncio.wait_for(
                    _fd_one_client(
                        "127.0.0.1", fd.port, key, req,
                        results[tenant],
                    ),
                    timeout=900,
                ))
        await asyncio.gather(*tasks)

    t0 = time.perf_counter()
    asyncio.run(run())
    return results, time.perf_counter() - t0


def _fd_tenant_summary(rows):
    from lens_tpu.obs.metrics import percentiles

    done = [r for r in rows if r["ok"]]
    return {
        "clients": len(rows),
        "completed": len(done),
        "throttled_429": sum(r["throttled"] for r in rows),
        "gave_up": sum(
            1 for r in rows if r["status"] == "gave_up"
        ),
        "first_byte_s": percentiles(
            [r["first_byte_s"] for r in done
             if r["first_byte_s"] is not None]
        ),
        "done_s": percentiles(
            [r["done_s"] for r in done if r["done_s"] is not None]
        ),
        "streamed_bytes": sum(len(r["raw"]) for r in rows),
    }


def _fd_bytes_equal(out_dir, rows):
    """Every completed request's streamed bytes vs its on-disk log."""
    checked = mismatched = 0
    for r in rows:
        if not r["ok"]:
            continue
        path = os.path.join(out_dir, f"{r['rid']}.lens")
        with open(path, "rb") as f:
            disk = f.read()
        checked += 1
        if r["raw"] != disk:
            mismatched += 1
    return checked, mismatched


def run_frontdoor_bench(args) -> int:
    import shutil
    import tempfile

    from lens_tpu.frontdoor import FrontDoor
    from lens_tpu.serve import FaultPlan

    lanes = (args.lanes or [8])[0]
    window = args.window
    horizon = float(args.horizon_windows * window)
    n_gold, n_silver, n_flood = args.frontdoor_clients
    record = {
        "bench": "frontdoor",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": window,
        "lanes": lanes,
        "horizon_steps": int(horizon),
        "clients": {"gold": n_gold, "silver": n_silver,
                    "flood": n_flood},
        "stream_poll_s": 0.1,
        "tenants": {
            "gold": {"weight": 2.0, "priority": "interactive"},
            "silver": {"weight": 1.0, "priority": "batch"},
            "flood": {"weight": 1.0, "priority": "batch",
                      "rate": args.flood_rate, "burst": 25,
                      "max_inflight": 32, "queue_depth": 64},
        },
        "rows": [],
    }

    def tenant_table():
        return [
            {"name": "gold", "api_key": "gk", "weight": 2.0,
             "default_priority": "interactive",
             "queue_depth": 4096},
            {"name": "silver", "api_key": "sk", "weight": 1.0,
             "queue_depth": 4096},
            {"name": "flood", "api_key": "fk", "weight": 1.0,
             "rate": args.flood_rate, "burst": 25,
             "max_inflight": 32, "queue_depth": 64},
        ]

    def one_row(label, n_clients, mesh, faults, io_victim=None):
        out_dir = tempfile.mkdtemp(prefix=f"bench_fd_{label}_")
        srv = SimServer.single_bucket(
            args.composite,
            capacity=args.capacity,
            lanes=lanes,
            window=window,
            emit_every=args.emit_every,
            queue_depth=64,
            sink="log",
            out_dir=out_dir,
            sink_errors="request",
            mesh=mesh,
            faults=faults,
        )
        _warm(srv, args.composite, lanes, window)
        fd = FrontDoor(
            srv, tenants=tenant_table(), own_server=True,
            stream_poll_s=0.1,
        ).start()
        try:
            gold, silver, flood = n_clients
            results, wall = _fd_run_load(fd, {
                "gold": ("gk", gold, {"horizon": horizon}),
                "silver": ("sk", silver, {"horizon": horizon}),
                "flood": ("fk", flood, {"horizon": horizon}),
            })
            snap = srv.metrics()
            row = {
                "row": label,
                "mesh": mesh,
                "wall_s": round(wall, 3),
                "req_s": round(
                    sum(
                        1 for rows in results.values()
                        for r in rows if r["ok"]
                    ) / wall, 2,
                ),
                "tenants": {
                    t: _fd_tenant_summary(rows)
                    for t, rows in results.items()
                },
                "server_tenants": snap["tenants"],
                "counters": {
                    k: snap["counters"][k]
                    for k in ("submitted", "admitted", "retired",
                              "failed", "rejected", "requeued",
                              "sink_failed")
                },
                "quarantined_devices": snap["quarantined_devices"],
            }
            # pushback must land on the flooding tenant only
            row["pushback_flood_only"] = (
                row["tenants"]["flood"]["throttled_429"] > 0
                and row["tenants"]["gold"]["throttled_429"] == 0
                and row["tenants"]["silver"]["throttled_429"] == 0
            )
            checked = mismatched = 0
            for rows in results.values():
                c, m = _fd_bytes_equal(out_dir, rows)
                checked += c
                mismatched += m
            row["bytes_checked"] = checked
            row["bytes_mismatched"] = mismatched
            if io_victim is not None:
                # chaos SLO: the io_error victim fails alone; every
                # OTHER submitted request completes (device_down
                # displacements re-run to done on the survivor)
                statuses = {
                    r["rid"]: r["status"]
                    for rows in results.values() for r in rows
                    if r["rid"] is not None
                }
                victim_status = statuses.get(io_victim)
                non_faulted = {
                    rid: s for rid, s in statuses.items()
                    if rid != io_victim
                }
                row["chaos"] = {
                    "io_victim": io_victim,
                    "io_victim_status": victim_status,
                    "non_faulted": len(non_faulted),
                    "non_faulted_completed": sum(
                        1 for s in non_faulted.values() if s == "done"
                    ),
                    "slo_held": all(
                        s == "done" for s in non_faulted.values()
                    ) and victim_status == "failed",
                }
            return row
        finally:
            fd.close()
            shutil.rmtree(out_dir, ignore_errors=True)

    row = one_row("load", (n_gold, n_silver, n_flood), None, None)
    record["rows"].append(row)
    print(json.dumps(row), flush=True)

    # chaos: device 1 dies shortly into the load (occurrence counts
    # that shard's window DISPATCHES — one per tick, so keep it well
    # under the load's dispatch count; warmup contributes ~2), one
    # request's sink raises — under the same 3-tenant HTTP load on a
    # mesh=2 server
    gold_c, silver_c, flood_c = args.chaos_clients
    victim = f"req-{lanes + (gold_c + silver_c) // 3:06d}"
    plan = FaultPlan([
        {"kind": "device_down", "shard": 1, "occurrence": 6},
        {"kind": "io_error", "request": victim},
    ])
    row = one_row(
        "chaos", (gold_c, silver_c, flood_c), 2, plan,
        io_victim=victim,
    )
    record["rows"].append(row)
    print(json.dumps(row), flush=True)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    for row in record["rows"]:
        g = row["tenants"]["gold"]["done_s"]
        f_ = row["tenants"]["flood"]
        print(
            f"{row['row']}: wall={row['wall_s']}s "
            f"gold p50/p99 done={g['p50']:.2f}/{g['p99']:.2f}s "
            f"flood throttled={f_['throttled_429']} "
            f"pushback_flood_only={row['pushback_flood_only']} "
            f"bytes_mismatched={row['bytes_mismatched']}"
            + (
                f" slo_held={row['chaos']['slo_held']} "
                f"quarantined={row['quarantined_devices']} "
                f"requeued={row['counters']['requeued']}"
                if "chaos" in row else ""
            )
        )
    return 0


def _zipf_draws(n, n_prefixes, alpha, rng):
    """Seeded skewed-popularity prefix indices: P(i) ~ 1/(i+1)^alpha."""
    import numpy as np

    probs = 1.0 / np.arange(1, n_prefixes + 1) ** alpha
    probs /= probs.sum()
    return rng.choice(n_prefixes, size=n, p=probs)


def tier_zipf_ab(
    composite, capacity, lanes, window, emit_every, horizon_steps,
    prefix_steps, n, n_prefixes, alpha, reps, tmp_root,
):
    """Skewed-popularity A/B: the round-11 LRU-only store vs the
    tiered store, SAME tight device budget (~3.5 snapshots of the
    ``n_prefixes`` distinct ones in play). Under Zipf traffic the flat
    store evicts warm prefixes outright and recomputes them on the
    next repeat; the tiered store demotes them to host/disk and
    promotes on the hit — so the claim is higher HIT RATE and lower
    WALL at identical device memory. Traffic arrives in WAVES of one
    lane-fill each (submit, run to idle, next wave): within one burst
    every repeat coalesces onto the in-flight run no matter the
    store, so only waves expose what the CACHE retained. Interleaved
    min-of-reps on two warmed servers; fresh seed base per rep (no
    cross-rep cache reuse), identical per-rep workload for both."""
    import os

    import numpy as np

    servers = {
        "lru": _make_server(
            composite, capacity, lanes, window, emit_every,
            queue_depth=max(4 * n, 64), pipeline="on",
        ),
        "tiered": SimServer.single_bucket(
            composite, capacity=capacity, lanes=lanes, window=window,
            emit_every=emit_every, queue_depth=max(4 * n, 64),
            host_budget_mb=0,  # placeholder; set from the probe below
            tier_dir=os.path.join(tmp_root, f"tier_{lanes}"),
        ),
    }
    for srv in servers.values():
        _warm(srv, composite, lanes, window)
        # probe: one prefix+override fork compiles the whole fork
        # path (fork-admit per override structure, lane capture)
        # outside timing, and tells us the snapshot's byte size so
        # the budget can be quoted in ENTRIES (~3.5) instead of MiB
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=999_999,
            horizon=float(horizon_steps),
            prefix={"horizon": float(prefix_steps)},
            overrides={"global": {"volume": 1.5}},
        ))
        srv.run_until_idle(max_ticks=10_000)
        assert srv.status(rid)["status"] == "done"
    entry_bytes = servers["lru"].metrics()["snapshot_bytes"]
    assert entry_bytes > 0
    device_budget = int(3.5 * entry_bytes)
    for srv in servers.values():
        srv.snapshots.budget_bytes = device_budget
    servers["tiered"].snapshots.host_budget_bytes = device_budget

    def round_workload(srv, seed_base, idx):
        t0 = time.perf_counter()
        ids = []
        for w0 in range(0, len(idx), lanes):
            ids.extend(
                srv.submit(ScenarioRequest(
                    composite=composite,
                    seed=seed_base + int(k),
                    horizon=float(horizon_steps),
                    prefix={"horizon": float(prefix_steps)},
                    overrides={
                        "global": {"volume": 1.0 + 0.001 * (w0 + i)}
                    },
                ))
                for i, k in enumerate(idx[w0:w0 + lanes])
            )
            srv.run_until_idle(max_ticks=100_000)
        wall = time.perf_counter() - t0
        assert all(srv.status(r)["status"] == "done" for r in ids)
        return wall

    base = {
        mode: srv.metrics()["counters"] for mode, srv in servers.items()
    }
    walls = {mode: float("inf") for mode in servers}
    for rep in range(reps):
        rng = np.random.default_rng(1234 + rep)
        idx = _zipf_draws(n, n_prefixes, alpha, rng)
        seed_base = 10_000 + rep * 1_000
        for mode, srv in servers.items():
            walls[mode] = min(
                walls[mode], round_workload(srv, seed_base, idx)
            )
    row = {
        "lanes": lanes,
        "n_requests": n,
        "n_prefixes": n_prefixes,
        "zipf_alpha": alpha,
        "horizon_steps": horizon_steps,
        "prefix_steps": prefix_steps,
        "device_budget_entries": 3.5,
        "walls_s": {m: round(w, 4) for m, w in walls.items()},
        "tiered_over_lru": round(
            walls["tiered"] / walls["lru"], 4
        ),
    }
    for mode, srv in servers.items():
        c = srv.metrics()["counters"]
        misses = c["prefix_misses"] - base[mode]["prefix_misses"]
        total = reps * n
        row[f"{mode}_hit_rate"] = round(1.0 - misses / total, 4)
        row[f"{mode}_misses"] = misses
    tiers = servers["tiered"].metrics()["snapshot_tiers"]
    row["tiered_promotions"] = {
        t: tiers[t]["promotions"] for t in ("host", "disk")
    }
    row["retraces"] = max(
        s.metrics()["retraces"] for s in servers.values()
    )
    for srv in servers.values():
        srv.close()
    return row


def tier_restart(
    composite, capacity, lanes, window, emit_every, horizon_steps,
    prefix_steps, n_prefixes, tmp_root,
):
    """The durability row: serve a distinct-prefix workload with
    every snapshot forced to disk (device/host budgets 0), KILL the
    server (no close — the rename-protocol spills do not care), then
    rebuild over the same tier dir with a NORMAL device budget and
    serve the repeat workload: each prefix promotes off disk once
    (one orbax restore) instead of recomputing (one prefix run). The
    claim: zero prefix misses, one DISK hit per prefix, and a wall
    under the cold control's (same repeat workload, fresh tier dir —
    it must recompute every prefix)."""
    import os

    def make(tier, force_disk):
        return SimServer.single_bucket(
            composite, capacity=capacity, lanes=lanes, window=window,
            emit_every=emit_every, queue_depth=max(4 * n_prefixes, 64),
            # force_disk: page everything out immediately (the
            # population run, so the kill leaves a full disk tier);
            # serving runs use an unbounded device tier — the honest
            # shape, where each prefix pages in at most once
            **(
                {"snapshot_budget_mb": 0, "host_budget_mb": 0}
                if force_disk
                else {"host_budget_mb": 0}
            ),
            tier_dir=os.path.join(tmp_root, tier),
        )

    def warm_fork(srv):
        # compile the fork path (fork-admit, lane capture, prefix
        # machinery) outside every timed phase — per SERVER, so no
        # store mode rides an earlier mode's compile cache
        rid = srv.submit(ScenarioRequest(
            composite=composite, seed=999_998,
            horizon=float(horizon_steps),
            prefix={"horizon": float(prefix_steps)},
            overrides={"global": {"volume": 1.5}},
        ))
        srv.run_until_idle(max_ticks=10_000)
        assert srv.status(rid)["status"] == "done"

    def workload(srv, two_forks=True):
        t0 = time.perf_counter()
        ids = []
        for k in range(n_prefixes):
            for f in range(2 if two_forks else 1):
                ids.append(srv.submit(ScenarioRequest(
                    composite=composite, seed=77_000 + k,
                    horizon=float(horizon_steps),
                    prefix={"horizon": float(prefix_steps)},
                    overrides={
                        "global": {"volume": 1.0 + 0.01 * (f + 1)}
                    },
                )))
        srv.run_until_idle(max_ticks=100_000)
        wall = time.perf_counter() - t0
        assert all(srv.status(r)["status"] == "done" for r in ids)
        return wall

    srv = make("restart_tier", force_disk=True)
    _warm(srv, composite, lanes, window)
    warm_fork(srv)
    workload(srv)  # populates the disk tier
    if srv._streamer is not None:
        srv._streamer.drain()
    del srv  # simulated kill: no close, durable spills only

    warm_srv = make("restart_tier", force_disk=False)  # re-adopts
    _warm(warm_srv, composite, lanes, window)
    warm_fork(warm_srv)
    snap = warm_srv.metrics()
    base, base_disk_hits = (
        snap["counters"], snap["snapshot_tiers"]["disk"]["hits"]
    )
    warm_wall = workload(warm_srv)
    c = warm_srv.metrics()["counters"]
    tiers = warm_srv.metrics()["snapshot_tiers"]
    misses = c["prefix_misses"] - base["prefix_misses"]
    disk_hits = tiers["disk"]["hits"] - base_disk_hits
    warm_srv.close()

    # control: nothing to adopt
    cold_srv = make("restart_cold_tier", force_disk=False)
    _warm(cold_srv, composite, lanes, window)
    warm_fork(cold_srv)
    cold_wall = workload(cold_srv)
    cold_srv.close()
    return {
        "lanes": lanes,
        "n_prefixes": n_prefixes,
        "horizon_steps": horizon_steps,
        "prefix_steps": prefix_steps,
        "restarted_wall_s": round(warm_wall, 4),
        "cold_wall_s": round(cold_wall, 4),
        "restarted_over_cold": round(warm_wall / cold_wall, 4),
        "restarted_misses": misses,
        "restarted_disk_hits": disk_hits,
    }


def tier_warm_sweep(composite, n_trials, reps, tmp_root):
    """The speculative-warming row: the same warmup-sharing sweep with
    and without ``backend.warm`` — warming pre-launches the shared
    warmup prefix, so the first trials coalesce onto it (speculative
    hits) instead of paying the miss on their own latency path."""
    import os

    from lens_tpu.sweep import run_sweep

    def spec(warm):
        return {
            "composite": composite,
            "space": {
                "kind": "random", "n_trials": n_trials,
                "params": {
                    "global/volume": {"low": 0.8, "high": 1.3},
                },
            },
            "seed": 0, "horizon": 384.0, "emit_every": 32,
            "capacity": 8,
            "objective": {
                "path": "global/volume",
                "reduction": "final_live_sum", "mode": "max",
            },
            "backend": {
                "kind": "server", "lanes": 8, "window": 32,
                **({"warm": True} if warm else {}),
            },
            "warmup": {"horizon": 288.0, "seed": 41},
        }

    rows = {}
    walls = {False: float("inf"), True: float("inf")}
    counters = {}
    for rep in range(reps):
        for warm in (False, True):  # interleaved: this clock wanders
            t0 = time.perf_counter()
            res = run_sweep(
                spec(warm),
                out_dir=os.path.join(
                    tmp_root, f"sweep_{int(warm)}_{rep}"
                ),
            )
            wall = time.perf_counter() - t0
            assert all(r["status"] == "done" for r in res.table)
            walls[warm] = min(walls[warm], wall)
            counters[warm] = res.metrics["server"]["counters"]
    for warm in (False, True):
        c = counters[warm]
        rows["warm" if warm else "nowarm"] = {
            "wall_s": round(walls[warm], 4),
            "trials_per_s": round(n_trials / walls[warm], 3),
            "warm_hits": c["warm_hits"],
            "warm_submitted": c["warm_submitted"],
        }
    return {"n_trials": n_trials, **rows}


def run_tier_bench(args) -> int:
    import tempfile

    horizon_steps = args.horizon_windows * args.window
    prefix_windows = int(round(args.prefix_frac * args.horizon_windows))
    prefix_steps = max(prefix_windows, 1) * args.window
    record = {
        "bench": "serve_tiers",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "prefix_steps": prefix_steps,
        "reps": args.reps,
        "protocol": "zipf row: interleaved min-of-reps, identical "
        "per-rep workload + device budget (~3.5 snapshot entries) on "
        "both stores, fresh prefix seeds per rep; restart row: "
        "populate the disk tier, del the server without close, "
        "rebuild over the same dir, repeat the workload (cold "
        "control on a fresh dir); sweep row: backend.warm A/B",
        "zipf_ab": [],
        "restart": [],
        "warm_sweep": [],
    }
    with tempfile.TemporaryDirectory() as tmp:
        for lanes in args.lanes:
            row = tier_zipf_ab(
                args.composite, args.capacity, lanes, args.window,
                args.emit_every, horizon_steps, prefix_steps,
                n=max(6 * lanes, 48), n_prefixes=args.tier_prefixes,
                alpha=args.zipf_alpha, reps=args.reps, tmp_root=tmp,
            )
            record["zipf_ab"].append(row)
            print(json.dumps(row), flush=True)
        # restart row: an all-but-one-window prefix, so one prefix
        # RECOMPUTE (the cold path) clearly exceeds one disk RESTORE
        # (the warm path) — the long-warmup regime the tier exists for
        row = tier_restart(
            args.composite, args.capacity, max(args.lanes),
            args.window, args.emit_every, horizon_steps,
            prefix_steps=horizon_steps - args.window,
            n_prefixes=args.tier_prefixes, tmp_root=tmp,
        )
        record["restart"].append(row)
        print(json.dumps(row), flush=True)
        row = tier_warm_sweep(
            args.composite, args.sweep_sizes[0],
            max(args.reps, 3), tmp,
        )
        record["warm_sweep"].append(row)
        print(json.dumps(row), flush=True)
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    for e in record["zipf_ab"]:
        print(
            f"zipf {e['lanes']} lanes: hit-rate "
            f"{e['lru_hit_rate']:.3f} -> {e['tiered_hit_rate']:.3f}, "
            f"wall x{e['tiered_over_lru']:.3f}"
        )
    r = record["restart"][0]
    print(
        f"restart: x{r['restarted_over_cold']:.3f} of cold, "
        f"{r['restarted_disk_hits']} disk hits, "
        f"{r['restarted_misses']} misses"
    )
    s = record["warm_sweep"][0]
    print(
        f"warm sweep: {s['nowarm']['trials_per_s']} -> "
        f"{s['warm']['trials_per_s']} trials/s, "
        f"{s['warm']['warm_hits']} speculative hits"
    )
    return 0


# -- request-stream CDN (round 18) -------------------------------------------


def _cdn_server(mode, composite, capacity, lanes, window, emit_every,
                tmp_root, tag):
    """One server per CDN knob setting: ``off`` is the round-17 path
    bit for bit, ``dedup``/``cache``/``both`` arm the knobs. Cache
    modes get a tier dir (the results dir lives under it) so the
    kill-restart row can rebuild over the same store."""
    import os

    kw = dict(
        capacity=capacity, lanes=lanes, window=window,
        emit_every=emit_every, queue_depth=512, pipeline="on",
        sink="log", out_dir=os.path.join(tmp_root, f"{tag}_out"),
    )
    if mode in ("cache", "both"):
        kw["result_cache_mb"] = 256
        kw["tier_dir"] = os.path.join(tmp_root, f"{tag}_tier")
    if mode in ("dedup", "both"):
        kw["dedup"] = "on"
    return SimServer.single_bucket(composite, **kw)


def _cdn_round(srv, composite, horizon_steps, lanes, seeds):
    """Submit the seed sequence in waves of two lane-fills (so
    within-wave duplicates are IN FLIGHT together — the dedup case —
    while across-wave repeats meet only the durable cache), run each
    wave to idle, return wall."""
    t0 = time.perf_counter()
    ids = []
    for w0 in range(0, len(seeds), 2 * lanes):
        ids.extend(
            srv.submit(ScenarioRequest(
                composite=composite, seed=int(s),
                horizon=float(horizon_steps),
            ))
            for s in seeds[w0:w0 + 2 * lanes]
        )
        srv.run_until_idle(max_ticks=100_000)
    wall = time.perf_counter() - t0
    assert all(srv.status(r)["status"] == "done" for r in ids)
    return wall


def run_cdn_bench(args) -> int:
    """Round-18 CDN bench (docs/serving.md, "Suffix dedup & result
    cache"): Zipf repeat-traffic over a small distinct-request pool —
    the sweep-driver / classroom / parameter-scan shape where the same
    coordinates are asked for again and again.

    Rows:

    - ``zipf``: the four knob settings (off / dedup / cache / both) on
      an identical per-rep workload, interleaved min-of-reps: wall,
      device windows, hits/coalesces, device seconds saved.
    - ``hot_cold``: p50 of a fully-hot repeat (submit returns a
      terminal ticket) vs p50 of a cold solo request, with the
      zero-device-windows claim counter-verified during the hot run.
    - ``overhead``: all-distinct traffic (every request a miss) on
      off vs both — what arming the knobs costs when nothing repeats.
    - ``restart``: kill the ``both`` server, rebuild over the same
      tier dir, repeat the workload — every request a durable hit,
      zero windows.
    """
    import os
    import tempfile

    import numpy as np

    horizon_steps = args.horizon_windows * args.window
    lanes = max(args.lanes)
    n = max(8 * lanes, 48)
    modes = ("off", "dedup", "cache", "both")
    record = {
        "bench": "serve_cdn",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "lanes": lanes,
        "n_requests": n,
        "n_distinct": args.cdn_distinct,
        "zipf_alpha": args.zipf_alpha,
        "reps": args.reps,
        "protocol": "zipf row: interleaved min-of-reps, identical "
        "per-rep Zipf workload on all four knob settings, fresh "
        "seed pool per rep (no cross-rep cache reuse), waves of two "
        "lane-fills; hot_cold: 20 hot repeats timed at submit with "
        "the windows counter pinned unchanged, vs solo cold "
        "requests run to idle; overhead: all-distinct traffic, "
        "off vs both; restart: rebuild over the same tier dir, "
        "repeat the workload",
        "zipf": [],
        "hot_cold": {},
        "overhead": {},
        "restart": {},
    }
    with tempfile.TemporaryDirectory() as tmp:
        servers = {
            m: _cdn_server(
                m, args.composite, args.capacity, lanes, args.window,
                args.emit_every, tmp, m,
            )
            for m in modes
        }
        for srv in servers.values():
            _warm(srv, args.composite, lanes, args.window)
        base = {
            m: dict(srv.metrics()["counters"])
            for m, srv in servers.items()
        }
        walls = {m: float("inf") for m in modes}
        last_seeds = None
        for rep in range(args.reps):
            rng = np.random.default_rng(4242 + rep)
            idx = _zipf_draws(n, args.cdn_distinct, args.zipf_alpha,
                              rng)
            pool = 100_000 + rep * 1_000 + np.arange(args.cdn_distinct)
            seeds = pool[idx]
            last_seeds = seeds
            for m, srv in servers.items():
                walls[m] = min(walls[m], _cdn_round(
                    srv, args.composite, horizon_steps, lanes, seeds,
                ))
        for m in modes:
            c = servers[m].metrics()["counters"]
            row = {
                "mode": m,
                "wall_s": round(walls[m], 4),
                "wall_over_off": round(walls[m] / walls["off"], 4),
                "windows": c["windows"] - base[m]["windows"],
                "result_hits": c["result_hits"]
                - base[m]["result_hits"],
                "suffix_coalesced": c["suffix_coalesced"]
                - base[m]["suffix_coalesced"],
                "device_seconds_saved": round(
                    c["device_seconds_saved"]
                    - base[m]["device_seconds_saved"], 3,
                ),
            }
            record["zipf"].append(row)
            print(json.dumps(row), flush=True)

        # hot/cold p50: repeats of the last rep's most popular request
        # against the warmed "both" server, windows pinned unchanged
        both = servers["both"]
        hot_seed = int(last_seeds[0])
        w0 = both.metrics()["counters"]["windows"]
        hot = []
        for _ in range(20):
            t0 = time.perf_counter()
            rid = both.submit(ScenarioRequest(
                composite=args.composite, seed=hot_seed,
                horizon=float(horizon_steps),
            ))
            assert both.status(rid)["status"] == "done"
            hot.append(time.perf_counter() - t0)
        hot_windows = both.metrics()["counters"]["windows"] - w0
        cold = []
        off = servers["off"]
        for i in range(8):
            t0 = time.perf_counter()
            off.submit(ScenarioRequest(
                composite=args.composite, seed=900_000 + i,
                horizon=float(horizon_steps),
            ))
            off.run_until_idle(max_ticks=100_000)
            cold.append(time.perf_counter() - t0)
        record["hot_cold"] = {
            "hot_p50_s": round(float(np.median(hot)), 6),
            "cold_p50_s": round(float(np.median(cold)), 6),
            "cold_over_hot": round(
                float(np.median(cold)) / float(np.median(hot)), 1,
            ),
            "hot_windows": hot_windows,  # the zero-device-work claim
        }
        print(json.dumps({"hot_cold": record["hot_cold"]}), flush=True)

        # cold-path overhead: all-distinct traffic, nothing repeats —
        # fingerprint hashing + cache puts are the whole delta
        pair = {
            m: _cdn_server(
                m, args.composite, args.capacity, lanes, args.window,
                args.emit_every, tmp, f"ov_{m}",
            )
            for m in ("off", "both")
        }
        for srv in pair.values():
            _warm(srv, args.composite, lanes, args.window)
        ov = {m: float("inf") for m in pair}
        for rep in range(args.reps):
            seeds = 500_000 + rep * 1_000 + np.arange(n)
            for m, srv in pair.items():
                ov[m] = min(ov[m], _cdn_round(
                    srv, args.composite, horizon_steps, lanes, seeds,
                ))
        for srv in pair.values():
            srv.close()
        record["overhead"] = {
            "off_wall_s": round(ov["off"], 4),
            "both_wall_s": round(ov["both"], 4),
            "both_over_off": round(ov["both"] / ov["off"], 4),
        }
        print(json.dumps({"overhead": record["overhead"]}), flush=True)

        # kill/restart: the results dir is durable state — a rebuilt
        # server answers the whole workload from disk, zero windows
        for m in ("off", "dedup", "cache"):
            servers[m].close()
        both.close()
        warm = _cdn_server(
            "both", args.composite, args.capacity, lanes, args.window,
            args.emit_every, tmp, "both",
        )
        w0 = warm.metrics()["counters"]["windows"]
        t0 = time.perf_counter()
        wall = _cdn_round(
            warm, args.composite, horizon_steps, lanes, last_seeds,
        )
        c = warm.metrics()["counters"]
        record["restart"] = {
            "wall_s": round(wall, 4),
            "wall_over_cold": round(wall / walls["off"], 4),
            "windows": c["windows"] - w0,
            "result_hits": c["result_hits"],
        }
        warm.close()
        print(json.dumps({"restart": record["restart"]}), flush=True)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    rows = {r["mode"]: r for r in record["zipf"]}
    print(
        f"zipf walls vs off: dedup x{rows['dedup']['wall_over_off']}"
        f" cache x{rows['cache']['wall_over_off']}"
        f" both x{rows['both']['wall_over_off']}"
    )
    hc = record["hot_cold"]
    print(
        f"hot p50 {hc['hot_p50_s'] * 1e3:.2f} ms vs cold "
        f"{hc['cold_p50_s'] * 1e3:.1f} ms (x{hc['cold_over_hot']}), "
        f"{hc['hot_windows']} device windows during hot repeats"
    )
    print(
        f"cold-path overhead x{record['overhead']['both_over_off']}; "
        f"restart x{record['restart']['wall_over_cold']} of cold with "
        f"{record['restart']['windows']} windows"
    )
    return 0


# -- multi-host cluster (round 17) -------------------------------------------


def run_cluster_bench(args) -> int:
    """Round-17 cluster bench (docs/serving.md, "Cluster serving").

    Rows per requested host count, every worker a REAL process behind
    the router over localhost TCP:

    - aggregate served row-steps/s vs TWO baselines: the in-process
      single-host server (the absolute ceiling of this box) and the
      1-host cluster (the same worker-process shape without fan-out —
      the apples-to-apples baseline for what ADDING hosts costs).
      On this box every "host" shares the same core(s), so the honest
      expectation is parity with the 1-host cluster, not scaling —
      the constant gap to the in-process ceiling is the cost of
      process isolation + RPC, and real scaling needs real chips;
    - a work-stealing A/B under a SKEWED offered load (every request
      pinned to host 0 — the shape a sticky tenant/locality pile-up
      produces): stealing on migrates queued work to the idle hosts
      (stolen counted, per-host retirement distribution shown),
      stealing off strands it on the one host;
    - a kill-one-host chaos row: a FaultPlan ``host_down`` SIGKILLs
      one worker mid-load; every request must complete and its log
      must be BYTE-EQUAL to the single-host no-fault oracle (the
      1-host cluster, same router id mint).
    """
    import shutil
    import tempfile

    from lens_tpu.cluster import ClusterServer
    from lens_tpu.serve.faults import FaultPlan

    sizes = args.cluster or [2, 4]
    lanes = args.lanes[0] if args.lanes else 2  # lanes PER HOST
    horizon_steps = args.horizon_windows * args.window
    # sync pipeline on BOTH sides: bitwise-identical results either
    # way (r10 pin) and one thread fewer per process on a box where
    # every process shares one core
    bucket = {
        "capacity": args.capacity, "lanes": lanes,
        "window": args.window, "emit_every": args.emit_every,
    }
    worker = {"pipeline": "off"}
    tmp_root = tempfile.mkdtemp(prefix="bench_cluster_")
    record = {
        "bench": "serve-cluster",
        "backend": jax.default_backend(),
        "cores": os.cpu_count(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "lanes_per_host": lanes,
        "note": (
            "every 'host' is a process on ONE box sharing "
            f"{os.cpu_count()} core(s): the in-process single server "
            "is the compute ceiling, the 1-host cluster isolates the "
            "constant process+RPC cost, and parity of the 2/4-host "
            "rows with the 1-host row means the multi-host fan-out "
            "itself is nearly free. Real scaling needs real chips."
        ),
        "single_host": None,
        "cluster_one_host": None,
        "cluster": [],
        "stealing": [],
        "failover": [],
    }

    def _round(srv, n, seed0):
        return _serve_round(
            srv, args.composite, n, horizon_steps, seed0
        )

    def _warm_cluster(cl, n):
        # like _warm, but the first windows compile inside worker
        # processes while the router ticks at poll cadence — the
        # tight in-process max_ticks bound does not apply
        for s in range(n):
            cl.submit(ScenarioRequest(
                composite=args.composite, seed=s,
                horizon=float(args.window),
            ))
        cl.run_until_idle(max_ticks=1_000_000)
        cl.reset_samples()

    def _make_cluster(tag, n_hosts, **kw):
        return ClusterServer(
            {args.composite: bucket}, hosts=n_hosts,
            cluster_dir=os.path.join(tmp_root, tag),
            queue_depth=256, worker=dict(worker), **kw,
        )

    def _rate(n, wall):
        return round(n * horizon_steps * args.capacity / wall)

    # baseline 1: the in-process single host (same per-host shape)
    srv = SimServer.single_bucket(
        args.composite, **bucket, queue_depth=256, pipeline="off",
    )
    _warm(srv, args.composite, lanes, args.window)
    n1 = args.fill_rounds * lanes
    wall1 = min(
        _round(srv, n1, 1000 + rep * n1) for rep in range(args.reps)
    )
    srv.close()
    single_rows_s = _rate(n1, wall1)
    record["single_host"] = {
        "lanes": lanes, "requests": n1,
        "served_row_steps_s": single_rows_s,
    }
    print(json.dumps(record["single_host"]), flush=True)

    # baseline 2: the 1-host cluster — one real worker process behind
    # the router, no fan-out
    cl = _make_cluster("c1", 1)
    _warm_cluster(cl, lanes)
    wall = min(
        _round(cl, n1, 1500 + rep * n1) for rep in range(args.reps)
    )
    cl.close()
    one_host_rows_s = _rate(n1, wall)
    record["cluster_one_host"] = {
        "lanes": lanes, "requests": n1,
        "served_row_steps_s": one_host_rows_s,
        "vs_single_host": round(one_host_rows_s / single_rows_s, 3),
    }
    print(json.dumps(record["cluster_one_host"]), flush=True)

    for n_hosts in sizes:
        n = args.fill_rounds * n_hosts * lanes
        cl = _make_cluster(f"c{n_hosts}", n_hosts)
        _warm_cluster(cl, n_hosts * lanes)
        wall = min(
            _round(cl, n, 2000 + rep * n) for rep in range(args.reps)
        )
        snap = cl.metrics()
        rate = _rate(n, wall)
        row = {
            "hosts": n_hosts,
            "lanes_total": n_hosts * lanes,
            "requests": n,
            "served_row_steps_s": rate,
            "vs_single_host": round(rate / single_rows_s, 3),
            "vs_one_host_cluster": round(rate / one_host_rows_s, 3),
            "stolen": snap["counters"].get("router_stolen", 0),
            "retired_per_host": [
                h["counters"].get("retired", 0)
                for h in snap["hosts"]
            ],
        }
        record["cluster"].append(row)
        print(json.dumps(row), flush=True)
        cl.close()

        # stealing A/B: the same skewed load (every request pinned to
        # host 0), stealing on vs off
        ab = {"hosts": n_hosts, "requests": n}
        for steal_on in (True, False):
            cl = _make_cluster(
                f"s{n_hosts}_{'on' if steal_on else 'off'}", n_hosts,
                steal_threshold=2 if steal_on else 10**9,
            )
            _warm_cluster(cl, n_hosts * lanes)
            walls = []
            for rep in range(max(args.reps // 2, 1)):
                t0 = time.perf_counter()
                rids = [
                    cl.submit(ScenarioRequest(
                        composite=args.composite,
                        seed=3000 + rep * n + i,
                        horizon=float(horizon_steps),
                    ), host=0)
                    for i in range(n)
                ]
                cl.run_until_idle(max_ticks=1_000_000)
                walls.append(time.perf_counter() - t0)
                assert all(
                    cl.status(r)["status"] == "done" for r in rids
                )
            snap = cl.metrics()
            tag = "steal_on" if steal_on else "steal_off"
            ab[tag] = {
                "wall_s": round(min(walls), 3),
                "stolen": snap["counters"].get("router_stolen", 0),
                "retired_per_host": [
                    h["counters"].get("retired", 0)
                    for h in snap["hosts"]
                ],
            }
            cl.close()
        ab["steal_speedup"] = round(
            ab["steal_off"]["wall_s"] / ab["steal_on"]["wall_s"], 3
        )
        record["stealing"].append(ab)
        print(json.dumps(ab), flush=True)

        # kill-one-host chaos row, bytes pinned vs the 1-host oracle
        chaos_reqs = [
            dict(seed=7000 + i, horizon=float(horizon_steps))
            for i in range(n)
        ]
        with ClusterServer(
            {args.composite: bucket}, hosts=1,
            cluster_dir=os.path.join(tmp_root, f"o{n_hosts}"),
            queue_depth=256, local=True, worker=dict(worker),
        ) as oracle:
            orids = [
                oracle.submit(ScenarioRequest(
                    composite=args.composite, **r
                ))
                for r in chaos_reqs
            ]
            oracle.run_until_idle(max_ticks=1_000_000)
            ref = {
                r: open(oracle.result(r), "rb").read()
                for r in orids
            }
        drill = _make_cluster(
            f"k{n_hosts}", n_hosts,
            faults=FaultPlan([{
                "kind": "host_down", "host": 1, "occurrence": 4,
            }]),
        )
        t0 = time.perf_counter()
        rids = [
            drill.submit(ScenarioRequest(
                composite=args.composite, **r
            ))
            for r in chaos_reqs
        ]
        drill.run_until_idle(max_ticks=1_000_000)
        drill_wall = time.perf_counter() - t0
        dsnap = drill.metrics()
        all_done = all(
            drill.status(r)["status"] == "done" for r in rids
        )
        pins = all(
            open(drill.result(r), "rb").read() == ref[r]
            for r in rids
        )
        frow = {
            "hosts": n_hosts,
            "victim_host": 1,
            "requests": n,
            "wall_s": round(drill_wall, 3),
            "all_done": bool(all_done),
            "bitwise_vs_oracle": bool(pins),
            "requeued": dsnap["counters"].get("router_requeued", 0),
            "hosts_down": dsnap["hosts_down"],
        }
        record["failover"].append(frow)
        print(json.dumps(frow), flush=True)
        drill.close()

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    print(
        f"baselines: in-process {single_rows_s} row-steps/s, 1-host "
        f"cluster {one_host_rows_s} "
        f"({record['cluster_one_host']['vs_single_host']:.2f}x — the "
        f"constant process+RPC cost on this box)"
    )
    for row in record["cluster"]:
        print(
            f"cluster {row['hosts']}: {row['served_row_steps_s']} "
            f"row-steps/s ({row['vs_one_host_cluster']:.2f}x the "
            f"1-host cluster, {row['vs_single_host']:.2f}x the "
            f"in-process ceiling)"
        )
    for ab in record["stealing"]:
        on, off = ab["steal_on"], ab["steal_off"]
        print(
            f"stealing {ab['hosts']} hosts: stolen={on['stolen']} "
            f"retired {on['retired_per_host']} vs off "
            f"{off['retired_per_host']}; wall {on['wall_s']}s vs "
            f"{off['wall_s']}s ({ab['steal_speedup']:.2f}x)"
        )
    ok = all(
        r["all_done"] and r["bitwise_vs_oracle"]
        for r in record["failover"]
    ) and all(
        ab["steal_on"]["stolen"] > 0 for ab in record["stealing"]
    )
    print(f"all cluster pins green: {ok}")
    shutil.rmtree(tmp_root, ignore_errors=True)
    return 0 if ok else 1


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--composite", default="toggle_colony")
    # 256-row buckets: small enough to serve interactively, big enough
    # that the window's device work is representative (a 32-row bucket
    # measures Python dispatch, not serving — see the README of
    # BENCH_SERVE record for the overhead-dominated small-bucket point)
    p.add_argument(
        "--capacity", type=int, default=None,
        help="bucket rows (default: 256; --frontdoor mode: 64 — the "
        "front-door bench measures the HTTP/tenancy layer, so the "
        "per-window device work stays small)",
    )
    p.add_argument(
        "--window", type=int, default=None,
        help="steps per scheduler tick (default: 64; --frontdoor "
        "mode: 8)",
    )
    p.add_argument("--emit-every", type=int, default=8)
    p.add_argument(
        "--lanes", type=int, nargs="+", default=None,
        help="lane counts (default: 2 4 8; --prefix mode: 1 8)",
    )
    p.add_argument(
        "--horizon-windows", type=int, default=None,
        help="request horizon in windows (default: 6; --prefix "
        "mode: 8)",
    )
    p.add_argument("--fill-rounds", type=int, default=4)
    p.add_argument("--sweep-n", type=int, default=48)
    p.add_argument(
        "--out", default=None,
        help="output JSON (default: BENCH_SERVE_CPU_r10.json; "
        "--prefix mode: BENCH_FORK_CPU_r11.json)",
    )
    p.add_argument(
        "--prefix", action="store_true",
        help="run the round-11 prefix-fork A/B instead of the "
        "saturation/offered-load bench (writes BENCH_FORK_CPU_r11.json "
        "unless --out is given)",
    )
    p.add_argument(
        "--faults", action="store_true",
        help="run the round-12 robustness-knob A/B (check_finite + "
        "WAL overhead, on vs off, per lane count; writes "
        "BENCH_FAULTS_CPU_r12.json unless --out is given)",
    )
    p.add_argument(
        "--mesh", type=int, nargs="*", default=None,
        help="run the round-13 mesh-serving scaling bench at these "
        "simulated device counts (bare flag: 2 4 8; forces "
        "xla_force_host_platform_device_count on CPU). Per size: "
        "served agent-steps/s, per-shard gauges, per-shard "
        "solo==co-batched bitwise pins, and a kill-one-device "
        "failover drill. Writes BENCH_MESH_CPU_r13.json unless "
        "--out is given; --lanes sets lanes PER SHARD (default 2)",
    )
    p.add_argument(
        "--trace", action="store_true",
        help="run the round-14 observability overhead A/B (span "
        "tracing + every-tick metrics sampling, on vs off, per lane "
        "count, with a byte-equal pin; writes BENCH_OBS_CPU_r14.json "
        "unless --out is given)",
    )
    p.add_argument(
        "--frontdoor", action="store_true",
        help="run the round-15 HTTP front-door bench: 1000 concurrent "
        "keep-alive clients across 3 tenants (one flooding) with "
        "per-tenant submit→first-byte / submit→done percentiles and "
        "429 pushback counts, plus a mesh=2 chaos row (device_down + "
        "sink io_error under load, SLO held). Writes "
        "BENCH_FRONTDOOR_CPU_r15.json unless --out is given",
    )
    p.add_argument(
        "--frontdoor-clients", type=int, nargs=3,
        default=[300, 300, 400], metavar=("GOLD", "SILVER", "FLOOD"),
        help="concurrent clients per tenant for the front-door load "
        "row (gold=interactive, silver=batch, flood=rate-limited "
        "batch)",
    )
    p.add_argument(
        "--chaos-clients", type=int, nargs=3, default=[60, 60, 80],
        metavar=("GOLD", "SILVER", "FLOOD"),
        help="concurrent clients per tenant for the front-door chaos "
        "row",
    )
    p.add_argument(
        "--flood-rate", type=float, default=40.0,
        help="the flooding tenant's token-bucket rate (requests/s) — "
        "its 400 clients burst far past this, so the 429 pushback "
        "is visible by construction",
    )
    p.add_argument(
        "--cluster", type=int, nargs="*", default=None,
        help="run the round-17 multi-host cluster bench at these "
        "simulated host counts (bare flag: 2 4; each host is a REAL "
        "worker process behind the router): aggregate throughput vs "
        "the single-host ceiling, a work-stealing A/B under skewed "
        "load, and a kill-one-host chaos row with bitwise oracle "
        "pins. Writes BENCH_CLUSTER_CPU_r17.json unless --out is "
        "given; --lanes sets lanes PER HOST (default 2)",
    )
    p.add_argument(
        "--tiers", action="store_true",
        help="run the round-16 tiered-store bench: a skewed-"
        "popularity (Zipf) workload A/B of the tiered store vs the "
        "LRU-only r11 store at identical device budget, a "
        "kill/restart disk-warmth row, and a speculative-warming "
        "sweep row (writes BENCH_TIER_CPU_r16.json unless --out is "
        "given)",
    )
    p.add_argument(
        "--cdn", action="store_true",
        help="run the round-18 request-stream CDN bench: a Zipf "
        "repeat-traffic A/B across the four knob settings (off / "
        "dedup / cache / both), a hot-vs-cold p50 row with the "
        "zero-device-windows claim counter-verified, an all-distinct "
        "cold-path overhead row, and a kill/restart durable-warmth "
        "row (writes BENCH_CDN_CPU_r18.json unless --out is given)",
    )
    p.add_argument(
        "--cdn-distinct", type=int, default=8,
        help="distinct requests in the CDN Zipf workload",
    )
    p.add_argument(
        "--tier-prefixes", type=int, default=12,
        help="distinct prefixes in the Zipf/restart tier workloads",
    )
    p.add_argument(
        "--zipf-alpha", type=float, default=1.1,
        help="Zipf popularity exponent for the tier workload",
    )
    p.add_argument(
        "--prefix-frac", type=float, default=0.75,
        help="shared-prefix fraction of the horizon (fork A/B), "
        "snapped to whole windows",
    )
    p.add_argument(
        "--fork-requests", type=int, default=8,
        help="requests sharing one prefix per fork A/B round (raised "
        "to 4 per lane so the suffix phase keeps full occupancy)",
    )
    p.add_argument(
        "--sweep-sizes", type=int, nargs="+", default=[32],
        help="trial counts for the warmup-sharing sweep A/B",
    )
    p.add_argument("--reps", type=int, default=5)
    args = p.parse_args()

    # per-mode defaults (None = not explicitly passed)
    if sum(
        1 for m in (args.prefix, args.faults, args.mesh is not None,
                    args.trace, args.frontdoor, args.tiers,
                    args.cluster is not None, args.cdn)
        if m
    ) > 1:
        raise SystemExit(
            "--prefix / --faults / --mesh / --trace / --frontdoor / "
            "--tiers / --cluster / --cdn are separate modes"
        )
    args.capacity = args.capacity or (
        64 if args.frontdoor else 256
    )
    args.window = args.window or (8 if args.frontdoor else 64)
    if args.frontdoor:
        args.out = args.out or "BENCH_FRONTDOOR_CPU_r15.json"
        args.horizon_windows = args.horizon_windows or 2
        return run_frontdoor_bench(args)
    if args.trace:
        args.out = args.out or "BENCH_OBS_CPU_r14.json"
        args.lanes = args.lanes or [2, 4, 8]
        args.horizon_windows = args.horizon_windows or 6
        return run_trace_bench(args)
    if args.cluster is not None:
        args.cluster = args.cluster or [2, 4]
        args.out = args.out or "BENCH_CLUSTER_CPU_r17.json"
        args.horizon_windows = args.horizon_windows or 6
        return run_cluster_bench(args)
    if args.mesh is not None:
        args.mesh = args.mesh or [2, 4, 8]
        args.out = args.out or "BENCH_MESH_CPU_r13.json"
        args.horizon_windows = args.horizon_windows or 6
        return run_mesh_bench(args)
    if args.faults:
        args.out = args.out or "BENCH_FAULTS_CPU_r12.json"
        args.lanes = args.lanes or [2, 4, 8]
        args.horizon_windows = args.horizon_windows or 6
        return run_faults_bench(args)
    if args.cdn:
        args.out = args.out or "BENCH_CDN_CPU_r18.json"
        args.lanes = args.lanes or [4]
        args.horizon_windows = args.horizon_windows or 6
        return run_cdn_bench(args)
    if args.tiers:
        args.out = args.out or "BENCH_TIER_CPU_r16.json"
        args.lanes = args.lanes or [8]
        args.horizon_windows = args.horizon_windows or 8
        return run_tier_bench(args)
    if args.prefix:
        args.out = args.out or "BENCH_FORK_CPU_r11.json"
        args.lanes = args.lanes or [1, 8]
        args.horizon_windows = args.horizon_windows or 8
        return run_prefix_bench(args)
    args.out = args.out or "BENCH_SERVE_CPU_r10.json"
    args.lanes = args.lanes or [2, 4, 8]
    args.horizon_windows = args.horizon_windows or 6

    horizon_steps = args.horizon_windows * args.window
    record = {
        "bench": "serve",
        "backend": jax.default_backend(),
        "composite": args.composite,
        "capacity": args.capacity,
        "window": args.window,
        "emit_every": args.emit_every,
        "horizon_steps": horizon_steps,
        "saturation": [],
        "offered_load": [],
    }

    for lanes in args.lanes:
        rows = saturation_point(
            args.composite, args.capacity, lanes, args.window,
            args.emit_every, horizon_steps, args.fill_rounds,
        )
        for entry in rows:
            record["saturation"].append(entry)
            print(json.dumps(entry), flush=True)

        piped = next(r for r in rows if r["pipeline"] == "on")
        for frac in (0.5, 0.9, 1.5):
            sweep = offered_load(
                args.composite, args.capacity, lanes, args.window,
                args.emit_every, horizon_steps,
                rate_req_s=max(frac * piped["saturated_req_s"], 0.5),
                n=args.sweep_n,
            )
            sweep["lanes"] = lanes
            sweep["load_fraction"] = frac
            record["offered_load"].append(sweep)
            print(json.dumps(sweep), flush=True)

    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
    print(f"wrote {args.out}")
    for mode in ("on", "off"):
        worst = min(
            e["served_over_ceiling"]
            for e in record["saturation"] if e["pipeline"] == mode
        )
        print(f"worst served/ceiling (pipeline {mode}): {worst:.3f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
