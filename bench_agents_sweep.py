"""Agent-count throughput sweep: where is the chip-fill knee?

VERDICT r2 weak #6: config 1 (1k agents, no lattice) under-fills the
chip — per-step dispatch overhead dominates and throughput looks ~20x
below config 2. This sweep measures agent-steps/sec vs colony size for
the lattice flagship (config-2 model) and the no-lattice toggle colony
(config-1 model), so the knee is recorded instead of guessed.

Run on the TPU:  python bench_agents_sweep.py
CPU half:        BENCH_FORCE_CPU=1 python bench_agents_sweep.py
Writes BENCH_AGENTS_SWEEP.json (BENCH_AGENTS_SWEEP_CPU.json when forced
to CPU) — both halves together locate the backend crossover.
"""

from __future__ import annotations

import json
import os
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/lens_tpu_jax_cache")

if os.environ.get("BENCH_FORCE_CPU"):
    # CPU pass: small colonies with tiny per-agent state are LATENCY-bound
    # on the accelerator (measured: config-1 1k agents runs ~50x faster on
    # host CPU than on the chip) — the sweep's job is to record the
    # crossover, so it must be runnable on both backends.
    from lens_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(1)

import jax

WINDOW_S = 32.0
SIZES = (256, 1024, 4096, 16384, 65536)
ENSEMBLE_COLONY = 1024  # agents per replicate in the ensemble rows


def measure(build, n) -> float:
    state, window = build()
    state = jax.block_until_ready(window(state))
    t0 = time.perf_counter()
    jax.block_until_ready(window(state))
    return n * WINDOW_S / (time.perf_counter() - t0)


def toggle(n):
    from lens_tpu.colony.colony import Colony
    from lens_tpu.models.composites import toggle_colony

    colony = Colony(toggle_colony({}), capacity=n)

    def build():
        state = colony.initial_state(n, key=jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: colony.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    return build


def lattice(n):
    from lens_tpu.models.composites import ecoli_lattice

    spatial, _ = ecoli_lattice({"capacity": n})

    def build():
        state = spatial.initial_state(n, jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: spatial.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return state, window

    return build


def toggle_ensemble(n):
    """n total agents as REPLICATES of a 1k colony: the ensemble answer
    to the small-colony latency knee (same agent count as `toggle_colony`
    at size n, split into n/1024 independent 1k replicates)."""
    from lens_tpu.colony import Colony, Ensemble
    from lens_tpu.models.composites import toggle_colony

    per = ENSEMBLE_COLONY
    ens = Ensemble(Colony(toggle_colony({}), capacity=per), n // per)

    def build():
        states = ens.initial_state(per, key=jax.random.PRNGKey(0))
        window = jax.jit(
            lambda s: ens.run(s, WINDOW_S, 1.0, emit_every=int(WINDOW_S))[0]
        )
        return states, window

    return build


def main() -> None:
    from lens_tpu.utils.platform import guard_accelerator_or_exit

    guard_accelerator_or_exit()
    report = {
        "backend": jax.default_backend(),
        "device": str(jax.devices()[0]),
        "results": [],
    }
    models = (
        ("toggle_colony", toggle),
        ("ecoli_lattice", lattice),
        ("toggle_ensemble_1k", toggle_ensemble),
    )
    for name, factory in models:
        for n in SIZES:
            if name == "toggle_ensemble_1k" and n < ENSEMBLE_COLONY:
                continue
            try:
                rate = measure(factory(n), n)
                row = {
                    "model": name,
                    "agents": n,
                    "agent_steps_per_sec": round(rate, 1),
                }
            except Exception as e:  # noqa: BLE001 — record and continue
                row = {"model": name, "agents": n, "error": str(e)[:200]}
            report["results"].append(row)
            print(json.dumps(row), flush=True)
    out = (
        "BENCH_AGENTS_SWEEP_CPU.json"
        if os.environ.get("BENCH_FORCE_CPU")
        else "BENCH_AGENTS_SWEEP.json"
    )
    with open(out, "w") as f:
        json.dump(report, f, indent=2)


if __name__ == "__main__":
    main()
