#!/bin/bash
# Round-4 on-device measurement queue. Run ONLY when no other process
# holds the TPU (the axon relay serves one client at a time). Each
# script probes the backend itself and writes its canonical BENCH_*.json;
# this wrapper snapshots each into the *_r04.json name the judge reads.
set -u
cd "$(dirname "$0")/.."
run() {
  local script=$1 src=$2 dst=$3
  echo "=== $script -> $dst ($(date -u +%H:%M:%S)) ==="
  timeout 3000 python "$script" 2>&1 | tail -20
  if [ -f "$src" ]; then cp "$src" "$dst"; else echo "!! $src missing"; fi
}
run bench_all.py          BENCH_ALL.json          BENCH_ALL_r04.json
run bench_diffusion_ab.py BENCH_DIFFUSION_AB.json BENCH_DIFFUSION_AB_r04.json
run bench_lp_sizes.py     BENCH_LP_SIZES.json     BENCH_LP_SIZES_r04.json
run bench_agents_sweep.py BENCH_AGENTS_SWEEP.json BENCH_AGENTS_SWEEP_r04.json
run bench_mfu.py          BENCH_MFU.json          BENCH_MFU_r04.json
# chip-sized example records (each writes its own committed JSON)
for ex in ensemble param_scan cross_feeding; do
  echo "=== examples/$ex.py ($(date -u +%H:%M:%S)) ==="
  timeout 3000 python "examples/$ex.py" 2>&1 | tail -8
done
echo "=== queue done ($(date -u +%H:%M:%S)) ==="
