#!/bin/bash
# Round-5 on-device measurement queue. Run ONLY when no other process
# holds the TPU (the axon relay serves one client at a time). Each
# script probes the backend itself and writes its canonical *.json;
# this wrapper snapshots each into the *_r05.json name the judge reads.
#
# Order = evidence value per minute of chip time (VERDICT r4 item 1):
# fresh headline configs first (incl. the 3b>=10k proof and the new 3c),
# then MFU with real peak, then the diffusion A/B that decides `auto`,
# then the REAL north star (VERDICT item 3 — cheap on chip: ~360M
# agent-steps), then sweeps, then chip-scale example records, then
# tests_tpu (run by the watcher after this script).
set -u
cd "$(dirname "$0")/.."
run() {
  local script=$1 src=$2 dst=$3
  shift 3
  echo "=== $script $* -> $dst ($(date -u +%H:%M:%S)) ==="
  rm -f "$src"   # never snapshot a stale pre-existing record as fresh
  timeout 4000 python "$script" "$@" 2>&1 | tail -20
  if [ ! -f "$src" ]; then echo "!! $src missing (script failed/timed out)"
  elif [ "$src" != "$dst" ]; then cp "$src" "$dst"; fi
}
run bench_all.py          BENCH_ALL.json          BENCH_ALL_r05.json
run bench_mfu.py          BENCH_MFU.json          BENCH_MFU_r05.json
run bench_phases.py       BENCH_PHASES.json       BENCH_PHASES_r05.json
run bench_diffusion_ab.py BENCH_DIFFUSION_AB.json BENCH_DIFFUSION_AB_r05.json
run examples/north_star.py NORTH_STAR.json        NORTH_STAR.json
run bench_lp_sizes.py     BENCH_LP_SIZES.json     BENCH_LP_SIZES_r05.json
run bench_lp_scale.py     BENCH_LP_SCALE.json     BENCH_LP_SCALE_r05.json
run bench_agents_sweep.py BENCH_AGENTS_SWEEP.json BENCH_AGENTS_SWEEP_r05.json
# chip-scale example records (each writes its own committed JSON)
for ex in full_core_colony ensemble param_scan cross_feeding chemotaxis; do
  echo "=== examples/$ex.py ($(date -u +%H:%M:%S)) ==="
  timeout 4000 python "examples/$ex.py" 2>&1 | tail -8
done
echo "=== queue done ($(date -u +%H:%M:%S)) ==="
