#!/bin/bash
# Poll the axon relay; the moment it answers, run the full on-device
# queue (benches then tests_tpu). Logs to .scratch/tpu_watch.log.
# Round-3 lesson: queued on-device work that waits for a human to press
# the button misses the recovery window — this presses it.
set -u
cd "$(dirname "$0")/.."
LOG=.scratch/tpu_watch.log
probe() {
  timeout 120 python -c "import jax; print('PLATFORM=' + jax.devices()[0].platform)" 2>/dev/null \
    | grep -q "PLATFORM=" && return 0
  return 1
}
echo "watch start $(date -u +%F'T'%T)" >> "$LOG"
for i in $(seq 1 200); do
  if probe; then
    echo "relay UP at $(date -u +%F'T'%T) (probe $i)" >> "$LOG"
    bash .scratch/tpu_queue.sh >> "$LOG" 2>&1
    echo "=== tests_tpu ===" >> "$LOG"
    LENS_TPU_DEVICE_TESTS=1 python -m pytest tests_tpu/ -q -p no:cacheprovider >> "$LOG" 2>&1
    echo "queue+tests done $(date -u +%F'T'%T)" >> "$LOG"
    exit 0
  fi
  echo "probe $i down $(date -u +%F'T'%T)" >> "$LOG"
  sleep 300
done
echo "gave up $(date -u +%F'T'%T)" >> "$LOG"
exit 1
