#!/bin/bash
# Canonical suite invocation for this box: ONE pytest process PER FILE.
#
# Since 2026-07-30 ~21:45 this machine's XLA CPU compiler segfaults
# probabilistically in LONG-lived processes with many compiles behind
# them (observed at different tests, with and without the axon PJRT
# plugin on PYTHONPATH, with the persistent compilation cache shared,
# fresh, and disabled — traces in SURVEY.md header). Short-lived
# processes have NEVER crashed. Two half-suite shards were enough
# through round 4 (~370 tests); by round 5 the suite grew past the
# crash horizon even in quarter shards (crashes at ~240 tests in a
# half-shard and again inside a 6-file quarter shard, 2026-07-31), so
# each file now runs alone — interpreter startup ~15 s/file is the
# price of determinism here. `python -m pytest tests/ -q` remains the
# honest single invocation to try first on a healthy box.
set -u
cd "$(dirname "$0")"
rc=0
for f in tests/test_*.py; do
  python -m pytest "$f" -q "$@"
  rc2=$?
  # exit 5 = "no tests collected" — expected under -k/-m filters when a
  # file's tests are all deselected; not a failure
  if [ "$rc2" -ne 0 ] && [ "$rc2" -ne 5 ]; then rc=$rc2; fi
done
exit $rc
