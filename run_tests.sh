#!/bin/bash
# Canonical suite invocation for this box: TWO pytest processes.
#
# Since 2026-07-30 ~21:45 this machine's XLA CPU compiler segfaults
# probabilistically in LONG-lived processes with many compiles behind
# them (observed at different tests, with and without the axon PJRT
# plugin on PYTHONPATH, with the persistent compilation cache shared,
# fresh, and disabled — traces in SURVEY.md header). Short-lived
# processes have never crashed: the same suite is consistently green
# split in two (~10 min each). Until the environment recovers, run it
# this way; `python -m pytest tests/ -q` remains the honest single
# invocation to try first on a healthy box.
set -u
cd "$(dirname "$0")"
files=$(ls tests/test_*.py)
n=$(echo "$files" | wc -l)
half=$(( (n + 1) / 2 ))
first=$(echo "$files" | head -n "$half" | tr '\n' ' ')
second=$(echo "$files" | tail -n +"$((half + 1))" | tr '\n' ' ')
rc=0
python -m pytest $first -q "$@" || rc=$?
python -m pytest $second -q "$@" || rc=$?
exit $rc
